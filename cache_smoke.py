"""Two-process persistent-compile-cache smoke drill (CPU backend).

Runs the same ``solve_jax_many`` batch in two fresh processes sharing one
persistent XLA cache dir. The first process compiles every canonical shape
class (``jit.compile`` > 0); the second must deserialize everything
(``jit.compile`` == 0, ``jit.cache_load`` > 0) and report a near-zero
compile wall clock — the property the throughput-first scheduler depends
on (docs/benchmarks.md#cold-vs-warm). CI runs this as a gate and uploads
the stats JSON as a build artifact.

Usage: python cache_smoke.py [--out stats.json] [--cache-dir DIR]
Exit code 0 when the second process is compile-free, 1 otherwise.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time


def _child() -> None:
    import numpy as np

    import jax

    jax.config.update('jax_platforms', 'cpu')
    from da4ml_tpu.cmvm.jax_search import ensure_compile_cache, executable_classes, solve_jax_many
    from da4ml_tpu.telemetry.metrics import enable_metrics, metrics_snapshot

    enable_metrics()
    cache_dir = ensure_compile_cache()

    rng = np.random.default_rng(20260804)
    # the (12, 5) kernel resumes across ladder rungs, so the drill also
    # exercises the device-resident rung-transition kernels — the warm
    # process must deserialize THOSE compile classes too
    kernels = [
        (rng.integers(0, 2**b, (d, d)) * rng.choice([-1.0, 1.0], (d, d))).astype(np.float64)
        for d, b in ((6, 3), (8, 4), (12, 5))
    ]
    t0 = time.perf_counter()
    sols = solve_jax_many(kernels)
    first = time.perf_counter() - t0
    t0 = time.perf_counter()
    solve_jax_many(kernels)
    steady = time.perf_counter() - t0
    for k, s in zip(kernels, sols):
        assert np.array_equal(np.asarray(s.kernel, np.float64), k), 'parity violated'
    # a quality='search' solve walks the device-beam classes on top (fork
    # step, frontier prune, widened-sel fan-out gathers, full_rec CSE
    # rungs) — the warm process must be compile-free for those too
    t0 = time.perf_counter()
    qsols = solve_jax_many(kernels[:2], quality='search')
    quality_s = time.perf_counter() - t0
    for k, s in zip(kernels, qsols):
        assert np.array_equal(np.asarray(s.kernel, np.float64), k), 'quality parity violated'

    snap = metrics_snapshot()
    print(
        json.dumps(
            {
                'cache_dir': cache_dir,
                'first_s': round(first, 3),
                'steady_s': round(steady, 3),
                'quality_s': round(quality_s, 3),
                'jax_compile_s': round(max(first - steady, 0.0), 3),
                'buckets': executable_classes(),
                'jit_compile': int(snap.get('jit.compile', {}).get('value', 0)),
                'jit_cache_load': int(snap.get('jit.cache_load', {}).get('value', 0)),
                # device-resident ladder evidence: transitions executed and
                # the host<->device traffic they saved (docs/cmvm.md#scheduler)
                'resident_rungs': int(snap.get('sched.device_resident_rungs', {}).get('value', 0)),
                'fetch_bytes': int(snap.get('sched.fetch_bytes', {}).get('value', 0)),
                'upload_bytes': int(snap.get('sched.upload_bytes', {}).get('value', 0)),
                # device-beam evidence: the quality solve's on-device forks
                # (its fork/prune/fan-out classes ride the same cache gate)
                'device_forks': int(snap.get('search.device_forks', {}).get('value', 0)),
                'metrics': snap,
            }
        )
    )


def main() -> int:
    argv = sys.argv[1:]
    if argv and argv[0] == '--child':
        _child()
        return 0
    out_path = None
    cache_dir = None
    i = 0
    while i < len(argv):
        if argv[i] == '--out' and i + 1 < len(argv):
            out_path = argv[i + 1]
            i += 1
        elif argv[i] == '--cache-dir' and i + 1 < len(argv):
            cache_dir = argv[i + 1]
            i += 1
        i += 1

    tmp = None
    if cache_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix='da4ml-cache-smoke-')
        cache_dir = tmp.name
    env = dict(
        os.environ,
        JAX_PLATFORMS='cpu',
        DA4ML_XLA_CACHE=cache_dir,
        # a fresh dir must be truly cold: neutralize any ambient jax cache
        # config the invoking environment (e.g. the test conftest) exports
        JAX_COMPILATION_CACHE_DIR='',
    )
    runs = []
    try:
        for phase in ('cold', 'warm'):
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), '--child'],
                capture_output=True,
                text=True,
                timeout=600,
                env=env,
            )
            lines = [ln for ln in (r.stdout or '').splitlines() if ln.startswith('{')]
            if r.returncode != 0 or not lines:
                tail = (r.stderr or '').strip().splitlines()[-5:]
                print(json.dumps({'phase': phase, 'error': ' | '.join(tail)[-400:] or f'rc={r.returncode}'}))
                return 1
            runs.append({'phase': phase, **json.loads(lines[-1])})
    finally:
        result = {
            'metric': 'persistent_cache_smoke',
            'runs': runs,
            'ok': bool(
                len(runs) == 2
                and runs[0]['jit_compile'] > 0
                and runs[1]['jit_compile'] == 0
                and runs[1]['jit_cache_load'] > 0
                # the warm process must stay compile-free WITH the
                # device-resident transition kernels in play (they are
                # compile classes too, markered + persisted like the rungs)
                and runs[1].get('resident_rungs', 0) > 0
                # ... and with the device-beam fork/prune classes in play
                # (the quality='search' solve above)
                and runs[1].get('device_forks', 0) > 0
            ),
        }
        print(json.dumps({k: v for k, v in result.items() if k != 'runs'} | {'runs': [
            {k: v for k, v in run.items() if k != 'metrics'} for run in runs
        ]}))
        if out_path:
            with open(out_path, 'w') as fh:
                json.dump(result, fh, indent=1)
        if tmp is not None:
            tmp.cleanup()
    return 0 if result['ok'] else 1


if __name__ == '__main__':
    raise SystemExit(main())
