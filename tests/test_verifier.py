"""DAIS verifier tests: clean programs verify clean, corrupted ones are caught.

Covers the acceptance contract of the analysis framework:

- every solver-produced program in this suite verifies with zero errors;
- for every DAIS opcode family, at least one ``reliability.faults``-driven
  corruption is detected with a structured diagnostic;
- the integration points (``from_dict``/``load``, the ``DA4ML_VERIFY=1``
  post-solve hook, codegen preconditions, the ``verify`` CLI) all fail fast.
"""

import json

import numpy as np
import pytest

from da4ml_tpu.analysis import (
    COMB_CORRUPTIONS,
    PIPELINE_CORRUPTIONS,
    RULES,
    VerificationError,
    apply_planned_corruptions,
    corruption_by_name,
    verify,
)
from da4ml_tpu.cmvm import solve
from da4ml_tpu.ir import CombLogic, Pipeline, QInterval, minimal_kif
from da4ml_tpu.reliability import fault_injection
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace


@pytest.fixture(scope='module')
def rich_comb() -> CombLogic:
    """One traced program containing every DAIS opcode family."""
    rng = np.random.default_rng(7)
    inp = FixedVariableArrayInput((8,), hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(8), np.full(8, 3), np.full(8, 2))
    w = rng.integers(-4, 4, (8, 3)).astype(np.float64)
    outs = [
        np.sin(x[:4]).quantize(np.ones(4), np.ones(4), np.full(4, 4)),  # lookup (8)
        x[:4] * x[4:],  # mul (7)
        np.where(x[:2] > 0, x[2:4], 1.25),  # msb-mux (6) + const (5)
        x[:4] & x[4:],  # binary bitwise (10)
        ~x[:2],  # unary bitwise (9)
        (x @ w).relu(),  # adds (0/1) + relu-quantize (2)
        x[1:3] + 1.5,  # const-add (4)
    ]
    out = np.concatenate([np.atleast_1d(v) for v in outs])
    return comb_trace(inp, out)


@pytest.fixture(scope='module')
def solved_pipeline() -> Pipeline:
    rng = np.random.default_rng(3)
    kernel = rng.integers(-8, 8, (6, 5)).astype(np.float64)
    return solve(kernel, qintervals=[QInterval(-8.0, 7.0, 1.0)] * 6)


def test_rich_comb_covers_all_families(rich_comb):
    # every opcode family of the DAIS v1 table appears at least once
    present = {op.opcode for op in rich_comb.ops}
    assert {-1, 4, 5, 7, 8, 10}.issubset(present)
    assert present & {0, 1} and present & {2, -2} and present & {3, -3}
    assert present & {6, -6} and present & {9, -9}


def test_clean_traced_program(rich_comb):
    result = verify(rich_comb)
    assert result.ok, result.format_text()


def test_clean_solver_programs(solved_pipeline):
    assert verify(solved_pipeline).ok
    # a couple more shapes/precisions, exercising the dc sweep
    for seed, shape, qb in ((0, (4, 7), 3), (1, (9, 2), 5)):
        rng = np.random.default_rng(seed)
        kernel = rng.integers(-16, 16, shape).astype(np.float64)
        qints = [QInterval(-(2.0 ** (qb - 1)), 2.0 ** (qb - 1) - 1, 1.0)] * shape[0]
        result = verify(solve(kernel, qintervals=qints))
        assert result.ok, result.format_text()


# ---------------------------------------------------------------------------
# mutation self-test: every catalogued corruption is caught
# ---------------------------------------------------------------------------


@pytest.mark.parametrize('name', [c.name for c in COMB_CORRUPTIONS])
def test_mutation_is_caught(rich_comb, name):
    corruption = corruption_by_name(name)
    with fault_injection(f'ir.mutate.{name}=corrupt:1'):
        mutated = apply_planned_corruptions(rich_comb)
        # budget of 1: a second sweep must not fire again
        assert apply_planned_corruptions(rich_comb) is rich_comb

    assert mutated is not rich_comb, 'armed corruption did not mutate the program'
    result = verify(mutated)
    hits = result.by_rule(corruption.expect_rule)
    assert hits, f'{name}: expected {corruption.expect_rule}, got {result.format_text()}'
    severity = RULES[corruption.expect_rule][1]
    assert all(d.severity == severity for d in hits)
    if severity == 'error':
        assert not result.ok
    # diagnostics are structured & serializable
    blob = json.loads(result.to_json())
    assert blob['diagnostics'][0]['rule']


@pytest.mark.parametrize('name', [c.name for c in PIPELINE_CORRUPTIONS])
def test_pipeline_mutation_is_caught(solved_pipeline, name):
    corruption = corruption_by_name(name)
    with fault_injection(f'ir.mutate.{name}=corrupt:1'):
        mutated = apply_planned_corruptions(solved_pipeline)
    result = verify(mutated)
    assert result.by_rule(corruption.expect_rule), result.format_text()
    assert not result.ok


def test_unarmed_plan_is_identity(rich_comb):
    assert apply_planned_corruptions(rich_comb) is rich_comb


def test_env_var_plan_arms_corruption(rich_comb, monkeypatch):
    monkeypatch.setenv('DA4ML_FAULT_INJECT', 'ir.mutate.add.forward_ref=corrupt:1')
    mutated = apply_planned_corruptions(rich_comb)
    assert not verify(mutated).ok


# ---------------------------------------------------------------------------
# satellite: QInterval.step validation in minimal_kif
# ---------------------------------------------------------------------------


@pytest.mark.parametrize('step', [0.75, 0.0, -1.0, float('nan'), float('inf')])
def test_minimal_kif_rejects_bad_step(step):
    with pytest.raises(ValueError, match='positive power of two'):
        minimal_kif(QInterval(-2.0, 1.75, step))


def test_minimal_kif_zero_interval_keeps_any_step():
    assert tuple(minimal_kif(QInterval(0.0, 0.0, 0.75))) == (False, 0, 0)


# ---------------------------------------------------------------------------
# integration: load-time verification, post-solve hook, codegen precondition
# ---------------------------------------------------------------------------


def test_from_dict_rejects_corrupt_program(rich_comb):
    blob = rich_comb.to_dict()
    blob['ops'][5][0] = len(blob['ops']) + 3  # id0 forward reference
    blob['ops'][5][2] = 0  # on an add op
    with pytest.raises(VerificationError):
        CombLogic.from_dict(blob)
    assert CombLogic.from_dict(blob, verify=False) is not None


def test_load_rejects_corrupt_file(tmp_path, solved_pipeline):
    blob = solved_pipeline.to_dict()
    blob['stages'][0]['out_idxs'][0] = 10**6
    path = tmp_path / 'pipeline.json'
    path.write_text(json.dumps(blob))
    with pytest.raises(VerificationError):
        Pipeline.load(path)
    assert Pipeline.load(path, verify=False) is not None


def test_roundtrip_still_clean(tmp_path, rich_comb):
    path = tmp_path / 'comb.json'
    rich_comb.save(path)
    assert CombLogic.load(path) == rich_comb


def test_post_solve_hook(monkeypatch, solved_pipeline):
    monkeypatch.setenv('DA4ML_VERIFY', '1')
    kernel = np.arange(-3.0, 3.0).reshape(2, 3)
    assert solve(kernel) is not None  # clean program passes the hook

    from da4ml_tpu.cmvm import api

    bad = corruption_by_name('pipeline.stage_interface').apply(solved_pipeline)
    monkeypatch.setattr(api, '_solve_dispatch', lambda *a, **k: bad)
    with pytest.raises(VerificationError, match='DA4ML_VERIFY'):
        api.solve(kernel, fallback=False)
    # hook is opt-in: without the env var the corrupt result passes through
    monkeypatch.delenv('DA4ML_VERIFY')
    assert api.solve(kernel, fallback=False) is bad


def test_codegen_precondition(tmp_path, rich_comb, monkeypatch):
    from da4ml_tpu.codegen import VerilogModel

    bad = corruption_by_name('mul.narrowed_interval').apply(rich_comb)
    with pytest.raises(VerificationError, match='precondition'):
        VerilogModel(bad, 'bad_model', tmp_path / 'proj').write()
    assert not (tmp_path / 'proj' / 'src').exists()
    monkeypatch.setenv('DA4ML_VERIFY', '0')  # explicit bypass
    VerilogModel(bad, 'bad_model', tmp_path / 'proj').write()
    assert (tmp_path / 'proj' / 'src').exists()


def test_hls_precondition(tmp_path, rich_comb):
    pytest.importorskip('da4ml_tpu.codegen.hls.hls_model')
    from da4ml_tpu.codegen import HLSModel

    bad = corruption_by_name('copy.bad_lane').apply(rich_comb)
    with pytest.raises(VerificationError, match='precondition'):
        HLSModel(bad, 'bad_model', tmp_path / 'hproj').write()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_cli_verify(tmp_path, rich_comb, solved_pipeline, capsys):
    from da4ml_tpu._cli import main

    good = tmp_path / 'good.json'
    rich_comb.save(good)
    bad_blob = solved_pipeline.to_dict()
    bad_blob['stages'][0]['ops'][0][2] = 42  # unknown opcode
    bad = tmp_path / 'bad.json'
    bad.write_text(json.dumps(bad_blob))

    assert main(['verify', str(good)]) == 0
    out = capsys.readouterr().out
    assert 'ok' in out

    assert main(['verify', str(good), str(bad)]) == 1
    out = capsys.readouterr().out
    assert 'W102' in out

    assert main(['verify', str(bad), '--json']) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload['ok'] is False
    assert any(d['rule'] == 'W102' for d in payload['diagnostics'])

    garbage = tmp_path / 'garbage.json'
    garbage.write_text('{not json')
    assert main(['verify', str(garbage)]) == 2


def test_cli_verify_project_dir(tmp_path, rich_comb):
    from da4ml_tpu._cli import main

    (tmp_path / 'proj' / 'model').mkdir(parents=True)
    rich_comb.save(tmp_path / 'proj' / 'model' / 'comb.json')
    assert main(['verify', str(tmp_path / 'proj')]) == 0


def test_cli_verify_pass_subset(tmp_path, rich_comb):
    from da4ml_tpu._cli import main

    good = tmp_path / 'good.json'
    rich_comb.save(good)
    assert main(['verify', str(good), '--passes', 'wellformed,deadcode']) == 0
    with pytest.raises(ValueError, match='unknown analysis pass'):
        main(['verify', str(good), '--passes', 'nope'])
