"""HGQ2 front-end ingestion.

Two tiers:

1. ``test_hgq2_surface_*`` (always run): mock layers replicating HGQ2's
   duck-typed attribute surface — wrapper quantizers with per-element
   heterogeneous (k, i, f) tensors (KIF and KBI parameterizations), leading
   broadcast axes, ``qkernel``/``qbias`` quantized weights, iq/oq input and
   output quantizers — traced through the real Keras plugin and pinned
   bit-exact against the model's own keras-ops forward.

2. ``test_hgq2_genuine_*`` (skipped without the ``hgq`` package): an actual
   HGQ2 model saved to ``.keras``, reloaded, traced, and pinned bit-exact
   against ``model.predict``. One-command run wherever HGQ2 is installed:

       pytest tests/test_hgq2_ingest.py -k genuine
"""

from __future__ import annotations

import importlib.util

import keras
import numpy as np
import pytest
from keras import ops

from da4ml_tpu.converter import trace_model
from da4ml_tpu.trace import HWConfig, comb_trace

_HAS_HGQ = importlib.util.find_spec('hgq') is not None


def _q_ops(x, k, i, f, round_mode='RND'):
    """keras-ops twin of the golden quantize_float (WRAP, TRN/RND)."""
    k = np.asarray(k, np.float64)
    i = np.asarray(i, np.float64)
    f = np.asarray(f, np.float64)
    eps = 2.0**-f
    b = k + i + f
    bias = 2.0 ** (b - 1) * k
    v = x + (eps * 0.5 if round_mode == 'RND' else 0.0)
    return eps * (ops.mod(ops.floor(v / eps) + bias, 2.0**b) - bias)


class _InnerKIF:
    """HGQ2-style internal fixed-point quantizer, KIF parameterization."""

    def __init__(self, k, i, f, overflow='WRAP', round_mode='RND'):
        # leading broadcast (batch) axis of 1, as HGQ2 parameter tensors carry
        self.k = np.asarray(k, np.float32)[None]
        self.i = np.asarray(i, np.float32)[None]
        self.f = np.asarray(f, np.float32)[None]
        self.overflow_mode = overflow
        self.round_mode = round_mode


class _InnerKBI:
    """KBI parameterization: f = b - i."""

    def __init__(self, k, b, i, overflow='WRAP', round_mode='RND'):
        self.k = np.asarray(k, np.float32)[None]
        self.b = np.asarray(b, np.float32)[None]
        self.i = np.asarray(i, np.float32)[None]
        self.overflow_mode = overflow
        self.round_mode = round_mode

    @property
    def f(self):
        return None  # force the KBI branch of the reader


class _Quantizer:
    """The wrapper object (hgq.quantizer.Quantizer look-alike)."""

    def __init__(self, inner):
        self.quantizer = inner
        self.enabled = True

    def kif(self):
        c = self.quantizer
        f = getattr(c, 'f', None)
        if f is None:
            f = c.b - c.i
        return c.k, c.i, f

    def __call__(self, x):
        k, i, f = self.kif()
        return _q_ops(x, k, i, f, self.quantizer.round_mode)


class QDense(keras.layers.Layer):
    """Mock with HGQ2 QDense's name and attribute surface (iq, oq, qkernel)."""

    def __init__(self, kernel, bias, iq, oq, activation='linear', **kw):
        super().__init__(**kw)
        self._kernel = np.asarray(kernel, np.float64)
        self._bias = np.asarray(bias, np.float64) if bias is not None else None
        self.iq = iq
        self.oq = oq
        self.activation = activation
        self.use_bias = bias is not None

    # weight quantizers: 4-bit fractional grid, exactly representable values
    @property
    def qkernel(self):
        return np.round(self._kernel * 16) / 16

    @property
    def qbias(self):
        return None if self._bias is None else np.round(self._bias * 16) / 16

    # the plugin reads .kernel only when qkernel is absent; keep both valid
    @property
    def kernel(self):
        return self.qkernel

    @property
    def bias(self):
        return self.qbias

    def call(self, x):
        y = x
        if self.iq is not None:
            y = self.iq(y)
        y = ops.matmul(y, ops.convert_to_tensor(self.qkernel, dtype=y.dtype))
        if self.qbias is not None:
            y = y + ops.convert_to_tensor(self.qbias, dtype=y.dtype)
        if self.activation == 'relu':
            y = ops.relu(y)
        if self.oq is not None:
            y = self.oq(y)
        return y


def _hetero_kif(rng, n, lo_i=1, hi_i=4, lo_f=1, hi_f=5, k=1):
    return (
        np.full(n, k, np.int64),
        rng.integers(lo_i, hi_i + 1, n),
        rng.integers(lo_f, hi_f + 1, n),
    )


@pytest.mark.parametrize('param', ['kif', 'kbi'])
def test_hgq2_surface_dense_chain(rng, param):
    """Two mock QDense layers with heterogeneous per-element kif, traced via
    the plugin, bit-exact vs the keras-ops forward."""
    n_in, n_mid, n_out = 6, 5, 3
    k0, i0, f0 = _hetero_kif(rng, n_in)
    k1, i1, f1 = _hetero_kif(rng, n_mid, k=0)  # post-relu: unsigned

    def make_q(k, i, f):
        if param == 'kif':
            return _Quantizer(_InnerKIF(k, i, f))
        return _Quantizer(_InnerKBI(k, i + f, i))

    iq0 = make_q(k0, i0, f0)
    oq0 = make_q(k1, i1, f1)
    w0 = rng.uniform(-2, 2, (n_in, n_mid))
    b0 = rng.uniform(-1, 1, n_mid)
    w1 = rng.uniform(-2, 2, (n_mid, n_out))
    k2, i2, f2 = _hetero_kif(rng, n_out)
    oq1 = make_q(k2, i2 + 4, f2)  # wide enough to pass sums through

    inp = keras.Input((n_in,))
    h = QDense(w0, b0, iq=iq0, oq=oq0, activation='relu')(inp)
    out = QDense(w1, None, iq=None, oq=oq1)(h)
    model = keras.Model(inp, out)

    x = rng.uniform(-4, 4, (64, n_in))
    golden = np.asarray(model(ops.convert_to_tensor(x, 'float64')))

    t_in, t_out = trace_model(model, HWConfig(1, -1, -1))
    comb = comb_trace(t_in, t_out)
    got = comb.predict(x)
    np.testing.assert_array_equal(got, golden)


def test_hgq2_surface_einsum_dense(rng):
    """EinsumDense path (HGQ2's flagship layer family) via keras's own layer
    with an hgq-style qkernel attached."""
    inp = keras.Input((4, 5))
    layer = keras.layers.EinsumDense('bij,jk->bik', (4, 6), bias_axes='k')
    out = layer(inp)
    model = keras.Model(inp, out)
    # quantize the built weights onto an exact grid, hgq-style
    qk = np.round(np.asarray(layer.kernel) * 8) / 8
    qb = np.round(np.asarray(layer.bias) * 8) / 8
    layer._kernel.assign(qk.astype(np.float32))
    layer.bias.assign(qb.astype(np.float32))

    x = (rng.integers(-32, 32, (16, 4, 5)) / 8.0).astype(np.float64)
    golden = np.einsum('bij,jk->bik', x, qk) + qb

    t_in, t_out = trace_model(model, HWConfig(1, -1, -1), inputs_kif=(1, 3, 3))
    comb = comb_trace(t_in, t_out)
    got = comb.predict(x.reshape(16, -1)).reshape(16, 4, 6)
    np.testing.assert_array_equal(got, golden)


@pytest.mark.skipif(not _HAS_HGQ, reason='hgq (HGQ2) not installed')
def test_hgq2_genuine_checkpoint(rng, tmp_path):
    """A real HGQ2 model: build, save .keras, reload, trace, bit-exact."""
    import hgq  # noqa: F401
    from hgq.layers import QDense

    inp = keras.Input((8,))
    h = QDense(16, activation='relu')(inp)
    out = QDense(4)(h)
    model = keras.Model(inp, out)
    x = rng.uniform(-2, 2, (256, 8)).astype(np.float32)
    _ = model(x)  # build quantizer state

    path = tmp_path / 'hgq2_model.keras'
    model.save(path)
    loaded = keras.models.load_model(path, compile=False)

    golden = np.asarray(loaded.predict(x, verbose=0), np.float64)
    t_in, t_out = trace_model(loaded, HWConfig(1, -1, -1))
    comb = comb_trace(t_in, t_out)
    np.testing.assert_array_equal(comb.predict(np.asarray(x, np.float64)), golden)
