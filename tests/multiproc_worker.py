"""Worker for the two-process distributed smoke test (test_multidevice.py).

Invoked as: python multiproc_worker.py <coordinator_port> <rank>

Each process brings up the JAX distributed runtime over CPU with two local
virtual devices (4 global), builds the global mesh, runs one cross-process
collective and one mesh-sharded CMVM solve, and prints a result line the
parent asserts on.
"""

from __future__ import annotations

import os
import sys

port, rank = sys.argv[1], int(sys.argv[2])
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
os.environ['JAX_PLATFORMS'] = 'cpu'
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

# the axon TPU plugin force-registers at interpreter start and ignores the
# JAX_PLATFORMS env override — without this config update the workers would
# rendezvous against the real (possibly wedged) chip instead of the CPU mesh
jax.config.update('jax_platforms', 'cpu')
# share XLA compiles between the two workers (and across runs): on a small
# CI host the CSE program compile dominates the test's wall clock
jax.config.update('jax_compilation_cache_dir', os.environ.get('DA4ML_JAX_CACHE', '/tmp/da4ml_jax_cache_cpu'))
jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
# cross-process collectives on the CPU backend need an explicit transport;
# without it every cross-host program silently deadlocks
jax.config.update('jax_cpu_collectives_implementation', 'gloo')

from da4ml_tpu.parallel.distributed import global_mesh, initialize  # noqa: E402

ok = initialize(coordinator_address=f'127.0.0.1:{port}', num_processes=2, process_id=rank)
assert ok, 'distributed runtime did not come up'
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, jax.devices()

mesh = global_mesh()
assert mesh.devices.size == 4

# cross-process collective: psum over a mesh-sharded axis spanning both hosts
from jax.sharding import NamedSharding, PartitionSpec  # noqa: E402

axis = mesh.axis_names[0]
sharded = jax.device_put(np.arange(8, dtype=np.float32), NamedSharding(mesh, PartitionSpec(axis)))
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, PartitionSpec()))(sharded)
assert float(total) == 28.0, float(total)

# mesh-sharded solve: candidate lanes split across both processes
from da4ml_tpu.cmvm.jax_search import solve_jax_many  # noqa: E402

rng = np.random.default_rng(5)
kernel = (rng.integers(0, 8, (8, 8)) * rng.choice([-1, 1], (8, 8))).astype(np.float64)
sol = solve_jax_many([kernel], mesh=mesh)[0]
assert np.array_equal(np.asarray(sol.kernel, np.float64), kernel)
print(f'RANK{rank} OK cost={float(sol.cost)}', flush=True)
