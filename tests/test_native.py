"""Native C++ interpreter parity: cpp backend == numpy backend (bit-exact)
over the full op matrix, plus batch-threading and error paths.

Mirrors the reference's role for dais_bin (src/da4ml/_binary/dais) as the
oracle executor; here the numpy backend is the golden semantics and the C++
build must agree exactly.
"""

import numpy as np
import pytest

from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace
from test_trace_ops import CASES, N

native = pytest.importorskip('da4ml_tpu.native')

if not native.is_available():
    pytest.skip('native toolchain unavailable', allow_module_level=True)


def _trace(op_sym, seed=42):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 2, N)
    i = rng.integers(-2, 5, N)
    f = np.maximum(rng.integers(-2, 5, N), 1 - k - i)
    inp = FixedVariableArrayInput(N, hwconf=HWConfig(1, -1, -1))
    out = op_sym(inp.quantize(k, i, f))
    return comb_trace(inp, out)


@pytest.mark.parametrize('name', sorted(CASES))
def test_cpp_matches_numpy(name):
    op_sym, _ = CASES[name]
    comb = _trace(op_sym)
    data = np.random.default_rng(3).uniform(-8, 8, (512, N))
    np.testing.assert_array_equal(
        comb.predict(data, backend='cpp'),
        comb.predict(data, backend='numpy'),
    )


def test_cpp_lookup():
    comb = _trace(lambda x: np.sin(x).quantize(np.ones(N), np.ones(N), np.full(N, 4)))
    data = np.random.default_rng(4).uniform(-8, 8, (256, N))
    np.testing.assert_array_equal(comb.predict(data, backend='cpp'), comb.predict(data, backend='numpy'))


def test_cpp_multithreaded_large_batch():
    comb = _trace(CASES['matmul_int'][0])
    data = np.random.default_rng(5).uniform(-8, 8, (4096, N))
    golden = comb.predict(data, backend='numpy')
    for n_threads in (1, 2, 8):
        np.testing.assert_array_equal(comb.predict(data, backend='cpp', n_threads=n_threads), golden)


def test_program_info():
    from da4ml_tpu.native.bindings import program_info

    comb = _trace(CASES['sum'][0])
    info = program_info(comb.to_binary())
    assert info['n_in'] == N and info['n_out'] == 1
    assert info['n_ops'] == len(comb.ops)
    assert 0 < info['max_width'] <= 63


def test_invalid_binary_rejected():
    from da4ml_tpu.native.bindings import run_binary

    with pytest.raises(RuntimeError, match='version mismatch'):
        run_binary(np.array([9, 0, 1, 1, 0, 0], dtype=np.int32), np.zeros((1, 1)))
