"""Verilator emulation binder: bit-packing semantics + emitted project checks.

verilator is not installed in CI, so the binder's C++ helpers are exercised
directly: ``binder_util.hh`` is compiled with g++ (verilated.h stubbed) into
a small .so and its set_bits/get_bits/sext are cross-checked against Python
golden packing over randomized fields, including word-boundary crossings on
wide (WData[]) ports. This pins the int packing semantics the reference's
ioutil.hh defines (src/da4ml/codegen/rtl/common_source/ioutil.hh:5-50 of
calad0i/da4ml). A full verilator compile+predict test runs when verilator is
in PATH (mirroring the reference's skip guard, tests/test_ops.py:72-79).
"""

from __future__ import annotations

import ctypes
import shutil
import subprocess
from pathlib import Path

import numpy as np
import pytest

from da4ml_tpu.codegen import RTLModel
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

_COMMON = Path(__file__).resolve().parents[1] / 'da4ml_tpu' / 'codegen' / 'rtl' / 'common'

_HARNESS = r"""
#include <cstdint>
#include "binder_util.hh"
using namespace da4ml_binder;

extern "C" {
uint64_t t_set_int(uint64_t port, int off, int width, uint64_t val) {
    set_bits(port, off, width, val);
    return port;
}
uint64_t t_get_int(uint64_t port, int off, int width) { return get_bits(port, off, width); }
void t_set_wide(uint32_t* words, int off, int width, uint64_t val) { set_bits(words, off, width, val); }
uint64_t t_get_wide(const uint32_t* words, int off, int width) { return get_bits(words, off, width); }
int64_t t_sext(uint64_t v, int width, int is_signed) { return sext(v, width, is_signed != 0); }
}
"""


@pytest.fixture(scope='module')
def binder_lib(tmp_path_factory):
    if shutil.which('g++') is None:
        pytest.skip('g++ not available')
    d = tmp_path_factory.mktemp('binder_util')
    (d / 'verilated.h').write_text('#pragma once\n')  # stub: only types are templated
    (d / 'harness.cc').write_text(_HARNESS)
    shutil.copy(_COMMON / 'binder_util.hh', d / 'binder_util.hh')
    so = d / 'libharness.so'
    subprocess.run(
        ['g++', '-O1', '-fPIC', '-shared', '-std=c++17', '-I', str(d), str(d / 'harness.cc'), '-o', str(so)],
        check=True,
        capture_output=True,
    )
    lib = ctypes.CDLL(str(so))
    lib.t_set_int.restype = ctypes.c_uint64
    lib.t_set_int.argtypes = [ctypes.c_uint64, ctypes.c_int, ctypes.c_int, ctypes.c_uint64]
    lib.t_get_int.restype = ctypes.c_uint64
    lib.t_get_int.argtypes = [ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.t_set_wide.restype = None
    lib.t_set_wide.argtypes = [u32p, ctypes.c_int, ctypes.c_int, ctypes.c_uint64]
    lib.t_get_wide.restype = ctypes.c_uint64
    lib.t_get_wide.argtypes = [u32p, ctypes.c_int, ctypes.c_int]
    lib.t_sext.restype = ctypes.c_int64
    lib.t_sext.argtypes = [ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
    return lib


def _mask(width: int) -> int:
    return (1 << width) - 1 if width < 64 else (1 << 64) - 1


def test_set_get_int_fields(binder_lib, rng):
    for _ in range(200):
        width = int(rng.integers(1, 33))
        off = int(rng.integers(0, 64 - width + 1))
        port = int(rng.integers(0, 1 << 63))
        val = int(rng.integers(0, 1 << 62))
        packed = binder_lib.t_set_int(port, off, width, val)
        want = (port & ~(_mask(width) << off)) | ((val & _mask(width)) << off)
        assert packed == want & ((1 << 64) - 1)
        assert binder_lib.t_get_int(packed, off, width) == (val & _mask(width))


def test_set_get_wide_fields_cross_word(binder_lib, rng):
    n_words = 8
    for _ in range(200):
        width = int(rng.integers(1, 49))
        off = int(rng.integers(0, n_words * 32 - width + 1))  # often crosses a 32-bit word
        words = np.asarray(rng.integers(0, 1 << 32, n_words), dtype=np.uint32)
        val = int(rng.integers(0, 1 << 62))
        buf = (ctypes.c_uint32 * n_words)(*words.tolist())
        binder_lib.t_set_wide(buf, off, width, val)
        # golden: big integer bit surgery over the 256-bit buffer
        big = sum(int(w) << (32 * i) for i, w in enumerate(words))
        want = (big & ~(_mask(width) << off)) | ((val & _mask(width)) << off)
        got = sum(int(buf[i]) << (32 * i) for i in range(n_words))
        assert got == want
        assert binder_lib.t_get_wide(buf, off, width) == (val & _mask(width))


def test_sext(binder_lib):
    assert binder_lib.t_sext(0b1000, 4, 1) == -8
    assert binder_lib.t_sext(0b0111, 4, 1) == 7
    assert binder_lib.t_sext(0b1111, 4, 0) == 15
    assert binder_lib.t_sext(0b1111, 4, 1) == -1
    assert binder_lib.t_sext(1 << 63, 64, 1) == -(1 << 63)
    assert binder_lib.t_sext(0, 1, 1) == 0
    assert binder_lib.t_sext(1, 1, 1) == -1


def _project(tmp_path, pipelined: bool):
    rng = np.random.default_rng(5)
    inp = FixedVariableArrayInput(6, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(6), np.full(6, 3), np.full(6, 2))
    x = x @ rng.integers(-8, 8, (6, 4)).astype(np.float64)
    comb = comb_trace(inp, x)
    model = RTLModel(comb, 'binder_t', tmp_path / ('p' if pipelined else 'c'), latency_cutoff=2.0 if pipelined else -1)
    model.write()
    return model


@pytest.mark.parametrize('pipelined', [False, True])
def test_binder_emission_consistent(tmp_path, pipelined):
    """binder.cc constants must agree with the solution's IO geometry."""
    model = _project(tmp_path, pipelined)
    bdir = model.path / 'binder'
    binder = (bdir / 'binder.cc').read_text()
    assert (bdir / 'binder_util.hh').exists()
    assert (bdir / 'Makefile').exists()
    n_in = model.solution.shape[0] if not pipelined else model.solution.stages[0].shape[0]
    n_out = len(model.solution.out_qint)
    assert f'N_IN = {n_in}, N_OUT = {n_out};' in binder
    assert ('top.clk' in binder) == pipelined
    assert 'extern "C" int inference' in binder
    mk = (bdir / 'Makefile').read_text()
    assert 'TOP = binder_t' in mk
    assert 'verilator' in mk.lower()


@pytest.mark.skipif(shutil.which('verilator') is None, reason='verilator not installed')
@pytest.mark.parametrize('pipelined', [False, True])
def test_verilator_emulation_exact(tmp_path, pipelined):
    """Full co-simulation triangle where verilator exists: the compiled
    Verilator emulator == DAIS interpreter == in-tree netlist simulator
    (reference test_rtl_gen; rtl_model.py:252-330 of calad0i/da4ml).

    One-command run on a machine with verilator installed:
        pytest tests/test_rtl_binder.py -k verilator
    """
    model = _project(tmp_path, pipelined).compile()
    data = np.random.default_rng(9).uniform(-8, 8, (64, 6))
    emu = model.predict(data, backend='emu')
    np.testing.assert_array_equal(emu, model.predict(data, backend='interp'))
    if not pipelined:  # the netlist sim oracle covers the comb project
        from da4ml_tpu.codegen.rtl.verilog.netlist_sim import simulate_comb

        np.testing.assert_array_equal(emu, simulate_comb(model.solution, name='binder_t', data=data))
