"""The examples gallery stays runnable (each script in a bounded subprocess)."""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

import pytest

_EXAMPLES = sorted((Path(__file__).resolve().parents[1] / 'examples').glob('0*.py'))


@pytest.mark.parametrize('script', _EXAMPLES, ids=lambda p: p.name)
def test_example_runs(script, tmp_path):
    env = {k: v for k, v in os.environ.items() if k != 'PALLAS_AXON_POOL_IPS'}
    env['JAX_PLATFORMS'] = 'cpu'
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=4'
    env['DA4ML_EXAMPLE_N'] = '6'  # CPU-XLA executes the search ~100x slower than a chip
    r = subprocess.run(
        [sys.executable, str(script), str(tmp_path / 'out')],
        capture_output=True,
        text=True,
        timeout=900,
        env=env,
    )
    assert r.returncode == 0, f'{script.name} failed:\n{r.stdout[-1500:]}\n{r.stderr[-1500:]}'
