"""HLS codegen oracle chain: the emitted C++ kernel, compiled with g++ and
driven through the emulation bridge, must agree exactly with the DAIS
interpreter over the full op matrix — the same role as the reference's
test_hls_gen (tests/test_ops.py:89-105 in the reference tree), with the
vendor-free integer kernel making the check runnable anywhere g++ exists.
"""

import shutil

import numpy as np
import pytest

from da4ml_tpu.codegen import HLSModel
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace, to_pipeline
from test_trace_ops import CASES, N

if shutil.which('g++') is None:
    pytest.skip('g++ not available', allow_module_level=True)


def _trace(op_sym, seed=42):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 2, N)
    i = rng.integers(-2, 5, N)
    f = np.maximum(rng.integers(-2, 5, N), 1 - k - i)
    inp = FixedVariableArrayInput(N, hwconf=HWConfig(1, -1, -1))
    out = op_sym(inp.quantize(k, i, f))
    return comb_trace(inp, out)


DATA = np.random.default_rng(3).uniform(-8, 8, (256, N))


@pytest.mark.parametrize('name', sorted(CASES))
def test_hls_exact(name, tmp_path):
    comb = _trace(CASES[name][0])
    model = HLSModel(comb, 'kern', tmp_path).write().compile()
    np.testing.assert_array_equal(model.predict(DATA, backend='emu'), comb.predict(DATA, backend='numpy'))


def test_hls_lookup(tmp_path):
    comb = _trace(lambda x: np.sin(x).quantize(np.ones(N), np.ones(N), np.full(N, 4)))
    model = HLSModel(comb, 'kern', tmp_path).write().compile()
    np.testing.assert_array_equal(model.predict(DATA), comb.predict(DATA, backend='numpy'))


def test_hls_pipeline(tmp_path):
    comb = _trace(CASES['matmul_int'][0])
    model = HLSModel(to_pipeline(comb, 2.0), 'kern', tmp_path).write().compile()
    np.testing.assert_array_equal(model.predict(DATA), comb.predict(DATA, backend='numpy'))


def test_hls_solver_pipeline(tmp_path):
    """Nonzero inp_shifts / out_shifts / out_negs pass through exactly."""
    from da4ml_tpu.cmvm import solve
    from da4ml_tpu.ir import QInterval

    rng = np.random.default_rng(7)
    kernel = rng.integers(-8, 8, (10, 6)).astype(np.float64)
    sol = solve(kernel, qintervals=[QInterval(-8, 7, 1)] * 10)
    x = rng.integers(-8, 8, (256, 10)).astype(np.float64)
    model = HLSModel(sol, 'kern', tmp_path).write().compile()
    np.testing.assert_array_equal(model.predict(x), x @ kernel)


def test_hls_project_files(tmp_path):
    comb = _trace(CASES['sum'][0])
    HLSModel(comb, 'kern', tmp_path, latency_cutoff=1.0).write()
    assert (tmp_path / 'src' / 'kern.hh').exists()
    assert (tmp_path / 'src' / 'dais_hls.hh').exists()
    assert (tmp_path / 'src' / 'bridge.cc').exists()
    assert (tmp_path / 'src' / 'hls_top.cc').exists()
    assert (tmp_path / 'tcl' / 'build_vitis.tcl').exists()
    assert (tmp_path / 'metadata.json').exists()
    text = (tmp_path / 'src' / 'kern.hh').read_text()
    assert '#pragma HLS PIPELINE II=1' in text


@pytest.mark.parametrize('flavor', ['vitis', 'hlslib', 'oneapi'])
def test_hls_flavors(flavor, tmp_path):
    """Every flavor (reference hls_model.py:45) writes its synthesis harness
    and stays bit-exact through the shared g++ emulation bridge."""
    comb = _trace(CASES['sum'][0])
    model = HLSModel(comb, 'kern', tmp_path, flavor=flavor).write().compile()
    np.testing.assert_array_equal(model.predict(DATA, backend='emu'), comb.predict(DATA, backend='numpy'))
    text = (tmp_path / 'src' / 'kern.hh').read_text()
    if flavor == 'vitis':
        assert (tmp_path / 'src' / 'hls_top.cc').exists()
        assert (tmp_path / 'tcl' / 'build_vitis.tcl').exists()
        assert '#pragma HLS PIPELINE II=1' in text
    elif flavor == 'hlslib':
        top = (tmp_path / 'src' / 'hls_top.cc').read_text()
        assert 'hls_component_ii(1) component void' in top
        assert (tmp_path / 'tcl' / 'build_hlslib.sh').exists()
        assert '#pragma HLS' not in text
    else:
        assert 'single_task' in (tmp_path / 'src' / 'hls_top_oneapi.cpp').read_text()
        assert (tmp_path / 'tcl' / 'build_oneapi.sh').exists()
        assert '#pragma HLS' not in text
    import json

    assert json.loads((tmp_path / 'metadata.json').read_text())['flavor'] == flavor


def test_hls_flavor_rejected(tmp_path):
    comb = _trace(CASES['sum'][0])
    with pytest.raises(ValueError, match='flavor'):
        HLSModel(comb, 'kern', tmp_path, flavor='catapult')


def test_hls_threads_match(tmp_path):
    comb = _trace(CASES['matmul_frac'][0])
    model = HLSModel(comb, 'kern', tmp_path).write().compile()
    golden = model.predict(DATA, n_threads=1)
    np.testing.assert_array_equal(model.predict(DATA, n_threads=8), golden)


def test_hls_depthwise_conv(tmp_path):
    """Depthwise conv comb compiles and matches the interpreter through g++."""
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace
    from da4ml_tpu.trace.ops import depthwise_conv2d

    rng = np.random.default_rng(5)
    shape = (4, 4, 2)
    inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(shape), np.full(shape, 3), np.zeros(shape, np.int64))
    w = rng.integers(-4, 4, (2, 2, 2, 2)).astype(np.float64)
    comb = comb_trace(inp, depthwise_conv2d(x, w))
    model = HLSModel(comb, 'kern', tmp_path).write().compile()
    data = rng.uniform(-8, 8, (64, int(np.prod(shape))))
    np.testing.assert_array_equal(model.predict(data), comb.predict(data, backend='numpy'))
