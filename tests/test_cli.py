"""CLI tests: convert on saved IR, report on canned vendor report fixtures."""

import json
import subprocess
import sys

import numpy as np
import pytest

from da4ml_tpu._cli import main
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

VIVADO_TIMING = """\
------------------------------------------------------------------------------------------------
| Design Timing Summary
| ---------------------
------------------------------------------------------------------------------------------------

    WNS(ns)      TNS(ns)  TNS Failing Endpoints  TNS Total Endpoints
    -------      -------  ---------------------  -------------------
      0.237        0.000                      0                 1924
"""

VIVADO_UTIL = """\
| DSPs                   |    2 |     0 |          0 |     12288 |  0.02 |
| LUT as Logic           | 1234 |     0 |          0 |   1728000 |  0.07 |
| LUT as Memory          |   10 |     0 |          0 |    791040 |  0.00 |
| CLB Registers          |  567 |     0 |          0 |   3456000 |  0.02 |
| CARRY8                 |   89 |     0 |          0 |    216000 |  0.04 |
| Register as Flip Flop  |  567 |     0 |          0 |   3456000 |  0.02 |
| Register as Latch      |    0 |     0 |          0 |   3456000 |  0.00 |
| RAMB18                 |    0 |     0 |          0 |      5376 |  0.00 |
| URAM                   |    0 |     0 |          0 |      1280 |  0.00 |
| Block RAM Tile         |    0 |     0 |          0 |      2688 |  0.00 |
"""

VIVADO_POWER = """\
| Total On-Chip Power (W)  | 1.234        |
| Dynamic (W)              | 0.900        |
| Device Static (W)        | 0.334        |
"""

QUARTUS_STA = """\
; Fmax Summary ;
+-----------+-----------------+------------+------+
; 312.5 MHz ; 300.0 MHz       ; clk        ;      ;
+-----------+-----------------+------------+------+

+----------------------------------------------------------+
; Setup Summary                                            ;
+------------+--------+---------------+---------------------+
; Clock      ; Slack  ; End Point TNS ; Failing Endpoints   ;
+------------+--------+---------------+---------------------+
; clk        ; 0.800  ; 0.000         ; 0                   ;
+------------+--------+---------------+---------------------+

+----------------------------------------------------------+
; Hold Summary                                             ;
+------------+--------+---------------+---------------------+
; Clock      ; Slack  ; End Point TNS ; Failing Endpoints   ;
+------------+--------+---------------+---------------------+
; clk        ; 0.123  ; 0.000         ; 0                   ;
+------------+--------+---------------+---------------------+
"""

QUARTUS_FIT = """\
; Logic utilization (in ALMs)           ; 1,024 / 933,120    ;
; Total dedicated logic registers       ; 2,048              ;
; Total block memory bits               ; 0 / 240,046,080    ;
; Total RAM Blocks                      ; 0 / 11,721         ;
; Total DSP Blocks                      ; 1 / 5,760          ;
; Combinational ALUT usage for logic    ; 1,500              ;
; Dedicated logic registers             ; 2,048              ;
"""

VITIS_CSYNTH = """\
<?xml version="1.0"?>
<profile>
  <PerformanceEstimates>
    <SummaryOfOverallLatency>
      <Best-caseLatency>3</Best-caseLatency>
      <Average-caseLatency>3</Average-caseLatency>
      <Worst-caseLatency>3</Worst-caseLatency>
    </SummaryOfOverallLatency>
  </PerformanceEstimates>
</profile>
"""


def _make_comb():
    rng = np.random.default_rng(7)
    inp = FixedVariableArrayInput(6, HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(6), np.full(6, 3), np.full(6, 2))
    w = rng.integers(-8, 8, (6, 4)).astype(np.float64)
    return comb_trace(inp, (x @ w).relu(i=np.full(4, 6), f=np.full(4, 2)))


@pytest.mark.parametrize('flavor', ['verilog', 'vhdl', 'vitis', 'hlslib', 'oneapi'])
def test_convert_from_json(tmp_path, flavor):
    comb = _make_comb()
    model_json = tmp_path / 'comb.json'
    comb.save(model_json)
    outdir = tmp_path / f'prj_{flavor}'
    rc = main(
        ['convert', str(model_json), str(outdir), '--flavor', flavor, '-n', '64', '-lc', '3', '--validate-rtl', '-v', '0']
    )
    assert rc == 0
    assert (outdir / 'metadata.json').exists()
    meta = json.loads((outdir / 'metadata.json').read_text())
    assert meta['flavor'] == flavor
    assert meta['pipelined']


def test_convert_comb_no_pipeline(tmp_path):
    comb = _make_comb()
    model_json = tmp_path / 'comb.json'
    comb.save(model_json)
    outdir = tmp_path / 'prj'
    rc = main(['convert', str(model_json), str(outdir), '-lc', '-1', '-n', '32', '--validate-rtl', '-v', '0'])
    assert rc == 0
    assert not json.loads((outdir / 'metadata.json').read_text())['pipelined']


def _fake_project(tmp_path, name, kind):
    d = tmp_path / name
    d.mkdir()
    (d / 'metadata.json').write_text(
        json.dumps({'name': 'model', 'flavor': 'verilog', 'cost': 100.0, 'latency_ticks': 4, 'clock_period': 5.0})
    )
    if kind == 'vivado':
        (d / 'timing_summary.rpt').write_text(VIVADO_TIMING)
        (d / 'utilization.rpt').write_text(VIVADO_UTIL)
        (d / 'power.rpt').write_text(VIVADO_POWER)
    elif kind == 'quartus':
        (d / 'model.sta.rpt').write_text(QUARTUS_STA)
        (d / 'model.fit.rpt').write_text(QUARTUS_FIT)
    elif kind == 'vitis':
        (d / 'csynth.xml').write_text(VITIS_CSYNTH)
    return d


def test_report_vivado(tmp_path):
    from da4ml_tpu._cli.report import load_project

    d = _fake_project(tmp_path, 'prj-bits=6-lc=2.5', 'vivado')
    res = load_project(d)
    assert res['WNS(ns)'] == 0.237
    assert res['LUT'] == 1244
    assert res['FF'] == 567
    assert res['DSP'] == 2
    assert res['Total On-Chip Power (W)'] == '1.234'
    assert abs(res['actual_period'] - (5.0 - 0.237)) < 1e-9
    assert abs(res['Fmax(MHz)'] - 1000.0 / (5.0 - 0.237)) < 1e-9
    assert abs(res['latency(ns)'] - 4 * (5.0 - 0.237)) < 1e-9


def test_report_quartus(tmp_path):
    from da4ml_tpu._cli.report import load_project

    d = _fake_project(tmp_path, 'q', 'quartus')
    res = load_project(d)
    assert res['Fmax(MHz)'] == 312.5
    assert res['Setup Slack'] == 0.8
    assert res['Hold Slack'] == 0.123
    assert res['Setup Failing Endpoints'] == 0
    assert res['ALM'] == 1024
    assert res['LUT'] == 1500
    assert res['FF'] == 2048
    assert res['DSP'] == 1


def test_report_vitis(tmp_path):
    from da4ml_tpu._cli.report import load_project

    d = _fake_project(tmp_path, 'v', 'vitis')
    assert load_project(d)['latency'] == 3


@pytest.mark.parametrize('ext', ['json', 'csv', 'tsv', 'md', 'html'])
def test_report_outputs(tmp_path, ext, capsys):
    d1 = _fake_project(tmp_path, 'a-bits=4', 'vivado')
    d2 = _fake_project(tmp_path, 'b-bits=8', 'quartus')
    out = tmp_path / f'out.{ext}'
    rc = main(['report', str(d1), str(d2), '-o', str(out)])
    assert rc == 0
    text = out.read_text()
    assert text
    if ext == 'json':
        vals = json.loads(text)
        assert len(vals) == 2
        assert {v['bits'] for v in vals} == {4, 8}


def test_report_stdout(tmp_path, capsys):
    d1 = _fake_project(tmp_path, 'a', 'vivado')
    rc = main(['report', str(d1), '--full'])
    assert rc == 0
    cap = capsys.readouterr().out
    assert 'LUT' in cap and 'cost' in cap


def test_vendor_flow_emission(tmp_path):
    """Projects ship fully-substituted OOC vendor flows + constraint files."""
    from da4ml_tpu.codegen import RTLModel

    comb = _make_comb()
    model = RTLModel(comb, 'flowprj', tmp_path / 'prj', latency_cutoff=3.0, clock_period=4.0, clock_uncertainty=0.15)
    model.write()
    viv = (model.path / 'tcl' / 'build_vivado.tcl').read_text()
    qts = (model.path / 'tcl' / 'build_quartus.tcl').read_text()
    xdc = (model.path / 'constraints' / 'flowprj.xdc').read_text()
    sdc = (model.path / 'constraints' / 'flowprj.sdc').read_text()
    for text in (viv, qts, xdc, sdc):
        assert '@' not in text, 'unresolved substitution token'
    # vivado flow: OOC synth, staged impl, report names the report CLI parses
    assert '-mode out_of_context' in viv
    for stage in ('synth_design', 'opt_design', 'place_design', 'phys_opt_design', 'route_design'):
        assert stage in viv
    for rpt in ('post_route_timing.rpt', 'post_route_util.rpt', 'post_route_power.rpt'):
        assert rpt in viv
    # quartus flow: virtual pins (OOC) + timing-driven compile
    assert 'VIRTUAL_PIN' in qts and 'execute_flow -compile' in qts
    # constraints: period and ratio-scaled uncertainty / IO delays
    assert 'set period 4.0' in xdc and 'set period 4.0' in sdc
    assert '$period * 0.15' in xdc and '$period * 0.15' in sdc
    assert 'set_input_delay' in xdc and 'set_output_delay' in sdc


def test_report_finds_build_dir_reports(tmp_path):
    """report CLI end-to-end: reports in build_<name>/reports (where the
    emitted vivado flow writes them) are merged with project metadata."""
    from da4ml_tpu._cli.report import load_project
    from da4ml_tpu.codegen import RTLModel

    comb = _make_comb()
    model = RTLModel(comb, 'rptprj', tmp_path / 'prj', latency_cutoff=3.0)
    model.write()
    rdir = model.path / 'build_rptprj' / 'reports'
    rdir.mkdir(parents=True)
    (rdir / 'rptprj_post_route_timing.rpt').write_text(VIVADO_TIMING)
    (rdir / 'rptprj_post_route_util.rpt').write_text(VIVADO_UTIL)
    (rdir / 'rptprj_post_route_power.rpt').write_text(VIVADO_POWER)
    res = load_project(model.path)
    assert res['WNS(ns)'] == 0.237
    assert res['LUT'] == 1244
    assert res['name'] == 'rptprj'


def test_convert_keras_quality_flags(tmp_path):
    """--n-restarts / --methods / --solver-backend jax flow through to the solver."""
    keras = pytest.importorskip('keras')
    from keras import layers

    rng = np.random.default_rng(7)
    model = keras.Sequential([layers.Input((6,)), layers.Dense(4, activation='relu'), layers.Dense(2)])
    for w in model.weights:
        w.assign(rng.integers(-4, 4, w.shape).astype(np.float32))
    mpath = tmp_path / 'm.keras'
    model.save(mpath)
    outdir = tmp_path / 'prj'
    rc = main(
        [
            'convert', str(mpath), str(outdir), '-n', '32', '-ikif', '1', '3', '0', '-v', '0',
            '--solver-backend', 'jax', '--n-restarts', '2', '--methods', 'wmc', 'mc',
        ]
    )  # fmt: skip
    assert rc == 0
    assert (outdir / 'metadata.json').exists()


def test_cli_convert_torch_model(tmp_path):
    """A pickled torch nn.Module converts end to end with zero mismatches.

    The model class lives in a real module (written to tmp_path and put on
    the subprocess's PYTHONPATH) because torch full-module pickles resolve
    the class by import path in the loading process — exactly a user's
    situation."""
    torch = pytest.importorskip('torch')
    import importlib.util
    import json as _json
    import os

    (tmp_path / 'torch_mlp_def.py').write_text(
        'import torch\n'
        'class SmallMLP(torch.nn.Module):\n'
        '    input_shape = (6,)\n'
        '    def __init__(self):\n'
        '        super().__init__()\n'
        '        self.fc1 = torch.nn.Linear(6, 8)\n'
        '        self.act = torch.nn.ReLU()\n'
        '        self.fc2 = torch.nn.Linear(8, 3)\n'
        '    def forward(self, x):\n'
        '        return self.fc2(self.act(self.fc1(x)))\n'
    )
    spec = importlib.util.spec_from_file_location('torch_mlp_def', tmp_path / 'torch_mlp_def.py')
    mod = importlib.util.module_from_spec(spec)
    sys.modules['torch_mlp_def'] = mod
    spec.loader.exec_module(mod)

    rng = np.random.default_rng(4)
    model = mod.SmallMLP()
    with torch.no_grad():
        for p in model.parameters():
            p.copy_(torch.tensor(rng.integers(-4, 4, p.shape).astype(np.float32)))
    path = tmp_path / 'mlp.pt'
    torch.save(model, path)

    env = dict(os.environ)
    env['PYTHONPATH'] = f'{tmp_path}{os.pathsep}' + env.get('PYTHONPATH', '')
    out = tmp_path / 'prj'
    r = subprocess.run(
        [sys.executable, '-m', 'da4ml_tpu', 'convert', str(path), str(out), '--flavor', 'verilog',
         '--inputs-kif', '1', '4', '0', '-n', '128'],  # fmt: skip
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    report = _json.loads((out / 'mismatches.json').read_text())
    assert report['n_mismatch'] == 0, report
