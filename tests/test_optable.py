"""The declarative opcode table and everything generated from it.

- table sanity: the rows cover DAIS v1 exactly, with the runtime dispatch
  classes and synth coverage the consumers expect;
- the table-generated reference interpreter is bit-exact with the numpy
  oracle over the synth fuzz corpus;
- the cross-backend conformance checker passes clean on every runtime mode
  and catches an injected backend bug with a per-opcode C401 diagnostic;
- the transfer-soundness fuzz proves every row's QInterval transfer against
  the concrete replay semantics, and catches an injected transfer bug
  (D310);
- satellites: the synth coverage audit (per-opcode corpus counts in the
  test output), the opcode-dispatch drift lint, the doc-drift check, the
  diagnostics' stable ``opcode`` field, and the generated mutation catalog
  (same entries the hand-written PR-2 catalog had — no detection
  regressions).
"""

import json
from pathlib import Path

import numpy as np
import pytest

from da4ml_tpu.analysis import (
    COMB_CORRUPTIONS,
    OPT_IN_PASSES,
    PASSES,
    check_conformance,
    check_spec_soundness,
    check_transfer_soundness,
    run_conformance_corpus,
    verify,
)
from da4ml_tpu.ir import DAIS_V1_OPCODES, OP_TABLE, OPCODE_TO_SPEC
from da4ml_tpu.ir.optable import COPY_OPCODES, VECTOR_CLASS, spec_of
from da4ml_tpu.ir.synth import FAMILIES, opcode_counts, random_inputs, random_program
from da4ml_tpu.runtime import reference
from da4ml_tpu.runtime.numpy_backend import run_program

REPO_ROOT = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------------------------
# table sanity
# ---------------------------------------------------------------------------


def test_table_covers_dais_v1_exactly():
    seen: list[int] = []
    for spec in OP_TABLE:
        seen.extend(spec.opcodes)
    assert len(seen) == len(set(seen)), 'an opcode appears in two table rows'
    assert set(seen) == set(DAIS_V1_OPCODES)
    assert set(seen) == {-1, 0, 1, 2, -2, 3, -3, 4, 5, 6, -6, 7, 8, 9, -9, 10}
    # dispatch classes are per-row and dense (the scan switch indexes by them)
    classes = [spec.vector_class for spec in OP_TABLE]
    assert classes == list(range(len(OP_TABLE)))
    assert all(VECTOR_CLASS[oc] == spec.vector_class for spec in OP_TABLE for oc in spec.opcodes)
    assert COPY_OPCODES == {-1}
    assert spec_of(7).family == 'mul' and spec_of(99) is None


def test_every_row_is_complete():
    for spec in OP_TABLE:
        assert callable(spec.replay) and callable(spec.kernel) and callable(spec.transfer)
        assert callable(spec.sample)
        assert spec.mutations, f'{spec.key}: every row must ship a mutation family'
        assert spec.semantics and spec.payload and spec.cost_model
        assert spec.pallas_lower, f'{spec.key}: every row must name its pallas emitter'
        if spec.synth_family is not None:
            assert spec.synth_family in FAMILIES


def test_pallas_lowering_registry_covers_table():
    """Every row's `pallas_lower` name resolves in the backend registry and
    the registry carries no stale names — the import-time audit, asserted."""
    pytest.importorskip('jax')
    from da4ml_tpu.runtime.pallas_backend import LOWERINGS

    for spec in OP_TABLE:
        assert spec.pallas_lower in LOWERINGS, f'{spec.key}: no LOWERINGS[{spec.pallas_lower!r}]'
    table_names = {spec.pallas_lower for spec in OP_TABLE}
    assert set(LOWERINGS) == table_names


def test_synth_coverage_audit():
    """Every table opcode is emitted by the fuzz generator; counts surfaced."""
    progs = [random_program(np.random.default_rng(100_003 + pi), n_ops=180, n_in=6, n_out=5) for pi in range(4)]
    counts = opcode_counts(progs)
    print('\nper-opcode synth corpus counts:')
    for oc in sorted(counts):
        print(f'  opcode {oc:>3} [{OPCODE_TO_SPEC[oc].family}]: {counts[oc]}')
    missing = [oc for oc, n in counts.items() if n == 0]
    assert not missing, f'table opcodes without synth coverage: {missing}'


# ---------------------------------------------------------------------------
# reference interpreter & conformance
# ---------------------------------------------------------------------------


@pytest.mark.parametrize('seed,wide', [(0, False), (1, False), (2, True)])
def test_reference_matches_numpy_oracle(seed, wide):
    rng = np.random.default_rng(seed)
    prog = random_program(rng, n_ops=220, n_in=6, n_out=5, wide=wide)
    data = random_inputs(rng, prog, 97)
    ref, ref_buf = reference.run_program(prog, data, return_buf=True)
    got, got_buf = run_program(prog, data, return_buf=True)
    np.testing.assert_array_equal(got, ref)
    np.testing.assert_array_equal(got_buf, ref_buf)


def test_conformance_corpus_all_modes_clean():
    report, diags = run_conformance_corpus(n_programs=3, n_ops=150, n_samples=48, seed=0)
    assert report['ok'], [str(d) for d in diags]
    assert set(report['per_opcode']) == {str(oc) for oc in DAIS_V1_OPCODES}
    assert all(info['mismatches'] == 0 for info in report['per_opcode'].values())
    # the report is a JSON-ready artifact
    json.dumps(report)


def test_conformance_coverage_gap_flagged():
    # an add-only corpus leaves most of the table uncovered -> C402 per gap
    rng = np.random.default_rng(0)
    prog = random_program(rng, n_ops=40, n_in=4, n_out=2, families=('add',))
    import da4ml_tpu.analysis.conformance as conf

    def fake_random_program(*a, **k):
        return prog

    orig = conf.random_program
    conf.random_program = fake_random_program
    try:
        report, diags = conf.run_conformance_corpus(n_programs=1, n_samples=16, modes=('numpy',))
    finally:
        conf.random_program = orig
    gaps = [d for d in diags if d.rule == 'C402']
    assert gaps and not report['ok']
    assert all(d.opcode is not None for d in gaps)


def test_conformance_catches_broken_backend(monkeypatch):
    """An injected numpy-backend bug is a C401 anchored at the divergent op."""
    from da4ml_tpu.runtime import numpy_backend

    rng = np.random.default_rng(3)
    prog = random_program(rng, n_ops=120, n_in=5, n_out=4)
    real = numpy_backend.run_program

    def broken(p, data, return_buf=False):
        out, buf = real(p, data, return_buf=True)
        bad = next(i for i in range(p.n_ops) if int(p.opcode[i]) == 7)
        buf = buf.copy()
        buf[bad] += 1
        idx = int(p.out_idxs[0]) if int(p.out_idxs[0]) >= 0 else 0
        out = out.copy()
        out[:, 0] = buf[idx] + 1  # force an output divergence too
        return (out, buf) if return_buf else out

    monkeypatch.setattr(numpy_backend, 'run_program', broken)
    diags = check_conformance(prog, modes=('numpy',), n_samples=32)
    assert diags and all(d.rule == 'C401' for d in diags)
    d = diags[0]
    assert d.opcode == 7 and d.op_index is not None
    assert OPCODE_TO_SPEC[d.opcode].family == 'mul'
    assert d.to_dict()['opcode_family'] == 'mul'


def test_conformance_is_opt_in_pass():
    assert 'conformance' in PASSES and 'conformance' in OPT_IN_PASSES
    rng = np.random.default_rng(5)
    prog = random_program(rng, n_ops=60, n_in=4, n_out=3)
    # a structurally clean program passes the full opt-in selection
    from da4ml_tpu.analysis.conformance import check_conformance as chk

    assert not chk(prog, modes=('numpy',), n_samples=16)


# ---------------------------------------------------------------------------
# transfer soundness
# ---------------------------------------------------------------------------


def test_transfer_soundness_all_rows_clean():
    report, diags = check_transfer_soundness(n_cases=20, n_samples=12, seed=1)
    assert report['ok'], [str(d) for d in diags]
    assert set(report['per_family']) == {spec.key for spec in OP_TABLE}


def test_soundness_catches_broken_transfer(monkeypatch):
    """A transfer that narrows the add interval is caught as D310."""
    from da4ml_tpu.ir import optable
    from da4ml_tpu.ir.types import QInterval

    add_spec = next(s for s in OP_TABLE if s.key == 'add')

    def narrowing_transfer(comb, op, q, operand):
        c, _ = optable._tf_add(comb, op, q, operand)
        return QInterval(c.min / 64.0, c.max / 64.0, c.step), []

    broken = add_spec._replace(transfer=narrowing_transfer)
    monkeypatch.setitem(optable.OPCODE_TO_SPEC, 0, broken)
    monkeypatch.setitem(optable.OPCODE_TO_SPEC, 1, broken)
    diags = check_spec_soundness(broken, np.random.default_rng(0), n_cases=10, n_samples=16)
    assert diags and all(d.rule == 'D310' for d in diags)
    assert diags[0].opcode in (0, 1)


# ---------------------------------------------------------------------------
# satellites: drift lint, doc drift, diagnostics opcode field, mutations
# ---------------------------------------------------------------------------


def test_driftlint_repo_is_clean():
    from da4ml_tpu.analysis.driftlint import lint_opcodes

    violations, stale = lint_opcodes(REPO_ROOT)
    assert not violations, [f'{s.path}:{s.lineno} {s.snippet}' for s in violations]
    assert not stale, f'stale allowlist entries: {stale}'


def test_driftlint_catches_new_dispatch_site(tmp_path):
    from da4ml_tpu.analysis.driftlint import lint_opcodes, scan_file

    pkg = tmp_path / 'da4ml_tpu'
    pkg.mkdir()
    evil = pkg / 'evil.py'
    evil.write_text('def f(op):\n    if op.opcode == 7:\n        return 1\n    return abs(op.opcode) == 6\n')
    violations, _ = lint_opcodes(tmp_path)
    assert {v.lineno for v in violations} == {2, 4}
    assert all(v.path == 'da4ml_tpu/evil.py' for v in violations)

    # pattern coverage: ==, in-tuple, abs() wrap, match; assignments and
    # table-constant membership are NOT dispatch sites
    probe = pkg / 'probe.py'
    probe.write_text(
        'def f(op, oc, COPY_OPCODES):\n'
        '    a = oc in (1, 2)\n'
        '    match op.opcode:\n'
        '        case 5: pass\n'
        '    opcode = 5\n'
        '    b = op.opcode in COPY_OPCODES\n'
        '    return a, b\n'
    )
    sites = scan_file(probe, 'probe.py')
    assert {s.lineno for s in sites} == {2, 3}


def test_cli_lint_opcodes():
    from da4ml_tpu._cli import main

    assert main(['lint-opcodes', '--root', str(REPO_ROOT)]) == 0


def test_generated_docs_in_sync():
    from da4ml_tpu.analysis.docgen import apply

    drifted = apply(REPO_ROOT, check=True)
    assert not drifted, f'doc sections drifted from the table: {drifted} (run python -m da4ml_tpu.analysis.docgen)'


def test_docgen_detects_drift(tmp_path):
    from da4ml_tpu.analysis.docgen import SECTIONS, apply

    docs = tmp_path / 'docs'
    docs.mkdir()
    for rel in SECTIONS:  # every generated doc must be present for apply()
        (docs / rel.split('/', 1)[1]).write_text((REPO_ROOT / rel).read_text())
    text = (docs / 'dais.md').read_text().replace('| `7` | mul |', '| `7` | HAND-EDITED |')
    (docs / 'dais.md').write_text(text)
    assert apply(tmp_path, check=True) == ['docs/dais.md']
    # non-check mode repairs it
    assert apply(tmp_path, check=False) == ['docs/dais.md']
    assert apply(tmp_path, check=True) == []


def test_diagnostics_carry_opcode(tmp_path):
    """verify --json output can be grouped per-opcode downstream."""
    from da4ml_tpu._cli import main as cli_main
    from da4ml_tpu.analysis import corruption_by_name

    rng = np.random.default_rng(9)
    prog_rng = np.random.default_rng(4)
    del rng
    # a traced comb with a corrupted mul interval -> Q210 diagnostic
    from da4ml_tpu.cmvm import solve
    from da4ml_tpu.ir import QInterval

    kernel = prog_rng.integers(-8, 8, (5, 4)).astype(np.float64)
    pipe = solve(kernel, qintervals=[QInterval(-8.0, 7.0, 1.0)] * 5)
    bad = corruption_by_name('add.bad_shift').apply(pipe.stages[0])
    result = verify(bad)
    flagged = [d for d in result.diagnostics if d.rule == 'W106']
    assert flagged and flagged[0].opcode in (0, 1)
    assert flagged[0].to_dict()['opcode_family'] == 'add/sub'
    groups = result.by_opcode()
    assert any(k in (0, 1) for k in groups)

    path = tmp_path / 'bad.json'
    bad.save(path)
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(['verify', str(path), '--json'])
    assert rc == 1
    payload = json.loads(buf.getvalue())
    w106 = [d for d in payload['diagnostics'] if d['rule'] == 'W106']
    assert w106 and w106[0]['opcode'] in (0, 1) and w106[0]['opcode_family'] == 'add/sub'


def test_mutation_catalog_is_generated_without_regressions():
    """The table-generated catalog carries exactly the entries the
    hand-written PR-2 catalog had (same names, same expected rules)."""
    legacy = {
        'copy.bad_lane': 'W104',
        'add.forward_ref': 'W103',
        'add.bad_shift': 'W106',
        'relu.step_not_pow2': 'Q201',
        'quantize.inverted_bounds': 'Q202',
        'cadd.bias_drift': 'Q210',
        'const.value_drift': 'Q210',
        'mux.cond_forward': 'W103',
        'mul.narrowed_interval': 'Q210',
        'lut.bad_table': 'W110',
        'bit_unary.bad_subop': 'W111',
        'bit_binary.bad_subop': 'W111',
        'any.unknown_opcode': 'W102',
        'any.nan_latency': 'D302',
        'any.negative_cost': 'D302',
        'io.out_of_range_output': 'W105',
        'io.truncated_inp_shifts': 'W101',
        'io.dead_subgraph': 'D301',
    }
    got = {c.name: c.expect_rule for c in COMB_CORRUPTIONS}
    assert got == legacy
    # one mutation family per table row, by construction
    per_row = {spec.key: [m.name for m in spec.mutations] for spec in OP_TABLE}
    assert all(per_row[spec.key] for spec in OP_TABLE)


def test_cli_verify_fuzz(tmp_path):
    from da4ml_tpu._cli import main as cli_main

    out = tmp_path / 'report.json'
    rc = cli_main(['verify', '--fuzz', '2', '--samples', '16', '--modes', 'numpy', '--out', str(out)])
    assert rc == 0
    report = json.loads(out.read_text())
    assert report['ok'] and report['conformance']['ok'] and report['transfer_soundness']['ok']
    assert set(report['conformance']['per_opcode']) == {str(oc) for oc in DAIS_V1_OPCODES}
    assert report['transfer_soundness']['per_family']['add']['counterexamples'] == 0


def test_cli_verify_no_paths_errors(capsys):
    from da4ml_tpu._cli import main as cli_main

    assert cli_main(['verify']) == 2
    assert 'fuzz' in capsys.readouterr().out
