"""Op-level integration tests: the bit-exactness oracle chain.

For each op: quantized-numpy golden == numpy DAIS interpreter (predict) ==
symbolic CombLogic replay — exact equality, mirroring the reference's
OperationTest harness (tests/test_ops.py:13-60). Ops are given as a pair
(symbolic fn, golden fn); golden defaults to the same fn when it is
numpy-polymorphic.
"""

import numpy as np
import pytest

from da4ml_tpu.ir.types import QInterval, minimal_kif
from da4ml_tpu.ops.numeric import numeric_binary_bit_op, numeric_unary_bit_op
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace
from da4ml_tpu.trace.ops.quantization import fixed_quantize, quantize, relu

N = 8


def random_kif(rng):
    k = rng.integers(0, 2, N)
    i = rng.integers(-2, 5, N)
    f = rng.integers(-2, 5, N)
    f = np.maximum(f, 1 - k - i)
    return k, i, f


def _elem_qints(k, i, f):
    out = []
    for kk, ii, ff in zip(k, i, f):
        step = 2.0**-ff
        hi = 2.0**ii
        out.append(QInterval(-hi * kk, hi - step, step))
    return out


def _bin_out_qint(q0: QInterval, q1: QInterval) -> QInterval:
    k0, i0, f0 = minimal_kif(q0)
    k1, i1, f1 = minimal_kif(q1)
    k, i, f = int(max(k0, k1)), max(i0, i1), max(f0, f1)
    return QInterval(-k * 2.0**i, 2.0**i - 2.0**-f, 2.0**-f)


def check_op(op_sym, op_gold=None, seed=42):
    rng = np.random.default_rng(seed)
    k, i, f = random_kif(rng)
    inp = FixedVariableArrayInput(N, hwconf=HWConfig(1, -1, -1))
    qinp = inp.quantize(k, i, f)
    out = op_sym(qinp)
    comb = comb_trace(inp, out)

    data = rng.uniform(-8, 8, (512, N))
    qdata = fixed_quantize(data, k, i, f)
    gold_fn = op_gold if op_gold is not None else op_sym
    golden = np.array([np.asarray(gold_fn(row), dtype=np.float64).ravel() for row in qdata])

    pred = comb.predict(data, backend='numpy')
    np.testing.assert_array_equal(pred, golden.reshape(pred.shape))

    replay = np.stack([np.asarray(comb(row, quantize=True), dtype=np.float64) for row in data[:64]])
    np.testing.assert_array_equal(replay, golden[:64].reshape(replay.shape))
    return comb


def _gold_bit_binary(subop):
    def fn(row):
        rng = np.random.default_rng(42)
        k, i, f = random_kif(rng)
        qints = _elem_qints(k, i, f)
        out = []
        for a, b, qa, qb in zip(row[:4], row[4:], qints[:4], qints[4:]):
            out.append(numeric_binary_bit_op(float(a), float(b), subop, qa, qb, _bin_out_qint(qa, qb)))
        return np.array(out)

    return fn


def _gold_not(row):
    rng = np.random.default_rng(42)
    k, i, f = random_kif(rng)
    qints = _elem_qints(k, i, f)
    return np.array([numeric_unary_bit_op(float(a), 0, q, q) for a, q in zip(row, qints)])


def _gold_reduce_bit(op):
    def fn(row):
        rng = np.random.default_rng(42)
        k, i, f = random_kif(rng)
        qints = _elem_qints(k, i, f)
        return np.array([numeric_unary_bit_op(float(a), op, q) for a, q in zip(row, qints)])

    return fn


K1, I2, F2 = np.ones(N), np.full(N, 2), np.full(N, 2)

CASES = {
    'identity': (lambda x: x, None),
    'neg': (lambda x: -x, None),
    'scale_pow2': (lambda x: x * 4, None),
    'scale_np2': (lambda x: x * 2.25, None),
    'scale_neg': (lambda x: x * -3.5, None),
    'add_pair': (lambda x: x[:4] + x[4:], None),
    'sub_pair': (lambda x: x[:4] - x[4:], None),
    'cadd': (lambda x: x + 1.5, None),
    'cadd_chain': (lambda x: (x + 1.5) + 0.25, None),
    'relu': (lambda x: relu(x), None),
    'relu_if': (lambda x: relu(x, i=np.full(N, 2), f=np.full(N, 2)), None),
    'relu_rnd': (lambda x: relu(x, i=np.full(N, 2), f=np.full(N, 2), round_mode='RND'), None),
    'quantize_narrow': (lambda x: quantize(x, K1, I2, F2), None),
    'quantize_rnd': (lambda x: quantize(x, K1, I2, F2, round_mode='RND'), None),
    'quantize_sat': (lambda x: quantize(x, K1, I2, F2, overflow_mode='SAT'), None),
    'quantize_sat_sym': (lambda x: quantize(x, K1, I2, F2, overflow_mode='SAT_SYM'), None),
    'abs': (lambda x: abs(x), None),
    'maximum': (lambda x: np.maximum(x[:4], x[4:]), None),
    'minimum': (lambda x: np.minimum(x[:4], x[4:]), None),
    'max_reduce': (lambda x: np.max(x), None),
    'min_reduce': (lambda x: np.min(x), None),
    'sum': (lambda x: np.sum(x), None),
    'mean8': (lambda x: np.mean(x), None),
    'vmul': (lambda x: x[:4] * x[4:], None),
    'square': (lambda x: x * x, None),
    'where': (lambda x: np.where(x[:4] > 0, x[:4], x[4:]), lambda x: np.where(x[:4] > 0, x[:4], x[4:])),
    'clip': (lambda x: np.clip(x, -1.0, 1.0), None),
    'matmul_var': (lambda x: x[:4].reshape(2, 2) @ x[4:].reshape(2, 2), None),
    'matmul_int': (lambda x: x @ np.arange(-2 * N, 2 * N).reshape(N, 4), None),
    'matmul_frac': (lambda x: x @ (np.arange(-2 * N, 2 * N).reshape(N, 4) * 0.25), None),
    'einsum': (lambda x: np.einsum('i,ij->j', x, np.arange(N * 3).reshape(N, 3) * 1.0), None),
    'einsum_rev': (lambda x: np.einsum('ij,j->i', np.arange(N * 3).reshape(3, N) * 1.0, x), None),
    'einsum_elemwise': (lambda x: np.einsum('...i,...i->...i', x[:4], x[4:]), None),
    'einsum_batched_mm': (
        lambda x: np.einsum('...ij,...jk->...ik', x.reshape(2, 2, 2), x.reshape(2, 2, 2)),
        None,
    ),
    'einsum_bcast_l': (lambda x: np.einsum('...i,ij->...j', x.reshape(2, 4), np.arange(12.0).reshape(4, 3)), None),
    'einsum_bcast_r': (lambda x: np.einsum('ij,...j->...i', np.arange(12.0).reshape(3, 4), x.reshape(2, 4)), None),
    'einsum_outer': (lambda x: np.einsum('i,j->ij', x[:4], x[4:]), None),
    'einsum_collapse': (lambda x: np.einsum('ij,jk->k', x.reshape(2, 4), np.arange(12.0).reshape(4, 3)), None),
    'einsum_scalar_out': (lambda x: np.einsum('i,i->', x, np.arange(N) * 1.0), None),
    'einsum_full_collapse': (lambda x: np.einsum('i,j->j', x, np.arange(4.0)), None),
    'dot': (lambda x: np.dot(x, np.arange(N) * 1.0), None),
    'gt': (lambda x: x[:4] > x[4:], lambda x: (x[:4] > x[4:]).astype(np.float64)),
    'le': (lambda x: x[:4] <= x[4:], lambda x: (x[:4] <= x[4:]).astype(np.float64)),
    'and': (lambda x: x[:4] & x[4:], _gold_bit_binary(0)),
    'or': (lambda x: x[:4] | x[4:], _gold_bit_binary(1)),
    'xor': (lambda x: x[:4] ^ x[4:], _gold_bit_binary(2)),
    'not': (lambda x: ~x, _gold_not),
    'any_elem': (lambda x: x.to_bool('any'), _gold_reduce_bit(1)),
    'all_elem': (lambda x: x.to_bool('all'), _gold_reduce_bit(2)),
}


@pytest.mark.parametrize('name', sorted(CASES))
def test_op(name):
    op_sym, op_gold = CASES[name]
    check_op(op_sym, op_gold)


def test_lookup_sin():
    check_op(
        lambda x: np.sin(x).quantize(K1, np.ones(N), np.full(N, 4)),
        lambda x: fixed_quantize(np.sin(x), 1, 1, 4),
    )


def test_lookup_composite():
    check_op(
        lambda x: np.tanh(np.sin(x)).quantize(K1, np.ones(N), np.full(N, 4)),
        lambda x: fixed_quantize(np.tanh(np.sin(x)), 1, 1, 4),
    )


def test_retrace():
    """IR round-trips through symbolic replay + re-trace (reference pattern)."""
    from da4ml_tpu.trace import FixedVariable

    op, _ = CASES['matmul_int']
    comb = check_op(op)
    hwconf = HWConfig(comb.adder_size, comb.carry_size, -1)
    inp = [FixedVariable(*qint, hwconf=hwconf) for qint in comb.inp_qint]
    out = list(comb(inp))
    comb2 = comb_trace(inp, out)
    assert comb.shape == comb2.shape
    data = np.random.default_rng(0).uniform(-8, 8, (128, N))
    np.testing.assert_array_equal(comb.predict(data, backend='numpy'), comb2.predict(data, backend='numpy'))


def test_serialization_roundtrip(tmp_path):
    op, _ = CASES['matmul_frac']
    comb = check_op(op)
    path = tmp_path / 'comb.json'
    comb.save(path)
    from da4ml_tpu.ir import CombLogic

    comb2 = CombLogic.load(path)
    assert comb == comb2


def test_sort():
    rng = np.random.default_rng(7)
    inp = FixedVariableArrayInput(6, hwconf=HWConfig(1, -1, -1))
    q = inp.quantize(np.ones(6), np.full(6, 3), np.full(6, 1))
    out = np.sort(q)
    comb = comb_trace(inp, out)
    data = rng.uniform(-8, 8, (256, 6))
    qdata = fixed_quantize(data, 1, 3, 1)
    golden = np.sort(qdata, axis=-1)
    np.testing.assert_array_equal(comb.predict(data, backend='numpy'), golden)


def test_argsort_gather():
    rng = np.random.default_rng(8)
    inp = FixedVariableArrayInput(5, hwconf=HWConfig(1, -1, -1))
    q = inp.quantize(np.ones(5), np.full(5, 3), np.full(5, 0))
    payload = q * 2
    order = np.argsort(q)
    out = payload[order]
    comb = comb_trace(inp, out.ravel())
    data = rng.uniform(-8, 8, (128, 5))
    qdata = fixed_quantize(data, 1, 3, 0)
    golden = np.stack([2 * np.sort(row) for row in qdata])
    np.testing.assert_array_equal(comb.predict(data, backend='numpy'), golden)


def test_input_precision_widening():
    inp = FixedVariableArrayInput(4, hwconf=HWConfig(1, -1, -1))
    a = inp.quantize(np.ones(4), np.full(4, 2), np.full(4, 1))
    b = inp.quantize(np.ones(4), np.full(4, 3), np.full(4, 0))
    out = a + b
    comb = comb_trace(inp, out)
    k, i, f = comb.inp_kifs
    assert (i >= 3).all() and (f >= 1).all()


def test_einsum_batched_jax_backend(rng):
    """Batched einsum blocks solve as one device batch on backend='jax'."""
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    shape = (3, 4, 5)
    inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, -1), solver_options={'backend': 'jax'})
    x = inp.quantize(np.ones(shape), np.full(shape, 3), np.zeros(shape, np.int64))
    w = rng.integers(-4, 4, (3, 5, 2)).astype(np.float64)
    for expr, ref_fn in (
        ('bmk,bkn->bmn', lambda d: np.einsum('bmk,bkn->bmn', d, w)),
        ('bkn,bmk->bmn', lambda d: np.einsum('bkn,bmk->bmn', w, d)),
    ):
        if expr == 'bmk,bkn->bmn':
            y = np.einsum(expr, x, w)
        else:  # const as the first operand exercises the transposed batch path
            y = np.einsum(expr, w, x)
        comb = comb_trace(inp, y)
        data = rng.integers(-8, 8, (8, *shape)).astype(np.float64)
        out = comb.predict(data.reshape(8, -1), backend='numpy')
        ref = np.stack([ref_fn(d) for d in data])
        np.testing.assert_array_equal(out, ref.reshape(8, -1))
