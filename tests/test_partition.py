"""Model-axis partitioner + sharded runtime (8-device virtual CPU mesh).

Covers the GSPMD model-parallel plane end to end: the level-segment
partitioner (`ir.partition`), the shard_map execution path inside
`DaisExecutor`, the export-time plan stamped into serving artifacts, and
the mesh/shape helpers in `parallel`. Every parity assertion is bit-exact:
the sharded program must be indistinguishable from single-device execution.
"""

import json

import numpy as np
import pytest

from da4ml_tpu.ir import synth
from da4ml_tpu.ir.dais_binary import encode
from da4ml_tpu.ir.partition import (
    build_shards,
    partition_program,
    plan_from_dict,
    plan_to_dict,
    validate_plan,
)
from da4ml_tpu.runtime import jax_backend as jb
from da4ml_tpu.runtime import numpy_backend as nb
from da4ml_tpu.runtime.jax_backend import DaisExecutor

# (seed, kwargs) — uneven levels, wide-i64, shallow/wide: the shapes that
# stress segment choice, the int64 carry path, and per-level balance
CORPUS = [
    (11, dict(n_ops=200, n_in=8, n_out=6)),
    (12, dict(n_ops=260, n_in=12, n_out=9, wide=True, n_levels=10)),
    (13, dict(n_ops=220, n_in=6, n_out=5, n_levels=25)),
    (14, dict(n_ops=180, n_in=10, n_out=4, n_levels=4)),
]


def _prog(seed: int, kwargs: dict):
    return synth.random_program(np.random.default_rng(seed), **kwargs)


@pytest.fixture
def shard_env(monkeypatch, tmp_path):
    """Isolated shard/mode decision caches; mode autotune off."""
    import jax

    old = jax.config.jax_compilation_cache_dir
    jax.config.update('jax_compilation_cache_dir', str(tmp_path))
    monkeypatch.setenv('DA4ML_RUN_AUTOTUNE', '0')
    saved_s, saved_m = dict(jb._SHARD_DECISIONS), dict(jb._MODE_DECISIONS)
    jb._SHARD_DECISIONS.clear()
    yield tmp_path
    jb._SHARD_DECISIONS.clear()
    jb._SHARD_DECISIONS.update(saved_s)
    jb._MODE_DECISIONS.clear()
    jb._MODE_DECISIONS.update(saved_m)
    jax.config.update('jax_compilation_cache_dir', old)


# ---------------------------------------------------------------------------
# plan construction + serialization
# ---------------------------------------------------------------------------


@pytest.mark.parametrize('k', [1, 2, 4, 8])
def test_partition_plan_well_formed(k):
    prog = _prog(*CORPUS[0])
    plan = partition_program(prog, k)
    validate_plan(prog, plan)  # digest, ranges, closure — must not raise
    build = build_shards(prog, plan)
    assert build.plan is plan
    assert int(build.shard_ops.sum()) == prog.n_ops
    if k > 1:
        assert build.imbalance >= 1.0
        # every boundary's exchange is k * padded-slab rows
        for g in range(plan.n_segments - 1):
            assert build.exchange_rows(g) == k * build.export_pad[g]


def test_plan_roundtrip_and_validation():
    prog = _prog(*CORPUS[1])
    plan = partition_program(prog, 4)
    doc = json.loads(json.dumps(plan_to_dict(plan)))
    plan2 = plan_from_dict(doc)
    assert plan2.k == plan.k and plan2.program_digest == plan.program_digest
    assert np.array_equal(plan2.assign, plan.assign)
    assert np.array_equal(plan2.seg_levels, plan.seg_levels)
    validate_plan(prog, plan2)
    # a plan built for a different program is refused fail-closed
    other = _prog(*CORPUS[2])
    with pytest.raises(ValueError, match='digest|ops'):
        validate_plan(other, plan2)
    # as is a tampered assignment
    bad = plan2._replace(assign=np.asarray([plan2.k] + list(plan2.assign[1:])))
    with pytest.raises(ValueError):
        validate_plan(prog, bad)


# ---------------------------------------------------------------------------
# sharded execution parity (forced k-way over the synth corpus)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize('case,k', [(0, 2), (1, 4), (2, 8), (3, 4)])
def test_model_shard_parity_fuzz(shard_env, monkeypatch, case, k):
    monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', str(k))
    seed, kwargs = CORPUS[case]
    prog = _prog(seed, kwargs)
    data = synth.random_inputs(np.random.default_rng(seed + 100), prog, 64)
    ref = np.asarray(nb.run_program(prog, data))

    ex = DaisExecutor(prog)
    assert ex.model_shards == k, 'forced policy must adopt the k-way cut'
    np.testing.assert_array_equal(np.asarray(ex(data)), ref)

    monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', '0')
    single = DaisExecutor(prog)
    assert single.model_shards == 0
    np.testing.assert_array_equal(np.asarray(single(data)), ref)


def test_model_shard_pallas_per_shard(shard_env, monkeypatch):
    """mode='pallas' lowers one mega-kernel per shard cell; parity holds."""
    monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', '4')
    monkeypatch.setenv('DA4ML_PALLAS_INTERPRET', '1')
    prog = _prog(*CORPUS[0])
    data = synth.random_inputs(np.random.default_rng(7), prog, 32)
    ex = DaisExecutor(prog, mode='pallas')
    assert ex.model_shards == 4 and ex.mode == 'pallas'
    np.testing.assert_array_equal(np.asarray(ex(data)), np.asarray(nb.run_program(prog, data)))


def test_model_shard_vmem_exceeding_program(shard_env, monkeypatch):
    """A program whose pallas footprint busts one chip's VMEM budget still
    runs in mode='pallas' once 4-way partitioned (each cell fits)."""
    monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', '4')
    monkeypatch.setenv('DA4ML_PALLAS_INTERPRET', '1')
    monkeypatch.setenv('DA4ML_PALLAS_VMEM', str(64 << 10))
    prog = _prog(*CORPUS[2])
    data = synth.random_inputs(np.random.default_rng(8), prog, 16)
    ex = DaisExecutor(prog, mode='pallas')
    assert ex.model_shards == 4
    np.testing.assert_array_equal(np.asarray(ex(data)), np.asarray(nb.run_program(prog, data)))


def test_ragged_batch_parity(shard_env, monkeypatch):
    """Small/ragged batches are padded onto the canonical grid, split across
    the mesh, and trimmed — byte-identical to single-device execution."""
    monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', '4')
    prog = _prog(*CORPUS[1])
    ex = DaisExecutor(prog)
    assert ex.model_shards == 4
    monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', '0')
    single = DaisExecutor(prog)
    rng = np.random.default_rng(9)
    for n in (1, 3, 7, 13):
        data = synth.random_inputs(rng, prog, n)
        a, b = np.asarray(ex(data)), np.asarray(single(data))
        assert a.shape[0] == n
        assert a.tobytes() == b.tobytes()


# ---------------------------------------------------------------------------
# policy + race decision cache
# ---------------------------------------------------------------------------


def test_model_shard_policy_parsing(monkeypatch):
    cases = {
        '0': ('off', 0),
        'off': ('off', 0),
        '': ('tpu', 0),
        'default': ('tpu', 0),
        'auto': ('race', 0),
        'on': ('force', 0),
        '1': ('force', 0),
        '4': ('force', 4),
        'bogus': ('tpu', 0),
    }
    for env, want in cases.items():
        monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', env)
        assert jb._model_shard_request() == want, env


def test_race_decision_cache_controls_adoption(shard_env, monkeypatch):
    """The race obeys its cached measurement: 0 = single-device won (never
    shard), k = sharded won (adopt without re-measuring)."""
    prog = _prog(*CORPUS[3])
    monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', '0')
    digest, platform = DaisExecutor(prog)._digest(), jb._platform()

    monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', 'auto')
    jb._SHARD_DECISIONS[(digest, platform)] = 0
    assert DaisExecutor(prog).model_shards == 0, 'measured loser must never be adopted'
    jb._SHARD_DECISIONS[(digest, platform)] = 4
    assert DaisExecutor(prog).model_shards == 4, 'measured winner adopts from cache'


def test_race_measures_and_persists(shard_env, monkeypatch):
    """policy 'auto' with a cold cache measures both sides and persists the
    verdict next to the mode decisions."""
    monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', 'auto')
    monkeypatch.setenv('DA4ML_RUN_AUTOTUNE_BATCH', '64')
    prog = _prog(*CORPUS[0])
    ex = DaisExecutor(prog)
    assert len(jb._SHARD_DECISIONS) == 1
    ((digest, platform), win) = next(iter(jb._SHARD_DECISIONS.items()))
    assert win in (0, 8)
    assert ex.model_shards == win
    blob = json.loads((shard_env / 'da4ml-run-modes' / f'{digest}.{platform}.shard.json').read_text())
    assert blob['model_shard'] == win
    assert blob['sharded_samples_per_s'] > 0 and blob['single_samples_per_s'] > 0


# ---------------------------------------------------------------------------
# mesh + shape helpers
# ---------------------------------------------------------------------------


def test_resolve_mesh_policy(monkeypatch):
    from da4ml_tpu.parallel import resolve_mesh

    monkeypatch.delenv('DA4ML_JAX_MESH', raising=False)
    assert resolve_mesh() is None, 'default policy is TPU-only'
    mesh = resolve_mesh(tpu_only=False)
    assert mesh is not None and mesh.devices.size == 8 and mesh.axis_names == ('batch',)
    monkeypatch.setenv('DA4ML_JAX_MESH', '1')
    assert resolve_mesh() is not None
    monkeypatch.setenv('DA4ML_JAX_MESH', '0')
    assert resolve_mesh(tpu_only=False) is None


def test_model_mesh_topology(monkeypatch):
    from da4ml_tpu.parallel import model_mesh

    monkeypatch.delenv('DA4ML_JAX_MESH', raising=False)
    for k in (2, 4, 8):
        mesh = model_mesh(k)
        assert mesh is not None and mesh.axis_names == ('batch', 'model')
        assert mesh.devices.shape == (8 // k, k)
    assert model_mesh(1) is None
    assert model_mesh(3) is None, '8 % 3 != 0: no even split'
    assert model_mesh(16) is None, 'more shards than devices'
    monkeypatch.setenv('DA4ML_JAX_MESH', '0')
    assert model_mesh(4) is None


def test_canon_multiple_grid():
    from da4ml_tpu.parallel.shapes import canon_multiple, pad_rows_multiple

    assert canon_multiple(5, 8) == 8
    assert canon_multiple(9, 8) == 16
    assert canon_multiple(16, 8) == 16
    assert canon_multiple(17, 5) == 20
    # off-grid multiples fall back to plain round-up
    assert canon_multiple(10, 7) == 14
    assert canon_multiple(100, 7) == 105
    padded, n = pad_rows_multiple(np.ones((5, 3)), 8)
    assert padded.shape == (8, 3) and n == 5 and padded[5:].sum() == 0


# ---------------------------------------------------------------------------
# export artifact + serve hot-load
# ---------------------------------------------------------------------------


def test_export_plan_roundtrip_and_tamper(tmp_path):
    from da4ml_tpu.serve.export import export_model, load_artifact, load_partition_plan

    prog = _prog(*CORPUS[0])
    outdir = tmp_path / 'art'
    meta = export_model(encode(prog), outdir, model_shards=4, stablehlo=False)
    assert meta['model_shards'] == 4 and meta['partition'] == 'partition.json'
    plan = load_partition_plan(outdir)
    assert plan is not None and plan.k == 4
    validate_plan(prog, plan)

    # artifacts without a plan stay plan-free
    meta2 = export_model(encode(prog), tmp_path / 'plain', stablehlo=False)
    assert meta2['partition'] is None and load_partition_plan(tmp_path / 'plain') is None

    # flipping one shard assignment in partition.json must be refused
    pj = outdir / 'partition.json'
    doc = json.loads(pj.read_text())
    doc['assign'][0] = (doc['assign'][0] + 1) % 4
    pj.write_text(json.dumps(doc, separators=(',', ':')))
    with pytest.raises(ValueError, match='partition plan digest mismatch'):
        load_artifact(outdir)


def test_serve_hot_loads_model_sharded(shard_env, monkeypatch, tmp_path):
    """A warm replica adopts the artifact's export-time plan (no race) and a
    same-artifact reload reuses the warm executor — zero new compiles."""
    from da4ml_tpu.serve.engine import ServeConfig, ServeEngine
    from da4ml_tpu.serve.export import export_model

    monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', 'auto')
    prog = _prog(*CORPUS[0])
    outdir = tmp_path / 'art'
    export_model(encode(prog), outdir, model_shards=4, stablehlo=False)

    eng = ServeEngine(ServeConfig(prewarm=False))
    eng.load_model('m', str(outdir))
    ex = eng._executor_for(eng._state('m'))
    assert ex.model_shards == 4, 'artifact plan is authoritative — no re-race'
    assert not jb._SHARD_DECISIONS, 'plan adoption must not run the race'

    data = synth.random_inputs(np.random.default_rng(3), prog, 24)
    np.testing.assert_array_equal(np.asarray(ex(data)), np.asarray(nb.run_program(prog, data)))

    eng.reload('m', str(outdir))
    assert eng._executor_for(eng._state('m')) is ex, 'same artifact: warm executor reused'


def test_single_device_host_ignores_plan(shard_env, monkeypatch):
    """A host whose topology cannot host the plan's mesh serves the same
    artifact single-device (the plan is advisory off-mesh)."""
    prog = _prog(*CORPUS[3])
    plan = partition_program(prog, 3)  # 8 % 3 != 0: unhostable here
    monkeypatch.setenv('DA4ML_RUN_MODEL_SHARD', 'auto')
    ex = DaisExecutor(prog, partition_plan=plan)
    assert ex.model_shards == 0
    data = synth.random_inputs(np.random.default_rng(4), prog, 8)
    np.testing.assert_array_equal(np.asarray(ex(data)), np.asarray(nb.run_program(prog, data)))
