"""Telemetry subsystem: spans, metrics, exporters, and pipeline integration.

Covers the observability acceptance surface (docs/telemetry.md):

- disabled path: no-op singleton, zero events, <2% solve overhead;
- span nesting and thread-safety under parallel multi-worker solves;
- Chrome trace-event JSON schema validity (ph/ts/pid/tid/name keys);
- metrics round-trip through ``SolveReport.to_dict()``;
- a full trace→solve→codegen run producing spans from four subsystems;
- CLI ``--trace`` capture and the ``stats`` renderer.
"""

import json
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from da4ml_tpu import telemetry
from da4ml_tpu._cli import main
from da4ml_tpu.cmvm import solve
from da4ml_tpu.reliability import SolveReport
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    """Telemetry is process-global state: start and leave every test clean."""
    telemetry.reset()
    yield
    telemetry.reset()


def _small_kernel(seed=3, n=6, m=4):
    rng = np.random.default_rng(seed)
    return rng.integers(-8, 8, (n, m)).astype(np.float64)


def _traced_comb():
    """trace → cmvm solve (orchestrated) → CombLogic, as a conversion does."""
    rng = np.random.default_rng(7)
    inp = FixedVariableArrayInput(6, HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(6), np.full(6, 3), np.full(6, 2))
    w = rng.integers(-8, 8, (6, 4)).astype(np.float64)
    return comb_trace(inp, (x @ w).relu(i=np.full(4, 6), f=np.full(4, 2)))


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------


def test_disabled_no_sink_receives_events(monkeypatch):
    monkeypatch.delenv('DA4ML_TRACE', raising=False)
    received = []

    class Probe:
        def emit(self, ev):
            received.append(ev)

        def close(self):
            pass

    # the probe exists but is never registered — exactly the DA4ML_TRACE-unset
    # state: no sink, so nothing anywhere may receive events
    Probe()
    assert not telemetry.tracing_active()
    assert telemetry.span('a') is telemetry.span('b')  # shared no-op singleton
    solve(_small_kernel(), backend='cpu')
    assert received == []
    assert telemetry.metrics_snapshot() == {}  # metrics registry never armed


def test_noop_span_is_reusable_and_falsy():
    sp = telemetry.span('x', k=1)
    assert not sp
    with sp as inner:
        assert inner.span_id is None
        inner.set(more=2)  # must not raise
    with sp:  # reentrant
        pass


def test_disabled_overhead_under_2pct():
    """Acceptance: telemetry-disabled instrumentation costs <2% of a solve."""
    kernel = _small_kernel(5, 8, 8)
    solve(kernel, backend='cpu')  # warm caches
    t0 = time.perf_counter()
    solve(kernel, backend='cpu')
    solve_s = time.perf_counter() - t0

    n = 10_000
    t0 = time.perf_counter()
    for _ in range(n):
        with telemetry.span('bench.noop', backend='cpu'):
            pass
        telemetry.counter('bench.noop').inc()
        telemetry.histogram('bench.noop_s').observe(0.0)
    per_call = (time.perf_counter() - t0) / n
    # one solve passes ~dozens of instrumentation sites; budget 100 of them
    assert 100 * per_call < 0.02 * solve_s, (per_call, solve_s)


# ---------------------------------------------------------------------------
# spans: nesting, threads, exporters
# ---------------------------------------------------------------------------


def test_span_nesting_and_chrome_schema(tmp_path):
    path = tmp_path / 'trace.json'
    telemetry.enable(path)
    with telemetry.span('outer', kind='test') as so:
        with telemetry.span('mid') as sm:
            with telemetry.span('leaf') as sl:
                pass
        assert sm.parent_id == so.span_id
        assert sl.parent_id == sm.span_id
    telemetry.instant('tick', n=1)
    telemetry.disable()

    events, _ = telemetry.load_trace(path)
    telemetry.validate_trace(events)
    by_name = {e['name']: e for e in events}
    assert by_name['leaf']['args']['parent_id'] == by_name['mid']['args']['span_id']
    assert by_name['mid']['args']['parent_id'] == by_name['outer']['args']['span_id']
    assert 'parent_id' not in by_name['outer']['args']
    assert by_name['tick']['ph'] == 'i'
    # containment: a child span lies inside its parent's [ts, ts+dur] window
    for child, parent in (('leaf', 'mid'), ('mid', 'outer')):
        c, p = by_name[child], by_name[parent]
        assert c['ts'] >= p['ts'] - 1e-6
        assert c['ts'] + c['dur'] <= p['ts'] + p['dur'] + 1e-6


def test_jsonl_sink_streams_and_appends_metrics(tmp_path):
    path = tmp_path / 'trace.jsonl'
    telemetry.enable(path)
    with telemetry.span('one'):
        pass
    telemetry.counter('c.x').inc(2)
    telemetry.disable()
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    # line 0 is the clock anchor the fleet collector aligns processes with
    assert lines[0]['name'] == 'clock_sync' and lines[0]['args']['unix_time_us'] > 0
    assert lines[1]['name'] == 'one' and lines[1]['ph'] == 'X'
    assert lines[-1]['ph'] == 'M' and lines[-1]['args']['metrics']['c.x']['value'] == 2.0
    events, metrics = telemetry.load_trace(path)
    telemetry.validate_trace(events)
    assert metrics['c.x']['value'] == 2.0


def test_span_thread_safety_parallel_solves(tmp_path):
    """Concurrent multi-worker solves: per-thread stacks must keep parentage
    within one thread and every exported event schema-valid."""
    path = tmp_path / 'trace.json'
    telemetry.enable(path)
    kernels = [_small_kernel(seed) for seed in range(8)]

    def one(kern):
        report = SolveReport()
        solve(kern, backend='cpu', report=report)
        return report

    with ThreadPoolExecutor(max_workers=4) as ex:
        reports = list(ex.map(one, kernels))
    telemetry.disable()

    events, _ = telemetry.load_trace(path)
    telemetry.validate_trace(events)
    spans = [e for e in events if e['ph'] == 'X']
    assert len({e['tid'] for e in spans}) > 1  # genuinely multi-threaded
    # parent links never cross threads
    by_id = {e['args']['span_id']: e for e in spans}
    for e in spans:
        parent = e['args'].get('parent_id')
        if parent is not None and parent in by_id:
            assert by_id[parent]['tid'] == e['tid']
    # every solve recorded its own root + attempt spans
    roots = [e for e in spans if e['name'] == 'reliability.solve']
    assert len(roots) == len(kernels)
    for rep in reports:
        assert rep.backend_used == 'pure-python'
        assert rep.phases  # phase collector worked on every worker thread


def test_collect_phases_is_thread_local():
    done = threading.Event()
    leaked = {}

    def other():
        done.wait(5)
        with telemetry.span('other.span'):
            pass

    t = threading.Thread(target=other)
    with telemetry.collect_phases() as phases:
        t.start()
        with telemetry.span('mine.span'):
            pass
        done.set()
        t.join()
        leaked = dict(phases)
    assert 'mine.span' in leaked
    assert 'other.span' not in leaked


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def test_metrics_registry_roundtrip():
    telemetry.enable(metrics=True)
    telemetry.counter('t.count').inc()
    telemetry.counter('t.count').inc(4)
    telemetry.gauge('t.gauge').set(2.5)
    h = telemetry.histogram('t.hist')
    for v in (0.0002, 0.02, 3.0):
        h.observe(v)
    snap = telemetry.metrics_snapshot()
    assert snap['t.count'] == {'type': 'counter', 'value': 5.0}
    assert snap['t.gauge']['value'] == 2.5
    hs = snap['t.hist']
    assert hs['count'] == 3 and hs['min'] == 0.0002 and hs['max'] == 3.0
    assert sum(hs['buckets']) == 3
    json.dumps(snap)  # JSON-serializable end to end


def test_metric_type_conflict_raises():
    telemetry.enable(metrics=True)
    telemetry.counter('t.same').inc()
    with pytest.raises(TypeError):
        telemetry.gauge('t.same')


def test_breaker_transitions_recorded():
    from da4ml_tpu.reliability.breaker import CircuitBreaker

    telemetry.enable(metrics=True)
    br = CircuitBreaker('probe', fail_threshold=2, reset_after=30.0)
    br.record_failure()
    br.record_failure()  # opens
    snap = telemetry.metrics_snapshot()
    assert snap['breaker.state.probe']['value'] == 1.0
    assert snap['breaker.transitions']['value'] == 1.0
    br.record_success()  # closes
    snap = telemetry.metrics_snapshot()
    assert snap['breaker.state.probe']['value'] == 0.0
    assert snap['breaker.transitions']['value'] == 2.0


# ---------------------------------------------------------------------------
# SolveReport integration
# ---------------------------------------------------------------------------


def test_solve_report_phases_and_span_ids(tmp_path):
    path = tmp_path / 'trace.json'
    telemetry.enable(path)
    report = SolveReport()
    solve(_small_kernel(), backend='cpu', report=report)
    telemetry.disable()

    d = report.to_dict()
    assert d['backend_used'] == 'pure-python'
    assert d['phases'], 'phase timings must be attached'
    assert 'cmvm.dispatch' in d['phases']
    assert all(v >= 0 for v in d['phases'].values())
    assert isinstance(d['trace_span_id'], int)
    assert all(isinstance(a['span_id'], int) for a in d['attempts'])
    json.dumps(d)  # the whole report stays JSON-serializable


def test_solve_report_phases_without_sink():
    """A passed-in report collects phases even with no trace file at all."""
    report = SolveReport()
    solve(_small_kernel(), backend='cpu', report=report)
    assert report.phases and 'cmvm.dispatch' in report.phases
    assert report.trace_span_id is None or isinstance(report.trace_span_id, int)


def test_campaign_heartbeats(tmp_path):
    from da4ml_tpu.reliability import solve_many

    path = tmp_path / 'trace.jsonl'
    telemetry.enable(path)
    kernels = [_small_kernel(seed) for seed in range(3)]
    results, report = solve_many(kernels, backend='pure-python')
    telemetry.disable()
    assert len(results) == 3
    events, metrics = telemetry.load_trace(path)
    beats = [e for e in events if e['name'] == 'campaign.progress']
    assert [b['args']['done'] for b in beats] == [1, 2, 3]
    assert all(b['args']['total'] == 3 for b in beats)
    assert metrics['campaign.done']['value'] == 3.0


# ---------------------------------------------------------------------------
# end-to-end: four subsystems in one trace
# ---------------------------------------------------------------------------


def test_trace_solve_codegen_four_subsystems(tmp_path):
    """Acceptance: one conversion-shaped run emits spans from trace, cmvm,
    reliability, and codegen."""
    from da4ml_tpu.codegen import RTLModel

    path = tmp_path / 'trace.json'
    telemetry.enable(path)
    comb = _traced_comb()
    RTLModel(comb, 'model', tmp_path / 'prj', latency_cutoff=-1).write()
    telemetry.disable()

    events, _ = telemetry.load_trace(path)
    telemetry.validate_trace(events)
    subsystems = {e['name'].split('.', 1)[0] for e in events if e['ph'] == 'X'}
    assert {'trace', 'cmvm', 'reliability', 'codegen'} <= subsystems, subsystems


def test_cli_keras_convert_trace_four_subsystems(tmp_path):
    """Acceptance: a --trace-captured `da4ml-tpu convert` of a model file
    yields valid Chrome trace JSON with spans from >= 4 subsystems."""
    keras = pytest.importorskip('keras')

    model = keras.Sequential(
        [
            keras.layers.Input((4,)),
            keras.layers.Dense(3, kernel_initializer='he_normal'),
        ]
    )
    model_path = tmp_path / 'm.keras'
    model.save(model_path)
    trace_path = tmp_path / 'trace.json'
    rc = main(
        [
            'convert', str(model_path), str(tmp_path / 'prj'),
            '--trace', str(trace_path), '-n', '16', '-v', '0', '-ikif', '1', '3', '2',
        ]  # fmt: skip
    )
    assert rc == 0
    events, metrics = telemetry.load_trace(trace_path)
    telemetry.validate_trace(events)
    subsystems = {e['name'].split('.', 1)[0] for e in events if e['ph'] == 'X'}
    assert {'trace', 'cmvm', 'reliability', 'codegen'} <= subsystems, subsystems
    assert metrics['solve.calls']['value'] >= 1


def test_env_var_activation(tmp_path):
    """DA4ML_TRACE=<path> alone (no code changes) captures a trace."""
    path = tmp_path / 'env_trace.json'
    code = (
        'import numpy as np\n'
        'from da4ml_tpu.cmvm import solve\n'
        "solve(np.array([[1.0, 2.0], [3.0, -1.0]]), backend='cpu')\n"
    )
    env = dict(os.environ, DA4ML_TRACE=str(path), JAX_PLATFORMS='cpu')
    subprocess.run([sys.executable, '-c', code], check=True, env=env, timeout=120)
    events, metrics = telemetry.load_trace(path)
    telemetry.validate_trace(events)
    assert any(e['name'] == 'cmvm.solve' for e in events)
    assert metrics['solve.calls']['value'] == 1.0


# ---------------------------------------------------------------------------
# CLI: --trace and stats
# ---------------------------------------------------------------------------


def test_cli_convert_trace_and_stats(tmp_path, capsys):
    comb = _traced_comb()
    model_json = tmp_path / 'comb.json'
    comb.save(model_json)
    trace_path = tmp_path / 'trace.json'
    rc = main(
        ['convert', str(model_json), str(tmp_path / 'prj'), '-n', '32', '-v', '0', '--trace', str(trace_path)]
    )
    assert rc == 0
    events, _ = telemetry.load_trace(trace_path)
    telemetry.validate_trace(events)
    names = {e['name'] for e in events}
    assert 'cli.convert' in names and 'codegen.rtl.write' in names and 'runtime.run_comb' in names

    capsys.readouterr()
    assert main(['stats', str(trace_path), '--validate']) == 0
    out = capsys.readouterr().out
    assert 'cli.convert' in out and 'codegen.rtl.write' in out

    capsys.readouterr()
    assert main(['stats', str(trace_path), '--json']) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc['n_events'] == len(events)
    assert doc['spans']['cli.convert']['count'] == 1


def test_stats_missing_file(tmp_path, capsys):
    assert main(['stats', str(tmp_path / 'nope.json')]) == 1


# ---------------------------------------------------------------------------
# logging satellite
# ---------------------------------------------------------------------------


def test_get_logger_stdout_and_stderr(capsys):
    log = telemetry.get_logger('test.site')
    log.info('plain info line')
    log.warning('something odd')
    cap = capsys.readouterr()
    assert 'plain info line\n' in cap.out
    assert '[WARNING] something odd\n' in cap.err
    assert 'plain info line' not in cap.err


def test_log_records_mirrored_into_trace(tmp_path):
    path = tmp_path / 'trace.json'
    telemetry.enable(path)
    telemetry.get_logger('test.mirror').warning('breaker opened')
    telemetry.disable()
    events, _ = telemetry.load_trace(path)
    warn = [e for e in events if e['name'] == 'log.warning']
    assert warn and warn[0]['args']['message'] == 'breaker opened'


# ---------------------------------------------------------------------------
# fleet trace context (docs/observability.md#fleet-tracing)
# ---------------------------------------------------------------------------


def test_traceparent_roundtrip_and_rejects():
    tid = telemetry.new_trace_id()
    sid = telemetry.new_span_id()
    assert len(tid) == 32 and int(tid, 16) != 0
    assert isinstance(sid, int) and sid > 0
    hdr = telemetry.format_traceparent(tid, sid)
    assert hdr == f'00-{tid}-{sid:016x}-01'
    assert telemetry.parse_traceparent(hdr) == (tid, sid)
    # malformed inputs all map to None (caller mints a fresh context)
    assert telemetry.parse_traceparent(None) is None
    assert telemetry.parse_traceparent('') is None
    assert telemetry.parse_traceparent('not-a-header') is None
    assert telemetry.parse_traceparent('01-' + 'a' * 32 + '-' + 'b' * 16 + '-01') is None  # unknown version
    assert telemetry.parse_traceparent('00-' + '0' * 32 + '-' + 'b' * 16 + '-01') is None  # all-zero trace id
    assert telemetry.parse_traceparent('00-' + 'a' * 30 + '-' + 'b' * 16 + '-01') is None  # short trace id
    assert telemetry.parse_traceparent('00-' + 'g' * 32 + '-' + 'b' * 16 + '-01') is None  # non-hex
    # all-zero parent span id -> valid context with no remote parent
    assert telemetry.parse_traceparent('00-' + 'a' * 32 + '-' + '0' * 16 + '-01') == ('a' * 32, None)


def test_bind_trace_attaches_trace_id_and_remote_parent(tmp_path):
    path = tmp_path / 'trace.jsonl'
    telemetry.enable(path)
    tid = 'ab' * 16
    with telemetry.bind_trace(tid, 0xBEEF):
        assert telemetry.current_trace_id() == tid
        with telemetry.span('root_here'):
            with telemetry.span('child'):
                pass
        telemetry.instant('tick')
    assert telemetry.current_trace() is None  # restored on exit
    telemetry.disable()
    events, _ = telemetry.load_trace(path)
    by = {e['name']: e for e in events}
    # the in-process root adopts the remote caller's span as parent
    assert by['root_here']['args']['trace_id'] == tid
    assert by['root_here']['args']['parent_id'] == 0xBEEF
    # nested spans keep in-thread parentage but share the trace id
    assert by['child']['args']['trace_id'] == tid
    assert by['child']['args']['parent_id'] == by['root_here']['args']['span_id']
    # instants under a binding are taggable too
    assert by['tick']['args']['trace_id'] == tid


def test_bind_trace_mints_when_unset_and_span_ids_are_ints():
    with telemetry.bind_trace() as tb:
        assert len(tb.trace_id) == 32 and int(tb.trace_id, 16) != 0
        assert tb.parent_span_id is None
    d = json.loads(json.dumps({'trace_span_id': telemetry.new_span_id()}))
    assert isinstance(d['trace_span_id'], int)  # span ids stay ints on the wire


def test_fork_reseeds_span_id_epoch():
    """Regression: a forked child must not mint span ids colliding with the
    parent's sequence — the per-process epoch is re-seeded after fork."""
    if not hasattr(os, 'fork'):
        pytest.skip('platform has no fork')
    import multiprocessing

    ctx = multiprocessing.get_context('fork')
    q = ctx.SimpleQueue()

    def child(out):
        out.put((os.getpid(), [telemetry.new_span_id() for _ in range(4)]))

    parent_ids = [telemetry.new_span_id() for _ in range(4)]
    p = ctx.Process(target=child, args=(q,))
    p.start()
    child_pid, child_ids = q.get()
    p.join(10)
    assert child_pid != os.getpid()
    assert (child_ids[0] >> 32) != (parent_ids[0] >> 32), 'child kept the parent epoch'
    assert not set(parent_ids) & set(child_ids)


def test_emit_span_and_monotonic_mapping(tmp_path):
    from da4ml_tpu.telemetry.core import monotonic_ts_us

    path = tmp_path / 'trace.jsonl'
    telemetry.enable(path)
    t0 = time.monotonic()
    sid = telemetry.emit_span('seg', monotonic_ts_us(t0), 0.002, trace_id='cd' * 16, parent_id=7, rows=3)
    telemetry.disable()
    assert sid > 0
    events, _ = telemetry.load_trace(path)
    seg = next(e for e in events if e['name'] == 'seg')
    assert seg['ph'] == 'X' and seg['dur'] == pytest.approx(2000.0)
    assert seg['args']['trace_id'] == 'cd' * 16 and seg['args']['parent_id'] == 7
    assert seg['args']['span_id'] == sid and seg['args']['rows'] == 3
    # disabled path: no sink -> no event, sentinel 0 id
    assert telemetry.emit_span('seg', 0.0, 0.1) == 0
