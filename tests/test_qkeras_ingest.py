"""Quantized-model ingestion: QKeras-style models convert with no manual kif.

Builds models from the in-tree qkeras-compatible classes (registered under
the 'qkeras' serialization package), round-trips them through .keras
serialization, and checks the traced DAIS program is bit-exact against
model.predict — with the input precision coming from the model's own input
quantizer, not --inputs-kif. Mirrors the reference's quantized entry path
(hgq custom objects at load, src/da4ml/_cli/convert.py:32-35).
"""

from __future__ import annotations

import json
import subprocess
import sys

import numpy as np
import pytest

keras = pytest.importorskip('keras')

from da4ml_tpu.converter import trace_model  # noqa: E402
from da4ml_tpu.converter.qkeras_compat import (  # noqa: E402
    QActivation,
    QConv2D,
    QDense,
    QDepthwiseConv2D,
    quantized_bits,
    quantized_relu,
)
from da4ml_tpu.trace import HWConfig, comb_trace  # noqa: E402


def _quantized_mlp():
    rng = np.random.default_rng(42)
    model = keras.Sequential(
        [
            keras.layers.Input((6,)),
            QActivation(quantized_bits(6, 2)),
            QDense(8, kernel_quantizer=quantized_bits(6, 2), bias_quantizer=quantized_bits(6, 2),
                   activation=quantized_relu(6, 3)),  # fmt: skip
            QDense(4, kernel_quantizer=quantized_bits(5, 1), bias_quantizer=quantized_bits(5, 1)),
        ]
    )
    for w in model.weights:
        w.assign(rng.uniform(-2, 2, w.shape))
    return model


def _quantized_cnn():
    rng = np.random.default_rng(7)
    model = keras.Sequential(
        [
            keras.layers.Input((6, 6, 2)),
            QActivation(quantized_bits(5, 2)),
            QConv2D(3, (3, 3), kernel_quantizer=quantized_bits(5, 1), bias_quantizer=quantized_bits(5, 1),
                    activation=quantized_relu(5, 2)),  # fmt: skip
            QDepthwiseConv2D((2, 2), depthwise_quantizer=quantized_bits(5, 1), bias_quantizer=quantized_bits(5, 1),
                             activation=quantized_relu(5, 2)),  # fmt: skip
            keras.layers.Flatten(),
            QDense(5, kernel_quantizer=quantized_bits(5, 1), bias_quantizer=quantized_bits(5, 1)),
        ]
    )
    for w in model.weights:
        w.assign(rng.uniform(-1.5, 1.5, w.shape))
    return model


def _grid_data(model, rng, n=256):
    """Random test data on the model's input quantization grid (in range)."""
    q = model.layers[0].quantizer
    s = q.da_spec
    eps = 2.0 ** -s['f']
    hi = 2.0 ** s['i'] - eps
    lo = -(2.0 ** s['i']) * s['k']
    shape = (n,) + model.input_shape[1:]
    return rng.integers(round(lo / eps), round(hi / eps), shape).astype(np.float64) * eps


@pytest.mark.parametrize('build', [_quantized_mlp, _quantized_cnn])
def test_quantized_model_bit_exact(build, tmp_path):
    model = build()
    # serialization round-trip through the registered 'qkeras' package names
    path = tmp_path / 'model.keras'
    model.save(path)
    model = keras.models.load_model(path, compile=False)

    inp, out = trace_model(model, HWConfig(1, -1, -1), {'hard_dc': 2})
    comb = comb_trace(inp, out)

    rng = np.random.default_rng(3)
    data = _grid_data(model, rng)
    golden = np.asarray(model.predict(data.reshape(len(data), *model.input_shape[1:]), verbose=0), np.float64)
    got = comb.predict(data.reshape(len(data), -1))
    np.testing.assert_array_equal(got.reshape(golden.shape), golden)


def test_quantized_model_cli_convert(tmp_path):
    model = _quantized_mlp()
    path = tmp_path / 'qmodel.keras'
    model.save(path)

    out = tmp_path / 'prj'
    r = subprocess.run(
        [sys.executable, '-m', 'da4ml_tpu', 'convert', str(path), str(out), '--flavor', 'verilog', '--validate'],
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    report = json.loads((out / 'mismatches.json').read_text())
    assert report['n_mismatch'] == 0, report
