"""JAX search backend: exactness oracle + cost quality vs the host solver."""

import numpy as np
import pytest

from da4ml_tpu.cmvm import solve
from da4ml_tpu.cmvm.jax_search import solve_jax, solve_jax_many
from da4ml_tpu.ir import QInterval


def random_kernel(rng, n_dim, bits):
    mag = rng.integers(0, 2**bits, (n_dim, n_dim)).astype(np.float64)
    sign = rng.choice([-1.0, 1.0], (n_dim, n_dim))
    return mag * sign


@pytest.mark.parametrize('n_dim', [4, 8])
@pytest.mark.parametrize('bits', [2, 4])
@pytest.mark.parametrize('method0', ['mc', 'wmc'])
def test_jax_solve_exact(rng, n_dim, bits, method0):
    kernel = random_kernel(rng, n_dim, bits)
    sol = solve_jax(kernel, method0=method0)
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)


@pytest.mark.parametrize('hard_dc', [0, 2, -1])
def test_jax_solve_hard_dc(rng, hard_dc):
    kernel = random_kernel(rng, 6, 4)
    sol = solve_jax(kernel, hard_dc=hard_dc)
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)


@pytest.mark.parametrize('search_all', [True, False])
def test_hard_dc_stays_on_device(rng, monkeypatch, search_all):
    """hard_dc >= 0 solves never fall back to the host solver (VERDICT r1 #6):
    the dc shrink ladder runs as device lanes and the forced dc=-1 terminal
    is accepted on device, mirroring api.py _solve's terminal break."""
    import da4ml_tpu.cmvm.api as host_api
    from da4ml_tpu.cmvm import jax_search

    def _boom(*a, **k):
        raise AssertionError('host _solve must not be called from the jax path')

    monkeypatch.setattr(host_api, '_solve', _boom)
    for hard_dc in (0, 1, 3):
        kernels = [random_kernel(rng, n, 4) for n in (4, 6, 8)]
        sols = solve_jax_many(kernels, hard_dc=hard_dc, search_all_decompose_dc=search_all)
        for k, s in zip(kernels, sols):
            np.testing.assert_array_equal(np.asarray(s.kernel, np.float64), k)


def test_hard_dc_budget_respected_vs_host(rng):
    """Device solutions meet the same latency budget the host enforces."""
    from math import inf

    from da4ml_tpu.cmvm.api import minimal_latency

    for hard_dc in (0, 2):
        kernel = random_kernel(rng, 8, 4)
        qints = [QInterval(-128.0, 127.0, 1.0)] * 8
        lats = [0.0] * 8
        sol = solve_jax(kernel, hard_dc=hard_dc)
        allowed = hard_dc + minimal_latency(kernel, qints, lats, -1, -1)
        max_lat = max((lt for st in sol.stages for lt in st.out_latency), default=0.0)
        assert max_lat <= allowed < inf, (max_lat, allowed)


def test_jax_solve_no_search(rng):
    kernel = random_kernel(rng, 8, 4)
    sol = solve_jax(kernel, search_all_decompose_dc=False)
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)


def test_jax_many(rng):
    kernels = [random_kernel(rng, n, b) for n, b in [(4, 2), (8, 4), (6, 3)]]
    sols = solve_jax_many(kernels)
    for k, s in zip(kernels, sols):
        np.testing.assert_array_equal(np.asarray(s.kernel, np.float64), k)


def test_jax_cost_quality(rng):
    """Avg cost over a batch within 10% of the host solver's (same heuristic)."""
    kernels = [random_kernel(rng, 8, 4) for _ in range(8)]
    jax_sols = solve_jax_many(kernels)
    host_costs = [solve(k).cost for k in kernels]
    jax_costs = [s.cost for s in jax_sols]
    assert np.mean(jax_costs) <= np.mean(host_costs) * 1.10, (jax_costs, host_costs)


def test_jax_predict_bit_exact(rng):
    kernel = random_kernel(rng, 8, 4)
    qints = [QInterval(-8.0, 7.0, 1.0)] * 8
    sol = solve_jax(kernel, qintervals=qints)
    x = rng.integers(-8, 8, (64, 8)).astype(np.float64)
    np.testing.assert_array_equal(sol.predict(x, backend='numpy'), x @ kernel)


def test_hbm_chunked_lanes_identical(rng, monkeypatch, capsys):
    """A tiny device-memory budget forces the lane batch through multiple
    sequential chunks of the same compiled program; results must be
    byte-identical to the unchunked solve (same decisions, same ops)."""
    kernels = [random_kernel(rng, 6, 4) for _ in range(6)]
    base = solve_jax_many(kernels)
    monkeypatch.setenv('DA4ML_JAX_HBM_BUDGET', str(1 << 20))
    monkeypatch.setenv('DA4ML_JAX_DEBUG', '1')
    chunked = solve_jax_many(kernels)
    rounds = [ln for ln in capsys.readouterr().out.splitlines() if '[jax_search] round' in ln]
    # at least one rung must have split its lanes (a chunk starting past 0)
    assert any(not ln.split('chunk=')[1].startswith('0+') for ln in rounds), rounds
    for k, b, c in zip(kernels, base, chunked):
        np.testing.assert_array_equal(np.asarray(c.kernel, np.float64), k)
        assert c.cost == b.cost and c.latency == b.latency
        for sb, sc in zip(b.stages, c.stages):
            assert len(sb.ops) == len(sc.ops)
            for ob, oc_ in zip(sb.ops, sc.ops):
                assert (ob.id0, ob.id1, ob.opcode, ob.data) == (oc_.id0, oc_.id1, oc_.opcode, oc_.data)


@pytest.mark.parametrize('seed', [0, 1])
def test_jax_heterogeneous_qintervals_fuzz(seed):
    """Exactness under fuzzed per-row qintervals/latencies and finite
    adder/carry sizes — the f32 scoring metadata on device must never leak
    into the emitted (f64-rederived) op metadata."""
    rng = np.random.default_rng(1000 + seed)
    kernels, qints_l, lats_l = [], [], []
    for _ in range(4):
        n_in = int(rng.integers(3, 9))
        kernels.append(random_kernel(rng, n_in, int(rng.integers(2, 6))))
        frac = 2.0 ** -rng.integers(0, 4, n_in)
        lo = -rng.integers(1, 128, n_in).astype(np.float64) * frac
        hi = rng.integers(1, 128, n_in).astype(np.float64) * frac
        qints_l.append([QInterval(float(lo[i]), float(hi[i]), float(frac[i])) for i in range(n_in)])
        lats_l.append([float(v) for v in rng.integers(0, 4, n_in)])
    sols = solve_jax_many(
        kernels, qintervals_list=qints_l, latencies_list=lats_l, adder_size=int(rng.integers(2, 9)), carry_size=8
    )
    for k, s, qints in zip(kernels, sols, qints_l):
        np.testing.assert_array_equal(np.asarray(s.kernel, np.float64), k)
        # inputs on each row's exact qinterval grid; predict must be bit-exact
        # (this is what would break if the device's f32 scoring metadata ever
        # leaked into the emitted op metadata instead of the f64 rederivation)
        cols = [q.step * rng.integers(round(q.min / q.step), round(q.max / q.step) + 1, 32) for q in qints]
        x = np.stack(cols, axis=1).astype(np.float64)
        np.testing.assert_array_equal(s.predict(x, backend='numpy'), x @ k)


def test_backend_dispatch(rng):
    kernel = random_kernel(rng, 4, 3)
    sol = solve(kernel, backend='jax')
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)


def test_method_candidates_quality(rng):
    """Widening the sweep with extra heuristics never worsens the argmin."""
    kernels = [random_kernel(rng, 8, 4) for _ in range(4)]
    base = solve_jax_many(kernels, method0='wmc')
    wide = solve_jax_many(kernels, method0='wmc', method0_candidates=['wmc', 'mc'])
    for k, b, w in zip(kernels, base, wide):
        np.testing.assert_array_equal(np.asarray(w.kernel, np.float64), k)
        assert w.cost <= b.cost, (w.cost, b.cost)


def test_include_host_portfolio(rng):
    """include_host folds the native solver into the argmin: the result can
    never cost more than the reference solver's per matrix, and exactness
    holds regardless of which lane wins."""
    from da4ml_tpu.cmvm import api as host_api

    kernels = [random_kernel(rng, 8, 4) for _ in range(4)]
    host = [host_api.solve(k, backend='auto') for k in kernels]
    port = solve_jax_many(kernels, include_host=True)
    for k, h, p in zip(kernels, host, port):
        np.testing.assert_array_equal(np.asarray(p.kernel, np.float64), k)
        assert p.cost <= h.cost, (p.cost, h.cost)


def test_method_candidates_via_solver_options(rng):
    """method0_candidates routes through solver_options on every backend."""
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    w = random_kernel(rng, 6, 3)
    for backend in ('jax', 'cpu'):
        opts = {'backend': backend, 'method0_candidates': ['wmc', 'mc']}
        inp = FixedVariableArrayInput((3, 6), hwconf=HWConfig(1, -1, -1), solver_options=opts)
        x = inp.quantize(np.ones((3, 6)), np.full((3, 6), 3), np.zeros((3, 6), np.int64))
        comb = comb_trace(inp, x @ w)
        data = rng.integers(-8, 8, (16, 18)).astype(np.float64)
        out = comb.predict(data, backend='numpy')
        np.testing.assert_array_equal(out.reshape(16, 3, -1), data.reshape(16, 3, 6) @ w)


def test_restart_lanes_exact_and_no_worse(rng):
    """Random-restart lanes: every restart is renumbered back exactly, and
    the argmin over the widened sweep never worsens the cost."""
    kernels = [random_kernel(rng, 8, 5) for _ in range(4)]
    base = solve_jax_many(kernels, method0='wmc')
    wide = solve_jax_many(kernels, method0='wmc', n_restarts=3)
    for k, b, w in zip(kernels, base, wide):
        np.testing.assert_array_equal(np.asarray(w.kernel, np.float64), k)
        assert w.cost <= b.cost, (w.cost, b.cost)
    # restart solutions replay bit-exactly through the interpreter
    data = rng.integers(-16, 16, (64, 8)).astype(np.float64)
    for k, w in zip(kernels, wide):
        np.testing.assert_array_equal(w.predict(data), data @ k)


def test_restart_lanes_under_hard_dc(rng):
    kernel = random_kernel(rng, 6, 4)
    sol = solve_jax_many([kernel], hard_dc=1, n_restarts=2)[0]
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)


def test_pmax_reroutes_big_matrices_to_host(rng, monkeypatch):
    """Matrices whose slot demand exceeds DA4ML_JAX_PMAX solve on the host
    (exactly), while small ones in the same batch stay on device."""
    from da4ml_tpu.cmvm import jax_search

    monkeypatch.setenv('DA4ML_JAX_PMAX', '64')
    big = random_kernel(rng, 8, 8)  # ~8 + digits/2 >> 64
    small = random_kernel(rng, 4, 2)
    before = jax_search.search_stats['pmax_host_fallbacks']
    sols = solve_jax_many([big, small])
    assert jax_search.search_stats['pmax_host_fallbacks'] > before
    for k, s in zip((big, small), sols):
        np.testing.assert_array_equal(np.asarray(s.kernel, np.float64), k)


def test_pmax_inladder_safety_net(rng, monkeypatch):
    """solve_single_lanes finishes stragglers on the host when the stage
    ladder would exceed PMAX mid-flight."""
    from da4ml_tpu.cmvm.jax_search import _Lane, solve_single_lanes

    monkeypatch.setenv('DA4ML_JAX_PMAX', '16')
    kernel = random_kernel(rng, 8, 4)
    qints = [QInterval(-128.0, 127.0, 1.0)] * 8
    lane = _Lane(kernel, qints, [0.0] * 8, 'wmc')
    (sol,) = solve_single_lanes([lane], -1, -1)
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)


def test_top4_select_quality_vs_scan(rng, monkeypatch):
    """The O(S*P) top-k score cache ('top4', the default) stays exact and
    within a few % of the decision-identical full-rescan path ('xla')."""
    from da4ml_tpu.cmvm.jax_search import _build_cse_fn

    kernels = [random_kernel(rng, n, b) for n, b in [(6, 3), (8, 4), (8, 6), (12, 4)]]
    monkeypatch.setenv('DA4ML_JAX_SELECT', 'top4')
    _build_cse_fn.cache_clear()
    top4 = solve_jax_many(kernels)
    monkeypatch.setenv('DA4ML_JAX_SELECT', 'xla')
    _build_cse_fn.cache_clear()
    scan = solve_jax_many(kernels)
    _build_cse_fn.cache_clear()
    for k, st, ss in zip(kernels, top4, scan):
        np.testing.assert_array_equal(np.asarray(st.kernel, np.float64), k)
        np.testing.assert_array_equal(np.asarray(ss.kernel, np.float64), k)
    mt, ms = np.mean([s.cost for s in top4]), np.mean([s.cost for s in scan])
    assert mt <= ms * 1.03, (mt, ms)


def test_native_emit_matches_python_emission(rng, monkeypatch):
    """solve_single_lanes' two host tails — native emit_batch vs the Python
    _host_state_from + to_solution path — must produce identical solutions."""
    from da4ml_tpu.cmvm import jax_search

    if not jax_search._native_emit_available():
        pytest.skip('native emission not built')
    kernels = [random_kernel(rng, 6, 4) for _ in range(3)]
    native = solve_jax_many(kernels)
    monkeypatch.setattr(jax_search, '_native_emit_available', lambda: False)
    python = solve_jax_many(kernels)
    for k, a, b in zip(kernels, native, python):
        np.testing.assert_array_equal(np.asarray(a.kernel, np.float64), k)
        assert a.cost == b.cost and a.latency == b.latency
        for sa, sb in zip(a.stages, b.stages):
            assert len(sa.ops) == len(sb.ops)
            for oa, ob in zip(sa.ops, sb.ops):
                assert (oa.id0, oa.id1, oa.opcode, oa.data, oa.qint) == (ob.id0, ob.id1, ob.opcode, ob.data, ob.qint)


def test_decompose_batch_matches_python(rng):
    """Native kernel decomposition == the Python reference, for every dc."""
    from da4ml_tpu.cmvm import jax_search
    from da4ml_tpu.cmvm.decompose import kernel_decompose

    if not jax_search._native_emit_available():
        pytest.skip('native library not built')
    from da4ml_tpu.native.bindings import decompose_batch

    kernels = [random_kernel(rng, n, 4) for n in (4, 6, 8)]
    dcs = [-1, 0, 2]
    native = decompose_batch(kernels, dcs)
    for k, dc, (m0, m1) in zip(kernels, dcs, native):
        r0, r1 = kernel_decompose(k, dc)
        np.testing.assert_array_equal(m0, r0)
        np.testing.assert_array_equal(m1, r1)
        np.testing.assert_array_equal(m0 @ m1, k)


@pytest.mark.parametrize('method0', ['wmc', 'mc'])
def test_decision_identity_op_for_op(rng, method0):
    """The device search is decision-identical with the host solver: not just
    equal cost — the exact same op sequence, because greedy ties resolve in
    the host's scan order (largest (id1, id0, sub, shift) among maxima, the
    >=-scan over its sorted freq map)."""
    from da4ml_tpu.cmvm.api import solve as host_solve

    for trial in range(3):
        kernel = random_kernel(rng, int(rng.integers(5, 13)), int(rng.integers(3, 11)))
        ref = host_solve(kernel, method0=method0, backend='auto')
        got = solve_jax_many([kernel], method0=method0)[0]
        assert float(got.cost) == float(ref.cost), (trial, got.cost, ref.cost)
        for sr, sg in zip(ref.stages, got.stages):
            assert len(sr.ops) == len(sg.ops), (trial, len(sr.ops), len(sg.ops))
            for a, b in zip(sr.ops, sg.ops):
                assert a == b, (trial, a, b)


def test_trit_codec_roundtrip(rng):
    """Host and device trit codecs invert each other bit-for-bit."""
    import jax.numpy as jnp

    from da4ml_tpu.cmvm.jax_search import _trit_pack_np, _trit_unpack_np

    digits = rng.integers(-1, 2, (5, 7, 48)).astype(np.int8)
    words = _trit_pack_np(digits.reshape(5, 7, 48))
    assert words.dtype == np.int32 and words.shape == (5, 7, 3)
    np.testing.assert_array_equal(_trit_unpack_np(words, 48), digits)
    # device-side unpack (the lane_trimmed path) agrees with the host codec
    import jax

    w = jnp.asarray(words.reshape(-1, 3))
    v = jax.lax.bitcast_convert_type(w, jnp.uint32)
    code = (v[..., None] >> (2 * jnp.arange(16, dtype=jnp.uint32))) & 3
    dev = (np.asarray(code, np.int8) - 1).reshape(5, 7, 48)
    np.testing.assert_array_equal(dev, digits)


def test_lane_level_routing_partial_device(rng, monkeypatch):
    """Slot-demand routing is per LANE: with a ceiling that only the
    undecomposed (dc=-1) lane exceeds, exactly that lane runs host-side
    while the decomposed candidates stay on device — and the solve is
    still exact."""
    from da4ml_tpu.cmvm import jax_search
    from da4ml_tpu.cmvm.csd import csd_decompose
    from da4ml_tpu.cmvm.decompose import kernel_decompose

    # correlated columns: every column = a dense base +- a sparse delta, so
    # the MST difference matrix has far fewer digits than the raw kernel
    srng = np.random.default_rng(99)
    base = (srng.integers(32, 128, 8) * srng.choice([-1, 1], 8)).astype(np.float64)
    deltas = srng.integers(-1, 2, (8, 8)).astype(np.float64)
    kernel = base[:, None] + deltas
    n_in = kernel.shape[0]
    full_demand = n_in + int((csd_decompose(kernel)[0] != 0).sum()) // 2
    dec_demands = []
    for dc in range(0, 4):
        m0, _ = kernel_decompose(kernel, dc)
        dec_demands.append(m0.shape[0] + int((csd_decompose(m0)[0] != 0).sum()) // 2)
    lo, hi = min(dec_demands), full_demand
    assert 2 * lo <= hi, 'deep decomposition must shrink the demand enough for a pow2 window'
    ceiling = 1 << (hi - 1).bit_length() - 1  # pow2 in (lo, hi)
    assert lo < ceiling < hi
    monkeypatch.setenv('DA4ML_JAX_PMAX', str(ceiling))
    before = jax_search.search_stats['pmax_host_fallbacks']
    (sol,) = solve_jax_many([kernel])
    routed = jax_search.search_stats['pmax_host_fallbacks'] - before
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)
    assert routed >= 1, 'the undecomposed lane should have routed host-side'
    # at least one decomposed candidate must have stayed on device
    n_lanes_total = 2 * (2 + min(10**9, int(np.ceil(np.log2(n_in)))) + 1)
    assert routed < n_lanes_total, 'not every lane may route to the host'


def test_prewarm_paths(rng, monkeypatch):
    """Forced-on background prewarm never changes results and its spec
    mirror stays callable (a drifted estimate may waste a compile, never
    break a solve)."""
    from da4ml_tpu.cmvm import jax_search as js

    monkeypatch.setenv('DA4ML_JAX_PREWARM', '1')
    kernels = [random_kernel(rng, 8, 4), random_kernel(rng, 20, 6)]  # 2nd resumes a rung
    sols = solve_jax_many(kernels)
    for k, s in zip(kernels, sols):
        np.testing.assert_array_equal(np.asarray(s.kernel, np.float64), k)
    # the mirror agrees with an actual first-rung spec for simple lanes
    lanes = [js._Lane(kernels[0], [QInterval(-128.0, 127.0, 1.0)] * 8, [0.0] * 8, 'wmc')]
    specs = js._first_rung_specs(lanes, -1, -1)
    assert specs
    spec, bucket = specs[0]
    assert spec.P >= 8 and spec.O >= 8 and bucket >= 1
    # the worker is a daemon on a SimpleQueue: queued AOT compiles never
    # block interpreter exit, so there is nothing to drain here


def test_prewarm_for_kernels_covers_solve_classes(rng, monkeypatch):
    """The model-level prewarm estimates exactly the shape classes a later
    solve_jax_many over the same kernel groups requests (both stages)."""
    from da4ml_tpu.cmvm import jax_search as js
    from da4ml_tpu.cmvm.jax_search import prewarm_for_kernels

    monkeypatch.setenv('DA4ML_JAX_PREWARM', '0')
    assert prewarm_for_kernels([[random_kernel(rng, 8, 4)]]) == 0  # disabled: no-op

    monkeypatch.setenv('DA4ML_JAX_PREWARM', '1')
    # drain stale background prewarm jobs queued by EARLIER tests: the
    # daemon worker is FIFO, so once a barrier job runs, no previously
    # queued job can append into the monkeypatched recorder below
    import threading

    _drained = threading.Event()
    js._prewarm_submit(_drained.set)
    assert _drained.wait(timeout=120), 'background prewarm worker wedged'

    warmed: list = []
    monkeypatch.setattr(js, '_prewarm_submit', lambda job: job())  # run inline
    monkeypatch.setattr(js, '_prewarm_class', lambda spec, bucket: warmed.append((spec, bucket)))
    kernels = [random_kernel(rng, 8, 4), random_kernel(rng, 12, 6)]
    assert prewarm_for_kernels([kernels]) == 1
    assert warmed, 'prewarm must estimate at least one class'

    used: list = []
    real_build = js._build_cse_fn
    monkeypatch.setattr(js, '_build_cse_fn', lambda spec: (used.append(spec), real_build(spec))[1])
    monkeypatch.setenv('DA4ML_JAX_PREWARM', '0')  # no in-loop prewarm noise
    sols = solve_jax_many(kernels)
    for k, s in zip(kernels, sols):
        np.testing.assert_array_equal(np.asarray(s.kernel, np.float64), k)
    warmed_specs = {spec for spec, _ in warmed}
    # no drift: every estimated class is one the real solve actually built
    # (resume rungs beyond the first are covered by the in-loop prewarm)
    assert warmed_specs <= set(used), f'drifted estimate: warmed={warmed_specs}, used={set(used)}'
    assert warmed_specs & set(used)


def test_plugin_prewarm_hook(monkeypatch):
    """TracerPluginBase.trace fires the model-level prewarm exactly when the
    backend is jax and the plugin reports kernel groups."""
    from da4ml_tpu.cmvm import jax_search as js
    from da4ml_tpu.converter.example import ExampleModel, ExampleTracer
    from da4ml_tpu.trace import HWConfig

    calls: list = []
    monkeypatch.setattr(js, 'prewarm_for_kernels', lambda groups, **kw: calls.append((groups, kw)) or 1)

    class WarmTracer(ExampleTracer):
        def prewarm_kernel_groups(self):
            return [[np.eye(4)]]

        def apply_model(self, verbose, inputs):  # the hook gating is the test
            return {'out': inputs[0]}, ['out']

    # backend jax -> hook fires with hwconf defaults forwarded
    WarmTracer(ExampleModel((4, 5)), HWConfig(1, -1, -1), {'backend': 'jax'}).trace()
    assert len(calls) == 1
    assert calls[0][1]['adder_size'] == 1 and calls[0][1]['carry_size'] == -1
    # non-jax backend -> no prewarm
    WarmTracer(ExampleModel((4, 5)), HWConfig(1, -1, -1), {'backend': 'cpu'}).trace()
    assert len(calls) == 1
