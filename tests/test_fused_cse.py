"""Fused Pallas CSE loop: decision identity with the XLA top4 path.

The fused kernel (cmvm/fused_cse.py) runs the whole greedy loop as one
pallas_call per lane block; on CPU it executes in interpreter mode, which is
semantics-identical with the TPU compile. The contract pinned here is strict
decision identity — op-for-op equality with the default top4 backend — plus
the usual exactness oracle (``Pipeline.kernel == kernel``).
"""

import numpy as np
import pytest

from da4ml_tpu.cmvm.jax_search import solve_jax_many


def random_kernel(rng, n_dim, bits, m=None):
    mag = rng.integers(0, 2**bits, (n_dim, m or n_dim)).astype(np.float64)
    sign = rng.choice([-1.0, 1.0], (n_dim, m or n_dim))
    return mag * sign


def ops_sig(p):
    return [[(o.id0, o.id1, o.opcode, o.data) for o in st.ops] for st in p.stages]


def _solve_with(monkeypatch, select, kernels, **kw):
    # no cache_clear: the select mode is part of the _KernelSpec cache key,
    # so top4 and fused programs coexist and repeat solves across tests
    # reuse compiled programs instead of recompiling per call
    monkeypatch.setenv('DA4ML_JAX_SELECT', select)
    return solve_jax_many(kernels, **kw)


@pytest.mark.slow
def test_fused_identity_batch(rng, monkeypatch):
    """Mixed-size batch (exercises trimmed upload + lane padding)."""
    kernels = [random_kernel(rng, n, b) for n, b in [(6, 3), (8, 4), (12, 4)]]
    top4 = _solve_with(monkeypatch, 'top4', kernels)
    fused = _solve_with(monkeypatch, 'fused', kernels)
    for k, a, b in zip(kernels, top4, fused):
        np.testing.assert_array_equal(np.asarray(b.kernel, np.float64), k)
        assert ops_sig(a) == ops_sig(b)
        assert float(a.cost) == float(b.cost)


@pytest.mark.slow
def test_fused_identity_long_lane_freeze(rng, monkeypatch):
    """A dense 128-slot-class kernel batched with a sparse mate that
    finishes hundreds of iterations earlier — pins the freeze semantics: a
    finished lane must neither mutate state nor latch its go flag while its
    block mates keep iterating (the vmapped while_loop cond equivalent).

    Restricting to the undecomposed dc=-1 lane keeps exactly the
    long-running lane while dropping the ~6x dc-sweep lanes whose
    interpret-mode cost used to dominate this test. (Fused cross-rung
    *resume* is structurally unreachable at test sizes: the fused select
    pads every class up to 128 slots, and the rung-resume plumbing is
    select-agnostic host-side state — covered for top4 in
    test_jax_search.)"""
    kernels = [random_kernel(rng, 12, 5), random_kernel(rng, 12, 2)]
    kw = dict(search_all_decompose_dc=False)
    top4 = _solve_with(monkeypatch, 'top4', kernels, **kw)
    fused = _solve_with(monkeypatch, 'fused', kernels, **kw)
    for k, a, b in zip(kernels, top4, fused):
        np.testing.assert_array_equal(np.asarray(b.kernel, np.float64), k)
        assert ops_sig(a) == ops_sig(b)


@pytest.mark.slow
def test_fused_identity_methods_and_budget(rng, monkeypatch):
    """Heuristic sweep lanes + a latency-budget dc ladder stay identical."""
    kernels = [random_kernel(rng, 8, 4)]
    kw = dict(method0_candidates=['wmc', 'mc', 'wmc-dc'], hard_dc=1)
    top4 = _solve_with(monkeypatch, 'top4', kernels, **kw)
    fused = _solve_with(monkeypatch, 'fused', kernels, **kw)
    np.testing.assert_array_equal(np.asarray(fused[0].kernel, np.float64), kernels[0])
    assert ops_sig(top4[0]) == ops_sig(fused[0])


def test_fused_runtime_fallback(rng, monkeypatch):
    """A fused kernel that fails at run time (the Mosaic-only failure mode)
    falls back to the XLA top4 program of the same shape class, warns once,
    and disables fused for the rest of the process."""
    from da4ml_tpu.cmvm import fused_cse
    from da4ml_tpu.cmvm import jax_search as js

    def boom_runner(spec, init_cache):
        def run(*args):
            raise RuntimeError('synthetic mosaic failure')

        return run

    monkeypatch.setattr(fused_cse, 'build_fused_runner', boom_runner)
    monkeypatch.setenv('DA4ML_JAX_SELECT', 'fused')
    js._build_cse_fn.cache_clear()
    js._FUSED_BROKEN.clear()
    try:
        kernels = [random_kernel(rng, 8, 4)]
        with pytest.warns(UserWarning, match='fused CSE kernel failed'):
            sols = solve_jax_many(kernels)
        np.testing.assert_array_equal(np.asarray(sols[0].kernel, np.float64), kernels[0])
        assert js._FUSED_BROKEN, 'failure must latch the process-wide fused kill switch'
        # later solves route straight to top4 with no further warnings
        import warnings as _warnings

        with _warnings.catch_warnings():
            _warnings.simplefilter('error')
            sols2 = solve_jax_many(kernels)
        np.testing.assert_array_equal(np.asarray(sols2[0].kernel, np.float64), kernels[0])
    finally:
        js._FUSED_BROKEN.clear()
        js._build_cse_fn.cache_clear()
