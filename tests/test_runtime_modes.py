"""Cross-mode runtime parity + autotuner determinism (docs/runtime.md).

Randomized DAIS programs (ir.synth) covering every opcode family — LUT ops,
negative shifts, muxes, bitwise ops, the int64 wide path, packed int8/int16
I/O — must run bit-exactly identical through the numpy oracle and all four
device execution modes (unroll / scan / level / pallas, the last in interpret
mode on CPU). Plus: the level scheduler's invariants, the mode autotuner's
cached (digest, platform)-keyed decision and env override, the pallas
fallback ladder, the bytes-adaptive chunking, and the sharded-by-default
batch path (conftest provides the virtual 8-device CPU mesh).
"""

import numpy as np
import pytest

from da4ml_tpu.ir.schedule import levelize_comb, levelize_program
from da4ml_tpu.ir.synth import FAMILIES, random_inputs, random_program
from da4ml_tpu.runtime import jax_backend as jb
from da4ml_tpu.runtime.jax_backend import MODES, DaisExecutor
from da4ml_tpu.runtime.numpy_backend import run_program


def _traced_model(rng):
    """A traced model exercising LUTs, relu, abs, and bitwise ops."""
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    inp = FixedVariableArrayInput((8,), hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(8), np.full(8, 4), np.full(8, 1))
    w = rng.integers(-8, 8, (8, 5)).astype(np.float64)
    y = np.sin(x[:4]).quantize(np.ones(4), np.ones(4), np.full(4, 6))
    z = (x @ w).relu()
    out = np.concatenate([z, y, abs(x[:2]), x[:2] & x[2:4]])
    return comb_trace(inp, out)


# ---------------------------------------------------------------------------
# level scheduler
# ---------------------------------------------------------------------------


def test_levelize_invariants():
    rng = np.random.default_rng(5)
    prog = random_program(rng, n_ops=300, n_in=6, n_out=4)
    sched = levelize_program(prog)
    lvl = sched.level
    for i in range(prog.n_ops):
        oc = int(prog.opcode[i])
        if oc in (-1, 5):
            assert lvl[i] == 0
            continue
        assert lvl[i] > lvl[int(prog.id0[i])]
        if oc in (0, 1, 6, -6, 7, 10):
            assert lvl[i] > lvl[int(prog.id1[i])]
        if abs(oc) == 6:
            assert lvl[i] > lvl[int(prog.data_lo[i])]
    # order is a permutation, level-sorted, with starts bounding each level
    assert sorted(sched.order.tolist()) == list(range(prog.n_ops))
    assert (np.diff(lvl[sched.order]) >= 0).all()
    for level in range(sched.depth):
        assert (lvl[sched.ops_at(level)] == level).all()
    assert sched.starts[-1] == prog.n_ops
    assert sched.width_max >= 1 and sched.width_mean > 0


def test_levelize_operand_liveness():
    """first_use/last_use track every (consumer, operand) edge; peak_live
    bounds the level-concurrent live-slot window the pallas backend sizes
    VMEM against."""
    rng = np.random.default_rng(9)
    prog = random_program(rng, n_ops=300, n_in=6, n_out=4)
    sched = levelize_program(prog)
    first, last = sched.first_use, sched.last_use
    # oracle: per-slot min/max reader via a plain op walk
    lo = np.full(prog.n_ops, prog.n_ops, dtype=np.int64)
    hi = np.full(prog.n_ops, -1, dtype=np.int64)
    for i in range(prog.n_ops):
        oc = int(prog.opcode[i])
        deps = []
        if oc not in (-1, 5):
            deps.append(int(prog.id0[i]))
        if oc in (0, 1, 6, -6, 7, 10):
            deps.append(int(prog.id1[i]))
        if abs(oc) == 6:
            deps.append(int(prog.data_lo[i]))
        for d in deps:
            lo[d] = min(lo[d], i)
            hi[d] = max(hi[d], i)
    lo[lo == prog.n_ops] = -1
    np.testing.assert_array_equal(first, lo)
    np.testing.assert_array_equal(last, hi)
    assert (first[first >= 0] > np.flatnonzero(first >= 0)).all(), 'consumers come after definitions'
    assert 1 <= sched.peak_live <= prog.n_ops
    assert sched.peak_live >= sched.width_max, 'a level is at least as live as its own width'


def test_levelize_comb_matches_program(rng):
    from da4ml_tpu.ir.dais_binary import decode

    comb = _traced_model(rng)
    sc = levelize_comb(comb)
    sp = levelize_program(decode(comb.to_binary()))
    np.testing.assert_array_equal(sc.level, sp.level)


def test_layered_program_depth():
    rng = np.random.default_rng(2)
    prog = random_program(rng, n_ops=2000, n_in=8, n_out=4, n_levels=10)
    sched = levelize_program(prog)
    assert 10 <= sched.depth <= 14  # n_levels + a little slack for muxes
    assert sched.width_mean > 100


# ---------------------------------------------------------------------------
# cross-mode bit-exactness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize('seed', [0, 1, 2, 3])
def test_parity_random_programs(seed):
    rng = np.random.default_rng(seed)
    prog = random_program(rng, n_ops=250, n_in=6, n_out=5)
    data = random_inputs(rng, prog, 257)  # odd: exercises shard padding
    ref = run_program(prog, data)
    for mode in MODES:
        got = DaisExecutor(prog, mode=mode)(data)
        np.testing.assert_array_equal(got, ref, err_msg=f'mode={mode} seed={seed}')


def test_parity_covers_all_families():
    present: set[int] = set()
    for seed in range(4):
        rng = np.random.default_rng(seed)
        prog = random_program(rng, n_ops=250, n_in=6, n_out=5, families=FAMILIES)
        present |= set(np.abs(prog.opcode).tolist())
    # input, add/sub, relu, quant, cadd, const, mux, mul, lookup, bitu, bitb
    assert {1, 0, 2, 3, 4, 5, 6, 7, 8, 9, 10} <= present


def test_parity_wide_i64_scoped():
    """Wide programs run on the int64 path without flipping jax_enable_x64
    process-wide (the old global flip invalidated unrelated cached jits)."""
    import jax

    flag_before = jax.config.read('jax_enable_x64')
    rng = np.random.default_rng(11)
    prog = random_program(rng, n_ops=150, n_in=4, n_out=3, wide=True)
    data = random_inputs(rng, prog, 65)
    ref = run_program(prog, data)
    for mode in MODES:
        ex = DaisExecutor(prog, mode=mode)
        assert ex.use_i64, 'wide program must take the int64 path'
        np.testing.assert_array_equal(ex(data), ref, err_msg=f'mode={mode}')
    assert jax.config.read('jax_enable_x64') == flag_before


def test_parity_traced_model_level(rng):
    """Level mode on a real traced program (LUT via sin, relu, bit ops)."""
    from da4ml_tpu.ir.dais_binary import decode

    comb = _traced_model(rng)
    prog = decode(comb.to_binary())
    data = rng.uniform(-16, 16, (64, 8))
    ref = comb.predict(data, backend='numpy')
    for mode in MODES:
        got = DaisExecutor(prog, mode=mode)(data)
        np.testing.assert_array_equal(got, ref, err_msg=f'mode={mode}')


def test_parity_packed_io_level():
    """Packed int8/int16 host<->device lanes are bit-exact in level mode."""
    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    rng = np.random.default_rng(12)
    inp = FixedVariableArrayInput(6, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(6), np.full(6, 2), np.full(6, 1))
    w = rng.integers(-4, 4, (6, 3)).astype(np.float64)
    comb = comb_trace(inp, (x @ w).relu(i=np.full(3, 5), f=np.full(3, 1)))
    ex = DaisExecutor(decode(comb.to_binary()), mode='level')
    assert ex._in_group in (2, 4) and ex._out_group in (2, 4)
    data = rng.uniform(-4, 4, (64, 6))
    np.testing.assert_array_equal(ex(data), comb.predict(data, backend='numpy'))


def test_unroll_refuses_large_level_runs_it():
    """Past UNROLL_LIMIT the unrolled jaxpr refuses; level compiles the same
    program in O(depth × families) and matches scan and the numpy oracle."""
    rng = np.random.default_rng(7)
    big = random_program(rng, n_ops=20_500, n_in=16, n_out=8, n_levels=24)
    assert big.n_ops > DaisExecutor.UNROLL_LIMIT
    with pytest.raises(ValueError, match='unroll'):
        DaisExecutor(big, mode='unroll')
    data = random_inputs(rng, big, 64)
    ref = run_program(big, data)
    out_level = DaisExecutor(big, mode='level')(data)
    out_scan = DaisExecutor(big, mode='scan')(data)
    np.testing.assert_array_equal(out_level, ref)
    np.testing.assert_array_equal(out_scan, ref)


# ---------------------------------------------------------------------------
# autotuner
# ---------------------------------------------------------------------------


@pytest.fixture
def tuner_env(monkeypatch, tmp_path):
    """Isolated decision cache + tiny autotune batch."""
    import jax

    old = jax.config.jax_compilation_cache_dir
    jax.config.update('jax_compilation_cache_dir', str(tmp_path))
    monkeypatch.setenv('DA4ML_RUN_AUTOTUNE_MIN_OPS', '0')
    monkeypatch.setenv('DA4ML_RUN_AUTOTUNE_BATCH', '64')
    saved = dict(jb._MODE_DECISIONS)
    jb._MODE_DECISIONS.clear()
    yield tmp_path
    jb._MODE_DECISIONS.clear()
    jb._MODE_DECISIONS.update(saved)
    jax.config.update('jax_compilation_cache_dir', old)


def test_autotune_decision_cached(tuner_env):
    from da4ml_tpu.telemetry.metrics import enable_metrics, metrics_snapshot

    enable_metrics()
    rng = np.random.default_rng(21)
    prog = random_program(rng, n_ops=300, n_in=6, n_out=4)
    ex1 = DaisExecutor(prog, mode='auto')
    assert ex1.mode in MODES
    n_tuned = metrics_snapshot().get('run.autotune', {}).get('value', 0)
    assert n_tuned >= 1
    files = list((tuner_env / 'da4ml-run-modes').glob('*.json'))
    assert len(files) == 1, 'decision must persist next to the XLA cache'

    # same process, memory cache cleared: the persisted decision is reused
    jb._MODE_DECISIONS.clear()
    ex2 = DaisExecutor(prog, mode='auto')
    assert ex2.mode == ex1.mode
    snap = metrics_snapshot()
    assert snap.get('run.autotune', {}).get('value', 0) == n_tuned, 'no re-measure on cache hit'
    assert snap.get('run.mode_cache_hit', {}).get('value', 0) >= 1


def test_run_mode_env_forces(tuner_env, monkeypatch):
    rng = np.random.default_rng(22)
    prog = random_program(rng, n_ops=300, n_in=6, n_out=4)
    monkeypatch.setenv('DA4ML_RUN_MODE', 'scan')
    ex = DaisExecutor(prog, mode='auto')
    assert ex.mode == 'scan'
    # explicit modes are not overridden
    ex2 = DaisExecutor(prog, mode='level')
    assert ex2.mode == 'level'


def test_autotune_disabled_heuristic(tuner_env, monkeypatch):
    monkeypatch.setenv('DA4ML_RUN_AUTOTUNE', '0')
    rng = np.random.default_rng(23)
    prog = random_program(rng, n_ops=300, n_in=6, n_out=4)
    assert DaisExecutor(prog, mode='auto').mode == 'unroll'


# ---------------------------------------------------------------------------
# batching: adaptive chunking, default sharding, donation knobs
# ---------------------------------------------------------------------------


def test_infer_chunks_bytes(monkeypatch):
    monkeypatch.delenv('DA4ML_JAX_INFER_CHUNKS', raising=False)
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNK_BYTES', str(1 << 20))
    assert jb._infer_chunks(1024, 16) == 1  # 16 KiB total: no chunking
    assert jb._infer_chunks(1 << 18, 16) == 4  # 4 MiB / 1 MiB budget
    assert jb._infer_chunks(1024, 1 << 16) == 16  # 64 MiB wide rows: capped
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNKS', '7')
    assert jb._infer_chunks(1 << 18, 16) == 7  # explicit count wins
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNKS', '0')
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNK_BYTES', '256')
    assert jb._infer_chunks(1024, 1) == 4  # 1 KiB / 256 B budget


def test_chunked_sharded_call_bit_exact(monkeypatch):
    """Chunking + default 8-device sharding + row padding are invisible:
    bit-identical to the numpy oracle."""
    rng = np.random.default_rng(31)
    prog = random_program(rng, n_ops=200, n_in=6, n_out=4)
    data = random_inputs(rng, prog, 1003)  # not divisible by chunks or devices
    ref = run_program(prog, data)
    ex = DaisExecutor(prog, mode='level')
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNKS', '7')
    np.testing.assert_array_equal(ex(data), ref)
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNKS', '0')
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNK_BYTES', '1024')
    np.testing.assert_array_equal(ex(data), ref)
    monkeypatch.setenv('DA4ML_RUN_SHARD', '0')
    np.testing.assert_array_equal(ex(data), ref)


def test_default_sharding_active():
    import jax

    assert jax.local_device_count() == 8, 'conftest provides the virtual mesh'
    assert jb._active_sharding() is not None
    assert int(jb._active_sharding().mesh.devices.size) == 8


# ---------------------------------------------------------------------------
# pipelines and the public entry points
# ---------------------------------------------------------------------------


def _pipeline_case(rng):
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace, to_pipeline

    inp = FixedVariableArrayInput(8, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(8), np.full(8, 3), np.full(8, 2))
    w1 = rng.integers(-8, 8, (8, 12)).astype(np.float64)
    x = (x @ w1).relu(i=np.full(12, 6), f=np.full(12, 2))
    w2 = rng.integers(-8, 8, (12, 4)).astype(np.float64)
    comb = comb_trace(inp, x @ w2)
    return comb, to_pipeline(comb, 3.0)


def test_pipeline_chained_device_resident(rng):
    """run_pipeline(fused=False): per-stage programs with device-resident
    donated intermediates, bit-exact with the fused path and the oracle."""
    from da4ml_tpu.runtime.jax_backend import run_pipeline

    comb, pipe = _pipeline_case(rng)
    assert len(pipe.stages) > 1
    data = rng.uniform(-8, 8, (333, 8))
    ref = comb.predict(data, backend='numpy')
    chain = [s.to_binary() for s in pipe.stages]
    np.testing.assert_array_equal(run_pipeline(chain, data), ref)
    np.testing.assert_array_equal(run_pipeline(chain, data, fused=False), ref)


def test_run_comb_mode_param(rng):
    from da4ml_tpu.runtime import run_comb

    comb = _traced_model(rng)
    data = rng.uniform(-16, 16, (64, 8))
    ref = comb.predict(data, backend='numpy')
    np.testing.assert_array_equal(run_comb(comb, data, mode='level'), ref)
    with pytest.raises(ValueError, match='mode'):
        run_comb(comb, data, backend='cpp', mode='level')


def test_run_metrics_emitted(rng):
    from da4ml_tpu.telemetry.metrics import enable_metrics, metrics_snapshot

    enable_metrics()
    prog_rng = np.random.default_rng(41)
    prog = random_program(prog_rng, n_ops=120, n_in=5, n_out=3)
    ex = DaisExecutor(prog, mode='level')
    ex(random_inputs(prog_rng, prog, 64))
    snap = metrics_snapshot()
    assert snap.get('run.mode.level', {}).get('value', 0) >= 1
    assert 'run.samples_per_s' in snap
    assert 'run.compile_s' in snap
    assert snap.get('run.samples', {}).get('value', 0) >= 64


def test_x64_warn_once_dedup():
    from da4ml_tpu.telemetry.log import _warned_once, warn_once

    key = 'test.warn_once_key'
    _warned_once.discard(key)
    assert warn_once(key, 'only once') is True
    assert warn_once(key, 'only once') is False


@pytest.mark.parametrize('env', ['0', '1'])
def test_donate_env_knob(monkeypatch, env):
    monkeypatch.setenv('DA4ML_RUN_DONATE', env)
    dn = jb._donate_argnums()
    if env == '0':
        assert dn == ()
    else:
        import jax

        assert dn == (() if jax.default_backend() == 'cpu' else (0,))


# ---------------------------------------------------------------------------
# pallas mega-kernel backend (docs/runtime.md#pallas-backend)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize('family', FAMILIES)
def test_pallas_parity_per_family(family):
    """One single-family program per opcode family through the mega-kernel
    (interpret mode on CPU), bit-exact vs the numpy oracle."""
    rng = np.random.default_rng(50_000 + FAMILIES.index(family))
    prog = random_program(rng, n_ops=160, n_in=5, n_out=4, families=(family,))
    data = random_inputs(rng, prog, 33)  # odd batch: exercises block padding
    ex = DaisExecutor(prog, mode='pallas')
    assert ex.mode == 'pallas'
    np.testing.assert_array_equal(ex(data), run_program(prog, data), err_msg=f'family={family}')


def test_pallas_parity_packed_io():
    """Packed int8/int16 host<->device lanes wrap the pallas kernel too."""
    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    rng = np.random.default_rng(12)
    inp = FixedVariableArrayInput(6, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(6), np.full(6, 2), np.full(6, 1))
    w = rng.integers(-4, 4, (6, 3)).astype(np.float64)
    comb = comb_trace(inp, (x @ w).relu(i=np.full(3, 5), f=np.full(3, 1)))
    ex = DaisExecutor(decode(comb.to_binary()), mode='pallas')
    assert ex.mode == 'pallas' and ex._in_group in (2, 4) and ex._out_group in (2, 4)
    data = rng.uniform(-4, 4, (64, 6))
    np.testing.assert_array_equal(ex(data), comb.predict(data, backend='numpy'))


def _fusion_workload(name, rng):
    """The bench.py fusion workloads (limited dims): a separable conv stack
    and a relu-attention transformer block, as stage pipelines."""
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace, to_pipeline
    from da4ml_tpu.trace.ops import conv2d, depthwise_conv2d, einsum, relu
    from da4ml_tpu.trace.ops.quantization import quantize

    if name == 'conv_stack':
        shape = (5, 5, 2)
        inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, 6))
        x = inp.quantize(np.ones(shape), np.full(shape, 2), np.zeros(shape, np.int64))
        h = relu(depthwise_conv2d(x, rng.integers(-3, 4, (3, 3, 2, 1)).astype(np.float64)), i=3, f=0)
        h = relu(conv2d(h, rng.integers(-3, 4, (1, 1, 2, 3)).astype(np.float64)), i=3, f=0)
        out = conv2d(h, rng.integers(-3, 4, (1, 1, 3, 2)).astype(np.float64))
        return to_pipeline(comb_trace(inp, out), 6, retiming=False), int(np.prod(shape))
    T, D, F = 4, 4, 8
    inp = FixedVariableArrayInput((T, D), hwconf=HWConfig(1, -1, 8))
    x = inp.quantize(np.ones((T, D)), np.full((T, D), 2), np.zeros((T, D), np.int64))
    wq, wk, wv = (rng.integers(-2, 3, (D, D)).astype(np.float64) for _ in range(3))
    q = quantize(einsum('td,df->tf', x, wq), 1, 3, 0)
    k = quantize(einsum('td,df->tf', x, wk), 1, 3, 0)
    v = quantize(einsum('td,df->tf', x, wv), 1, 3, 0)
    scores = relu(einsum('td,sd->ts', q, k), i=3, f=0)  # relu-attention, no softmax
    h = quantize(x + quantize(einsum('ts,sd->td', scores, v), 1, 3, 0), 1, 3, 0)
    w1 = rng.integers(-2, 3, (D, F)).astype(np.float64)
    w2 = rng.integers(-2, 3, (F, D)).astype(np.float64)
    ffn = quantize(einsum('tf,fd->td', relu(einsum('td,df->tf', h, w1), i=3, f=0), w2), 1, 3, 0)
    return to_pipeline(comb_trace(inp, quantize(h + ffn, 1, 3, 0)), 8, retiming=False), T * D


@pytest.mark.parametrize('workload', ['conv_stack', 'transformer_block'])
def test_pallas_fused_workload_bit_exact(workload):
    """The IR-fused bench workloads run whole through ONE pallas kernel."""
    rng = np.random.default_rng(23)
    pipe, n_in = _fusion_workload(workload, rng)
    chain = [s.to_binary() for s in pipe.stages]
    data = rng.integers(-4, 4, (257, n_in)).astype(np.float64)
    golden = pipe.predict(data, backend='numpy')
    ex = jb.fused_executor_for_binaries(chain, mode='pallas')
    assert ex.mode == 'pallas'
    np.testing.assert_array_equal(ex(data), golden, err_msg=f'workload={workload}')


def test_pallas_env_force(tuner_env, monkeypatch):
    monkeypatch.setenv('DA4ML_RUN_MODE', 'pallas')
    rng = np.random.default_rng(26)
    prog = random_program(rng, n_ops=200, n_in=5, n_out=4)
    ex = DaisExecutor(prog, mode='auto')
    assert ex.mode == 'pallas'
    data = random_inputs(rng, prog, 65)
    np.testing.assert_array_equal(ex(data), run_program(prog, data))


def test_pallas_fallback_warns_and_counts(monkeypatch):
    """mode='pallas' degrades to 'level' (warn_once + counter) when the
    backend reports itself unavailable, instead of raising."""
    from da4ml_tpu.runtime import pallas_backend
    from da4ml_tpu.telemetry.log import _warned_once
    from da4ml_tpu.telemetry.metrics import enable_metrics, metrics_snapshot

    enable_metrics()
    monkeypatch.setattr(pallas_backend, 'unavailable_reason', lambda prog: 'jax.experimental.pallas is unavailable')
    _warned_once.discard('runtime.pallas_fallback')
    before = metrics_snapshot().get('run.pallas.fallbacks', {}).get('value', 0)
    prog = random_program(np.random.default_rng(3), n_ops=80, n_in=4, n_out=3)
    ex = DaisExecutor(prog, mode='pallas')
    assert ex.mode == 'level'
    assert metrics_snapshot().get('run.pallas.fallbacks', {}).get('value', 0) == before + 1
    data = random_inputs(np.random.default_rng(4), prog, 16)
    np.testing.assert_array_equal(ex(data), run_program(prog, data))


def test_autotune_decision_platform_keyed(tuner_env, monkeypatch):
    """Decisions persist under (digest, platform): a cpu decision must not
    answer for the same program on another backend platform."""
    import jax

    from da4ml_tpu.telemetry.metrics import enable_metrics, metrics_snapshot

    enable_metrics()
    rng = np.random.default_rng(29)
    prog = random_program(rng, n_ops=300, n_in=6, n_out=4)
    ex1 = DaisExecutor(prog, mode='auto')
    platform = str(jax.default_backend())
    files = list((tuner_env / 'da4ml-run-modes').glob('*.json'))
    assert len(files) == 1 and files[0].name.endswith(f'.{platform}.json')
    assert any(k.endswith(f'@{platform}') for k in jb.mode_decisions())

    # same digest, different platform: both the memory and the file cache miss
    jb._MODE_DECISIONS.clear()
    monkeypatch.setattr(jb, '_platform', lambda: 'tpu-imaginary')
    n_before = metrics_snapshot().get('run.autotune', {}).get('value', 0)
    ex2 = DaisExecutor(prog, mode='auto')
    assert ex2.mode in MODES
    assert metrics_snapshot().get('run.autotune', {}).get('value', 0) == n_before + 1, 'cross-platform decision reuse'
    assert len(list((tuner_env / 'da4ml-run-modes').glob('*.json'))) == 2
    assert ex1.mode in MODES


def test_autotune_pallas_measured_never_favored_when_slower(tuner_env, monkeypatch):
    """DA4ML_PALLAS_AUTOTUNE=1 forces the pallas candidate into the race even
    on an interpret-only platform; the tuner measures it and must only pick
    it when it actually won the clock."""
    import json

    monkeypatch.setenv('DA4ML_PALLAS_AUTOTUNE', '1')
    rng = np.random.default_rng(37)
    prog = random_program(rng, n_ops=300, n_in=6, n_out=4)
    ex = DaisExecutor(prog, mode='auto')
    assert ex.mode in MODES
    files = list((tuner_env / 'da4ml-run-modes').glob('*.json'))
    assert len(files) == 1
    blob = json.loads(files[0].read_text())
    assert blob['mode'] == ex.mode
    assert 'pallas_samples_per_s' in blob or 'pallas_error' in blob, 'pallas must have been measured'
    if ex.mode != 'pallas' and 'pallas_samples_per_s' in blob:
        assert blob['pallas_samples_per_s'] <= blob[f'{ex.mode}_samples_per_s']
