"""Cross-mode runtime parity + autotuner determinism (docs/runtime.md).

Randomized DAIS programs (ir.synth) covering every opcode family — LUT ops,
negative shifts, muxes, bitwise ops, the int64 wide path, packed int8/int16
I/O — must run bit-exactly identical through the numpy oracle and all three
device execution modes (unroll / scan / level). Plus: the level scheduler's
invariants, the mode autotuner's cached decision and env override, the
bytes-adaptive chunking, and the sharded-by-default batch path (conftest
provides the virtual 8-device CPU mesh).
"""

import numpy as np
import pytest

from da4ml_tpu.ir.schedule import levelize_comb, levelize_program
from da4ml_tpu.ir.synth import FAMILIES, random_inputs, random_program
from da4ml_tpu.runtime import jax_backend as jb
from da4ml_tpu.runtime.jax_backend import MODES, DaisExecutor
from da4ml_tpu.runtime.numpy_backend import run_program


def _traced_model(rng):
    """A traced model exercising LUTs, relu, abs, and bitwise ops."""
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    inp = FixedVariableArrayInput((8,), hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(8), np.full(8, 4), np.full(8, 1))
    w = rng.integers(-8, 8, (8, 5)).astype(np.float64)
    y = np.sin(x[:4]).quantize(np.ones(4), np.ones(4), np.full(4, 6))
    z = (x @ w).relu()
    out = np.concatenate([z, y, abs(x[:2]), x[:2] & x[2:4]])
    return comb_trace(inp, out)


# ---------------------------------------------------------------------------
# level scheduler
# ---------------------------------------------------------------------------


def test_levelize_invariants():
    rng = np.random.default_rng(5)
    prog = random_program(rng, n_ops=300, n_in=6, n_out=4)
    sched = levelize_program(prog)
    lvl = sched.level
    for i in range(prog.n_ops):
        oc = int(prog.opcode[i])
        if oc in (-1, 5):
            assert lvl[i] == 0
            continue
        assert lvl[i] > lvl[int(prog.id0[i])]
        if oc in (0, 1, 6, -6, 7, 10):
            assert lvl[i] > lvl[int(prog.id1[i])]
        if abs(oc) == 6:
            assert lvl[i] > lvl[int(prog.data_lo[i])]
    # order is a permutation, level-sorted, with starts bounding each level
    assert sorted(sched.order.tolist()) == list(range(prog.n_ops))
    assert (np.diff(lvl[sched.order]) >= 0).all()
    for level in range(sched.depth):
        assert (lvl[sched.ops_at(level)] == level).all()
    assert sched.starts[-1] == prog.n_ops
    assert sched.width_max >= 1 and sched.width_mean > 0


def test_levelize_comb_matches_program(rng):
    from da4ml_tpu.ir.dais_binary import decode

    comb = _traced_model(rng)
    sc = levelize_comb(comb)
    sp = levelize_program(decode(comb.to_binary()))
    np.testing.assert_array_equal(sc.level, sp.level)


def test_layered_program_depth():
    rng = np.random.default_rng(2)
    prog = random_program(rng, n_ops=2000, n_in=8, n_out=4, n_levels=10)
    sched = levelize_program(prog)
    assert 10 <= sched.depth <= 14  # n_levels + a little slack for muxes
    assert sched.width_mean > 100


# ---------------------------------------------------------------------------
# cross-mode bit-exactness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize('seed', [0, 1, 2, 3])
def test_parity_random_programs(seed):
    rng = np.random.default_rng(seed)
    prog = random_program(rng, n_ops=250, n_in=6, n_out=5)
    data = random_inputs(rng, prog, 257)  # odd: exercises shard padding
    ref = run_program(prog, data)
    for mode in MODES:
        got = DaisExecutor(prog, mode=mode)(data)
        np.testing.assert_array_equal(got, ref, err_msg=f'mode={mode} seed={seed}')


def test_parity_covers_all_families():
    present: set[int] = set()
    for seed in range(4):
        rng = np.random.default_rng(seed)
        prog = random_program(rng, n_ops=250, n_in=6, n_out=5, families=FAMILIES)
        present |= set(np.abs(prog.opcode).tolist())
    # input, add/sub, relu, quant, cadd, const, mux, mul, lookup, bitu, bitb
    assert {1, 0, 2, 3, 4, 5, 6, 7, 8, 9, 10} <= present


def test_parity_wide_i64_scoped():
    """Wide programs run on the int64 path without flipping jax_enable_x64
    process-wide (the old global flip invalidated unrelated cached jits)."""
    import jax

    flag_before = jax.config.read('jax_enable_x64')
    rng = np.random.default_rng(11)
    prog = random_program(rng, n_ops=150, n_in=4, n_out=3, wide=True)
    data = random_inputs(rng, prog, 65)
    ref = run_program(prog, data)
    for mode in MODES:
        ex = DaisExecutor(prog, mode=mode)
        assert ex.use_i64, 'wide program must take the int64 path'
        np.testing.assert_array_equal(ex(data), ref, err_msg=f'mode={mode}')
    assert jax.config.read('jax_enable_x64') == flag_before


def test_parity_traced_model_level(rng):
    """Level mode on a real traced program (LUT via sin, relu, bit ops)."""
    from da4ml_tpu.ir.dais_binary import decode

    comb = _traced_model(rng)
    prog = decode(comb.to_binary())
    data = rng.uniform(-16, 16, (64, 8))
    ref = comb.predict(data, backend='numpy')
    for mode in MODES:
        got = DaisExecutor(prog, mode=mode)(data)
        np.testing.assert_array_equal(got, ref, err_msg=f'mode={mode}')


def test_parity_packed_io_level():
    """Packed int8/int16 host<->device lanes are bit-exact in level mode."""
    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    rng = np.random.default_rng(12)
    inp = FixedVariableArrayInput(6, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(6), np.full(6, 2), np.full(6, 1))
    w = rng.integers(-4, 4, (6, 3)).astype(np.float64)
    comb = comb_trace(inp, (x @ w).relu(i=np.full(3, 5), f=np.full(3, 1)))
    ex = DaisExecutor(decode(comb.to_binary()), mode='level')
    assert ex._in_group in (2, 4) and ex._out_group in (2, 4)
    data = rng.uniform(-4, 4, (64, 6))
    np.testing.assert_array_equal(ex(data), comb.predict(data, backend='numpy'))


def test_unroll_refuses_large_level_runs_it():
    """Past UNROLL_LIMIT the unrolled jaxpr refuses; level compiles the same
    program in O(depth × families) and matches scan and the numpy oracle."""
    rng = np.random.default_rng(7)
    big = random_program(rng, n_ops=20_500, n_in=16, n_out=8, n_levels=24)
    assert big.n_ops > DaisExecutor.UNROLL_LIMIT
    with pytest.raises(ValueError, match='unroll'):
        DaisExecutor(big, mode='unroll')
    data = random_inputs(rng, big, 64)
    ref = run_program(big, data)
    out_level = DaisExecutor(big, mode='level')(data)
    out_scan = DaisExecutor(big, mode='scan')(data)
    np.testing.assert_array_equal(out_level, ref)
    np.testing.assert_array_equal(out_scan, ref)


# ---------------------------------------------------------------------------
# autotuner
# ---------------------------------------------------------------------------


@pytest.fixture
def tuner_env(monkeypatch, tmp_path):
    """Isolated decision cache + tiny autotune batch."""
    import jax

    old = jax.config.jax_compilation_cache_dir
    jax.config.update('jax_compilation_cache_dir', str(tmp_path))
    monkeypatch.setenv('DA4ML_RUN_AUTOTUNE_MIN_OPS', '0')
    monkeypatch.setenv('DA4ML_RUN_AUTOTUNE_BATCH', '64')
    saved = dict(jb._MODE_DECISIONS)
    jb._MODE_DECISIONS.clear()
    yield tmp_path
    jb._MODE_DECISIONS.clear()
    jb._MODE_DECISIONS.update(saved)
    jax.config.update('jax_compilation_cache_dir', old)


def test_autotune_decision_cached(tuner_env):
    from da4ml_tpu.telemetry.metrics import enable_metrics, metrics_snapshot

    enable_metrics()
    rng = np.random.default_rng(21)
    prog = random_program(rng, n_ops=300, n_in=6, n_out=4)
    ex1 = DaisExecutor(prog, mode='auto')
    assert ex1.mode in MODES
    n_tuned = metrics_snapshot().get('run.autotune', {}).get('value', 0)
    assert n_tuned >= 1
    files = list((tuner_env / 'da4ml-run-modes').glob('*.json'))
    assert len(files) == 1, 'decision must persist next to the XLA cache'

    # same process, memory cache cleared: the persisted decision is reused
    jb._MODE_DECISIONS.clear()
    ex2 = DaisExecutor(prog, mode='auto')
    assert ex2.mode == ex1.mode
    snap = metrics_snapshot()
    assert snap.get('run.autotune', {}).get('value', 0) == n_tuned, 'no re-measure on cache hit'
    assert snap.get('run.mode_cache_hit', {}).get('value', 0) >= 1


def test_run_mode_env_forces(tuner_env, monkeypatch):
    rng = np.random.default_rng(22)
    prog = random_program(rng, n_ops=300, n_in=6, n_out=4)
    monkeypatch.setenv('DA4ML_RUN_MODE', 'scan')
    ex = DaisExecutor(prog, mode='auto')
    assert ex.mode == 'scan'
    # explicit modes are not overridden
    ex2 = DaisExecutor(prog, mode='level')
    assert ex2.mode == 'level'


def test_autotune_disabled_heuristic(tuner_env, monkeypatch):
    monkeypatch.setenv('DA4ML_RUN_AUTOTUNE', '0')
    rng = np.random.default_rng(23)
    prog = random_program(rng, n_ops=300, n_in=6, n_out=4)
    assert DaisExecutor(prog, mode='auto').mode == 'unroll'


# ---------------------------------------------------------------------------
# batching: adaptive chunking, default sharding, donation knobs
# ---------------------------------------------------------------------------


def test_infer_chunks_bytes(monkeypatch):
    monkeypatch.delenv('DA4ML_JAX_INFER_CHUNKS', raising=False)
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNK_BYTES', str(1 << 20))
    assert jb._infer_chunks(1024, 16) == 1  # 16 KiB total: no chunking
    assert jb._infer_chunks(1 << 18, 16) == 4  # 4 MiB / 1 MiB budget
    assert jb._infer_chunks(1024, 1 << 16) == 16  # 64 MiB wide rows: capped
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNKS', '7')
    assert jb._infer_chunks(1 << 18, 16) == 7  # explicit count wins
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNKS', '0')
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNK_BYTES', '256')
    assert jb._infer_chunks(1024, 1) == 4  # 1 KiB / 256 B budget


def test_chunked_sharded_call_bit_exact(monkeypatch):
    """Chunking + default 8-device sharding + row padding are invisible:
    bit-identical to the numpy oracle."""
    rng = np.random.default_rng(31)
    prog = random_program(rng, n_ops=200, n_in=6, n_out=4)
    data = random_inputs(rng, prog, 1003)  # not divisible by chunks or devices
    ref = run_program(prog, data)
    ex = DaisExecutor(prog, mode='level')
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNKS', '7')
    np.testing.assert_array_equal(ex(data), ref)
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNKS', '0')
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNK_BYTES', '1024')
    np.testing.assert_array_equal(ex(data), ref)
    monkeypatch.setenv('DA4ML_RUN_SHARD', '0')
    np.testing.assert_array_equal(ex(data), ref)


def test_default_sharding_active():
    import jax

    assert jax.local_device_count() == 8, 'conftest provides the virtual mesh'
    assert jb._active_sharding() is not None
    assert int(jb._active_sharding().mesh.devices.size) == 8


# ---------------------------------------------------------------------------
# pipelines and the public entry points
# ---------------------------------------------------------------------------


def _pipeline_case(rng):
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace, to_pipeline

    inp = FixedVariableArrayInput(8, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(8), np.full(8, 3), np.full(8, 2))
    w1 = rng.integers(-8, 8, (8, 12)).astype(np.float64)
    x = (x @ w1).relu(i=np.full(12, 6), f=np.full(12, 2))
    w2 = rng.integers(-8, 8, (12, 4)).astype(np.float64)
    comb = comb_trace(inp, x @ w2)
    return comb, to_pipeline(comb, 3.0)


def test_pipeline_chained_device_resident(rng):
    """run_pipeline(fused=False): per-stage programs with device-resident
    donated intermediates, bit-exact with the fused path and the oracle."""
    from da4ml_tpu.runtime.jax_backend import run_pipeline

    comb, pipe = _pipeline_case(rng)
    assert len(pipe.stages) > 1
    data = rng.uniform(-8, 8, (333, 8))
    ref = comb.predict(data, backend='numpy')
    chain = [s.to_binary() for s in pipe.stages]
    np.testing.assert_array_equal(run_pipeline(chain, data), ref)
    np.testing.assert_array_equal(run_pipeline(chain, data, fused=False), ref)


def test_run_comb_mode_param(rng):
    from da4ml_tpu.runtime import run_comb

    comb = _traced_model(rng)
    data = rng.uniform(-16, 16, (64, 8))
    ref = comb.predict(data, backend='numpy')
    np.testing.assert_array_equal(run_comb(comb, data, mode='level'), ref)
    with pytest.raises(ValueError, match='mode'):
        run_comb(comb, data, backend='cpp', mode='level')


def test_run_metrics_emitted(rng):
    from da4ml_tpu.telemetry.metrics import enable_metrics, metrics_snapshot

    enable_metrics()
    prog_rng = np.random.default_rng(41)
    prog = random_program(prog_rng, n_ops=120, n_in=5, n_out=3)
    ex = DaisExecutor(prog, mode='level')
    ex(random_inputs(prog_rng, prog, 64))
    snap = metrics_snapshot()
    assert snap.get('run.mode.level', {}).get('value', 0) >= 1
    assert 'run.samples_per_s' in snap
    assert 'run.compile_s' in snap
    assert snap.get('run.samples', {}).get('value', 0) >= 64


def test_x64_warn_once_dedup():
    from da4ml_tpu.telemetry.log import _warned_once, warn_once

    key = 'test.warn_once_key'
    _warned_once.discard(key)
    assert warn_once(key, 'only once') is True
    assert warn_once(key, 'only once') is False


@pytest.mark.parametrize('env', ['0', '1'])
def test_donate_env_knob(monkeypatch, env):
    monkeypatch.setenv('DA4ML_RUN_DONATE', env)
    dn = jb._donate_argnums()
    if env == '0':
        assert dn == ()
    else:
        import jax

        assert dn == (() if jax.default_backend() == 'cpu' else (0,))
