"""Beam/portfolio search invariants (docs/cmvm.md#search-strategies).

The contracts under test:

- never-worse: a beam solve's cost is <= the greedy solve's on every kernel
  (the unforked greedy lane always rides in the batch);
- ``quality='fast'`` (the default) is byte-identical to the pre-beam solver;
- beam solves are deterministic across runs and across mesh shardings on
  the 8-device CPU mesh;
- ``SearchSpec`` round-trips through checkpoint keys;
- the learned ranker reproduces train -> save -> load -> rank bit-exactly.
"""

import json

import numpy as np
import pytest

from da4ml_tpu.cmvm import QUALITY_PRESETS, SearchSpec, resolve_quality, solve
from da4ml_tpu.cmvm.jax_search import solve_jax_many
from da4ml_tpu.ir import QInterval


def random_kernel(rng, n_dim, bits, n_out=None):
    n_out = n_dim if n_out is None else n_out
    mag = rng.integers(0, 2**bits, (n_dim, n_out)).astype(np.float64)
    sign = rng.choice([-1.0, 1.0], (n_dim, n_out))
    return mag * sign


def assert_pipelines_identical(a, b):
    """Op-for-op byte identity of two solved pipelines."""
    assert a.cost == b.cost and a.latency == b.latency
    for sa, sb in zip(a.stages, b.stages):
        assert len(sa.ops) == len(sb.ops)
        for oa, ob in zip(sa.ops, sb.ops):
            assert (oa.id0, oa.id1, oa.opcode, oa.data) == (ob.id0, ob.id1, ob.opcode, ob.data)


# ---------------------------------------------------------------------------
# spec / presets
# ---------------------------------------------------------------------------


def test_spec_presets_and_resolution():
    assert resolve_quality(None).is_fast and resolve_quality('fast').is_fast
    s = resolve_quality('search')
    assert s.forks and s.beam == 5 and s.focus == 3 and s.include_host
    m = resolve_quality('max')
    assert m.beam == 8 and len(m.portfolio) == 6 and m.n_restarts == 4 and m.focus == 0
    assert resolve_quality(s) is s
    assert resolve_quality(s.to_dict()) == s
    with pytest.raises(ValueError):
        resolve_quality('bogus')
    with pytest.raises(TypeError):
        resolve_quality(3)
    with pytest.raises(ValueError):
        SearchSpec(beam=0)
    with pytest.raises(ValueError):
        SearchSpec(portfolio=('nope',))
    with pytest.raises(ValueError):
        SearchSpec.from_dict({'beam': 2, 'bogus_key': 1})


def test_spec_roundtrip_through_checkpoint_keys(tmp_path):
    from da4ml_tpu.reliability.checkpoint import kernel_key
    from da4ml_tpu.reliability.orchestrator import _checkpoint_opts

    k = np.eye(4)
    spec = QUALITY_PRESETS['search']
    key_name = kernel_key(k, _checkpoint_opts({'method0': 'wmc', 'quality': 'search'}))
    key_spec = kernel_key(k, _checkpoint_opts({'method0': 'wmc', 'quality': spec}))
    key_dict = kernel_key(k, _checkpoint_opts({'method0': 'wmc', 'quality': spec.to_dict()}))
    key_fast = kernel_key(k, _checkpoint_opts({'method0': 'wmc', 'quality': 'fast'}))
    key_none = kernel_key(k, _checkpoint_opts({'method0': 'wmc'}))
    assert key_name == key_spec == key_dict
    assert key_fast == key_none != key_name


def test_spec_checkpoint_hit_across_spellings(rng, tmp_path):
    """A beam solve checkpointed under the preset name is restored by the
    equivalent SearchSpec — and never by a fast solve."""
    from da4ml_tpu.reliability import SolveReport

    kernel = random_kernel(rng, 5, 3)
    ckpt = tmp_path / 'ck.json'
    r1 = SolveReport()
    s1 = solve(kernel, backend='jax', quality='search', checkpoint=ckpt, report=r1)
    assert r1.checkpoint_misses == 1
    r2 = SolveReport()
    s2 = solve(kernel, backend='jax', quality=QUALITY_PRESETS['search'], checkpoint=ckpt, report=r2)
    assert r2.checkpoint_hits == 1
    assert_pipelines_identical(s1, s2)
    r3 = SolveReport()
    solve(kernel, backend='jax', checkpoint=ckpt, report=r3)
    assert r3.checkpoint_hits == 0 and r3.checkpoint_misses == 1


# ---------------------------------------------------------------------------
# beam invariants
# ---------------------------------------------------------------------------


def test_beam_never_worse_randomized_corpus(rng):
    """Beam result cost <= greedy cost on every kernel of a randomized
    corpus, with exactness (the acceptance invariant)."""
    kernels = [
        random_kernel(rng, int(rng.integers(4, 11)), int(rng.integers(2, 5)), int(rng.integers(4, 11)))
        for _ in range(8)
    ]
    greedy = solve_jax_many(kernels)
    beam = solve_jax_many(kernels, quality='search')
    for k, g, b in zip(kernels, greedy, beam):
        np.testing.assert_array_equal(np.asarray(b.kernel, np.float64), k)
        assert b.cost <= g.cost, (b.cost, g.cost)
        x = rng.integers(-8, 8, (32, k.shape[0])).astype(np.float64)
        np.testing.assert_array_equal(b.predict(x, backend='numpy'), x @ k)


def test_quality_fast_byte_identical(rng):
    """The default path must not change at all under the beam integration."""
    kernels = [random_kernel(rng, n, 4) for n in (4, 6, 8)]
    base = solve_jax_many(kernels)
    fast = solve_jax_many(kernels, quality='fast')
    none = solve_jax_many(kernels, quality=None)
    for b, f, n in zip(base, fast, none):
        assert_pipelines_identical(b, f)
        assert_pipelines_identical(b, n)


def test_beam_deterministic_across_runs(rng):
    kernels = [random_kernel(rng, 7, 4) for _ in range(3)]
    a = solve_jax_many(kernels, quality='search')
    b = solve_jax_many(kernels, quality='search')
    for x, y in zip(a, b):
        assert_pipelines_identical(x, y)


def test_beam_deterministic_across_mesh_shardings(rng):
    """Same decisions whether the lane batch runs on 1, 4, or 8 of the CPU
    mesh devices (beam slots shard like any other lane)."""
    import jax
    from jax.sharding import Mesh

    kernels = [random_kernel(rng, 6, 4) for _ in range(3)]
    devs = jax.devices()
    assert len(devs) >= 8, 'conftest must provide the virtual 8-device mesh'
    ref = solve_jax_many(kernels, quality='search', mesh=None)
    for nd in (4, 8):
        mesh = Mesh(np.asarray(devs[:nd]), ('batch',))
        got = solve_jax_many(kernels, quality='search', mesh=mesh)
        for x, y in zip(ref, got):
            assert_pipelines_identical(x, y)


def test_beam_under_hard_dc_budget(rng):
    from math import inf

    from da4ml_tpu.cmvm.api import minimal_latency

    kernel = random_kernel(rng, 6, 4)
    for hard_dc in (0, 2):
        sol = solve_jax_many([kernel], hard_dc=hard_dc, quality='search')[0]
        np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)
        qints = [QInterval(-128.0, 127.0, 1.0)] * 6
        allowed = hard_dc + minimal_latency(kernel, qints, [0.0] * 6, -1, -1)
        max_lat = max((lt for st in sol.stages for lt in st.out_latency), default=0.0)
        assert max_lat <= allowed < inf


def test_beam_heterogeneous_qintervals(rng):
    """Fork prefixes respect per-input metadata (restart perms included)."""
    kernel = random_kernel(rng, 6, 4)
    qints = [QInterval(-(2.0**e), 2.0**e - 2.0**-2, 2.0**-2) for e in range(2, 8)]
    lats = [float(i % 3) for i in range(6)]
    sol = solve_jax_many([kernel], qintervals_list=[qints], latencies_list=[lats], quality='search')[0]
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)
    x = np.stack([rng.integers(-(2**e), 2**e, 64) for e in range(2, 8)], axis=1).astype(np.float64)
    np.testing.assert_array_equal(sol.predict(x, backend='numpy'), x @ kernel)


def test_beam_never_worse_than_host_oracle(rng):
    """quality='search' folds the oracle in: never a cost regression."""
    from da4ml_tpu.cmvm import api as host_api

    kernels = [random_kernel(rng, 8, 4) for _ in range(4)]
    host = [host_api.solve(k, backend='auto') for k in kernels]
    beam = solve_jax_many(kernels, quality='search')
    for k, h, b in zip(kernels, host, beam):
        assert b.cost <= h.cost, (b.cost, h.cost)


# ---------------------------------------------------------------------------
# heuristics / expansion primitives
# ---------------------------------------------------------------------------


@pytest.mark.parametrize('method', ['mc', 'wmc', 'mc-dc', 'wmc-dc', 'mc-pdc', 'wmc-pdc'])
def test_top_candidates_head_matches_select_pair(rng, method):
    from da4ml_tpu.cmvm.heuristics import select_pair, top_candidates
    from da4ml_tpu.cmvm.state import create_state, update_state

    kernel = random_kernel(rng, 7, 4)
    st = create_state(kernel, [QInterval(-128.0, 127.0, 1.0)] * 7, [0.0] * 7)
    steps = 0
    while st.freq_stat and steps < 64:
        cands = top_candidates(st, method, 4)
        pair = select_pair(st, method)
        if pair.id0 == -1:
            break
        assert cands and cands[0][0] == pair
        assert len({c[0] for c in cands}) == len(cands)  # distinct pairs
        update_state(st, pair, -1, -1)
        steps += 1
    assert steps > 0


def test_expand_beam_lanes_prefix_contract(rng):
    """Fork prefixes are valid CSE states: records reference earlier slots,
    digit tensors stay trits, and forks of byte-identical lanes are shared."""
    from da4ml_tpu.cmvm.jax_search import _Lane
    from da4ml_tpu.cmvm.search.beam import expand_beam_lanes

    kernel = random_kernel(rng, 6, 4)
    qints = [QInterval(-128.0, 127.0, 1.0)] * 6
    lanes = [
        _Lane(kernel, qints, [0.0] * 6, 'wmc'),
        _Lane(kernel, qints, [0.0] * 6, 'wmc'),  # duplicate: shares expansion
        _Lane(kernel, qints, [0.0] * 6, 'dummy'),  # never forked
    ]
    spec = SearchSpec(beam=3, depth=2)
    forks = expand_beam_lanes(lanes, spec, -1, -1)
    assert forks, 'beam must fork at least one trajectory'
    assert all(ji in (0, 1) for ji, _, _ in forks)
    shared = {}
    for ji, fln, meta in forks:
        pfx = fln.prefix
        d = len(pfx.rec)
        assert 1 <= d <= spec.depth
        assert pfx.E.shape == (6 + d, 6, pfx.E.shape[2])
        assert set(np.unique(pfx.E)) <= {-1, 0, 1}
        for t, (id0, id1, sub, shift) in enumerate(pfx.rec):
            assert 0 <= id0 <= id1 < 6 + t and sub in (0, 1)
        assert len(meta) == d and all('features' in s for s in meta)
        shared.setdefault(ji, []).append(pfx.rec.tobytes())
    # the duplicate lane reuses the memoized expansion byte-for-byte
    assert shared.get(0) == shared.get(1)


# ---------------------------------------------------------------------------
# device-resident beam: fork/score/prune on device vs the host-beam oracle
# ---------------------------------------------------------------------------


def _fork_key(fln):
    return fln.prefix.key


def test_device_beam_fork_parity_fuzz(rng):
    """The device fork phase (_device_beam_expand) is fork-for-fork
    byte-identical to the host beam under CostRanker: same source lanes,
    same frontier order, same prefixes (rec/E/qmeta/lat) and trace meta —
    across methods, beam/depth shapes, grid-edge dims, restart perms, and
    heterogeneous qintervals."""
    from da4ml_tpu.cmvm.jax_search import _Lane, _device_beam_expand
    from da4ml_tpu.cmvm.search.beam import expand_beam_lanes

    def lane(kern, method, perm=None, qints=None, lats=None):
        n = kern.shape[0]
        return _Lane(
            kern,
            qints or [QInterval(-128.0, 127.0, 1.0)] * n,
            lats or [0.0] * n,
            method,
            perm=perm,
        )

    het_q = [QInterval(-(2.0**e), 2.0**e - 2.0**-2, 2.0**-2) for e in range(2, 8)]
    lanes = [
        lane(random_kernel(rng, 6, 4), 'wmc'),
        lane(random_kernel(rng, 7, 3, 5), 'mc'),
        lane(random_kernel(rng, 9, 4, 5), 'wmc-dc'),
        lane(random_kernel(rng, 12, 5, 12), 'wmc'),  # pow2-edge dims
        lane(random_kernel(rng, 6, 4), 'wmc', perm=rng.permutation(6)),
        lane(random_kernel(rng, 6, 3), 'mc-pdc', qints=list(het_q), lats=[float(i % 3) for i in range(6)]),
    ]
    lanes.append(lanes[0])  # duplicate: must share its expansion
    for beam, depth in ((3, 2), (5, 1), (2, 3)):
        spec = SearchSpec(beam=beam, depth=depth)
        host = expand_beam_lanes(
            [_Lane(l.kernel, l.qintervals, l.latencies, l.method, perm=l.perm) for l in lanes], spec, -1, -1
        )
        dev, ecarry = _device_beam_expand(lanes, spec, -1, -1)
        assert len(host) == len(dev), (beam, depth, len(host), len(dev))
        assert set(ecarry) == set(range(len(dev)))
        for (hi, hl, hm), (di, dl, dm) in zip(host, dev):
            assert hi == di
            assert hl.prefix.key == dl.prefix.key
            assert hl.prefix.qmeta.tobytes() == dl.prefix.qmeta.tobytes()
            assert hl.prefix.lat.tobytes() == dl.prefix.lat.tobytes()
            assert hm == dm


def test_device_beam_full_solve_parity_focus_modes(rng):
    """quality= solves are byte-identical between the resident beam and the
    host-beam path across focus modes (single-phase focus=0, two-phase
    focus>0) and beam/depth shapes."""
    import os

    kernels = [random_kernel(rng, 10, 4), random_kernel(rng, 8, 3, 12)]
    for quality in ('search', {'beam': 3, 'depth': 2, 'focus': 0}, {'beam': 4, 'depth': 1, 'focus': 2}):
        resident = solve_jax_many(kernels, quality=quality)
        os.environ['DA4ML_JAX_DEVICE_RESIDENT'] = '0'
        try:
            hostbeam = solve_jax_many(kernels, quality=quality)
        finally:
            os.environ.pop('DA4ML_JAX_DEVICE_RESIDENT', None)
        for a, b in zip(resident, hostbeam):
            assert_pipelines_identical(a, b)


def test_device_beam_learned_ranker_never_worse(rng, tmp_path):
    """Under a LearnedRanker the device prune scores in f32 (the host beam
    in f64), so fork choices may diverge in ties — the contract is
    exactness plus never-worse-than-greedy, and determinism across runs."""
    from da4ml_tpu.cmvm.search.ranker import FEATURE_NAMES
    from da4ml_tpu.cmvm.search.train import train_ranker

    prng = np.random.default_rng(5)
    X = prng.normal(size=(64, len(FEATURE_NAMES)))
    y = X @ np.asarray([1.0, -0.5, 0.2, 0.0, 0.3]) + 0.1 * prng.normal(size=64)
    path = tmp_path / 'ranker.json'
    train_ranker(X, y).save(path)
    spec = SearchSpec(beam=3, depth=2, ranker=str(path))
    kernels = [random_kernel(rng, 7, 4) for _ in range(3)]
    greedy = solve_jax_many(kernels)
    a = solve_jax_many(kernels, quality=spec)
    b = solve_jax_many(kernels, quality=spec)
    for k, g, x, y2 in zip(kernels, greedy, a, b):
        np.testing.assert_array_equal(np.asarray(x.kernel, np.float64), k)
        assert x.cost <= g.cost
        assert_pipelines_identical(x, y2)


def test_device_beam_telemetry_and_traffic(rng):
    """The resident beam reports the search.device_* counter family and a
    fraction of the host-beam path's host<->device traffic; the host-beam
    path reports host-seeded prefix lanes instead."""
    import os

    from da4ml_tpu import telemetry
    from da4ml_tpu.telemetry.metrics import metrics_snapshot

    kernels = [random_kernel(rng, 10, 4), random_kernel(rng, 9, 3)]
    telemetry.enable()
    try:
        resident = solve_jax_many(kernels, quality='search')
        s_res = metrics_snapshot()
        telemetry.reset()
        telemetry.enable()
        os.environ['DA4ML_JAX_DEVICE_RESIDENT'] = '0'
        try:
            legacy = solve_jax_many(kernels, quality='search')
        finally:
            os.environ.pop('DA4ML_JAX_DEVICE_RESIDENT', None)
        s_leg = metrics_snapshot()
    finally:
        telemetry.disable()
        telemetry.reset()
    for a, b in zip(resident, legacy):
        assert_pipelines_identical(a, b)
    assert s_res.get('search.device_forks', {}).get('value', 0) > 0
    assert 'search.device_prunes' in s_res
    assert s_res.get('sched.entry_carry_groups', {}).get('value', 0) > 0
    assert s_res.get('search.host_seeded_lanes', {}).get('value', 0) == 0
    assert s_leg.get('search.device_forks', {}).get('value', 0) == 0
    assert s_leg.get('search.host_seeded_lanes', {}).get('value', 0) > 0
    # decisions-only fork fetch: >= 3x below the host-beam path (the CI
    # quality gate enforces the same floor on the committed corpus)
    assert s_res['sched.fetch_bytes']['value'] * 3 <= s_leg['sched.fetch_bytes']['value']
    assert s_res['sched.upload_bytes']['value'] < s_leg['sched.upload_bytes']['value']


def test_device_beam_prewarm_enumeration(rng, monkeypatch):
    """prewarm_for_kernels(quality=...) enumerates the fork-phase classes
    (fork step, frontier prune, widened-sel fan-out transitions) plus the
    fork lanes' full_rec CSE ladder."""
    import da4ml_tpu.cmvm.jax_search as js

    kernels = [random_kernel(rng, 6, 4), random_kernel(rng, 8, 3)]
    forks, prunes, trans, classes = [], [], [], []
    monkeypatch.setattr(js, '_prewarm_fork', lambda fs, b: forks.append((fs, b)))
    monkeypatch.setattr(js, '_prewarm_prune', lambda C, K, kind, G: prunes.append((C, K, kind, G)))
    monkeypatch.setattr(js, '_prewarm_transition', lambda s, b1, b2: trans.append((s, b1, b2)))
    monkeypatch.setattr(js, '_prewarm_class', lambda spec, bucket: classes.append(spec))
    n = js.prewarm_for_kernels([kernels], full_ladder=True, inline=True, quality='search')
    assert n > 0
    assert forks and prunes and trans
    assert all(fs.beam == 5 for fs, _ in forks)
    assert any(spec.full_rec for spec in classes), 'fork-lane CSE ladder classes must be enumerated'


# ---------------------------------------------------------------------------
# ranker / training
# ---------------------------------------------------------------------------


def test_ranker_train_save_load_rank_reproducible(tmp_path):
    from da4ml_tpu.cmvm.search.ranker import FEATURE_NAMES, LearnedRanker
    from da4ml_tpu.cmvm.search.train import train_ranker

    prng = np.random.default_rng(11)
    X = prng.normal(size=(64, len(FEATURE_NAMES)))
    y = X @ np.asarray([1.0, -0.5, 0.2, 0.0, 0.3]) + 0.1 * prng.normal(size=64)
    r1 = train_ranker(X, y)
    p = tmp_path / 'ranker.json'
    r1.save(p)
    r2 = LearnedRanker.load(p)
    np.testing.assert_array_equal(r1.predict(X), r2.predict(X))
    # training is deterministic: same data -> identical weights
    r3 = train_ranker(X, y)
    np.testing.assert_array_equal(r1.weights, r3.weights)
    blob1 = json.loads(p.read_text())
    r2.save(p)
    assert json.loads(p.read_text()) == blob1


def test_trace_export_and_training_workflow(rng, tmp_path, monkeypatch):
    """DA4ML_SEARCH_TRACE_DIR -> (features, chosen, final-cost-delta) JSONL
    -> trained ranker -> steers a solve (the full satellite workflow)."""
    from da4ml_tpu.cmvm.search.trace import load_trace_dir
    from da4ml_tpu.cmvm.search.train import main as train_main
    from da4ml_tpu.cmvm.search.train import records_to_xy

    tdir = tmp_path / 'traces'
    monkeypatch.setenv('DA4ML_SEARCH_TRACE_DIR', str(tdir))
    kernels = [random_kernel(rng, 6, 4) for _ in range(2)]
    solve_jax_many(kernels, quality='search')
    monkeypatch.delenv('DA4ML_SEARCH_TRACE_DIR')
    records = load_trace_dir(tdir)
    assert records
    for r in records:
        assert len(r['features']) == 5 and isinstance(r['chosen'], bool)
        assert isinstance(r['final_cost_delta'], float)
    X, y = records_to_xy(records)
    assert X.shape[0] == len(records)
    out = tmp_path / 'ranker.json'
    assert train_main([str(tdir), str(out)]) == 0
    spec = SearchSpec(beam=3, depth=2, ranker=str(out))
    greedy = solve_jax_many(kernels)
    learned = solve_jax_many(kernels, quality=spec)
    for k, g, b in zip(kernels, greedy, learned):
        np.testing.assert_array_equal(np.asarray(b.kernel, np.float64), k)
        assert b.cost <= g.cost


# ---------------------------------------------------------------------------
# degradation satellites
# ---------------------------------------------------------------------------


def test_host_backend_degrades_with_report_warnings(rng):
    """n_restarts / beam quality on a host backend: recorded in the
    SolveReport instead of dropped on the floor (warn_once fires too)."""
    from da4ml_tpu.reliability import SolveReport

    kernel = random_kernel(rng, 5, 3)
    rep = SolveReport()
    sol = solve(kernel, backend='cpu', quality='search', n_restarts=4, report=rep)
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)
    assert any('n_restarts' in w for w in rep.warnings), rep.warnings
    assert any('quality' in w for w in rep.warnings), rep.warnings
    assert rep.to_dict()['warnings'] == rep.warnings
    # a jax-backend beam solve records no degradation
    rep2 = SolveReport()
    solve(kernel, backend='jax', quality='search', n_restarts=2, report=rep2)
    assert rep2.backend_used == 'jax' and not rep2.warnings


def test_quality_through_solver_options(rng):
    """quality= routes through solver_options on the tracer path and keeps
    bit-exactness on both backends."""
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    w = random_kernel(rng, 6, 3)
    for backend in ('jax', 'cpu'):
        opts = {'backend': backend, 'quality': 'search'}
        inp = FixedVariableArrayInput((3, 6), hwconf=HWConfig(1, -1, -1), solver_options=opts)
        x = inp.quantize(np.ones((3, 6)), np.full((3, 6), 3), np.zeros((3, 6), np.int64))
        comb = comb_trace(inp, x @ w)
        data = rng.integers(-8, 8, (16, 18)).astype(np.float64)
        out = comb.predict(data, backend='numpy')
        np.testing.assert_array_equal(out.reshape(16, 3, -1), data.reshape(16, 3, 6) @ w)


def test_search_telemetry_counters(rng):
    """A beam solve emits the search.* metric family (docs/telemetry.md)."""
    from da4ml_tpu import telemetry
    from da4ml_tpu.telemetry.metrics import metrics_snapshot

    telemetry.enable()
    try:
        solve_jax_many([random_kernel(rng, 6, 4)], quality='search')
        snap = metrics_snapshot()
    finally:
        telemetry.disable()
        telemetry.reset()
    assert snap.get('search.beam_width', {}).get('value') == 5
    assert 'search.fork_lanes' in snap and snap['search.fork_lanes']['value'] > 0
    assert 'search.frontier_culled' in snap
    # include_host ran: win/tie/rescue counters sum to the matrix count
    total = sum(int(snap.get(k, {}).get('value', 0)) for k in ('search.strict_wins', 'search.ties', 'search.host_rescues'))
    assert total == 1


def test_cli_quality_flag_wiring():
    """convert --quality is exposed and defaults to the byte-identical path."""
    import argparse

    from da4ml_tpu._cli.convert import add_convert_args

    parser = argparse.ArgumentParser()
    add_convert_args(parser)
    args = parser.parse_args(['model.json', 'out'])
    assert args.quality == 'fast'
    args = parser.parse_args(['model.json', 'out', '--quality', 'search'])
    assert args.quality == 'search'
