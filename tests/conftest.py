"""Test fixtures.

JAX runs on a virtual 8-device CPU mesh so sharding paths are exercised
without TPU hardware (set before any jax import).
"""

import os

# The axon TPU plugin force-registers itself at interpreter start (overriding
# the JAX_PLATFORMS env var); override via jax.config so tests run on the
# virtual CPU mesh instead of contending for the real chip.
os.environ['JAX_PLATFORMS'] = 'cpu'
# single-threaded native runtime for deterministic tests regardless of the
# invoking environment (the reference pins this in pyproject; CI also sets it)
os.environ.setdefault('DA_DEFAULT_THREADS', '1')
flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (flags + ' --xla_force_host_platform_device_count=8').strip()

import jax

jax.config.update('jax_platforms', 'cpu')

# Share compiled XLA programs across test processes: test data is seeded, so
# program shapes repeat run-to-run and the suite is compile-dominated on
# small boxes. First run populates the cache; later runs (local re-runs, CI
# with a cached dir) skip the compiles. Point DA4ML_TEST_JAX_CACHE elsewhere
# or at '' to disable.
import getpass

_cache_dir = os.environ.get('DA4ML_TEST_JAX_CACHE', f'/tmp/da4ml_test_jax_cache_{getpass.getuser()}')
if _cache_dir:
    jax.config.update('jax_compilation_cache_dir', _cache_dir)
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.5)
    jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)
    # child processes (example scripts, CLI converts, the two-process
    # distributed test) inherit the same cache — thresholds included, or
    # their sub-second compiles would never persist
    os.environ.setdefault('JAX_COMPILATION_CACHE_DIR', _cache_dir)
    os.environ.setdefault('JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS', '0.5')
    os.environ.setdefault('JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES', '0')

import numpy as np
import pytest


@pytest.fixture
def rng() -> np.random.Generator:
    return np.random.default_rng(42)
