"""Replica-fleet suite (docs/serving.md#replica-fleets).

Fast, CPU-only, no subprocess spawning: the router is exercised against
in-process fake replicas (stdlib HTTP servers with scripted latency,
status, and health), the registry against real lease files, and the
tiered cache against real store directories.

- hedged dispatch: the hedge leg wins, the straggler is cancelled, and
  ``router.samples`` tallies the client request exactly once no matter
  how many legs raced;
- retry rotation on retryable statuses (503) vs. 504 staying definitive;
- an explicitly ``draining`` replica is unroutable without a breaker
  penalty; a fleet of only draining replicas raises
  :class:`NoReplicaAvailable`;
- the router's request-body ceiling (``DA4ML_SERVE_MAX_BODY_BYTES``)
  rejects with 413 before buffering or forwarding;
- registry: duplicate announcements refused while the holder is live,
  ``close()`` withdraws, and an expired slot is stolen by exactly one of
  N racing successors;
- tiered cache: publish-writethrough and shared→local promotion are
  byte-identical, repeats hit mem, the LRU bound evicts, and
  ``DA4ML_STORE_LOCAL_TIER`` upgrades ``resolve_store`` for explicit
  store paths (what fleet replicas pass via ``--solve-store``);
- ``retry_call`` honors a server-supplied ``retry_after_s`` hint (capped,
  jittered upward only) instead of the exponential guess.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from da4ml_tpu import telemetry
from da4ml_tpu.cmvm.api import solve
from da4ml_tpu.reliability.breaker import reset_all_breakers
from da4ml_tpu.reliability.retry import retry_call
from da4ml_tpu.serve.batching import QueueFull
from da4ml_tpu.serve.fleet import Fleet, announce_replica, discover_replicas
from da4ml_tpu.serve.router import NoReplicaAvailable, Router, RouterServer
from da4ml_tpu.store import SolutionStore, reset_store_registry, resolve_store, store_key
from da4ml_tpu.store.tiered import TieredStore

BACKEND = 'pure-python'


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    from da4ml_tpu.telemetry.metrics import enable_metrics, reset_metrics

    monkeypatch.delenv('DA4ML_SOLUTION_STORE', raising=False)
    monkeypatch.delenv('DA4ML_STORE_LOCAL_TIER', raising=False)
    enable_metrics()
    reset_metrics()
    reset_all_breakers()
    reset_store_registry()
    yield
    reset_all_breakers()
    reset_store_registry()


def _counter(name: str) -> float:
    m = telemetry.metrics_snapshot().get(name)
    return float(m.get('value', 0.0)) if m else 0.0


def _kernel(seed=0, dim=4, bits=3):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 2**bits, (dim, dim)) * rng.choice([-1.0, 1.0], (dim, dim))).astype(np.float64)


def _blob(pipe) -> str:
    return json.dumps(pipe.to_dict(), sort_keys=True)


# ----------------------------------------------------------- fake replicas


class _FakeReplica:
    """A scripted stand-in for one ``da4ml-tpu serve`` process: answers
    ``/healthz`` with a configurable status and ``/v1/infer`` with a
    configurable delay + HTTP status, counting every infer it serves."""

    def __init__(self, *, delay_s: float = 0.0, status: int = 200, health: str = 'ok'):
        self.delay_s = delay_s
        self.status = status
        self.health = health
        self.infers = 0
        self.traceparents: list[str | None] = []
        self.metrics_text = (
            '# HELP da4ml_serve_requests total\n# TYPE da4ml_serve_requests counter\n'
            'da4ml_serve_requests_total 7 # {trace_id="feedface"} 1 1700000000.0\n# EOF\n'
        )
        self._lock = threading.Lock()
        fake = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = 'HTTP/1.1'

            def log_message(self, fmt, *args):
                pass

            def _send(self, code: int, doc: dict):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split('?', 1)[0]
                if path == '/healthz':
                    self._send(200, {'status': fake.health})
                elif path == '/metrics':
                    body = fake.metrics_text.encode()
                    self.send_response(200)
                    self.send_header('Content-Type', 'application/openmetrics-text; version=1.0.0; charset=utf-8')
                    self.send_header('Content-Length', str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._send(404, {'error': 'not found'})

            def do_POST(self):
                length = int(self.headers.get('Content-Length', '0') or 0)
                self.rfile.read(length)
                with fake._lock:
                    fake.infers += 1
                    fake.traceparents.append(self.headers.get('traceparent'))
                if fake.delay_s:
                    time.sleep(fake.delay_s)
                if fake.status == 200:
                    self._send(200, {'model': 'default', 'outputs': [[1.0]], 'served_by': 'fake'})
                else:
                    self._send(fake.status, {'error': {'type': 'Scripted', 'http_status': fake.status}})

        class _Server(ThreadingHTTPServer):
            daemon_threads = True

        self._httpd = _Server(('127.0.0.1', 0), _Handler)
        self.url = f'http://127.0.0.1:{self._httpd.server_address[1]}'
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()

    def close(self):
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass


def _post(url: str, doc: dict, timeout: float = 10.0):
    req = urllib.request.Request(
        url + '/v1/infer',
        data=json.dumps(doc).encode(),
        headers={'Content-Type': 'application/json'},
        method='POST',
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


# ------------------------------------------------------------------ router


def test_hedge_wins_cancels_straggler_and_tallies_once():
    slow = _FakeReplica(delay_s=0.6)
    fast = _FakeReplica(delay_s=0.0)
    router = Router(replicas={'slow': slow.url, 'fast': fast.url}, hedge_ms=30.0, default_deadline_ms=5000.0)
    server = RouterServer(router)
    try:
        # steer the first pick to the straggler: fresh replicas tie at the
        # ewma floor, so a raised ewma on `fast` demotes it for leg one
        router._replicas['fast'].ewma_s = 0.05
        before = {k: _counter(k) for k in ('router.requests', 'router.samples', 'router.hedges_fired', 'router.hedges_won', 'router.hedge_cancelled')}
        status, doc, headers = _post(server.url, {'model': 'default', 'inputs': [[0.0]] * 3, 'deadline_ms': 5000})
        assert status == 200 and doc['outputs'] == [[1.0]]
        assert headers.get('X-DA4ML-Replica') == 'fast'  # the hedge won
        assert _counter('router.hedges_fired') - before['router.hedges_fired'] >= 1
        assert _counter('router.hedges_won') - before['router.hedges_won'] >= 1
        assert _counter('router.hedge_cancelled') - before['router.hedge_cancelled'] >= 1
        # one client request = one tally, even though two legs raced
        assert _counter('router.requests') - before['router.requests'] == 1
        assert _counter('router.samples') - before['router.samples'] == 3
    finally:
        server.close()
        slow.close()
        fast.close()


def test_hedged_request_logs_one_access_record_and_cancelled_leg_span(tmp_path):
    """One ``request.access`` record per *client* request however many legs
    raced; the losing leg appears as a cancelled ``router.leg`` child span;
    both replicas saw the same forwarded trace id with distinct leg span ids."""
    trace = tmp_path / 'router.jsonl'
    telemetry.enable(trace)
    slow = _FakeReplica(delay_s=0.6)
    fast = _FakeReplica(delay_s=0.0)
    # max_attempts=2 pins the leg count: under a loaded machine the default
    # third hedge timer can expire before the winner's answer lands
    router = Router(
        replicas={'slow': slow.url, 'fast': fast.url}, hedge_ms=30.0, max_attempts=2, default_deadline_ms=5000.0
    )
    server = RouterServer(router)
    client_tid = telemetry.new_trace_id()
    try:
        router._replicas['fast'].ewma_s = 0.05  # steer leg one to the straggler
        req = urllib.request.Request(
            server.url + '/v1/infer',
            data=json.dumps({'model': 'default', 'inputs': [[0.0]] * 2, 'deadline_ms': 5000}).encode(),
            headers={'Content-Type': 'application/json', 'traceparent': f'00-{client_tid}-00000000000000aa-01'},
            method='POST',
        )
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            assert resp.status == 200

        # the cancelled leg emits its span when its socket unblocks (the
        # straggler answers ~0.6s in) — wait for both leg records to land;
        # key on OUR trace id: a straggler leg from an earlier test can land
        # in this sink too (emission checks tracing_active at unblock time)
        def _my_legs():
            events = [json.loads(ln) for ln in trace.read_text().splitlines()]
            return [
                e for e in events if e.get('name') == 'router.leg' and e['args'].get('trace_id') == client_tid
            ]

        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and len(_my_legs()) < 2:
            time.sleep(0.05)
    finally:
        server.close()
        slow.close()
        fast.close()
        telemetry.disable()
    events = [json.loads(ln) for ln in trace.read_text().splitlines()]
    access = [e for e in events if e.get('name') == 'request.access']
    assert len(access) == 1, 'exactly one access-log record per client request'
    assert access[0]['args']['status'] == 200 and access[0]['args']['route'] == '/v1/infer'
    assert access[0]['args']['trace_id'] == client_tid
    legs = [e for e in events if e.get('name') == 'router.leg' and e['args'].get('trace_id') == client_tid]
    assert len(legs) == 2
    cancelled = [e for e in legs if e['args'].get('cancelled')]
    winners = [e for e in legs if not e['args'].get('cancelled')]
    assert len(cancelled) == 1 and cancelled[0]['args']['replica'] == 'slow'
    assert len(winners) == 1 and winners[0]['args']['replica'] == 'fast'
    # both legs hang off the same router.request span
    parents = {e['args'].get('parent_id') for e in legs}
    assert len(parents) == 1
    # ...and forwarded the adopted trace id with distinct per-leg span ids
    seen = [telemetry.parse_traceparent(tp) for tp in slow.traceparents + fast.traceparents if tp]
    assert len(seen) == 2
    assert {p[0] for p in seen} == {client_tid}
    assert seen[0][1] != seen[1][1]


def test_metrics_fleet_federates_replica_scrapes():
    """``GET /metrics/fleet`` aggregates every replica's ``/metrics`` plus
    the router's own registry into one valid OpenMetrics document with
    ``replica=``-labeled samples and exemplars passed through intact."""
    from da4ml_tpu.telemetry.obs import validate_openmetrics

    r0 = _FakeReplica()
    r1 = _FakeReplica()
    router = Router(replicas={'r0': r0.url, 'r1': r1.url}, default_deadline_ms=5000.0)
    server = RouterServer(router)
    try:
        with urllib.request.urlopen(server.url + '/metrics/fleet', timeout=10.0) as resp:
            assert resp.status == 200
            assert 'openmetrics' in resp.headers['Content-Type']
            fed = resp.read().decode()
    finally:
        server.close()
        r0.close()
        r1.close()
    validate_openmetrics(fed)
    assert fed.count('da4ml_serve_requests_total{replica=') == 2
    for rid in ('r0', 'r1', 'router'):
        assert f'replica="{rid}"' in fed
    # exemplars survive federation (one per scraped replica)
    assert fed.count('# {trace_id="feedface"}') == 2
    # the scrape is itself metered, and those families ride the same doc
    assert _counter('router.scrape.errors') == 0
    assert telemetry.metrics_snapshot()['router.scrape.replicas']['value'] == 2
    assert 'da4ml_router_scrape_replicas{replica="router"} 2' in fed


def test_retryable_status_rotates_to_next_replica():
    bad = _FakeReplica(status=503)
    good = _FakeReplica(status=200)
    router = Router(replicas={'bad': bad.url, 'good': good.url}, hedge_ms=500.0, default_deadline_ms=5000.0)
    server = RouterServer(router)
    try:
        router._replicas['good'].ewma_s = 0.05  # bad goes first
        before_retries = _counter('router.retries')
        status, doc, headers = _post(server.url, {'model': 'default', 'inputs': [[0.0]], 'deadline_ms': 5000})
        assert status == 200
        assert headers.get('X-DA4ML-Replica') == 'good'
        assert bad.infers >= 1  # the 503 really was attempted first
        assert _counter('router.retries') - before_retries >= 1
        assert _counter('router.leg_failures') >= 1
    finally:
        server.close()
        bad.close()
        good.close()


def test_504_is_definitive_no_rotation():
    expired = _FakeReplica(status=504)
    spare = _FakeReplica(status=200)
    router = Router(replicas={'expired': expired.url, 'spare': spare.url}, hedge_ms=500.0, default_deadline_ms=5000.0)
    server = RouterServer(router)
    try:
        router._replicas['spare'].ewma_s = 0.05
        before = _counter('router.retries')
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.url, {'model': 'default', 'inputs': [[0.0]], 'deadline_ms': 5000})
        assert ei.value.code == 504  # the deadline is the client's budget
        assert spare.infers == 0
        assert _counter('router.retries') == before
    finally:
        server.close()
        expired.close()
        spare.close()


def test_draining_replica_is_unroutable_without_breaker_penalty():
    draining = _FakeReplica(health='draining')
    healthy = _FakeReplica(health='ok')
    router = Router(replicas={'drn': draining.url, 'ok': healthy.url}, hedge_ms=500.0, default_deadline_ms=5000.0)
    try:
        router.refresh()
        snap = {r['replica_id']: r for r in router.replicas()}
        assert snap['drn']['probe_status'] == 'draining' and not snap['drn']['routable']
        assert snap['drn']['breaker'] == 'closed'  # shutting down cleanly, not failing
        assert snap['ok']['routable']
        status, body, headers = router.forward('POST', '/v1/infer', b'{"inputs": [[0.0]]}', 5.0)
        assert status == 200 and headers['X-DA4ML-Replica'] == 'ok'
        assert draining.infers == 0
    finally:
        router.close()
        draining.close()
        healthy.close()


def test_all_draining_raises_no_replica():
    draining = _FakeReplica(health='draining')
    router = Router(replicas={'drn': draining.url}, default_deadline_ms=1000.0)
    try:
        router.refresh()
        before = _counter('router.no_replica')
        with pytest.raises(NoReplicaAvailable) as ei:
            router.forward('POST', '/v1/infer', b'{}', 1.0)
        assert ei.value.http_status == 503 and ei.value.retry_after_s is not None
        assert _counter('router.no_replica') - before == 1
    finally:
        router.close()
        draining.close()


def test_router_rejects_oversized_body_before_forwarding(monkeypatch):
    monkeypatch.setenv('DA4ML_SERVE_MAX_BODY_BYTES', '1024')
    replica = _FakeReplica()
    router = Router(replicas={'r': replica.url}, default_deadline_ms=5000.0)
    server = RouterServer(router)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.url, {'model': 'default', 'inputs': [[0.0] * 600]})
        assert ei.value.code == 413
        doc = json.loads(ei.value.read())
        assert doc['error']['type'] == 'PayloadTooLarge'
        assert replica.infers == 0  # rejected before any leg fired
    finally:
        server.close()
        replica.close()


# ---------------------------------------------------------------- registry


def test_announce_refuses_live_duplicate_and_close_withdraws(tmp_path):
    reg = tmp_path / 'registry'
    a = announce_replica(reg, 'r0', 'http://127.0.0.1:1/', ttl_s=5.0)
    assert a is not None and a.live
    assert announce_replica(reg, 'r0', 'http://127.0.0.1:2/', ttl_s=5.0) is None  # slot held
    live = discover_replicas(reg)
    assert [d['replica_id'] for d in live] == ['r0']
    assert live[0]['url'] == 'http://127.0.0.1:1/'
    a.close()
    assert discover_replicas(reg) == []  # withdrawn, not just expired
    b = announce_replica(reg, 'r0', 'http://127.0.0.1:3/', ttl_s=5.0)
    assert b is not None
    b.close()


def test_expired_slot_stolen_by_exactly_one_successor(tmp_path):
    reg = tmp_path / 'registry'
    a = announce_replica(reg, 'r0', 'http://127.0.0.1:1/', ttl_s=0.5)
    assert a is not None
    # simulate SIGKILL: renewal stops without withdrawing the lease
    a._stop.set()
    a._thread.join(timeout=2.0)
    expires_at = float(a.lease.expires_at)
    time.sleep(max(expires_at + 1.0 + 0.4 - time.time(), 0.0))  # ttl + steal grace

    winners: list = []
    barrier = threading.Barrier(6)

    def race(i):
        barrier.wait()
        got = announce_replica(reg, 'r0', f'http://127.0.0.1:{10 + i}/', ttl_s=5.0)
        if got is not None:
            winners.append(got)

    threads = [threading.Thread(target=race, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10.0)
    assert len(winners) == 1  # single-winner steal, however many restarts race
    assert len(discover_replicas(reg)) == 1
    winners[0].close()


def test_fleet_gives_each_replica_its_own_local_tier(tmp_path):
    fleet = Fleet(tmp_path / 'artifact.json', replicas=2, fleet_dir=tmp_path / 'fleet', shared_store=tmp_path / 'store')
    try:
        envs = [fleet._env_for(s) for s in fleet._slots]
        assert all(e['DA4ML_SOLUTION_STORE'] == str(tmp_path / 'store') for e in envs)
        tiers = [e['DA4ML_STORE_LOCAL_TIER'] for e in envs]
        assert tiers[0].endswith('local/r0') and tiers[1].endswith('local/r1')
        assert len(set(tiers)) == 2  # local tiers are per-replica, never shared
    finally:
        fleet._stop.set()


# ------------------------------------------------------------ tiered cache


def test_tiered_publish_and_promotion_are_byte_identical(tmp_path):
    shared = tmp_path / 'shared'
    warm = TieredStore(shared, tmp_path / 'local-warm')
    k = _kernel(3)
    key = store_key(k, BACKEND)
    pipe = solve(k, backend=BACKEND, store=False)
    assert warm.publish(key, pipe)
    raw = warm._entry_path(key).read_bytes()
    assert warm.local._entry_path(key).read_bytes() == raw  # write-through copy
    assert _counter('store.tier.writethroughs') == 1

    # a cold replica (empty mem + local) warms from the shared tier
    cold = TieredStore(shared, tmp_path / 'local-cold')
    before = {k2: _counter(k2) for k2 in ('store.tier.shared_hits', 'store.tier.mem_hits', 'store.tier.promotes_local')}
    hit = cold.lookup(key)
    assert hit is not None and _blob(hit.pipeline) == _blob(pipe)
    assert _counter('store.tier.shared_hits') - before['store.tier.shared_hits'] == 1
    assert _counter('store.tier.promotes_local') - before['store.tier.promotes_local'] == 1
    assert cold.local._entry_path(key).read_bytes() == raw  # promotion is a raw copy

    # the repeat is answered from mem — no tier below is touched again
    again = cold.lookup(key)
    assert again is not None and _blob(again.pipeline) == _blob(pipe)
    assert _counter('store.tier.mem_hits') - before['store.tier.mem_hits'] == 1
    assert _counter('store.tier.shared_hits') - before['store.tier.shared_hits'] == 1


def test_tiered_mem_lru_evicts_and_falls_back_to_local(tmp_path):
    store = TieredStore(tmp_path / 'shared', tmp_path / 'local', mem_entries=1)
    keys = []
    for seed in (1, 2):
        k = _kernel(seed)
        keys.append(store_key(k, BACKEND))
        assert store.publish(keys[-1], solve(k, backend=BACKEND, store=False))
    assert _counter('store.tier.mem_evictions') >= 1
    assert store.tier_occupancy()['mem'] == {'entries': 1, 'cap': 1}
    before_local = _counter('store.tier.local_hits')
    assert store.lookup(keys[0]) is not None  # evicted from mem, still local
    assert _counter('store.tier.local_hits') - before_local == 1


def test_resolve_store_env_upgrades_explicit_paths(tmp_path, monkeypatch):
    plain = resolve_store(tmp_path / 'shared')
    assert isinstance(plain, SolutionStore) and not isinstance(plain, TieredStore)
    reset_store_registry()
    # the fleet wiring: replicas get --solve-store <shared> on the command
    # line plus DA4ML_STORE_LOCAL_TIER in the environment — the explicit
    # path must still read through the local tier
    monkeypatch.setenv('DA4ML_STORE_LOCAL_TIER', str(tmp_path / 'local'))
    tiered = resolve_store(tmp_path / 'shared')
    assert isinstance(tiered, TieredStore)
    assert tiered.local is not None and str(tiered.local.root).endswith('local')


# ------------------------------------------------------------- retry hints


def test_retry_call_honors_server_hint_capped_and_upward_jittered():
    delays: list[float] = []
    calls: list[int] = []

    def flaky():
        if not calls:
            calls.append(1)
            raise QueueFull('shed', retry_after_s=0.2)
        return 'served'

    before = _counter('retry.hints_honored')
    out = retry_call(flaky, retries=3, base_delay=10.0, max_delay=5.0, retry_on=lambda e: True, sleep=delays.append)
    assert out == 'served'
    assert len(delays) == 1
    # the hint replaces the exponential guess (base_delay=10 would have
    # slept seconds) and jitters upward only, never below the hint
    assert 0.2 <= delays[0] <= 0.2 * 1.25 + 1e-9
    assert _counter('retry.hints_honored') - before == 1

    def always_hinting():
        raise QueueFull('shed', retry_after_s=30.0)

    delays.clear()
    with pytest.raises(QueueFull):
        retry_call(always_hinting, retries=2, base_delay=0.01, max_delay=0.5, retry_on=lambda e: True, sleep=delays.append)
    assert delays and all(d <= 0.5 + 1e-9 for d in delays)  # hint capped at max_delay
