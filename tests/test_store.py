"""Global solution store suite (docs/store.md).

Fast, CPU-only (``pure-python`` backend throughout, so solves are
deterministic without device warmup): key canonicalization, cold→warm
byte-identity in- and cross-process, verify-on-read quarantine under three
corruption shapes, thundering-herd single-flight, winner-death recovery,
negative-cache TTL, read-only/unreachable degradation behind the breaker
pair, lease-guarded gc under a concurrent reader, the ``/v1/solve``
service + HTTP plane, the campaign publish hook, and the cache CLI.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from da4ml_tpu import telemetry
from da4ml_tpu.cmvm.api import solve
from da4ml_tpu.reliability.breaker import reset_all_breakers
from da4ml_tpu.reliability.errors import BackendUnavailable, SolveTimeout
from da4ml_tpu.reliability.faults import fault_injection
from da4ml_tpu.reliability.lease import claim_lease
from da4ml_tpu.store import (
    SolutionStore,
    SolveService,
    StoreNegativeEntry,
    canonical_solve_opts,
    reset_store_registry,
    resolve_store,
    store_at,
    store_key,
)

REPO_ROOT = Path(__file__).resolve().parents[1]
BACKEND = 'pure-python'


def _kernel(seed=0, dim=5, bits=3):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 2**bits, (dim, dim)) * rng.choice([-1.0, 1.0], (dim, dim))).astype(np.float64)


def _blob(pipe) -> str:
    return json.dumps(pipe.to_dict(), sort_keys=True)


@pytest.fixture(autouse=True)
def _isolated():
    from da4ml_tpu.telemetry.metrics import enable_metrics, reset_metrics

    enable_metrics()
    reset_metrics()
    reset_all_breakers()
    reset_store_registry()
    yield
    reset_all_breakers()
    reset_store_registry()


def _counter(name: str) -> float:
    m = telemetry.metrics_snapshot().get(name)
    return float(m.get('value', 0.0)) if m else 0.0


# ------------------------------------------------------------------- keys


def test_store_key_full_digest_and_canonicalization():
    k = _kernel()
    key = store_key(k, BACKEND)
    assert len(key) == 64  # full sha256, no truncation
    # sparse options (campaign manifests) and explicit signature defaults
    # (api calls) must agree on the key
    assert store_key(k, BACKEND, {}) == store_key(k, BACKEND, {'method0': 'wmc', 'n_restarts': 1, 'quality': 'fast'})
    # but an option that shapes the solution changes it
    assert store_key(k, BACKEND, {'n_restarts': 3}) != key
    # determinism is per backend: same kernel, different backend → different key
    assert store_key(k, 'jax') != key


def test_canonical_solve_opts_quality_roundtrip():
    a = canonical_solve_opts({'quality': 'search'})
    b = canonical_solve_opts({'quality': a['quality']})  # dict form round-trips
    assert a == b
    assert 'quality' not in canonical_solve_opts({'quality': 'fast'})  # fast drops out


# ------------------------------------------------------- cold→warm identity


def test_cold_warm_byte_identity(tmp_path):
    k = _kernel(1)
    ref = solve(k, backend=BACKEND, store=False)
    cold = solve(k, backend=BACKEND, store=tmp_path)
    warm = solve(k, backend=BACKEND, store=tmp_path)
    assert _blob(ref) == _blob(cold) == _blob(warm)
    assert _counter('store.misses') == 1 and _counter('store.hits') == 1
    assert store_at(tmp_path).occupancy()['entries'] == 1


def test_warm_hit_across_processes(tmp_path):
    k = _kernel(2)
    ref = solve(k, backend=BACKEND, store=tmp_path)  # publishes
    # a separate process must hit without ever running a search: its cold
    # path raises, so returning at all proves the store answered
    code = (
        'import json, numpy as np\n'
        'from da4ml_tpu.store import store_at, store_key\n'
        f'k = np.asarray({k.tolist()!r}, dtype=np.float64)\n'
        f'store = store_at({str(tmp_path)!r})\n'
        'def cold():\n'
        '    raise AssertionError("cross-process warm hit ran a search")\n'
        f'pipe = store.solve_through(store_key(k, {BACKEND!r}), cold)\n'
        'print(json.dumps(pipe.to_dict(), sort_keys=True))\n'
    )
    env = dict(os.environ, JAX_PLATFORMS='cpu', PYTHONPATH=str(REPO_ROOT))
    out = subprocess.run([sys.executable, '-c', code], env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-500:]
    assert out.stdout.strip().splitlines()[-1] == _blob(ref)


def test_env_var_wires_solve_through_the_store(tmp_path, monkeypatch):
    monkeypatch.setenv('DA4ML_SOLUTION_STORE', str(tmp_path))
    k = _kernel(3)
    ref = solve(k, backend=BACKEND, store=False)  # store=False escapes even with env set
    assert store_at(tmp_path).occupancy()['entries'] == 0
    warm_path = solve(k, backend=BACKEND)
    assert store_at(tmp_path).occupancy()['entries'] == 1
    assert _blob(warm_path) == _blob(ref)


# ------------------------------------------------------- verify-on-read


def test_truncated_entry_quarantined_and_resolved(tmp_path):
    k = _kernel(4)
    ref = solve(k, backend=BACKEND, store=tmp_path)
    store = store_at(tmp_path)
    key = store_key(k, BACKEND)
    path = store._entry_path(key)
    path.write_bytes(path.read_bytes()[:40])  # torn write / bit rot
    again = solve(k, backend=BACKEND, store=tmp_path)  # transparently re-solves
    assert _blob(again) == _blob(ref)
    assert store.occupancy()['corrupt'] == 1
    assert _counter('store.corrupt_quarantined') == 1
    assert json.loads(path.read_bytes())['key'] == key  # republished clean


def test_semantic_bitflip_caught_by_verifier(tmp_path):
    k = _kernel(5)
    ref = solve(k, backend=BACKEND, store=tmp_path)
    store = store_at(tmp_path)
    # store.verify=corrupt mutates the parsed doc in-memory: it parses and
    # schema-checks fine; ONLY the DAIS verifier can reject it
    with fault_injection('store.verify=corrupt:1'):
        again = solve(k, backend=BACKEND, store=tmp_path)
    assert _blob(again) == _blob(ref)
    assert store.occupancy()['corrupt'] == 1


def test_wrong_key_entry_quarantined(tmp_path):
    k, other = _kernel(6), _kernel(7)
    solve(other, backend=BACKEND, store=tmp_path)
    store = store_at(tmp_path)
    key = store_key(k, BACKEND)
    # an entry claiming a different key (misplaced file) must never serve
    src = store._entry_path(store_key(other, BACKEND))
    dst = store._entry_path(key)
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_bytes(src.read_bytes())
    assert store.lookup(key) is None
    assert store.occupancy()['corrupt'] == 1


# ------------------------------------------------------- single-flight


def test_thundering_herd_single_search(tmp_path):
    store = SolutionStore(tmp_path, lease_ttl_s=10.0)
    k = _kernel(8)
    key = store_key(k, BACKEND)
    searches = []
    lock = threading.Lock()

    def cold():
        with lock:
            searches.append(threading.get_ident())
        time.sleep(0.2)  # hold the herd long enough that everyone collides
        return solve(k, backend=BACKEND, store=False)

    results: list = [None] * 6
    barrier = threading.Barrier(6)

    def worker(i):
        barrier.wait()
        results[i] = _blob(store.solve_through(key, cold))

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    [t.start() for t in ts]
    [t.join(timeout=60) for t in ts]
    assert len(searches) == 1  # the herd collapsed to one search
    assert len(set(results)) == 1 and results[0] is not None
    assert _counter('store.singleflight_waits') >= 1


def test_winner_death_recovered_by_steal(tmp_path):
    store = SolutionStore(tmp_path, lease_ttl_s=0.4)
    k = _kernel(9)
    key = store_key(k, BACKEND)
    # a "winner" that died mid-solve: a claimed lease nobody ever renews
    dead = claim_lease(store.leases_dir, key, owner='dead-winner', ttl_s=0.4)
    assert dead is not None
    t0 = time.monotonic()
    pipe = store.solve_through(key, lambda: solve(k, backend=BACKEND, store=False))
    assert _blob(pipe) == _blob(solve(k, backend=BACKEND, store=False))
    assert time.monotonic() - t0 > 0.3  # actually waited for the corpse's ttl
    assert store.occupancy()['entries'] == 1


def test_deadline_fallthrough_solves_locally(tmp_path):
    store = SolutionStore(tmp_path, lease_ttl_s=30.0)
    k = _kernel(10)
    key = store_key(k, BACKEND)
    blocker = claim_lease(store.leases_dir, key, owner='slow-winner', ttl_s=30.0)
    assert blocker is not None
    pipe = store.solve_through(key, lambda: solve(k, backend=BACKEND, store=False), deadline_s=0.5)
    assert pipe is not None
    assert _counter('store.singleflight_fallthroughs') == 1


# ------------------------------------------------------- negative cache


def test_negative_cache_blocks_then_expires(tmp_path):
    store = SolutionStore(tmp_path, negative_ttl_s=0.5)
    key = store_key(_kernel(11), BACKEND)
    calls = []

    def poisoned():
        calls.append(1)
        raise ValueError('kernel is cursed')  # classify → fatal

    with pytest.raises(ValueError):
        store.solve_through(key, poisoned)
    # the failure is negative-cached: no re-search, classified fallback
    with pytest.raises(StoreNegativeEntry) as ei:
        store.solve_through(key, poisoned)
    assert len(calls) == 1 and ei.value.retry_after_s <= 0.5
    assert isinstance(ei.value, BackendUnavailable)
    assert _counter('store.negative_hits') == 1
    time.sleep(0.6)  # marker expires → the key is retryable again
    with pytest.raises(ValueError):
        store.solve_through(key, poisoned)
    assert len(calls) == 2


def test_deadline_timeout_is_not_negative_cached(tmp_path):
    store = SolutionStore(tmp_path)
    key = store_key(_kernel(12), BACKEND)

    def starved():
        raise SolveTimeout('deadline blown')

    with pytest.raises(SolveTimeout):
        store.solve_through(key, starved)
    assert store.occupancy()['negative'] == 0  # a caller with more budget may succeed


# ------------------------------------------------------- degradation


def test_unreachable_store_degrades_to_local_solve(tmp_path):
    k = _kernel(13)
    ref = solve(k, backend=BACKEND, store=False)
    with fault_injection('store.read=unavailable'):
        for _ in range(4):  # breaker opens at 3 failures; solves never fail
            assert _blob(solve(k, backend=BACKEND, store=tmp_path)) == _blob(ref)
    from da4ml_tpu.store import store_health

    health = store_health()
    assert health['status'] == 'degraded' and health['breakers']['store.read'] == 'open'
    # /healthz carries the store check and flips to degraded
    from da4ml_tpu.telemetry.obs.health import health_snapshot, status_snapshot

    doc = health_snapshot()
    assert doc['status'] == 'degraded' and doc['checks']['store']['status'] == 'degraded'
    assert status_snapshot()['store'] is not None
    assert _counter('store.read_errors') >= 3


def test_unwritable_store_serves_hits_but_never_fails(tmp_path):
    k = _kernel(14)
    ref = solve(k, backend=BACKEND, store=tmp_path)  # publish while healthy
    with fault_injection('store.write=error'):
        warm = solve(k, backend=BACKEND, store=tmp_path)  # hit path untouched
        assert _blob(warm) == _blob(ref)
        k2 = _kernel(15)
        cold = solve(k2, backend=BACKEND, store=tmp_path)  # publish fails silently
        assert _blob(cold) == _blob(solve(k2, backend=BACKEND, store=False))
    assert store_at(tmp_path).occupancy()['entries'] == 1
    assert _counter('store.write_errors') >= 1


def test_readonly_store_serves_hits_without_writing(tmp_path):
    k = _kernel(16)
    ref = solve(k, backend=BACKEND, store=tmp_path)
    reset_store_registry()
    ro = SolutionStore(tmp_path, readonly=True)
    hit = ro.lookup(store_key(k, BACKEND))
    assert hit is not None and _blob(hit.pipeline) == _blob(ref)
    k2 = _kernel(17)
    pipe = ro.solve_through(store_key(k2, BACKEND), lambda: solve(k2, backend=BACKEND, store=False))
    assert pipe is not None
    assert ro.occupancy()['entries'] == 1  # nothing new written
    assert not ro.leases_dir.exists() or not list(ro.leases_dir.iterdir())  # no lease litter


def test_degraded_backend_result_not_published(tmp_path, monkeypatch):
    monkeypatch.delenv('DA4ML_SOLVE_FALLBACK', raising=False)
    k = _kernel(18)
    # request native-threads, but it is faulted away: the orchestrator
    # degrades to pure-python — publishing THAT under the native key would
    # silently break per-backend byte-identity
    with fault_injection('cmvm.native=unavailable'):
        pipe = solve(k, backend='native-threads', store=tmp_path)
    assert pipe is not None
    assert store_at(tmp_path).occupancy()['entries'] == 0


# ------------------------------------------------------------------- gc


def test_gc_age_and_size_eviction_with_lease_guard(tmp_path):
    store = SolutionStore(tmp_path)
    kernels = [_kernel(20 + i) for i in range(4)]
    for k in kernels:
        solve(k, backend=BACKEND, store=store)
    keys = [store_key(k, BACKEND) for k in kernels]
    old = time.time() - 3600
    for key in keys[:2]:
        os.utime(store._entry_path(key), (old, old))
    live = claim_lease(store.leases_dir, keys[0], owner='solver', ttl_s=30.0)  # a solver holds key 0
    report = store.gc(max_age_s=600)
    assert report['evicted'] == 1 and report['skipped_live'] == 1  # key 1 evicted, key 0 protected
    assert store._entry_path(keys[0]).exists() and not store._entry_path(keys[1]).exists()
    from da4ml_tpu.reliability.lease import release_lease

    release_lease(live)
    # size-based LRU: shrink to one entry's worth of bytes
    sizes = [store._entry_path(k).stat().st_size for k in (keys[0], keys[2], keys[3])]
    report = store.gc(max_bytes=max(sizes) + 1)
    assert store.occupancy()['entries'] == 1
    assert _counter('store.gc_evictions') >= 2


def test_gc_under_concurrent_reader(tmp_path):
    store = SolutionStore(tmp_path)
    kernels = [_kernel(30 + i) for i in range(3)]
    refs = {store_key(k, BACKEND): _blob(solve(k, backend=BACKEND, store=store)) for k in kernels}
    stop = threading.Event()
    errors: list = []

    def reader():
        while not stop.is_set():
            for key, ref in refs.items():
                try:
                    hit = store.lookup(key)
                    if hit is not None and _blob(hit.pipeline) != ref:
                        errors.append(f'wrong bytes for {key[:8]}')
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))

    t = threading.Thread(target=reader)
    t.start()
    try:
        for _ in range(5):
            store.gc(max_bytes=0)  # evict everything not actively leased
            for k in kernels:  # re-publish so the reader has something to hit
                store.publish(store_key(k, BACKEND), solve(k, backend=BACKEND, store=False))
    finally:
        stop.set()
        t.join(timeout=30)
    assert errors == []  # a gc'd entry is a miss, never an exception or wrong bytes


# ------------------------------------------------------------ campaign


def test_campaign_publishes_into_store(tmp_path):
    from da4ml_tpu.parallel.campaign import run_campaign

    kernels = [_kernel(40 + i) for i in range(3)]
    store_dir = tmp_path / 'store'
    results, _ = run_campaign(kernels, workers=1, campaign_dir=tmp_path / 'camp', backend=BACKEND, store=store_dir)
    assert store_at(store_dir).occupancy()['entries'] == 3
    # the published entries answer future solve() calls byte-identically
    warm = solve(kernels[0], backend=BACKEND, store=store_dir)
    assert _blob(warm) == json.dumps(results[0]['pipeline'], sort_keys=True)
    assert _counter('store.hits') >= 1


# ------------------------------------------------------------- service


def test_solve_service_hit_miss_and_identity(tmp_path):
    k = _kernel(50)
    ref = solve(k, backend=BACKEND, store=False)
    svc = SolveService(store=tmp_path, backend=BACKEND, workers=2, default_deadline_s=60.0)
    try:
        r1 = svc.submit(k).result(timeout=60)
        r2 = svc.submit(k).result(timeout=60)
    finally:
        svc.close()
    assert r1['source'] == 'solve' and r2['source'] == 'store'
    assert json.dumps(r1['pipeline'], sort_keys=True) == json.dumps(r2['pipeline'], sort_keys=True) == _blob(ref)
    assert r1['key'] == r2['key'] == store_key(k, BACKEND)
    assert _counter('serve.solve_hits') == 1 and _counter('serve.solve_misses') == 1


def test_solve_service_validates_and_sheds(tmp_path):
    from da4ml_tpu.reliability.errors import InvalidInputError
    from da4ml_tpu.serve.batching import DeadlineExpired, QueueFull

    svc = SolveService(store=tmp_path, backend=BACKEND, workers=1, queue_cap_rows=16)
    try:
        with pytest.raises(InvalidInputError):
            svc.submit([[1.0, float('nan')]])
        with pytest.raises(InvalidInputError):
            svc.submit(np.zeros((0, 4)))
        with pytest.raises(QueueFull) as ei:
            svc.submit(np.ones((17, 4)))  # larger than the whole queue → 429
        assert ei.value.http_status == 429
        assert _counter('serve.solve_shed') >= 1
        # a request whose deadline passes before dispatch → 504: park the
        # single worker on a fault-slowed solve, then queue a request whose
        # deadline cannot survive the wait
        with fault_injection('cmvm.solve=sleep:1:1'):
            first = svc.submit(_kernel(51), deadline_s=60.0)
            time.sleep(0.1)  # the worker has taken `first` and is parked
            doomed = svc.submit(_kernel(52), deadline_s=0.05)
            first.result(timeout=60)
            with pytest.raises(DeadlineExpired):
                doomed.result(timeout=60)
        assert _counter('serve.solve_expired') >= 1
    finally:
        svc.close()


def test_negative_cached_key_maps_to_503(tmp_path):
    store = SolutionStore(tmp_path)
    k = _kernel(53)
    store.publish_negative(store_key(k, BACKEND), 'solver exploded', ttl_s=60.0)
    svc = SolveService(store=store, backend=BACKEND, workers=1)
    try:
        from da4ml_tpu.store.service import SolveUnavailable

        with pytest.raises(SolveUnavailable) as ei:
            svc.submit(k).result(timeout=60)
        assert ei.value.http_status == 503 and ei.value.retry_after_s > 0
    finally:
        svc.close()


# ---------------------------------------------------------------- HTTP


def _post(url, doc, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(), headers={'Content-Type': 'application/json'}, method='POST'
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_v1_solve_over_http(tmp_path):
    from da4ml_tpu.serve.engine import ServeConfig, ServeEngine
    from da4ml_tpu.serve.http import ServeServer

    k = _kernel(54)
    ref = solve(k, backend=BACKEND, store=False)
    engine = ServeEngine(ServeConfig(prewarm=False))
    svc = SolveService(store=tmp_path, backend=BACKEND, workers=1, default_deadline_s=60.0)
    server = ServeServer(engine, solve_service=svc)
    try:
        code, doc = _post(f'{server.url}/v1/solve', {'kernel': k.tolist()})
        assert code == 200 and doc['source'] == 'solve'
        assert json.dumps(doc['pipeline'], sort_keys=True) == _blob(ref)
        code, doc = _post(f'{server.url}/v1/solve', {'kernel': k.tolist()})
        assert code == 200 and doc['source'] == 'store'
        # pipeline=false trims the payload to provenance only
        code, doc = _post(f'{server.url}/v1/solve', {'kernel': k.tolist(), 'pipeline': False})
        assert code == 200 and 'pipeline' not in doc and doc['source'] == 'store'
        # taxonomy over the wire: bad kernel → 400 with a structured doc
        code, doc = _post(f'{server.url}/v1/solve', {'kernel': [[1.0, None]]})
        assert code == 400 and doc['error']['type'] == 'InvalidInputError'
        code, doc = _post(f'{server.url}/v1/solve', {})
        assert code == 400
        # oversize kernel → 429 + Retry-After semantics via QueueFull
        code, doc = _post(f'{server.url}/v1/solve', {'kernel': np.ones((512, 4)).tolist()})
        assert code == 429
        # root endpoint advertises the solve plane
        with urllib.request.urlopen(f'{server.url}/', timeout=10) as resp:
            assert '/v1/solve' in resp.read().decode()
    finally:
        server.close()
        svc.close()
        engine.close()


def test_v1_solve_404_without_service():
    from da4ml_tpu.serve.engine import ServeConfig, ServeEngine
    from da4ml_tpu.serve.http import ServeServer

    engine = ServeEngine(ServeConfig(prewarm=False))
    server = ServeServer(engine)
    try:
        code, doc = _post(f'{server.url}/v1/solve', {'kernel': [[1.0]]})
        assert code == 404
    finally:
        server.close()
        engine.close()


# ----------------------------------------------------------------- CLI


def test_cache_cli_stats_verify_gc(tmp_path, capsys):
    from da4ml_tpu._cli import main

    k = _kernel(55)
    solve(k, backend=BACKEND, store=tmp_path)
    assert main(['cache', 'stats', '--store', str(tmp_path)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc['entries'] == 1 and doc['breakers']['store.read'] == 'closed'

    assert main(['cache', 'verify', '--store', str(tmp_path)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc == {'checked': 1, 'ok': 1, 'quarantined': 0}

    # corrupt the entry: verify exits 1 and quarantines it
    path = store_at(tmp_path)._entry_path(store_key(k, BACKEND))
    path.write_bytes(b'{"garbage"')
    assert main(['cache', 'verify', '--store', str(tmp_path)]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc['quarantined'] == 1

    solve(k, backend=BACKEND, store=tmp_path)  # repopulate
    assert main(['cache', 'gc', '--store', str(tmp_path), '--max-bytes', '0']) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc['evicted'] == 1
    assert store_at(tmp_path).occupancy()['entries'] == 0


def test_cache_cli_size_and_age_parsers():
    from da4ml_tpu._cli.cache import parse_age, parse_size

    assert parse_size('512M') == 512 << 20
    assert parse_size('2G') == 2 << 30
    assert parse_size('1024') == 1024
    assert parse_age('7d') == 7 * 86400.0
    assert parse_age('90') == 90.0
    with pytest.raises(Exception):
        parse_size('many')


# ------------------------------------------------------------- resolve


def test_resolve_store_semantics(tmp_path, monkeypatch):
    assert resolve_store(False) is None
    monkeypatch.delenv('DA4ML_SOLUTION_STORE', raising=False)
    assert resolve_store(None) is None
    monkeypatch.setenv('DA4ML_SOLUTION_STORE', str(tmp_path))
    assert resolve_store(None) is not None
    assert resolve_store(False) is None  # False beats the env var
    st = SolutionStore(tmp_path)
    assert resolve_store(st) is st
    assert resolve_store(tmp_path) is resolve_store(str(tmp_path))  # registry-cached
