"""Solver unit tests, mirroring reference tests/test_cmvm.py:
CSD reconstruction identity, kernel_decompose product identity, and the full
solve oracle ``Pipeline.kernel == kernel`` over the method/dc config matrix.
"""

import numpy as np
import pytest

from da4ml_tpu.cmvm import csd_decompose, kernel_decompose, solve


def random_kernel(rng: np.random.Generator, n_dim: int, bits: int) -> np.ndarray:
    mag = rng.integers(0, 2**bits, (n_dim, n_dim)).astype(np.float64)
    sign = rng.choice([-1.0, 1.0], (n_dim, n_dim))
    scale = 2.0 ** rng.integers(-4, 4, (n_dim,))
    return mag * sign * scale


@pytest.mark.parametrize('n_dim', [2, 4, 8])
@pytest.mark.parametrize('bits', [2, 4, 8])
def test_csd_decompose(rng, n_dim, bits):
    kernel = random_kernel(rng, n_dim, bits)
    csd, shift0, shift1 = csd_decompose(kernel)
    n_bits = csd.shape[2]
    powers = 2.0 ** np.arange(n_bits)
    recon = (csd.astype(np.float64) * powers).sum(axis=2)
    recon = recon * 2.0 ** shift0.astype(np.float64)[:, None] * 2.0 ** shift1.astype(np.float64)[None, :]
    np.testing.assert_array_equal(recon, kernel)


@pytest.mark.parametrize('dc', [-2, -1, 0, 1, 2])
def test_kernel_decompose(rng, dc):
    kernel = random_kernel(rng, 6, 4)
    m0, m1 = kernel_decompose(kernel, dc)
    np.testing.assert_allclose(m0 @ m1, kernel, rtol=0, atol=0)


@pytest.mark.parametrize('method', ['mc', 'wmc', 'mc-dc', 'wmc-dc', 'mc-pdc', 'wmc-pdc'])
def test_heuristic_selection_order_incremental(rng, method, monkeypatch):
    """Micro-assert: the incrementally maintained sorted freq view
    (DAState.sorted_stat) reproduces the full re-sort exactly at every
    greedy step, so heuristic selection order is unchanged."""
    from da4ml_tpu.cmvm import heuristics as H
    from da4ml_tpu.cmvm.core import cmvm as run_cmvm

    orig = H._sorted_items
    calls = []

    def checked(state):
        items = orig(state)
        assert items == sorted(state.freq_stat.items(), key=lambda kv: kv[0].sort_key)
        calls.append(len(items))
        return items

    monkeypatch.setattr(H, '_sorted_items', checked)
    kernel = random_kernel(rng, 6, 4)
    state = run_cmvm(kernel, method)
    assert calls and len(state.ops) > 6  # the greedy loop ran through the instrumented scan


@pytest.mark.parametrize('method0', ['mc', 'wmc'])
@pytest.mark.parametrize('method1', ['mc', 'wmc', 'auto'])
@pytest.mark.parametrize('hard_dc', [0, 2, -1])
@pytest.mark.parametrize('decompose_dc', [0, -1, -2])
@pytest.mark.parametrize('search_all', [False, True])
def test_solve(rng, method0, method1, hard_dc, decompose_dc, search_all):
    kernel = random_kernel(rng, 4, 4)
    sol = solve(
        kernel,
        method0=method0,
        method1=method1,
        hard_dc=hard_dc,
        decompose_dc=decompose_dc,
        search_all_decompose_dc=search_all,
    )
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)


@pytest.mark.parametrize('n_dim', [2, 8, 16])
@pytest.mark.parametrize('bits', [2, 8])
def test_solve_sizes(rng, n_dim, bits):
    kernel = random_kernel(rng, n_dim, bits)
    sol = solve(kernel)
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)
    assert sol.cost > 0 or np.all(kernel == 0)


def test_solve_zero_kernel():
    sol = solve(np.zeros((4, 3)))
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), np.zeros((4, 3)))
