"""Keras / Torch front-end plugins: DAIS predict == framework predict.

Integer weights and integer inputs keep float32 framework math exact, so the
comparison is strict equality (reference pattern: tests/test_plugin.py of
calad0i/da4ml applied to real frameworks).
"""

import numpy as np
import pytest

from da4ml_tpu.trace import HWConfig, comb_trace

keras = pytest.importorskip('keras')
torch = pytest.importorskip('torch')


def _int_weights_keras(model, rng, lo=-4, hi=4):
    for w in model.weights:
        w.assign(rng.integers(lo, hi, w.shape).astype(np.float32))


def _trace_predict(model, data, **kw):
    from da4ml_tpu.converter import trace_model

    inp, out = trace_model(model, HWConfig(1, -1, -1), **kw)
    comb = comb_trace(inp, out)
    return comb.predict(data.reshape(len(data), -1), backend='numpy')


def test_keras_sequential_mlp(rng):
    from keras import layers

    model = keras.Sequential([layers.Input((8,)), layers.Dense(6, activation='relu'), layers.Dense(3)])
    _int_weights_keras(model, rng)
    data = rng.integers(-8, 8, (32, 8)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 4, 0))
    ref = np.asarray(model(data.astype(np.float32))).astype(np.float64)
    np.testing.assert_array_equal(out, ref)


def test_keras_functional_residual(rng):
    from keras import layers

    i = keras.Input((6,))
    a = layers.Dense(6, activation='relu')(i)
    b = layers.Add()([a, i])
    o = layers.Dense(2)(b)
    model = keras.Model(i, o)
    _int_weights_keras(model, rng)
    data = rng.integers(-4, 4, (16, 6)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    ref = np.asarray(model(data.astype(np.float32))).astype(np.float64)
    np.testing.assert_array_equal(out, ref)


def test_keras_multiply_cropping(rng):
    from keras import layers

    i = keras.Input((8, 8, 2))
    a = layers.Cropping2D(((1, 1), (2, 1)))(i)
    b = layers.Cropping2D(((1, 1), (2, 1)))(i)
    m = layers.Multiply()([a, b])
    o = layers.Flatten()(m)
    model = keras.Model(i, o)
    data = rng.integers(-4, 4, (8, 8, 8, 2)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    ref = np.asarray(model(data.astype(np.float32))).reshape(8, -1).astype(np.float64)
    np.testing.assert_array_equal(out, ref)


def test_keras_cropping1d(rng):
    from keras import layers

    model = keras.Sequential([keras.Input((10, 2)), layers.Cropping1D((2, 3)), layers.Flatten()])
    data = rng.integers(-4, 4, (8, 10, 2)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    ref = np.asarray(model(data.astype(np.float32))).reshape(8, -1).astype(np.float64)
    np.testing.assert_array_equal(out, ref)


def test_keras_conv2d_model(rng):
    from keras import layers

    model = keras.Sequential(
        [
            layers.Input((6, 6, 1)),
            layers.Conv2D(2, (3, 3), activation='relu'),
            layers.MaxPooling2D((2, 2)),
            layers.Flatten(),
            layers.Dense(3),
        ]
    )
    _int_weights_keras(model, rng, -3, 3)
    data = rng.integers(-4, 4, (8, 6, 6, 1)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    ref = np.asarray(model(data.astype(np.float32))).reshape(8, -1).astype(np.float64)
    np.testing.assert_array_equal(out, ref)


def test_keras_prewarm_kernel_groups(rng):
    """The keras plugin enumerates one kernel group per CMVM layer, shaped
    exactly as the trace handlers shape the solve calls."""
    from keras import layers

    from da4ml_tpu.converter.keras_plugin import KerasTracer
    from da4ml_tpu.trace import HWConfig

    model = keras.Sequential(
        [
            layers.Input((6, 6, 2)),
            layers.Conv2D(3, (3, 3)),
            layers.DepthwiseConv2D((2, 2), depth_multiplier=2),
            layers.Flatten(),
            layers.Dense(4),
        ]
    )
    _int_weights_keras(model, rng, -3, 3)
    groups = KerasTracer(model, HWConfig(1, -1, -1), {'backend': 'jax'}).prewarm_kernel_groups()
    assert groups is not None and len(groups) == 3
    assert [k.shape for k in groups[0]] == [(3 * 3 * 2, 3)]  # conv im2col
    assert [k.shape for k in groups[1]] == [(2 * 2, 2)] * 3  # depthwise, per channel
    assert [k.shape for k in groups[2]] == [(3 * 3 * 6, 4)]  # dense on the flattened (3,3,6) map


def test_torch_prewarm_kernel_groups(rng):
    import torch.nn as nn

    from da4ml_tpu.converter.torch_plugin import TorchTracer
    from da4ml_tpu.trace import HWConfig

    model = nn.Sequential(nn.Conv2d(2, 3, 3), nn.Flatten(), nn.LazyLinear(4))
    model(torch.zeros(1, 2, 5, 5))  # materialize lazy shapes
    model.input_shape = (2, 5, 5)
    groups = TorchTracer(model, HWConfig(1, -1, -1), {'backend': 'jax'}).prewarm_kernel_groups()
    assert groups is not None and len(groups) == 2
    assert groups[0][0].shape == (3 * 3 * 2, 3)
    assert groups[1][0].shape[1] == 4


def test_keras_concat_multi_branch(rng):
    from keras import layers

    i = keras.Input((5,))
    a = layers.Dense(4, activation='relu')(i)
    b = layers.Dense(4)(i)
    o = layers.Concatenate()([a, b])
    model = keras.Model(i, o)
    _int_weights_keras(model, rng)
    data = rng.integers(-4, 4, (16, 5)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    ref = np.asarray(model(data.astype(np.float32))).astype(np.float64)
    np.testing.assert_array_equal(out, ref)


class _TorchMLP(torch.nn.Module):
    input_shape = (8,)

    def __init__(self):
        super().__init__()
        self.fc1 = torch.nn.Linear(8, 6)
        self.act = torch.nn.ReLU()
        self.fc2 = torch.nn.Linear(6, 3)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


class _TorchResidual(torch.nn.Module):
    input_shape = (6,)

    def __init__(self):
        super().__init__()
        self.fc = torch.nn.Linear(6, 6)
        self.out = torch.nn.Linear(6, 2)

    def forward(self, x):
        return self.out(torch.relu(self.fc(x)) + x)


class _TorchConv(torch.nn.Module):
    input_shape = (1, 6, 6)

    def __init__(self):
        super().__init__()
        self.conv = torch.nn.Conv2d(1, 2, 3)
        self.act = torch.nn.ReLU()
        self.flat = torch.nn.Flatten(0)
        self.fc = torch.nn.Linear(32, 3)

    def forward(self, x):
        return self.fc(self.flat(self.act(self.conv(x))))


def _int_weights_torch(model, rng, lo=-4, hi=4):
    with torch.no_grad():
        for p in model.parameters():
            p.copy_(torch.tensor(rng.integers(lo, hi, tuple(p.shape)).astype(np.float32)))


@pytest.mark.parametrize('cls', [_TorchMLP, _TorchResidual])
def test_torch_mlp(rng, cls):
    model = cls()
    _int_weights_torch(model, rng)
    n_in = int(np.prod(model.input_shape))
    data = rng.integers(-4, 4, (16, n_in)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    with torch.no_grad():
        ref = model(torch.tensor(data.astype(np.float32))).numpy().astype(np.float64)
    np.testing.assert_array_equal(out, ref)


def test_torch_conv(rng):
    model = _TorchConv()
    _int_weights_torch(model, rng, -3, 3)
    data = rng.integers(-4, 4, (8, 1, 6, 6)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    with torch.no_grad():
        ref = np.stack([model(torch.tensor(d.astype(np.float32))).numpy() for d in data]).astype(np.float64)
    np.testing.assert_array_equal(out, ref.reshape(8, -1))


def test_keras_avg_pool_same_padding(rng):
    """'same'-padded average pooling must average only in-bounds cells."""
    from keras import layers

    model = keras.Sequential([layers.Input((3, 3, 1)), layers.AveragePooling2D((2, 2), padding='same')])
    data = np.arange(9, dtype=np.float64).reshape(1, 3, 3, 1)
    out = _trace_predict(model, data, inputs_kif=(1, 4, 0))
    ref = np.asarray(model(data.astype(np.float32))).reshape(1, -1).astype(np.float64)
    np.testing.assert_array_equal(out, ref)


class _TorchCat(torch.nn.Module):
    input_shape = (4,)

    def __init__(self):
        super().__init__()
        self.fc = torch.nn.Linear(4, 3)

    def forward(self, x):
        return torch.cat([self.fc(x), x], dim=1)  # batched-forward convention


def test_torch_cat_batched_dim(rng):
    model = _TorchCat()
    _int_weights_torch(model, rng)
    data = rng.integers(-4, 4, (8, 4)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    with torch.no_grad():
        ref = model(torch.tensor(data.astype(np.float32))).numpy().astype(np.float64)
    np.testing.assert_array_equal(out, ref)


def test_torch_padded_pool_rejected(rng):
    class M(torch.nn.Module):
        input_shape = (1, 6, 6)

        def __init__(self):
            super().__init__()
            self.pool = torch.nn.MaxPool2d(2, padding=1)

        def forward(self, x):
            return self.pool(x)

    from da4ml_tpu.converter import trace_model

    with pytest.raises(NotImplementedError, match='padding'):
        trace_model(M(), HWConfig(1, -1, -1), inputs_kif=(1, 3, 0))


def test_keras_batchnorm_axis(rng):
    """BatchNormalization must broadcast stats along its configured axis."""
    from keras import layers

    for axis in (1, -1):
        model = keras.Sequential([layers.Input((3, 4)), layers.BatchNormalization(axis=axis)])
        ch = model.layers[-1].moving_mean.shape[0]
        model.layers[-1].moving_mean.assign(np.arange(ch, dtype=np.float32))
        model.layers[-1].moving_variance.assign(np.full(ch, 0.25 - model.layers[-1].epsilon, np.float32))
        model.layers[-1].gamma.assign(np.full(ch, 2.0, np.float32))
        data = rng.integers(-4, 4, (4, 3, 4)).astype(np.float64)
        out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
        ref = np.asarray(model(data.astype(np.float32))).reshape(4, -1).astype(np.float64)
        # BN folds through a float rsqrt: f32 (keras) vs f64 (trace) differ in
        # the last ulp, so this checks axis semantics, not bit-exactness
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_torch_partial_flatten_rejected(rng):
    class M(torch.nn.Module):
        input_shape = (2, 3, 4)

        def forward(self, x):
            return torch.flatten(x, 2)

    from da4ml_tpu.converter import trace_model

    with pytest.raises(NotImplementedError, match='flatten'):
        trace_model(M(), HWConfig(1, -1, -1), inputs_kif=(1, 3, 0))


def test_keras_1d_pool_pad_upsample(rng):
    from keras import layers

    model = keras.Sequential(
        [
            layers.Input((8, 2)),
            layers.ZeroPadding1D(1),
            layers.Conv1D(3, 3, activation='relu'),
            layers.MaxPooling1D(2),
            layers.UpSampling1D(2),
            layers.AveragePooling1D(2),
            layers.GlobalMaxPooling1D(),
            layers.Dense(2),
        ]
    )
    _int_weights_keras(model, rng, -3, 3)
    data = rng.integers(-4, 4, (8, 8, 2)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    ref = np.asarray(model(data.astype(np.float32))).reshape(8, -1).astype(np.float64)
    np.testing.assert_array_equal(out, ref)


def test_keras_depthwise_separable(rng):
    from keras import layers

    model = keras.Sequential(
        [
            layers.Input((5, 5, 2)),
            layers.ZeroPadding2D(((1, 0), (0, 1))),
            layers.DepthwiseConv2D((3, 3), depth_multiplier=2, activation='relu'),
            layers.SeparableConv2D(3, (2, 2)),
            layers.UpSampling2D((1, 2)),
            layers.GlobalAveragePooling2D(),
        ]
    )
    _int_weights_keras(model, rng, -3, 3)
    data = rng.integers(-4, 4, (6, 5, 5, 2)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    # GlobalAveragePooling divides by a non-pow2 count in f32; the
    # fixed-point trace computes the same mean exactly, so compare with the
    # f64 mean of the pre-pool f32 values instead of strict f32 equality
    pre = keras.Model(model.inputs, model.layers[-2].output)
    ref = np.asarray(pre(data.astype(np.float32))).astype(np.float64).mean(axis=(1, 2))
    np.testing.assert_allclose(out, ref.reshape(6, -1), rtol=0, atol=1e-5)


class _TorchDepthwise(torch.nn.Module):
    input_shape = (2, 6, 6)

    def __init__(self):
        super().__init__()
        self.pad = torch.nn.ZeroPad2d((1, 0, 0, 1))
        self.dw = torch.nn.Conv2d(2, 4, 3, groups=2)  # depthwise, mult 2
        self.act = torch.nn.ReLU()
        self.up = torch.nn.Upsample(scale_factor=2, mode='nearest')
        self.pool = torch.nn.MaxPool2d(2)
        self.flat = torch.nn.Flatten(0)

    def forward(self, x):
        return self.flat(self.pool(self.up(self.act(self.dw(self.pad(x))))))


def test_torch_depthwise_pad_upsample(rng):
    model = _TorchDepthwise()
    _int_weights_torch(model, rng, -3, 3)
    data = rng.integers(-4, 4, (6, 2, 6, 6)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    with torch.no_grad():
        # batched reference: nn.Upsample requires the batch dim to interpret
        # [N, C, H, W]; Flatten(0) then flattens per-batch — reshape instead
        mb = torch.nn.Sequential(model.pad, model.dw, model.act, model.up, model.pool)
        ref = mb(torch.tensor(data.astype(np.float32))).numpy().astype(np.float64)
    np.testing.assert_array_equal(out, ref.reshape(6, -1))


def test_keras_string_activations(rng):
    from keras import layers

    model = keras.Sequential([keras.Input((6,)), layers.Dense(6, activation='relu6')])
    _int_weights_keras(model, rng)
    data = rng.integers(-4, 4, (16, 6)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    ref = np.asarray(model(data.astype(np.float32))).astype(np.float64)
    np.testing.assert_array_equal(out, ref)

    # activation='leaky_relu': the 0.2 default slope is not binary-
    # representable, so keras's f32 product differs from the exact trace in
    # the last ulp — tolerance-checked, unlike every representable-slope case
    m2 = keras.Sequential([keras.Input((6,)), layers.Dense(6, activation='leaky_relu')])
    _int_weights_keras(m2, rng)
    out2 = _trace_predict(m2, data, inputs_kif=(1, 3, 0))
    ref2 = np.asarray(m2(data.astype(np.float32))).astype(np.float64)
    np.testing.assert_allclose(out2, ref2, rtol=1e-6)


def test_keras_leaky_prelu(rng):
    from keras import layers

    i = keras.Input((6,))
    a = layers.LeakyReLU(negative_slope=0.25)(i)
    p = layers.PReLU()(a)
    model = keras.Model(i, p)
    # pow2 alphas stay exact in the f32 reference model
    model.layers[-1].set_weights([np.full((6,), 0.5, np.float32)])
    data = rng.integers(-8, 8, (16, 6)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 4, 0))
    ref = np.asarray(model(data.astype(np.float32))).astype(np.float64)
    np.testing.assert_array_equal(out, ref)


class _TorchLeaky(torch.nn.Module):
    input_shape = (6,)

    def __init__(self):
        super().__init__()
        self.fc = torch.nn.Linear(6, 6)
        self.lk = torch.nn.LeakyReLU(0.25)
        self.pr = torch.nn.PReLU(6, init=0.5)

    def forward(self, x):
        return self.pr(self.lk(self.fc(x)))


def test_keras_relu_negative_slope_max_value(rng):
    from keras import layers

    model = keras.Sequential([keras.Input((6,)), layers.ReLU(negative_slope=0.25, max_value=4.0)])
    data = rng.integers(-8, 8, (16, 6)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 4, 0))
    ref = np.asarray(model(data.astype(np.float32))).astype(np.float64)
    np.testing.assert_array_equal(out, ref)


class _TorchFnLeaky(torch.nn.Module):
    input_shape = (6,)

    def __init__(self):
        super().__init__()
        self.fc = torch.nn.Linear(6, 6)

    def forward(self, x):
        import torch.nn.functional as F

        return F.leaky_relu(self.fc(x), 0.25)


def test_torch_functional_leaky_relu(rng):
    model = _TorchFnLeaky()
    _int_weights_torch(model, rng, -3, 3)
    data = rng.integers(-4, 4, (8, 6)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    with torch.no_grad():
        ref = model(torch.tensor(data.astype(np.float32))).numpy().astype(np.float64)
    np.testing.assert_array_equal(out, ref)


class _TorchClamp(torch.nn.Module):
    input_shape = (6,)

    def __init__(self):
        super().__init__()
        self.fc = torch.nn.Linear(6, 6)
        self.r6 = torch.nn.ReLU6()
        self.ht = torch.nn.Hardtanh(-2.0, 3.0)

    def forward(self, x):
        return torch.clamp(self.ht(self.r6(self.fc(x))), min=-1.0, max=2.5)


def test_torch_relu6_hardtanh_clamp(rng):
    model = _TorchClamp()
    _int_weights_torch(model, rng, -3, 3)
    data = rng.integers(-4, 4, (8, 6)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    with torch.no_grad():
        ref = model(torch.tensor(data.astype(np.float32))).numpy().astype(np.float64)
    np.testing.assert_array_equal(out, ref)


def test_torch_leaky_prelu(rng):
    model = _TorchLeaky()
    _int_weights_torch(model, rng, -3, 3)
    with torch.no_grad():
        model.pr.weight.fill_(0.5)
    data = rng.integers(-4, 4, (8, 6)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    with torch.no_grad():
        ref = model(torch.tensor(data.astype(np.float32))).numpy().astype(np.float64)
    np.testing.assert_array_equal(out, ref)


class _TorchSliceMax(torch.nn.Module):
    input_shape = (8,)

    def __init__(self):
        super().__init__()
        self.fc = torch.nn.Linear(8, 8)

    def forward(self, x):
        y = self.fc(x)
        return torch.maximum(y[:, :4], y[:, 4:])


def test_torch_getitem_maximum(rng):
    model = _TorchSliceMax()
    _int_weights_torch(model, rng, -3, 3)
    data = rng.integers(-4, 4, (6, 8)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    with torch.no_grad():
        ref = model(torch.tensor(data.astype(np.float32))).numpy().astype(np.float64)
    np.testing.assert_array_equal(out, ref)


class _TorchPool1d(torch.nn.Module):
    input_shape = (2, 8)

    def __init__(self):
        super().__init__()
        self.dw = torch.nn.Conv1d(2, 2, 3, groups=2)
        self.mp = torch.nn.MaxPool1d(2)
        self.ap = torch.nn.AvgPool1d(2, stride=1)  # pow2 window: f32 mean stays exact
        self.flat = torch.nn.Flatten(0)

    def forward(self, x):
        return self.flat(self.ap(self.mp(self.dw(x))))


def test_torch_1d_depthwise_pooling(rng):
    model = _TorchPool1d()
    _int_weights_torch(model, rng, -3, 3)
    data = rng.integers(-4, 4, (6, 2, 8)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    with torch.no_grad():
        ref = np.stack([model(torch.tensor(d.astype(np.float32))).numpy() for d in data]).astype(np.float64)
    np.testing.assert_array_equal(out, ref.reshape(6, -1))


def test_keras_ops_functional_graph(rng):
    """Functional graphs built with keras.ops (the HGQ2 style) trace through
    the same walker: relu / slicing / einsum / reductions / concat / abs,
    with every batch-axis reference stripped."""
    inp = keras.Input((6,))
    a = keras.layers.Dense(4)(inp)
    b = keras.ops.relu(a)
    c = keras.ops.concatenate([a, b], axis=-1)
    d = c[:, :5]
    e = keras.ops.einsum('bi,ij->bj', d, np.ones((5, 3)))
    f = keras.ops.max(e, axis=1, keepdims=True)
    g = keras.ops.concatenate([e, keras.ops.absolute(f)], axis=-1)
    model = keras.Model(inp, g)
    _int_weights_keras(model, rng)

    data = (rng.integers(-16, 16, (32, 6)) * 0.5).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 4, 1))
    ref = np.asarray(model(data.astype(np.float32))).astype(np.float64).reshape(32, -1)
    np.testing.assert_array_equal(out, ref)


def test_keras_ops_einsum_two_symbolic(rng):
    """ops.einsum with BOTH operands symbolic (batch letter in every term)."""
    inp = keras.Input((4, 3))
    a = keras.layers.Dense(3)(inp)
    e = keras.ops.einsum('bik,bjk->bij', a, a)
    model = keras.Model(inp, e)
    _int_weights_keras(model, rng)
    data = rng.integers(-3, 3, (8, 4, 3)).astype(np.float64)
    out = _trace_predict(model, data, inputs_kif=(1, 3, 0))
    ref = np.asarray(model(data.astype(np.float32))).astype(np.float64).reshape(8, -1)
    np.testing.assert_array_equal(out, ref)
