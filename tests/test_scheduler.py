"""Throughput-first device scheduler: dedupe, overlap, cache split, mesh,
and cooperative deadlines (docs/api.md#scheduler-knobs)."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import da4ml_tpu.cmvm.jax_search as js
from da4ml_tpu.cmvm.jax_search import solve_jax_many


def random_kernel(rng, n_in, n_out, bits):
    mag = rng.integers(0, 2**bits, (n_in, n_out)).astype(np.float64)
    return mag * rng.choice([-1.0, 1.0], (n_in, n_out))


def _identical(a, b):
    assert float(a.cost) == float(b.cost)
    for sa, sb in zip(a.stages, b.stages):
        assert len(sa.ops) == len(sb.ops)
        for oa, ob in zip(sa.ops, sb.ops):
            assert (oa.id0, oa.id1, oa.opcode, oa.data) == (ob.id0, ob.id1, ob.opcode, ob.data)


def test_duplicate_lanes_dedupe(rng):
    """Byte-identical kernels in one batch solve once and fan out; results
    are identical objects and still exact."""
    from da4ml_tpu.telemetry.metrics import disable_metrics, enable_metrics, metrics_snapshot, reset_metrics

    k = random_kernel(rng, 6, 6, 4)
    enable_metrics()
    reset_metrics()
    try:
        sols = solve_jax_many([k, k.copy(), k.copy()])
        snap = metrics_snapshot()
    finally:
        disable_metrics()
    for s in sols:
        np.testing.assert_array_equal(np.asarray(s.kernel, np.float64), k)
    _identical(sols[0], sols[1])
    _identical(sols[0], sols[2])
    # the dc ladder of 3 identical matrices dedupes at least the copies
    assert snap.get('sched.dedup_lanes', {}).get('value', 0) >= 2


def test_async_emit_toggle_identical(rng, monkeypatch):
    """DA4ML_JAX_ASYNC_EMIT=0 (serial emit) and the default overlapped emit
    produce identical solutions for a multi-bucket batch."""
    kernels = [random_kernel(rng, 6, 6, 2), random_kernel(rng, 8, 8, 6)]  # 2 canonical buckets
    base = solve_jax_many(kernels)
    monkeypatch.setenv('DA4ML_JAX_ASYNC_EMIT', '0')
    serial = solve_jax_many(kernels)
    for a, b in zip(base, serial):
        _identical(a, b)


def test_auto_mesh_parity(rng, monkeypatch):
    """DA4ML_JAX_MESH=1 shards the lane batch over the 8 virtual cpu
    devices; solutions are identical to the single-device path."""
    kernels = [random_kernel(rng, 6, 6, 4), random_kernel(rng, 8, 6, 3)]
    base = solve_jax_many(kernels)
    monkeypatch.setenv('DA4ML_JAX_MESH', '1')
    js._auto_mesh_for.cache_clear()
    try:
        meshy = solve_jax_many(kernels)
    finally:
        js._auto_mesh_for.cache_clear()
    for k, a, b in zip(kernels, base, meshy):
        np.testing.assert_array_equal(np.asarray(b.kernel, np.float64), k)
        _identical(a, b)


def test_auto_mesh_off_by_default_on_cpu():
    assert js._auto_mesh() is None  # cpu backend: explicit opt-in only


def test_first_call_classification_markers(tmp_path, monkeypatch):
    """_classify_first_call: first sighting of a class against a cache dir
    is 'compile' (and writes the marker), later sightings are 'cache_load'
    — including from other processes sharing the dir."""
    import jax

    prev = getattr(jax.config, 'jax_compilation_cache_dir', None)
    jax.config.update('jax_compilation_cache_dir', str(tmp_path))
    try:
        cls = ('probe-class', 123)
        assert js._classify_first_call(cls) == 'compile'
        assert js._classify_first_call(cls) == 'cache_load'
        other = ('probe-class', 456)
        assert js._classify_first_call(other) == 'compile'
    finally:
        jax.config.update('jax_compilation_cache_dir', prev)


def test_record_first_call_metrics(tmp_path):
    import jax

    from da4ml_tpu.telemetry.metrics import disable_metrics, enable_metrics, metrics_snapshot, reset_metrics

    prev = getattr(jax.config, 'jax_compilation_cache_dir', None)
    jax.config.update('jax_compilation_cache_dir', str(tmp_path))
    enable_metrics()
    reset_metrics()
    try:
        js._record_first_call(('m1', 1), 0.25)
        js._record_first_call(('m1', 1), 0.01)  # marker now exists -> cache_load
        snap = metrics_snapshot()
    finally:
        disable_metrics()
        reset_metrics()
        jax.config.update('jax_compilation_cache_dir', prev)
    assert snap['jit.compile']['value'] == 1
    assert snap['jit.cache_load']['value'] == 1
    # the legacy aggregate still counts both first calls
    assert snap['jit.cache_miss']['value'] == 2


def test_cooperative_deadline_check():
    from da4ml_tpu.reliability import deadline as dl
    from da4ml_tpu.reliability.errors import SolveTimeout

    # no active deadline: a no-op
    dl.check_deadline('unit test')
    # expired deadline on this thread: raises
    dl._local.deadline = time.monotonic() - 1.0
    try:
        with pytest.raises(SolveTimeout):
            dl.check_deadline('unit test')
    finally:
        dl._local.deadline = None


def test_run_with_deadline_arms_cooperative_checks():
    from da4ml_tpu.reliability import deadline as dl

    got = dl.run_with_deadline(dl.active_deadline, 5.0, name='probe')
    assert got is not None and got > time.monotonic()
    assert dl.active_deadline() is None  # restored outside the worker


def test_solve_deadline_aborts_device_rungs(rng, monkeypatch):
    """A budgeted orchestrated jax solve stops between rungs instead of
    burning the detached worker: the cooperative check fires inside
    solve_single_lanes."""
    from da4ml_tpu.reliability import deadline as dl
    from da4ml_tpu.reliability.errors import SolveTimeout

    kernel = random_kernel(rng, 8, 8, 4)
    dl._local.deadline = time.monotonic() - 1.0
    try:
        with pytest.raises(SolveTimeout):
            solve_jax_many([kernel])
    finally:
        dl._local.deadline = None


def test_warmup_grid_mirror(rng, monkeypatch):
    """_ladder_specs (the warmup grid enumerator) contains every class the
    live solve builds for the same kernels — the no-drift property."""
    from da4ml_tpu.ir import QInterval

    kernels = [random_kernel(rng, 8, 8, 4)]
    monkeypatch.setenv('DA4ML_JAX_PREWARM', '0')

    used: list = []
    real_build = js._build_cse_fn
    monkeypatch.setattr(js, '_build_cse_fn', lambda spec: (used.append(spec), real_build(spec))[1])
    sols = solve_jax_many(kernels)
    np.testing.assert_array_equal(np.asarray(sols[0].kernel, np.float64), kernels[0])
    monkeypatch.setattr(js, '_build_cse_fn', real_build)

    warmed: list = []
    trans: list = []
    monkeypatch.setattr(js, '_prewarm_class', lambda spec, bucket: warmed.append(spec))
    monkeypatch.setattr(js, '_prewarm_transition', lambda s, b1, b2: trans.append((s, b1, b2)))
    n = js.prewarm_for_kernels([kernels], full_ladder=True, inline=True)
    assert n == len(warmed) + len(trans) and n > 0
    # the full-ladder grid also precompiles the device-resident rung hops
    assert trans, 'full_ladder warmup enumerated no rung-transition classes'
    missing = set(used) - set(warmed)
    assert not missing, f'live classes missing from the warmup grid: {missing}'


def test_cache_smoke_script(tmp_path):
    """The two-process persistent-cache drill (also the CI gate): the second
    process must report zero jit.compile events and a sub-second compile
    wall clock."""
    script = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), 'cache_smoke.py')
    out = tmp_path / 'stats.json'
    r = subprocess.run(
        [sys.executable, script, '--out', str(out), '--cache-dir', str(tmp_path / 'xla')],
        capture_output=True,
        text=True,
        timeout=560,
    )
    assert r.returncode == 0, (r.stdout or '')[-500:] + (r.stderr or '')[-500:]
    data = json.loads(out.read_text())
    assert data['ok']
    cold, warm = data['runs']
    assert cold['jit_compile'] > 0 and cold['jit_cache_load'] == 0
    assert warm['jit_compile'] == 0 and warm['jit_cache_load'] > 0
