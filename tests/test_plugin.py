"""Converter plugin system tests (parity: reference tests/test_plugin.py)."""

import numpy as np
import pytest

from da4ml_tpu.converter import get_available_plugins, register_plugin, trace_model
from da4ml_tpu.converter.example import ExampleModel, ExampleTracer, operation
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace


@pytest.mark.parametrize('inputs_defined', [True, False])
def test_example_plugin(inputs_defined):
    model = ExampleModel(input_shape=(4, 5) if not inputs_defined else None)

    if inputs_defined:
        inputs = FixedVariableArrayInput((4, 5), HWConfig(1, -1, -1))
        inp, out = trace_model(model, inputs=inputs)
    else:
        inp, out = trace_model(model)

    comb = comb_trace(inp, out)

    rng = np.random.default_rng(42)
    data = rng.uniform(-128, 128, (1000, 4, 5))
    golden = np.array([operation(x).ravel() for x in data])
    pred = comb.predict(data.reshape(1000, -1), backend='numpy')
    np.testing.assert_array_equal(pred, golden)


def test_plugin_shape_inference_failure():
    model = ExampleModel(input_shape=None)
    with pytest.raises(ValueError, match='cannot determine input shapes'):
        trace_model(model)


def test_unknown_framework():
    with pytest.raises(ValueError, match='No plugin found'):
        trace_model(object())


def test_register_plugin():
    class Dummy:
        pass

    register_plugin('dummyfw', ExampleTracer)
    try:
        assert 'dummyfw' in get_available_plugins()
        model = ExampleModel(input_shape=(4, 5))
        inp, out = trace_model(model, framework='dummyfw')
        assert inp.size == 20
    finally:
        get_available_plugins()  # registry is module state; leave the entry in place
