"""Padded-bucket parity: canonical shape-bucket padding is bit-exact.

The throughput scheduler rounds every lane's class dims (O, B) up to the
canonical 2^k / 3*2^k / 5*2^k grid, pads the slot axis P to the pow2 rung
ladder, and pads the lane axis to mesh-divisible buckets. All of that
padding must be *decision-invariant*: a matrix solved inside a larger
canonical bucket must produce a bit-identical ``Pipeline`` (same kernel,
same ops, same cost) to the minimal-bucket solve. These property tests pin
that across the grid edges, the resumable R_in partial-row path, and
heterogeneous batches.
"""

import numpy as np
import pytest

import da4ml_tpu.cmvm.jax_search as js
from da4ml_tpu.cmvm.jax_search import solve_jax_many


def random_kernel(rng, n_in, n_out, bits):
    mag = rng.integers(0, 2**bits, (n_in, n_out)).astype(np.float64)
    return mag * rng.choice([-1.0, 1.0], (n_in, n_out))


def assert_pipelines_identical(a, b):
    np.testing.assert_array_equal(np.asarray(a.kernel, np.float64), np.asarray(b.kernel, np.float64))
    assert float(a.cost) == float(b.cost), (a.cost, b.cost)
    assert a.latency == b.latency
    for sa, sb in zip(a.stages, b.stages):
        assert len(sa.ops) == len(sb.ops)
        for oa, ob in zip(sa.ops, sb.ops):
            assert (oa.id0, oa.id1, oa.opcode, oa.data, oa.qint) == (ob.id0, ob.id1, ob.opcode, ob.data, ob.qint)


def test_canon_dim_grid_properties():
    """_canon_dim is monotone, idempotent, >= input, and on the documented
    2^k / 3*2^k / 5*2^k even grid."""
    grid = {2, 4, 6, 8, 10, 12, 16, 20, 24, 32, 40, 48, 64, 80, 96, 128}
    prev = 0
    for x in range(1, 129):
        c = js._canon_dim(x)
        assert c >= max(x, 2)
        assert c in grid, (x, c)
        assert js._canon_dim(c) == c  # idempotent: grid points are fixed
        assert c >= prev or c >= x  # monotone up to rung boundaries
        prev = c


def test_classes_are_batch_independent(rng):
    """A lane's first-rung compile class is the same whether it is estimated
    alone or inside a heterogeneous batch — the property that makes the
    persistent cache hit across workloads."""
    from da4ml_tpu.ir import QInterval

    def probe(kern):
        return js._Lane(kern, [QInterval(-128.0, 127.0, 1.0)] * kern.shape[0], [0.0] * kern.shape[0], 'wmc')

    a = probe(random_kernel(rng, 6, 6, 3))
    b = probe(random_kernel(rng, 12, 10, 7))
    solo = js._first_rung_specs([a], -1, -1)
    both = js._first_rung_specs([a, b], -1, -1)
    # a's group spec must appear unchanged in the batched estimate
    assert solo, 'probe lane must route to the device'
    assert solo[0][0] in {spec for spec, _ in both}


@pytest.mark.parametrize('dims', [(4, 4, 2), (5, 7, 3), (8, 8, 4), (9, 5, 2)])
def test_padded_canonical_bucket_bit_identical(rng, monkeypatch, dims):
    """Forcing every canonical dim one grid rung up (more outputs, more bit
    planes than needed) yields a bit-identical Pipeline — zero-padded
    outputs/bit planes are never selectable, and the scan-order tie-break
    keys are order-preserved under padding."""
    n, o, b = dims
    kernel = random_kernel(rng, n, o, b)
    base = solve_jax_many([kernel])[0]

    orig = js._canon_dim
    monkeypatch.setattr(js, '_canon_dim', lambda x, lo=2: orig(orig(x, lo) + 1, lo))
    js._build_cse_fn.cache_clear()
    try:
        padded = solve_jax_many([kernel])[0]
    finally:
        js._build_cse_fn.cache_clear()
    assert_pipelines_identical(base, padded)


def test_padded_slot_ladder_bit_identical(rng, monkeypatch):
    """Doubling every P rung (slot-axis padding) is bit-identical: pad slots
    carry benign metadata and can never be selected, and the rung budget
    only changes WHERE the resumable search pauses, not what it decides."""
    kernels = [random_kernel(rng, 6, 6, 4), random_kernel(rng, 8, 5, 3)]
    base = solve_jax_many(kernels)
    orig = js._ladder_P
    monkeypatch.setattr(js, '_ladder_P', lambda cur, step: 2 * orig(cur, step))
    js._build_cse_fn.cache_clear()
    try:
        padded = solve_jax_many(kernels)
    finally:
        js._build_cse_fn.cache_clear()
    for a, b in zip(base, padded):
        assert_pipelines_identical(a, b)


def test_r_in_partial_row_path_bit_identical(rng, monkeypatch):
    """The trimmed-row (R_in < P) resume path under a larger canonical
    bucket: a kernel big enough to resume across rungs must still be
    bit-identical when padded one grid rung up."""
    kernel = random_kernel(rng, 16, 12, 5)  # resumes past the first pow2 rung
    base = solve_jax_many([kernel])[0]
    orig = js._canon_dim
    monkeypatch.setattr(js, '_canon_dim', lambda x, lo=2: orig(orig(x, lo) + 1, lo))
    js._build_cse_fn.cache_clear()
    try:
        padded = solve_jax_many([kernel])[0]
    finally:
        js._build_cse_fn.cache_clear()
    assert_pipelines_identical(base, padded)


def test_heterogeneous_batch_matches_solo(rng):
    """A small matrix batched with a larger one of the SAME canonical
    (O, B) class (so its group n_in_max and lane bucket both grow) solves
    bit-identically to the solo solve."""
    small = random_kernel(rng, 6, 6, 4)  # O canon 8, B canon from 4-bit digits
    big = random_kernel(rng, 12, 7, 4)  # same canonical class, larger n_in
    solo = solve_jax_many([small])[0]
    batched = solve_jax_many([small, big])
    assert_pipelines_identical(solo, batched[0])
    np.testing.assert_array_equal(np.asarray(batched[1].kernel, np.float64), big)


# ---------------------------------------------------------------------------
# device-resident rung ladder (DA4ML_JAX_DEVICE_RESIDENT): the resident
# chain (on-device transitions, decisions-only fetch, host-side digit
# replay) must be byte-identical to the legacy host-state rung loop.
# ---------------------------------------------------------------------------


def _solve_pair(kernels, monkeypatch, **kw):
    """(resident, legacy) solves of the same batch; env restored after."""
    monkeypatch.delenv('DA4ML_JAX_DEVICE_RESIDENT', raising=False)
    resident = solve_jax_many(kernels, **kw)
    monkeypatch.setenv('DA4ML_JAX_DEVICE_RESIDENT', '0')
    legacy = solve_jax_many(kernels, **kw)
    monkeypatch.delenv('DA4ML_JAX_DEVICE_RESIDENT', raising=False)
    return resident, legacy


def test_device_resident_fuzz_grid_edges(rng, monkeypatch):
    """Resident == legacy op-for-op across grid-edge shapes (pow2 and
    3*2^k boundaries) whose ladders span multiple rungs."""
    shapes = [(7, 6, 3), (9, 5, 4), (12, 12, 5), (16, 12, 5)]
    kernels = [random_kernel(rng, *s) for s in shapes]
    resident, legacy = _solve_pair(kernels, monkeypatch)
    for a, b in zip(resident, legacy):
        assert_pipelines_identical(a, b)


def test_device_resident_resume_traffic_and_metrics(rng, monkeypatch):
    """A multi-rung lane chains on device: the resident solve reports
    ``sched.device_resident_rungs`` > 0 and a fraction of the legacy
    host<->device traffic, at byte-identical decisions (R_in resume)."""
    from da4ml_tpu.telemetry.metrics import disable_metrics, enable_metrics, metrics_snapshot, reset_metrics

    kernel = random_kernel(rng, 16, 12, 5)
    enable_metrics()
    try:
        reset_metrics()
        monkeypatch.delenv('DA4ML_JAX_DEVICE_RESIDENT', raising=False)
        (res,) = solve_jax_many([kernel])
        s_res = metrics_snapshot()
        reset_metrics()
        monkeypatch.setenv('DA4ML_JAX_DEVICE_RESIDENT', '0')
        (leg,) = solve_jax_many([kernel])
        s_leg = metrics_snapshot()
    finally:
        monkeypatch.delenv('DA4ML_JAX_DEVICE_RESIDENT', raising=False)
        disable_metrics()
        reset_metrics()
    assert_pipelines_identical(res, leg)
    assert s_res.get('sched.device_resident_rungs', {}).get('value', 0) > 0
    assert s_leg.get('sched.device_resident_rungs', {}).get('value', 0) == 0
    # decisions-only fetch: a fraction of the full-state fetch, and the
    # resident chain re-uploads no state between rungs
    assert s_res['sched.fetch_bytes']['value'] < s_leg['sched.fetch_bytes']['value'] / 2
    assert s_res['sched.upload_bytes']['value'] < s_leg['sched.upload_bytes']['value']


def test_device_resident_prefix_fork_parity(rng, monkeypatch):
    """Beam-fork (LanePrefix) lanes — heterogeneous cur0, full-capacity op
    records — ride the resident ladder bit-exactly. Resident mode runs the
    whole fork generation on device (fork/score/prune in the ladder);
    legacy mode is the host beam + host-state rung loop."""
    kernels = [random_kernel(rng, 12, 8, 4), random_kernel(rng, 9, 6, 3)]
    quality = {'beam': 2, 'depth': 1, 'focus': 1}
    resident, legacy = _solve_pair(kernels, monkeypatch, quality=quality)
    for a, b in zip(resident, legacy):
        assert_pipelines_identical(a, b)


def test_device_beam_mesh_parity(rng, monkeypatch):
    """quality= solves under 4- and 8-device sub-meshes of the virtual cpu
    mesh: the device beam (fork phase unsharded, CSE lanes sharded) matches
    the host-beam path and the unsharded solve bit-exactly."""
    import jax
    from jax.sharding import Mesh

    kernels = [random_kernel(rng, 10, 6, 4), random_kernel(rng, 8, 6, 3)]
    quality = {'beam': 3, 'depth': 1, 'focus': 2}
    base, legacy0 = _solve_pair(kernels, monkeypatch, quality=quality)
    for a, b in zip(base, legacy0):
        assert_pipelines_identical(a, b)
    for nd in (4, 8):
        mesh = Mesh(np.asarray(jax.devices('cpu')[:nd]), ('batch',))
        resident, legacy = _solve_pair(kernels, monkeypatch, quality=quality, mesh=mesh)
        for a, b, c in zip(resident, legacy, base):
            assert_pipelines_identical(a, b)
            assert_pipelines_identical(a, c)


def test_device_beam_deadline_abort(rng, monkeypatch):
    """An expired cooperative deadline aborts a quality= solve mid-ladder in
    both beam modes (SolveTimeout, no hang, no stuck carry) — and the next
    solve in the process is unaffected."""
    import time

    from da4ml_tpu.reliability import deadline as dl
    from da4ml_tpu.reliability.errors import SolveTimeout

    kernels = [random_kernel(rng, 12, 8, 4)]
    for env in (None, '0'):
        if env is None:
            monkeypatch.delenv('DA4ML_JAX_DEVICE_RESIDENT', raising=False)
        else:
            monkeypatch.setenv('DA4ML_JAX_DEVICE_RESIDENT', env)
        dl._local.deadline = time.monotonic() - 1.0
        try:
            with pytest.raises(SolveTimeout):
                solve_jax_many(kernels, quality='search')
        finally:
            dl._local.deadline = None
    monkeypatch.delenv('DA4ML_JAX_DEVICE_RESIDENT', raising=False)
    (sol,) = solve_jax_many(kernels, quality='search')
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernels[0])


def test_device_resident_deadline_abort(rng, monkeypatch):
    """An expired cooperative deadline aborts the resident ladder between
    rungs exactly like the legacy loop (SolveTimeout raised, no hang, no
    stuck device carry)."""
    import time

    from da4ml_tpu.reliability import deadline as dl
    from da4ml_tpu.reliability.errors import SolveTimeout

    kernel = random_kernel(rng, 16, 12, 5)
    for env in (None, '0'):
        if env is None:
            monkeypatch.delenv('DA4ML_JAX_DEVICE_RESIDENT', raising=False)
        else:
            monkeypatch.setenv('DA4ML_JAX_DEVICE_RESIDENT', env)
        dl._local.deadline = time.monotonic() - 1.0
        try:
            with pytest.raises(SolveTimeout):
                solve_jax_many([kernel])
        finally:
            dl._local.deadline = None
    monkeypatch.delenv('DA4ML_JAX_DEVICE_RESIDENT', raising=False)


def test_device_resident_mesh_parity(rng, monkeypatch):
    """The resident transition under a sharded lane mesh (4- and 8-device
    sub-meshes of the virtual cpu mesh) matches both the legacy mesh path
    and the unsharded solve bit-exactly."""
    import jax
    from jax.sharding import Mesh

    kernels = [random_kernel(rng, 16, 10, 5), random_kernel(rng, 8, 6, 3)]
    base, legacy0 = _solve_pair(kernels, monkeypatch)
    for a, b in zip(base, legacy0):
        assert_pipelines_identical(a, b)
    for nd in (4, 8):
        mesh = Mesh(np.asarray(jax.devices('cpu')[:nd]), ('batch',))
        monkeypatch.delenv('DA4ML_JAX_DEVICE_RESIDENT', raising=False)
        resident = solve_jax_many(kernels, mesh=mesh)
        monkeypatch.setenv('DA4ML_JAX_DEVICE_RESIDENT', '0')
        legacy = solve_jax_many(kernels, mesh=mesh)
        monkeypatch.delenv('DA4ML_JAX_DEVICE_RESIDENT', raising=False)
        for a, b, c in zip(resident, legacy, base):
            assert_pipelines_identical(a, b)
            assert_pipelines_identical(a, c)


def test_explicit_step_ladder_bit_identical(rng):
    """The legacy explicit-step rung policy and the default geometric
    ladder pause the resumable search at different rungs but decide
    identically (small sizes: the top-k cache is exact)."""
    from da4ml_tpu.cmvm.jax_search import _Lane, solve_single_lanes
    from da4ml_tpu.ir import QInterval

    kernel = random_kernel(rng, 8, 8, 5)
    qints = [QInterval(-128.0, 127.0, 1.0)] * 8

    def lane():
        return _Lane(kernel, list(qints), [0.0] * 8, 'wmc')

    (a,) = solve_single_lanes([lane()], -1, -1)
    (b,) = solve_single_lanes([lane()], -1, -1, step=8)
    assert len(a.ops) == len(b.ops)
    for oa, ob in zip(a.ops, b.ops):
        assert (oa.id0, oa.id1, oa.opcode, oa.data) == (ob.id0, ob.id1, ob.opcode, ob.data)
