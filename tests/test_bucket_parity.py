"""Padded-bucket parity: canonical shape-bucket padding is bit-exact.

The throughput scheduler rounds every lane's class dims (O, B) up to the
canonical 2^k / 3*2^k / 5*2^k grid, pads the slot axis P to the pow2 rung
ladder, and pads the lane axis to mesh-divisible buckets. All of that
padding must be *decision-invariant*: a matrix solved inside a larger
canonical bucket must produce a bit-identical ``Pipeline`` (same kernel,
same ops, same cost) to the minimal-bucket solve. These property tests pin
that across the grid edges, the resumable R_in partial-row path, and
heterogeneous batches.
"""

import numpy as np
import pytest

import da4ml_tpu.cmvm.jax_search as js
from da4ml_tpu.cmvm.jax_search import solve_jax_many


def random_kernel(rng, n_in, n_out, bits):
    mag = rng.integers(0, 2**bits, (n_in, n_out)).astype(np.float64)
    return mag * rng.choice([-1.0, 1.0], (n_in, n_out))


def assert_pipelines_identical(a, b):
    np.testing.assert_array_equal(np.asarray(a.kernel, np.float64), np.asarray(b.kernel, np.float64))
    assert float(a.cost) == float(b.cost), (a.cost, b.cost)
    assert a.latency == b.latency
    for sa, sb in zip(a.stages, b.stages):
        assert len(sa.ops) == len(sb.ops)
        for oa, ob in zip(sa.ops, sb.ops):
            assert (oa.id0, oa.id1, oa.opcode, oa.data, oa.qint) == (ob.id0, ob.id1, ob.opcode, ob.data, ob.qint)


def test_canon_dim_grid_properties():
    """_canon_dim is monotone, idempotent, >= input, and on the documented
    2^k / 3*2^k / 5*2^k even grid."""
    grid = {2, 4, 6, 8, 10, 12, 16, 20, 24, 32, 40, 48, 64, 80, 96, 128}
    prev = 0
    for x in range(1, 129):
        c = js._canon_dim(x)
        assert c >= max(x, 2)
        assert c in grid, (x, c)
        assert js._canon_dim(c) == c  # idempotent: grid points are fixed
        assert c >= prev or c >= x  # monotone up to rung boundaries
        prev = c


def test_classes_are_batch_independent(rng):
    """A lane's first-rung compile class is the same whether it is estimated
    alone or inside a heterogeneous batch — the property that makes the
    persistent cache hit across workloads."""
    from da4ml_tpu.ir import QInterval

    def probe(kern):
        return js._Lane(kern, [QInterval(-128.0, 127.0, 1.0)] * kern.shape[0], [0.0] * kern.shape[0], 'wmc')

    a = probe(random_kernel(rng, 6, 6, 3))
    b = probe(random_kernel(rng, 12, 10, 7))
    solo = js._first_rung_specs([a], -1, -1)
    both = js._first_rung_specs([a, b], -1, -1)
    # a's group spec must appear unchanged in the batched estimate
    assert solo, 'probe lane must route to the device'
    assert solo[0][0] in {spec for spec, _ in both}


@pytest.mark.parametrize('dims', [(4, 4, 2), (5, 7, 3), (8, 8, 4), (9, 5, 2)])
def test_padded_canonical_bucket_bit_identical(rng, monkeypatch, dims):
    """Forcing every canonical dim one grid rung up (more outputs, more bit
    planes than needed) yields a bit-identical Pipeline — zero-padded
    outputs/bit planes are never selectable, and the scan-order tie-break
    keys are order-preserved under padding."""
    n, o, b = dims
    kernel = random_kernel(rng, n, o, b)
    base = solve_jax_many([kernel])[0]

    orig = js._canon_dim
    monkeypatch.setattr(js, '_canon_dim', lambda x, lo=2: orig(orig(x, lo) + 1, lo))
    js._build_cse_fn.cache_clear()
    try:
        padded = solve_jax_many([kernel])[0]
    finally:
        js._build_cse_fn.cache_clear()
    assert_pipelines_identical(base, padded)


def test_padded_slot_ladder_bit_identical(rng, monkeypatch):
    """Doubling every P rung (slot-axis padding) is bit-identical: pad slots
    carry benign metadata and can never be selected, and the rung budget
    only changes WHERE the resumable search pauses, not what it decides."""
    kernels = [random_kernel(rng, 6, 6, 4), random_kernel(rng, 8, 5, 3)]
    base = solve_jax_many(kernels)
    orig = js._ladder_P
    monkeypatch.setattr(js, '_ladder_P', lambda cur, step: 2 * orig(cur, step))
    js._build_cse_fn.cache_clear()
    try:
        padded = solve_jax_many(kernels)
    finally:
        js._build_cse_fn.cache_clear()
    for a, b in zip(base, padded):
        assert_pipelines_identical(a, b)


def test_r_in_partial_row_path_bit_identical(rng, monkeypatch):
    """The trimmed-row (R_in < P) resume path under a larger canonical
    bucket: a kernel big enough to resume across rungs must still be
    bit-identical when padded one grid rung up."""
    kernel = random_kernel(rng, 16, 12, 5)  # resumes past the first pow2 rung
    base = solve_jax_many([kernel])[0]
    orig = js._canon_dim
    monkeypatch.setattr(js, '_canon_dim', lambda x, lo=2: orig(orig(x, lo) + 1, lo))
    js._build_cse_fn.cache_clear()
    try:
        padded = solve_jax_many([kernel])[0]
    finally:
        js._build_cse_fn.cache_clear()
    assert_pipelines_identical(base, padded)


def test_heterogeneous_batch_matches_solo(rng):
    """A small matrix batched with a larger one of the SAME canonical
    (O, B) class (so its group n_in_max and lane bucket both grow) solves
    bit-identically to the solo solve."""
    small = random_kernel(rng, 6, 6, 4)  # O canon 8, B canon from 4-bit digits
    big = random_kernel(rng, 12, 7, 4)  # same canonical class, larger n_in
    solo = solve_jax_many([small])[0]
    batched = solve_jax_many([small, big])
    assert_pipelines_identical(solo, batched[0])
    np.testing.assert_array_equal(np.asarray(batched[1].kernel, np.float64), big)


def test_explicit_step_ladder_bit_identical(rng):
    """The legacy explicit-step rung policy and the default geometric
    ladder pause the resumable search at different rungs but decide
    identically (small sizes: the top-k cache is exact)."""
    from da4ml_tpu.cmvm.jax_search import _Lane, solve_single_lanes
    from da4ml_tpu.ir import QInterval

    kernel = random_kernel(rng, 8, 8, 5)
    qints = [QInterval(-128.0, 127.0, 1.0)] * 8

    def lane():
        return _Lane(kernel, list(qints), [0.0] * 8, 'wmc')

    (a,) = solve_single_lanes([lane()], -1, -1)
    (b,) = solve_single_lanes([lane()], -1, -1, step=8)
    assert len(a.ops) == len(b.ops)
    for oa, ob in zip(a.ops, b.ops):
        assert (oa.id0, oa.id1, oa.opcode, oa.data) == (ob.id0, ob.id1, ob.opcode, ob.data)
