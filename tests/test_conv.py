"""Convolution/pooling tracing: exactness vs direct numpy computation.

Oracle: integer-valued inputs on the quantization grid make the fixed-point
computation exactly equal to float64 numpy, so DAIS predict must match a
direct conv/pool reference bit for bit (reference test pattern:
tests/test_ops.py of calad0i/da4ml).
"""

import numpy as np
import pytest

from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace
from da4ml_tpu.trace.ops import avg_pool2d, conv1d, conv2d, max_pool2d


def _np_conv2d(x, w, strides=(1, 1), padding='valid', dilation=(1, 1)):
    kh, kw, cin, cout = w.shape
    sh, sw = strides
    dh, dw = dilation
    H, W, _ = x.shape
    if padding == 'same':
        from math import ceil

        def pad_amt(size, k, s, d):
            keff = (k - 1) * d + 1
            out = ceil(size / s)
            total = max((out - 1) * s + keff - size, 0)
            return total // 2, total - total // 2

        ph, pw = pad_amt(H, kh, sh, dh), pad_amt(W, kw, sw, dw)
        x = np.pad(x, (ph, pw, (0, 0)))
        H, W = x.shape[:2]
    Ho = (H - (kh - 1) * dh - 1) // sh + 1
    Wo = (W - (kw - 1) * dw - 1) // sw + 1
    out = np.zeros((Ho, Wo, cout))
    for ho in range(Ho):
        for wo in range(Wo):
            patch = x[ho * sh : ho * sh + kh * dh : dh, wo * sw : wo * sw + kw * dw : dw]
            out[ho, wo] = np.tensordot(patch, w, axes=([0, 1, 2], [0, 1, 2]))
    return out


def _traced_input(rng, shape, i_bits=3):
    inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(shape), np.full(shape, i_bits), np.zeros(shape, np.int64))
    data = rng.integers(-(2**i_bits), 2**i_bits, (32, *shape)).astype(np.float64)
    return inp, x, data


@pytest.mark.parametrize('padding', ['valid', 'same'])
@pytest.mark.parametrize('strides', [(1, 1), (2, 2)])
def test_conv2d(rng, padding, strides):
    shape = (6, 7, 2)
    inp, x, data = _traced_input(rng, shape)
    w = rng.integers(-4, 4, (3, 3, 2, 3)).astype(np.float64)
    y = conv2d(x, w, strides=strides, padding=padding)
    comb = comb_trace(inp, y)
    ref = np.stack([_np_conv2d(d, w, strides, padding) for d in data])
    out = comb.predict(data.reshape(len(data), -1), backend='numpy')
    np.testing.assert_array_equal(out, ref.reshape(len(data), -1))


def test_conv2d_dilation(rng):
    shape = (8, 8, 1)
    inp, x, data = _traced_input(rng, shape)
    w = rng.integers(-4, 4, (3, 3, 1, 2)).astype(np.float64)
    y = conv2d(x, w, dilation=(2, 2))
    comb = comb_trace(inp, y)
    ref = np.stack([_np_conv2d(d, w, dilation=(2, 2)) for d in data])
    out = comb.predict(data.reshape(len(data), -1), backend='numpy')
    np.testing.assert_array_equal(out, ref.reshape(len(data), -1))


@pytest.mark.parametrize('padding', ['valid', 'same'])
def test_conv1d(rng, padding):
    shape = (9, 2)
    inp, x, data = _traced_input(rng, shape)
    w = rng.integers(-4, 4, (3, 2, 4)).astype(np.float64)
    y = conv1d(x, w, stride=2, padding=padding)
    comb = comb_trace(inp, y)
    w2d = np.expand_dims(w, 0)  # reuse the 2d reference with H=1
    ref = np.stack([_np_conv2d(d[None], w2d, (1, 2), padding)[0] for d in data])
    out = comb.predict(data.reshape(len(data), -1), backend='numpy')
    np.testing.assert_array_equal(out, ref.reshape(len(data), -1))


def test_conv2d_jax_backend(rng):
    """Batched + deduplicated solve path: same result through backend='jax'."""
    shape = (5, 5, 1)
    inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, -1), solver_options={'backend': 'jax'})
    x = inp.quantize(np.ones(shape), np.full(shape, 3), np.zeros(shape, np.int64))
    w = rng.integers(-4, 4, (3, 3, 1, 2)).astype(np.float64)
    y = conv2d(x, w)
    comb = comb_trace(inp, y)
    data = rng.integers(-8, 8, (16, *shape)).astype(np.float64)
    ref = np.stack([_np_conv2d(d, w) for d in data])
    out = comb.predict(data.reshape(len(data), -1), backend='numpy')
    np.testing.assert_array_equal(out, ref.reshape(len(data), -1))


@pytest.mark.parametrize('padding', ['valid', 'same'])
def test_max_pool2d(rng, padding):
    shape = (5, 6, 2)
    inp, x, data = _traced_input(rng, shape)
    y = max_pool2d(x, (2, 2), padding=padding)
    comb = comb_trace(inp, y)
    outs = comb.predict(data.reshape(len(data), -1), backend='numpy')
    for d, o in zip(data, outs):
        Ho, Wo = y.shape[0], y.shape[1]
        ref = np.full((Ho, Wo, 2), -np.inf)
        for ho in range(Ho):
            for wo in range(Wo):
                ref[ho, wo] = d[ho * 2 : ho * 2 + 2, wo * 2 : wo * 2 + 2].reshape(-1, 2).max(axis=0)
        np.testing.assert_array_equal(o.reshape(Ho, Wo, 2), ref)


def test_avg_pool2d(rng):
    shape = (6, 6, 1)
    inp, x, data = _traced_input(rng, shape)
    y = avg_pool2d(x, (2, 2))
    comb = comb_trace(inp, y)
    outs = comb.predict(data.reshape(len(data), -1), backend='numpy')
    for d, o in zip(data, outs):
        ref = d.reshape(3, 2, 3, 2).mean(axis=(1, 3))
        np.testing.assert_array_equal(o.reshape(3, 3), ref)


def _np_depthwise2d(x, w, padding='valid'):
    kh, kw, cin, mult = w.shape
    cols = [_np_conv2d(x[..., c : c + 1], w[:, :, c : c + 1, :], padding=padding) for c in range(cin)]
    return np.concatenate(cols, axis=-1)


@pytest.mark.parametrize('backend', ['auto', 'jax'])
@pytest.mark.parametrize('padding', ['valid', 'same'])
def test_depthwise_conv2d(rng, padding, backend):
    """Per-channel CMVMs (batched into one device call on the jax backend)."""
    from da4ml_tpu.trace.ops import depthwise_conv2d

    shape = (5, 5, 3)
    inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, -1), solver_options={'backend': backend})
    x = inp.quantize(np.ones(shape), np.full(shape, 3), np.zeros(shape, np.int64))
    data = rng.integers(-8, 8, (16, *shape)).astype(np.float64)
    w = rng.integers(-4, 4, (3, 3, 3, 2)).astype(np.float64)
    comb = comb_trace(inp, depthwise_conv2d(x, w, padding=padding))
    out = comb.predict(data.reshape(len(data), -1), backend='numpy')
    ref = np.stack([_np_depthwise2d(d, w, padding=padding) for d in data])
    np.testing.assert_array_equal(out, ref.reshape(len(data), -1))
