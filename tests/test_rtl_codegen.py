"""Verilog codegen oracle chain: for each traced op, the emitted netlist —
parsed and executed by the bundled netlist simulator — must agree exactly
with the DAIS interpreter. Mirrors the reference's test_rtl_gen pattern
(tests/test_ops.py:72-86 in the reference tree) with the netlist simulator
standing in for Verilator when it is not installed.
"""

import json

import numpy as np
import pytest

from da4ml_tpu.codegen import RTLModel, VerilogModel
from da4ml_tpu.codegen.rtl.verilog.comb import VerilogCombEmitter
from da4ml_tpu.codegen.rtl.verilog.netlist_sim import simulate_comb
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace, to_pipeline
from test_trace_ops import CASES, N


def _trace(op_sym, seed=42):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 2, N)
    i = rng.integers(-2, 5, N)
    f = np.maximum(rng.integers(-2, 5, N), 1 - k - i)
    inp = FixedVariableArrayInput(N, hwconf=HWConfig(1, -1, -1))
    out = op_sym(inp.quantize(k, i, f))
    return comb_trace(inp, out)


@pytest.mark.parametrize('name', sorted(CASES))
def test_verilog_netlist_exact(name):
    op_sym, _ = CASES[name]
    comb = _trace(op_sym)
    data = np.random.default_rng(3).uniform(-8, 8, (128, N))
    golden = comb.predict(data, backend='numpy')
    np.testing.assert_array_equal(simulate_comb(comb, data=data), golden)


def test_verilog_lookup_chain():
    comb = _trace(lambda x: np.sin(x).quantize(np.ones(N), np.ones(N), np.full(N, 4)))
    data = np.random.default_rng(4).uniform(-8, 8, (64, N))
    np.testing.assert_array_equal(simulate_comb(comb, data=data), comb.predict(data, backend='numpy'))


@pytest.mark.parametrize('cutoff', [0.5, 1.0, 2.0])
def test_verilog_pipeline_stages_exact(cutoff):
    comb = _trace(CASES['matmul_int'][0])
    pipe = to_pipeline(comb, cutoff)
    data = np.random.default_rng(6).uniform(-8, 8, (64, N))
    cur = data
    for si, stage in enumerate(pipe.stages):
        ref = stage.predict(cur, backend='numpy')
        np.testing.assert_array_equal(simulate_comb(stage, name=f's{si}', data=cur), ref)
        cur = ref
    np.testing.assert_array_equal(cur, comb.predict(data, backend='numpy'))


@pytest.mark.parametrize('cutoff,register_layers', [(0.5, 1), (1.0, 1), (2.0, 2)])
def test_verilog_pipelined_top_exact(cutoff, register_layers):
    """The *registered* II=1 top module, executed with clocked semantics
    (one sample per rising edge, outputs read after the register latency),
    agrees bit-exactly with the interpreter — the streaming analog of the
    reference's Verilator `_inference` loop (reference
    codegen/rtl/common_source/binder_util.hh:11-40)."""
    from da4ml_tpu.codegen.rtl.verilog.netlist_sim import simulate_pipeline

    comb = _trace(CASES['matmul_int'][0])
    pipe = to_pipeline(comb, cutoff)
    assert len(pipe.stages) > 1, 'need a genuinely pipelined top'
    data = np.random.default_rng(7).uniform(-8, 8, (64, N))
    golden = comb.predict(data, backend='numpy')
    got = simulate_pipeline(pipe, data=data, register_layers=register_layers)
    np.testing.assert_array_equal(got, golden)


def test_verilog_pipelined_top_latency_ticks():
    """Register latency of the emitted top = (n_stages-1) * register_layers."""
    from da4ml_tpu.codegen.rtl.verilog.netlist_sim import VerilogPipelineSim
    from da4ml_tpu.codegen.rtl.verilog.pipeline import emit_pipeline

    comb = _trace(CASES['matmul_int'][0])
    pipe = to_pipeline(comb, 0.5)
    for layers in (1, 3):
        top, mem, stages = emit_pipeline(pipe, 'lat', register_layers=layers)
        sim = VerilogPipelineSim(top, stages, mem)
        assert sim.latency_ticks == (len(pipe.stages) - 1) * layers


def test_rtl_project_write(tmp_path):
    comb = _trace(CASES['matmul_frac'][0])
    pipe = to_pipeline(comb, 2.0)
    model = RTLModel(pipe, 'prj', tmp_path).write()
    src = tmp_path / 'src'
    assert (src / 'prj.v').exists()
    for si in range(len(pipe.stages)):
        assert (src / f'prj_s{si}.v').exists()
    assert (src / 'prj_wrapper.v').exists()
    assert (src / 'shift_adder.v').exists()
    meta = json.loads((tmp_path / 'metadata.json').read_text())
    assert meta['cost'] == pipe.cost
    assert meta['n_stages'] == len(pipe.stages)
    assert (tmp_path / 'binder' / 'binder.cc').exists()
    assert (tmp_path / 'binder' / 'Makefile').exists()
    assert (tmp_path / 'tcl' / 'build_vivado.tcl').exists()
    assert (tmp_path / 'constraints' / 'prj.xdc').exists()
    # IR round-trips from the project dump
    from da4ml_tpu.ir import Pipeline

    pipe2 = Pipeline.load(tmp_path / 'model' / 'pipeline.json')
    assert pipe2 == pipe
    data = np.random.default_rng(1).uniform(-8, 8, (32, N))
    np.testing.assert_array_equal(model.predict(data, backend='interp'), comb.predict(data, backend='numpy'))
    # the 'netlist' backend executes the emitted clocked top
    np.testing.assert_array_equal(model.predict(data, backend='netlist'), comb.predict(data, backend='numpy'))


def test_rtl_comb_project_write(tmp_path):
    comb = _trace(CASES['sum'][0])
    model = VerilogModel(comb, 'prj', tmp_path).write()
    assert (tmp_path / 'src' / 'prj.v').exists()
    assert (tmp_path / 'model' / 'comb.json').exists()
    text = (tmp_path / 'src' / 'prj.v').read_text()
    assert 'module prj (' in text and 'endmodule' in text
    assert model.latency_ticks == 0


@pytest.mark.skipif(not RTLModel.emulation_available(), reason='verilator not installed')
def test_rtl_verilator_emulation(tmp_path):
    comb = _trace(CASES['matmul_int'][0])
    model = RTLModel(to_pipeline(comb, 2.0), 'prj', tmp_path).write().compile()
    data = np.random.default_rng(2).uniform(-8, 8, (256, N))
    np.testing.assert_array_equal(model.predict(data, backend='emu'), comb.predict(data, backend='numpy'))


def test_mem_file_x_entries():
    comb = _trace(lambda x: np.sin(x).quantize(np.ones(N), np.ones(N), np.full(N, 4)))
    em = VerilogCombEmitter(comb, 'm')
    em.emit()
    assert em.mem_files, 'lookup op must emit a .mem file'
    for content in em.mem_files.values():
        lines = content.strip().splitlines()
        assert all(set(ln) <= set('0123456789abcdefx') for ln in lines)


def test_verilog_netlist_depthwise_conv():
    """New conv ops lower to codegen-able primitives: netlist sim == interp."""
    from da4ml_tpu.trace.ops import depthwise_conv2d, max_pool1d

    rng = np.random.default_rng(5)
    shape = (4, 4, 2)
    inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(shape), np.full(shape, 3), np.zeros(shape, np.int64))
    w = rng.integers(-4, 4, (2, 2, 2, 1)).astype(np.float64)
    y = depthwise_conv2d(x, w)  # [3, 3, 2]
    y = max_pool1d(y.reshape(9, 2), 3)  # reuse the spatial axis as a 1-d length
    comb = comb_trace(inp, y)
    data = rng.uniform(-8, 8, (64, int(np.prod(shape))))
    golden = comb.predict(data, backend='numpy')
    np.testing.assert_array_equal(simulate_comb(comb, data=data), golden)
