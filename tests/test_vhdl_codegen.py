"""VHDL codegen oracle chain: emitted VHDL netlists, executed by the bundled
VHDL netlist simulator, must agree exactly with the DAIS interpreter —
the GHDL-flavored twin of test_rtl_codegen.py.
"""

import numpy as np
import pytest

from da4ml_tpu.codegen import VHDLModel
from da4ml_tpu.codegen.rtl.vhdl.netlist_sim import simulate_comb_vhdl
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace, to_pipeline
from test_trace_ops import CASES, N


def _trace(op_sym, seed=42):
    rng = np.random.default_rng(seed)
    k = rng.integers(0, 2, N)
    i = rng.integers(-2, 5, N)
    f = np.maximum(rng.integers(-2, 5, N), 1 - k - i)
    inp = FixedVariableArrayInput(N, hwconf=HWConfig(1, -1, -1))
    return comb_trace(inp, op_sym(inp.quantize(k, i, f)))


DATA = np.random.default_rng(3).uniform(-8, 8, (64, N))


@pytest.mark.parametrize('name', sorted(CASES))
def test_vhdl_netlist_exact(name):
    comb = _trace(CASES[name][0])
    np.testing.assert_array_equal(simulate_comb_vhdl(comb, data=DATA), comb.predict(DATA, backend='numpy'))


def test_vhdl_lookup():
    comb = _trace(lambda x: np.sin(x).quantize(np.ones(N), np.ones(N), np.full(N, 4)))
    np.testing.assert_array_equal(simulate_comb_vhdl(comb, data=DATA), comb.predict(DATA, backend='numpy'))


def test_vhdl_solver_pipeline():
    from da4ml_tpu.cmvm import solve
    from da4ml_tpu.ir import QInterval

    rng = np.random.default_rng(7)
    kernel = rng.integers(-8, 8, (10, 6)).astype(np.float64)
    sol = solve(kernel, qintervals=[QInterval(-8, 7, 1)] * 10)
    x = rng.integers(-8, 8, (64, 10)).astype(np.float64)
    cur = x
    for si, stage in enumerate(sol.stages):
        ref = stage.predict(cur, backend='numpy')
        np.testing.assert_array_equal(simulate_comb_vhdl(stage, name=f's{si}', data=cur), ref)
        cur = ref
    np.testing.assert_array_equal(cur, x @ kernel)


@pytest.mark.parametrize('cutoff,register_layers', [(1.0, 1), (2.0, 2)])
def test_vhdl_pipelined_top_exact(cutoff, register_layers):
    """The registered VHDL top, executed clock-by-clock, == interpreter."""
    from da4ml_tpu.codegen.rtl.vhdl.netlist_sim import simulate_pipeline_vhdl

    comb = _trace(CASES['matmul_int'][0])
    pipe = to_pipeline(comb, cutoff)
    assert len(pipe.stages) > 1
    golden = comb.predict(DATA, backend='numpy')
    got = simulate_pipeline_vhdl(pipe, data=DATA, register_layers=register_layers)
    np.testing.assert_array_equal(got, golden)


def test_vhdl_project_write(tmp_path):
    comb = _trace(CASES['matmul_int'][0])
    pipe = to_pipeline(comb, 2.0)
    model = VHDLModel(pipe, 'vh', tmp_path).write()
    src = tmp_path / 'src'
    assert (src / 'vh.vhd').exists()
    assert (src / 'vh_wrapper.vhd').exists()
    assert (src / 'da4ml_util.vhd').exists()
    assert (src / 'shift_adder.vhd').exists()
    assert 'ghdl' in (tmp_path / 'binder' / 'Makefile').read_text().lower()
    np.testing.assert_array_equal(model.predict(DATA, backend='interp'), comb.predict(DATA, backend='numpy'))
    np.testing.assert_array_equal(model.predict(DATA, backend='netlist'), comb.predict(DATA, backend='numpy'))


@pytest.mark.skipif(not VHDLModel.emulation_available(), reason='verilator/ghdl not installed')
def test_vhdl_ghdl_emulation(tmp_path):
    comb = _trace(CASES['matmul_int'][0])
    model = VHDLModel(to_pipeline(comb, 2.0), 'vh', tmp_path).write().compile()
    np.testing.assert_array_equal(model.predict(DATA, backend='emu'), comb.predict(DATA, backend='numpy'))
