"""Multi-device tests on the virtual 8-device CPU mesh (conftest.py).

These exercise the framework's two parallel axes (SURVEY.md §2.6) for real:
sharded DAIS batch inference must stay bit-exact vs the numpy oracle, the
sharded candidate search must return exactly the same solutions as the
unsharded one, and the batch-padding helpers must place shards as promised.
Mirrors the sample/candidate parallelism of the reference's OpenMP paths
(dais/bindings.cc:58-96, cmvm/api.cc:208-238 of calad0i/da4ml).
"""

from __future__ import annotations

import os

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from da4ml_tpu.ir.dais_binary import decode
from da4ml_tpu.parallel import batch_sharding, default_mesh, pad_to_multiple, shard_batch
from da4ml_tpu.runtime.jax_backend import DaisExecutor
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

N_DEV = 8


@pytest.fixture(scope='module')
def mesh() -> Mesh:
    devices = np.asarray(jax.devices()[:N_DEV])
    assert devices.size == N_DEV, 'conftest must provide 8 virtual CPU devices'
    return Mesh(devices, ('batch',))


@pytest.fixture(scope='module')
def small_comb():
    rng = np.random.default_rng(7)
    inp = FixedVariableArrayInput(6, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(6), np.full(6, 3), np.full(6, 2))
    x = x @ rng.integers(-8, 8, (6, 5)).astype(np.float64)
    x = x.relu(i=np.full(5, 5), f=np.full(5, 2))
    x = x @ rng.integers(-4, 4, (5, 3)).astype(np.float64)
    return comb_trace(inp, x)


def test_pad_to_multiple():
    x = np.arange(10.0).reshape(10, 1)
    padded, n_pad = pad_to_multiple(x, N_DEV)
    assert padded.shape == (16, 1) and n_pad == 6
    np.testing.assert_array_equal(padded[:10], x)
    np.testing.assert_array_equal(padded[10:], 0)
    same, none = pad_to_multiple(np.zeros((16, 2)), N_DEV)
    assert same.shape == (16, 2) and none == 0


def test_shard_batch_placement(mesh):
    x = np.arange(20.0 * 3).reshape(20, 3)
    arr, n_pad = shard_batch(x, mesh)
    assert n_pad == 4 and arr.shape == (24, 3)
    assert isinstance(arr.sharding, NamedSharding)
    assert arr.sharding.spec == PartitionSpec('batch')
    shards = arr.addressable_shards
    assert len(shards) == N_DEV
    assert {s.data.shape for s in shards} == {(24 // N_DEV, 3)}
    # every device holds exactly one shard, and concatenation restores the batch
    assert len({s.device for s in shards}) == N_DEV
    back = np.concatenate([np.asarray(s.data) for s in sorted(shards, key=lambda s: s.index[0].start)])
    np.testing.assert_array_equal(back[:20], x)


def test_default_mesh_covers_all_devices():
    m = default_mesh()
    assert m.devices.size == len(jax.devices())
    assert m.axis_names == ('batch',)


def test_predict_sharded_bit_exact(mesh, small_comb):
    """Sharded inference == numpy oracle, including a non-divisible batch."""
    ex = DaisExecutor(decode(small_comb.to_binary()))
    rng = np.random.default_rng(0)
    for n in (N_DEV * 4, N_DEV * 2 + 3, 1):  # divisible, padded, single sample
        data = rng.uniform(-8, 8, (n, small_comb.shape[0]))
        out = ex.predict_sharded(data, mesh)
        ref = small_comb.predict(data, backend='numpy')
        assert out.shape == ref.shape
        np.testing.assert_array_equal(out, ref)


def test_solve_jax_many_sharded_matches_unsharded(mesh):
    """Mesh-sharded candidate search returns the same solutions as unsharded."""
    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    rng = np.random.default_rng(3)
    kernels = [rng.integers(-8, 8, (5, 5)).astype(np.float64) for _ in range(2 * N_DEV + 1)]
    plain = solve_jax_many(kernels)
    sharded = solve_jax_many(kernels, mesh=Mesh(np.asarray(jax.devices()[:N_DEV]), ('lanes',)))
    assert len(plain) == len(sharded) == len(kernels)
    for k, p, s in zip(kernels, plain, sharded):
        np.testing.assert_array_equal(np.asarray(s.kernel, np.float64), k)
        assert s.cost == p.cost
        assert s.latency == p.latency


def test_solve_jax_many_sharded_exactness_stress(mesh):
    """Sharded search over mixed shapes keeps the kernel-identity oracle."""
    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    rng = np.random.default_rng(11)
    shapes = [(3, 7), (7, 3), (6, 6), (4, 9), (9, 4), (5, 5), (8, 2), (2, 8), (6, 3)]
    kernels = [rng.integers(-16, 16, s).astype(np.float64) for s in shapes]
    lanes_mesh = Mesh(np.asarray(jax.devices()[:N_DEV]), ('lanes',))
    for k, s in zip(kernels, solve_jax_many(kernels, mesh=lanes_mesh)):
        np.testing.assert_array_equal(np.asarray(s.kernel, np.float64), k)


def test_batch_sharding_spec(mesh):
    sh = batch_sharding(mesh)
    assert sh.spec == PartitionSpec('batch')
    assert sh.mesh.axis_names == ('batch',)


def test_global_mesh_and_initialize_single_host():
    """Single-host behavior of the multi-host entry points: initialize()
    reports no multi-process runtime, global_mesh spans the local devices
    and drives a sharded solve exactly."""
    import numpy as np

    from da4ml_tpu.cmvm.jax_search import solve_jax_many
    from da4ml_tpu.parallel import global_mesh, initialize_distributed

    assert initialize_distributed() is False  # no coordinator configured
    mesh = global_mesh('lanes')
    assert mesh.devices.size == len(jax.devices())
    rng = np.random.default_rng(3)
    ks = [rng.integers(-8, 8, (6, 6)).astype(np.float64) for _ in range(4)]
    for k, s in zip(ks, solve_jax_many(ks, mesh=mesh)):
        np.testing.assert_array_equal(np.asarray(s.kernel, np.float64), k)


def test_predict_mesh_through_public_api(mesh, small_comb):
    """CombLogic.predict(mesh=...) == numpy golden (top-level multi-chip API)."""
    data = np.random.default_rng(0).uniform(-8, 8, (24, small_comb.shape[0]))
    golden = small_comb.predict(data, backend='numpy')
    np.testing.assert_array_equal(small_comb.predict(data, mesh=mesh), golden)
    with pytest.raises(ValueError, match='mesh'):
        small_comb.predict(data, backend='cpp', mesh=mesh)


def test_two_process_distributed_solve():
    """Two real OS processes form a JAX distributed runtime (CPU backend, 2
    virtual devices each), run a cross-process collective, and complete one
    mesh-sharded CMVM solve with lanes split across both — exercising
    initialize()/global_mesh() multi-host paths for real (VERDICT r2 item 7).
    """
    import socket
    import subprocess
    import sys
    from pathlib import Path

    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]

    worker = Path(__file__).parent / 'multiproc_worker.py'
    env = {k: v for k, v in os.environ.items() if k not in ('XLA_FLAGS', 'JAX_PLATFORMS', 'JAX_NUM_PROCESSES')}
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(port), str(rank)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=1200)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f'rank {rank} failed:\n{out[-3000:]}'
        assert f'RANK{rank} OK' in out, out[-2000:]
    # both processes must agree on the solution cost
    costs = {ln.split('cost=')[1].strip() for out in outs for ln in out.splitlines() if 'cost=' in ln}
    assert len(costs) == 1, costs
