"""Interpreter oracle chain on solver outputs:

float matmul golden == CombLogic float replay == numpy DAIS interpreter
== jitted JAX executor — all exact (assert_array_equal), mirroring the
reference's bit-exactness test pattern (tests/test_ops.py).
"""

import numpy as np
import pytest

from da4ml_tpu.cmvm import solve
from da4ml_tpu.ir import QInterval


def random_case(rng, n_in=6, n_out=5, bits=4):
    kernel = rng.integers(-(2 ** (bits - 1)), 2 ** (bits - 1), (n_in, n_out)).astype(np.float64)
    qints = [QInterval(-8.0, 7.0, 1.0)] * n_in
    sol = solve(kernel, qintervals=qints)
    x = rng.integers(-8, 8, (64, n_in)).astype(np.float64)
    return kernel, sol, x


def test_predict_matches_matmul(rng):
    kernel, sol, x = random_case(rng)
    golden = x @ kernel
    for stage_in, stage in zip([x, x @ sol.stages[0].kernel], sol.stages):
        out_np = stage.predict(stage_in, backend='numpy')
        np.testing.assert_array_equal(out_np, stage_in @ np.asarray(stage.kernel, np.float64))
    out = sol.predict(x, backend='numpy')
    np.testing.assert_array_equal(out, golden)


def test_replay_matches_predict(rng):
    _, sol, x = random_case(rng)
    stage = sol.stages[0]
    out_pred = stage.predict(x, backend='numpy')
    out_replay = np.stack([stage(row) for row in x])
    np.testing.assert_array_equal(out_pred, out_replay)


def test_jax_matches_numpy(rng):
    _, sol, x = random_case(rng)
    for stage in sol.stages:
        out_np = stage.predict(x, backend='numpy')
        out_jax = stage.predict(x, backend='jax')
        np.testing.assert_array_equal(out_np, out_jax)
        x = out_np


def test_binary_roundtrip(rng):
    from da4ml_tpu.ir.dais_binary import decode

    _, sol, _ = random_case(rng)
    stage = sol.stages[0]
    binary = stage.to_binary()
    prog = decode(binary)
    assert prog.n_in == stage.shape[0]
    assert prog.n_out == stage.shape[1]
    assert prog.n_ops == len(stage.ops)
    prog.validate()


def test_json_roundtrip(tmp_path, rng):
    _, sol, x = random_case(rng)
    path = tmp_path / 'pipeline.json'
    sol.save(path)
    from da4ml_tpu.ir import Pipeline

    sol2 = Pipeline.load(path)
    assert sol2 == sol
    np.testing.assert_array_equal(sol.predict(x, backend='numpy'), sol2.predict(x, backend='numpy'))


@pytest.mark.parametrize('seed', [0, 1, 2])
def test_fuzz_bits_shapes(seed):
    rng = np.random.default_rng(seed)
    n_in = int(rng.integers(2, 12))
    n_out = int(rng.integers(1, 12))
    bits = int(rng.integers(2, 7))
    kernel = rng.integers(-(2**bits), 2**bits, (n_in, n_out)).astype(np.float64)
    qb = int(rng.integers(2, 6))
    qints = [QInterval(-(2.0 ** (qb - 1)), 2.0 ** (qb - 1) - 1, 1.0)] * n_in
    sol = solve(kernel, qintervals=qints)
    x = rng.integers(-(2 ** (qb - 1)), 2 ** (qb - 1), (32, n_in)).astype(np.float64)
    golden = x @ kernel
    np.testing.assert_array_equal(sol.predict(x, backend='numpy'), golden)
    np.testing.assert_array_equal(sol.stages[0].predict(x, backend='jax'), x @ np.asarray(sol.stages[0].kernel, np.float64))


def test_scan_executor_matches_unrolled(rng):
    """The lax.scan interpreter (compile-time fallback for huge programs) is
    bit-exact with the unrolled jaxpr executor."""
    import numpy as np

    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.runtime.jax_backend import DaisExecutor
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    inp = FixedVariableArrayInput((8,), hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(8), np.full(8, 4), np.full(8, 1))
    w = rng.integers(-8, 8, (8, 5)).astype(np.float64)
    y = np.sin(x[:4]).quantize(np.ones(4), np.ones(4), np.full(4, 6))
    z = (x @ w).relu()
    out = np.concatenate([z, y, abs(x[:2]), x[:2] & x[2:4]])
    comb = comb_trace(inp, out)

    prog = decode(comb.to_binary())
    data = rng.uniform(-16, 16, (64, 8))
    ref = DaisExecutor(prog, mode='unroll')(data)
    got = DaisExecutor(prog, mode='scan')(data)
    np.testing.assert_array_equal(got, ref)
    np.testing.assert_array_equal(ref, comb.predict(data, backend='numpy'))


def test_scan_executor_i64(rng):
    """Wide programs (int64 path) run in scan mode (x64 index dtypes)."""
    import numpy as np

    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.runtime.jax_backend import DaisExecutor
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    inp = FixedVariableArrayInput((6,), hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(6), np.full(6, 20), np.full(6, 4))
    w = rng.integers(-(2**10), 2**10, (6, 3)).astype(np.float64)
    comb = comb_trace(inp, x @ w)
    prog = decode(comb.to_binary())
    data = rng.uniform(-(2**19), 2**19, (32, 6))
    ex_scan = DaisExecutor(prog, mode='scan')
    assert ex_scan.use_i64, 'test requires the int64 path'
    ref = DaisExecutor(prog, mode='unroll')(data)
    np.testing.assert_array_equal(ex_scan(data), ref)


def test_packed_io_plan_and_roundtrip():
    """The packed host<->device inference boundary is bit-exact and the width
    analysis picks narrow lanes for narrow programs."""
    import numpy as np

    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.runtime.jax_backend import DaisExecutor
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    rng = np.random.default_rng(12)
    inp = FixedVariableArrayInput(6, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(6), np.full(6, 2), np.full(6, 1))
    w = rng.integers(-4, 4, (6, 3)).astype(np.float64)
    comb = comb_trace(inp, (x @ w).relu(i=np.full(3, 5), f=np.full(3, 1)))
    ex = DaisExecutor(decode(comb.to_binary()))
    assert ex._in_group in (2, 4) and ex._out_group in (2, 4)  # narrow lanes packed
    data = rng.uniform(-4, 4, (64, 6))
    np.testing.assert_array_equal(ex(data), comb.predict(data, backend='numpy'))


def test_chunked_overlap_bit_exact(rng, monkeypatch):
    """The overlapped chunked inference path (large batches / env override)
    is bit-identical to the monolithic device call and the numpy oracle."""
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    inp = FixedVariableArrayInput(6, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(6), np.full(6, 3), np.full(6, 3))
    w = rng.integers(-8, 8, (6, 4)).astype(np.float64)
    comb = comb_trace(inp, (x @ w).relu(i=np.full(4, 6), f=np.full(4, 3)))
    data = rng.uniform(-8, 8, (1000, 6))  # not divisible by the chunk count
    golden = comb.predict(data, backend='numpy')
    mono = comb.predict(data, backend='jax')
    monkeypatch.setenv('DA4ML_JAX_INFER_CHUNKS', '7')
    chunked = comb.predict(data, backend='jax')
    np.testing.assert_array_equal(mono, golden)
    np.testing.assert_array_equal(chunked, golden)
