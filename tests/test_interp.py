"""Interpreter oracle chain on solver outputs:

float matmul golden == CombLogic float replay == numpy DAIS interpreter
== jitted JAX executor — all exact (assert_array_equal), mirroring the
reference's bit-exactness test pattern (tests/test_ops.py).
"""

import numpy as np
import pytest

from da4ml_tpu.cmvm import solve
from da4ml_tpu.ir import CombLogic, QInterval


def random_case(rng, n_in=6, n_out=5, bits=4):
    kernel = rng.integers(-(2 ** (bits - 1)), 2 ** (bits - 1), (n_in, n_out)).astype(np.float64)
    qints = [QInterval(-8.0, 7.0, 1.0)] * n_in
    sol = solve(kernel, qintervals=qints)
    x = rng.integers(-8, 8, (64, n_in)).astype(np.float64)
    return kernel, sol, x


def test_predict_matches_matmul(rng):
    kernel, sol, x = random_case(rng)
    golden = x @ kernel
    for stage_in, stage in zip([x, x @ sol.stages[0].kernel], sol.stages):
        out_np = stage.predict(stage_in, backend='numpy')
        np.testing.assert_array_equal(out_np, stage_in @ np.asarray(stage.kernel, np.float64))
    out = sol.predict(x, backend='numpy')
    np.testing.assert_array_equal(out, golden)


def test_replay_matches_predict(rng):
    _, sol, x = random_case(rng)
    stage = sol.stages[0]
    out_pred = stage.predict(x, backend='numpy')
    out_replay = np.stack([stage(row) for row in x])
    np.testing.assert_array_equal(out_pred, out_replay)


def test_jax_matches_numpy(rng):
    _, sol, x = random_case(rng)
    for stage in sol.stages:
        out_np = stage.predict(x, backend='numpy')
        out_jax = stage.predict(x, backend='jax')
        np.testing.assert_array_equal(out_np, out_jax)
        x = out_np


def test_binary_roundtrip(rng):
    from da4ml_tpu.ir.dais_binary import decode

    _, sol, _ = random_case(rng)
    stage = sol.stages[0]
    binary = stage.to_binary()
    prog = decode(binary)
    assert prog.n_in == stage.shape[0]
    assert prog.n_out == stage.shape[1]
    assert prog.n_ops == len(stage.ops)
    prog.validate()


def test_json_roundtrip(tmp_path, rng):
    _, sol, x = random_case(rng)
    path = tmp_path / 'pipeline.json'
    sol.save(path)
    from da4ml_tpu.ir import Pipeline

    sol2 = Pipeline.load(path)
    assert sol2 == sol
    np.testing.assert_array_equal(sol.predict(x, backend='numpy'), sol2.predict(x, backend='numpy'))


@pytest.mark.parametrize('seed', [0, 1, 2])
def test_fuzz_bits_shapes(seed):
    rng = np.random.default_rng(seed)
    n_in = int(rng.integers(2, 12))
    n_out = int(rng.integers(1, 12))
    bits = int(rng.integers(2, 7))
    kernel = rng.integers(-(2**bits), 2**bits, (n_in, n_out)).astype(np.float64)
    qb = int(rng.integers(2, 6))
    qints = [QInterval(-(2.0 ** (qb - 1)), 2.0 ** (qb - 1) - 1, 1.0)] * n_in
    sol = solve(kernel, qintervals=qints)
    x = rng.integers(-(2 ** (qb - 1)), 2 ** (qb - 1), (32, n_in)).astype(np.float64)
    golden = x @ kernel
    np.testing.assert_array_equal(sol.predict(x, backend='numpy'), golden)
    np.testing.assert_array_equal(sol.stages[0].predict(x, backend='jax'), x @ np.asarray(sol.stages[0].kernel, np.float64))
