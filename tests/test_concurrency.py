"""Concurrency soundness plane: static lock/thread lint, the runtime
lock-order tracer, and the knob/metric catalog drift gates."""

import pytest

from da4ml_tpu._cli import main as cli_main
from da4ml_tpu.analysis.catalogs import (
    KNOBS,
    lint_catalogs,
    lint_knobs,
    lint_metrics,
    render_knob_table,
    scan_metrics,
)
from da4ml_tpu.analysis.concurrency import _scan_source, lint_concurrency
from da4ml_tpu.reliability import locktrace
from da4ml_tpu.reliability.locktrace import THREAD_TABLE, ThreadSpec


@pytest.fixture
def tracer():
    """Armed, clean lock tracer; restores the prior armed state."""
    was = locktrace.locktrace_enabled()
    locktrace.enable_locktrace()
    locktrace.reset_locktrace()
    yield locktrace
    locktrace.reset_locktrace()
    if not was:
        locktrace.disable_locktrace()


def _rules(result):
    return [d.rule for d in result.diagnostics]


# -- static lint -------------------------------------------------------------


def test_repo_is_clean():
    result = lint_concurrency()
    assert result.ok, result.format_text()


def test_raw_lock_construction_flagged():
    s = _scan_source('da4ml_tpu/serve/engine.py', 'import threading\n_lock = threading.Lock()\n')
    assert any(d.rule == 'X501' for d in s.diags)


def test_unregistered_make_lock_name_flagged():
    s = _scan_source('da4ml_tpu/serve/engine.py', "from ..reliability.locktrace import make_lock\n_l = make_lock('no.such.lock')\n")
    assert any(d.rule == 'X501' and 'no.such.lock' in d.message for d in s.diags)


def test_make_lock_outside_owning_module_flagged():
    s = _scan_source('da4ml_tpu/serve/engine.py', "_l = make_lock('serve.queue')\n")
    assert any(d.rule == 'X501' and 'serve.queue' in d.message for d in s.diags)


def test_lexical_rank_inversion_flagged():
    # breaker.py owns the registry lock (rank 60) and the instance lock
    # (rank 65): acquiring the registry inside the instance descends rank
    src = 'def f(self):\n    with self._lock:\n        with _registry_lock:\n            pass\n'
    s = _scan_source('da4ml_tpu/reliability/breaker.py', src)
    assert any(d.rule == 'X503' for d in s.diags)
    ascending = 'def f(self):\n    with _registry_lock:\n        with self._lock:\n            pass\n'
    assert not _scan_source('da4ml_tpu/reliability/breaker.py', ascending).diags


def test_io_under_lock_flagged():
    src = 'import time\n\ndef f(self):\n    with self._lock:\n        time.sleep(1.0)\n'
    s = _scan_source('da4ml_tpu/reliability/breaker.py', src)
    assert any(d.rule == 'X504' for d in s.diags)
    # serve.fleet.slots declares io_ok: the same shape passes there
    assert not any(
        d.rule == 'X504' for d in _scan_source('da4ml_tpu/serve/fleet.py', src).diags
    )


def test_unnamed_thread_flagged():
    s = _scan_source('da4ml_tpu/serve/engine.py', 'import threading\nt = threading.Thread(target=print)\n')
    assert any(d.rule == 'X505' for d in s.diags)


def test_unknown_thread_prefix_flagged():
    src = "import threading\nt = threading.Thread(target=print, name='rogue-worker-1')\n"
    s = _scan_source('da4ml_tpu/serve/engine.py', src)
    assert any(d.rule == 'X505' and 'rogue-worker' in d.message for d in s.diags)


def test_daemon_thread_without_shutdown_flagged():
    THREAD_TABLE['da4ml-x507fixture-'] = ThreadSpec('da4ml-x507fixture-', 'da4ml_tpu/foo.py', '', 'fixture')
    try:
        src = "import threading\nt = threading.Thread(target=print, name='da4ml-x507fixture-0', daemon=True)\n"
        s = _scan_source('da4ml_tpu/foo.py', src)
        assert any(d.rule == 'X507' for d in s.diags)
    finally:
        del THREAD_TABLE['da4ml-x507fixture-']


# -- runtime tracer ----------------------------------------------------------


def test_make_lock_rejects_unregistered_name():
    with pytest.raises(KeyError):
        locktrace.make_lock('definitely.not.registered')


def test_injected_rank_inversion_caught(tracer):
    low = tracer.make_lock('reliability.breaker.registry')  # rank 60
    high = tracer.make_lock('reliability.breaker.instance')  # rank 65
    with high:
        with low:  # descends 65 -> 60
            pass
    violations = tracer.locktrace_violations()
    assert any(v['rule'] == 'X511' for v in violations), violations
    diags = tracer.locktrace_diagnostics()
    assert any(d.rule == 'X511' for d in diags)
    assert tracer.locktrace_counters()['rank_inversions'] >= 1


def test_injected_order_cycle_caught(tracer):
    a = tracer.make_lock('reliability.breaker.registry')
    b = tracer.make_lock('reliability.breaker.instance')
    with a:
        with b:
            pass
    with b:
        with a:  # closes the a->b->a cycle
            pass
    assert any(v['rule'] == 'X510' for v in tracer.locktrace_violations())
    assert tracer.locktrace_counters()['cycles'] >= 1


def test_clean_nesting_records_no_violations(tracer):
    a = tracer.make_lock('reliability.breaker.registry')
    b = tracer.make_lock('reliability.breaker.instance')
    with a:
        with b:
            pass
    assert tracer.locktrace_violations() == []
    counters = tracer.locktrace_counters()
    assert counters['acquires'] >= 2 and counters['edges'] >= 1


def test_locktrace_report_feeds_statusz(tracer):
    from da4ml_tpu.telemetry.obs.health import status_snapshot

    with tracer.make_lock('reliability.breaker.registry'):
        pass
    section = status_snapshot()['locktrace']
    assert section is not None and section['acquires'] >= 1
    assert section['violations'] == []


# -- catalog drift gates -----------------------------------------------------


def test_catalogs_are_clean():
    result = lint_catalogs()
    assert result.ok, result.format_text()


def test_undocumented_knob_flagged(tmp_path):
    pkg = tmp_path / 'da4ml_tpu'
    pkg.mkdir()
    (pkg / 'mod.py').write_text("import os\nX = os.environ.get('DA4ML_BOGUS_FIXTURE')\n")
    result = lint_knobs(pkg)
    assert any(d.rule == 'X524' and 'DA4ML_BOGUS_FIXTURE' in d.message for d in result.diagnostics)
    # every real knob is absent from the fixture tree -> stale
    assert any(d.rule == 'X525' and 'DA4ML_LOCKTRACE' in d.message for d in result.diagnostics)


def test_undocumented_metric_flagged(tmp_path):
    pkg = tmp_path / 'da4ml_tpu'
    pkg.mkdir()
    (pkg / 'mod.py').write_text("from . import telemetry\ntelemetry.counter('not.in.catalog').inc()\n")
    result = lint_metrics(pkg, docs_root=tmp_path)
    assert any(d.rule == 'X520' and 'not.in.catalog' in d.message for d in result.diagnostics)


def test_unregistered_dynamic_metric_flagged(tmp_path):
    pkg = tmp_path / 'da4ml_tpu'
    pkg.mkdir()
    (pkg / 'mod.py').write_text("from . import telemetry\ntelemetry.counter(f'thing.{x}').inc()\n")
    result = lint_metrics(pkg, docs_root=tmp_path)
    assert any(d.rule == 'X522' for d in result.diagnostics)


def test_conditional_metric_names_are_scanned():
    # counter('a' if p else 'b') must contribute BOTH literals, not slip
    # through as unscannable (the store.hits/store.misses emission shape)
    literal, _ = scan_metrics()
    assert 'store.misses' in literal and 'store.hits' in literal


def test_metric_fold_maps_variants_to_family():
    from da4ml_tpu.telemetry.catalog import METRICS, fold_family

    assert fold_family('run.mode.fused_ir') == 'run.mode'
    assert fold_family('breaker.state.cmvm.jax') == 'breaker.state'
    assert fold_family('serve.requests') == 'serve.requests'
    assert 'run.mode' in METRICS and 'breaker.state' in METRICS


def test_openmetrics_help_comes_from_catalog():
    from da4ml_tpu.telemetry.catalog import METRICS
    from da4ml_tpu.telemetry.obs.openmetrics import render_openmetrics, validate_openmetrics

    text = render_openmetrics({'solve.calls': {'type': 'counter', 'value': 3.0}})
    validate_openmetrics(text)
    assert f'# HELP da4ml_solve_calls {METRICS["solve.calls"]}' in text


def test_knob_table_renders_every_knob():
    table = render_knob_table()
    for name in KNOBS:
        assert f'`{name}`' in table
    assert table.count('\n') == len(KNOBS) + 1  # header + separator


def test_docgen_sections_in_sync():
    from da4ml_tpu.analysis.docgen import apply

    assert apply(check=True) == []


def test_cli_verify_concurrency(capsys):
    assert cli_main(['verify', '--concurrency']) == 0
    out = capsys.readouterr().out
    assert 'concurrency: ok' in out
    assert cli_main(['verify', '--concurrency', '--json']) == 0
    import json

    report = json.loads(capsys.readouterr().out)
    assert report['ok'] is True and 'locktrace' in report
