"""Resilient serving layer (docs/serving.md): batching, admission control,
deadlines, degradation, drain/reload races, and graceful shutdown.

Covers the ISSUE-8 acceptance surface:

- canonical-grid padding is bit-identical through ``DaisExecutor.__call__``
  (the ``parallel.shapes`` satellite);
- executor input validation raises the typed reliability taxonomy
  (``InvalidInputError``) — the serve plane maps it to HTTP 400;
- bounded admission with both shed policies, Retry-After backpressure,
  and the 10× overload burst (hard ceiling, no deadlock, no lost work);
- per-request deadlines rejected *before* dispatch;
- breaker trip → bit-exact fallback serving → recovery without restart;
- drain/reload races: in-flight work completes during drain, hot reload
  drops nothing, and a SIGTERM'd serve process exits 0 with zero lost
  accepted requests;
- /healthz + /statusz + OpenMetrics serve-plane integration.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from da4ml_tpu import telemetry
from da4ml_tpu.parallel.shapes import canon_dim, grid_rungs, next_pow2, pad_rows
from da4ml_tpu.reliability.breaker import breaker_for, reset_all_breakers
from da4ml_tpu.reliability.errors import InvalidInputError, classify
from da4ml_tpu.reliability.faults import fault_injection
from da4ml_tpu.runtime.numpy_backend import run_binary as np_run_binary
from da4ml_tpu.serve import (
    DeadlineExpired,
    Draining,
    ModelNotFound,
    ModelUnavailable,
    QueueFull,
    ServeConfig,
    ServeEngine,
)
from da4ml_tpu.serve.batching import AdmissionQueue, InferRequest
from da4ml_tpu.serve.loadgen import burst, closed_loop, engine_infer_fn, http_infer_fn, make_request_pool

REPO = Path(__file__).resolve().parent.parent
FIXTURE = REPO / 'examples' / 'kernels' / 'cmvm_pipeline.json'


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    monkeypatch.delenv('DA4ML_FAULT_INJECT', raising=False)
    reset_all_breakers()
    telemetry.reset()
    yield
    reset_all_breakers()
    telemetry.reset()


@pytest.fixture(scope='module')
def model():
    """One deterministic solved model shared by the module (host solve)."""
    from da4ml_tpu.cmvm import solve

    rng = np.random.default_rng(7)
    pipe = solve(rng.integers(-8, 8, (8, 6)).astype(np.float64), backend='cpu')
    return pipe


@pytest.fixture(scope='module')
def binaries(model):
    return [s.to_binary() for s in model.stages]


def oracle_fn(binaries):
    def oracle(x):
        out = np.asarray(x, dtype=np.float64)
        for b in binaries:
            out = np_run_binary(b, out)
        return out

    return oracle


def make_engine(model, **cfg):
    defaults = dict(
        max_batch_rows=16,
        max_latency_ms=1.0,
        queue_cap_rows=64,
        breaker_threshold=3,
        breaker_reset_s=0.4,
        prewarm=False,
        default_deadline_ms=5000.0,
    )
    defaults.update(cfg)
    engine = ServeEngine(ServeConfig(**defaults))
    engine.load_model('m', model)
    return engine


# ---------------------------------------------------------------------------
# satellite: canonical grid shared helper + padded bit-identity
# ---------------------------------------------------------------------------


def test_canon_dim_matches_cmvm_scheduler():
    from da4ml_tpu.cmvm.jax_search import _canon_dim

    for x in range(1, 600):
        assert _canon_dim(x) == canon_dim(x, lo=2, even=True)
        assert _canon_dim(x, lo=8) == canon_dim(x, lo=8, even=True)
    # even grid: odd 3*2^0 / 5*2^0 rungs excluded
    assert canon_dim(3, even=True) == 4 and canon_dim(3, lo=1, even=False) == 3
    assert canon_dim(5, even=True) == 6 and canon_dim(5, lo=1, even=False) == 5
    assert next_pow2(7) == 8 and next_pow2(1) == 1


def test_grid_rungs_cover_every_batch_size():
    rungs = grid_rungs(64)
    assert rungs[0] == 1 and rungs[-1] == 64
    for n in range(1, 65):
        assert canon_dim(n, lo=1, even=False) in rungs
    # the ladder stays logarithmic, not linear
    assert len(rungs) < 20


def test_padded_batch_bit_identical_through_executor(binaries):
    from da4ml_tpu.runtime.jax_backend import DaisExecutor
    from da4ml_tpu.ir.dais_binary import decode

    ex = DaisExecutor(decode(binaries[0]))
    rng = np.random.default_rng(3)
    for n in (1, 3, 5, 7, 11, 13):
        x = np.round(rng.uniform(-4, 4, (n, ex.prog.n_in)) * 16) / 16
        xp, kept = pad_rows(x)
        assert kept == n and xp.shape[0] == canon_dim(n, lo=1, even=False)
        exact = ex(x)
        padded = ex(xp)[:n]
        np.testing.assert_array_equal(padded, exact)
        np.testing.assert_array_equal(exact, np_run_binary(binaries[0], x))


# ---------------------------------------------------------------------------
# satellite: typed input validation (400s, not 500s)
# ---------------------------------------------------------------------------


def test_executor_input_validation_taxonomy(binaries):
    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.runtime.jax_backend import DaisExecutor

    ex = DaisExecutor(decode(binaries[0]))
    n_in = ex.prog.n_in
    with pytest.raises(InvalidInputError, match='feature width'):
        ex(np.zeros((4, n_in + 2)))
    with pytest.raises(InvalidInputError, match='2-D'):
        ex(np.zeros(n_in))
    with pytest.raises(InvalidInputError, match='2-D'):
        ex(np.zeros((2, 2, n_in)))
    bad = np.zeros((3, n_in))
    bad[1, 0] = np.nan
    with pytest.raises(InvalidInputError, match='non-finite'):
        ex(bad)
    bad[1, 0] = np.inf
    with pytest.raises(InvalidInputError, match='non-finite'):
        ex(bad)
    with pytest.raises(InvalidInputError, match='not a numeric array'):
        ex([[1, 'x']])
    # classified fatal: a malformed request must not trigger backend fallback
    assert classify(InvalidInputError('x')) == 'fatal'
    assert isinstance(InvalidInputError('x'), ValueError)


# ---------------------------------------------------------------------------
# admission queue + shed policies
# ---------------------------------------------------------------------------


def _req(rows=1, deadline_s=None, n_in=4):
    return InferRequest(np.zeros((rows, n_in)), deadline_s)


def test_admission_queue_reject_newest():
    q = AdmissionQueue(cap_rows=4, policy='reject-newest')
    q.push(_req(2))
    q.push(_req(2))
    with pytest.raises(QueueFull) as ei:
        q.push(_req(1))
    assert ei.value.retry_after_s is not None and ei.value.http_status == 429
    assert q.depth_rows() == 4 and q.shed_total == 1


def test_admission_queue_deadline_edf_evicts_slack():
    q = AdmissionQueue(cap_rows=2, policy='deadline-edf')
    lazy = _req(1, deadline_s=60.0)
    lazier = _req(1, deadline_s=120.0)
    q.push(lazy)
    q.push(lazier)
    urgent = _req(1, deadline_s=0.5)
    q.push(urgent)  # evicts the laziest queued request
    assert lazier.finished
    with pytest.raises(QueueFull):
        lazier.result(0)
    # service order is earliest-deadline-first
    batch = q.take_batch(max_rows=8, window_s=0.0, stop=threading.Event())
    assert [r.id for r in batch] == [urgent.id, lazy.id]
    # an arrival no more urgent than every queued request is itself shed
    q2 = AdmissionQueue(cap_rows=1, policy='deadline-edf')
    q2.push(_req(1, deadline_s=0.2))
    with pytest.raises(QueueFull):
        q2.push(_req(1, deadline_s=10.0))


def test_take_batch_respects_row_budget():
    q = AdmissionQueue(cap_rows=64, policy='reject-newest')
    for _ in range(5):
        q.push(_req(3))
    batch = q.take_batch(max_rows=8, window_s=0.0, stop=threading.Event())
    assert sum(r.n_rows for r in batch) == 6  # 3+3 fits, a third would overshoot
    batch2 = q.take_batch(max_rows=8, window_s=0.0, stop=threading.Event())
    assert sum(r.n_rows for r in batch2) == 6
    assert q.depth_requests() == 1


def test_oversized_request_rejected(model):
    engine = make_engine(model, max_batch_rows=8)
    try:
        with pytest.raises(InvalidInputError, match='split the batch'):
            engine.submit('m', np.zeros((9, 8)))
        with pytest.raises(ModelNotFound):
            engine.submit('nope', np.zeros((1, 8)))
    finally:
        engine.close()


# ---------------------------------------------------------------------------
# request path: bit-exactness, deadlines, degradation
# ---------------------------------------------------------------------------


def test_coalesced_batches_bit_exact(model, binaries):
    engine = make_engine(model, max_latency_ms=5.0)
    oracle = oracle_fn(binaries)
    try:
        pool = make_request_pool(oracle, 8, rows_choices=(1, 2, 3), pool=12)
        reqs = [engine.submit('m', x) for x, _ in pool]
        for (x, y_exp), r in zip(pool, reqs):
            np.testing.assert_array_equal(r.result(30.0), y_exp)
            assert r.served_by == 'jax'
        snap = telemetry.metrics_snapshot()
        # coalescing happened: fewer batches than requests
        if snap:
            assert snap.get('serve.batches', {}).get('value', 0) <= len(reqs)
    finally:
        engine.close()


def test_warm_engine_has_no_shape_miss(model, binaries):
    telemetry.enable(metrics=True)
    engine = make_engine(model, prewarm=True, max_batch_rows=8)
    oracle = oracle_fn(binaries)
    try:
        pool = make_request_pool(oracle, 8, rows_choices=(1, 2, 3, 4), pool=16)
        for x, y_exp in pool:
            np.testing.assert_array_equal(engine.infer('m', x, deadline_s=30.0), y_exp)
        snap = telemetry.metrics_snapshot()
        assert snap.get('serve.shape_miss', {}).get('value', 0) == 0
        assert snap.get('serve.shape_hit', {}).get('value', 0) >= 1
        assert engine._state('m').warm_rows == set(grid_rungs(8))
    finally:
        engine.close()


def test_deadline_expired_rejected_before_dispatch(model):
    # a long coalescing window guarantees the deadline fires while queued
    engine = make_engine(model, max_latency_ms=300.0)
    try:
        req = engine.submit('m', np.zeros((1, 8)), deadline_s=0.05)
        with pytest.raises(DeadlineExpired) as ei:
            req.result(5.0)
        assert ei.value.http_status == 504
        snap = telemetry.metrics_snapshot()
        if snap:
            assert snap.get('serve.deadline_miss', {}).get('value', 0) >= 1
    finally:
        engine.close()


def test_breaker_trip_falls_back_bit_exact_then_recovers(model, binaries):
    engine = make_engine(model, max_latency_ms=0.5)
    oracle = oracle_fn(binaries)
    pool = make_request_pool(oracle, 8, pool=8)
    try:
        with fault_injection('serve.dispatch=error:4'):
            for i in range(5):
                x, y_exp = pool[i % len(pool)]
                np.testing.assert_array_equal(engine.infer('m', x, deadline_s=30.0), y_exp)
        br = breaker_for('serve.m')
        assert br.state in ('open', 'half-open')
        assert engine.health_doc()['status'] == 'degraded'
        # cooldown elapses; the half-open probe closes the breaker in place
        time.sleep(0.45)
        x, y_exp = pool[0]
        np.testing.assert_array_equal(engine.infer('m', x, deadline_s=30.0), y_exp)
        assert br.state == 'closed'
        assert engine.health_doc()['status'] == 'ok'
        snap = telemetry.metrics_snapshot()
        if snap:
            assert snap.get('serve.degraded', {}).get('value', 0) >= 1
    finally:
        engine.close()


def test_degraded_shed_mode_returns_structured_503(model):
    engine = make_engine(model, degraded='shed', breaker_reset_s=30.0)
    try:
        with fault_injection('serve.dispatch=error:3'):
            for _ in range(3):
                engine.infer('m', np.zeros((1, 8)), deadline_s=10.0)  # served via per-batch fallback
        assert breaker_for('serve.m').state == 'open'
        with pytest.raises(ModelUnavailable) as ei:
            engine.infer('m', np.zeros((1, 8)), deadline_s=10.0)
        assert ei.value.http_status == 503 and ei.value.retry_after_s is not None
    finally:
        engine.close()


def test_hedged_dispatch_bit_exact(model, binaries):
    engine = make_engine(model, hedge_ms=5.0, max_latency_ms=0.5)
    oracle = oracle_fn(binaries)
    pool = make_request_pool(oracle, 8, pool=4)
    try:
        # a slow device batch: the hedge races the fallback chain and wins
        with fault_injection('serve.dispatch=sleep:1:0.5'):
            x, y_exp = pool[0]
            np.testing.assert_array_equal(engine.infer('m', x, deadline_s=30.0), y_exp)
        snap = telemetry.metrics_snapshot()
        if snap:
            assert snap.get('serve.hedge_fired', {}).get('value', 0) >= 1
        # healthy path unaffected
        x, y_exp = pool[1]
        np.testing.assert_array_equal(engine.infer('m', x, deadline_s=30.0), y_exp)
    finally:
        engine.close()


# ---------------------------------------------------------------------------
# overload: the 10x burst holds the ceiling
# ---------------------------------------------------------------------------


def test_burst_10x_bounded_no_deadlock(model, binaries):
    engine = make_engine(model, queue_cap_rows=16, max_batch_rows=8, max_latency_ms=0.5)
    oracle = oracle_fn(binaries)
    pool = make_request_pool(oracle, 8, rows_choices=(1, 2), pool=16)
    try:
        rep = burst(engine_infer_fn(engine, 'm'), pool, n_requests=160, deadline_ms=5000.0, timeout_s=60.0)
        assert rep['resolved_all'] and rep['hung_requests'] == 0
        assert rep['mismatches'] == 0 and rep['errors'] == 0
        assert rep['shed'] > 0  # the ceiling actually engaged
        assert rep['ok'] + rep['bounded_rejections'] == rep['requests']
        # the queue never exceeded its bound
        assert engine._state('m').queue.depth_rows() <= 16
    finally:
        engine.close()


def test_closed_loop_availability(model, binaries):
    engine = make_engine(model, prewarm=True, max_batch_rows=8, max_latency_ms=1.0)
    oracle = oracle_fn(binaries)
    pool = make_request_pool(oracle, 8, rows_choices=(1, 2, 4), pool=16)
    try:
        rep = closed_loop(engine_infer_fn(engine, 'm'), pool, workers=4, duration_s=1.0, deadline_ms=2000.0)
        assert rep['mismatches'] == 0 and rep['errors'] == 0
        assert rep['ok'] > 0 and (rep['availability'] or 0) >= 0.99
        assert rep['p99_ms'] > 0 and rep['samples_per_s'] > 0
    finally:
        engine.close()


# ---------------------------------------------------------------------------
# drain / reload races
# ---------------------------------------------------------------------------


def test_drain_completes_in_flight_then_rejects(model, binaries):
    engine = make_engine(model, max_latency_ms=50.0)
    oracle = oracle_fn(binaries)
    pool = make_request_pool(oracle, 8, pool=6)
    try:
        reqs = [engine.submit('m', x) for x, _ in pool]
        assert engine.drain(timeout=30.0)
        for (x, y_exp), r in zip(pool, reqs):
            np.testing.assert_array_equal(r.result(1.0), y_exp)  # already resolved
        with pytest.raises(Draining):
            engine.submit('m', pool[0][0])
    finally:
        engine.close()


def test_reload_swaps_executor_without_dropping_queued_work(model, binaries):
    engine = make_engine(model, max_latency_ms=1.0)
    oracle = oracle_fn(binaries)
    pool = make_request_pool(oracle, 8, pool=8)
    try:
        # hold the batcher busy so work queues up behind the reload
        with fault_injection('serve.dispatch=sleep:1:0.3'):
            first = engine.submit('m', pool[0][0])
            time.sleep(0.05)  # batcher is now sleeping inside dispatch
            queued = [engine.submit('m', x) for x, _ in pool[1:]]
            version = engine.reload('m')
        assert version == 2
        np.testing.assert_array_equal(first.result(30.0), pool[0][1])
        for (x, y_exp), r in zip(pool[1:], queued):
            np.testing.assert_array_equal(r.result(30.0), y_exp)
        assert engine.models()['models'][0]['version'] == 2
        snap = telemetry.metrics_snapshot()
        if snap:
            assert snap.get('serve.reloads', {}).get('value', 0) >= 1
    finally:
        engine.close()


def test_reload_rejects_interface_change(model):
    engine = make_engine(model)
    try:
        from da4ml_tpu.cmvm import solve

        other = solve(np.ones((4, 3)), backend='cpu')
        with pytest.raises(ValueError, match='interface'):
            engine.reload('m', other)
    finally:
        engine.close()


def test_executor_cache_lru_bound(model):
    telemetry.enable(metrics=True)
    engine = ServeEngine(ServeConfig(executor_cache_cap=2, prewarm=False, max_latency_ms=0.5))
    try:
        for name in ('a', 'b', 'c'):
            engine.load_model(name, model)
            engine.infer(name, np.zeros((1, 8)), deadline_s=30.0)
        doc = engine.models()
        assert doc['executor_cache']['occupancy'] <= 2
        assert doc['executor_cache']['cap'] == 2
        snap = telemetry.metrics_snapshot()
        assert snap.get('serve.executor_evictions', {}).get('value', 0) >= 1
        # evicted model still serves (executor rebuilt on demand)
        engine.infer('a', np.zeros((1, 8)), deadline_s=30.0)
    finally:
        engine.close()


# ---------------------------------------------------------------------------
# observability integration
# ---------------------------------------------------------------------------


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_http_endpoints_and_serve_plane_health(model, binaries):
    from da4ml_tpu.serve.http import ServeServer
    from da4ml_tpu.telemetry.obs.openmetrics import validate_openmetrics

    engine = make_engine(model, prewarm=True, max_batch_rows=8)
    server = ServeServer(engine)
    oracle = oracle_fn(binaries)
    pool = make_request_pool(oracle, 8, pool=4)
    try:
        fn = http_infer_fn(server.url, 'm')
        y, served_by = fn(pool[0][0], 5.0)
        np.testing.assert_array_equal(y, pool[0][1])
        assert served_by == 'jax'
        # client errors map to 400/404, not 500
        with pytest.raises(InvalidInputError):
            fn(np.zeros((1, 3)), 5.0)
        code, body = _get(f'{server.url}/v1/models')
        doc = json.loads(body)
        assert code == 200 and doc['models'][0]['name'] == 'm'
        assert doc['models'][0]['executor_cached'] and doc['executor_cache']['occupancy'] == 1
        # /healthz carries the serve-plane check
        code, body = _get(f'{server.url}/healthz')
        health = json.loads(body)
        assert code == 200 and health['checks']['serve']['models']['m']['breaker'] == 'closed'
        # /statusz lists loaded models + executor-cache occupancy
        code, body = _get(f'{server.url}/statusz')
        status = json.loads(body)
        names = [m['name'] for e in status['serve']['engines'] for m in e['models']]
        assert 'm' in names
        # /metrics: serve families + per-model serve breaker label folding
        code, text = _get(f'{server.url}/metrics')
        fams = validate_openmetrics(text)
        assert any(f.startswith('da4ml_serve_') for f in fams)
        br = fams['da4ml_breaker_state']
        assert br['samples'].get('da4ml_breaker_state{breaker="serve.m"}') == 0.0
    finally:
        server.close()
        engine.close()


def test_healthz_degrades_on_open_serve_breaker_over_http(model):
    from da4ml_tpu.serve.http import ServeServer

    engine = make_engine(model, degraded='shed', breaker_reset_s=30.0)
    server = ServeServer(engine)
    try:
        with fault_injection('serve.dispatch=error:3'):
            for _ in range(3):
                engine.infer('m', np.zeros((1, 8)), deadline_s=10.0)
        code, body = _get(f'{server.url}/healthz')
        assert code == 503
        doc = json.loads(body)
        assert doc['status'] == 'degraded'
        assert doc['checks']['serve']['models']['m']['breaker'] == 'open'
    finally:
        server.close()
        engine.close()


def test_http_429_with_retry_after_under_burst(model):
    from da4ml_tpu.serve.http import ServeServer

    engine = make_engine(model, queue_cap_rows=2, max_batch_rows=2, max_latency_ms=20.0)
    server = ServeServer(engine)
    try:
        codes = []

        def post():
            body = json.dumps({'model': 'm', 'inputs': [[0.0] * 8], 'deadline_ms': 5000}).encode()
            req = urllib.request.Request(f'{server.url}/v1/infer', data=body)
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    codes.append((resp.status, resp.headers.get('Retry-After')))
            except urllib.error.HTTPError as e:
                codes.append((e.code, e.headers.get('Retry-After')))

        threads = [threading.Thread(target=post) for _ in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert all(c in (200, 429) for c, _ in codes)
        rejected = [ra for c, ra in codes if c == 429]
        assert rejected and all(ra is not None for ra in rejected)
    finally:
        server.close()
        engine.close()


# ---------------------------------------------------------------------------
# process-level: SIGTERM exits 0 with zero lost accepted requests
# ---------------------------------------------------------------------------


@pytest.mark.skipif(sys.platform == 'win32', reason='POSIX signals')
def test_sigterm_graceful_exit_zero_lost_requests(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS='cpu', PYTHONUNBUFFERED='1')
    env.pop('DA4ML_TRACE', None)
    proc = subprocess.Popen(
        [
            sys.executable, '-m', 'da4ml_tpu', 'serve', f'm={FIXTURE}',
            '--port', '0', '--max-batch-rows', '8', '--max-latency-ms', '20',
            '--deadline-ms', '30000', '--no-prewarm',
        ],  # fmt: skip
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
        cwd=str(REPO),
    )
    try:
        ready = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            try:
                ready = json.loads(line)
                break
            except ValueError:
                continue
        assert ready and 'serving' in ready, f'no ready line (rc={proc.poll()}): {proc.stderr.read()[:2000]}'
        url = ready['serving']

        outcomes = []
        lock = threading.Lock()

        def client(i):
            body = json.dumps({'model': 'm', 'inputs': [[0.25 * i] * 8], 'deadline_ms': 30000}).encode()
            req = urllib.request.Request(f'{url}/v1/infer', data=body)
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    doc = json.load(resp)
                    with lock:
                        outcomes.append(('ok', doc['outputs']))
            except urllib.error.HTTPError as e:
                with lock:
                    outcomes.append(('rejected', e.code))  # structured rejection, not lost
            except urllib.error.URLError as e:
                # connection refused = the listener was already closed, the
                # request was never accepted; reset mid-stream would be loss
                kind = 'refused' if isinstance(e.reason, ConnectionRefusedError) else 'lost'
                with lock:
                    outcomes.append((kind, repr(e)))
            except Exception as e:
                with lock:
                    outcomes.append(('lost', repr(e)))

        # a first request proves the path, then SIGTERM lands while a wave
        # of accepted requests is still in flight (20 ms coalesce window)
        client(0)
        assert outcomes and outcomes[0][0] == 'ok', outcomes
        threads = [threading.Thread(target=client, args=(i,)) for i in range(1, 9)]
        for t in threads:
            t.start()
        time.sleep(0.02)
        proc.send_signal(signal.SIGTERM)
        for t in threads:
            t.join(90)
        rc = proc.wait(timeout=90)
        assert rc == 0, (rc, proc.stderr.read()[:2000])
        lost = [o for o in outcomes if o[0] == 'lost']
        assert not lost, f'accepted requests lost on SIGTERM: {lost}'
        assert sum(1 for o in outcomes if o[0] == 'ok') >= 1
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait(30)


# ---------------------------------------------------------------------------
# chaos drill (the CI serve-chaos gate, in miniature)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_drill_end_to_end():
    from da4ml_tpu.serve.chaos import chaos_drill

    report = chaos_drill(duration_s=4.0, workers=3)
    assert report['ok'], report['checks']
    assert report['load']['mismatches'] == 0
    assert report['phases']['breaker']['tripped']
    assert report['final_healthz'] == 'ok'
