"""Live observability plane: exposition, endpoints, health, bench-diff.

Covers the PR-6 acceptance surface (docs/observability.md):

- OpenMetrics exposition validated line-by-line against the format
  grammar (HELP/TYPE ordering, label escaping, cumulative buckets, EOF);
- endpoint smoke over a real device solve on the 8-device CPU mesh;
- /healthz reflecting breaker transitions and stalled-campaign heartbeats;
- bench-diff pass / injected-regression fail / budget-override cases on
  the committed BENCH trajectory;
- disabled path: no server thread, no registry, unless explicitly armed;
- stats --follow incremental tail of a streaming JSONL trace.
"""

import json
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from da4ml_tpu import telemetry
from da4ml_tpu.cmvm import solve
from da4ml_tpu.telemetry.obs import (
    TraceTailer,
    diff_metrics,
    health_snapshot,
    load_bench_metrics,
    load_budgets,
    render_openmetrics,
    serve,
    server_port,
    status_snapshot,
    stop_server,
    validate_openmetrics,
)
from da4ml_tpu.telemetry.obs.bench_diff import Budgets, classify_metric, flatten_bench

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _isolated_obs(monkeypatch):
    """Server + telemetry are process-global: start and leave every test clean."""
    monkeypatch.delenv('DA4ML_METRICS_PORT', raising=False)
    monkeypatch.delenv('DA4ML_PROFILE', raising=False)
    stop_server()
    telemetry.reset()
    from da4ml_tpu.reliability.breaker import reset_all_breakers

    reset_all_breakers()
    yield
    stop_server()
    telemetry.reset()
    reset_all_breakers()


def _small_kernel(seed=3, n=6, m=4):
    rng = np.random.default_rng(seed)
    return rng.integers(-8, 8, (n, m)).astype(np.float64)


def _get(url: str):
    """(status, body) even for non-2xx responses."""
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# OpenMetrics exposition format
# ---------------------------------------------------------------------------


def test_exposition_valid_over_real_registry():
    telemetry.enable(metrics=True)
    solve(_small_kernel(), backend='cpu')
    text = render_openmetrics()
    fams = validate_openmetrics(text)
    assert 'da4ml_solve_calls' in fams
    assert fams['da4ml_solve_calls']['type'] == 'counter'
    assert fams['da4ml_solve_calls']['samples']['da4ml_solve_calls_total'] == 1.0
    # seconds rename + histogram triplet
    dur = fams['da4ml_solve_duration_seconds']
    assert dur['type'] == 'histogram'
    assert any(k.startswith('da4ml_solve_duration_seconds_bucket') for k in dur['samples'])
    assert dur['samples']['da4ml_solve_duration_seconds_count'] == 1.0
    # count-valued histogram rides the count ladder, not seconds: the
    # observed adder cost must land in a finite bucket
    adders = fams['da4ml_solve_adders']
    finite = [k for k in adders['samples'] if '_bucket' in k and '+Inf' not in k]
    assert sum(adders['samples'][k] for k in finite) >= 1.0


def test_exposition_label_folding_and_escaping():
    telemetry.enable(metrics=True)
    telemetry.gauge('breaker.state.native-threads').set(1.0)
    telemetry.gauge('breaker.state.jax').set(0.0)
    telemetry.gauge('run.mode.level').set(3.0)
    fams = validate_openmetrics(render_openmetrics())
    br = fams['da4ml_breaker_state']
    assert br['samples']['da4ml_breaker_state{breaker="jax"}'] == 0.0
    assert br['samples']['da4ml_breaker_state{breaker="native-threads"}'] == 1.0
    assert fams['da4ml_run_mode']['samples']['da4ml_run_mode{mode="level"}'] == 3.0


def test_exposition_escapes_hostile_label_values():
    from da4ml_tpu.telemetry.obs.openmetrics import _labels_str

    rendered = _labels_str({'breaker': 'a"b\\c\nd'})
    # the validator must accept the escaped form and round-trip the value
    text = f'# HELP da4ml_x x\n# TYPE da4ml_x gauge\nda4ml_x{rendered} 1\n# EOF\n'
    fams = validate_openmetrics(text)
    (key,) = fams['da4ml_x']['samples']
    assert '\\"' in key and '\\\\' in key and '\\n' in key


@pytest.mark.parametrize(
    'bad',
    [
        'da4ml_x 1\n# EOF\n',  # sample before any HELP/TYPE
        '# HELP da4ml_x x\n# TYPE da4ml_x gauge\nda4ml_x 1\n',  # missing EOF
        '# HELP da4ml_x x\n# TYPE da4ml_x counter\nda4ml_x 1\n# EOF\n',  # counter w/o _total
        '# HELP da4ml_x x\n# TYPE da4ml_x wat\nda4ml_x 1\n# EOF\n',  # unknown type
        '# HELP da4ml_x x\n# TYPE da4ml_x gauge\nda4ml_x{le>="0"} 1\n# EOF\n',  # bad label
        '# HELP da4ml_x x\n# TYPE da4ml_x gauge\nda4ml_x 1\nda4ml_x 2\n# EOF\n',  # duplicate
        (
            '# HELP da4ml_x x\n# TYPE da4ml_x histogram\n'
            'da4ml_x_bucket{le="1"} 5\nda4ml_x_bucket{le="+Inf"} 3\n'
            'da4ml_x_sum 1\nda4ml_x_count 3\n# EOF\n'
        ),  # non-cumulative buckets
        (
            '# HELP da4ml_x x\n# TYPE da4ml_x histogram\n'
            'da4ml_x_bucket{le="1"} 1\nda4ml_x_sum 1\nda4ml_x_count 1\n# EOF\n'
        ),  # missing +Inf bucket
    ],
)
def test_exposition_validator_rejects(bad):
    with pytest.raises(ValueError):
        validate_openmetrics(bad)


def test_histogram_bucket_presets():
    """Count/byte histograms must not dump everything into +Inf."""
    assert telemetry.COUNT_BUCKETS[0] <= 1 and telemetry.COUNT_BUCKETS[-1] >= 1e6
    assert telemetry.BYTES_BUCKETS[0] <= 4096 and telemetry.BYTES_BUCKETS[-1] >= 2**30
    telemetry.enable(metrics=True)
    telemetry.histogram('t.count', telemetry.COUNT_BUCKETS).observe(5000)
    telemetry.histogram('t.bytes', telemetry.BYTES_BUCKETS).observe(2**20)
    snap = telemetry.metrics_snapshot()
    for name in ('t.count', 't.bytes'):
        m = snap[name]
        assert sum(m['buckets']) == 1, f'{name}: sample fell through to +Inf'


# ---------------------------------------------------------------------------
# HTTP endpoints
# ---------------------------------------------------------------------------


def test_endpoint_smoke_over_device_solve():
    """Acceptance: scraping /metrics during a live solve on the 8-device CPU
    mesh yields valid OpenMetrics with solver, runtime, reliability, and
    scheduler families."""
    srv = serve(0)
    assert server_port() == srv.port
    solve(_small_kernel(5, 8, 8), backend='jax')  # device rungs on the mesh
    # and one runtime batch so run.* families are live too
    from da4ml_tpu.ir.synth import random_inputs, random_program
    from da4ml_tpu.runtime.jax_backend import DaisExecutor

    rng = np.random.default_rng(0)
    prog = random_program(rng, n_ops=40, n_in=4, n_out=2)
    ex = DaisExecutor(prog, mode='scan')
    ex(random_inputs(rng, prog, 64))

    status, body = _get(srv.url + '/metrics')
    assert status == 200
    fams = validate_openmetrics(body)
    assert 'da4ml_solve_calls' in fams  # solver
    assert 'da4ml_cse_device_rounds' in fams
    assert 'da4ml_sched_device_seconds' in fams  # scheduler: per-rung device timing
    assert 'da4ml_run_device_seconds' in fams  # runtime
    run_mode = fams.get('da4ml_run_mode', {'samples': {}})['samples']
    assert any(k.startswith('da4ml_run_mode_total{mode=') for k in run_mode), run_mode
    assert 'da4ml_breaker_state' in fams  # reliability, label-folded
    assert 'da4ml_health_status' in fams

    status, body = _get(srv.url + '/healthz')
    assert status == 200
    doc = json.loads(body)
    assert doc['status'] == 'ok'
    assert doc['checks']['breakers']['status'] == 'ok'

    status, body = _get(srv.url + '/statusz')
    assert status == 200
    doc = json.loads(body)
    assert doc['telemetry']['metrics_enabled'] is True
    assert doc['scheduler'], 'statusz missing scheduler occupancy'

    status, _ = _get(srv.url + '/nope')
    assert status == 404


def test_serve_idempotent_and_stop():
    a = serve(0)
    b = serve(0)
    assert a is b
    stop_server()
    assert server_port() is None
    c = serve(0)
    assert c is not a
    assert server_port() == c.port


def test_healthz_reflects_breaker_transitions():
    from da4ml_tpu.reliability.breaker import breaker_for

    srv = serve(0)
    br = breaker_for('obs-test-backend', fail_threshold=1, reset_after=60.0)
    status, body = _get(srv.url + '/healthz')
    assert status == 200 and json.loads(body)['status'] == 'ok'

    br.record_failure()  # threshold 1: opens immediately
    status, body = _get(srv.url + '/healthz')
    doc = json.loads(body)
    assert status == 503
    assert doc['status'] == 'degraded'
    assert 'obs-test-backend' in doc['checks']['breakers']['open']
    # the open breaker is also a labeled gauge on /metrics
    fams = validate_openmetrics(_get(srv.url + '/metrics')[1])
    assert fams['da4ml_breaker_state']['samples']['da4ml_breaker_state{breaker="obs-test-backend"}'] == 1.0

    br.record_success()
    status, body = _get(srv.url + '/healthz')
    assert status == 200 and json.loads(body)['status'] == 'ok'


def test_healthz_stalled_campaign_degrades(monkeypatch):
    """A worker that stops beating mid-campaign flips health to degraded."""
    from da4ml_tpu.telemetry import core

    telemetry.enable(metrics=True)
    telemetry.gauge('campaign.total').set(3.0)
    telemetry.gauge('campaign.done').set(1.0)
    telemetry.beat('campaign')
    doc = health_snapshot()
    assert doc['checks']['campaign']['in_progress'] is True
    assert doc['status'] == 'ok'

    # age the heartbeat past the stall threshold without sleeping
    core._heartbeats['campaign'] -= 500.0
    doc = health_snapshot()
    assert doc['checks']['campaign']['status'] == 'degraded'
    assert doc['status'] == 'degraded'
    assert doc['checks']['campaign']['heartbeat_age_s'] > 120.0

    # a finished campaign stops gating no matter how old the beat is
    telemetry.gauge('campaign.done').set(3.0)
    assert health_snapshot()['status'] == 'ok'

    # threshold is tunable
    monkeypatch.setenv('DA4ML_HEALTH_STALL_S', '1e9')
    telemetry.gauge('campaign.done').set(1.0)
    assert health_snapshot()['status'] == 'ok'


def test_campaign_heartbeat_age_gauge():
    """solve_many beats per kernel; the age gauge lands on /metrics."""
    from da4ml_tpu.reliability import solve_many

    serve(0)
    results, report = solve_many([_small_kernel(s) for s in range(2)], backend='pure-python')
    assert len(results) == 2
    assert telemetry.beat_age_s('campaign') is not None
    fams = validate_openmetrics(render_openmetrics())
    (age,) = fams['da4ml_campaign_heartbeat_age_seconds']['samples'].values()
    assert 0.0 <= age < 60.0


def test_statusz_active_spans():
    """A live endpoint arms real spans even without a trace sink, so
    /statusz shows what the process is doing right now."""
    srv = serve(0)
    with telemetry.span('obs.outer', probe=1):
        doc = json.loads(_get(srv.url + '/statusz')[1])
        names = [s['name'] for s in doc['active_spans']]
        assert 'obs.outer' in names
    assert all(s['name'] != 'obs.outer' for s in status_snapshot()['active_spans'])
    stop_server()
    # watcher released with the server: spans fall back to the no-op singleton
    assert telemetry.span('a') is telemetry.span('b')


def test_broken_provider_returns_500_not_dead_thread():
    srv = serve(0, status_provider=lambda: (_ for _ in ()).throw(RuntimeError('boom')))
    status, body = _get(srv.url + '/statusz')
    assert status == 500 and 'boom' in body
    # the serving thread survived: next scrape still answers
    status, _ = _get(srv.url + '/metrics')
    assert status == 200


# ---------------------------------------------------------------------------
# disabled path
# ---------------------------------------------------------------------------


def test_disabled_no_server_thread():
    """Acceptance: telemetry-disabled runs spawn no server and no registry."""
    assert server_port() is None
    solve(_small_kernel(), backend='cpu')
    assert server_port() is None
    assert not any(t.name == 'da4ml-obs-server' for t in threading.enumerate())
    assert telemetry.metrics_snapshot() == {}


def test_env_var_activation_subprocess(tmp_path):
    """DA4ML_METRICS_PORT arms the endpoint at import with no code changes."""
    code = (
        'import os, urllib.request\n'
        'import da4ml_tpu.telemetry as tm\n'
        'from da4ml_tpu.telemetry.obs.server import server_port\n'
        'p = server_port()\n'
        'assert p, "endpoint not armed"\n'
        'body = urllib.request.urlopen(f"http://127.0.0.1:{p}/metrics", timeout=10).read().decode()\n'
        'from da4ml_tpu.telemetry.obs import validate_openmetrics\n'
        'validate_openmetrics(body)\n'
        'print("PORT_OK")\n'
    )
    env = dict(
        __import__('os').environ, DA4ML_METRICS_PORT='0', JAX_PLATFORMS='cpu'
    )
    out = subprocess.run([sys.executable, '-c', code], capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stderr
    assert 'PORT_OK' in out.stdout


def test_bad_metrics_port_does_not_break_import():
    code = 'import da4ml_tpu.telemetry; print("IMPORT_OK")'
    env = dict(__import__('os').environ, DA4ML_METRICS_PORT='not-a-port', JAX_PLATFORMS='cpu')
    out = subprocess.run([sys.executable, '-c', code], capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stderr
    assert 'IMPORT_OK' in out.stdout


# ---------------------------------------------------------------------------
# bench-diff regression gates
# ---------------------------------------------------------------------------


def test_bench_diff_committed_trajectory_passes():
    """Acceptance: the committed r04 -> r05 round passes default budgets."""
    a = load_bench_metrics(REPO / 'BENCH_r04.json')
    b = load_bench_metrics(REPO / 'BENCH_r05.json')
    assert len(a) > 20 and len(b) > 20, 'tail recovery found too few metrics'
    result = diff_metrics(a, b)
    assert result['n_compared'] > 20
    assert result['regressions'] == []


def test_bench_diff_detects_injected_regression(tmp_path):
    base = {'metric': 'x', 'value': 10.0, 'detail': {'configs': [{'config': 'c1', 'jax_rate': 10.0, 'cost': 100}]}}
    bad = {'metric': 'x', 'value': 2.0, 'detail': {'configs': [{'config': 'c1', 'jax_rate': 2.0, 'cost': 110}]}}
    pa, pb = tmp_path / 'a.json', tmp_path / 'b.json'
    pa.write_text(json.dumps(base))
    pb.write_text(json.dumps(bad))
    result = diff_metrics(load_bench_metrics(pa), load_bench_metrics(pb))
    regressed = {r['metric'] for r in result['regressions']}
    assert regressed == {'value', 'configs.c1.jax_rate', 'configs.c1.cost'}
    # CLI exit codes: 1 regression, 0 after loosening the budgets
    from da4ml_tpu._cli import main

    assert main(['bench-diff', str(pa), str(pb)]) == 1
    budget = tmp_path / 'budgets.toml'
    budget.write_text('[default]\nrate_drop_pct = 90.0\ncost_rise_pct = 15.0\n')
    assert main(['bench-diff', str(pa), str(pb), '--budget', str(budget)]) == 0


def test_bench_diff_budget_rules(tmp_path):
    budget = tmp_path / 'budgets.toml'
    budget.write_text(
        '[default]\nrate_drop_pct = 50.0\n\n'
        '[rules."configs.*.jax_rate"]\nmax_drop_pct = 5.0\n\n'
        '[rules."configs.*.host_rate"]\nignore = true\n\n'
        '[rules."configs.*.compile_s"]\nmax_rise_pct = 10.0\n'
    )
    budgets = load_budgets(budget)
    a = {'configs.c.jax_rate': 100.0, 'configs.c.host_rate': 100.0, 'configs.c.compile_s': 1.0}
    b = {'configs.c.jax_rate': 90.0, 'configs.c.host_rate': 1.0, 'configs.c.compile_s': 1.5}
    result = diff_metrics(a, b, budgets)
    by_name = {r['metric']: r for r in result['rows']}
    assert by_name['configs.c.jax_rate']['status'] == 'regressed'  # -10% > 5% rule
    assert by_name['configs.c.host_rate']['status'] == 'ignored'
    assert by_name['configs.c.compile_s']['status'] == 'regressed'  # +50% > 10% opt-in


def test_bench_diff_exactness_never_drops():
    assert classify_metric('quality_sweep.exact') == 'exact'
    result = diff_metrics({'quality_sweep.exact': 1.0}, {'quality_sweep.exact': 0.9375})
    assert len(result['regressions']) == 1
    result = diff_metrics({'quality_sweep.exact': 1.0}, {'quality_sweep.exact': 1.0})
    assert result['regressions'] == []


def test_bench_diff_wallclock_is_info_by_default():
    result = diff_metrics({'configs.c.jax_compile_s': 1.0}, {'configs.c.jax_compile_s': 50.0})
    assert result['regressions'] == []
    (row,) = result['rows']
    assert row['status'] == 'info'


def test_flatten_shapes():
    # exactness ratio strings and config-keyed lists
    flat = flatten_bench(
        {'value': 5.0, 'detail': {'quality': {'exact': '16/16'}, 'configs': [{'config': 'a', 'rate': 2.0}]}}
    )
    assert flat['quality.exact'] == 1.0
    assert flat['configs.a.rate'] == 2.0
    # a telemetry metrics snapshot flattens counters/gauges/histograms
    telemetry.enable(metrics=True)
    telemetry.counter('c.x').inc(3)
    telemetry.histogram('h.y').observe(0.5)
    flat = flatten_bench(telemetry.metrics_snapshot())
    assert flat['c.x'] == 3.0
    assert flat['h.y.count'] == 1.0


def test_bench_diff_unreadable_input(tmp_path):
    from da4ml_tpu._cli import main

    bad = tmp_path / 'bad.json'
    bad.write_text('[]')
    ok = tmp_path / 'ok.json'
    ok.write_text(json.dumps({'value': 1.0, 'detail': {}}))
    assert main(['bench-diff', str(bad), str(ok)]) == 2
    assert main(['bench-diff', str(tmp_path / 'missing.json'), str(ok)]) == 2


# ---------------------------------------------------------------------------
# trace tailing (stats --follow / monitor --follow)
# ---------------------------------------------------------------------------


def test_tailer_incremental_and_truncation(tmp_path):
    path = tmp_path / 't.jsonl'
    ev = {'ph': 'X', 'name': 'a', 'ts': 0, 'dur': 1, 'pid': 1, 'tid': 1}
    with open(path, 'w') as fh:
        fh.write(json.dumps(ev) + '\n')
    tailer = TraceTailer(path)
    assert tailer.poll() == 1
    assert tailer.poll() == 0  # nothing new
    with open(path, 'a') as fh:
        fh.write(json.dumps(dict(ev, name='b')) + '\n')
        fh.write('{"partial": ')  # incomplete trailing line must be buffered
    assert tailer.poll() == 1
    assert [e['name'] for e in tailer.events] == ['a', 'b']
    with open(path, 'a') as fh:
        fh.write('1}\n')  # completes the buffered line
    assert tailer.poll() == 1
    # metrics records update .metrics instead of .events
    with open(path, 'a') as fh:
        rec = {'ph': 'M', 'name': 'metrics', 'args': {'metrics': {'solve.calls': {'type': 'counter', 'value': 2}}}}
        fh.write(json.dumps(rec) + '\n')
    assert tailer.poll() == 0
    assert tailer.metrics['solve.calls']['value'] == 2
    # truncation resets
    path.write_text(json.dumps(ev) + '\n')
    assert tailer.poll() == 1
    assert len(tailer.events) == 1


def test_stats_follow_cli(tmp_path, capsys):
    from da4ml_tpu._cli import main

    path = tmp_path / 'trace.jsonl'
    telemetry.enable(path)
    solve(_small_kernel(), backend='cpu')
    telemetry.disable()
    rc = main(['stats', '--follow', str(path), '--max-updates', '1', '--interval', '0.01'])
    out = capsys.readouterr().out
    assert rc == 0
    assert 'update 1' in out
    assert 'cmvm.solve' in out
    # non-jsonl rejected
    assert main(['stats', '--follow', str(tmp_path / 'trace.json'), '--max-updates', '1']) == 1


def test_monitor_follow_serves_mirrored_metrics(tmp_path):
    import argparse

    from da4ml_tpu._cli.monitor import monitor_main

    path = tmp_path / 'trace.jsonl'
    telemetry.enable(path)
    solve(_small_kernel(), backend='cpu')
    telemetry.reset()

    args = argparse.Namespace(
        port=0, host='127.0.0.1', follow=path, interval=0.05, duration=4.0, stall_after=60.0
    )
    t = threading.Thread(target=monitor_main, args=(args,), daemon=True)
    t.start()
    port = None
    for _ in range(100):
        port = server_port()
        if port:
            break
        time.sleep(0.05)
    assert port, 'monitor never bound'
    fams = validate_openmetrics(_get(f'http://127.0.0.1:{port}/metrics')[1])
    assert 'da4ml_solve_calls' in fams, 'mirrored solver metrics missing'
    doc = json.loads(_get(f'http://127.0.0.1:{port}/statusz')[1])
    assert doc['n_events'] > 0
    t.join(timeout=30)
    assert not t.is_alive()


# ---------------------------------------------------------------------------
# device-profile correlation
# ---------------------------------------------------------------------------


def test_profile_annotate_disabled_is_noop(monkeypatch):
    from contextlib import nullcontext

    from da4ml_tpu.telemetry.obs import profile

    monkeypatch.delenv('DA4ML_PROFILE', raising=False)
    cm = profile.annotate('cmvm.rung.dispatch')
    assert isinstance(cm, nullcontext)
    with cm:
        pass


def test_profile_armed_writes_xplane(tmp_path):
    """DA4ML_PROFILE correlates device events: a solve under the env var
    produces an xplane capture next to the telemetry trace."""
    out = subprocess.run(
        [
            sys.executable,
            '-c',
            'import numpy as np\n'
            'from da4ml_tpu.cmvm import solve\n'
            'from da4ml_tpu.telemetry.obs import profile\n'
            'm = np.random.default_rng(2).integers(-8, 8, (8, 8)).astype(np.float64)\n'
            'solve(m, backend="jax")\n'
            'assert profile.profiling_active(), "profiler did not arm"\n'
            'print("PROF_OK")\n',
        ],
        capture_output=True,
        text=True,
        env=dict(__import__('os').environ, DA4ML_PROFILE=str(tmp_path / 'prof'), JAX_PLATFORMS='cpu'),
        timeout=300,
    )
    assert out.returncode == 0, out.stderr
    assert 'PROF_OK' in out.stdout
    captures = list((tmp_path / 'prof').rglob('*.xplane.pb'))
    assert captures, 'no xplane capture written'


def test_budgets_defaults_and_rule_matching():
    budgets = Budgets(rules={'configs.*.jax_rate': {'max_drop_pct': 5.0}})
    assert budgets.rule_for('configs.c3.jax_rate') == {'max_drop_pct': 5.0}
    assert budgets.rule_for('configs.c3.other') is None
    assert budgets.defaults['rate_drop_pct'] == 50.0


# ---------------------------------------------------------------------------
# exemplars + federation + fleet trace merge (docs/observability.md#fleet-tracing)
# ---------------------------------------------------------------------------


def test_histogram_exemplar_renders_and_validates():
    from da4ml_tpu.telemetry.metrics import enable_metrics

    enable_metrics()
    tid = telemetry.new_trace_id()
    telemetry.histogram('serve.latency_s').observe(0.011, trace_id=tid)
    telemetry.histogram('serve.latency_s').observe(0.012)  # no exemplar
    text = render_openmetrics()
    assert ('# {trace_id="%s"} 0.011' % tid) in text
    fams = validate_openmetrics(text)  # exemplar suffix passes the grammar
    assert fams['da4ml_serve_latency_seconds']['type'] == 'histogram'


@pytest.mark.parametrize(
    'bad',
    [
        # exemplar on a gauge sample
        '# HELP da4ml_g g\n# TYPE da4ml_g gauge\nda4ml_g 1 # {trace_id="x"} 1 1\n# EOF\n',
        # exemplar on a histogram _sum sample (only _bucket may carry one)
        '# HELP da4ml_h h\n# TYPE da4ml_h histogram\n'
        'da4ml_h_bucket{le="+Inf"} 1\nda4ml_h_sum 1 # {trace_id="x"} 1\nda4ml_h_count 1\n# EOF\n',
        # exemplar label set beyond the 128-char OpenMetrics bound
        '# HELP da4ml_c c\n# TYPE da4ml_c counter\nda4ml_c_total 1 # {trace_id="' + 'a' * 130 + '"} 1\n# EOF\n',
        # malformed exemplar label pair
        '# HELP da4ml_c c\n# TYPE da4ml_c counter\nda4ml_c_total 1 # {notquoted} 1\n# EOF\n',
    ],
)
def test_validator_rejects_bad_exemplars(bad):
    with pytest.raises(ValueError):
        validate_openmetrics(bad)


def test_validator_accepts_counter_exemplar():
    ok = '# HELP da4ml_c c\n# TYPE da4ml_c counter\nda4ml_c_total 5 # {trace_id="ab12"} 1 1700000000.5\n# EOF\n'
    fams = validate_openmetrics(ok)
    assert fams['da4ml_c']['samples']['da4ml_c_total'] == 5.0


def test_federate_metrics_labels_sources_and_validates():
    from da4ml_tpu.serve.router import federate_metrics
    from da4ml_tpu.telemetry.metrics import enable_metrics

    enable_metrics()
    tid = telemetry.new_trace_id()
    telemetry.counter('solve.calls').inc(2)
    telemetry.histogram('serve.latency_s').observe(0.02, trace_id=tid)
    text = render_openmetrics()
    fed = federate_metrics({'r0': text, 'r1': text, 'router': text})
    fams = validate_openmetrics(fed)  # one HELP/TYPE per family, no interleaving
    # every source's samples survive, labeled with their origin
    assert fed.count('da4ml_solve_calls_total{replica=') == 3
    for rid in ('r0', 'r1', 'router'):
        assert f'replica="{rid}"' in fed
    # exemplars pass through federation intact
    assert fed.count('# {trace_id="%s"}' % tid) == 3
    assert fams['da4ml_solve_calls']['samples']['da4ml_solve_calls_total{replica="r0"}'] == 2.0


def _write_trace(path, pid, unix_time_us, events):
    lines = [{'name': 'clock_sync', 'ph': 'M', 'ts': 0.0, 'pid': pid, 'tid': 0, 'args': {'unix_time_us': unix_time_us}}]
    lines += [dict(ev, pid=pid, tid=ev.get('tid', 0)) for ev in events]
    path.write_text('\n'.join(json.dumps(ln) for ln in lines) + '\n')


def test_merge_traces_aligns_clocks_and_indexes_by_trace_id(tmp_path):
    from da4ml_tpu.telemetry.obs.collect import merge_traces, write_merged

    tid = 'ab' * 16
    # same local ts=10us in both files, but process 2's wall clock anchor is
    # 1s later: after alignment its span must land 1s later on the shared axis
    _write_trace(
        tmp_path / 'r0-0.jsonl', 101, 5_000_000.0,
        [{'name': 'serve.request', 'ph': 'X', 'ts': 10.0, 'dur': 50.0, 'args': {'span_id': 1, 'trace_id': tid}}],
    )
    _write_trace(
        tmp_path / 'router.jsonl', 202, 6_000_000.0,
        [{'name': 'router.leg', 'ph': 'X', 'ts': 10.0, 'dur': 30.0, 'args': {'span_id': 2, 'trace_id': tid}},
         {'name': 'unrelated', 'ph': 'X', 'ts': 1.0, 'dur': 1.0, 'args': {'span_id': 3}}],
    )
    report = merge_traces(sorted(tmp_path.glob('*.jsonl')))
    assert report['max_processes_per_trace'] == 2
    t = report['traces'][tid]
    assert t['n_spans'] == 2 and t['pids'] == [101, 202]
    assert set(t['names']) == {'serve.request', 'router.leg'}
    evs = {e['args']['span_id']: e for e in report['doc']['traceEvents'] if e.get('ph') == 'X'}
    assert evs[2]['ts'] - evs[1]['ts'] == pytest.approx(1_000_000.0)  # clock offset applied
    names = [e['args']['name'] for e in report['doc']['traceEvents'] if e.get('name') == 'process_name']
    assert any('r0-0' in n for n in names) and any('router' in n for n in names)
    out = tmp_path / 'merged.json'
    write_merged(report, out)
    doc = json.loads(out.read_text())
    assert doc['otherData']['sources'][0]['aligned'] is True
    # the merged document round-trips through the standard loader
    events, _ = telemetry.load_trace(out)
    assert len(events) == report['n_events']


def test_load_trace_merges_multiprocess_metrics_without_double_count(tmp_path):
    """A merged / multi-writer JSONL trace: latest snapshot per pid, then
    summed across pids — repeated mirrors from one process never double."""
    path = tmp_path / 'merged.jsonl'
    lines = []
    for pid in (11, 22):
        for v in (1.0, 3.0):  # two mirrors per process: only the last counts
            lines.append(
                {'name': 'metrics', 'ph': 'M', 'ts': 2.0, 'pid': pid, 'tid': 0,
                 'args': {'metrics': {'c.x': {'type': 'counter', 'value': v}}}}
            )
    path.write_text('\n'.join(json.dumps(ln) for ln in lines) + '\n')
    _, metrics = telemetry.load_trace(path)
    assert metrics['c.x']['value'] == 6.0


def test_tailer_merges_multi_pid_metrics(tmp_path):
    path = tmp_path / 'fleet.jsonl'
    recs = [
        {'name': 'metrics', 'ph': 'M', 'ts': 1.0, 'pid': 1, 'tid': 0,
         'args': {'metrics': {'c.x': {'type': 'counter', 'value': 2.0}}}},
        {'name': 'metrics', 'ph': 'M', 'ts': 2.0, 'pid': 1, 'tid': 0,
         'args': {'metrics': {'c.x': {'type': 'counter', 'value': 5.0}}}},  # replaces pid 1's first mirror
        {'name': 'metrics', 'ph': 'M', 'ts': 2.0, 'pid': 2, 'tid': 0,
         'args': {'metrics': {'c.x': {'type': 'counter', 'value': 7.0}}}},
    ]
    path.write_text('\n'.join(json.dumps(r) for r in recs) + '\n')
    tailer = TraceTailer(path)
    tailer.poll()
    assert tailer.metrics['c.x']['value'] == 12.0  # 5 (pid 1, latest) + 7 (pid 2)


def test_merge_metrics_histograms_and_exemplars():
    from da4ml_tpu.telemetry.obs.collect import merge_metrics

    h1 = {'type': 'histogram', 'count': 2, 'sum': 0.3, 'bounds': [0.1, 1.0], 'buckets': [1, 1],
          'min': 0.05, 'max': 0.25, 'exemplars': {'0': ['t-old', 0.05, 100.0]}}
    h2 = {'type': 'histogram', 'count': 1, 'sum': 0.05, 'bounds': [0.1, 1.0], 'buckets': [1, 0],
          'min': 0.05, 'max': 0.05, 'exemplars': {'0': ['t-new', 0.04, 200.0]}}
    merged = merge_metrics({1: {'h': h1}, 2: {'h': h2}})['h']
    assert merged['count'] == 3 and merged['buckets'] == [2, 1]
    assert merged['sum'] == pytest.approx(0.35)
    assert merged['min'] == 0.05 and merged['max'] == 0.25
    assert merged['exemplars']['0'][0] == 't-new'  # newest exemplar wins
