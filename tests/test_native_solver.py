"""Native CMVM solver parity: the C++ solver must be decision-identical with
the Python host solver — same op lists, same cost, exact kernel — across the
method/dc/adder-size config space (mirrors the reference's test_cmvm.py
cartesian, tests/test_cmvm.py:40-55 in the reference tree).
"""

import numpy as np
import pytest

from da4ml_tpu.cmvm import solve
from da4ml_tpu.ir.types import QInterval

native = pytest.importorskip('da4ml_tpu.native')

if not native.has_solver():
    pytest.skip('native CMVM solver unavailable', allow_module_level=True)


def _random_kernel(rng, n_in, n_out, bits):
    return (rng.integers(0, 2**bits, (n_in, n_out)) * rng.choice([-1.0, 1.0], (n_in, n_out))).astype(np.float64)


def _assert_identical(py, cp, kernel):
    assert np.array_equal(np.asarray(cp.kernel, np.float64), kernel)
    assert py.cost == cp.cost
    for s_py, s_cp in zip(py.stages, cp.stages):
        assert len(s_py.ops) == len(s_cp.ops)
        for a, b in zip(s_py.ops, s_cp.ops):
            assert a == b
        assert s_py.out_idxs == s_cp.out_idxs
        assert s_py.out_shifts == s_cp.out_shifts
        assert s_py.out_negs == s_cp.out_negs
        assert s_py.inp_shifts == s_cp.inp_shifts


@pytest.mark.parametrize('method0', ['mc', 'wmc'])
@pytest.mark.parametrize('hard_dc', [0, 2, -1])
@pytest.mark.parametrize('decompose_dc', [0, -1, -2])
def test_solver_config_parity(method0, hard_dc, decompose_dc):
    rng = np.random.default_rng(hash((method0, hard_dc, decompose_dc)) % 2**31)
    kernel = _random_kernel(rng, 6, 5, 4)
    kw = dict(
        method0=method0,
        hard_dc=hard_dc,
        decompose_dc=decompose_dc,
        search_all_decompose_dc=False,
        qintervals=[QInterval(-8.0, 7.0, 1.0)] * 6,
    )
    _assert_identical(solve(kernel, backend='cpu', **kw), solve(kernel, backend='cpp', **kw), kernel)


@pytest.mark.parametrize('seed', [0, 1, 2, 3])
def test_solver_search_all_parity(seed):
    rng = np.random.default_rng(seed)
    n_in, n_out = int(rng.integers(2, 10)), int(rng.integers(1, 10))
    kernel = _random_kernel(rng, n_in, n_out, 4)
    qints = [QInterval(-128.0, 127.0, 1.0)] * n_in
    _assert_identical(
        solve(kernel, backend='cpu', qintervals=qints),
        solve(kernel, backend='cpp', qintervals=qints),
        kernel,
    )


def test_solver_sized_cost_model():
    rng = np.random.default_rng(9)
    kernel = _random_kernel(rng, 8, 6, 4)
    qints = [QInterval(-16.0, 15.0, 0.5)] * 8
    kw = dict(adder_size=6, carry_size=8, qintervals=qints, latencies=[float(i % 3) for i in range(8)])
    _assert_identical(solve(kernel, backend='cpu', **kw), solve(kernel, backend='cpp', **kw), kernel)


def test_solver_predict_exact():
    rng = np.random.default_rng(10)
    kernel = _random_kernel(rng, 10, 7, 4)
    sol = solve(kernel, backend='cpp', qintervals=[QInterval(-8.0, 7.0, 1.0)] * 10)
    x = rng.integers(-8, 8, (128, 10)).astype(np.float64)
    np.testing.assert_array_equal(sol.predict(x, backend='cpp'), x @ kernel)


def test_solver_threads_deterministic():
    rng = np.random.default_rng(11)
    kernel = _random_kernel(rng, 8, 8, 4)
    from da4ml_tpu.native.bindings import solve_native

    a = solve_native(kernel, n_threads=1)
    b = solve_native(kernel, n_threads=8)
    assert a == b
