"""Lease + campaign fault-tolerance suite (docs/distributed.md).

Fast, CPU-only: lease claim/renew/expire/steal races under thread and
subprocess contention, crash-safe per-kernel results, resume-after-SIGKILL
byte-identity, a real two-worker steal drill, ``/healthz`` worker
degradation, the campaign CLI, and the configurable distributed connect
budget satellite. All solves use the ``pure-python`` backend so results
are deterministic without device warmup.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from da4ml_tpu.parallel import campaign as camp
from da4ml_tpu.reliability import (
    atomic_write_bytes,
    claim_lease,
    exclusive_create,
    read_lease,
    release_lease,
    renew_lease,
)
from da4ml_tpu.reliability.lease import list_leases

REPO_ROOT = Path(__file__).resolve().parents[1]


def _corpus(n=3, dim=5, bits=3, seed=7):
    rng = np.random.default_rng(seed)
    return [
        (rng.integers(0, 2**bits, (dim, dim)) * rng.choice([-1.0, 1.0], (dim, dim))).astype(np.float64)
        for _ in range(n)
    ]


def _blobs(results):
    return {d['key']: json.dumps(d['pipeline'], sort_keys=True) for d in results}


@pytest.fixture(autouse=True)
def _no_active_campaign():
    yield
    camp._ACTIVE_DIR = None


# ------------------------------------------------------------------ durability


def test_atomic_write_replaces_whole_file(tmp_path):
    p = tmp_path / 'a' / 'doc.json'
    atomic_write_bytes(p, b'{"v": 1}')
    atomic_write_bytes(p, b'{"v": 2}')
    assert json.loads(p.read_text()) == {'v': 2}
    assert list(p.parent.glob('*.tmp*')) == []  # no tmp litter


def test_exclusive_create_single_winner_threads(tmp_path):
    p = tmp_path / 'claim'
    wins = []
    barrier = threading.Barrier(12)

    def worker(i):
        barrier.wait()
        if exclusive_create(p, f'{i}'.encode()):
            wins.append(i)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(12)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(wins) == 1
    assert p.read_text() == str(wins[0])


# ------------------------------------------------------------------ leases


def test_claim_is_exclusive_and_releasable(tmp_path):
    a = claim_lease(tmp_path, 'k', owner='a', ttl_s=10.0)
    assert a is not None and a.remaining_s() > 5
    assert claim_lease(tmp_path, 'k', owner='b', ttl_s=10.0) is None
    release_lease(a)
    b = claim_lease(tmp_path, 'k', owner='b', ttl_s=10.0)
    assert b is not None and b.stolen_from is None


def test_same_owner_reclaims_own_live_lease(tmp_path):
    a = claim_lease(tmp_path, 'k', owner='a', ttl_s=10.0)
    again = claim_lease(tmp_path, 'k', owner='a', ttl_s=10.0)
    assert again is not None and again.key == 'k'
    doc = read_lease(a.path)
    assert doc['owner'] == 'a' and doc['generation'] >= 1  # adopted via renew


def test_renew_extends_and_detects_loss(tmp_path):
    a = claim_lease(tmp_path, 'k', owner='a', ttl_s=0.2)
    assert renew_lease(a, ttl_s=10.0)
    assert a.remaining_s() > 5
    a.path.unlink()  # simulate release/steal out from under the owner
    assert not renew_lease(a)
    assert a.lost


def test_expired_lease_is_stolen_with_attribution(tmp_path):
    dead = claim_lease(tmp_path, 'k', owner='dead', ttl_s=0.05)
    time.sleep(0.3)
    thief = claim_lease(tmp_path, 'k', owner='thief', ttl_s=10.0, grace_s=0.1)
    assert thief is not None and thief.stolen_from == 'dead'
    assert read_lease(thief.path)['owner'] == 'thief'
    assert not renew_lease(dead) and dead.lost
    release_lease(dead)  # must not remove the thief's lease
    assert read_lease(thief.path)['owner'] == 'thief'


def test_live_lease_is_not_stealable(tmp_path):
    claim_lease(tmp_path, 'k', owner='a', ttl_s=30.0)
    assert claim_lease(tmp_path, 'k', owner='b', ttl_s=30.0, grace_s=0.1) is None


def test_steal_disabled(tmp_path):
    claim_lease(tmp_path, 'k', owner='a', ttl_s=0.05)
    time.sleep(0.2)
    assert claim_lease(tmp_path, 'k', owner='b', ttl_s=5.0, steal=False, grace_s=0.05) is None


def test_torn_lease_file_stolen_after_grace(tmp_path):
    # a crash between O_EXCL create and payload write leaves an empty file
    (tmp_path / 'k.lease').touch()
    assert claim_lease(tmp_path, 'k', owner='b', ttl_s=5.0, grace_s=0.2) is None  # too fresh
    time.sleep(0.4)
    lease = claim_lease(tmp_path, 'k', owner='b', ttl_s=5.0, grace_s=0.2)
    assert lease is not None


def test_dead_stealers_lock_is_broken(tmp_path):
    claim_lease(tmp_path, 'k', owner='dead', ttl_s=0.05)
    lock = tmp_path / 'k.steal-lock'
    lock.write_text('{"owner": "crashed-stealer"}')
    old = time.time() - 60
    os.utime(lock, (old, old))
    time.sleep(0.2)
    lease = claim_lease(tmp_path, 'k', owner='b', ttl_s=5.0, grace_s=0.1)
    assert lease is not None and lease.stolen_from == 'dead'
    assert not lock.exists()


def test_steal_race_threads_single_winner(tmp_path):
    for rnd in range(5):
        d = tmp_path / f'r{rnd}'
        claim_lease(d, 'k', owner='victim', ttl_s=0.01)
        time.sleep(0.15)
        wins = []
        barrier = threading.Barrier(8)

        def worker(i, d=d, wins=wins, barrier=barrier):
            barrier.wait()
            lease = claim_lease(d, 'k', owner=f's{i}', ttl_s=10.0, grace_s=0.05)
            if lease is not None:
                wins.append(lease)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert len(wins) == 1, f'round {rnd}: {len(wins)} steal winners'
        assert wins[0].stolen_from == 'victim'
        assert read_lease(wins[0].path)['owner'] == wins[0].owner


def test_claim_contention_subprocesses(tmp_path):
    """8 keys, 4 racing processes: every key claimed exactly once fleet-wide."""
    keys = [f'k{i}' for i in range(8)]
    script = (
        'import json,sys\n'
        f'sys.path.insert(0, {str(REPO_ROOT)!r})\n'
        'from da4ml_tpu.reliability.lease import claim_lease\n'
        'd, owner = sys.argv[1], sys.argv[2]\n'
        f'won = [k for k in {keys!r} if claim_lease(d, k, owner=owner, ttl_s=30.0)]\n'
        'print(json.dumps(won))\n'
    )
    procs = [
        subprocess.Popen(
            [sys.executable, '-c', script, str(tmp_path), f'p{i}'],
            stdout=subprocess.PIPE,
            text=True,
        )
        for i in range(4)
    ]
    won: list[str] = []
    for p in procs:
        out, _ = p.communicate(timeout=120)
        assert p.returncode == 0
        won.extend(json.loads(out.strip().splitlines()[-1]))
    assert sorted(won) == sorted(keys)  # no key double-claimed, none lost
    assert sorted(list_leases(tmp_path)) == sorted(keys)


# ------------------------------------------------------------------ campaign core


def test_create_campaign_manifest_is_exclusive_and_validated(tmp_path):
    kernels = _corpus(3)
    m1 = camp.create_campaign(tmp_path / 'c', kernels, backend='pure-python')
    m2 = camp.create_campaign(tmp_path / 'c', kernels, backend='pure-python', resume=True)
    assert m1['keys'] == m2['keys']
    with pytest.raises(camp.CampaignError, match='different corpus'):
        camp.create_campaign(tmp_path / 'c', _corpus(3, seed=99), backend='pure-python')


def test_create_campaign_refuses_stale_results_without_resume(tmp_path):
    kernels = _corpus(2)
    camp.create_campaign(tmp_path / 'c', kernels, backend='pure-python')
    (tmp_path / 'c' / 'results' / 'junk.json').write_text('{}')
    with pytest.raises(camp.CampaignError, match='resume=True'):
        camp.create_campaign(tmp_path / 'c', kernels, backend='pure-python')


def test_single_worker_loop_solves_corpus_and_collects_in_order(tmp_path):
    kernels = _corpus(3)
    kernels.append(kernels[0].copy())  # duplicate collapses onto one solve
    manifest = camp.create_campaign(tmp_path / 'c', kernels, backend='pure-python')
    assert len(manifest['keys']) == 3 and len(manifest['key_per_kernel']) == 4
    summary = camp.worker_loop(tmp_path / 'c', ttl_s=10.0)
    assert summary['complete'] and summary['n_solved'] == 3
    results = camp.collect_results(tmp_path / 'c')
    assert len(results) == 4  # duplicates fan back out in corpus order
    assert results[0]['key'] == results[3]['key']
    assert results[0]['pipeline'] == results[3]['pipeline']
    pipes = camp.results_to_pipelines(results)
    assert len(pipes) == 4 and all(p.cost == r['cost'] for p, r in zip(pipes, results))


def test_collect_incomplete_campaign_raises(tmp_path):
    camp.create_campaign(tmp_path / 'c', _corpus(2), backend='pure-python')
    with pytest.raises(camp.CampaignError, match='incomplete'):
        camp.collect_results(tmp_path / 'c')


def test_terminal_failure_completes_campaign(tmp_path):
    """A kernel failing on every backend fleet-wide lands a failed-result
    doc after max_failures, so the campaign terminates instead of looping."""
    kernels = _corpus(2)
    camp.create_campaign(tmp_path / 'c', kernels, backend='pure-python')
    with pytest.MonkeyPatch.context() as mp:
        mp.setenv('DA4ML_FAULT_INJECT', 'cmvm.solve=error')
        summary = camp.worker_loop(tmp_path / 'c', ttl_s=10.0, max_failures=2)
    assert summary['complete'] and summary['n_solved'] == 0
    with pytest.raises(camp.CampaignError, match='failed on every backend'):
        camp.collect_results(tmp_path / 'c')
    results = camp.collect_results(tmp_path / 'c', allow_failed=True)
    assert all(doc.get('failed') for doc in results)
    assert len(list((tmp_path / 'c' / 'failures').glob('*.json'))) == 2 * 2


def test_run_campaign_two_workers_byte_identical_to_single(tmp_path):
    kernels = _corpus(4, dim=6)
    ref, _ = camp.run_campaign(kernels, workers=1, campaign_dir=tmp_path / 'ref', backend='pure-python')
    par, rep = camp.run_campaign(
        kernels, workers=2, campaign_dir=tmp_path / 'par', backend='pure-python', ttl_s=10.0, poll_s=0.1
    )
    assert _blobs(ref) == _blobs(par)
    assert rep['n_kernels'] == 4 and len(rep['worker_summaries']) == 2
    owners = {doc['owner'] for doc in par}
    assert all(doc['owner'] in owners for doc in par)
    assert sum(s['n_solved'] for s in rep['worker_summaries']) == len(camp.load_manifest(tmp_path / 'par')['keys'])


def test_resume_after_sigkill_byte_identity(tmp_path):
    """Worker hard-killed right after its first durable result; a resumed
    worker finishes the corpus and the results are byte-identical to an
    uninterrupted single-process run (no kernel lost, none solved twice)."""
    kernels = _corpus(3, dim=6)
    ref, _ = camp.run_campaign(kernels, workers=1, campaign_dir=tmp_path / 'ref', backend='pure-python')

    drill = tmp_path / 'drill'
    camp.create_campaign(drill, kernels, backend='pure-python')
    env = dict(os.environ, DA4ML_FAULT_INJECT='campaign.post_result=kill:1')
    proc = camp._spawn_worker(drill, 'victim', ttl_s=5.0, poll_s=0.1, deadline_per_solve=None, env=env)
    proc.communicate(timeout=180)
    assert proc.returncode != 0  # died mid-campaign
    assert len(camp._done_keys(drill / 'results')) == 1  # exactly one durable result

    summary = camp.worker_loop(drill, owner='rescuer', ttl_s=2.0, grace_s=0.5, poll_s=0.1)
    assert summary['complete'] and summary['n_solved'] == 2  # only the remainder
    assert _blobs(camp.collect_results(drill)) == _blobs(ref)


@pytest.mark.parametrize('seed', [20260804])
def test_two_worker_steal_drill_sigkill(tmp_path, seed):
    """A real SIGKILL steal: the victim subprocess parks mid-solve holding a
    renewing lease; an in-process survivor steals the kernel after expiry
    and finishes the corpus byte-identical to the reference."""
    kernels = _corpus(3, dim=6, seed=seed)
    ref, _ = camp.run_campaign(kernels, workers=1, campaign_dir=tmp_path / 'ref', backend='pure-python')

    drill = tmp_path / 'drill'
    camp.create_campaign(drill, kernels, backend='pure-python')
    env = dict(os.environ, DA4ML_FAULT_INJECT='campaign.solve=sleep:1:120')
    victim = camp._spawn_worker(drill, 'victim', ttl_s=1.0, poll_s=0.1, deadline_per_solve=None, env=env)
    try:
        deadline = time.monotonic() + 60
        victim_key = None
        while victim_key is None and time.monotonic() < deadline:
            for key, doc in list_leases(drill / 'leases').items():
                if doc.get('owner') == 'victim':
                    victim_key = key
            time.sleep(0.05)
        assert victim_key is not None, 'victim never claimed a lease'
        os.kill(victim.pid, signal.SIGKILL)
        victim.communicate(timeout=60)
    finally:
        if victim.poll() is None:
            victim.kill()

    summary = camp.worker_loop(drill, owner='survivor', ttl_s=1.0, grace_s=0.4, poll_s=0.1)
    assert summary['complete'] and summary['stolen'] >= 1
    results = camp.collect_results(drill)
    assert _blobs(results) == _blobs(ref)
    owners = {doc['key']: doc['owner'] for doc in results}
    assert owners[victim_key] == 'survivor'  # the victim's kernel was rescued
    stolen_docs = [d for d in results if d.get('stolen_from')]
    assert any(d['key'] == victim_key for d in stolen_docs)


def test_campaign_jax_backend_on_mesh(tmp_path):
    """A campaign through the device chain on the 8-device virtual CPU mesh
    (conftest): results land durable + resume is a pure checkpoint read."""
    kernels = _corpus(2, dim=4, bits=2)
    camp.create_campaign(tmp_path / 'c', kernels, backend='jax')
    summary = camp.worker_loop(tmp_path / 'c', ttl_s=60.0)
    assert summary['complete'] and summary['n_solved'] == 2
    first = camp.collect_results(tmp_path / 'c')
    assert all(doc['backend'] == 'jax' for doc in first)
    # a second worker over the finished directory solves nothing
    again = camp.worker_loop(tmp_path / 'c', owner='late-joiner', ttl_s=60.0)
    assert again['complete'] and again['n_solved'] == 0
    assert _blobs(camp.collect_results(tmp_path / 'c')) == _blobs(first)


# ------------------------------------------------------------------ health plane


def test_campaign_status_and_healthz_degrade_on_stalled_worker(tmp_path):
    kernels = _corpus(2)
    camp.create_campaign(tmp_path / 'c', kernels, backend='pure-python')
    d = camp._dirs(tmp_path / 'c')
    camp._beat_worker(d['workers'], 'live-worker', done=0)
    stale = {'owner': 'dead-worker', 'pid': 1, 'ts': time.time() - 900, 'done': 1}
    (d['workers'] / 'dead-worker.json').write_text(json.dumps(stale))

    st = camp.campaign_status(tmp_path / 'c', stall_s=60.0)
    assert st['in_progress'] and st['total'] == 2 and st['done'] == 0
    assert st['stalled'] == ['dead-worker'] and st['workers_alive'] == 1

    # /healthz: a stalled worker of the active campaign degrades health
    from da4ml_tpu.telemetry.obs.health import health_snapshot

    camp._ACTIVE_DIR = str(tmp_path / 'c')
    doc = health_snapshot()
    assert doc['status'] == 'degraded'
    assert doc['checks']['campaign']['workers']['stalled'] == ['dead-worker']
    camp._ACTIVE_DIR = None
    assert camp.worker_health() is None
    assert health_snapshot()['checks']['campaign'].get('workers') is None


# ------------------------------------------------------------------ CLI


def test_cli_load_corpus_specs(tmp_path):
    from da4ml_tpu._cli.campaign import load_corpus

    q = load_corpus('quality:3')
    assert len(q) == 3 and all(k.ndim == 2 for k in q)
    assert _blobs([]) == {}  # sanity: helper tolerates empty
    np.testing.assert_array_equal(load_corpus('quality:3')[0], q[0])  # deterministic

    npz = tmp_path / 'c.npz'
    np.savez(npz, a=q[0], b=q[1])
    loaded = load_corpus(str(npz))
    assert len(loaded) == 2

    stack = tmp_path / 's.npy'
    np.save(stack, np.stack([np.ones((3, 3)), np.zeros((3, 3))]))
    assert len(load_corpus(str(stack))) == 2

    js = tmp_path / 'k.json'
    js.write_text(json.dumps([[[1, 2], [3, 4]]]))
    assert load_corpus(str(js))[0].shape == (2, 2)

    assert len(load_corpus(str(tmp_path))) == 5  # directory walk
    with pytest.raises(ValueError, match='unrecognized corpus'):
        load_corpus(str(tmp_path / 'missing.txt'))


def test_cli_campaign_run_and_status(tmp_path, capsys):
    from da4ml_tpu._cli import main

    rc = main(
        [
            'campaign',
            'drill:2',
            '--workers',
            '1',
            '--backend',
            'pure-python',
            '--dir',
            str(tmp_path / 'c'),
            '--out',
            str(tmp_path / 'report.json'),
        ]
    )
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line['n_kernels'] == 2 and line['total_cost'] > 0
    report = json.loads((tmp_path / 'report.json').read_text())
    assert report['workers'] == 1 and len(report['costs']) == 2

    rc = main(['campaign', '--status', str(tmp_path / 'c')])
    assert rc == 0
    st = json.loads(capsys.readouterr().out)
    assert st['done'] == 2 and not st['in_progress']

    # resume of a finished dir is a fast no-op with identical results
    rc = main(
        ['campaign', 'drill:2', '--workers', '1', '--backend', 'pure-python', '--dir', str(tmp_path / 'c'), '--resume']
    )
    assert rc == 0
    assert json.loads(capsys.readouterr().out.strip().splitlines()[-1])['total_cost'] == line['total_cost']


def test_cli_campaign_bad_corpus_exit_code(tmp_path, capsys):
    from da4ml_tpu._cli import main

    assert main(['campaign', str(tmp_path / 'nope.npz')]) == 2
    assert main(['campaign']) == 2


# ------------------------------------------------------------------ satellites


def test_connect_budget_env_overrides(monkeypatch):
    from da4ml_tpu.parallel.distributed import (
        DEFAULT_CONNECT_RETRIES,
        DEFAULT_CONNECT_TIMEOUT_S,
        connect_budget,
    )

    monkeypatch.delenv('DA4ML_DIST_CONNECT_RETRIES', raising=False)
    monkeypatch.delenv('DA4ML_DIST_CONNECT_TIMEOUT_S', raising=False)
    assert connect_budget() == (DEFAULT_CONNECT_RETRIES, DEFAULT_CONNECT_TIMEOUT_S)
    monkeypatch.setenv('DA4ML_DIST_CONNECT_RETRIES', '7')
    monkeypatch.setenv('DA4ML_DIST_CONNECT_TIMEOUT_S', '120')
    assert connect_budget() == (7, 120.0)
    monkeypatch.setenv('DA4ML_DIST_CONNECT_RETRIES', 'junk')
    monkeypatch.setenv('DA4ML_DIST_CONNECT_TIMEOUT_S', '-3')
    retries, timeout_s = connect_budget()
    assert retries == DEFAULT_CONNECT_RETRIES and timeout_s == 1.0  # clamped floor


def test_checkpoint_write_still_durable_roundtrip(tmp_path):
    """The checkpoint satellite: saves still round-trip through the new
    atomic_write_bytes path (tmp+fsync+rename+dirfsync)."""
    from da4ml_tpu.reliability import CheckpointStore

    store = CheckpointStore(tmp_path / 'ck.json')
    store.put('k1', {'cost': 3.0})
    fresh = CheckpointStore(tmp_path / 'ck.json')
    assert fresh.records['k1'] == {'cost': 3.0}
    assert list(tmp_path.glob('*.tmp*')) == []
