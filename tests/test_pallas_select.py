"""Pallas fused selection: decision-identical with the XLA select path.

Runs in interpret mode off-TPU (tests force the CPU platform), so this
validates semantics; performance is measured on hardware by bench.py.
"""

import numpy as np
import pytest

from da4ml_tpu.cmvm.jax_search import _build_cse_fn, solve_jax_many


def _with_select(monkeypatch, impl):
    monkeypatch.setenv('DA4ML_JAX_SELECT', impl)
    _build_cse_fn.cache_clear()


def random_kernel(rng, n_dim, bits):
    mag = rng.integers(0, 2**bits, (n_dim, n_dim)).astype(np.float64)
    sign = rng.choice([-1.0, 1.0], (n_dim, n_dim))
    return mag * sign


@pytest.mark.parametrize('method0', ['mc', 'wmc'])
def test_pallas_select_decision_identical(rng, monkeypatch, method0):
    kernels = [random_kernel(rng, 6, 3) for _ in range(3)]

    _with_select(monkeypatch, 'xla')
    ref = solve_jax_many(kernels, method0=method0)

    _with_select(monkeypatch, 'pallas')
    got = solve_jax_many(kernels, method0=method0)
    _build_cse_fn.cache_clear()

    for k, a, b in zip(kernels, ref, got):
        np.testing.assert_array_equal(np.asarray(b.kernel, np.float64), k)
        assert a.cost == b.cost, (a.cost, b.cost)
        for sa, sb in zip(a.stages, b.stages):
            assert len(sa.ops) == len(sb.ops)
            for oa, ob in zip(sa.ops, sb.ops):
                assert oa == ob


def test_pallas_select_hard_dc(rng, monkeypatch):
    kernel = random_kernel(rng, 6, 4)
    _with_select(monkeypatch, 'pallas')
    sol = solve_jax_many([kernel], hard_dc=1)[0]
    _build_cse_fn.cache_clear()
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)


def _np_select(Cs, Cd, nov, dlat, coef):
    """Numpy reference of the fused select: host-order tie-break (largest
    (id1, id0, sub, shift) key among score maxima), returning the rank
    parts (major, minor) the kernel emits."""
    S, P, _ = Cs.shape
    w_mc, w_ov, pen, absolute = coef[0]
    idx = np.arange(P)
    s0 = (np.arange(S)[None, :, None, None] > 0) | (idx[None, None, :, None] < idx[None, None, None, :])
    out = []
    for c in (Cs, Cd):
        cf = c.astype(np.float64)
        score = w_mc * cf + w_ov * cf * nov[None] - pen * dlat[None]
        valid = (cf >= 2) & s0[0] & ((absolute == 0) | (score >= 0))
        out.append(np.where(valid, score, -np.inf))
    score = np.stack(out)  # [2, S, P, P]
    m = score.max()
    if not np.isfinite(m):
        return -1, -1, False
    sub_ax, s_ax, i_ax, j_ax = np.indices(score.shape)
    major = np.maximum(i_ax, j_ax) * P + np.minimum(i_ax, j_ax)
    minor = sub_ax * (2 * S + 1) + np.where(i_ax < j_ax, s_ax, -s_ax) + S
    tie = score == m
    r1 = major[tie].max()
    r2 = minor[tie & (major == r1)].max()
    return int(r1), int(r2), True


@pytest.mark.parametrize('P', [24, 512])  # 512 exercises RB > 1 with a ragged last tile
@pytest.mark.parametrize('coef_row', [(1.0, 0.0, 0.0, 0.0), (0.0, 1.0, 0.0, 1.0), (1.0, 0.0, 1e9, 1.0)])
def test_make_select_tiled_matches_numpy(rng, P, coef_row):
    """Kernel-level check incl. the row-tiled path end-to-end tests never hit."""
    import jax

    from da4ml_tpu.cmvm.pallas_select import _row_tile, make_select

    B = 4
    if P == 512:
        assert P % _row_tile(P) != 0, 'pick P so the last tile is ragged'
    Cs = rng.integers(0, 7, (B, P, P)).astype(np.int16)
    Cd = rng.integers(0, 7, (B, P, P)).astype(np.int16)
    nov = rng.uniform(0.5, 4.0, (P, P)).astype(np.float32)
    dlat = rng.integers(0, 3, (P, P)).astype(np.float32)
    coef = np.asarray([coef_row], np.float32)

    sel = make_select(P, B, 'int16', interpret=jax.default_backend() != 'tpu')
    r1, r2, any_valid = jax.jit(sel)(Cs, Cd, nov, dlat, coef)
    ref_r1, ref_r2, ref_valid = _np_select(Cs, Cd, nov.astype(np.float64), dlat.astype(np.float64), coef.astype(np.float64))
    assert bool(any_valid) == ref_valid
    if ref_valid:
        assert (int(r1), int(r2)) == (ref_r1, ref_r2)
