"""Pallas fused selection: decision-identical with the XLA select path.

Runs in interpret mode off-TPU (tests force the CPU platform), so this
validates semantics; performance is measured on hardware by bench.py.
"""

import numpy as np
import pytest

from da4ml_tpu.cmvm.jax_search import _build_cse_fn, solve_jax_many


def _with_select(monkeypatch, impl):
    monkeypatch.setenv('DA4ML_JAX_SELECT', impl)
    _build_cse_fn.cache_clear()


def random_kernel(rng, n_dim, bits):
    mag = rng.integers(0, 2**bits, (n_dim, n_dim)).astype(np.float64)
    sign = rng.choice([-1.0, 1.0], (n_dim, n_dim))
    return mag * sign


@pytest.mark.parametrize('method0', ['mc', 'wmc'])
def test_pallas_select_decision_identical(rng, monkeypatch, method0):
    kernels = [random_kernel(rng, 6, 3) for _ in range(3)]

    _with_select(monkeypatch, 'xla')
    ref = solve_jax_many(kernels, method0=method0)

    _with_select(monkeypatch, 'pallas')
    got = solve_jax_many(kernels, method0=method0)
    _build_cse_fn.cache_clear()

    for k, a, b in zip(kernels, ref, got):
        np.testing.assert_array_equal(np.asarray(b.kernel, np.float64), k)
        assert a.cost == b.cost, (a.cost, b.cost)
        for sa, sb in zip(a.stages, b.stages):
            assert len(sa.ops) == len(sb.ops)
            for oa, ob in zip(sa.ops, sb.ops):
                assert oa == ob


def test_pallas_select_hard_dc(rng, monkeypatch):
    kernel = random_kernel(rng, 6, 4)
    _with_select(monkeypatch, 'pallas')
    sol = solve_jax_many([kernel], hard_dc=1)[0]
    _build_cse_fn.cache_clear()
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), kernel)
