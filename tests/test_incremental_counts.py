"""Invariant: incrementally-maintained pair counts == full recount.

The device CSE carries the pair-count tensors in its while-loop state and
refreshes only the rows touched by each substitution (jax_search
``update_counts``; strategy of the reference's dirty-row ``update_stats``,
src/da4ml/_binary/cmvm/state_opr.cc:285-345 of calad0i/da4ml). Oracle test:
a from-scratch numpy greedy loop — full pair recount before every selection,
same mc scoring, same host-order tie-break (largest (id1, id0, sub, shift)
key among maxima, matching the host solver's >=-scan), same substitution
semantics — must produce exactly the device kernel's op records across a
multi-iteration call. Any drift in the carried counts changes a selection
and the sequences diverge.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip('jax')
import jax.numpy as jnp  # noqa: E402

from da4ml_tpu.cmvm.csd import csd_decompose  # noqa: E402
from da4ml_tpu.cmvm.jax_search import _KernelSpec, _build_cse_fn, _unpack_digits  # noqa: E402


def _full_counts(E):
    """C_same/C_diff [S, P, P]: matches of E[i] bit b with E[j] bit b+s."""
    P, O, B = E.shape
    Cs = np.zeros((B, P, P), np.int32)
    Cd = np.zeros((B, P, P), np.int32)
    for s in range(B):
        sh = np.zeros_like(E)
        sh[:, :, : B - s] = E[:, :, s:]
        both = (E[:, None] != 0) & (sh[None, :] != 0)
        same = both & (E[:, None] == sh[None, :])
        Cs[s] = same.sum((2, 3))
        Cd[s] = (both & ~same).sum((2, 3))
    return Cs, Cd


def _np_substitute(E, cur, sub, s, i, j):
    """Numpy mirror of the device ``substitute`` + new-row placement."""
    O, B = E.shape[1:]
    row_i = E[i].copy()
    row_j = E[j].copy()
    shifted_j = np.zeros_like(row_j)
    shifted_j[:, : B - s] = row_j[:, s:] if s else row_j[:, :]
    target = -1 if sub else 1
    sign_ok = (row_i != 0) & (shifted_j != 0) & (row_i * shifted_j == target)

    if i == j:
        avail = row_i != 0
        M = np.zeros((O, B), bool)
        for b in range(B):
            nxt = avail[:, b + s] if b + s < B else np.zeros(O, bool)
            ok = sign_ok[:, b] & avail[:, b] & nxt
            avail[:, b] &= ~ok
            if b + s < B:
                avail[:, b + s] &= ~ok
            M[:, b] = ok
    else:
        M = sign_ok

    M_up = np.zeros((O, B), bool)
    M_up[:, s:] = M[:, : B - s] if s else M[:, :]
    E[i][M] = 0
    E[j][M_up] = 0
    E[cur] = (M * row_i) if i < j else (M_up * row_j)


@pytest.mark.parametrize('dup', [False, True])
def test_select_place_matches_scatter(dup):
    """_select_place is the loop body's replacement for vector-indexed mid-axis
    scatters (a TPU scatter kernel dominated the whole iteration); it must be
    value-identical to `.at[...].set` for distinct and duplicate row indices
    (duplicates always carry identical payload slices at the call sites)."""
    from da4ml_tpu.cmvm.jax_search import _select_place

    rng = np.random.default_rng(3)
    S, P, K = 4, 16, 5
    base = jnp.asarray(rng.standard_normal((2, S, P, K)).astype(np.float32))
    R_np = np.asarray([2, 2, 9] if dup else [2, 7, 9], np.int32)
    src_np = rng.standard_normal((2, S, 3, K)).astype(np.float32)
    if dup:  # duplicate indices must carry identical payloads (call-site invariant)
        src_np[:, :, 1] = src_np[:, :, 0]
    src = jnp.asarray(src_np)
    R = jnp.asarray(R_np)
    got = _select_place(base, src, R, 2)
    want = base.at[:, :, R_np].set(src)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    base3 = jnp.asarray(rng.standard_normal((S, P, P)).astype(np.float32))
    srcr_np = rng.standard_normal((S, 3, P)).astype(np.float32)
    if dup:
        srcr_np[:, 1] = srcr_np[:, 0]
    srcr = jnp.asarray(srcr_np)
    np.testing.assert_array_equal(
        np.asarray(_select_place(base3, srcr, R, 1)), np.asarray(base3.at[:, R_np, :].set(srcr))
    )


@pytest.mark.parametrize('select', ['xla', 'top4'])
@pytest.mark.parametrize('seed', [0, 1, 2])
def test_incremental_counts_match_numpy_oracle(seed, select):
    rng = np.random.default_rng(seed)
    kernel = (rng.integers(0, 16, (6, 8)) * rng.choice([-1, 1], (6, 8))).astype(np.float64)
    csd, _, _ = csd_decompose(kernel)
    ni, no, nb = csd.shape
    K = 10
    P = ni + K

    # device path: one call, K iterations; 'xla' carries counts and
    # rescans, 'top4' maintains the O(S*P) score cache — at this scale both
    # must reproduce the full-recount oracle's decisions exactly
    E0 = np.zeros((1, P, no, nb), np.int8)
    E0[0, :ni] = csd
    q0 = np.zeros((1, P, 3), np.float32)
    q0[:, :, 0], q0[:, :, 1], q0[:, :, 2] = -128.0, 127.0, 1.0
    fn = _build_cse_fn(_KernelSpec(P, no, nb, -1, -1, select))
    E_dev, _, _, rec, cur = fn(
        jnp.asarray(E0),
        jnp.asarray(q0),
        jnp.zeros((1, P), jnp.float32),
        jnp.full((1,), ni, jnp.int32),
        jnp.zeros((1,), jnp.int32),  # method 0 == mc: score is the raw count
    )
    n_dev = int(cur[0]) - ni
    rec_dev = [tuple(int(v) for v in r) for r in np.asarray(rec)[0, :n_dev]]

    # oracle path: full recount before every selection
    E_ref = np.zeros((P, no, nb), np.int8)
    E_ref[:ni] = csd
    rec_ref = []
    for step in range(K):
        Cs, Cd = _full_counts(E_ref)
        C = np.stack([Cs, Cd]).astype(np.float64)
        idx = np.arange(P)
        s0 = (np.arange(nb)[None, :, None, None] > 0) | (idx[None, None, :, None] < idx[None, None, None, :])
        score = np.where((C >= 2) & s0, C, -np.inf)
        m = score.max()
        if not np.isfinite(m):
            break
        # host scan order: among maxima take the largest (id1, id0, sub, shift)
        sub_ax, s_ax, i_ax, j_ax = np.indices(score.shape)
        id0_ax, id1_ax = np.minimum(i_ax, j_ax), np.maximum(i_ax, j_ax)
        shift_ax = np.where(i_ax < j_ax, s_ax, -s_ax)
        tie = score == m
        major = id1_ax * P + id0_ax
        r1 = major[tie].max()
        tie &= major == r1
        r2 = (sub_ax * (2 * nb + 1) + shift_ax + nb)[tie].max()
        id1_w, id0_w = divmod(r1, P)
        sub, sk = divmod(r2, 2 * nb + 1)
        shift = sk - nb
        i = id0_w if shift >= 0 else id1_w
        j = id1_w if shift >= 0 else id0_w
        s = abs(shift)
        _np_substitute(E_ref, ni + step, sub, s, i, j)
        rec_ref.append((min(i, j), max(i, j), sub, s if i < j else -s))

    assert n_dev > 0, 'no CSE opportunity in this kernel; pick another seed'
    assert rec_dev == rec_ref
    np.testing.assert_array_equal(_unpack_digits(np.asarray(E_dev), no, nb)[0], E_ref)
