"""Fault-injection smoke suite for the reliability subsystem.

Fast, CPU-only (conftest pins JAX_PLATFORMS=cpu): the fallback chain's
bit-exactness, deadline firing, retry/backoff on transients, circuit
breakers, checkpointed kill/resume, and regression tests for the netlist
port-width / prewarm-return / simulate-data satellites.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from da4ml_tpu.cmvm import solve
from da4ml_tpu.reliability import (
    BackendUnavailable,
    CheckpointCorrupt,
    CheckpointStore,
    SolveReport,
    SolveTimeout,
    TransientError,
    breaker_for,
    classify,
    fault_injection,
    kernel_key,
    reset_all_breakers,
    reset_store_cache,
    retry_call,
    run_program,
    run_with_deadline,
    solve_many,
)

REPO_ROOT = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _isolate_reliability_state():
    reset_all_breakers()
    reset_store_cache()
    yield
    reset_all_breakers()
    reset_store_cache()


def _kernel(rng, n=8, bits=3):
    return (rng.integers(0, 2**bits, (n, n)) * rng.choice([-1.0, 1.0], (n, n))).astype(np.float64)


def _ops_sig(p):
    return [[(o.id0, o.id1, o.opcode, o.data) for o in st.ops] for st in p.stages]


# --------------------------------------------------------------- fallback


def test_fallback_chain_bit_exact_vs_native(rng):
    """JAX disabled by fault injection: solve degrades and the result is
    bit-identical (ops, cost, outputs) to the direct native/host path."""
    k = _kernel(rng)
    rep = SolveReport()
    with fault_injection('cmvm.jax=unavailable'):
        degraded = solve(k, backend='jax', report=rep)
    direct = solve(k, backend='auto', fallback=False)

    assert rep.backend_used in ('native-threads', 'pure-python')
    assert rep.degraded
    assert rep.chain == ('jax', 'native-threads', 'pure-python')
    assert rep.attempts[0].backend == 'jax' and not rep.attempts[0].ok
    assert rep.attempts[0].error_kind == 'fallback'
    assert rep.attempts[1].ok

    assert float(degraded.cost) == float(direct.cost)
    assert _ops_sig(degraded) == _ops_sig(direct)
    data = rng.uniform(-8, 8, (64, k.shape[0]))
    np.testing.assert_array_equal(degraded.predict(data, backend='numpy'), direct.predict(data, backend='numpy'))


def test_fallback_walks_to_pure_python(rng):
    """Both device and native backends down: the pure-python reference
    answers, and the report shows the whole walk."""
    k = _kernel(rng)
    rep = SolveReport()
    with fault_injection('cmvm.jax=unavailable,cmvm.native=unavailable'):
        degraded = solve(k, backend='jax', report=rep)
    direct = solve(k, backend='cpu', fallback=False)
    assert rep.backend_used == 'pure-python'
    assert [a.backend for a in rep.attempts] == ['jax', 'native-threads', 'pure-python']
    assert _ops_sig(degraded) == _ops_sig(direct)


def test_fault_inject_env_var(rng, monkeypatch):
    """The DA4ML_FAULT_INJECT env var (not just the context manager) drives
    the chain — the form subprocess campaigns use."""
    monkeypatch.setenv('DA4ML_FAULT_INJECT', 'cmvm.jax=unavailable')
    rep = SolveReport()
    solve(_kernel(rng), backend='jax', report=rep)
    assert rep.degraded and rep.backend_used != 'jax'


def test_chain_exhaustion_raises(rng):
    with fault_injection('cmvm.cpu=unavailable'):
        with pytest.raises(BackendUnavailable, match='all backends failed'):
            solve(_kernel(rng), backend='cpu', report=SolveReport())


def test_fatal_errors_do_not_fall_back():
    with pytest.raises(ValueError, match='non-empty 2D matrix'):
        solve(np.zeros((0, 4)), backend='jax')


def test_fallback_disabled_raises(rng, monkeypatch):
    """DA4ML_SOLVE_FALLBACK=0 restores raise-on-failure: the injected
    device error propagates raw, with no orchestration in the stack."""
    monkeypatch.setenv('DA4ML_SOLVE_FALLBACK', '0')
    with fault_injection('cmvm.jax=unavailable'):
        with pytest.raises(BackendUnavailable, match='injected fault'):
            solve(_kernel(rng), backend='jax')


# --------------------------------------------------------------- deadline


def test_deadline_fires_within_2x_budget():
    t0 = time.monotonic()
    with pytest.raises(SolveTimeout):
        run_with_deadline(time.sleep, 0.15, 5.0)
    assert time.monotonic() - t0 < 0.3


def test_solve_deadline_raises_instead_of_hanging(rng):
    """A (simulated) hung backend with a 0.3s budget raises SolveTimeout
    within 2x the budget instead of blocking for the full hang."""
    k = _kernel(rng)
    t0 = time.monotonic()
    with fault_injection('cmvm.cpu=sleep:1:3'):
        with pytest.raises(SolveTimeout):
            solve(k, backend='cpu', deadline=0.3)
    assert time.monotonic() - t0 < 0.6


def test_deadline_untriggered_returns_result(rng):
    k = _kernel(rng)
    rep = SolveReport()
    sol = solve(k, backend='cpu', deadline=60.0, report=rep)
    assert rep.backend_used == 'pure-python' and float(sol.cost) > 0


# ----------------------------------------------------------------- retry


def test_retry_call_backoff_and_jitter():
    calls, delays = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientError('flake')
        return 'ok'

    assert retry_call(flaky, retries=4, base_delay=0.05, on_retry=lambda a, e, d: delays.append(d), sleep=lambda s: None) == 'ok'
    assert len(calls) == 3 and len(delays) == 2
    # full jitter: every delay within the exponential envelope
    assert 0 <= delays[0] <= 0.05 and 0 <= delays[1] <= 0.1


def test_retry_does_not_retry_fatal():
    calls = []

    def bad():
        calls.append(1)
        raise ValueError('malformed request')

    with pytest.raises(ValueError):
        retry_call(bad, retries=5, sleep=lambda s: None)
    assert len(calls) == 1


def test_transient_fault_retried_same_backend(rng):
    """Two injected transient failures: the solve stays on the requested
    backend, recording the retries — no degradation."""
    k = _kernel(rng)
    rep = SolveReport()
    with fault_injection('cmvm.cpu=transient:2'):
        solve(k, backend='cpu', report=rep)
    assert rep.backend_used == 'pure-python'
    assert not rep.degraded
    assert rep.attempts[0].ok and rep.attempts[0].retries == 2


def test_classify_taxonomy():
    assert classify(TransientError('x')) == 'retryable'
    assert classify(ConnectionError('refused')) == 'retryable'
    assert classify(RuntimeError('connection reset by peer')) == 'retryable'
    assert classify(SolveTimeout('x')) == 'fallback'
    assert classify(BackendUnavailable('x')) == 'fallback'
    assert classify(RuntimeError('RESOURCE_EXHAUSTED: out of memory')) == 'fallback'
    assert classify(ImportError('no module named jax')) == 'fallback'
    assert classify(ValueError('bad shape')) == 'fatal'


# --------------------------------------------------------------- breaker


def test_circuit_breaker_opens_and_skips(rng):
    k = _kernel(rng)
    with fault_injection('cmvm.jax=unavailable:100'):
        for _ in range(3):  # default fail_threshold
            solve(k, backend='jax')
        rep = SolveReport()
        solve(k, backend='jax', report=rep)
    assert breaker_for('jax').state == 'open'
    assert rep.attempts[0].backend == 'jax' and rep.attempts[0].error_kind == 'skipped'
    assert rep.backend_used in ('native-threads', 'pure-python')


def test_circuit_breaker_half_open_probe_recovers():
    br = breaker_for('probe-test', fail_threshold=2, reset_after=0.05)
    br.record_failure()
    br.record_failure()
    assert br.state == 'open' and not br.allow()
    time.sleep(0.06)
    assert br.state == 'half-open'
    assert br.allow()  # the probe slot
    assert not br.allow()  # only one probe at a time
    br.record_success()
    assert br.state == 'closed' and br.allow()


# ------------------------------------------------------------ checkpoint


def test_checkpoint_resume_after_kill(rng, tmp_path):
    """Kill a campaign child right after its first durable record; the
    resumed run must produce results identical to an uninterrupted one
    (the tests/multiproc_worker.py child pattern)."""
    ckpt = tmp_path / 'campaign.json'
    child = tmp_path / 'child.py'
    child.write_text(
        'import json, sys\n'
        f'sys.path.insert(0, {str(REPO_ROOT)!r})\n'
        'import numpy as np\n'
        'from da4ml_tpu.reliability import solve_many\n'
        'rng = np.random.default_rng(7)\n'
        'ks = [(rng.integers(0, 8, (6, 6)) * rng.choice([-1.0, 1.0], (6, 6))) for _ in range(3)]\n'
        f'res, rep = solve_many(ks, backend="cpu", checkpoint={str(ckpt)!r})\n'
        'print(json.dumps({"n": len(res), "hits": rep.checkpoint_hits}))\n'
    )
    env = dict(os.environ, DA4ML_FAULT_INJECT='checkpoint.post_save=kill:1')
    r1 = subprocess.run([sys.executable, str(child)], capture_output=True, text=True, timeout=120, env=env)
    assert r1.returncode != 0, 'child should have been hard-killed'
    assert len(CheckpointStore(ckpt).records) == 1, 'exactly the first result should be durable'

    env2 = {k: v for k, v in os.environ.items() if k != 'DA4ML_FAULT_INJECT'}
    r2 = subprocess.run([sys.executable, str(child)], capture_output=True, text=True, timeout=120, env=env2)
    assert r2.returncode == 0, r2.stderr[-1000:]
    out = json.loads(r2.stdout.strip().splitlines()[-1])
    assert out == {'n': 3, 'hits': 1}

    # uninterrupted reference run, same campaign definition
    rng7 = np.random.default_rng(7)
    ks = [(rng7.integers(0, 8, (6, 6)) * rng7.choice([-1.0, 1.0], (6, 6))) for _ in range(3)]
    fresh, _ = solve_many(ks, backend='cpu')
    store = CheckpointStore(ckpt)
    resumed = sorted(json.dumps(rec['pipeline'], sort_keys=True) for rec in store.records.values())
    expect = sorted(json.dumps(p.to_dict(), sort_keys=True) for p in fresh)
    assert resumed == expect


def test_checkpoint_atomic_and_keyed(rng, tmp_path):
    ckpt = tmp_path / 'ck.json'
    k = _kernel(rng)
    rep1 = SolveReport()
    sol1 = solve(k, backend='cpu', checkpoint=ckpt, report=rep1)
    assert rep1.checkpoint_misses == 1 and rep1.checkpoint_hits == 0
    reset_store_cache()  # force a re-read from disk
    rep2 = SolveReport()
    sol2 = solve(k, backend='cpu', checkpoint=ckpt, report=rep2)
    assert rep2.checkpoint_hits == 1 and rep2.checkpoint_misses == 0
    assert _ops_sig(sol1) == _ops_sig(sol2)
    # a different option set must miss (key covers kernel AND options)
    rep3 = SolveReport()
    solve(k, backend='cpu', hard_dc=2, checkpoint=ckpt, report=rep3)
    assert rep3.checkpoint_misses == 1
    assert kernel_key(k, {'a': 1}) != kernel_key(k, {'a': 2})


def test_checkpoint_corrupt_quarantine_and_strict(tmp_path):
    ckpt = tmp_path / 'bad.json'
    ckpt.write_text('{"version": 1, "records": {tr')  # torn write
    with pytest.raises(CheckpointCorrupt):
        CheckpointStore(ckpt, strict=True)
    store = CheckpointStore(ckpt)  # non-strict: quarantine + fresh start
    assert store.recovered_corrupt and len(store.records) == 0
    assert (tmp_path / 'bad.json.corrupt').exists()


def test_checkpoint_injected_corrupt_write_recovers(tmp_path):
    ckpt = tmp_path / 'c.json'
    store = CheckpointStore(ckpt)
    with fault_injection('checkpoint.write=corrupt:1'):
        store.put('k1', {'v': 1})  # this flush writes torn JSON
    reset_store_cache()
    reread = CheckpointStore(ckpt)
    assert reread.recovered_corrupt and 'k1' not in reread
    store2 = CheckpointStore(ckpt)
    store2.put('k2', {'v': 2})
    assert CheckpointStore(ckpt).get('k2') == {'v': 2}


# ------------------------------------------------------- runtime chain


def test_run_program_degrades_bit_exact(rng):
    k = _kernel(rng, n=6)
    comb = solve(k, backend='cpu', fallback=False).stages[0]
    binary = comb.to_binary()
    data = rng.uniform(-8, 8, (32, 6))
    from da4ml_tpu.runtime.numpy_backend import run_binary as run_np

    golden = run_np(binary, data)
    rep = SolveReport()
    with fault_injection('runtime.jax=unavailable'):
        out = run_program(binary, data, report=rep)
    assert rep.backend_used in ('cpp', 'numpy') and rep.degraded
    np.testing.assert_array_equal(out, golden)


# ------------------------------------------------- satellite regressions


def test_netlist_sim_rejects_unparsed_ports():
    from da4ml_tpu.codegen.rtl.verilog.netlist_sim import VerilogNetlistSim, VerilogPipelineSim
    from da4ml_tpu.codegen.rtl.vhdl.netlist_sim import VHDLNetlistSim, VHDLPipelineSim

    with pytest.raises(ValueError, match='Unparsed module ports'):
        VerilogNetlistSim('module m(inp, out);\nendmodule', {})
    with pytest.raises(ValueError, match='Unparsed pipelined top ports'):
        VerilogPipelineSim('module top(clk, inp, out);\nendmodule', [], {})
    with pytest.raises(ValueError, match='Unparsed entity ports'):
        VHDLNetlistSim('entity e is end entity;\narchitecture rtl of e is\nbegin\nend architecture;', {})
    with pytest.raises(ValueError, match='Unparsed VHDL top ports'):
        VHDLPipelineSim('entity t is end entity;\narchitecture rtl of t is\nbegin\nend architecture;', [], {})


def test_prewarm_returns_queued_flag(monkeypatch, rng):
    import da4ml_tpu.cmvm.jax_search as js

    submitted = []
    monkeypatch.setattr(js, '_prewarm_enabled', lambda: True)
    monkeypatch.setattr(js, '_prewarm_submit', lambda job: submitted.append(job))
    assert js.prewarm_for_kernels([[_kernel(rng)]]) == 1
    assert len(submitted) == 1
    assert js.prewarm_for_kernels([]) == 0
    assert js.prewarm_for_kernels([[]]) == 0
    monkeypatch.setattr(js, '_prewarm_enabled', lambda: False)
    assert js.prewarm_for_kernels([[_kernel(rng)]]) == 0


def test_simulate_requires_data():
    from da4ml_tpu.codegen.rtl.verilog.netlist_sim import simulate_comb, simulate_pipeline
    from da4ml_tpu.codegen.rtl.vhdl.netlist_sim import simulate_comb_vhdl, simulate_pipeline_vhdl

    for fn in (simulate_comb, simulate_pipeline, simulate_comb_vhdl, simulate_pipeline_vhdl):
        with pytest.raises(ValueError, match='data batch, got None'):
            fn(None, data=None)


# ---------------------------------------------------------- CLI surface


def test_convert_cli_accepts_reliability_flags():
    from da4ml_tpu._cli.convert import add_convert_args
    import argparse

    p = argparse.ArgumentParser()
    add_convert_args(p)
    args = p.parse_args(['m.json', 'out', '--deadline', '2.5', '--fallback', 'off', '--resume', 'ck.json'])
    assert args.deadline == 2.5 and args.fallback == 'off' and args.resume == Path('ck.json')


def test_tracer_batched_jax_degrades(rng):
    """A device failure inside the tracer's batched matmul path degrades to
    the host chain instead of losing the trace."""
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    w = rng.integers(-4, 4, (4, 3)).astype(np.float64)

    def _trace():
        inp = FixedVariableArrayInput((2, 4), hwconf=HWConfig(1, -1, -1), solver_options={'backend': 'jax'})
        # distinct per-row precisions -> >1 unique metadata group -> the
        # batched solve_jax_many path (the one _solve_jax_many_guarded wraps)
        f = np.stack([np.full(4, 2), np.full(4, 3)])
        x = inp.quantize(np.ones((2, 4)), np.full((2, 4), 3), f)
        return comb_trace(inp, x @ w)

    golden = _trace()  # healthy device path (cpu-XLA here)
    with fault_injection('cmvm.jax=unavailable:100'):
        with pytest.warns(RuntimeWarning, match='degrading'):
            degraded = _trace()
    data = rng.uniform(-4, 4, (16, 8))
    np.testing.assert_array_equal(
        degraded.predict(data, backend='numpy'), golden.predict(data, backend='numpy')
    )
