"""Deterministic interleaving harness: seeded-schedule reproduction and
injected-corruption self-tests (the X512/X513 detector must actually fire)."""

import pytest

from da4ml_tpu.analysis.interleave import SCENARIOS, run_scenario, run_suite

FAST_SCENARIOS = ['fleet', 'lease', 'queue', 'router']  # 'store' pays real backoff sleeps


def _rules(result):
    return [d.rule for d in result.diagnostics]


@pytest.mark.parametrize('name', sorted(SCENARIOS))
def test_scenario_passes_at_seed_zero(name):
    result = run_scenario(name, seed=0)
    assert result.ok, '\n'.join(d.message for d in result.diagnostics)


@pytest.mark.parametrize('name', FAST_SCENARIOS)
def test_schedule_log_is_byte_identical(name):
    a = run_scenario(name, seed=7)
    b = run_scenario(name, seed=7)
    assert a.deterministic_log
    assert a.log == b.log
    assert a.log  # a real schedule, not an empty pass


def test_different_seeds_explore_different_schedules():
    logs = {run_scenario('queue', seed=s).log for s in range(8)}
    assert len(logs) > 1


def test_injected_lease_double_claim_caught():
    result = run_scenario('lease', seed=3, inject='double-claim')
    assert not result.ok
    assert 'X512' in _rules(result)
    assert any('winner' in d.message or 'claim' in d.message for d in result.diagnostics)


def test_injected_queue_double_serve_caught():
    result = run_scenario('queue', seed=3, inject='double-serve')
    assert 'X512' in _rules(result)


def test_injected_router_lost_leg_caught():
    result = run_scenario('router', seed=3, inject='lost-leg')
    assert 'X512' in _rules(result)


def test_injected_store_double_solve_caught():
    result = run_scenario('store', seed=1, inject='double-solve')
    assert 'X512' in _rules(result)


def test_fast_suite_sweep():
    result = run_suite(FAST_SCENARIOS, seeds=25)
    assert result.ok, result.format_text()


def test_store_suite_smoke():
    result = run_suite(['store'], seeds=2)
    assert result.ok, result.format_text()


def test_failing_seed_is_named_in_diagnostics():
    result = run_scenario('lease', seed=11, inject='double-claim')
    assert any('seed=11' in d.message for d in result.diagnostics)


def test_cli_show_log(capsys):
    from da4ml_tpu.analysis.interleave import main

    assert main(['--scenario', 'lease', '--show-log', '7']) == 0
    out = capsys.readouterr().out
    assert 'lease seed=7 ok=True' in out and 'grant' in out
