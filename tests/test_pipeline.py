"""to_pipeline stage splitting + retiming: stage latency bounds and exactness."""

import numpy as np

from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace, to_pipeline
from da4ml_tpu.trace.ops.quantization import fixed_quantize

N = 8


def build_comb(latency_cutoff=-1):
    rng = np.random.default_rng(3)
    inp = FixedVariableArrayInput(N, hwconf=HWConfig(1, -1, latency_cutoff))
    q = inp.quantize(np.ones(N), np.full(N, 3), np.full(N, 2))
    w1 = rng.integers(-8, 8, (N, 6)).astype(np.float64)
    w2 = rng.integers(-8, 8, (6, 4)).astype(np.float64)
    h = (q @ w1).relu()
    out = h @ w2
    return inp, out, comb_trace(inp, out)


def test_to_pipeline_exact():
    _, _, comb = build_comb(latency_cutoff=4)
    pipe = to_pipeline(comb, 4, retiming=False)
    assert len(pipe.stages) >= 2
    rng = np.random.default_rng(0)
    data = rng.uniform(-8, 8, (256, N))
    golden = comb.predict(data, backend='numpy')
    np.testing.assert_array_equal(pipe.predict(data, backend='numpy'), golden)
    # replay path as well
    qdata = fixed_quantize(data, 1, 3, 2)
    rep = np.stack([np.asarray(pipe(row), dtype=np.float64) for row in qdata[:32]])
    np.testing.assert_array_equal(rep, golden[:32])


def test_pipeline_fused_jax_predict_exact():
    """backend='jax' runs all stages + inter-stage rescaling as one device
    program; it must bit-match the per-stage numpy chain."""
    _, _, comb = build_comb(latency_cutoff=4)
    pipe = to_pipeline(comb, 4, retiming=False)
    assert len(pipe.stages) >= 2
    rng = np.random.default_rng(1)
    data = rng.uniform(-8, 8, (128, N))
    golden = pipe.predict(data, backend='numpy')
    np.testing.assert_array_equal(pipe.predict(data, backend='jax'), golden)


def test_pipeline_fused_jax_predict_sharded():
    import jax
    from jax.sharding import Mesh

    _, _, comb = build_comb(latency_cutoff=4)
    pipe = to_pipeline(comb, 4, retiming=False)
    rng = np.random.default_rng(2)
    data = rng.uniform(-8, 8, (8 * len(jax.devices()) + 3, N))  # pad path too
    golden = pipe.predict(data, backend='numpy')
    mesh = Mesh(np.asarray(jax.devices()), ('batch',))
    np.testing.assert_array_equal(pipe.predict(data, mesh=mesh), golden)


def test_pipeline_mesh_requires_jax_backend():
    import jax
    import pytest
    from jax.sharding import Mesh

    _, _, comb = build_comb(latency_cutoff=4)
    pipe = to_pipeline(comb, 4, retiming=False)
    mesh = Mesh(np.asarray(jax.devices()), ('batch',))
    with pytest.raises(ValueError, match='mesh sharding'):
        pipe.predict(np.zeros((4, N)), backend='cpp', mesh=mesh)


def test_to_pipeline_stage_latency_bound():
    _, _, comb = build_comb(latency_cutoff=4)
    pipe = to_pipeline(comb, 4, retiming=False)
    for i, stage in enumerate(pipe.stages):
        assert max(stage.out_latency) <= 4 * (i + 1) + 1e-9


def test_retiming_preserves_function():
    _, _, comb = build_comb(latency_cutoff=5)
    pipe = to_pipeline(comb, 5, retiming=True, verbose=False)
    rng = np.random.default_rng(1)
    data = rng.uniform(-8, 8, (128, N))
    golden = comb.predict(data, backend='numpy')
    np.testing.assert_array_equal(pipe.predict(data, backend='numpy'), golden)


def test_pipeline_json_roundtrip(tmp_path):
    from da4ml_tpu.ir import Pipeline

    _, _, comb = build_comb(latency_cutoff=4)
    pipe = to_pipeline(comb, 4, retiming=False)
    pipe.save(tmp_path / 'p.json')
    pipe2 = Pipeline.load(tmp_path / 'p.json')
    assert pipe == pipe2
