"""IR-level pipeline fusion (ir/fuse.py): bit-exactness of the fused
whole-model program vs the chained runtime oracle and the numpy staged
reference, across traced workloads and the synth pipeline fuzz corpus;
export artifact round-trip + digest refusal (docs/runtime.md#ir-fusion)."""

import json
import os

import numpy as np
import pytest

from da4ml_tpu.ir.dais_binary import decode, encode
from da4ml_tpu.ir.fuse import FUSABLE_OPCODES, fuse_binaries, fuse_pipeline
from da4ml_tpu.ir.synth import FAMILIES, random_inputs, random_pipeline
from da4ml_tpu.runtime import jax_backend as jb
from da4ml_tpu.runtime.numpy_backend import run_program
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace, to_pipeline

N = 8


def _mlp_pipeline(seed=3, cutoff=4):
    rng = np.random.default_rng(seed)
    inp = FixedVariableArrayInput(N, hwconf=HWConfig(1, -1, cutoff))
    q = inp.quantize(np.ones(N), np.full(N, 3), np.full(N, 2))
    w1 = rng.integers(-8, 8, (N, 6)).astype(np.float64)
    w2 = rng.integers(-8, 8, (6, 4)).astype(np.float64)
    out = ((q @ w1).relu()) @ w2
    pipe = to_pipeline(comb_trace(inp, out), cutoff, retiming=False)
    assert len(pipe.stages) >= 2
    data = rng.uniform(-8, 8, (128, N))
    return pipe, data


def _run_staged_numpy(stages, data):
    out = np.asarray(data, dtype=np.float64)
    for p in stages:
        out = run_program(p, out)
    return out


# -- op-level fusion ---------------------------------------------------------


def test_fuse_traced_pipeline_exact():
    pipe, data = _mlp_pipeline()
    fused, rep = pipe.fuse(report=True)
    assert rep.stages == len(pipe.stages)
    assert rep.ops_after <= rep.ops_before + rep.seam_ops
    # cross-stage level packing: fused critical path never exceeds the sum
    # of per-stage depths, and interleaving should strictly shorten it here
    assert rep.depth_after < rep.depth_before
    golden = pipe.predict(data, backend='numpy')
    np.testing.assert_array_equal(fused.predict(data, backend='numpy'), golden)


def test_fused_program_verifies_clean():
    from da4ml_tpu.analysis import verify

    pipe, _ = _mlp_pipeline()
    res = verify(pipe.fuse())
    assert res.ok, res.errors
    assert not res.warnings, res.warnings  # seam ops must stay latency-monotone


def test_fuse_reports_telemetry():
    """fuse.* counters/gauges + run.mode.fused_ir ride the metrics registry."""
    from da4ml_tpu.telemetry.metrics import disable_metrics, enable_metrics, metrics_snapshot, reset_metrics

    pipe, data = _mlp_pipeline()
    bins = [s.to_binary() for s in pipe.stages]
    _, rep = pipe.fuse(report=True)  # the deterministic expected payload
    enable_metrics()
    try:
        reset_metrics()
        jb._fused_ir_cache.clear()  # force a fused-executor build
        jb.run_pipeline(bins, data[:8], fused='ir')
        snap = metrics_snapshot()
        assert snap['fuse.stages']['value'] == rep.stages
        assert snap['fuse.seam_ops']['value'] == rep.seam_ops
        assert snap['fuse.depth_before']['value'] == rep.depth_before
        assert snap['fuse.depth_after']['value'] == rep.depth_after
        assert snap['run.mode.fused_ir']['value'] >= 1
    finally:
        disable_metrics()
        reset_metrics()


def test_fuse_single_stage_is_identity():
    rng = np.random.default_rng(0)
    inp = FixedVariableArrayInput(4, hwconf=HWConfig(1, -1, -1))
    q = inp.quantize(np.ones(4), np.full(4, 3), np.full(4, 1))
    out = q @ rng.integers(-4, 4, (4, 3)).astype(np.float64)
    comb = comb_trace(inp, out)
    fused = fuse_binaries([comb.to_binary()])
    np.testing.assert_array_equal(fused, comb.to_binary())


def test_fuse_empty_pipeline_rejected():
    from da4ml_tpu.ir.comb import Pipeline

    with pytest.raises(ValueError, match='empty'):
        fuse_pipeline(Pipeline(()))


# -- binary-level fusion + the runtime path ----------------------------------


def test_fuse_binaries_matches_op_level():
    pipe, _ = _mlp_pipeline()
    via_binaries = fuse_binaries([s.to_binary() for s in pipe.stages])
    np.testing.assert_array_equal(via_binaries, pipe.fuse().to_binary())


def test_run_pipeline_fused_ir_exact_and_cached():
    pipe, data = _mlp_pipeline()
    bins = [s.to_binary() for s in pipe.stages]
    golden = pipe.predict(data, backend='numpy')
    np.testing.assert_array_equal(jb.run_pipeline(bins, data, fused='ir'), golden)
    ex = jb.fused_executor_for_binaries(bins)
    assert jb.fused_executor_for_binaries(bins) is ex  # warm: no refuse/refit
    np.testing.assert_array_equal(jb.run_pipeline(bins, data, fused='ir'), golden)


@pytest.mark.parametrize('seed', range(8))
def test_synth_pipeline_fuzz_parity(seed):
    """Fused-IR vs chained-XLA vs per-stage-device vs numpy staged: all four
    executions of a random well-formed stage chain must agree bit for bit."""
    rng = np.random.default_rng(seed)
    stages = random_pipeline(rng, n_stages=int(rng.integers(2, 5)), n_ops=int(rng.integers(40, 140)))
    bins = [encode(p) for p in stages]
    data = random_inputs(rng, stages[0], 64)
    golden = _run_staged_numpy(stages, data)
    np.testing.assert_array_equal(jb.run_pipeline(bins, data, fused=True), golden)
    np.testing.assert_array_equal(jb.run_pipeline(bins, data, fused=False), golden)
    np.testing.assert_array_equal(jb.run_pipeline(bins, data, fused='ir'), golden)


def test_synth_pipeline_all_families_fuse():
    """Every generator family fuses: a full-family chain round-trips through
    fuse_binaries and stays bit-exact on the numpy interpreter."""
    rng = np.random.default_rng(7)
    stages = random_pipeline(rng, n_stages=3, n_ops=200, families=FAMILIES)
    fused = decode(fuse_binaries([encode(p) for p in stages]))
    assert set(fused.opcode.tolist()) <= FUSABLE_OPCODES
    data = random_inputs(rng, stages[0], 32)
    np.testing.assert_array_equal(run_program(fused, data), _run_staged_numpy(stages, data))


def test_encode_is_decode_inverse():
    rng = np.random.default_rng(11)
    (prog,) = random_pipeline(rng, n_stages=1, n_ops=150)
    b = encode(prog)
    np.testing.assert_array_equal(encode(decode(b)), b)


# -- new traced workloads ----------------------------------------------------


def _conv_stack_pipeline(cutoff=6):
    """Depthwise + pointwise (separable) conv stack, two blocks deep."""
    from da4ml_tpu.trace.ops import conv2d, depthwise_conv2d, relu

    rng = np.random.default_rng(5)
    shape = (5, 5, 2)
    inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, cutoff))
    x = inp.quantize(np.ones(shape), np.full(shape, 2), np.zeros(shape, np.int64))
    h = depthwise_conv2d(x, rng.integers(-3, 4, (3, 3, 2, 1)).astype(np.float64))
    h = relu(h, i=3, f=0)
    h = conv2d(h, rng.integers(-3, 4, (1, 1, 2, 3)).astype(np.float64))
    h = relu(h, i=3, f=0)
    h = depthwise_conv2d(h, rng.integers(-2, 3, (2, 2, 3, 1)).astype(np.float64))
    h = relu(h, i=3, f=0)
    out = conv2d(h, rng.integers(-3, 4, (1, 1, 3, 2)).astype(np.float64))
    pipe = to_pipeline(comb_trace(inp, out), cutoff, retiming=False)
    data = rng.integers(-4, 4, (64, int(np.prod(shape)))).astype(np.float64)
    return pipe, data


def _transformer_block_pipeline(cutoff=8):
    """Softmax-free transformer block: relu-attention + residual + FFN,
    traced entirely with existing tracer ops (einsum/relu/quantize)."""
    from da4ml_tpu.trace.ops import einsum, relu
    from da4ml_tpu.trace.ops.quantization import quantize

    rng = np.random.default_rng(9)
    T, D, F = 4, 4, 8
    shape = (T, D)
    inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, cutoff))
    x = inp.quantize(np.ones(shape), np.full(shape, 2), np.zeros(shape, np.int64))
    wq = rng.integers(-2, 3, (D, D)).astype(np.float64)
    wk = rng.integers(-2, 3, (D, D)).astype(np.float64)
    wv = rng.integers(-2, 3, (D, D)).astype(np.float64)
    q = quantize(einsum('td,df->tf', x, wq), 1, 3, 0)
    k = quantize(einsum('td,df->tf', x, wk), 1, 3, 0)
    v = quantize(einsum('td,df->tf', x, wv), 1, 3, 0)
    scores = relu(einsum('td,sd->ts', q, k), i=3, f=0)  # relu-attention, no softmax
    ctx = quantize(einsum('ts,sd->td', scores, v), 1, 3, 0)
    h = quantize(x + ctx, 1, 3, 0)  # residual
    w1 = rng.integers(-2, 3, (D, F)).astype(np.float64)
    w2 = rng.integers(-2, 3, (F, D)).astype(np.float64)
    ffn = quantize(einsum('tf,fd->td', relu(einsum('td,df->tf', h, w1), i=3, f=0), w2), 1, 3, 0)
    out = quantize(h + ffn, 1, 3, 0)
    pipe = to_pipeline(comb_trace(inp, out), cutoff, retiming=False)
    data = rng.integers(-4, 4, (64, T * D)).astype(np.float64)
    return pipe, data


@pytest.mark.parametrize('build', [_conv_stack_pipeline, _transformer_block_pipeline])
def test_workload_fused_parity(build):
    pipe, data = build()
    assert len(pipe.stages) >= 2
    golden = pipe.predict(data, backend='numpy')
    fused = pipe.fuse()
    np.testing.assert_array_equal(fused.predict(data, backend='numpy'), golden)
    bins = [s.to_binary() for s in pipe.stages]
    np.testing.assert_array_equal(jb.run_pipeline(bins, data, fused=True), golden)
    np.testing.assert_array_equal(jb.run_pipeline(bins, data, fused='ir'), golden)


@pytest.mark.parametrize('build', [_conv_stack_pipeline, _transformer_block_pipeline])
def test_workload_fused_verifies_clean(build):
    from da4ml_tpu.analysis import verify

    pipe, _ = build()
    res = verify(pipe.fuse())
    assert res.ok, res.errors
    assert not res.warnings, res.warnings


# -- export artifacts + serve hot-load ---------------------------------------


def test_export_artifact_roundtrip(tmp_path):
    from da4ml_tpu.serve.export import export_model, load_artifact, program_digest

    pipe, data = _mlp_pipeline()
    art = tmp_path / 'artifact'
    meta = export_model(pipe, art, name='probe')
    assert meta['source_stages'] == len(pipe.stages)
    binary, meta2 = load_artifact(art)
    assert meta2['digest'] == program_digest(binary) == meta['digest']
    np.testing.assert_array_equal(binary, fuse_binaries([s.to_binary() for s in pipe.stages]))
    # meta.json written last: a dir with fused.json only is not an artifact
    from da4ml_tpu.serve.export import is_artifact

    assert is_artifact(art)
    (art / 'meta.json').unlink()
    assert not is_artifact(art)


def test_export_cli_check(tmp_path):
    from da4ml_tpu._cli import main

    pipe, _ = _mlp_pipeline()
    mj = tmp_path / 'pipe.json'
    pipe.save(mj)
    rc = main(['export', str(mj), str(tmp_path / 'art'), '--name', 'probe', '--no-stablehlo', '--check'])
    assert rc == 0
    meta = json.loads((tmp_path / 'art' / 'meta.json').read_text())
    assert meta['stablehlo'] is None and meta['name'] == 'probe'


def test_serve_hot_load_artifact_zero_new_compiles(tmp_path):
    """A warm engine re-pointed at the export artifact of its own model must
    keep its executor (zero new XLA compiles) and answer byte-identically."""
    from da4ml_tpu.serve import ServeConfig, ServeEngine
    from da4ml_tpu.serve.export import export_model

    pipe, data = _mlp_pipeline()
    data = data[:16]
    art = tmp_path / 'artifact'
    export_model(pipe, art, stablehlo=False)
    golden = pipe.predict(data, backend='numpy')

    eng = ServeEngine(ServeConfig(max_batch_rows=64, prewarm=True))
    try:
        eng.load_model('m', str(art))
        got = np.asarray(eng.submit('m', data).result(timeout=30.0))
        np.testing.assert_array_equal(got, golden)
        warm_exec = eng._executors['m'][1]
        v = eng.reload('m')  # re-reads the artifact path
        assert v == 2
        assert eng._executors['m'][1] is warm_exec  # same program -> executor reused
        got2 = np.asarray(eng.submit('m', data).result(timeout=30.0))
        np.testing.assert_array_equal(got2, golden)
    finally:
        eng.unload('m')


def test_serve_refuses_digest_mismatch(tmp_path):
    from da4ml_tpu.serve import ServeConfig, ServeEngine
    from da4ml_tpu.serve.export import export_model, load_artifact

    pipe, _ = _mlp_pipeline()
    art = tmp_path / 'artifact'
    export_model(pipe, art, stablehlo=False)
    doc = json.loads((art / 'fused.json').read_text())
    doc['binary'][7] ^= 1
    (art / 'fused.json').write_text(json.dumps(doc))
    with pytest.raises(ValueError, match='digest mismatch'):
        load_artifact(art)
    eng = ServeEngine(ServeConfig(prewarm=False))
    try:
        eng.load_model('m', pipe)
        with pytest.raises(ValueError, match='digest mismatch'):
            eng.reload('m', str(art))
        assert eng._state('m').version == 1  # refusal left the live model untouched
    finally:
        eng.unload('m')


def test_export_stablehlo_serializes(tmp_path):
    """jax.export serialization is available in this environment; the
    artifact must carry it (other environments may record the error)."""
    pytest.importorskip('jax.export')
    from da4ml_tpu.serve.export import export_model

    pipe, _ = _mlp_pipeline()
    art = tmp_path / 'artifact'
    meta = export_model(pipe, art)
    if meta['stablehlo'] is None:
        pytest.skip(f'jax.export unavailable here: {meta["stablehlo_error"]}')
    blob = (art / meta['stablehlo']).read_bytes()
    assert len(blob) > 0
    assert os.path.getsize(art / 'fused.json') > 0
