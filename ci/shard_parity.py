"""CI gate for model-axis sharding (docs/runtime.md#model-parallel-execution).

Partitions the ir.synth corpus plus the fused conv-stack / transformer
bench workloads 4-way (one case 8-way), then gates two things on the
8-device virtual CPU mesh:

1. **Bit-exactness** — the forced model-sharded executor must match the
   numpy oracle exactly, in level mode and with one pallas mega-kernel
   per shard (interpret mode on CPU runners);
2. **Conformance of every partition cell** — each (segment, shard) cell
   program is differentially executed through every runtime mode against
   the table-generated reference interpreter (`analysis.check_conformance`,
   the same C401 gate `da4ml-tpu verify --conformance` applies to saved
   kernels).

Exits non-zero on any mismatch. Run from the repo root:

    python ci/shard_parity.py
"""

import os
import sys

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
if '--xla_force_host_platform_device_count' not in os.environ.get('XLA_FLAGS', ''):
    os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '') + ' --xla_force_host_platform_device_count=8').strip()
os.environ.setdefault('DA4ML_PALLAS_INTERPRET', '1')
os.environ.setdefault('DA4ML_RUN_AUTOTUNE', '0')

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _fusion_workloads():
    """The fused bench workloads as per-stage binary chains — the same
    traces and seeds bench.py's `fusion_workloads` section commits, so this
    gate covers exactly what the committed baselines measure."""
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace, to_pipeline
    from da4ml_tpu.trace.ops import conv2d, depthwise_conv2d, einsum, relu
    from da4ml_tpu.trace.ops.quantization import quantize

    rng = np.random.default_rng(23)

    def conv_stack():
        shape = (5, 5, 2)
        inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, 6))
        x = inp.quantize(np.ones(shape), np.full(shape, 2), np.zeros(shape, np.int64))
        h = relu(depthwise_conv2d(x, rng.integers(-3, 4, (3, 3, 2, 1)).astype(np.float64)), i=3, f=0)
        h = relu(conv2d(h, rng.integers(-3, 4, (1, 1, 2, 3)).astype(np.float64)), i=3, f=0)
        h = relu(depthwise_conv2d(h, rng.integers(-2, 3, (2, 2, 3, 1)).astype(np.float64)), i=3, f=0)
        out = conv2d(h, rng.integers(-3, 4, (1, 1, 3, 2)).astype(np.float64))
        return to_pipeline(comb_trace(inp, out), 6, retiming=False)

    def transformer_block():
        T, D, F = 4, 4, 8
        shape = (T, D)
        inp = FixedVariableArrayInput(shape, hwconf=HWConfig(1, -1, 8))
        x = inp.quantize(np.ones(shape), np.full(shape, 2), np.zeros(shape, np.int64))
        wq, wk, wv = (rng.integers(-2, 3, (D, D)).astype(np.float64) for _ in range(3))
        q = quantize(einsum('td,df->tf', x, wq), 1, 3, 0)
        k = quantize(einsum('td,df->tf', x, wk), 1, 3, 0)
        v = quantize(einsum('td,df->tf', x, wv), 1, 3, 0)
        scores = relu(einsum('td,sd->ts', q, k), i=3, f=0)  # relu-attention
        h = quantize(x + quantize(einsum('ts,sd->td', scores, v), 1, 3, 0), 1, 3, 0)
        w1 = rng.integers(-2, 3, (D, F)).astype(np.float64)
        w2 = rng.integers(-2, 3, (F, D)).astype(np.float64)
        ffn = quantize(einsum('tf,fd->td', relu(einsum('td,df->tf', h, w1), i=3, f=0), w2), 1, 3, 0)
        return to_pipeline(comb_trace(inp, quantize(h + ffn, 1, 3, 0)), 8, retiming=False)

    for name, build in (('conv_stack', conv_stack), ('transformer_block', transformer_block)):
        yield name, [s.to_binary() for s in build().stages]


def main() -> int:
    import jax

    from da4ml_tpu.analysis.conformance import check_conformance
    from da4ml_tpu.ir import synth
    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.ir.fuse import fuse_binaries
    from da4ml_tpu.ir.partition import build_shards, partition_program, validate_plan
    from da4ml_tpu.runtime import numpy_backend as nb
    from da4ml_tpu.runtime.jax_backend import DaisExecutor

    if jax.local_device_count() < 8:
        print(f'FATAL: need the 8-device virtual mesh, got {jax.local_device_count()}')
        return 2

    cases = []
    for seed, kwargs, k in (
        (11, dict(n_ops=200, n_in=8, n_out=6), 4),
        (12, dict(n_ops=260, n_in=12, n_out=9, wide=True, n_levels=10), 4),
        (13, dict(n_ops=220, n_in=6, n_out=5, n_levels=25), 8),
    ):
        cases.append((f'synth[{seed}]', synth.random_program(np.random.default_rng(seed), **kwargs), k))
    for name, chain in _fusion_workloads():
        cases.append((name, decode(fuse_binaries(chain)), 4))

    failures = 0
    for name, prog, k in cases:
        plan = partition_program(prog, k)
        validate_plan(prog, plan)
        build = build_shards(prog, plan)
        data = synth.random_inputs(np.random.default_rng(99), prog, 64)
        ref, buf = nb.run_program(prog, data, return_buf=True)
        ref = np.asarray(ref)
        # conformance per cell, on the cell's ACTUAL upstream carries: raw
        # input lanes pre-scaled by the program's inp_shift, received values
        # as their float codes (cells declare inp_shift=0; a receive lane's
        # wrap is an identity on in-range carries by construction)
        lane_scale = np.exp2(prog.inp_shifts.astype(np.float64))
        op_scale = np.exp2(-prog.fractionals.astype(np.float64))
        n_cells = 0
        for seg in build.shards:
            for cell in seg:
                if cell.prog.n_ops == 0:
                    continue
                n_cells += 1
                cols = [
                    data[:, -1 - int(src)] * lane_scale[-1 - int(src)]
                    if src < 0
                    else np.asarray(buf[int(src)], dtype=np.float64) * op_scale[int(src)]
                    for src in cell.in_ops
                ]
                cell_data = np.stack(cols, axis=1) if cols else np.zeros((len(data), cell.prog.n_in))
                for d in check_conformance(cell.prog, data=cell_data):
                    print(f'FAIL {name}: cell conformance: {d}')
                    failures += 1
        for mode in ('level', 'pallas'):
            ex = DaisExecutor(prog, mode=mode, partition_plan=plan, model_shard=True)
            if ex.model_shards != k:
                print(f'FAIL {name}: mode={mode}: sharded build fell back (model_shards={ex.model_shards})')
                failures += 1
                continue
            ok = np.array_equal(np.asarray(ex(data)), ref)
            print(f'{"ok  " if ok else "FAIL"} {name}: k={k} mode={mode} segments={plan.n_segments} cells={n_cells}')
            if not ok:
                failures += 1
    print(f'{"FAILED" if failures else "PASSED"}: {len(cases)} programs, {failures} failures')
    return 1 if failures else 0


if __name__ == '__main__':
    sys.exit(main())
