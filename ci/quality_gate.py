#!/usr/bin/env python
"""CI quality gate for the beam search (docs/cmvm.md#search-strategies).

Runs ``quality='search'`` on the committed corpus (ci/quality_corpus.npz)
against the host oracle and gates on the PR's acceptance invariants:

- zero cost regressions (beam <= oracle on EVERY kernel);
- at least ``--min-strict-wins`` strict wins (beam < oracle);
- never worse than the greedy device solve on any kernel;
- wall-clock <= ``--max-wall-multiplier`` x the greedy device solve;
- device-resident beam vs the host-beam path (DA4ML_JAX_DEVICE_RESIDENT=0):
  byte-identical costs on every kernel and ``sched.fetch_bytes`` at least
  ``--min-fetch-drop`` x lower (docs/cmvm.md#search-strategies).

Writes a JSON report (uploaded as a CI artifact) whose ``quality_beam.*``
metrics ride the ci/budgets.toml rules through ``da4ml-tpu bench-diff``.

Regenerate the corpus (deterministic) with ``--regen``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

CORPUS_SEED = 20260804


def regen_corpus(path: str) -> None:
    rng = np.random.default_rng(CORPUS_SEED)
    kernels = {}
    # mixed sizes around the quality-sweep shape; small enough that the gate
    # runs in CI minutes, large enough that the beam has room to win
    for i, (dim, bits) in enumerate([(10, 4), (12, 4), (12, 3), (14, 4), (16, 4), (16, 3), (16, 4), (14, 3)]):
        mag = rng.integers(0, 2**bits, (dim, dim)).astype(np.float64)
        sign = rng.choice([-1.0, 1.0], (dim, dim))
        kernels[f'k{i:02d}'] = mag * sign
    np.savez(path, **kernels)
    print(f'wrote {len(kernels)} kernels -> {path}')


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument('--corpus', default='ci/quality_corpus.npz')
    ap.add_argument('--out', default=None, help='JSON report path')
    ap.add_argument('--min-strict-wins', type=int, default=1)
    ap.add_argument('--max-wall-multiplier', type=float, default=2.5)
    ap.add_argument('--min-fetch-drop', type=float, default=3.0, help='resident-beam fetch_bytes must be this factor lower than the host-beam path')
    ap.add_argument('--regen', action='store_true', help='regenerate the committed corpus and exit')
    args = ap.parse_args()

    if args.regen:
        regen_corpus(args.corpus)
        return 0

    from da4ml_tpu.cmvm import api as host_api
    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    with np.load(args.corpus) as blob:
        kernels = [np.asarray(blob[k], np.float64) for k in sorted(blob.files)]

    host_costs = np.asarray([float(host_api.solve(k, backend='auto').cost) for k in kernels])

    from da4ml_tpu.telemetry.metrics import enable_metrics, metrics_snapshot, reset_metrics

    solve_jax_many(kernels[:2])  # warm the dominant shape classes off the clock
    solve_jax_many(kernels[:2], quality='search')  # fork/prune classes too
    t0 = time.perf_counter()
    greedy_costs = np.asarray([float(s.cost) for s in solve_jax_many(kernels)])
    greedy_wall = time.perf_counter() - t0
    enable_metrics()
    reset_metrics()
    t0 = time.perf_counter()
    beam_sols = solve_jax_many(kernels, quality='search')
    beam_wall = time.perf_counter() - t0
    res_snap = metrics_snapshot()
    beam_costs = np.asarray([float(s.cost) for s in beam_sols])

    # the host-beam / legacy-ladder A/B: the resident beam must match its
    # costs byte-for-byte (CostRanker) at a fraction of the traffic
    reset_metrics()
    os.environ['DA4ML_JAX_DEVICE_RESIDENT'] = '0'
    try:
        hostbeam_costs = np.asarray([float(s.cost) for s in solve_jax_many(kernels, quality='search')])
    finally:
        os.environ.pop('DA4ML_JAX_DEVICE_RESIDENT', None)
    leg_snap = metrics_snapshot()

    def _m(snap, key):
        return float(snap.get(key, {}).get('value', 0))

    fetch_res = _m(res_snap, 'sched.fetch_bytes')
    fetch_leg = _m(leg_snap, 'sched.fetch_bytes')
    fetch_drop = fetch_leg / fetch_res if fetch_res > 0 else float('inf')
    resident_mismatch = int((beam_costs != hostbeam_costs).sum())

    # exactness first: a cheap wrong answer must fail loudly
    for k, s in zip(kernels, beam_sols):
        np.testing.assert_array_equal(np.asarray(s.kernel, np.float64), k)

    strict_wins = int((beam_costs < host_costs).sum())
    regressions = int((beam_costs > host_costs).sum())
    worse_than_greedy = int((beam_costs > greedy_costs).sum())
    mult = beam_wall / greedy_wall if greedy_wall > 0 else float('inf')
    report = {
        'quality_beam': {
            'n_kernels': len(kernels),
            'strict_wins': f'{strict_wins}/{len(kernels)}',
            'win_or_tie': f'{len(kernels) - regressions}/{len(kernels)}',
            'regressions': regressions,
            'worse_than_greedy': worse_than_greedy,
            'mean_cost_host': round(float(host_costs.mean()), 3),
            'mean_cost_greedy': round(float(greedy_costs.mean()), 3),
            'mean_cost_beam': round(float(beam_costs.mean()), 3),
            'cost_delta_vs_host': round(float((beam_costs - host_costs).mean()), 3),
            'greedy_wall_s': round(greedy_wall, 2),
            'beam_wall_s': round(beam_wall, 2),
            'wall_multiplier': round(mult, 2),
            # device-resident beam vs host-beam path A/B (the fetch columns
            # ride ci/budgets.toml through bench-diff)
            'resident_cost_mismatches': resident_mismatch,
            'fetch_bytes': int(fetch_res),
            'fetch_bytes_hostbeam': int(fetch_leg),
            'fetch_drop': round(fetch_drop, 2) if fetch_drop != float('inf') else None,
            'device_forks': int(_m(res_snap, 'search.device_forks')),
            'entry_carry_groups': int(_m(res_snap, 'sched.entry_carry_groups')),
        }
    }
    print(json.dumps(report, indent=1))
    if args.out:
        with open(args.out, 'w') as fh:
            json.dump(report, fh, indent=1)

    failures = []
    if regressions:
        failures.append(f'{regressions} kernels cost MORE than the host oracle (must be 0)')
    if worse_than_greedy:
        failures.append(f'{worse_than_greedy} kernels cost more than the greedy solve (must be 0)')
    if strict_wins < args.min_strict_wins:
        failures.append(f'only {strict_wins} strict wins (< {args.min_strict_wins})')
    if mult > args.max_wall_multiplier:
        failures.append(f'wall multiplier {mult:.2f}x exceeds {args.max_wall_multiplier}x')
    if resident_mismatch:
        failures.append(f'{resident_mismatch} kernels cost differently resident vs host-beam (must be byte-identical)')
    if fetch_drop < args.min_fetch_drop:
        failures.append(f'resident fetch drop {fetch_drop:.2f}x below the {args.min_fetch_drop}x floor')
    if failures:
        print('QUALITY GATE FAILED:\n  - ' + '\n  - '.join(failures), file=sys.stderr)
        return 1
    print(
        f'quality gate OK: {strict_wins}/{len(kernels)} strict wins, 0 regressions, '
        f'{mult:.2f}x wall, {fetch_drop:.1f}x resident fetch drop'
    )
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
