"""One-shot TPU measurement campaign (run when the chip/tunnel is healthy).

Runs, in order and each in a bounded subprocess:

1. the validation ladder (writes docs/tpu_validation.json),
2. the full bench (refreshes docs/bench_snapshot.json from its live JSON
   when the run was on a real TPU),
3. the on-demand sections: quality_1000, 3b_large_dim with
   DA4ML_BENCH_LARGE=1, select_modes,
4. an inference-packing A/B (packed __call__ vs raw fn_int + transfers).

Usage: python tests_tpu/measure_campaign.py [--skip-ladder] [--unattended]

``--unattended`` (the auto-fire mode of the tunnel prober) skips the
sections most likely to need a first multi-minute remote compile
(quality_1000 on device, 3b_large_dim): killing a mid-flight remote
compile is the known tunnel-wedge trigger, and their quality evidence is
decision-equivalent on CPU anyway.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]

_AB_SRC = """
import numpy as np, time, jax
jax.config.update('jax_compilation_cache_dir', '/tmp/da4ml_jax_cache')
jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
from da4ml_tpu.ir.dais_binary import decode
from da4ml_tpu.runtime.jax_backend import DaisExecutor
from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace
rng = np.random.default_rng(11)
n_in, hidden = 16, 64
inp = FixedVariableArrayInput(n_in, hwconf=HWConfig(1, -1, -1))
x = inp.quantize(np.ones(n_in), np.full(n_in, 3), np.full(n_in, 2))
w1 = rng.integers(-8, 8, (n_in, hidden)).astype(np.float64)
x = (x @ w1).relu(i=np.full(hidden, 6), f=np.full(hidden, 2))
w2 = rng.integers(-8, 8, (hidden, 8)).astype(np.float64)
comb = comb_trace(inp, x @ w2)
ex = DaisExecutor(decode(comb.to_binary()))
data = rng.uniform(-8, 8, (262144, n_in))
ex(data)  # compile packed
t0 = time.perf_counter(); out_p = ex(data); tp = time.perf_counter() - t0
xi = ex._int_inputs(data)
np.testing.assert_array_equal(out_p, comb.predict(data, backend='numpy'))
jax.block_until_ready(ex.fn_int(xi))  # compile raw
t0 = time.perf_counter()
out_r = np.asarray(jax.device_get(ex.fn_int(xi)), np.float64) * ex._out_scale()
tr = time.perf_counter() - t0
t0 = time.perf_counter(); y = comb.predict(data, n_threads=16); th = time.perf_counter() - t0
print(f'PACKED_AB packed={262144/tp:.0f}/s raw={262144/tr:.0f}/s host={262144/th:.0f}/s packed_vs_raw={tr/tp:.2f} packed_vs_host={th/tp:.2f}')
"""


def run(name: str, cmd: list[str], timeout: float, env_extra: dict | None = None) -> dict:
    import os

    env = dict(os.environ)
    env.update(env_extra or {})
    t0 = time.time()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout, cwd=ROOT, env=env)
        tail = (r.stdout or '').strip().splitlines()[-5:]
        ok = r.returncode == 0
        print(f'[{name}] {"ok" if ok else f"rc={r.returncode}"} in {time.time() - t0:.0f}s')
        for ln in tail:
            print('   ' + ln)
        return {'name': name, 'ok': ok, 'tail': tail, 'wall_s': round(time.time() - t0, 1)}
    except subprocess.TimeoutExpired:
        print(f'[{name}] TIMEOUT after {timeout:.0f}s')
        return {'name': name, 'ok': False, 'tail': [f'timeout {timeout:.0f}s'], 'wall_s': timeout}


def main() -> int:
    results = []
    if '--skip-ladder' not in sys.argv:
        results.append(run('ladder', [sys.executable, 'tests_tpu/validate_ladder.py', '--fast'], 1500))
        if not results[-1]['ok']:
            print('ladder failed — stopping (chip unhealthy)')
            return 1

    # fused-kernel first: Mosaic smoke + identity + head-to-head rate (the
    # round-4 lever); its outcome decides whether to flip the default select
    results.append(run('fused_profile', [sys.executable, 'tests_tpu/profile_fused.py', '64'], 1500))

    results.append(run('bench_full', [sys.executable, 'bench.py', '64'], 900, {'DA4ML_BENCH_BUDGET_S': '560'}))
    # refresh the committed snapshot when the live run was on a real TPU
    for ln in reversed(results[-1]['tail']):
        if ln.startswith('{'):
            try:
                data = json.loads(ln)
                if not data['detail'].get('limited_cpu_fallback', True):
                    snap = {k: v for k, v in data.items()}
                    (ROOT / 'docs' / 'bench_snapshot.json').write_text(json.dumps(snap, indent=1) + '\n')
                    print('   bench_snapshot.json refreshed')
            except Exception as e:
                print(f'   snapshot refresh skipped: {e}')
            break

    if '--unattended' not in sys.argv:
        results.append(run('quality_1000', [sys.executable, 'bench.py', '--section', 'quality_1000'], 1800))
        results.append(
            run('large_dim', [sys.executable, 'bench.py', '--section', '3b_large_dim'], 1800, {'DA4ML_BENCH_LARGE': '1'})
        )
    results.append(run('select_modes', [sys.executable, 'bench.py', '--section', 'select_modes', '16'], 1200))
    results.append(run('packed_ab', [sys.executable, '-u', '-c', _AB_SRC], 900))

    (ROOT / 'docs' / 'tpu_campaign.json').write_text(json.dumps(results, indent=1) + '\n')
    print('campaign record written to docs/tpu_campaign.json')
    return 0 if all(r['ok'] for r in results) else 1


if __name__ == '__main__':
    raise SystemExit(main())
