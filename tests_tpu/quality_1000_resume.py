"""Checkpointed quality_1000 sweep (manual tool).

Reproduces the exact kernel distribution of ``bench.py --section
quality_1000`` (seed 1000, dims 2-32, 1-8 bit) and walks it in 20-kernel
chunks with a JSON checkpoint after every chunk, so a multi-hour CPU-XLA
run survives interruption. Per-kernel host cost, device cost, and
op-for-op identity are recorded (stronger than the cost-only ``identical``
of the bench section).

Usage:
    JAX_PLATFORMS=cpu DA4ML_JAX_HBM_BUDGET=512000000 \
        python tests_tpu/quality_1000_resume.py [start] [stop] [ckpt.json]

Defaults: start=400 (rounds 1-4 already captured 0..400 in
docs/quality_r4_cpu.json), stop=1000, ckpt=docs/quality_1000_ckpt.json.
"""

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root

if os.environ.get('JAX_PLATFORMS') == 'cpu':
    import jax

    jax.config.update('jax_platforms', 'cpu')

CHUNK = 20


def gen_kernels(n=1000):
    """The exact quality_1000 sequence (bench.py seed/sampling order)."""
    rng = np.random.default_rng(1000)
    kernels = []
    for _ in range(n):
        d1, d2 = int(rng.integers(2, 33)), int(rng.integers(2, 33))
        bits = int(rng.integers(1, 9))
        mag = rng.integers(0, 2**bits, (d1, d2)).astype(np.float64)
        kernels.append(mag * rng.choice([-1.0, 1.0], (d1, d2)))
    return kernels


def ops_sig(p):
    return [[(o.id0, o.id1, o.opcode, o.data) for o in st.ops] for st in p.stages]


def main():
    start = int(sys.argv[1]) if len(sys.argv) > 1 else 400
    stop = int(sys.argv[2]) if len(sys.argv) > 2 else 1000
    ckpt_path = Path(sys.argv[3]) if len(sys.argv) > 3 else Path(__file__).resolve().parents[1] / 'docs' / 'quality_1000_ckpt.json'

    from da4ml_tpu.cmvm import solve as host_solve
    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    kernels = gen_kernels()
    state = {'records': []}
    if ckpt_path.exists():
        state = json.loads(ckpt_path.read_text())
    done = {r['idx'] for r in state['records']}

    idxs = [i for i in range(start, stop) if i not in done]
    print(f'{len(idxs)} kernels to go ({start}..{stop}), ckpt={ckpt_path}', flush=True)
    while idxs:
        batch, idxs = idxs[:CHUNK], idxs[CHUNK:]
        ks = [kernels[i] for i in batch]
        t0 = time.perf_counter()
        host = [host_solve(k, backend='auto') for k in ks]
        t_host = time.perf_counter() - t0
        t0 = time.perf_counter()
        dev = solve_jax_many(ks)
        t_dev = time.perf_counter() - t0
        for i, k, h, d in zip(batch, ks, host, dev):
            assert np.array_equal(np.asarray(d.kernel, np.float64), k), f'exactness violated at {i}'
            state['records'].append(
                {
                    'idx': i,
                    'dims': list(k.shape),
                    'cost_host': float(h.cost),
                    'cost_dev': float(d.cost),
                    'ops_identical': ops_sig(h) == ops_sig(d),
                }
            )
        state['meta'] = {
            'platform': 'cpu-xla' if os.environ.get('JAX_PLATFORMS') == 'cpu' else 'device',
            'seed': 1000,
            'chunk_host_s': round(t_host, 1),
            'chunk_dev_s': round(t_dev, 1),
            'n_done': len(state['records']),
        }
        ckpt_path.write_text(json.dumps(state))
        print(f'{len(state["records"])} done (chunk host {t_host:.0f}s dev {t_dev:.0f}s)', flush=True)

    recs = state['records']
    hc = np.array([r['cost_host'] for r in recs])
    dc = np.array([r['cost_dev'] for r in recs])
    summary = {
        'n_kernels': len(recs),
        'cost_identical': int((dc == hc).sum()),
        'ops_identical': int(sum(r['ops_identical'] for r in recs)),
        'win': int((dc < hc).sum()),
        'loss': int((dc > hc).sum()),
        'mean_cost_host': round(float(hc.mean()), 3),
        'mean_cost_dev': round(float(dc.mean()), 3),
        'max_loss': float((dc - hc).max()),
    }
    print(json.dumps(summary))


if __name__ == '__main__':
    main()
