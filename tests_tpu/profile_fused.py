"""On-hardware fused-vs-top4 profiler (manual tool, not a pytest suite).

The first thing to run in a healthy tunnel window:

    python tests_tpu/profile_fused.py [n_matrices]

Phases, each bounded so a Mosaic lowering failure or wedge costs minutes,
not the window:

1. tiny fused Mosaic-compile smoke (the real risk: interpret mode passes
   where Mosaic tiling constraints bite),
2. decision-identity spot check fused vs top4 on hardware,
3. steady-rate head-to-head on the BASELINE config-1 class (16x16 int4),
4. the derived per-iteration loop-body time for both modes.

Exit code 0 = fused compiled, identical, and its rate is printed; the
select_modes bench section then captures the formal numbers.
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root


def _mk(rng, n, bits, count):
    return [
        (rng.integers(0, 2**bits, (n, n)) * rng.choice([-1.0, 1.0], (n, n))).astype(np.float64)
        for _ in range(count)
    ]


def _solve(kernels, select):
    # no cache_clear: the select mode is baked into the _KernelSpec lru key,
    # so top4/fused programs never alias and warm compiles stay warm
    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    os.environ['DA4ML_JAX_SELECT'] = select
    try:
        return solve_jax_many(kernels)
    finally:
        os.environ.pop('DA4ML_JAX_SELECT', None)


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    os.environ.setdefault('DA4ML_JAX_DEBUG', '1')

    import jax

    jax.config.update('jax_compilation_cache_dir', os.environ.get('DA4ML_JAX_CACHE', '/tmp/da4ml_jax_cache'))
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
    print(f'backend: {jax.default_backend()}, devices: {jax.devices()}', flush=True)

    rng = np.random.default_rng(20260731)

    # 1) Mosaic smoke: one tiny fused solve (small class, fast compile)
    t0 = time.perf_counter()
    tiny = _mk(rng, 6, 3, 2)
    sols = _solve(tiny, 'fused')
    for k, s in zip(tiny, sols):
        if not np.array_equal(np.asarray(s.kernel, np.float64), k):
            raise SystemExit('FAIL: fused exactness failed on hardware')
    print(f'[1] fused Mosaic smoke: OK ({time.perf_counter() - t0:.1f}s incl. compile)', flush=True)

    # 2) identity spot check vs top4
    ks = _mk(rng, 12, 4, 4) + _mk(rng, 8, 6, 2)
    st = _solve(ks, 'top4')
    sf = _solve(ks, 'fused')
    n_id = 0
    for a, b in zip(st, sf):
        ops_a = [[(o.id0, o.id1, o.opcode, o.data) for o in stg.ops] for stg in a.stages]
        ops_b = [[(o.id0, o.id1, o.opcode, o.data) for o in stg.ops] for stg in b.stages]
        n_id += ops_a == ops_b
    print(f'[2] decision identity fused vs top4 on hardware: {n_id}/{len(ks)}', flush=True)
    if n_id != len(ks):
        raise SystemExit('FAIL: fused diverged from top4 on hardware')

    # 3) config-1 head-to-head; the warm pass uses the FULL batch so the
    # timed pass hits the exact compiled (bucketed) program
    k1 = _mk(rng, 16, 4, n)
    rates = {}
    for mode in ('top4', 'fused'):
        _solve(k1, mode)  # compile pass at the real lane bucket
        t0 = time.perf_counter()
        sols = _solve(k1, mode)
        dt = time.perf_counter() - t0
        rates[mode] = n / dt
        cost = float(np.mean([s.cost for s in sols]))
        print(f'[3] {mode}: {n / dt:.1f} matrices/s (mean cost {cost:.1f})', flush=True)
    print(
        f'[4] fused/top4 rate ratio: {rates["fused"] / rates["top4"]:.2f}x '
        f'(per-iteration body time scales inversely)',
        flush=True,
    )
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
