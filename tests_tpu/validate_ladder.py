"""Careful on-hardware validation ladder for the axon TPU tunnel.

The tunnel's remote worker can crash (and stay wedged) if a program OOMs or
faults on-device, so each rung runs in its own bounded subprocess and the
ladder stops at the first failure — never leaving an unbounded process
holding the chip. Run after any substantial change to the device search:

    python tests_tpu/validate_ladder.py [--fast]

Rungs: basic device op -> tiny solve -> config-1 batch -> wide-output
matrix (the staged-search stressor) -> bench.py -> tests_tpu suite.
"""

from __future__ import annotations

import subprocess
import sys
import time

FAST = '--fast' in sys.argv

RUNGS: list[tuple[str, int, str]] = [
    (
        'basic',
        120,
        "import jax, jax.numpy as jnp; print('dev', jax.devices()); print('sum', (jnp.arange(16)**2).sum())",
    ),
    (
        'tiny_solve',
        300,
        """
import numpy as np
from da4ml_tpu.cmvm.jax_search import solve_jax_many
rng = np.random.default_rng(0)
ks = [rng.integers(-8, 8, (6, 6)).astype(np.float64) for _ in range(2)]
sols = solve_jax_many(ks)
for k, s in zip(ks, sols):
    assert np.array_equal(np.asarray(s.kernel, np.float64), k)
print('tiny solve exact')
""",
    ),
    (
        'config1_batch',
        420,
        """
import numpy as np, time
from da4ml_tpu.cmvm.jax_search import solve_jax_many
rng = np.random.default_rng(20260729)
ks = [(rng.integers(0, 16, (16, 16)) * rng.choice([-1.0, 1.0], (16, 16))).astype(np.float64) for _ in range(32)]
solve_jax_many(ks)
t0 = time.perf_counter(); sols = solve_jax_many(ks); dt = time.perf_counter() - t0
for k, s in zip(ks, sols):
    assert np.array_equal(np.asarray(s.kernel, np.float64), k)
print(f'config1 rate {32/dt:.1f} matrices/s')
""",
    ),
    (
        'wide_output',
        560,
        """
import numpy as np, time, os
os.environ['DA4ML_JAX_DEBUG'] = '1'
from da4ml_tpu.cmvm.jax_search import solve_jax_many
rng = np.random.default_rng(20260729)
k = (rng.integers(0, 64, (16, 64)) * rng.choice([-1.0, 1.0], (16, 64))).astype(np.float64)
t0 = time.perf_counter(); sols = solve_jax_many([k]); dt = time.perf_counter() - t0
assert np.array_equal(np.asarray(sols[0].kernel, np.float64), k)
print(f'wide 16x64x6 in {dt:.1f}s (incl. compiles)')
""",
    ),
]
if not FAST:
    RUNGS += [
        ('bench', 580, None),  # special: runs bench.py
        ('tests_tpu', 580, 'TESTS'),  # special: pytest tests_tpu
    ]


def main() -> int:
    import json
    import os
    from datetime import datetime, timezone
    from pathlib import Path

    record: dict = {
        'captured_utc': datetime.now(timezone.utc).isoformat(timespec='seconds'),
        'git_head': subprocess.run(['git', 'rev-parse', '--short', 'HEAD'], capture_output=True, text=True).stdout.strip(),
        'rungs': [],
    }

    def _save(status: str) -> None:
        # recorded hardware evidence: committed so a green tests_tpu run is
        # auditable, not just narrated
        record['status'] = status
        out = Path(__file__).resolve().parents[1] / 'docs' / 'tpu_validation.json'
        out.write_text(json.dumps(record, indent=1) + '\n')
        print(f'record written to {out}')

    for name, tmo, src in RUNGS:
        env = dict(os.environ)
        if name == 'bench':
            cmd = [sys.executable, 'bench.py']
            # keep bench's own worst case (probe retries + budget + grace)
            # inside this rung's timeout
            env['DA4ML_BENCH_BUDGET_S'] = '240'
        elif src == 'TESTS':
            cmd = [sys.executable, '-m', 'pytest', 'tests_tpu/', '-x', '-q']
        else:
            cmd = [sys.executable, '-u', '-c', src]
        t0 = time.time()
        try:
            r = subprocess.run(cmd, capture_output=True, text=True, timeout=tmo, env=env)
        except subprocess.TimeoutExpired:
            print(f'[{name}] TIMEOUT after {tmo}s — stopping ladder (chip may be wedged)')
            record['rungs'].append({'rung': name, 'result': f'timeout after {tmo}s'})
            _save('failed')
            return 1
        dt = time.time() - t0
        tail = (r.stdout or '').strip().splitlines()[-3:]
        if r.returncode != 0:
            err = (r.stderr or '').strip().splitlines()[-5:]
            print(f'[{name}] FAIL rc={r.returncode} in {dt:.0f}s')
            print('\n'.join('  ' + ln for ln in tail + err))
            record['rungs'].append({'rung': name, 'result': f'fail rc={r.returncode}', 'tail': tail + err})
            _save('failed')
            return 1
        print(f'[{name}] ok in {dt:.0f}s: ' + (tail[-1] if tail else ''))
        record['rungs'].append({'rung': name, 'result': f'ok in {dt:.0f}s', 'last_line': tail[-1] if tail else ''})
    print('ladder complete')
    _save('passed')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
