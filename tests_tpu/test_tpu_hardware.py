"""On-hardware checks: things CI's virtual CPU mesh cannot prove.

1. int32/int64 DAIS execution is bit-exact on the real chip (two's-complement
   wrap + arithmetic shifts compile correctly through XLA's TPU backend).
2. The fused Pallas CSE loop (DA4ML_JAX_SELECT=fused) Mosaic-compiles and is
   decision-identical with the XLA top4 path on hardware (CPU CI covers
   interpret mode only; Mosaic tiling constraints only bite on the chip).
3. unroll vs scan executor modes agree on TPU.

Run: ``pytest tests_tpu/`` with the TPU plugin active (skips off-TPU).
"""

from __future__ import annotations

import os

import numpy as np
import pytest


def _solve_costs(kernels, select: str):
    """Solve a batch under a given selection backend; return comparable state."""
    from da4ml_tpu.cmvm.jax_search import _build_cse_fn, solve_jax_many

    old = os.environ.get('DA4ML_JAX_SELECT')
    os.environ['DA4ML_JAX_SELECT'] = select
    try:
        _build_cse_fn.cache_clear()
        sols = solve_jax_many(kernels)
    finally:
        if old is None:
            os.environ.pop('DA4ML_JAX_SELECT', None)
        else:
            os.environ['DA4ML_JAX_SELECT'] = old
    return sols


def test_executor_bit_exact_on_tpu(rng):
    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.runtime.jax_backend import DaisExecutor
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    inp = FixedVariableArrayInput(8, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(8), np.full(8, 3), np.full(8, 2))
    w = rng.integers(-8, 8, (8, 6)).astype(np.float64)
    x = (x @ w).relu(i=np.full(6, 6), f=np.full(6, 2))
    comb = comb_trace(inp, x)
    data = rng.uniform(-8, 8, (256, 8))
    golden = comb.predict(data, backend='numpy')
    for force_i64 in (None, True):
        ex = DaisExecutor(decode(comb.to_binary()), force_i64=force_i64)
        np.testing.assert_array_equal(ex(data), golden)


def test_unroll_scan_parity_on_tpu(rng):
    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.runtime.jax_backend import DaisExecutor
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    inp = FixedVariableArrayInput(6, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(6), np.full(6, 3), np.full(6, 2))
    x = x @ rng.integers(-8, 8, (6, 6)).astype(np.float64)
    comb = comb_trace(inp, x)
    prog = decode(comb.to_binary())
    data = rng.uniform(-8, 8, (64, 6))
    out_u = DaisExecutor(prog, mode='unroll')(data)
    out_s = DaisExecutor(prog, mode='scan')(data)
    np.testing.assert_array_equal(out_u, out_s)


def test_fused_cse_decision_identity_on_tpu(rng):
    """Same kernels, same solutions (op-for-op) under fused vs top4 — the
    fused path Mosaic-compiles here, where tiling constraints are real."""
    pytest.importorskip('jax.experimental.pallas')
    kernels = [
        (rng.integers(0, 2**b, (n, n)) * rng.choice([-1.0, 1.0], (n, n))).astype(np.float64)
        for n, b in ((6, 4), (8, 4), (8, 2), (12, 4))
    ]
    sols_t = _solve_costs(kernels, 'top4')
    sols_f = _solve_costs(kernels, 'fused')
    for k, sx, sp in zip(kernels, sols_t, sols_f):
        np.testing.assert_array_equal(np.asarray(sp.kernel, np.float64), k)
        assert sp.cost == sx.cost, (sp.cost, sx.cost)
        assert sp.latency == sx.latency
        for st_x, st_p in zip(sx.stages, sp.stages):
            assert len(st_x.ops) == len(st_p.ops)
            for ox, op in zip(st_x.ops, st_p.ops):
                assert (ox.id0, ox.id1, ox.opcode, ox.data) == (op.id0, op.id1, op.opcode, op.data)


def test_fused_cse_multirung_on_tpu(rng):
    """A rung-resuming dense kernel batched with an active lane (the freeze
    path) compiles and stays identical on hardware."""
    ks = [
        (rng.integers(0, 64, (20, 20)) * rng.choice([-1.0, 1.0], (20, 20))).astype(np.float64),
        (rng.integers(0, 4, (20, 20)) * rng.choice([-1.0, 1.0], (20, 20))).astype(np.float64),
    ]
    sols_t = _solve_costs(ks, 'top4')
    sols_f = _solve_costs(ks, 'fused')
    for k, sx, sp in zip(ks, sols_t, sols_f):
        np.testing.assert_array_equal(np.asarray(sp.kernel, np.float64), k)
        assert sp.cost == sx.cost


def test_top4_select_on_tpu(rng):
    """The default O(S*P) score-cache select: exact on hardware, cost within
    a few % of the full-rescan reference path."""
    kernels = [
        (rng.integers(0, 2**b, (n, n)) * rng.choice([-1.0, 1.0], (n, n))).astype(np.float64)
        for n, b in ((6, 4), (8, 4), (12, 4))
    ]
    sols_t = _solve_costs(kernels, 'top4')
    sols_x = _solve_costs(kernels, 'xla')
    for k, st, sx in zip(kernels, sols_t, sols_x):
        np.testing.assert_array_equal(np.asarray(st.kernel, np.float64), k)
    mt = float(np.mean([s.cost for s in sols_t]))
    mx = float(np.mean([s.cost for s in sols_x]))
    assert mt <= mx * 1.03, (mt, mx)


def test_jedi_layer_shape_on_tpu(rng):
    """A 16x64 6-bit layer (BASELINE config 2's widest class, P=512 stage):
    must solve exactly on hardware within a sane wall-time budget.

    This is the shape class whose compile crashed the remote TPU worker in
    round 1's bench (BENCH/VERDICT r1); it pins the fix."""
    import time

    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    mag = rng.integers(0, 64, (16, 64)).astype(np.float64)
    k = mag * rng.choice([-1.0, 1.0], (16, 64))
    t0 = time.perf_counter()
    (sol,) = solve_jax_many([k])
    wall = time.perf_counter() - t0
    np.testing.assert_array_equal(np.asarray(sol.kernel, np.float64), k)
    assert wall < 420.0, f'16x64 solve took {wall:.0f}s (compile + search)'


def test_fused_pipeline_on_tpu(rng):
    """The fused multi-stage pipeline program is bit-exact on hardware and
    agrees with the chained per-stage path."""
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace, to_pipeline

    inp = FixedVariableArrayInput(8, hwconf=HWConfig(1, -1, 3))
    x = inp.quantize(np.ones(8), np.full(8, 3), np.full(8, 2))
    w1 = rng.integers(-8, 8, (8, 8)).astype(np.float64)
    w2 = rng.integers(-8, 8, (8, 4)).astype(np.float64)
    comb = comb_trace(inp, ((x @ w1).relu()) @ w2)
    pipe = to_pipeline(comb, 3, retiming=False)
    assert len(pipe.stages) >= 2
    data = rng.uniform(-8, 8, (512, 8))
    golden = pipe.predict(data, backend='numpy')
    np.testing.assert_array_equal(pipe.predict(data, backend='jax'), golden)


def test_decision_identity_vs_host_on_tpu(rng):
    """Host-order tie-breaking holds on real hardware: device op sequences
    equal the host solver's (r3 feature; CPU XLA proves semantics, this
    proves the TPU lowering — incl. HIGHEST-precision payload contractions
    — does not perturb them)."""
    from da4ml_tpu.cmvm.api import solve as host_solve
    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    for _ in range(2):
        kernel = (rng.integers(0, 16, (12, 10)) * rng.choice([-1, 1], (12, 10))).astype(np.float64)
        ref = host_solve(kernel, backend='auto')
        got = solve_jax_many([kernel])[0]
        assert float(got.cost) == float(ref.cost)
        for sr, sg in zip(ref.stages, got.stages):
            assert len(sr.ops) == len(sg.ops)
            for a, b in zip(sr.ops, sg.ops):
                assert a == b


def test_packed_inference_on_tpu(rng):
    """The int8/int16-packed transfer boundary is bit-exact on hardware and
    engages for narrow programs (r3 feature)."""
    from da4ml_tpu.ir.dais_binary import decode
    from da4ml_tpu.runtime.jax_backend import DaisExecutor
    from da4ml_tpu.trace import FixedVariableArrayInput, HWConfig, comb_trace

    inp = FixedVariableArrayInput(8, hwconf=HWConfig(1, -1, -1))
    x = inp.quantize(np.ones(8), np.full(8, 2), np.full(8, 2))
    w = rng.integers(-4, 4, (8, 5)).astype(np.float64)
    comb = comb_trace(inp, (x @ w).relu(i=np.full(5, 5), f=np.full(5, 2)))
    ex = DaisExecutor(decode(comb.to_binary()))
    assert ex._in_group or ex._out_group, 'narrow program should pack at least one direction'
    data = rng.uniform(-4, 4, (4096, 8))
    np.testing.assert_array_equal(ex(data), comb.predict(data, backend='numpy'))


def test_large_class_top4_k16_on_tpu(rng):
    """A P=512-class matrix (deeper K=16 cache) solves exactly and no worse
    than the host on hardware (r3 policy)."""
    from da4ml_tpu.cmvm.api import solve as host_solve
    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    kernel = (rng.integers(0, 16, (64, 64)) * rng.choice([-1, 1], (64, 64))).astype(np.float64)
    got = solve_jax_many([kernel], include_host=False)[0]
    np.testing.assert_array_equal(np.asarray(got.kernel, np.float64), kernel)
    ref = host_solve(kernel, backend='auto')
    assert float(got.cost) <= float(ref.cost) * 1.01, (got.cost, ref.cost)
