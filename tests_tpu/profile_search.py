"""On-hardware search profiler (manual tool, not a pytest suite).

Run on a machine with the TPU plugin active:

    python tests_tpu/profile_search.py [n_matrices] [--trace DIR]

Prints per-stage device round times (DA4ML_JAX_DEBUG) plus a phase
breakdown of ``solve_jax_many`` for BASELINE config 1, and optionally a
jax.profiler trace to inspect in TensorBoard/xprof. Use it to attribute
steady-state time between device rounds, host prep, and emission before
touching the kernel code.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 and not sys.argv[1].startswith('-') else 64
    trace_dir = None
    if '--trace' in sys.argv:
        trace_dir = sys.argv[sys.argv.index('--trace') + 1]
    os.environ.setdefault('DA4ML_JAX_DEBUG', '1')

    import jax

    jax.config.update('jax_compilation_cache_dir', os.environ.get('DA4ML_JAX_CACHE', '/tmp/da4ml_jax_cache'))
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
    print(f'backend: {jax.default_backend()}, devices: {jax.devices()}')

    from da4ml_tpu.cmvm.jax_search import solve_jax_many

    rng = np.random.default_rng(20260729)
    kernels = [
        (rng.integers(0, 16, (16, 16)) * rng.choice([-1.0, 1.0], (16, 16))).astype(np.float64) for _ in range(n)
    ]

    t0 = time.perf_counter()
    solve_jax_many(kernels)
    print(f'first call (compiles): {time.perf_counter() - t0:.2f}s')

    if trace_dir:
        with jax.profiler.trace(trace_dir):
            t0 = time.perf_counter()
            sols = solve_jax_many(kernels)
            steady = time.perf_counter() - t0
        print(f'trace written to {trace_dir}')
    else:
        t0 = time.perf_counter()
        sols = solve_jax_many(kernels)
        steady = time.perf_counter() - t0
    print(f'steady: {steady:.2f}s = {n / steady:.1f} matrices/s, mean cost {np.mean([s.cost for s in sols]):.1f}')


if __name__ == '__main__':
    main()
