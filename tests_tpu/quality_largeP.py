"""Large-P (>=512 slot) quality evidence (manual tool).

Runs on TPU when the plugin is active, or on CPU XLA (decision-equivalent;
set JAX_PLATFORMS=cpu) when the tunnel is down.

Compares the default deep-cache top4 (K=16 above P=256) against the host
solver — the decision-sequence reference — on kernels whose slot demand
lands in the P=512 class, quantifying the cache's identity-vs-cost
tradeoff (VERDICT r3 item 8): op-for-op identity count, cost deltas, and
win/tie/loss distribution. Optionally (``--rescan``, slow) also runs the
decision-identical full-rescan ``xla`` mode for a three-way check.
"""

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))  # repo root

if os.environ.get('JAX_PLATFORMS') == 'cpu':
    # the axon plugin ignores the env var; pin via config before backend init
    import jax

    jax.config.update('jax_platforms', 'cpu')

from da4ml_tpu.cmvm import solve as host_solve
from da4ml_tpu.cmvm.jax_search import solve_jax_many


def _solve(kernels, select):
    os.environ['DA4ML_JAX_SELECT'] = select
    try:
        return solve_jax_many(kernels)
    finally:
        os.environ.pop('DA4ML_JAX_SELECT', None)


def ops_sig(p):
    return [[(o.id0, o.id1, o.opcode, o.data) for o in st.ops] for st in p.stages]


def main():
    args = [a for a in sys.argv[1:] if not a.startswith('-')]
    n = int(args[0]) if args else 6
    # dim range: defaults to the original 80-128 class; pass lo hi for the
    # 44-64 (first P=512 rung) class of VERDICT r4 item 8
    d_lo = int(args[1]) if len(args) > 1 else 80
    d_hi = int(args[2]) if len(args) > 2 else 128
    rng = np.random.default_rng(512)
    kernels = []
    for _ in range(n):
        d = int(rng.integers(d_lo, d_hi + 1))
        b = int(rng.integers(5, 8))
        kernels.append((rng.integers(0, 2**b, (d, d)) * rng.choice([-1.0, 1.0], (d, d))).astype(np.float64))

    host = [host_solve(k, backend='auto') for k in kernels]
    t0 = time.perf_counter()
    sols_t = _solve(kernels, 'top4')
    t_top4 = time.perf_counter() - t0

    ct = np.array([s.cost for s in sols_t])
    ch = np.array([s.cost for s in host])
    ident_host = sum(ops_sig(a) == ops_sig(b) for a, b in zip(sols_t, host))
    for k, s in zip(kernels, sols_t):
        assert np.array_equal(np.asarray(s.kernel, np.float64), k), 'exactness violated'
    out = {
        'n_kernels': n,
        'dims': [int(k.shape[0]) for k in kernels],
        'slot_class': f'dims {d_lo}-{d_hi} (deep cache K=16 above P=256)',
        'ops_identical_vs_host': f'{ident_host}/{n}',
        'cost_top4': ct.tolist(),
        'cost_host': ch.tolist(),
        'mean_delta_vs_host_pct': round(float((ct - ch).sum() / ch.sum()) * 100, 3),
        'win': int((ct < ch).sum()),
        'tie': int((ct == ch).sum()),
        'loss': int((ct > ch).sum()),
        'wall_top4_s': round(t_top4, 1),
    }
    if '--rescan' in sys.argv:
        t0 = time.perf_counter()
        sols_x = _solve(kernels, 'xla')
        cx = np.array([s.cost for s in sols_x])
        out['cost_rescan'] = cx.tolist()
        out['ops_identical_top4_vs_rescan'] = f'{sum(ops_sig(a) == ops_sig(b) for a, b in zip(sols_t, sols_x))}/{n}'
        out['wall_rescan_s'] = round(time.perf_counter() - t0, 1)
    import jax as _jax

    out['platform'] = _jax.default_backend()
    print(json.dumps(out))


if __name__ == '__main__':
    main()
