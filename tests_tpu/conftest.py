"""Real-hardware test fixtures (run with: pytest tests_tpu/).

Unlike tests/conftest.py this does NOT force the CPU platform — the whole
point is to exercise the real TPU. Every test is skipped unless a TPU-class
backend actually initialized, so this directory is safe to collect anywhere.
"""

from __future__ import annotations

import subprocess
import sys

import numpy as np
import pytest

# Pallas kernel tracing stacks ~900 Python frames on top of pytest's own
# (assertion rewriting adds more); mid-suite that exceeds the default 1000
# recursion limit while the same test passes in isolation. Headroom, not a
# behavioral change.
sys.setrecursionlimit(max(sys.getrecursionlimit(), 10_000))


def _tpu_backend() -> bool:
    """Bounded-subprocess probe: TPU plugin init can hang, not just fail."""
    try:
        r = subprocess.run(
            [sys.executable, '-c', "import jax; print('BK=' + jax.default_backend())"],
            capture_output=True,
            text=True,
            timeout=90,
        )
    except Exception:
        return False
    lines = r.stdout.strip().splitlines()
    return r.returncode == 0 and bool(lines) and lines[-1].startswith('BK=') and lines[-1][3:] not in ('cpu', 'gpu')


def pytest_collection_modifyitems(config, items):
    if _tpu_backend():
        return
    skip = pytest.mark.skip(reason='no TPU backend available')
    for item in items:
        item.add_marker(skip)


@pytest.fixture
def rng() -> np.random.Generator:
    return np.random.default_rng(42)
