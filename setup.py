"""Build hook: compile the native C++ library at install/wheel-build time.

The library is a plain C-ABI shared object loaded with ctypes (no Python
extension API), so the standard build_ext is overridden to invoke the same
g++ command as da4ml_tpu/native/build.py and drop ``_da4ml_native.so`` into
the package. The extension is optional: when no C++ toolchain is available
the install still succeeds and the runtime falls back to the committed
binary or the first-use auto-build (bindings.load_lib).

Parity: the reference builds its native modules at install time via
meson-python (meson.build:25-52 of calad0i/da4ml).
"""

from __future__ import annotations

import os
from glob import glob
from pathlib import Path

from setuptools import Extension, setup
from setuptools.command.build_ext import build_ext as _build_ext

_CXX_FLAGS = ['-std=c++20', '-O3', '-fPIC', '-shared', '-fopenmp', '-fvisibility=hidden', '-Wall']


class NativeLibBuild(_build_ext):
    def get_ext_filename(self, fullname: str) -> str:
        # plain .so, no CPython ABI tag: the library is loaded via ctypes
        return os.path.join(*fullname.split('.')) + '.so'

    def build_extension(self, ext: Extension) -> None:
        out = Path(self.get_ext_fullpath(ext.name))
        out.parent.mkdir(parents=True, exist_ok=True)
        cxx = os.environ.get('CXX', 'g++')
        self.spawn([cxx, *_CXX_FLAGS, *ext.sources, '-o', str(out)])


setup(
    ext_modules=[
        Extension(
            'da4ml_tpu.native._da4ml_native',
            sources=sorted(glob('da4ml_tpu/native/src/*.cc')),
            depends=sorted(glob('da4ml_tpu/native/src/*.hh')),
            optional=True,
        )
    ],
    cmdclass={'build_ext': NativeLibBuild},
)
