"""``da4ml-tpu monitor`` — serve the live observability endpoints.

Two shapes (docs/observability.md):

- ``da4ml-tpu monitor --port 9100`` — serve *this* process's registry.
  Mostly useful programmatically (``telemetry.serve``) or via
  ``DA4ML_METRICS_PORT`` inside a solve process; standalone it shows an
  empty registry.
- ``da4ml-tpu monitor --follow trace.jsonl --port 9100`` — tail a
  *running campaign's* streaming JSONL trace and serve its mirrored
  metrics snapshot over ``/metrics`` (plus follow health on ``/healthz``:
  a trace that stops growing while spans are still open reads degraded).

``--duration`` bounds the serve loop (CI smoke); default runs until
interrupted.
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path


def add_monitor_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument('--port', type=int, default=None, help='Bind port (default: DA4ML_METRICS_PORT or ephemeral)')
    parser.add_argument('--host', default='127.0.0.1', help='Bind host (default 127.0.0.1)')
    parser.add_argument('--follow', type=Path, default=None, help='Streaming .jsonl trace of the process to monitor')
    parser.add_argument('--interval', type=float, default=1.0, help='Trace poll interval in seconds')
    parser.add_argument('--duration', type=float, default=0.0, help='Serve for N seconds then exit (0 = until Ctrl-C)')
    parser.add_argument(
        '--stall-after', type=float, default=60.0, help='--follow: seconds without new events before health degrades'
    )


def monitor_main(args: argparse.Namespace) -> int:
    from ..telemetry import get_logger
    from ..telemetry.obs.server import serve

    log = get_logger('cli.monitor')
    tailer = None
    if args.follow is not None:
        if args.follow.suffix != '.jsonl':
            log.warning(f'--follow expects a streaming .jsonl trace, got {args.follow}')
            return 2
        from ..telemetry.obs.openmetrics import render_openmetrics
        from ..telemetry.obs.tailer import TraceTailer

        tailer = TraceTailer(args.follow)
        tailer.poll()

        def _metrics() -> str:
            return render_openmetrics(tailer.metrics)

        def _health() -> dict:
            stale = tailer.staleness_s > args.stall_after
            return {
                'status': 'degraded' if stale else 'ok',
                'checks': {
                    'follow': {
                        'status': 'degraded' if stale else 'ok',
                        'trace': str(args.follow),
                        'n_events': len(tailer.events),
                        'staleness_s': round(tailer.staleness_s, 3),
                        'stall_after_s': args.stall_after,
                    }
                },
            }

        def _status() -> dict:
            from .stats import summarize_events

            return {
                'follow': str(args.follow),
                'n_events': len(tailer.events),
                'n_bad_lines': tailer.n_bad_lines,
                'staleness_s': round(tailer.staleness_s, 3),
                'summary': summarize_events(tailer.events),
                'metrics': tailer.metrics,
            }

        server = serve(
            port=args.port, host=args.host, metrics_provider=_metrics, health_provider=_health, status_provider=_status
        )
    else:
        server = serve(port=args.port, host=args.host)

    log.info(json.dumps({'serving': server.url, 'endpoints': ['/metrics', '/healthz', '/statusz']}))
    deadline = time.monotonic() + args.duration if args.duration > 0 else None
    try:
        while deadline is None or time.monotonic() < deadline:
            if tailer is not None:
                tailer.poll()
            time.sleep(min(args.interval, 0.5))
    except KeyboardInterrupt:
        pass
    return 0
