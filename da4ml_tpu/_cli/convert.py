"""``da4ml-tpu convert`` — model file → RTL/HLS project.

Accepts a Keras model (.keras/.h5, requires the keras tracer plugin) or a
saved CombLogic/Pipeline ``.json``. Writes the project, runs a bit-exact
DAIS-vs-framework mismatch report, and can optionally compile and validate
the generated RTL/HLS emulator against the interpreter (parity: reference
src/da4ml/_cli/convert.py:8-147).
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path

import numpy as np


def _load_solution(path: Path):
    """Load a saved CombLogic or Pipeline from .json."""
    from ..ir import CombLogic, Pipeline

    blob = json.loads(Path(path).read_text())
    if isinstance(blob, dict) and 'stages' in blob:
        return Pipeline.from_dict(blob)
    return CombLogic.from_dict(blob)


def _emulate(da_model, flavor: str, data: np.ndarray) -> np.ndarray:
    """Run the generated project: compiled emulator if the toolchain exists
    (Verilator for RTL, g++ for HLS), else the bundled netlist simulator.

    Real build failures propagate — only a missing toolchain falls back."""
    if flavor not in ('verilog', 'vhdl') or da_model.emulation_available():
        return da_model.compile().predict(data)
    print('[WARNING] verilator/ghdl not found; validating with the bundled netlist simulator instead of compiled RTL.')
    if flavor == 'verilog':
        from ..codegen.rtl.verilog.netlist_sim import simulate_comb
    else:
        from ..codegen.rtl.vhdl.netlist_sim import simulate_comb_vhdl as simulate_comb

    sol = da_model.solution
    stages = sol.stages if hasattr(sol, 'stages') else (sol,)
    cur = data
    for si, stage in enumerate(stages):
        cur = simulate_comb(stage, name=f's{si}', data=cur)
    return cur


def convert(
    model_path: Path,
    outdir: Path,
    n_test_sample: int = 1024,
    clock_period: float = 5.0,
    clock_uncertainty: float = 10.0,
    flavor: str = 'verilog',
    latency_cutoff: float = 5,
    part_name: str = 'xcvu13p-flga2577-2-e',
    verbose: int = 1,
    validate_rtl: bool = False,
    hwconf: tuple[int, int, int] = (1, -1, -1),
    hard_dc: int = 2,
    n_threads: int = 0,
    inputs_kif: tuple[int, int, int] | None = None,
    solver_backend: str = 'auto',
    n_restarts: int = 1,
    method0_candidates: list[str] | None = None,
    quality: str = 'fast',
    deadline: float | None = None,
    fallback: str | bool | None = None,
    resume: Path | None = None,
):
    from ..codegen import HLSModel, RTLModel, VHDLModel

    model_path, outdir = Path(model_path), Path(outdir)
    outdir.mkdir(parents=True, exist_ok=True)

    model = None
    if model_path.suffix in {'.h5', '.keras', '.pt', '.pth'}:
        if model_path.suffix in {'.h5', '.keras'}:
            try:
                import keras  # noqa: F401
            except ImportError as e:
                raise RuntimeError('Converting .keras/.h5 models requires keras to be installed.') from e
            # register the QKeras-compatible custom objects so quantized models
            # deserialize (reference: hgq import in src/da4ml/_cli/convert.py:32-35)
            from ..converter import qkeras_compat  # noqa: F401

            model = keras.models.load_model(model_path, compile=False)
            if verbose > 1:
                model.summary()
        else:
            try:
                import torch
            except ImportError as e:
                raise RuntimeError('Converting .pt/.pth models requires torch to be installed.') from e
            # a pickled nn.Module (torch.save(model, path)); a bare state_dict
            # carries no architecture and is rejected with a clear message
            model = torch.load(model_path, map_location='cpu', weights_only=False)
            if not isinstance(model, torch.nn.Module):
                raise ValueError(
                    f'{model_path} does not contain an nn.Module (got {type(model).__name__}); '
                    'save the full module with torch.save(model, path), not just its state_dict'
                )
            model.eval()
        from ..converter import trace_model
        from ..trace import HWConfig, comb_trace

        # reliability layer (docs/reliability.md): per-solve deadline,
        # backend fallback chain, and crash-safe per-kernel checkpoint so a
        # killed conversion resumes instead of re-solving finished layers
        reliability_opts: dict = {}
        if deadline is not None:
            reliability_opts['deadline'] = deadline
        if fallback is not None:
            reliability_opts['fallback'] = {'on': True, 'off': False}.get(fallback, fallback)
        if resume is not None:
            from ..reliability import store_for

            reliability_opts['checkpoint'] = store_for(resume)
        inp, out = trace_model(
            model,
            HWConfig(*hwconf),
            {
                'hard_dc': hard_dc,
                'backend': solver_backend,
                'n_restarts': n_restarts,
                **({'method0_candidates': method0_candidates} if method0_candidates else {}),
                **({'quality': quality} if quality and quality != 'fast' else {}),
                **reliability_opts,
            },
            verbose > 1,
            inputs_kif=inputs_kif,
        )
        comb = comb_trace(inp, out)
    elif model_path.suffix == '.json':
        comb = _load_solution(model_path)
    else:
        raise ValueError(f'Unsupported model file format: {model_path.suffix}')

    if flavor == 'verilog':
        da_model = RTLModel(
            comb, 'model', outdir, latency_cutoff=latency_cutoff, part=part_name,
            clock_period=clock_period, clock_uncertainty=clock_uncertainty / 100,
        )  # fmt: skip
    elif flavor == 'vhdl':
        da_model = VHDLModel(
            comb, 'model', outdir, latency_cutoff=latency_cutoff, part=part_name,
            clock_period=clock_period, clock_uncertainty=clock_uncertainty / 100,
        )  # fmt: skip
    elif flavor in ('vitis', 'hls', 'hlslib', 'oneapi'):
        da_model = HLSModel(
            comb, 'model', outdir, latency_cutoff=latency_cutoff, part=part_name, clock_period=clock_period,
            flavor='vitis' if flavor == 'hls' else flavor,
        )  # fmt: skip
    else:
        raise ValueError(f'Unknown flavor: {flavor}')

    da_model.write()
    solution = da_model.solution
    if verbose > 1:
        print(repr(da_model))
    if verbose:
        print(f'[INFO] Project written to {outdir} (flavor={flavor})')

    if not n_test_sample:
        return da_model

    n_in = solution.shape[0] if not hasattr(solution, 'stages') else solution.stages[0].shape[0]
    rng = np.random.default_rng(0)

    def _input_grid_data() -> np.ndarray | None:
        """Random samples on the traced inputs' own fixed-point grid, in
        range — the only data a fixed-point input lane can physically carry
        (off-grid floats would compare the framework's saturation against
        the hardware's wrap)."""
        try:
            k_, i_, f_ = (np.asarray(v, np.float64).ravel() for v in inp.kif)
        except Exception:
            return None
        if not np.all(np.isfinite(i_)) or not np.all(np.isfinite(f_)):
            return None
        eps = 2.0**-f_
        lo_i = np.round(-(2.0**i_) * k_ / eps).astype(np.int64)
        hi_i = np.round((2.0**i_ - eps) / eps).astype(np.int64)
        # stay one lsb inside both ends: the recorded input precision can
        # carry a rounding guard bit (RND input quantizers), and boundary
        # values would round out of range — where the framework saturates
        # but the recorded WRAP input wraps
        return rng.integers(lo_i + 1, np.maximum(hi_i, lo_i + 2), (n_test_sample, len(eps))).astype(np.float64) * eps

    if model is not None:
        if hasattr(model, 'predict') and hasattr(model, 'inputs'):  # keras
            in_shapes = [tuple(int(v) for v in i.shape[1:]) for i in model.inputs]

            def _forward(parts):
                y = model.predict(parts if len(parts) > 1 else parts[0], batch_size=16384, verbose=0)
                ys = y if isinstance(y, list) else [y]
                return np.concatenate([np.asarray(v).reshape(n_test_sample, -1) for v in ys], axis=1)
        else:  # torch module: input_shape is in torch-native layout
            import torch

            shape = getattr(model, 'input_shape', None)
            if shape is None:
                raise ValueError('torch models need an `input_shape` attribute (torch-native layout) for validation')
            in_shapes = [tuple(int(d) for d in shape)]

            def _forward(parts):
                with torch.no_grad():
                    y = model(torch.as_tensor(parts[0], dtype=torch.float32))
                ys = y if isinstance(y, (list, tuple)) else [y]
                return np.concatenate([np.asarray(v, np.float64).reshape(n_test_sample, -1) for v in ys], axis=1)

        grid = _input_grid_data()
        if grid is not None:
            sizes = [int(np.prod(s)) for s in in_shapes]
            split = np.split(grid, np.cumsum(sizes)[:-1], axis=1)
            data_in = [part.reshape(n_test_sample, *s).astype(np.float32) for part, s in zip(split, in_shapes)]
        else:
            data_in = [rng.uniform(-32, 32, (n_test_sample, *s)).astype(np.float32) for s in in_shapes]
        y_model = _forward(data_in)
        flat_in = np.concatenate([d.reshape(n_test_sample, -1) for d in data_in], axis=1)
        y_comb = solution.predict(flat_in, n_threads=n_threads)

        mask = y_comb != y_model
        ndiff, total = int(np.sum(mask)), int(y_comb.size)
        if ndiff:
            abs_diff = np.abs(y_comb - y_model)[mask]
            rel_diff = abs_diff / (np.abs(y_model[mask]) + 1e-6)
            stats = {
                'max_diff': float(abs_diff.max()),
                'max_rel_diff': float(rel_diff.max()),
                'mean_diff': float(abs_diff.mean()),
                'mean_rel_diff': float(rel_diff.mean()),
            }
            print(f'[WARNING] {ndiff}/{total} mismatches vs framework output: {stats}')
        else:
            stats = {'max_diff': 0.0, 'max_rel_diff': 0.0, 'mean_diff': 0.0, 'mean_rel_diff': 0.0}
            if verbose:
                print(f'[INFO] DAIS simulation matches framework: [0/{total}] mismatches.')
        (outdir / 'mismatches.json').write_text(
            json.dumps({'n_total': total, 'n_mismatch': ndiff, **stats})
        )
    else:
        data_in = rng.uniform(-32, 32, (n_test_sample, n_in)).astype(np.float64)
        flat_in = data_in
        y_comb = solution.predict(flat_in, n_threads=n_threads)

    if validate_rtl:
        y_emu = _emulate(da_model, flavor, flat_in)
        total = int(y_comb.size)
        if not np.array_equal(y_comb, y_emu):
            raise RuntimeError(f'[CRITICAL] emulation validation failed: {int(np.sum(y_comb != y_emu))}/{total} mismatches!')
        if verbose:
            kind = 'RTL' if flavor in ('verilog', 'vhdl') else 'FUNC'
            print(f'[INFO] {kind} validation passed: [0/{total}] mismatches.')

    return da_model


def convert_main(args: argparse.Namespace) -> int:
    from .. import telemetry

    if getattr(args, 'trace', None):
        # one sink for the whole conversion; closed (and flushed to disk)
        # before the command returns so the file is complete even when a
        # later CLI step in the same process runs more solves
        telemetry.enable(args.trace)
    try:
        return _convert_main(args)
    finally:
        if getattr(args, 'trace', None):
            telemetry.disable()


def _convert_main(args: argparse.Namespace) -> int:
    if getattr(args, 'warmup', False) and args.solver_backend == 'jax':
        # overlap the dominant-shape-class compile ladder with model load +
        # host-side tracing (CSD/decompose): by the time the first device
        # solve dispatches, the small classes are already in the caches.
        # Only meaningful for the device solver — 'auto' resolves to the
        # host path, which compiles nothing.
        import threading

        from .warmup import warmup_main

        wargs = argparse.Namespace(
            max_dim=args.warmup_max_dim, bits=6, verbose=args.verbose > 1, quiet=args.verbose < 1
        )
        threading.Thread(target=warmup_main, args=(wargs,), daemon=True, name='da4ml-warmup').start()
    elif getattr(args, 'warmup', False) and args.verbose:
        print('[INFO] --warmup skipped: only applies with --solver-backend jax')
    from .. import telemetry

    with telemetry.span('cli.convert', model=str(args.model), flavor=args.flavor):
        convert(
            args.model,
            args.outdir,
            n_test_sample=args.n_test_sample,
            clock_period=args.clock_period,
            clock_uncertainty=args.clock_uncertainty,
            flavor=args.flavor,
            latency_cutoff=args.latency_cutoff,
            part_name=args.part_name,
            verbose=args.verbose,
            validate_rtl=args.validate_rtl,
            hwconf=tuple(args.hw_config),
            hard_dc=args.delay_constraint,
            n_threads=args.n_threads,
            inputs_kif=tuple(args.inputs_kif) if args.inputs_kif else None,
            solver_backend=args.solver_backend,
            n_restarts=args.n_restarts,
            method0_candidates=args.methods,
            quality=args.quality,
            deadline=args.deadline,
            fallback=args.fallback,
            resume=args.resume,
        )
    return 0


def add_convert_args(parser: argparse.ArgumentParser):
    parser.add_argument('model', type=Path, help='Model file: .keras/.h5 (needs keras) or saved CombLogic/Pipeline .json')
    parser.add_argument('outdir', type=Path, help='Output project directory')
    parser.add_argument('--n-test-sample', '-n', type=int, default=1024, help='Validation sample count (0 disables)')
    parser.add_argument('--clock-period', '-c', type=float, default=5.0, help='Clock period in ns')
    parser.add_argument('--clock-uncertainty', '-unc', type=float, default=10.0, help='Clock uncertainty in percent')
    parser.add_argument(
        '--flavor', type=str, default='verilog', choices=['verilog', 'vhdl', 'vitis', 'hls', 'hlslib', 'oneapi']
    )
    parser.add_argument('--latency-cutoff', '-lc', type=float, default=5, help='Latency cutoff for pipelining (<=0: comb)')
    parser.add_argument('--part-name', '-p', type=str, default='xcvu13p-flga2577-2-e', help='FPGA part name')
    parser.add_argument('--verbose', '-v', default=1, type=int, help='0 silent, 1 info, 2 debug')
    parser.add_argument('--validate-rtl', '-vr', action='store_true', help='Compile the emulator and check bit-exactness')
    parser.add_argument('--n-threads', '-j', type=int, default=0, help='Threads for native DAIS simulation (0 = all)')
    parser.add_argument(
        '--hw-config', '-hc', type=int, nargs=3, metavar=('ADDER_SIZE', 'CARRY_SIZE', 'CUTOFF'), default=[1, -1, -1]
    )
    parser.add_argument('--delay-constraint', '-dc', type=int, default=2, help='hard_dc per CMVM block')
    parser.add_argument('--inputs-kif', '-ikif', type=int, nargs=3, default=None, help='Input precision (keep_neg, int, frac)')
    parser.add_argument(
        '--solver-backend', type=str, default='auto', choices=['auto', 'cpu', 'cpp', 'jax'], help='CMVM solver backend'
    )
    parser.add_argument(
        '--warmup',
        action='store_true',
        help='Pre-compile the dominant device shape classes in the background while the model loads/traces',
    )
    parser.add_argument('--warmup-max-dim', type=int, default=64, help='Largest square class the --warmup ladder compiles')
    parser.add_argument(
        '--n-restarts',
        type=int,
        default=1,
        help='Random-restart lanes per CMVM solve (jax backend): widens the sweep, argmin keeps the cheapest',
    )
    parser.add_argument(
        '--methods',
        type=str,
        nargs='+',
        default=None,
        choices=['mc', 'wmc', 'mc-dc', 'mc-pdc', 'wmc-dc', 'wmc-pdc'],
        help='Selection heuristics to sweep (replaces the default wmc; the argmin keeps the cheapest)',
    )
    parser.add_argument(
        '--quality',
        type=str,
        default='fast',
        choices=['fast', 'search', 'max'],
        help="CMVM search strategy (docs/cmvm.md#search-strategies): 'fast' = greedy (default, "
        "byte-identical to previous releases), 'search' = focused beam-5 with the host oracle "
        "folded in, 'max' = beam-8 + all heuristics + restarts. Beam lanes need the jax solver "
        'backend; host backends keep the portfolio sweep and warn once',
    )
    parser.add_argument(
        '--deadline',
        type=float,
        default=None,
        help='Per-CMVM-solve wall-clock budget in seconds; a hung solve raises SolveTimeout instead of stalling',
    )
    parser.add_argument(
        '--fallback',
        type=str,
        default=None,
        help="Backend degradation: 'on' (default; jax -> native-threads -> pure-python), 'off', "
        "or an explicit comma-separated chain (e.g. 'native-threads,pure-python')",
    )
    parser.add_argument(
        '--resume',
        type=Path,
        default=None,
        help='Checkpoint file for per-kernel CMVM results: a killed conversion resumes here '
        'instead of re-solving finished layers (host solver paths)',
    )
    parser.add_argument(
        '--trace',
        type=Path,
        default=None,
        help='Capture a telemetry trace of the conversion to this path: Chrome trace-event JSON '
        '(open in Perfetto / chrome://tracing), or a streaming JSONL event log when the path '
        'ends in .jsonl. Summarize with `da4ml-tpu stats <path>`. Equivalent to DA4ML_TRACE=<path>.',
    )
