"""``da4ml-tpu serve`` — the resilient HTTP inference front-end.

Serves one or more saved models (``name=path.json`` or bare paths, names
defaulting to the file stem) behind deadline-aware dynamic batching with
admission control (docs/serving.md):

    da4ml-tpu serve examples/kernels/cmvm_pipeline.json --port 8080
    da4ml-tpu serve mlp=model.json --max-batch-rows 512 --shed-policy deadline-edf

Prints one JSON line with the bound URL + loaded models once warm, then
runs until SIGTERM/SIGINT (or ``--duration``). Shutdown is graceful:
admission stops, every accepted request is served, and the process exits
0 with zero lost accepted requests. ``--chaos`` runs the breaker-trip +
reload drill instead and exits 0/1 on its gate (the CI ``serve-chaos``
job).
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time
from pathlib import Path


def add_serve_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument('models', nargs='*', help="Models to serve: 'name=path.json' or bare paths (name = stem)")
    parser.add_argument('--port', type=int, default=0, help='Bind port (0 = ephemeral, printed on the ready line)')
    parser.add_argument('--host', default='127.0.0.1', help='Bind host (default 127.0.0.1)')
    parser.add_argument('--max-batch-rows', type=int, default=256, help='Row budget per coalesced device batch')
    parser.add_argument('--max-latency-ms', type=float, default=5.0, help='Batch coalescing window')
    parser.add_argument('--queue-cap-rows', type=int, default=1024, help='Hard admission ceiling (rows) per model')
    parser.add_argument(
        '--shed-policy', choices=('reject-newest', 'deadline-edf'), default='reject-newest', help='Overload shed policy'
    )
    parser.add_argument('--deadline-ms', type=float, default=1000.0, help='Default per-request deadline (0 = unbounded)')
    parser.add_argument('--hedge-ms', type=float, default=0.0, help='Straggler hedge delay (0 = off)')
    parser.add_argument(
        '--degraded', choices=('fallback', 'shed'), default='fallback', help='Open-breaker mode (docs/serving.md)'
    )
    parser.add_argument('--degraded-max-rows', type=int, default=32, help='Row budget while degraded')
    parser.add_argument('--breaker-threshold', type=int, default=3, help='Consecutive failures that open the breaker')
    parser.add_argument('--breaker-reset-s', type=float, default=5.0, help='Breaker cooldown before a half-open probe')
    parser.add_argument('--no-prewarm', action='store_true', help='Skip the canonical-grid warmup on load')
    parser.add_argument(
        '--solve-store',
        default=None,
        metavar='DIR',
        help='Mount POST /v1/solve over this solution store dir (default: DA4ML_SOLUTION_STORE if set)',
    )
    parser.add_argument('--solve-backend', default='auto', help='/v1/solve solver backend (default auto)')
    parser.add_argument('--solve-workers', type=int, default=1, help='/v1/solve worker threads')
    parser.add_argument('--solve-queue-rows', type=int, default=256, help='/v1/solve admission ceiling (kernel rows)')
    parser.add_argument(
        '--solve-deadline-ms', type=float, default=30000.0, help='/v1/solve default deadline (0 = unbounded)'
    )
    parser.add_argument(
        '--registry',
        type=Path,
        default=None,
        metavar='DIR',
        help='Announce this replica in a fleet registry dir (lease + URL sidecar; docs/serving.md#replica-fleets)',
    )
    parser.add_argument(
        '--replica-id', default=None, help='Registry slot id (default: r<pid>); requires --registry'
    )
    parser.add_argument('--duration', type=float, default=0.0, help='Serve for N seconds then drain (0 = until signal)')
    parser.add_argument('--chaos', action='store_true', help='Run the breaker-trip + reload chaos drill and exit')
    parser.add_argument('--drill-duration', type=float, default=6.0, help='--chaos: load duration in seconds')
    parser.add_argument('--json', action='store_true', dest='as_json', help='--chaos: print the full report as JSON')
    parser.add_argument('--out', type=Path, default=None, help='--chaos: also write the report JSON here')


def _parse_models(specs: list[str]) -> list[tuple[str, str]]:
    out = []
    for spec in specs:
        if '=' in spec:
            name, path = spec.split('=', 1)
        else:
            name, path = Path(spec).stem, spec
        out.append((name, path))
    return out


def serve_main(args: argparse.Namespace) -> int:
    from ..serve.engine import ServeConfig, ServeEngine
    from ..telemetry import get_logger

    log = get_logger('cli.serve')
    config = ServeConfig(
        max_batch_rows=args.max_batch_rows,
        max_latency_ms=args.max_latency_ms,
        queue_cap_rows=args.queue_cap_rows,
        shed_policy=args.shed_policy,
        default_deadline_ms=args.deadline_ms if args.deadline_ms > 0 else None,
        hedge_ms=args.hedge_ms,
        degraded=args.degraded,
        degraded_max_rows=args.degraded_max_rows,
        breaker_threshold=args.breaker_threshold,
        breaker_reset_s=args.breaker_reset_s,
        prewarm=not args.no_prewarm,
    )

    if args.chaos:
        from ..serve.chaos import chaos_drill

        source = args.models[0].split('=', 1)[-1] if args.models else None
        report = chaos_drill(source, duration_s=args.drill_duration, config=None)
        text = json.dumps(report if args.as_json else report['checks'], indent=1)
        log.info(text)
        if args.out is not None:
            args.out.write_text(json.dumps(report, indent=1))
        return 0 if report['ok'] else 1

    import os

    solve_store = args.solve_store if args.solve_store is not None else os.environ.get('DA4ML_SOLUTION_STORE')
    if not args.models and not solve_store:
        log.warning('no models given (pass name=path.json) and no --solve-store; nothing to serve')
        return 2

    engine = ServeEngine(config)
    for name, path in _parse_models(args.models):
        engine.load_model(name, path)

    solve_service = None
    if solve_store:
        from ..store.service import SolveService

        solve_service = SolveService(
            store=solve_store,
            backend=args.solve_backend,
            queue_cap_rows=args.solve_queue_rows,
            workers=args.solve_workers,
            default_deadline_s=args.solve_deadline_ms / 1e3 if args.solve_deadline_ms > 0 else None,
        )

    from ..serve.http import ServeServer

    server = ServeServer(engine, port=args.port, host=args.host, solve_service=solve_service)
    endpoints = ['/v1/infer', '/v1/models', '/metrics', '/healthz', '/statusz']
    if solve_service is not None:
        endpoints.insert(1, '/v1/solve')

    announcement = None
    if args.registry is not None:
        from ..serve.fleet import announce_replica

        replica_id = args.replica_id or f'r{os.getpid()}'
        announcement = announce_replica(
            args.registry,
            replica_id,
            server.url,
            meta={'models': [m['name'] for m in engine.models()['models']]},
        )
        if announcement is None:
            log.warning(json.dumps({'error': f'registry slot {replica_id} is held by a live replica', 'exit': 3}))
            server.close()
            return 3

    ready = {
        'serving': server.url,
        'models': [m['name'] for m in engine.models()['models']],
        'endpoints': endpoints,
    }
    if announcement is not None:
        ready['replica_id'] = announcement.replica_id
    log.info(json.dumps(ready))
    sys.stdout.flush()

    stop = threading.Event()

    def _graceful(signum, frame):
        stop.set()

    prev_term = signal.signal(signal.SIGTERM, _graceful)
    prev_int = signal.signal(signal.SIGINT, _graceful)
    deadline = time.monotonic() + args.duration if args.duration > 0 else None
    try:
        while not stop.is_set() and (deadline is None or time.monotonic() < deadline):
            stop.wait(0.2)
    finally:
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)
        # withdraw from the registry FIRST so routers stop sending new
        # traffic, then drain what was already accepted — the
        # zero-lost-accepted-requests exit contract
        if announcement is not None:
            announcement.close()
        drained = engine.drain(timeout=30.0)
        if solve_service is not None:
            solve_service.close()
        server.close()
        log.info(json.dumps({'drained': drained, 'exit': 0 if drained else 1}))
    return 0 if drained else 1
