"""``da4ml-tpu warmup`` — pre-populate the persistent XLA compile cache.

The device search compiles one program per (P, O, B, select, rows) shape
class; through a remote TPU compiler a cold class costs seconds. A first
conversion therefore pays a compile-dominated wall clock (the round-2 cold
full-model trace measured 0.76x the host). This command runs one tiny solve
per common shape class up front so later conversions hit the persistent
cache (``jax_compilation_cache_dir``, env ``DA4ML_JAX_CACHE``).

Class lattice note: O buckets to powers of two (min 8), B to even counts,
P to the pow2 rung ladder — so one warm class serves every matrix that
buckets into it, across processes.
"""

from __future__ import annotations

import os
import time


def add_warmup_args(parser) -> None:
    parser.add_argument(
        '--max-dim', '-d', type=int, default=64, help='Largest square-kernel dimension class to warm (default 64)'
    )
    parser.add_argument('--bits', '-b', type=int, default=6, help='Weight bit width used for the probe kernels')
    parser.add_argument('--verbose', '-v', action='store_true')


def warmup_main(args) -> int:
    import jax

    try:
        # arm the persistent cache only when the process has not configured
        # one — when warmup runs inside a conversion process (--warmup) it
        # must never redirect a user-configured cache dir mid-run
        if not jax.config.read('jax_compilation_cache_dir'):
            jax.config.update('jax_compilation_cache_dir', os.environ.get('DA4ML_JAX_CACHE', '/tmp/da4ml_jax_cache'))
            jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
    except Exception:
        pass

    import numpy as np

    from .. import telemetry
    from ..cmvm.jax_search import solve_jax_many
    from ..telemetry.metrics import enable_metrics

    # each ladder's compile wall clock lands in the warmup.compile_s
    # histogram (visible via `da4ml-tpu stats` / bench metrics snapshots)
    # alongside the human-readable lines below
    enable_metrics()

    rng = np.random.default_rng(0)
    dims = [d for d in (4, 8, 16, 32, 64, 128, 256) if d <= args.max_dim]
    t_all = time.perf_counter()
    for d in dims:
        kern = (rng.integers(0, 2**args.bits, (d, d)) * rng.choice([-1, 1], (d, d))).astype(np.float64)
        t0 = time.perf_counter()
        sol = solve_jax_many([kern])[0]
        assert np.array_equal(np.asarray(sol.kernel, np.float64), kern)
        dt = time.perf_counter() - t0
        telemetry.histogram('warmup.compile_s').observe(dt)
        if args.verbose:
            print(f'  {d}x{d}: {dt:.1f}s')
    if not getattr(args, 'quiet', False):
        print(f'warmup: {len(dims)} shape-class ladders compiled/cached in {time.perf_counter() - t_all:.1f}s')
    return 0
