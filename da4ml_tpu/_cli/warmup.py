"""``da4ml-tpu warmup`` — pre-populate the persistent XLA compile cache.

The device search compiles one program per (P, O, B, select, rows, lane
bucket) shape class; through a remote TPU compiler a cold class costs
seconds. A first conversion therefore pays a compile-dominated wall clock
(the round-2 cold full-model trace measured 0.76x the host). This command
populates the persistent cache (``jax_compilation_cache_dir``, env
``DA4ML_XLA_CACHE``, default ``~/.cache/da4ml_tpu/xla``) up front so later
conversions — in ANY process on this machine — deserialize compiled
executables instead of compiling.

Two mechanisms, both on by default:

- ``--grid`` AOT-precompiles the **canonical bucket grid**: every rung of
  every canonical (O, B) bucket a standard ``solve_jax_many`` over square
  kernels up to ``--max-dim`` would walk (lower + compile, no execution —
  mirrors the live scheduler through ``_ladder_specs``, so the classes
  match exactly);
- the **solve ladder** then runs one tiny real solve per dimension class,
  which exercises upload/fetch/emit and verifies the cached executables
  actually load.

Class lattice note: O and B bucket to the canonical 2^k / 3*2^k / 5*2^k
grid per lane, P to the pow2 rung ladder — classes are batch-independent,
so one warm class serves every matrix that buckets into it, across
processes (docs/api.md#bucketing).
"""

from __future__ import annotations

import os
import time


def add_warmup_args(parser) -> None:
    parser.add_argument(
        '--max-dim', '-d', type=int, default=64, help='Largest square-kernel dimension class to warm (default 64)'
    )
    parser.add_argument('--bits', '-b', type=int, default=6, help='Weight bit width used for the probe kernels')
    parser.add_argument(
        '--cache-dir',
        default=None,
        help='Persistent compile cache directory (default DA4ML_XLA_CACHE or ~/.cache/da4ml_tpu/xla)',
    )
    parser.add_argument(
        '--no-grid',
        dest='grid',
        action='store_false',
        default=True,
        help='Skip the AOT canonical-bucket-grid precompile (solve ladder only)',
    )
    parser.add_argument(
        '--grid-only',
        action='store_true',
        help='AOT-precompile the canonical grid but skip the live solve ladder',
    )
    parser.add_argument(
        '--quality',
        default=None,
        help="Also warm the device-beam classes of this search preset (e.g. 'search'): "
        'fork/prune/fan-out kernels and the fork lanes’ full-record CSE rungs, so a '
        "warm quality= solve compiles nothing (default: greedy classes only)",
    )
    parser.add_argument('--verbose', '-v', action='store_true')


def warmup_main(args) -> int:
    if getattr(args, 'cache_dir', None):
        os.environ['DA4ML_XLA_CACHE'] = args.cache_dir

    import numpy as np

    from .. import telemetry
    from ..cmvm.jax_search import ensure_compile_cache, prewarm_for_kernels, solve_jax_many
    from ..telemetry.metrics import enable_metrics

    cache_dir = ensure_compile_cache()

    # each ladder's compile wall clock lands in the warmup.compile_s
    # histogram (visible via `da4ml-tpu stats` / bench metrics snapshots)
    # alongside the human-readable lines below
    enable_metrics()

    rng = np.random.default_rng(0)
    dims = [d for d in (4, 8, 16, 32, 64, 128, 256) if d <= args.max_dim]
    kernels = {
        d: (rng.integers(0, 2**args.bits, (d, d)) * rng.choice([-1, 1], (d, d))).astype(np.float64) for d in dims
    }
    t_all = time.perf_counter()

    if getattr(args, 'grid', True):
        # AOT pass: every (spec, lane bucket) class of the canonical grid,
        # compiled inline on this thread (lower + compile, no device
        # execution), each recorded in the cache-marker set so later
        # processes classify their first calls as jit.cache_load
        t0 = time.perf_counter()
        n_classes = prewarm_for_kernels(
            [[k] for k in kernels.values()], full_ladder=True, inline=True, quality=getattr(args, 'quality', None)
        )
        dt = time.perf_counter() - t0
        telemetry.histogram('warmup.grid_s').observe(dt)
        if args.verbose:
            print(f'  grid: {n_classes} canonical classes AOT-compiled in {dt:.1f}s')

    if not getattr(args, 'grid_only', False):
        for d in dims:
            kern = kernels[d]
            t0 = time.perf_counter()
            sol = solve_jax_many([kern], quality=getattr(args, 'quality', None))[0]
            assert np.array_equal(np.asarray(sol.kernel, np.float64), kern)
            dt = time.perf_counter() - t0
            telemetry.histogram('warmup.compile_s').observe(dt)
            if args.verbose:
                print(f'  {d}x{d}: {dt:.1f}s')
    if not getattr(args, 'quiet', False):
        where = f' -> {cache_dir}' if cache_dir else ''
        print(f'warmup: {len(dims)} shape-class ladders compiled/cached in {time.perf_counter() - t_all:.1f}s{where}')
    return 0
