"""``da4ml-tpu fleet`` — replica-fleet serving driver.

Spawns N supervised ``da4ml-tpu serve`` replicas hot-loading one export
artifact, mounts the health-aware hedging router above them, and prints
one JSON ready line with the router URL (docs/serving.md#replica-fleets):

    da4ml-tpu export model.json artifact/
    da4ml-tpu fleet --artifact artifact/ --replicas 4 --store /mnt/solutions

``--status`` prints the live replica set of an existing registry dir;
``--chaos`` runs the fleet chaos drill (SIGKILL + hot reload under
sustained load, the CI ``fleet-chaos`` job) and exits 0/1 on its gate.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
from pathlib import Path


def add_fleet_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument('--artifact', type=Path, default=None, help='Export artifact dir every replica hot-loads')
    parser.add_argument('--replicas', type=int, default=4, help='Number of serve replicas (default 4)')
    parser.add_argument(
        '--fleet-dir', type=Path, default=None, help='Fleet state dir: registry, logs, local cache tiers (default tmp)'
    )
    parser.add_argument(
        '--store', type=Path, default=None, help='Shared solution store dir (replicas get per-replica local tiers)'
    )
    parser.add_argument('--model-name', default='default', help='Model name the replicas serve (default: default)')
    parser.add_argument('--router-port', type=int, default=0, help='Router bind port (0 = ephemeral)')
    parser.add_argument('--router-host', default='127.0.0.1', help='Router bind host')
    parser.add_argument('--hedge-ms', type=float, default=75.0, help='Straggler hedge delay at the router')
    parser.add_argument('--max-attempts', type=int, default=3, help='Max legs (primary + hedge/retries) per request')
    parser.add_argument('--duration', type=float, default=0.0, help='Run for N seconds then stop (0 = until signal)')
    parser.add_argument('--status', action='store_true', help='Print the live replica set of --fleet-dir and exit')
    parser.add_argument(
        '--trace',
        action='store_true',
        help='Arm per-replica JSONL tracing under <fleet-dir>/traces; with --chaos the drill merges one fleet timeline',
    )
    parser.add_argument('--chaos', action='store_true', help='Run the fleet SIGKILL+reload chaos drill and exit')
    parser.add_argument('--drill-duration', type=float, default=10.0, help='--chaos: sustained load duration (s)')
    parser.add_argument('--json', action='store_true', dest='as_json', help='--chaos: print the full report as JSON')
    parser.add_argument('--out', type=Path, default=None, help='--chaos: also write the report JSON here')


def fleet_main(args: argparse.Namespace) -> int:
    from ..telemetry import get_logger

    log = get_logger('cli.fleet')

    if args.status:
        if args.fleet_dir is None:
            log.warning('--status requires --fleet-dir')
            return 2
        from ..serve.fleet import discover_replicas

        live = discover_replicas(Path(args.fleet_dir) / 'registry')
        log.info(json.dumps({'n_live': len(live), 'replicas': live}, indent=1, default=str))
        return 0

    if args.chaos:
        from ..serve.chaos import fleet_chaos_drill

        report = fleet_chaos_drill(
            replicas=args.replicas,
            duration_s=args.drill_duration,
            hedge_ms=args.hedge_ms,
            fleet_dir=args.fleet_dir,
            trace=args.trace,
        )
        log.info(json.dumps(report if args.as_json else report['checks'], indent=1, default=str))
        if args.out is not None:
            args.out.write_text(json.dumps(report, indent=1, default=str))
        return 0 if report['ok'] else 1

    if args.artifact is None:
        log.warning('--artifact is required (run `da4ml-tpu export` first), or use --chaos / --status')
        return 2

    from ..serve.fleet import Fleet
    from ..serve.router import Router, RouterServer

    fleet = Fleet(
        args.artifact,
        replicas=args.replicas,
        fleet_dir=args.fleet_dir,
        model_name=args.model_name,
        shared_store=args.store,
    )
    if args.trace:
        # resolved after construction: Fleet picks a tmp fleet_dir when none
        # was given, and the traces ride inside it either way
        fleet.trace_dir = fleet.fleet_dir / 'traces'
        fleet.trace_dir.mkdir(parents=True, exist_ok=True)
    fleet.start()
    try:
        live = fleet.wait_ready(timeout_s=120.0)
    except TimeoutError as e:
        log.warning(json.dumps({'error': str(e), 'exit': 1}))
        fleet.stop()
        return 1
    router = Router(registry_dir=fleet.registry_dir, hedge_ms=args.hedge_ms, max_attempts=args.max_attempts)
    router.refresh()
    server = RouterServer(router, port=args.router_port, host=args.router_host)
    ready = {
        'routing': server.url,
        'replicas': [{'replica_id': d['replica_id'], 'url': d['url']} for d in live],
        'fleet_dir': str(fleet.fleet_dir),
        'endpoints': ['/v1/infer', '/v1/solve', '/v1/replicas', '/metrics', '/healthz', '/statusz'],
    }
    log.info(json.dumps(ready))
    sys.stdout.flush()

    stop = threading.Event()

    def _graceful(signum, frame):
        stop.set()

    prev_term = signal.signal(signal.SIGTERM, _graceful)
    prev_int = signal.signal(signal.SIGINT, _graceful)
    import time

    deadline = time.monotonic() + args.duration if args.duration > 0 else None
    try:
        while not stop.is_set() and (deadline is None or time.monotonic() < deadline):
            stop.wait(0.2)
    finally:
        signal.signal(signal.SIGTERM, prev_term)
        signal.signal(signal.SIGINT, prev_int)
        server.close()
        fleet.stop()
        log.info(json.dumps({'stopped': True, 'exit': 0}))
    return 0
