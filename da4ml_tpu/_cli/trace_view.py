"""``da4ml-tpu trace-view`` — merge per-process traces into one timeline.

Feeds N JSONL trace files (one per replica/router process, e.g. a fleet's
``<fleet_dir>/traces/`` directory) through the collector
(:mod:`..telemetry.obs.collect`): per-process clock-offset alignment from
each sink's clock anchor, one Chrome/Perfetto document with ``process_name``
metadata per source process, and a per-trace-id index so a fleet-wide
request — router legs, replica serve spans, store-tier solves — reads as
one waterfall (docs/observability.md#fleet-tracing)::

    da4ml-tpu trace-view fleet/traces/ --out merged.json
    da4ml-tpu trace-view r0-0.jsonl r1-0.jsonl router.jsonl --min-processes 3

``--min-processes N`` turns the view into a gate: exit 1 unless at least
one trace id carries spans from >= N distinct processes (the CI
``fleet-trace`` smoke job's assertion).
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path


def add_trace_view_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument('traces', nargs='+', type=Path, help='JSONL trace files, or directories of *.jsonl')
    parser.add_argument('--out', type=Path, default=Path('merged.json'), help='Merged Perfetto timeline path')
    parser.add_argument('--no-align', action='store_true', help='Skip per-process clock-offset alignment')
    parser.add_argument(
        '--min-processes',
        type=int,
        default=0,
        help='Exit 1 unless some trace id spans >= N distinct processes (0 = no gate)',
    )
    parser.add_argument('--json', action='store_true', dest='as_json', help='Print the full merge summary as JSON')


def trace_view_main(args: argparse.Namespace) -> int:
    from ..telemetry import get_logger
    from ..telemetry.obs.collect import merge_traces, write_merged

    log = get_logger('cli.trace_view')
    paths: list[Path] = []
    for p in args.traces:
        if p.is_dir():
            paths.extend(sorted(p.glob('*.jsonl')))
        elif p.exists():
            paths.append(p)
        else:
            log.warning(f'no such trace: {p}')
            return 2
    if not paths:
        log.warning('no .jsonl trace files found')
        return 2
    report = merge_traces(paths, align=not args.no_align)
    write_merged(report, args.out)
    summary = {
        'out': str(args.out),
        'n_files': len(paths),
        'n_events': report['n_events'],
        'n_traces': len(report['traces']),
        'n_traces_multiprocess': sum(1 for t in report['traces'].values() if len(t['pids']) >= 2),
        'max_processes_per_trace': report['max_processes_per_trace'],
    }
    if args.as_json:
        summary['sources'] = report['sources']
        summary['traces'] = report['traces']
    log.info(json.dumps(summary, indent=1, default=str))
    if args.min_processes and report['max_processes_per_trace'] < args.min_processes:
        log.warning(f'gate failed: no trace spans >= {args.min_processes} distinct processes')
        return 1
    return 0
