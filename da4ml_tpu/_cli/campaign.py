"""``da4ml-tpu campaign`` — fault-tolerant multi-process solve campaigns.

Front end of :mod:`da4ml_tpu.parallel.campaign` (docs/distributed.md).
Three shapes:

- ``da4ml-tpu campaign corpus.npz --workers 3 --dir /shared/run1 --resume``
  — solve a kernel corpus with N local worker processes over a
  shared-filesystem work queue; a killed worker's kernels are stolen by
  survivors, and re-running the same command resumes the directory.
- ``da4ml-tpu campaign --status /shared/run1`` — live progress/liveness
  view of a campaign directory from any process.
- ``da4ml-tpu campaign --chaos`` — the deterministic kill-a-worker drill
  (CI job ``campaign-chaos``): SIGKILL a fault-parked worker mid-solve and
  assert survivors finish the corpus byte-identical to the single-process
  reference. Exit 0 iff every check passes.

Corpus formats for ``<kernels>``: ``.npz`` (one kernel per array),
``.npy`` (one 2-D kernel, or a 3-D stack), ``.json`` (list of matrices),
a directory of those, or the synthetic specs ``quality:N`` (the bench
``quality_1000`` distribution, seed 1000) and ``drill:N`` (the chaos-drill
corpus).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def add_campaign_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        'kernels',
        nargs='?',
        default=None,
        help='Corpus: .npz/.npy/.json file, directory of those, or quality:N / drill:N synthetic spec',
    )
    parser.add_argument('--workers', '-w', type=int, default=3, help='Local worker processes (1 = in-process)')
    parser.add_argument(
        '--dir',
        dest='campaign_dir',
        default=None,
        help='Campaign directory (shared filesystem for multi-host; default: a fresh temp dir)',
    )
    parser.add_argument('--resume', action='store_true', help='Continue a campaign directory with prior results')
    parser.add_argument('--backend', default='auto', help='Solver backend (auto/jax/native-threads/pure-python)')
    parser.add_argument('--ttl', type=float, default=30.0, help='Lease TTL seconds (steal latency ~ ttl + grace)')
    parser.add_argument('--poll', type=float, default=0.5, help='Idle worker poll interval seconds')
    parser.add_argument('--deadline', type=float, default=None, help='Per-solve wall-clock deadline seconds')
    parser.add_argument('--timeout', type=float, default=3600.0, help='Whole-campaign timeout seconds')
    parser.add_argument('--trace', action='store_true', help='Per-worker JSONL traces under <dir>/traces/')
    parser.add_argument('--out', type=Path, default=None, help='Write the campaign report JSON to a file')
    parser.add_argument('--json', action='store_true', help='Print the full report as JSON (default: summary line)')
    parser.add_argument('--status', metavar='DIR', default=None, help='Print live status of a campaign directory')
    parser.add_argument(
        '--store', metavar='DIR', default=None, help='Publish results into this solution store (docs/store.md)'
    )
    parser.add_argument('--chaos', action='store_true', help='Run the SIGKILL chaos drill instead of a campaign')
    parser.add_argument('--seed', type=int, default=1000, help='Seed for synthetic quality:N corpora')


def load_corpus(spec: str, seed: int = 1000) -> list:
    """Resolve a corpus spec (file / directory / synthetic) to kernel arrays."""
    import numpy as np

    if spec.startswith('quality:'):
        n = int(spec.split(':', 1)[1])
        # the exact quality_1000 sampling order (bench.py / tests_tpu)
        rng = np.random.default_rng(seed)
        kernels = []
        for _ in range(n):
            d1, d2 = int(rng.integers(2, 33)), int(rng.integers(2, 33))
            bits = int(rng.integers(1, 9))
            mag = rng.integers(0, 2**bits, (d1, d2)).astype(np.float64)
            kernels.append(mag * rng.choice([-1.0, 1.0], (d1, d2)))
        return kernels
    if spec.startswith('drill:'):
        from ..parallel.campaign import _drill_corpus

        return _drill_corpus(n=int(spec.split(':', 1)[1]))

    path = Path(spec)
    if path.is_dir():
        out = []
        for p in sorted(path.iterdir()):
            if p.suffix in ('.npy', '.npz', '.json'):
                out.extend(load_corpus(str(p), seed=seed))
        if not out:
            raise ValueError(f'no .npy/.npz/.json kernels under {path}')
        return out
    if path.suffix == '.npz':
        with np.load(path) as z:
            return [np.asarray(z[name], dtype=np.float64) for name in z.files]
    if path.suffix == '.npy':
        arr = np.asarray(np.load(path), dtype=np.float64)
        if arr.ndim == 2:
            return [arr]
        if arr.ndim == 3:
            return [a for a in arr]
        raise ValueError(f'{path}: expected a 2-D kernel or 3-D stack, got shape {arr.shape}')
    if path.suffix == '.json':
        doc = json.loads(path.read_text())
        if isinstance(doc, dict):  # a single saved {'kernel': ...} doc
            doc = [doc]
        return [np.asarray(k.get('kernel', k) if isinstance(k, dict) else k, dtype=np.float64) for k in doc]
    raise ValueError(f'unrecognized corpus spec {spec!r} (file not found or unknown suffix)')


def campaign_main(args: argparse.Namespace) -> int:
    from ..parallel import campaign as C
    from ..telemetry import get_logger

    log = get_logger('cli.campaign')

    if args.status is not None:
        print(json.dumps(C.campaign_status(args.status), indent=2))
        return 0

    if args.chaos:
        kernels = load_corpus(args.kernels, seed=args.seed) if args.kernels else None
        rep = C.chaos_drill(
            kernels,
            workers=max(2, args.workers),
            base_dir=args.campaign_dir,
            backend=args.backend if args.backend != 'auto' else 'pure-python',
            timeout_s=args.timeout,
            trace=args.trace,
        )
        if args.out is not None:
            args.out.write_text(json.dumps(rep, indent=2, default=str))
        print(json.dumps(rep if args.json else {'ok': rep['ok'], **rep['checks']}, indent=2, default=str))
        return 0 if rep['ok'] else 1

    if args.kernels is None:
        log.warning('no corpus given: pass <kernels>, --status DIR, or --chaos')
        return 2
    try:
        kernels = load_corpus(args.kernels, seed=args.seed)
    except (OSError, ValueError) as exc:
        log.warning(f'cannot load corpus {args.kernels!r}: {exc}')
        return 2
    try:
        results, report = C.run_campaign(
            kernels,
            workers=args.workers,
            campaign_dir=args.campaign_dir,
            backend=args.backend,
            resume=args.resume or args.campaign_dir is None,
            ttl_s=args.ttl,
            poll_s=args.poll,
            deadline_per_solve=args.deadline,
            timeout_s=args.timeout,
            trace=args.trace,
            store=args.store,
        )
    except C.CampaignError as exc:
        log.warning(f'campaign failed: {exc}')
        return 1
    if args.out is not None:
        args.out.write_text(json.dumps(report, indent=2, default=str))
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(
            json.dumps(
                {
                    'dir': report['dir'],
                    'n_kernels': report['n_kernels'],
                    'workers': report['workers'],
                    'kernels_stolen': report['kernels_stolen'],
                    'wall_s': report['wall_s'],
                    'total_cost': sum(c for c in report['costs'] if c is not None),
                }
            )
        )
    return 0


if __name__ == '__main__':  # pragma: no cover - convenience entry
    ap = argparse.ArgumentParser(prog='da4ml-tpu campaign')
    add_campaign_args(ap)
    sys.exit(campaign_main(ap.parse_args()))
