"""``da4ml-tpu export`` — write a self-contained serving artifact.

Fuses a saved model's stages into ONE level-packed DAIS program
(docs/runtime.md#ir-fusion) and writes the artifact directory the serve
plane hot-loads without retracing: fused DAIS JSON, a best-effort
``jax.export`` StableHLO serialization of the whole computation, and a
digest-carrying ``meta.json`` that ``ServeEngine.reload()`` verifies before
swapping executors (docs/serving.md#export-artifacts).
"""

from __future__ import annotations


def add_export_args(parser) -> None:
    parser.add_argument('model', help='Saved CombLogic/Pipeline .json (or an existing artifact dir to re-fuse)')
    parser.add_argument('outdir', help='Artifact directory to write (created if missing)')
    parser.add_argument('--name', default='model', help='Model name recorded in meta.json (default: model)')
    parser.add_argument(
        '--no-stablehlo',
        dest='stablehlo',
        action='store_false',
        default=True,
        help='Skip the jax.export StableHLO serialization (fused DAIS JSON only)',
    )
    parser.add_argument(
        '--check',
        action='store_true',
        help='After writing, reload the artifact and run a zero batch through it (round-trip self-check)',
    )
    parser.add_argument('--verbose', '-v', action='store_true')


def export_main(args) -> int:
    from ..serve.export import export_model, load_artifact

    meta = export_model(args.model, args.outdir, name=args.name, stablehlo=args.stablehlo)
    print(
        f'export: {args.outdir} <- {args.model} '
        f'({meta["source_stages"]} stage(s) -> {meta["fused_ops"]} fused ops, '
        f'{meta["n_in"]}->{meta["n_out"]}, digest {meta["digest"][:12]}...)'
    )
    if args.verbose and meta.get('stablehlo') is None:
        print(f'  stablehlo: skipped ({meta.get("stablehlo_error")})')
    if args.check:
        import numpy as np

        from ..ir.dais_binary import decode
        from ..runtime.jax_backend import DaisExecutor

        binary, meta2 = load_artifact(args.outdir)
        ex = DaisExecutor(decode(binary))
        ex(np.zeros((4, max(meta2['n_in'], 1)), dtype=np.float64))
        print(f'  check: artifact reloads clean ({meta2["fused_ops"]} ops, digest verified)')
    return 0
