"""``da4ml-tpu report`` — summarize vendor synthesis results.

Parses Vivado (timing summary / utilization / power), Quartus (sta / fit) and
Vitis HLS (csynth.xml) reports found in project directories, merges them with
the project's ``metadata.json``, derives Fmax / latency(ns), and renders a
table (stdout / json / csv / tsv / md / html). Parity: reference
src/da4ml/_cli/report.py:20-238 (same vendor file formats, fresh parsers).
"""

from __future__ import annotations

import argparse
import json
import os
import re
from pathlib import Path
from typing import Any

# --------------------------------------------------------------- Vivado


def parse_timing_summary_vivado(text: str) -> dict[str, Any]:
    """Parse the 'Design Timing Summary' block of report_timing_summary.

    The block is a two-row table: a header line of column names separated by
    2+ spaces, a dashed underline, then the value row.
    """
    loc = text.find('Design Timing Summary')
    if loc < 0:
        return {}
    lines = [ln for ln in text[loc:].split('\n')[3:10] if ln.strip()]
    if len(lines) < 3 or set(lines[1].strip()) != {'-'} and set(lines[1]) != {' ', '-'}:
        return {}
    keys = [k.strip() for k in re.split(r'\s{2,}', lines[0].strip()) if k]
    vals_s = [v for v in re.split(r'\s{2,}', lines[2].strip()) if v]
    out: dict[str, Any] = {}
    for k, v in zip(keys, vals_s):
        try:
            out[k] = int(v) if re.fullmatch(r'-?\d+', v) else float(v)
        except ValueError:
            out[k] = v
    return out


_VIVADO_UTIL_ROWS = [
    'DSPs',
    'LUT as Logic',
    'LUT as Memory',
    'CLB Registers',
    'CARRY8',
    'Register as Latch',
    'Register as Flip Flop',
    'RAMB18',
    'URAM',
    'Block RAM Tile',
]


def parse_utilization_vivado(text: str) -> dict[str, Any]:
    """Parse report_utilization table rows: | name | used | fixed | prohibited | available | % |."""
    out: dict[str, Any] = {}
    for name in _VIVADO_UTIL_ROWS:
        m = re.search(
            rf'\|\s*{re.escape(name)}\s*\|\s*(\d+)\s*\|\s*(\d+)\s*\|\s*(\d+)\s*\|\s*(\d+)\s*\|',
            text,
        )
        if not m:
            continue
        used, fixed, prohibited, available = map(int, m.groups())
        out[name] = used
        out[f'{name}_available'] = available
    if 'Register as Flip Flop' in out:
        out['FF'] = out['Register as Flip Flop'] + out.get('Register as Latch', 0)
        out['FF_available'] = out['Register as Flip Flop_available']
    if 'LUT as Logic' in out:
        out['LUT'] = out['LUT as Logic'] + out.get('LUT as Memory', 0)
        out['LUT_available'] = out['LUT as Logic_available']
    if 'DSPs' in out:
        out['DSP'] = out['DSPs']
    return out


def parse_power_vivado(text: str) -> dict[str, Any]:
    out: dict[str, Any] = {}
    for name in ('Total On-Chip Power (W)', 'Dynamic (W)', 'Device Static (W)'):
        m = re.search(rf'\|\s*{re.escape(name)}\s*\|\s*([^\|]+?)\s*\|', text)
        if m:
            out[name] = m.group(1).strip()
    return out


# -------------------------------------------------------------- Quartus


def parse_timing_quartus(text: str) -> dict[str, Any]:
    out: dict[str, Any] = {}
    m = re.search(r';\s*([\d.]+)\s*MHz\s*;\s*([\d.]+)\s*MHz\s*;', text)
    if m:
        out['Fmax(MHz)'] = float(m.group(1))
        out['Restricted Fmax(MHz)'] = float(m.group(2))
    for section, prefix in (('Setup Summary', 'Setup'), ('Hold Summary', 'Hold')):
        loc = text.find(f'; {section}')
        if loc < 0:
            continue
        # First data row in the section window: clock name followed by numeric
        # slack / TNS / failing-endpoint fields (the header row is non-numeric).
        row = re.search(r';\s*[^;+\n]+?\s*;\s*(-?[\d.]+)\s*;\s*(-?[\d.]+)\s*;\s*(\d+)\s*;', text[loc : loc + 4000])
        if row:
            out[f'{prefix} Slack'] = float(row.group(1))
            out[f'{prefix} TNS'] = float(row.group(2))
            out[f'{prefix} Failing Endpoints'] = int(row.group(3))
    return out


def parse_utilization_quartus(text: str) -> dict[str, Any]:
    out: dict[str, Any] = {}

    def _int(s: str) -> int:
        return int(s.replace(',', ''))

    patterns = [
        (r';\s*Logic utilization \(in ALMs\)\s*;\s*([\d,]+)\s*/\s*([\d,]+)', 'ALM', True),
        (r';\s*Total dedicated logic registers\s*;\s*([\d,]+)', 'Registers', False),
        (r';\s*Total block memory bits\s*;\s*([\d,]+)\s*/\s*([\d,]+)', 'Block Memory Bits', True),
        (r';\s*Total RAM Blocks\s*;\s*([\d,]+)\s*/\s*([\d,]+)', 'RAM Blocks', True),
        (r';\s*Total DSP Blocks\s*;\s*([\d,]+)\s*/\s*([\d,]+)', 'DSP', True),
        (r';\s*Combinational ALUT usage for logic\s*;\s*([\d,]+)', 'LUT', False),
        (r';\s*Dedicated logic registers\s*;\s*([\d,]+)', 'FF', False),
    ]
    for pattern, name, has_avail in patterns:
        m = re.search(pattern, text)
        if not m:
            continue
        out[name] = _int(m.group(1))
        if has_avail:
            out[f'{name}_available'] = _int(m.group(2))
    return out


# ---------------------------------------------------------------- Vitis


def parse_vitis_latency(xml_text: str) -> int | None:
    lats = re.findall(r'<(?:Best|Average|Worst)-caseLatency>(\d+)</(?:Best|Average|Worst)-caseLatency>', xml_text)
    if not lats:
        return None
    vals = sorted({int(v) for v in lats})
    return vals[-1]  # worst case if they differ


# ------------------------------------------------------------- assembly


def _first_existing(*paths: Path) -> Path | None:
    for p in paths:
        if p.exists():
            return p
    return None


def load_project(path: str | Path) -> dict[str, Any]:
    """Merge metadata.json with any vendor reports found in a project dir."""
    path = Path(path)
    meta_path = path / 'metadata.json'
    if not meta_path.exists():
        raise FileNotFoundError(f'{meta_path} not found — not a da4ml-tpu project directory')
    d: dict[str, Any] = json.loads(meta_path.read_text())
    lat = d.get('latency_ticks', d.get('latency'))
    if isinstance(lat, list):
        lat = lat[-1]

    name = d.get('name', 'model')
    rdirs = [path, path / 'reports', path / f'build_{name}' / 'reports']

    # Vivado
    f = _first_existing(*(r / n for r in rdirs for n in ('timing_summary.rpt', f'{name}_post_route_timing.rpt')))
    if f is not None:
        timing = parse_timing_summary_vivado(f.read_text())
        d.update(timing)
        if 'WNS(ns)' in timing and 'clock_period' in d:
            d['actual_period'] = d['clock_period'] - timing['WNS(ns)']
            d['Fmax(MHz)'] = 1000.0 / d['actual_period']
            if lat is not None:
                d['latency(ns)'] = lat * d['actual_period']
    f = _first_existing(*(r / n for r in rdirs for n in ('utilization.rpt', f'{name}_post_route_util.rpt')))
    if f is not None:
        d.update(parse_utilization_vivado(f.read_text()))
    f = _first_existing(*(r / n for r in rdirs for n in ('power.rpt', f'{name}_post_route_power.rpt')))
    if f is not None:
        d.update(parse_power_vivado(f.read_text()))

    # Quartus
    f = _first_existing(*(r / f'{name}.sta.rpt' for r in rdirs))
    if f is not None:
        timing = parse_timing_quartus(f.read_text())
        d.update(timing)
        if 'Fmax(MHz)' in timing:
            d['actual_period'] = 1000.0 / timing['Fmax(MHz)']
            if lat is not None:
                d['latency(ns)'] = lat * d['actual_period']
    f = _first_existing(*(r / f'{name}.fit.rpt' for r in rdirs))
    if f is not None:
        d.update(parse_utilization_quartus(f.read_text()))

    # Vitis
    f = _first_existing(*(r / 'csynth.xml' for r in rdirs), path / 'syn' / 'report' / 'csynth.xml')
    if f is not None:
        v = parse_vitis_latency(f.read_text())
        if v is not None:
            d['latency'] = v

    return d


def extra_info_from_fname(fname: str) -> dict[str, Any]:
    """Extract k=v pairs from '-'-separated directory names."""
    out: dict[str, Any] = {}
    for part in fname.split('-'):
        if '=' not in part:
            continue
        k, v = part.split('=', 1)
        for cast in (int, float, str):
            try:
                out[k] = cast(v)
                break
            except ValueError:
                continue
    return out


# ------------------------------------------------------------- rendering


def _table(vals: list[dict[str, Any]]) -> list[list]:
    attrs: set[str] = set()
    for v in vals:
        attrs.update(v)
    cols = sorted(attrs)
    return [cols] + [[v.get(a, '') for a in cols] for v in vals]


def _fmt_cell(v: Any) -> str:
    if isinstance(v, float):
        return f'{v:.4g}'
    return str(v)


def render_stdout(arr: list[list], full: bool, columns: list[str] | None) -> str:
    default_columns = [
        'name', 'flavor', 'clock_period', 'actual_period', 'cost', 'latency',
        'latency_ticks', 'DSP', 'LUT', 'FF', 'Fmax(MHz)', 'latency(ns)',
    ]  # fmt: skip
    cols = columns if columns is not None else default_columns
    if not full:
        header = arr[0]
        keep = [header.index(c) for c in cols if c in header]
        arr = [[row[i] for i in keep] for row in arr]

    if len(arr) == 2:  # single project: key/value listing
        kw = max((len(str(k)) for k in arr[0]), default=0)
        return '\n'.join(f'{str(k).ljust(kw)} : {_fmt_cell(v)}' for k, v in zip(arr[0], arr[1]))

    widths = [max(len(_fmt_cell(arr[r][c])) for r in range(len(arr))) for c in range(len(arr[0]))]
    try:
        tw = os.get_terminal_size().columns if os.isatty(1) else 1 << 16
    except OSError:
        tw = 1 << 16
    if sum(widths) + 3 * len(widths) + 1 > tw:
        widths = [min(w, max(8, (tw - 3 * len(widths) - 1) // len(widths))) for w in widths]
    lines = [
        '| ' + ' | '.join(_fmt_cell(v).ljust(w)[:w] for v, w in zip(arr[0], widths)) + ' |',
        '|-' + '-|-'.join('-' * w for w in widths) + '-|',
    ]
    for row in arr[1:]:
        lines.append('| ' + ' | '.join(_fmt_cell(v).ljust(w)[:w] for v, w in zip(row, widths)) + ' |')
    return '\n'.join(lines)


def write_output(vals: list[dict[str, Any]], arr: list[list], output: str):
    ext = Path(output).suffix
    with open(output, 'w') as f:
        if ext == '.json':
            json.dump(vals, f, indent=2)
        elif ext in ('.csv', '.tsv'):
            import csv

            writer = csv.writer(f, delimiter=',' if ext == '.csv' else '\t')
            writer.writerows(arr)
        elif ext == '.md':
            f.write('| ' + ' | '.join(map(str, arr[0])) + ' |\n')
            f.write('|' + '|'.join(['---'] * len(arr[0])) + '|\n')
            for row in arr[1:]:
                f.write('| ' + ' | '.join(map(str, row)) + ' |\n')
        elif ext == '.html':
            f.write('<table>\n')
            f.write('  <tr>' + ''.join(f'<th>{a}</th>' for a in arr[0]) + '</tr>\n')
            for row in arr[1:]:
                f.write('  <tr>' + ''.join(f'<td>{a}</td>' for a in row) + '</tr>\n')
            f.write('</table>\n')
        else:
            raise ValueError(f'Unsupported output format: {ext}')


def report_main(args: argparse.Namespace) -> int:
    from ..telemetry import get_logger

    logger = get_logger('cli.report')
    vals: list[dict[str, Any]] = []
    for p in args.paths:
        try:
            d = load_project(p)
        except Exception as e:
            logger.warning(f'skipping {p}: {e}')
            continue
        for k, v in extra_info_from_fname(Path(p).name).items():
            d.setdefault(k, v)
        vals.append(d)
    if not vals:
        logger.warning('No readable projects.')
        return 1

    key = args.sort_by
    # values for a key may differ in type across projects (e.g. filename tags
    # parsed as int for one dir, left as str for another) — sort numerics
    # first, then everything else by string form, so mixed types never raise
    def _sort_key(d: dict):
        v = d.get(key)
        if v is None:
            return (2, 0.0, '')
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return (0, float(v), '')
        return (1, 0.0, str(v))

    vals.sort(key=_sort_key)
    arr = _table(vals)

    if args.output == 'stdout':
        print(render_stdout(arr, args.full, args.columns))
    else:
        write_output(vals, arr, args.output)
    return 0


def add_report_args(parser: argparse.ArgumentParser):
    parser.add_argument('paths', type=str, nargs='+', help='Project directories containing metadata.json + vendor reports')
    parser.add_argument('--output', '-o', type=str, default='stdout', help='stdout or a .json/.csv/.tsv/.md/.html file')
    parser.add_argument('--sort-by', '-s', type=str, default='cost', help='Attribute to sort by')
    parser.add_argument('--full', '-f', action='store_true', help='Show all columns on stdout')
    parser.add_argument('--columns', '-c', type=str, nargs='+', default=None, help='Columns to show on stdout')
