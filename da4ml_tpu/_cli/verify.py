"""``da4ml-tpu verify`` — static analysis of saved DAIS programs.

Runs the verifier passes (docs/analysis.md) over one or more saved programs:
a ``CombLogic``/``Pipeline`` ``.json`` file, or a generated project directory
(the embedded ``model/comb.json`` / ``model/pipeline.json`` is used). Exits
non-zero when any program has errors (or warnings, with ``--strict``), so it
slots directly into CI::

    da4ml-tpu verify examples/kernels/*.json
    da4ml-tpu verify build/my_project --json
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path


def add_verify_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument('paths', nargs='+', type=Path, help='saved program .json files or project directories')
    parser.add_argument('--json', action='store_true', dest='as_json', help='emit machine-readable JSON diagnostics')
    parser.add_argument('--strict', action='store_true', help='exit non-zero on warnings as well as errors')
    parser.add_argument('--no-warnings', action='store_true', help='hide warnings from the text output')
    parser.add_argument(
        '--passes',
        default=None,
        help='comma-separated pass subset to run (default: all); available: wellformed,qinterval,deadcode',
    )


def _resolve_program_file(path: Path) -> Path:
    if path.is_dir():
        for candidate in (path / 'model' / 'pipeline.json', path / 'model' / 'comb.json'):
            if candidate.is_file():
                return candidate
        raise FileNotFoundError(f'{path} contains no model/pipeline.json or model/comb.json')
    return path


def _load_program(path: Path):
    """Load without the on-load verification — the point is to report
    structured diagnostics, not to crash in ``from_dict``."""
    from ..ir import CombLogic, Pipeline

    blob = json.loads(path.read_text())
    if isinstance(blob, dict) and 'stages' in blob:
        return Pipeline.from_dict(blob, verify=False)
    return CombLogic.from_dict(blob, verify=False)


def _schedule_stats(program) -> list[dict]:
    """ASAP level-schedule stats per stage (ir.schedule): depth is the
    dependency critical path in ops; mean level width is how many ops are
    executable together — the parallelism the level-packed runtime exploits."""
    from ..ir.schedule import levelize_comb

    stages = program.stages if hasattr(program, 'stages') else [program]
    per = []
    for st in stages:
        s = levelize_comb(st)
        per.append({'n_ops': len(st.ops), 'depth': s.depth, 'width_max': s.width_max, 'width_mean': round(s.width_mean, 1)})
    return per


def verify_main(args: argparse.Namespace) -> int:
    from ..analysis import verify

    passes = None
    if args.passes:
        passes = tuple(p.strip() for p in args.passes.split(',') if p.strip())

    results = []
    rc = 0
    for raw_path in args.paths:
        try:
            path = _resolve_program_file(raw_path)
            program = _load_program(path)
        except Exception as e:  # unreadable/corrupt beyond parsing
            results.append({'target': str(raw_path), 'ok': False, 'load_error': f'{type(e).__name__}: {e}'})
            rc = max(rc, 2)
            if not args.as_json:
                print(f'{raw_path}: LOAD FAILED ({type(e).__name__}: {e})')
            continue

        result = verify(program, passes=passes, target=str(raw_path))
        entry = result.to_dict()
        try:
            entry['schedule'] = _schedule_stats(program)
        except Exception:  # stats are informational; never fail the verify
            pass
        results.append(entry)
        if not result.ok or (args.strict and result.warnings):
            rc = max(rc, 1)
        if not args.as_json:
            print(result.format_text(show_warnings=not args.no_warnings))
            for i, s in enumerate(entry.get('schedule', [])):
                print(f'  stage {i}: {s["n_ops"]} ops, schedule depth {s["depth"]}, mean level width {s["width_mean"]}')

    if args.as_json:
        print(json.dumps(results if len(results) > 1 else results[0], indent=2))
    return rc
