"""``da4ml-tpu verify`` — static analysis of saved DAIS programs.

Runs the verifier passes (docs/analysis.md) over one or more saved programs:
a ``CombLogic``/``Pipeline`` ``.json`` file, or a generated project directory
(the embedded ``model/comb.json`` / ``model/pipeline.json`` is used). Exits
non-zero when any program has errors (or warnings, with ``--strict``), so it
slots directly into CI::

    da4ml-tpu verify examples/kernels/*.json
    da4ml-tpu verify build/my_project --json
    da4ml-tpu verify prog.json --conformance     # + differential backends
    da4ml-tpu verify --fuzz 12 --out report.json # corpus conformance +
                                                 # transfer-soundness sweep
    da4ml-tpu verify --concurrency               # lock/thread lint + catalog
                                                 # drift gates + locktrace

``--conformance`` adds the opt-in cross-backend conformance pass per
program; ``--fuzz N`` needs no paths — it sweeps N randomized ``ir.synth``
programs through every runtime mode against the table-generated reference
interpreter and fuzz-proves the per-opcode interval transfers
(docs/analysis.md#conformance).
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path


def add_verify_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument('paths', nargs='*', type=Path, help='saved program .json files or project directories')
    parser.add_argument('--json', action='store_true', dest='as_json', help='emit machine-readable JSON diagnostics')
    parser.add_argument('--strict', action='store_true', help='exit non-zero on warnings as well as errors')
    parser.add_argument('--no-warnings', action='store_true', help='hide warnings from the text output')
    parser.add_argument(
        '--passes',
        default=None,
        help='comma-separated pass subset to run (default: all non-opt-in); '
        'available: wellformed,qinterval,deadcode,conformance',
    )
    parser.add_argument(
        '--conformance',
        action='store_true',
        help='also run the cross-backend conformance pass per program (differential execution '
        'of numpy/unroll/scan/level vs the table-generated reference interpreter)',
    )
    parser.add_argument(
        '--fuzz',
        type=int,
        default=0,
        metavar='N',
        help='no paths needed: run the N-program ir.synth differential conformance corpus plus '
        'the per-opcode transfer-soundness fuzz, and exit non-zero on any finding',
    )
    parser.add_argument(
        '--concurrency',
        action='store_true',
        help='no paths needed: run the concurrency soundness plane — the static lock/thread '
        'lint (X501-X507), the knob/metric catalog drift gates (X520-X525), and the runtime '
        'lock-order report when DA4ML_LOCKTRACE is armed (X510/X511)',
    )
    parser.add_argument('--seed', type=int, default=0, help='base seed for --fuzz / --conformance inputs')
    parser.add_argument('--samples', type=int, default=64, help='input samples per program for conformance runs')
    parser.add_argument(
        '--modes', default=None, help='comma-separated backend modes for conformance (default: numpy,unroll,scan,level,pallas)'
    )
    parser.add_argument('--out', type=Path, default=None, help='write the --fuzz JSON report to this path')


def _resolve_program_file(path: Path) -> Path:
    if path.is_dir():
        for candidate in (path / 'model' / 'pipeline.json', path / 'model' / 'comb.json'):
            if candidate.is_file():
                return candidate
        raise FileNotFoundError(f'{path} contains no model/pipeline.json or model/comb.json')
    return path


def _load_program(path: Path):
    """Load without the on-load verification — the point is to report
    structured diagnostics, not to crash in ``from_dict``."""
    from ..ir import CombLogic, Pipeline

    blob = json.loads(path.read_text())
    if isinstance(blob, dict) and 'stages' in blob:
        return Pipeline.from_dict(blob, verify=False)
    return CombLogic.from_dict(blob, verify=False)


def _schedule_stats(program) -> list[dict]:
    """ASAP level-schedule stats per stage (ir.schedule): depth is the
    dependency critical path in ops; mean level width is how many ops are
    executable together — the parallelism the level-packed runtime exploits."""
    from ..ir.schedule import levelize_comb

    stages = program.stages if hasattr(program, 'stages') else [program]
    per = []
    for st in stages:
        s = levelize_comb(st)
        per.append(
            {
                'n_ops': len(st.ops),
                'depth': s.depth,
                'width_max': s.width_max,
                'width_mean': round(s.width_mean, 1),
                'peak_live': s.peak_live,
            }
        )
    return per


def _fused_stats(program) -> dict | None:
    """Level-schedule stats of the IR-fused whole-model program
    (docs/runtime.md#ir-fusion), for multi-stage Pipelines: what the
    ``run_pipeline(fused='ir')`` runtime actually executes."""
    if len(getattr(program, 'stages', ())) < 2:
        return None
    from ..ir.fuse import fuse_pipeline
    from ..ir.schedule import levelize_comb

    fused, rep = fuse_pipeline(program, report=True)
    s = levelize_comb(fused)
    return {
        'n_ops': len(fused.ops),
        'seam_ops': rep.seam_ops,
        'depth': s.depth,
        'depth_chained': rep.depth_before,
        'width_max': s.width_max,
        'width_mean': round(s.width_mean, 1),
        'peak_live': s.peak_live,
    }


def _fuzz_main(args: argparse.Namespace) -> int:
    """Corpus mode: differential conformance + transfer-soundness fuzz."""
    from ..analysis.conformance import CONFORMANCE_MODES, run_conformance_corpus
    from ..analysis.soundness import check_transfer_soundness

    modes = tuple(m.strip() for m in args.modes.split(',') if m.strip()) if args.modes else CONFORMANCE_MODES
    conf_report, conf_diags = run_conformance_corpus(
        n_programs=args.fuzz, n_samples=args.samples, seed=args.seed, modes=modes
    )
    sound_report, sound_diags = check_transfer_soundness(seed=args.seed)
    report = {
        'ok': conf_report['ok'] and sound_report['ok'],
        'conformance': conf_report,
        'transfer_soundness': sound_report,
    }
    if args.out:
        args.out.write_text(json.dumps(report, indent=2))
    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        print(f'conformance: {args.fuzz} programs x {len(modes)} modes ({",".join(modes)}), {args.samples} samples each')
        for oc, info in conf_report['per_opcode'].items():
            print(f'  opcode {oc:>3} [{info["family"]}]: {info["ops"]} ops, {info["mismatches"]} mismatches')
        for d in conf_diags:
            print(f'  {d}')
        print('transfer-soundness:')
        for key, info in sound_report['per_family'].items():
            print(
                f'  {key} {tuple(info["opcodes"])}: {info["cases"]} cases x {info["samples_per_case"]} samples, '
                f'{info["counterexamples"]} counterexamples'
            )
        for d in sound_diags:
            print(f'  {d}')
        print('opcode conformance: ' + ('ok' if report['ok'] else 'FAILED'))
    return 0 if report['ok'] else 1


def _concurrency_main(args: argparse.Namespace) -> int:
    """The concurrency soundness plane as one CI-gateable verdict: static
    lock/thread lint + catalog drift gates + (when armed) the runtime
    lock-order report."""
    from ..analysis.catalogs import lint_catalogs
    from ..analysis.concurrency import lint_concurrency
    from ..analysis.diagnostics import VerifyResult
    from ..reliability import locktrace

    static = lint_concurrency()
    catalogs = lint_catalogs()
    runtime = VerifyResult(locktrace.locktrace_diagnostics(), target='locktrace')
    combined = VerifyResult(
        static.diagnostics + catalogs.diagnostics + runtime.diagnostics, target='concurrency'
    )
    rc = 0 if combined.ok and not (args.strict and combined.warnings) else 1
    if args.as_json:
        report = combined.to_dict()
        report['locktrace'] = locktrace.locktrace_report()
        if args.out:
            args.out.write_text(json.dumps(report, indent=2))
        print(json.dumps(report, indent=2))
        return rc
    print(combined.format_text(show_warnings=not args.no_warnings))
    trace = locktrace.locktrace_report()
    if trace['enabled']:
        c = trace['counters']
        print(
            f'  locktrace: {c["acquires"]} acquires, {c["edges"]} order edges, '
            f'{c["rank_inversions"]} rank inversions, {c["cycles"]} cycles'
        )
    else:
        print('  locktrace: not armed (set DA4ML_LOCKTRACE=1 to record runtime lock order)')
    if args.out:
        report = combined.to_dict()
        report['locktrace'] = trace
        args.out.write_text(json.dumps(report, indent=2))
    return rc


def verify_main(args: argparse.Namespace) -> int:
    from ..analysis import verify

    if args.concurrency:
        return _concurrency_main(args)
    if args.fuzz:
        return _fuzz_main(args)
    if not args.paths:
        print('verify: provide program paths, or --fuzz N for the corpus sweep')
        return 2

    passes = None
    if args.passes:
        passes = tuple(p.strip() for p in args.passes.split(',') if p.strip())
    if args.conformance:
        from ..analysis import OPT_IN_PASSES, PASSES

        base = passes if passes is not None else tuple(p for p in PASSES if p not in OPT_IN_PASSES)
        passes = tuple(dict.fromkeys(base + ('conformance',)))

    results = []
    rc = 0
    for raw_path in args.paths:
        try:
            path = _resolve_program_file(raw_path)
            program = _load_program(path)
        except Exception as e:  # unreadable/corrupt beyond parsing
            results.append({'target': str(raw_path), 'ok': False, 'load_error': f'{type(e).__name__}: {e}'})
            rc = max(rc, 2)
            if not args.as_json:
                print(f'{raw_path}: LOAD FAILED ({type(e).__name__}: {e})')
            continue

        result = verify(program, passes=passes, target=str(raw_path))
        entry = result.to_dict()
        try:
            entry['schedule'] = _schedule_stats(program)
        except Exception:  # stats are informational; never fail the verify
            pass
        try:
            fused_stats = _fused_stats(program)
            if fused_stats is not None:
                entry['schedule_fused'] = fused_stats
        except Exception:
            fused_stats = None
        if fused_stats is not None:
            # the fused whole-model program must pass the same verifier
            # passes as the staged one (incl. --conformance when requested)
            fres = verify(program.fuse(), passes=passes, target=f'{raw_path}#fused')
            entry['fused'] = fres.to_dict()
            if not fres.ok or (args.strict and fres.warnings):
                rc = max(rc, 1)
        results.append(entry)
        if not result.ok or (args.strict and result.warnings):
            rc = max(rc, 1)
        if not args.as_json:
            print(result.format_text(show_warnings=not args.no_warnings))
            for i, s in enumerate(entry.get('schedule', [])):
                print(
                    f'  stage {i}: {s["n_ops"]} ops, schedule depth {s["depth"]}, '
                    f'mean level width {s["width_mean"]}, peak live window {s["peak_live"]}'
                )
            if fused_stats is not None:
                f = fused_stats
                fd = entry['fused']
                suffix = '' if fd['ok'] else ' [VERIFY FAILED]'
                if fd['ok'] and fd['n_warnings']:
                    suffix = f' [{fd["n_warnings"]} warning(s)]'
                print(
                    f'  fused: {f["n_ops"]} ops ({f["seam_ops"]} seam), schedule depth {f["depth"]} '
                    f'(chained {f["depth_chained"]}), mean level width {f["width_mean"]}' + suffix
                )

    if args.as_json:
        print(json.dumps(results if len(results) > 1 else results[0], indent=2))
    return rc
