"""``da4ml-tpu stats`` — summarize a captured telemetry trace.

Reads a trace produced by ``DA4ML_TRACE=<path>`` / ``--trace <path>``
(either format: Chrome trace-event JSON or JSONL event log) and renders:

- a per-span-name aggregate table (count, total/mean/max wall clock) sorted
  by total time — where the conversion actually went;
- the metrics snapshot embedded in the trace (counters, gauges, histogram
  summaries).

``--json`` emits the same summary as one machine-readable JSON document.
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path


def summarize_events(events: list[dict]) -> dict:
    """Aggregate Chrome trace events: span stats by name + instant counts."""
    spans: dict[str, dict] = {}
    instants: dict[str, int] = {}
    for ev in events:
        ph = ev.get('ph')
        name = ev.get('name', '?')
        if ph == 'X':
            dur_s = float(ev.get('dur', 0.0)) / 1e6
            s = spans.setdefault(name, {'count': 0, 'total_s': 0.0, 'max_s': 0.0})
            s['count'] += 1
            s['total_s'] += dur_s
            if dur_s > s['max_s']:
                s['max_s'] = dur_s
        elif ph == 'i':
            instants[name] = instants.get(name, 0) + 1
    for s in spans.values():
        s['mean_s'] = s['total_s'] / s['count']
    return {'spans': spans, 'instants': instants}


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f'{v:.2f}s'
    if v >= 1e-3:
        return f'{v * 1e3:.1f}ms'
    return f'{v * 1e6:.0f}µs'


def render_summary(summary: dict, metrics: dict, top: int = 0) -> str:
    lines: list[str] = []
    spans = sorted(summary['spans'].items(), key=lambda kv: -kv[1]['total_s'])
    if top:
        spans = spans[:top]
    if spans:
        name_w = max(len('span'), *(len(n) for n, _ in spans))
        lines.append(f'{"span":<{name_w}}  {"count":>6}  {"total":>9}  {"mean":>9}  {"max":>9}')
        lines.append('-' * (name_w + 40))
        for name, s in spans:
            lines.append(
                f'{name:<{name_w}}  {s["count"]:>6}  {_fmt_s(s["total_s"]):>9}  '
                f'{_fmt_s(s["mean_s"]):>9}  {_fmt_s(s["max_s"]):>9}'
            )
    else:
        lines.append('(no spans recorded)')
    if summary['instants']:
        lines.append('')
        lines.append('instant events:')
        for name, n in sorted(summary['instants'].items()):
            lines.append(f'  {name}: {n}')
    if metrics:
        lines.append('')
        lines.append('metrics:')
        for name, m in sorted(metrics.items()):
            kind = m.get('type')
            if kind == 'histogram':
                # the `_s` suffix convention marks seconds-valued histograms
                fmt = _fmt_s if name.endswith('_s') else (lambda v: f'{v:g}')
                if m.get('count'):
                    lines.append(
                        f'  {name}: n={m["count"]} mean={fmt(m["mean"])} min={fmt(m["min"])} max={fmt(m["max"])}'
                    )
                else:
                    lines.append(f'  {name}: n=0')
            else:
                lines.append(f'  {name}: {m.get("value"):g}')
    return '\n'.join(lines)


def _follow(args: argparse.Namespace) -> int:
    """Tail a streaming JSONL trace: incrementally absorb new events and
    re-render the summary every ``--interval`` seconds, so a long campaign
    can be watched live without the HTTP endpoint. Stops after
    ``--max-updates`` renders (0 = until Ctrl-C / EOF of a finished trace)."""
    from ..telemetry import get_logger
    from ..telemetry.obs.tailer import TraceTailer

    path = Path(args.trace)
    if path.suffix != '.jsonl':
        get_logger('cli.stats').warning(f'--follow expects a streaming .jsonl trace, got {path}')
        return 1
    tailer = TraceTailer(path)
    updates = 0
    try:
        while True:
            n_new = tailer.poll()
            if n_new or updates == 0:
                updates += 1
                summary = summarize_events(tailer.events)
                print(f'--- update {updates}: {path} +{n_new} events ({len(tailer.events)} total) ---')
                print(render_summary(summary, tailer.metrics, top=args.top))
            if args.max_updates and updates >= args.max_updates:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def stats_main(args: argparse.Namespace) -> int:
    from ..telemetry import load_trace, validate_trace

    if args.follow:
        return _follow(args)
    path = Path(args.trace)
    if not path.is_file():
        from ..telemetry import get_logger

        get_logger('cli.stats').warning(f'no such trace file: {path}')
        return 1
    events, metrics = load_trace(path)
    if args.validate:
        validate_trace(events)
    summary = summarize_events(events)
    if args.json:
        print(json.dumps({'file': str(path), 'n_events': len(events), **summary, 'metrics': metrics}, indent=2))
    else:
        print(f'{path}: {len(events)} events')
        print(render_summary(summary, metrics, top=args.top))
    return 0


def add_stats_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument('trace', type=Path, help='Trace file captured with --trace / DA4ML_TRACE (.json or .jsonl)')
    parser.add_argument('--json', action='store_true', help='Emit the summary as JSON instead of a table')
    parser.add_argument('--top', type=int, default=0, help='Show only the N span names with the largest total time')
    parser.add_argument(
        '--validate', action='store_true', help='Additionally check every event against the Chrome trace-event schema'
    )
    parser.add_argument(
        '--follow', action='store_true', help='Tail a growing .jsonl trace, re-rendering the summary as events stream in'
    )
    parser.add_argument('--interval', type=float, default=2.0, help='--follow: poll interval in seconds')
    parser.add_argument('--max-updates', type=int, default=0, help='--follow: stop after N renders (0 = until Ctrl-C)')
