"""``da4ml-tpu stats`` — summarize a captured telemetry trace.

Reads a trace produced by ``DA4ML_TRACE=<path>`` / ``--trace <path>``
(either format: Chrome trace-event JSON or JSONL event log) and renders:

- a per-span-name aggregate table (count, total/mean/max wall clock) sorted
  by total time — where the conversion actually went;
- the metrics snapshot embedded in the trace (counters, gauges, histogram
  summaries).

``--json`` emits the same summary as one machine-readable JSON document.
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path


def summarize_events(events: list[dict]) -> dict:
    """Aggregate Chrome trace events: span stats by name + instant counts."""
    spans: dict[str, dict] = {}
    instants: dict[str, int] = {}
    for ev in events:
        ph = ev.get('ph')
        name = ev.get('name', '?')
        if ph == 'X':
            dur_s = float(ev.get('dur', 0.0)) / 1e6
            s = spans.setdefault(name, {'count': 0, 'total_s': 0.0, 'max_s': 0.0})
            s['count'] += 1
            s['total_s'] += dur_s
            if dur_s > s['max_s']:
                s['max_s'] = dur_s
        elif ph == 'i':
            instants[name] = instants.get(name, 0) + 1
    for s in spans.values():
        s['mean_s'] = s['total_s'] / s['count']
    return {'spans': spans, 'instants': instants}


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f'{v:.2f}s'
    if v >= 1e-3:
        return f'{v * 1e3:.1f}ms'
    return f'{v * 1e6:.0f}µs'


def render_summary(summary: dict, metrics: dict, top: int = 0) -> str:
    lines: list[str] = []
    spans = sorted(summary['spans'].items(), key=lambda kv: -kv[1]['total_s'])
    if top:
        spans = spans[:top]
    if spans:
        name_w = max(len('span'), *(len(n) for n, _ in spans))
        lines.append(f'{"span":<{name_w}}  {"count":>6}  {"total":>9}  {"mean":>9}  {"max":>9}')
        lines.append('-' * (name_w + 40))
        for name, s in spans:
            lines.append(
                f'{name:<{name_w}}  {s["count"]:>6}  {_fmt_s(s["total_s"]):>9}  '
                f'{_fmt_s(s["mean_s"]):>9}  {_fmt_s(s["max_s"]):>9}'
            )
    else:
        lines.append('(no spans recorded)')
    if summary['instants']:
        lines.append('')
        lines.append('instant events:')
        for name, n in sorted(summary['instants'].items()):
            lines.append(f'  {name}: {n}')
    if metrics:
        lines.append('')
        lines.append('metrics:')
        for name, m in sorted(metrics.items()):
            kind = m.get('type')
            if kind == 'histogram':
                # the `_s` suffix convention marks seconds-valued histograms
                fmt = _fmt_s if name.endswith('_s') else (lambda v: f'{v:g}')
                if m.get('count'):
                    lines.append(
                        f'  {name}: n={m["count"]} mean={fmt(m["mean"])} min={fmt(m["min"])} max={fmt(m["max"])}'
                    )
                else:
                    lines.append(f'  {name}: n=0')
            else:
                lines.append(f'  {name}: {m.get("value"):g}')
    return '\n'.join(lines)


def stats_main(args: argparse.Namespace) -> int:
    from ..telemetry import load_trace, validate_trace

    path = Path(args.trace)
    if not path.is_file():
        from ..telemetry import get_logger

        get_logger('cli.stats').warning(f'no such trace file: {path}')
        return 1
    events, metrics = load_trace(path)
    if args.validate:
        validate_trace(events)
    summary = summarize_events(events)
    if args.json:
        print(json.dumps({'file': str(path), 'n_events': len(events), **summary, 'metrics': metrics}, indent=2))
    else:
        print(f'{path}: {len(events)} events')
        print(render_summary(summary, metrics, top=args.top))
    return 0


def add_stats_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument('trace', type=Path, help='Trace file captured with --trace / DA4ML_TRACE (.json or .jsonl)')
    parser.add_argument('--json', action='store_true', help='Emit the summary as JSON instead of a table')
    parser.add_argument('--top', type=int, default=0, help='Show only the N span names with the largest total time')
    parser.add_argument(
        '--validate', action='store_true', help='Additionally check every event against the Chrome trace-event schema'
    )
