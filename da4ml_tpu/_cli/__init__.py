"""``da4ml-tpu`` command line interface.

Two subcommands (parity with the reference console script, reference
src/da4ml/_cli/__init__.py:8-27):

- ``convert`` — model file (.keras/.h5 via the keras plugin, or a saved
  CombLogic/Pipeline .json) → RTL/HLS project with optional bit-exact
  validation;
- ``report`` — parse vendor synthesis reports from project directories into
  a summary table;
- ``verify`` — run the DAIS static-analysis verifier over saved programs or
  generated project directories (docs/analysis.md); ``--conformance`` adds
  the cross-backend differential pass, ``--fuzz N`` runs the corpus
  conformance + transfer-soundness sweep without paths;
- ``lint-opcodes`` — fail on opcode dispatch sites outside the declarative
  opcode table's allowlisted consumers (docs/analysis.md#drift-lint);
- ``warmup`` — pre-compile the device-search shape classes;
- ``stats`` — summarize a telemetry trace captured with ``--trace`` /
  ``DA4ML_TRACE`` (docs/telemetry.md); ``--follow`` tails a streaming
  JSONL trace live;
- ``trace-view`` — merge N per-process JSONL traces (a fleet's replicas +
  router) into one clock-aligned Perfetto timeline, with a per-trace-id
  multiprocess gate (docs/observability.md#fleet-tracing);
- ``monitor`` — serve the live ``/metrics`` / ``/healthz`` / ``/statusz``
  endpoints, optionally mirroring a followed trace
  (docs/observability.md);
- ``bench-diff`` — gate a BENCH/metrics snapshot against a baseline under
  per-metric tolerance budgets (exit 1 on regression);
- ``campaign`` — fault-tolerant multi-process solve campaigns over a
  shared-filesystem work queue, plus the SIGKILL chaos drill
  (docs/distributed.md);
- ``serve`` — resilient HTTP inference front-end: deadline-aware dynamic
  batching, admission control/shedding, per-model breakers with graceful
  degradation, plus its own chaos drill (docs/serving.md);
- ``cache`` — operate a global content-addressed solution store: stats,
  re-verification, lease-guarded LRU gc, and the zipf-traffic + bit-flip
  chaos drill (docs/store.md);
- ``export`` — fuse a saved model into ONE DAIS program and write the
  self-contained, digest-stamped serving artifact ``ServeEngine`` hot-loads
  without retracing (docs/runtime.md#ir-fusion);
- ``fleet`` — spawn + supervise N serve replicas over one artifact behind
  the health-aware hedging router, with its SIGKILL+reload chaos drill
  (docs/serving.md#replica-fleets).
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog='da4ml-tpu', description='TPU-native distributed-arithmetic compiler')
    sub = parser.add_subparsers(dest='command', required=True)

    from .convert import add_convert_args, convert_main
    from .report import add_report_args, report_main

    p_convert = sub.add_parser('convert', help='Convert a model into an RTL/HLS project')
    add_convert_args(p_convert)
    p_convert.set_defaults(func=convert_main)

    p_report = sub.add_parser('report', help='Summarize synthesis reports of project directories')
    add_report_args(p_report)
    p_report.set_defaults(func=report_main)

    from .warmup import add_warmup_args, warmup_main

    p_warm = sub.add_parser('warmup', help='Pre-compile the device-search shape classes into the XLA cache')
    add_warmup_args(p_warm)
    p_warm.set_defaults(func=warmup_main)

    from .verify import add_verify_args, verify_main

    p_verify = sub.add_parser('verify', help='Statically verify saved DAIS programs (well-formedness, intervals, lint)')
    add_verify_args(p_verify)
    p_verify.set_defaults(func=verify_main)

    from ..analysis.driftlint import add_lint_opcodes_args, lint_opcodes_main

    p_lint = sub.add_parser(
        'lint-opcodes', help='Fail on opcode dispatch sites outside the declarative table consumers'
    )
    add_lint_opcodes_args(p_lint)
    p_lint.set_defaults(func=lint_opcodes_main)

    from .stats import add_stats_args, stats_main

    p_stats = sub.add_parser('stats', help='Summarize a telemetry trace captured with --trace / DA4ML_TRACE')
    add_stats_args(p_stats)
    p_stats.set_defaults(func=stats_main)

    from .trace_view import add_trace_view_args, trace_view_main

    p_tv = sub.add_parser('trace-view', help='Merge per-process JSONL traces into one Perfetto fleet timeline')
    add_trace_view_args(p_tv)
    p_tv.set_defaults(func=trace_view_main)

    from .monitor import add_monitor_args, monitor_main

    p_mon = sub.add_parser('monitor', help='Serve the live /metrics /healthz /statusz observability endpoints')
    add_monitor_args(p_mon)
    p_mon.set_defaults(func=monitor_main)

    from ..telemetry.obs.bench_diff import add_bench_diff_args, bench_diff_main

    p_bd = sub.add_parser('bench-diff', help='Gate a BENCH/metrics snapshot against a baseline under tolerance budgets')
    add_bench_diff_args(p_bd)
    p_bd.set_defaults(func=bench_diff_main)

    from .campaign import add_campaign_args, campaign_main

    p_camp = sub.add_parser('campaign', help='Run a fault-tolerant multi-worker solve campaign (or its chaos drill)')
    add_campaign_args(p_camp)
    p_camp.set_defaults(func=campaign_main)

    from .serve import add_serve_args, serve_main

    p_serve = sub.add_parser('serve', help='Serve models over HTTP with dynamic batching and admission control')
    add_serve_args(p_serve)
    p_serve.set_defaults(func=serve_main)

    from .export import add_export_args, export_main

    p_export = sub.add_parser('export', help='Write a fused, digest-stamped serving artifact (hot-loadable)')
    add_export_args(p_export)
    p_export.set_defaults(func=export_main)

    from .cache import add_cache_args, cache_main

    p_cache = sub.add_parser('cache', help='Operate a global solution store (stats / verify / gc / chaos)')
    add_cache_args(p_cache)
    p_cache.set_defaults(func=cache_main)

    from .fleet import add_fleet_args, fleet_main

    p_fleet = sub.add_parser('fleet', help='Drive a replica fleet behind the health-aware hedging router')
    add_fleet_args(p_fleet)
    p_fleet.set_defaults(func=fleet_main)

    args = parser.parse_args(argv)
    return args.func(args) or 0


if __name__ == '__main__':
    sys.exit(main())
