"""``da4ml-tpu cache`` — operate a global solution store (docs/store.md).

Four actions over a store directory (``--store`` or ``DA4ML_SOLUTION_STORE``):

- ``stats``  — occupancy, hit/miss accounting, breaker states;
- ``verify`` — re-run the DAIS verifier over every entry; bad entries are
  quarantined to ``corrupt/`` exactly as a read would;
- ``gc``     — lease-guarded LRU eviction under ``--max-bytes`` /
  ``--max-age`` (never unlinks a key a solver holds right now);
- ``chaos``  — the zipf-traffic + bit-flip drill (CI job ``store-chaos``);
  exit 0/1 on its gate.

Sizes accept ``K``/``M``/``G`` suffixes (``--max-bytes 512M``); ages accept
``s``/``m``/``h``/``d`` (``--max-age 7d``).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def parse_size(text: str) -> int:
    """'512M' → bytes (K/M/G/T suffixes, case-insensitive)."""
    t = text.strip().upper()
    mult = {'K': 1 << 10, 'M': 1 << 20, 'G': 1 << 30, 'T': 1 << 40}.get(t[-1:] or '', None)
    try:
        return int(float(t[:-1]) * mult) if mult else int(float(t))
    except ValueError:
        raise argparse.ArgumentTypeError(f'not a size: {text!r} (expected e.g. 512M, 2G, 1048576)') from None


def parse_age(text: str) -> float:
    """'7d' → seconds (s/m/h/d suffixes; bare numbers are seconds)."""
    t = text.strip().lower()
    mult = {'s': 1.0, 'm': 60.0, 'h': 3600.0, 'd': 86400.0}.get(t[-1:] or '', None)
    try:
        return float(t[:-1]) * mult if mult else float(t)
    except ValueError:
        raise argparse.ArgumentTypeError(f'not an age: {text!r} (expected e.g. 7d, 12h, 600)') from None


def add_cache_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument('action', choices=('stats', 'verify', 'gc', 'chaos'), help='What to do with the store')
    parser.add_argument('--store', default=None, help='Store directory (default: DA4ML_SOLUTION_STORE)')
    parser.add_argument('--max-bytes', type=parse_size, default=None, help='gc: evict LRU entries down to this size')
    parser.add_argument('--max-age', type=parse_age, default=None, help='gc: evict entries older than this (e.g. 7d)')
    parser.add_argument('--workers', type=int, default=3, help='chaos: worker subprocesses')
    parser.add_argument('--requests', type=int, default=None, help='chaos: total requests (default 300)')
    parser.add_argument('--kernels', type=int, default=None, help='chaos: corpus size (default 48)')
    parser.add_argument('--backend', default='pure-python', help='chaos: solver backend')
    parser.add_argument('--json', action='store_true', dest='as_json', help='Print the full report as JSON')
    parser.add_argument('--out', type=Path, default=None, help='Also write the report JSON to a file')


def cache_main(args: argparse.Namespace) -> int:
    from ..telemetry import get_logger

    log = get_logger('cli.cache')

    if args.action == 'chaos':
        from ..store.chaos import N_KERNELS, N_REQUESTS, store_chaos_drill

        report = store_chaos_drill(
            workers=max(2, args.workers),
            base_dir=args.store,
            backend=args.backend,
            n_kernels=args.kernels if args.kernels is not None else N_KERNELS,
            n_requests=args.requests if args.requests is not None else N_REQUESTS,
        )
        if args.out is not None:
            args.out.write_text(json.dumps(report, indent=2, default=str))
        print(json.dumps(report if args.as_json else {'ok': report['ok'], **report['checks']}, indent=2, default=str))
        return 0 if report['ok'] else 1

    from ..store.solution_store import resolve_store

    store = resolve_store(args.store)
    if store is None:
        log.warning('no store: pass --store DIR or set DA4ML_SOLUTION_STORE')
        return 2

    if args.action == 'stats':
        print(json.dumps(store.stats(), indent=2))
        return 0
    if args.action == 'verify':
        report = store.verify_all()
        print(json.dumps(report, indent=2))
        return 0 if report['quarantined'] == 0 else 1
    # gc
    report = store.gc(max_bytes=args.max_bytes, max_age_s=args.max_age)
    if args.out is not None:
        args.out.write_text(json.dumps(report, indent=2))
    print(json.dumps(report, indent=2))
    return 0


if __name__ == '__main__':  # pragma: no cover - convenience entry
    ap = argparse.ArgumentParser(prog='da4ml-tpu cache')
    add_cache_args(ap)
    sys.exit(cache_main(ap.parse_args()))
