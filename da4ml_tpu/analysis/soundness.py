"""Transfer-soundness checker: the abstract interval transfers must contain
every concrete result.

For each opcode row of the declarative table (``ir/optable.py``), the row's
``sample`` builds an *honest* randomized one-op program: operand slots are
copy ops carrying randomized QIntervals, and the op under test carries the
annotation a correct producer would write. Concrete inputs are drawn from
the operand intervals' dyadic grids and replayed through the real
``CombLogic.__call__`` float path; the abstract output interval comes from
the same per-opcode ``transfer`` functions the ``qinterval`` verifier pass
dispatches on (``interval.compute_intervals``).

A concrete result escaping the abstract interval — or the verifier flagging
an honest program as unsound — is a **verifier bug**, surfaced as a
**D310 transfer-unsound** diagnostic (not a silent miscompile): it means the
``qinterval`` pass could green-light an annotation that overflows in
hardware, since codegen sizes every wire from ``minimal_kif(op.qint)``.
"""

from __future__ import annotations

import numpy as np

from ..ir.comb import CombLogic
from ..ir.optable import COPY_OPCODES, OP_TABLE, OpSpec
from ..ir.types import QInterval
from .diagnostics import ERROR, Diagnostic
from .interval import compute_intervals

_TOL = 1e-9


def _grid_samples(rng: np.random.Generator, qi: QInterval, n: int) -> np.ndarray:
    """Concrete values on the interval's dyadic grid."""
    lo, hi = round(qi.min / qi.step), round(qi.max / qi.step)
    return rng.integers(lo, hi + 1, n) * qi.step


def _case_comb(case) -> CombLogic:
    n_lanes = max(1, sum(1 for o in case.ops if o.opcode in COPY_OPCODES))
    return CombLogic(
        shape=(n_lanes, 1),
        inp_shifts=[0] * n_lanes,
        out_idxs=[case.op_index],
        out_shifts=[0],
        out_negs=[False],
        ops=list(case.ops),
        carry_size=32,
        adder_size=32,
        lookup_tables=case.tables,
    )


def check_spec_soundness(
    spec: OpSpec, rng: np.random.Generator, n_cases: int = 25, n_samples: int = 16
) -> list[Diagnostic]:
    """Fuzz one table row: ``n_cases`` honest programs × ``n_samples``
    concrete grid points each."""
    diags: list[Diagnostic] = []
    for ci in range(n_cases):
        case = spec.sample(rng)
        comb = _case_comb(case)
        op = comb.ops[case.op_index]
        computed, interval_diags = compute_intervals(comb)
        false_positives = [d for d in interval_diags if d.severity == ERROR]
        if false_positives:
            diags.append(
                Diagnostic(
                    'D310',
                    f'{spec.key} case {ci}: the qinterval pass flags an honest program as unsound '
                    f'({false_positives[0].rule}: {false_positives[0].message})',
                    op_index=case.op_index,
                    opcode=op.opcode,
                )
            )
            continue
        ci_abs = computed[case.op_index]
        if ci_abs is None:
            continue
        lanes = [o for o in comb.ops if o.opcode in COPY_OPCODES and o is not op]
        tol = _TOL * max(1.0, abs(ci_abs.min), abs(ci_abs.max))
        for si in range(n_samples):
            x = np.zeros(comb.shape[0])
            for o in lanes:
                x[int(o.id0)] = _grid_samples(rng, o.qint, 1)[0]
            if op.opcode in COPY_OPCODES:  # the op under test reads the input directly
                x[int(op.id0)] = _grid_samples(rng, op.qint, 1)[0]
            y = float(comb(x)[0])
            if not (ci_abs.min - tol <= y <= ci_abs.max + tol):
                diags.append(
                    Diagnostic(
                        'D310',
                        f'{spec.key} case {ci} sample {si}: concrete result {y} escapes the abstract '
                        f'interval [{ci_abs.min}, {ci_abs.max}] (inputs {x.tolist()}, op {op})',
                        op_index=case.op_index,
                        opcode=op.opcode,
                    )
                )
                break
    return diags


def check_transfer_soundness(
    n_cases: int = 25, n_samples: int = 16, seed: int = 0
) -> tuple[dict, list[Diagnostic]]:
    """Fuzz every opcode row; returns ``(report, diagnostics)``.

    The report carries per-opcode case counts so the CI artifact shows what
    was proven, not just that nothing failed.
    """
    diags: list[Diagnostic] = []
    per_family: dict[str, dict] = {}
    for spec in OP_TABLE:
        rng = np.random.default_rng(seed * 1_000_003 + spec.vector_class)
        found = check_spec_soundness(spec, rng, n_cases=n_cases, n_samples=n_samples)
        per_family[spec.key] = {
            'family': spec.family,
            'opcodes': list(spec.opcodes),
            'cases': n_cases,
            'samples_per_case': n_samples,
            'counterexamples': len(found),
        }
        diags.extend(found)
    report = {
        'ok': not diags,
        'seed': seed,
        'per_family': per_family,
        'diagnostics': [d.to_dict() for d in diags],
    }
    return report, diags


__all__ = ['check_spec_soundness', 'check_transfer_soundness']
