"""Documentation generator for the table-owned reference sections.

The opcode reference table in ``docs/dais.md``, the rule catalog in
``docs/analysis.md``, and the environment-knob table in ``docs/api.md``
are *generated* from the single sources of truth (``ir/optable.py`` rows,
``analysis.diagnostics.RULES``, ``analysis.catalogs.KNOBS``) between
marker comments::

    <!-- BEGIN GENERATED: dais-opcode-table -->
    ...
    <!-- END GENERATED: dais-opcode-table -->

Usage::

    python -m da4ml_tpu.analysis.docgen            # rewrite in place
    python -m da4ml_tpu.analysis.docgen --check    # exit 1 on drift (CI)

Edits inside the markers are overwritten; the prose around them is never
touched. The CI lint job runs ``--check`` so a table/rule change cannot
land without its regenerated docs.
"""

from __future__ import annotations

import argparse
import re
import sys
from pathlib import Path

from ..ir.optable import OP_TABLE
from .catalogs import render_knob_table
from .diagnostics import RULES


def render_opcode_table() -> str:
    """The docs/dais.md opcode reference, one row per table entry."""
    lines = [
        '| opcode | family | semantics | payload (`data`) | cost/latency model |',
        '|---|---|---|---|---|',
    ]
    for spec in OP_TABLE:
        ocs = ' / '.join(f'`{oc}`' for oc in spec.opcodes)
        lines.append(f'| {ocs} | {spec.family} | {spec.semantics} | {spec.payload} | {spec.cost_model} |')
    return '\n'.join(lines)


def render_rule_catalog() -> str:
    """The docs/analysis.md diagnostic rule catalog."""
    lines = ['| rule | name | severity | meaning |', '|---|---|---|---|']
    for rule, (name, severity, meaning) in RULES.items():
        lines.append(f'| {rule} | {name} | {severity} | {meaning} |')
    return '\n'.join(lines)


#: doc file (relative to repo root) -> {marker name -> renderer}
SECTIONS: dict[str, dict[str, object]] = {
    'docs/dais.md': {'dais-opcode-table': render_opcode_table},
    'docs/analysis.md': {'analysis-rule-catalog': render_rule_catalog},
    'docs/api.md': {'env-knob-table': render_knob_table},
}


def _splice(text: str, marker: str, body: str) -> str:
    begin = f'<!-- BEGIN GENERATED: {marker} -->'
    end = f'<!-- END GENERATED: {marker} -->'
    pattern = re.compile(re.escape(begin) + r'.*?' + re.escape(end), re.DOTALL)
    if not pattern.search(text):
        raise ValueError(f'marker {marker!r} not found')
    return pattern.sub(f'{begin}\n{body}\n{end}', text)


def apply(root: str | Path | None = None, check: bool = False) -> list[str]:
    """Regenerate every marked section. Returns the list of drifted files
    (``check=True`` leaves files untouched)."""
    if root is None:
        root = Path(__file__).resolve().parents[2]
    root = Path(root)
    drifted: list[str] = []
    for rel, markers in SECTIONS.items():
        path = root / rel
        text = original = path.read_text()
        for marker, renderer in markers.items():
            text = _splice(text, marker, renderer())
        if text != original:
            drifted.append(rel)
            if not check:
                path.write_text(text)
    return drifted


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog='python -m da4ml_tpu.analysis.docgen', description=__doc__)
    parser.add_argument('--check', action='store_true', help='exit 1 if the committed docs drift from the table')
    parser.add_argument('--root', default=None, help='repository root (default: the installed package root)')
    args = parser.parse_args(argv)
    drifted = apply(args.root, check=args.check)
    if not drifted:
        print('docgen: generated doc sections are in sync')
        return 0
    if args.check:
        print(f'docgen: DRIFT in {drifted} — run `python -m da4ml_tpu.analysis.docgen` and commit')
        return 1
    print(f'docgen: regenerated {drifted}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
