"""Opcode-dispatch drift lint (``da4ml-tpu lint-opcodes``).

The declarative opcode table (``ir/optable.py``) is the single source of
truth for DAIS semantics. The one way that guarantee erodes is a new
hand-written dispatch-on-opcode site: an ``if op.opcode == 7`` in a fresh
module re-encodes semantics the table already owns, and the next opcode
lands everywhere but there.

This lint AST-scans the package for opcode dispatch sites — comparisons
(``==``/``!=``/``in``/``not in``, including ``abs(...)`` wrapping and
``match`` statements) whose subject is named ``opcode``/``oc``/``opc`` and
whose comparator involves integer constants — and fails when a file
*outside the explicit allowlist* contains one. The allowlist names every
legitimate consumer: the table itself, the declared backends that compile
it to other forms (numpy/jax kernels, C++/HDL emitters, the tracer), and
the synth fuzzer. Growing the allowlist is a reviewed act; silently
growing a new dispatch site is not.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import NamedTuple

_SUBJECT_NAMES = frozenset({'opcode', 'oc', 'opc', 'opr'})

#: files allowed to dispatch on opcodes, with the reason. Paths are relative
#: to the repository root (the ``da4ml_tpu`` package's parent).
ALLOWLIST: dict[str, str] = {
    'da4ml_tpu/ir/optable.py': 'the declarative opcode table itself',
    'da4ml_tpu/ir/comb.py': 'binary stream encoder (opcode-8 table padding) over table-generated replay',
    'da4ml_tpu/ir/dais_binary.py': 'binary stream causality validator (struct-of-arrays fast path)',
    'da4ml_tpu/ir/fuse.py': 'pipeline fuser: seam lowering replaces boundary copies; binary round-trip pads opcode-8 tables (fused output conformance-checked vs staged execution)',
    'da4ml_tpu/ir/schedule.py': 'levelizer: dependency-field usage via table-exported sets',
    'da4ml_tpu/ir/partition.py': 'model-axis partitioner: seam lowering re-emits boundary copies and carries const/lookup metadata across shards (cells conformance-checked vs the reference)',
    'da4ml_tpu/runtime/numpy_backend.py': 'vectorized interpreter backend (conformance-checked vs the reference)',
    'da4ml_tpu/runtime/jax_backend.py': 'XLA kernel builders (conformance-checked vs the reference)',
    'da4ml_tpu/trace/tracer.py': 'IR producer: encodes traced ops into opcodes',
    'da4ml_tpu/trace/pipeline.py': 'retimer: splits on quantize-family boundaries',
    # C++/HDL layers: emit per-opcode source text; semantics validated by
    # the bit-exactness suites, not regenerable from python callables
    'da4ml_tpu/codegen/rtl/verilog/comb.py': 'HDL emitter (C++/HDL layer allowance)',
    'da4ml_tpu/codegen/rtl/vhdl/comb.py': 'HDL emitter (C++/HDL layer allowance)',
    'da4ml_tpu/codegen/hls/hls_codegen.py': 'HLS emitter (C++/HDL layer allowance)',
}


class DispatchSite(NamedTuple):
    path: str  # repo-relative posix path
    lineno: int
    snippet: str


def _names_opcode(node: ast.expr) -> bool:
    """Does this expression reference an opcode-ish value?"""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and node.func.id == 'abs' and node.args:
        return _names_opcode(node.args[0])
    if isinstance(node, ast.Name):
        return node.id in _SUBJECT_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _SUBJECT_NAMES
    if isinstance(node, ast.Subscript):
        return _names_opcode(node.value)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and node.func.id == 'int' and node.args:
        return _names_opcode(node.args[0])
    return False


def _has_int_constant(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, int) and not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _has_int_constant(node.operand)
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return any(_has_int_constant(e) for e in node.elts)
    return False


class _Scanner(ast.NodeVisitor):
    def __init__(self, path: str, source_lines: list[str]):
        self.path = path
        self.lines = source_lines
        self.sites: list[DispatchSite] = []

    def _record(self, node: ast.AST):
        line = self.lines[node.lineno - 1].strip() if node.lineno - 1 < len(self.lines) else ''
        self.sites.append(DispatchSite(self.path, node.lineno, line))

    def visit_Compare(self, node: ast.Compare):
        subjects = [node.left, *node.comparators]
        if any(_names_opcode(s) for s in subjects) and any(_has_int_constant(s) for s in subjects):
            if any(isinstance(op, (ast.Eq, ast.NotEq, ast.In, ast.NotIn)) for op in node.ops):
                self._record(node)
        self.generic_visit(node)

    def visit_Match(self, node: ast.Match):
        if _names_opcode(node.subject):
            self._record(node)
        self.generic_visit(node)


def scan_file(path: Path, rel: str) -> list[DispatchSite]:
    source = path.read_text()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    scanner = _Scanner(rel, source.splitlines())
    scanner.visit(tree)
    return scanner.sites


def lint_opcodes(root: str | Path | None = None) -> tuple[list[DispatchSite], list[str]]:
    """Scan the package for opcode dispatch sites.

    Returns ``(violations, stale_allowlist)``: sites in files outside the
    allowlist, and allowlist entries whose file no longer has any site
    (or no longer exists) — both fail the lint, so the allowlist cannot rot.
    """
    if root is None:
        root = Path(__file__).resolve().parents[2]
    root = Path(root)
    pkg = root / 'da4ml_tpu'
    by_file: dict[str, list[DispatchSite]] = {}
    for path in sorted(pkg.rglob('*.py')):
        rel = path.relative_to(root).as_posix()
        sites = scan_file(path, rel)
        if sites:
            by_file[rel] = sites
    violations = [s for rel, sites in by_file.items() if rel not in ALLOWLIST for s in sites]
    stale = [rel for rel in ALLOWLIST if rel not in by_file]
    return violations, stale


def lint_opcodes_main(args) -> int:
    violations, stale = lint_opcodes(getattr(args, 'root', None))
    if not violations and not stale:
        print(f'lint-opcodes: ok ({len(ALLOWLIST)} allowlisted dispatch files, 0 untracked sites)')
        return 0
    for s in violations:
        print(f'{s.path}:{s.lineno}: untracked opcode dispatch site: {s.snippet}')
    if violations:
        print(
            'lint-opcodes: opcode dispatch outside the table consumers — route the new logic through '
            'ir/optable.py (add a row field or consume an existing one), or allowlist the file in '
            'analysis/driftlint.py with a reason'
        )
    for rel in stale:
        print(f'lint-opcodes: stale allowlist entry (no dispatch sites found): {rel}')
    return 1


def add_lint_opcodes_args(parser) -> None:
    parser.add_argument('--root', default=None, help='repository root to scan (default: the installed package root)')


__all__ = ['ALLOWLIST', 'DispatchSite', 'lint_opcodes', 'lint_opcodes_main', 'add_lint_opcodes_args', 'scan_file']
