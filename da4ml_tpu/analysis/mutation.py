"""Deterministic IR corruption harness for verifier self-tests.

Each :class:`Corruption` damages one field of one op of a given DAIS opcode
family and names the verifier rule that must catch it. The per-opcode
entries are *generated* from the declarative opcode table — every
``OpSpec.mutations`` row of ``ir/optable.py`` becomes a catalog entry, so a
new opcode ships with its corruption (and its detection test) by
construction, with no hand-maintained list to drift. Only the container-
level corruptions (io bindings, cost fields, pipeline interfaces) live
here, since they are not tied to an opcode.

Corruptions are wired into the fault-injection plan machinery
(reliability/faults.py): site ``ir.mutate.<name>`` with mode ``corrupt``
arms one corruption, so a chaos drill can corrupt programs exactly the way
it degrades backends::

    with fault_injection('ir.mutate.add.forward_ref=corrupt:1'):
        prog = apply_planned_corruptions(prog)   # mutates iff armed

    verify(prog)   # -> W103 operand-violation

The mutation self-test (tests/test_verifier.py) asserts every catalog entry
is caught with a structured diagnostic; the catalog covers every opcode
family of the DAIS v1 table by construction.
"""

from __future__ import annotations

from dataclasses import dataclass
from math import nan
from typing import Callable

from ..ir.comb import CombLogic, Pipeline
from ..ir.optable import OP_TABLE, _find_op, mutate_op
from ..reliability.faults import fault_active

FAULT_SITE_PREFIX = 'ir.mutate.'


def _corrupt_outputs_dead(comb: CombLogic) -> CombLogic:
    copy = _find_op(comb, (-1,))
    return comb._replace(out_idxs=[copy] * len(comb.out_idxs))


def _corrupt_out_binding(comb: CombLogic) -> CombLogic:
    out = list(comb.out_idxs)
    out[0] = len(comb.ops) + 5
    return comb._replace(out_idxs=out)


def _corrupt_inp_shifts(comb: CombLogic) -> CombLogic:
    return comb._replace(inp_shifts=list(comb.inp_shifts)[:-1])


def _corrupt_stage_interface(pipe: Pipeline) -> Pipeline:
    s0 = pipe.stages[0]
    s0 = s0._replace(
        shape=(s0.shape[0], s0.shape[1] - 1),
        out_idxs=list(s0.out_idxs)[:-1],
        out_shifts=list(s0.out_shifts)[:-1],
        out_negs=list(s0.out_negs)[:-1],
    )
    return Pipeline(stages=(s0,) + pipe.stages[1:])


@dataclass(frozen=True)
class Corruption:
    """One catalogued IR corruption: what it damages and who must catch it."""

    name: str  # fault site suffix, e.g. 'add.forward_ref'
    family: str  # DAIS opcode family it targets
    expect_rule: str  # verifier rule id that must flag it
    apply: Callable  # CombLogic -> CombLogic (or Pipeline -> Pipeline)


#: container-level corruptions: not tied to one opcode row
_CONTAINER_CORRUPTIONS: tuple[Corruption, ...] = (
    Corruption('any.unknown_opcode', 'any', 'W102', lambda c: mutate_op(c, (0, 1), opcode=42)),
    Corruption('any.nan_latency', 'any', 'D302', lambda c: mutate_op(c, (0, 1), latency=nan)),
    Corruption('any.negative_cost', 'any', 'D302', lambda c: mutate_op(c, (2, -2, 3, -3), cost=-1.0)),
    Corruption('io.out_of_range_output', 'io', 'W105', _corrupt_out_binding),
    Corruption('io.truncated_inp_shifts', 'io', 'W101', _corrupt_inp_shifts),
    Corruption('io.dead_subgraph', 'io', 'D301', _corrupt_outputs_dead),
)

#: one corruption family per opcode-table row, plus the container-level set
COMB_CORRUPTIONS: tuple[Corruption, ...] = tuple(
    Corruption(m.name, spec.family, m.expect_rule, m.apply) for spec in OP_TABLE for m in spec.mutations
) + _CONTAINER_CORRUPTIONS

PIPELINE_CORRUPTIONS: tuple[Corruption, ...] = (
    Corruption('pipeline.stage_interface', 'pipeline', 'W120', _corrupt_stage_interface),
)


def corruption_by_name(name: str) -> Corruption:
    for c in COMB_CORRUPTIONS + PIPELINE_CORRUPTIONS:
        if c.name == name:
            return c
    raise KeyError(f'unknown corruption {name!r}')


def apply_planned_corruptions(program: CombLogic | Pipeline):
    """Apply every corruption armed through the active fault plan.

    Consults ``fault_active('ir.mutate.<name>', 'corrupt')`` for each catalog
    entry — the reliability fault plan (env var or :class:`fault_injection`)
    decides which corruptions fire, and their firing budgets.
    """
    catalog = PIPELINE_CORRUPTIONS if isinstance(program, Pipeline) else COMB_CORRUPTIONS
    for c in catalog:
        if fault_active(FAULT_SITE_PREFIX + c.name, 'corrupt'):
            program = c.apply(program)
    if isinstance(program, Pipeline):
        stages = list(program.stages)
        for c in COMB_CORRUPTIONS:
            if fault_active(FAULT_SITE_PREFIX + c.name, 'corrupt'):
                stages[0] = c.apply(stages[0])
        program = Pipeline(stages=tuple(stages))
    return program


__all__ = [
    'COMB_CORRUPTIONS',
    'PIPELINE_CORRUPTIONS',
    'FAULT_SITE_PREFIX',
    'Corruption',
    'apply_planned_corruptions',
    'corruption_by_name',
]
