"""Deterministic IR corruption harness for verifier self-tests.

Each :class:`Corruption` damages one field of one op of a given DAIS opcode
family and names the verifier rule that must catch it. Corruptions are wired
into the fault-injection plan machinery (reliability/faults.py): site
``ir.mutate.<name>`` with mode ``corrupt`` arms one corruption, so a chaos
drill can corrupt programs exactly the way it degrades backends::

    with fault_injection('ir.mutate.add.forward_ref=corrupt:1'):
        prog = apply_planned_corruptions(prog)   # mutates iff armed

    verify(prog)   # -> W103 operand-violation

The mutation self-test (tests/test_verifier.py) asserts every catalog entry
is caught with a structured diagnostic; the catalog covers every opcode
family of the DAIS v1 table.
"""

from __future__ import annotations

from dataclasses import dataclass
from math import nan
from typing import Callable

from ..ir.comb import CombLogic, Pipeline
from ..ir.types import QInterval
from ..reliability.faults import fault_active

FAULT_SITE_PREFIX = 'ir.mutate.'


def _find(comb: CombLogic, opcodes: tuple[int, ...]) -> int:
    for i, op in enumerate(comb.ops):
        if op.opcode in opcodes:
            return i
    raise ValueError(f'program has no op with opcode in {opcodes}; cannot apply corruption')


def _mutate_op(comb: CombLogic, opcodes: tuple[int, ...], **fields) -> CombLogic:
    i = _find(comb, opcodes)
    ops = list(comb.ops)
    ops[i] = ops[i]._replace(**fields)
    return comb._replace(ops=ops)


def _mutate_qint(comb: CombLogic, opcodes: tuple[int, ...], fn: Callable[[QInterval], QInterval]) -> CombLogic:
    i = _find(comb, opcodes)
    ops = list(comb.ops)
    ops[i] = ops[i]._replace(qint=fn(ops[i].qint))
    return comb._replace(ops=ops)


def _self_reference(comb: CombLogic, opcodes: tuple[int, ...], field: str) -> CombLogic:
    i = _find(comb, opcodes)
    ops = list(comb.ops)
    ops[i] = ops[i]._replace(**{field: i})
    return comb._replace(ops=ops)


def _corrupt_mux_cond(comb: CombLogic) -> CombLogic:
    i = _find(comb, (6, -6))
    ops = list(comb.ops)
    data = int(ops[i].data)
    shift = data >> 32  # keep the shift word, repoint the condition at self
    ops[i] = ops[i]._replace(data=(shift << 32) | i)
    return comb._replace(ops=ops)


def _corrupt_bitbin_subop(comb: CombLogic) -> CombLogic:
    i = _find(comb, (10,))
    ops = list(comb.ops)
    data = int(ops[i].data)
    ops[i] = ops[i]._replace(data=(9 << 56) | (data & ((1 << 56) - 1)))
    return comb._replace(ops=ops)


def _corrupt_outputs_dead(comb: CombLogic) -> CombLogic:
    copy = _find(comb, (-1,))
    return comb._replace(out_idxs=[copy] * len(comb.out_idxs))


def _corrupt_out_binding(comb: CombLogic) -> CombLogic:
    out = list(comb.out_idxs)
    out[0] = len(comb.ops) + 5
    return comb._replace(out_idxs=out)


def _corrupt_inp_shifts(comb: CombLogic) -> CombLogic:
    return comb._replace(inp_shifts=list(comb.inp_shifts)[:-1])


def _corrupt_stage_interface(pipe: Pipeline) -> Pipeline:
    s0 = pipe.stages[0]
    s0 = s0._replace(
        shape=(s0.shape[0], s0.shape[1] - 1),
        out_idxs=list(s0.out_idxs)[:-1],
        out_shifts=list(s0.out_shifts)[:-1],
        out_negs=list(s0.out_negs)[:-1],
    )
    return Pipeline(stages=(s0,) + pipe.stages[1:])


@dataclass(frozen=True)
class Corruption:
    """One catalogued IR corruption: what it damages and who must catch it."""

    name: str  # fault site suffix, e.g. 'add.forward_ref'
    family: str  # DAIS opcode family it targets
    expect_rule: str  # verifier rule id that must flag it
    apply: Callable  # CombLogic -> CombLogic (or Pipeline -> Pipeline)


COMB_CORRUPTIONS: tuple[Corruption, ...] = (
    Corruption('copy.bad_lane', 'copy', 'W104', lambda c: _mutate_op(c, (-1,), id0=c.shape[0] + 7)),
    Corruption('add.forward_ref', 'add/sub', 'W103', lambda c: _self_reference(c, (0, 1), 'id1')),
    Corruption('add.bad_shift', 'add/sub', 'W106', lambda c: _mutate_op(c, (0, 1), data=3000)),
    Corruption(
        'relu.step_not_pow2',
        'relu-quantize',
        'Q201',
        lambda c: _mutate_qint(c, (2, -2), lambda q: QInterval(q.min, q.max, q.step * 0.75)),
    ),
    Corruption(
        'quantize.inverted_bounds',
        'quantize',
        'Q202',
        lambda c: _mutate_qint(c, (3, -3), lambda q: QInterval(q.max + 1.0, q.min, q.step)),
    ),
    Corruption(
        'cadd.bias_drift',
        'const-add',
        'Q210',
        lambda c: _mutate_op(c, (4,), data=int(c.ops[_find(c, (4,))].data) + (1 << 16)),
    ),
    Corruption(
        'const.value_drift',
        'const',
        'Q210',
        lambda c: _mutate_op(c, (5,), data=int(c.ops[_find(c, (5,))].data) + (1 << 16) + 1),
    ),
    Corruption('mux.cond_forward', 'msb-mux', 'W103', _corrupt_mux_cond),
    Corruption(
        'mul.narrowed_interval',
        'mul',
        'Q210',
        lambda c: _mutate_qint(c, (7,), lambda q: QInterval(q.min / 64.0, q.max / 64.0, q.step)),
    ),
    Corruption('lut.bad_table', 'lut', 'W110', lambda c: _mutate_op(c, (8,), data=99)),
    Corruption('bit_unary.bad_subop', 'unary-bitwise', 'W111', lambda c: _mutate_op(c, (9, -9), data=7)),
    Corruption('bit_binary.bad_subop', 'binary-bitwise', 'W111', _corrupt_bitbin_subop),
    Corruption('any.unknown_opcode', 'any', 'W102', lambda c: _mutate_op(c, (0, 1), opcode=42)),
    Corruption('any.nan_latency', 'any', 'D302', lambda c: _mutate_op(c, (0, 1), latency=nan)),
    Corruption('any.negative_cost', 'any', 'D302', lambda c: _mutate_op(c, (2, -2, 3, -3), cost=-1.0)),
    Corruption('io.out_of_range_output', 'io', 'W105', _corrupt_out_binding),
    Corruption('io.truncated_inp_shifts', 'io', 'W101', _corrupt_inp_shifts),
    Corruption('io.dead_subgraph', 'io', 'D301', _corrupt_outputs_dead),
)

PIPELINE_CORRUPTIONS: tuple[Corruption, ...] = (
    Corruption('pipeline.stage_interface', 'pipeline', 'W120', _corrupt_stage_interface),
)


def corruption_by_name(name: str) -> Corruption:
    for c in COMB_CORRUPTIONS + PIPELINE_CORRUPTIONS:
        if c.name == name:
            return c
    raise KeyError(f'unknown corruption {name!r}')


def apply_planned_corruptions(program: CombLogic | Pipeline):
    """Apply every corruption armed through the active fault plan.

    Consults ``fault_active('ir.mutate.<name>', 'corrupt')`` for each catalog
    entry — the reliability fault plan (env var or :class:`fault_injection`)
    decides which corruptions fire, and their firing budgets.
    """
    catalog = PIPELINE_CORRUPTIONS if isinstance(program, Pipeline) else COMB_CORRUPTIONS
    for c in catalog:
        if fault_active(FAULT_SITE_PREFIX + c.name, 'corrupt'):
            program = c.apply(program)
    if isinstance(program, Pipeline):
        stages = list(program.stages)
        for c in COMB_CORRUPTIONS:
            if fault_active(FAULT_SITE_PREFIX + c.name, 'corrupt'):
                stages[0] = c.apply(stages[0])
        program = Pipeline(stages=tuple(stages))
    return program


__all__ = [
    'COMB_CORRUPTIONS',
    'PIPELINE_CORRUPTIONS',
    'FAULT_SITE_PREFIX',
    'Corruption',
    'apply_planned_corruptions',
    'corruption_by_name',
]
