"""Deterministic thread-interleaving harness for the serve/store plane.

The concurrency primitives this library leans on — the EDF admission
queue, lease claim/renew/steal, single-flighted store misses, hedged
router legs, fleet slot adoption — are exactly the code paths ordinary
tests exercise under one lucky scheduling. This module replays them under
*seeded* schedules instead: every traced lock operation
(:mod:`da4ml_tpu.reliability.locktrace`) and every fault-injection site
(:func:`da4ml_tpu.reliability.faults.fault_check`) is a preemption point,
a cooperative :class:`Schedule` holds all participant threads parked and
grants exactly one of them a step at a time, and a ``random.Random(seed)``
picks who runs next. The same seed therefore produces the same
interleaving — byte-identical schedule logs — and 200 seeds are 200
genuinely different thread orderings of the same scenario.

Each scenario checks *invariants*, not outputs: a request is resolved
exactly once, a contended lease has exactly one winner, a dead
single-flight winner's key is re-solved exactly once, hedged legs return
the inflight count to zero, one fleet slot is adopted by one announcer.
An invariant failure is a structured ``X512`` diagnostic; a schedule in
which every runnable thread is blocked on a lock is a real interleaving
deadlock, ``X513``. Lock-order violations observed while the tracer is
armed (``X510``/``X511``) are folded into the result as well.

CLI: ``python -m da4ml_tpu.analysis.interleave --seeds 200`` (the CI
concurrency gate); single scenarios via ``--scenario queue``.
"""

from __future__ import annotations

import os
import random
import tempfile
import threading
import time
from dataclasses import dataclass, field

from ..reliability import locktrace
from .diagnostics import Diagnostic, VerifyResult

__all__ = [
    'SCENARIOS',
    'Schedule',
    'ScenarioResult',
    'run_scenario',
    'run_suite',
]

# acceptance floor: schedules per primitive in CI; DA4ML_INTERLEAVE_SEEDS
# widens (soak runs) or narrows (quick local loops) the sweep
_DEFAULT_SEEDS = int(os.environ.get('DA4ML_INTERLEAVE_SEEDS', '') or 200)
_MAX_STEPS = 20_000  # livelock backstop: a scenario must converge well below


class _Aborted(BaseException):
    """Raised inside a participant to unwind it when the schedule aborts
    (deadlock detected or step budget exhausted). BaseException so scenario
    code's ``except Exception`` recovery paths cannot swallow it."""


class _Participant:
    __slots__ = ('name', 'thread', 'gate', 'state', 'blocked_on', 'error')

    def __init__(self, name: str):
        self.name = name
        self.thread: threading.Thread | None = None
        self.gate = threading.Event()
        self.state = 'new'  # new | ready | running | blocked | finished
        self.blocked_on: str | None = None
        self.error: BaseException | None = None


class Schedule:
    """Cooperative scheduler: all participants parked, one granted a step.

    Participants are registered with :meth:`spawn` before :meth:`run`.
    While the schedule runs, :func:`locktrace.set_schedule_hook` routes
    every traced lock acquire/release, condition wait and fault-check site
    reached *by a participant thread* into :meth:`_yield_point`; threads
    the library spawns internally (lease renewers, ...) pass through
    unscheduled. The grant log is deterministic in the seed — it is the
    reproduction artifact a failing seed prints.
    """

    def __init__(self, seed: int, max_steps: int = _MAX_STEPS):
        self.seed = seed
        self.rng = random.Random(seed)
        self.max_steps = max_steps
        self.log: list[str] = []
        self.deadlocked = False
        self.aborted = False
        self._parts: dict[str, _Participant] = {}
        self._m = threading.Lock()  # harness-internal, deliberately raw
        self._sched_evt = threading.Event()

    # -- participant side ----------------------------------------------------

    def spawn(self, name: str, fn, *args, **kwargs) -> None:
        """Register participant ``name`` running ``fn(*args, **kwargs)``.

        The thread starts parked; it takes its first step only when the
        scheduler grants it.
        """
        if name in self._parts:
            raise ValueError(f'duplicate participant {name!r}')
        part = _Participant(name)

        def _body():
            try:
                self._park(part, 'start', '-')
                fn(*args, **kwargs)
            except _Aborted:
                pass
            except BaseException as e:  # noqa: BLE001 - surfaced via .errors
                part.error = e
            finally:
                with self._m:
                    part.state = 'finished'
                    self._sched_evt.set()

        part.thread = threading.Thread(target=_body, name=f'da4ml-interleave-{name}', daemon=True)
        self._parts[name] = part

    def checkpoint(self, label: str) -> None:
        """An explicit preemption point for scenario code (canned
        transports etc.) — equivalent to reaching a fault-check site."""
        self._yield_point('site', label)

    # -- the hook ------------------------------------------------------------

    def _yield_point(self, op: str, name: str) -> None:
        part = self._parts.get(threading.current_thread().name.removeprefix('da4ml-interleave-'))
        if part is None:
            return  # library-internal thread: runs unscheduled
        if op == 'release':
            with self._m:
                for other in self._parts.values():
                    if other.blocked_on == name:
                        other.blocked_on = None
                        other.state = 'ready'
            self._park(part, op, name)
        elif op == 'blocked':
            with self._m:
                self.log.append(f'{part.name} blocked {name}')
                part.blocked_on = name
                part.state = 'blocked'
                self._sched_evt.set()
            part.gate.wait()
            part.gate.clear()
            if self.aborted:
                raise _Aborted
        else:  # acquire | cond_wait | site | start
            self._park(part, op, name)

    def _park(self, part: _Participant, op: str, name: str) -> None:
        with self._m:
            self.log.append(f'{part.name} {op} {name}')
            part.state = 'ready'
            self._sched_evt.set()
        part.gate.wait()
        part.gate.clear()
        if self.aborted:
            raise _Aborted

    # -- scheduler side ------------------------------------------------------

    def run(self, join_timeout_s: float = 30.0) -> None:
        """Drive the schedule to completion (every participant finished),
        deadlock, or step-budget exhaustion."""
        prev_hook = locktrace._sched_hook
        locktrace.set_schedule_hook(self._yield_point)
        try:
            for part in self._parts.values():
                part.thread.start()
            steps = 0
            while True:
                with self._m:
                    states = [p.state for p in self._parts.values()]
                    ready = sorted(n for n, p in self._parts.items() if p.state == 'ready')
                if all(s == 'finished' for s in states):
                    return
                if any(s in ('running', 'new') for s in states):
                    # someone is executing between yield points (or still
                    # starting): wait for the next park/finish
                    self._sched_evt.wait(timeout=10.0)
                    self._sched_evt.clear()
                    continue
                if not ready:
                    # every unfinished participant is blocked on a lock:
                    # a genuine interleaving deadlock under this schedule
                    self.deadlocked = True
                    self.log.append('DEADLOCK')
                    self._abort()
                    return
                steps += 1
                if steps > self.max_steps:
                    self.log.append('STEP-BUDGET')
                    self._abort()
                    raise RuntimeError(
                        f'schedule seed={self.seed} exceeded {self.max_steps} steps (scenario livelock)'
                    )
                pick = self._parts[self.rng.choice(ready)]
                self.log.append(f'grant {pick.name}')
                with self._m:
                    pick.state = 'running'
                pick.gate.set()
        finally:
            locktrace.set_schedule_hook(prev_hook)
            self._join_all(join_timeout_s)

    def _abort(self) -> None:
        self.aborted = True
        for part in self._parts.values():
            part.gate.set()

    def _join_all(self, timeout_s: float) -> None:
        # after the hook is cleared, unwound/granted threads run freely;
        # anything still parked is released by abort semantics
        self.aborted = True
        for part in self._parts.values():
            part.gate.set()
        deadline = time.monotonic() + timeout_s
        for part in self._parts.values():
            part.thread.join(timeout=max(deadline - time.monotonic(), 0.1))

    @property
    def errors(self) -> dict[str, BaseException]:
        return {n: p.error for n, p in self._parts.items() if p.error is not None}

    def log_text(self) -> str:
        return '\n'.join(self.log)


# ---------------------------------------------------------------------------
# scenario plumbing
# ---------------------------------------------------------------------------


@dataclass
class ScenarioResult:
    """Outcome of one scenario under one seed."""

    scenario: str
    seed: int
    log: str
    diagnostics: list[Diagnostic] = field(default_factory=list)
    #: False for scenarios whose step count depends on wall-clock backoff
    #: (their invariants still hold; their logs are not byte-comparable)
    deterministic_log: bool = True

    @property
    def ok(self) -> bool:
        return not self.diagnostics


def _finish(schedule: Schedule, scenario: str, violations: list[str], deterministic_log=True) -> ScenarioResult:
    diags = [Diagnostic(rule='X512', message=f'{scenario}[seed={schedule.seed}]: {v}') for v in violations]
    if schedule.deadlocked:
        diags.append(
            Diagnostic(rule='X513', message=f'{scenario}[seed={schedule.seed}]: all participants blocked')
        )
    for name, err in schedule.errors.items():
        diags.append(
            Diagnostic(
                rule='X512',
                message=f'{scenario}[seed={schedule.seed}]: participant {name} died: {type(err).__name__}: {err}',
            )
        )
    diags.extend(
        Diagnostic(rule=v['rule'], message=f'{scenario}[seed={schedule.seed}]: [{v["thread"]}] {v["message"]}')
        for v in locktrace.locktrace_violations()
    )
    return ScenarioResult(scenario, schedule.seed, schedule.log_text(), diags, deterministic_log)


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------

_pipeline_cache: list = []


def _reference_pipeline():
    """One tiny solved pipeline, shared across every store-scenario seed
    (the scenario exercises the store's coordination, not the solver)."""
    if not _pipeline_cache:
        import numpy as np

        from ..cmvm.api import solve

        kernel = (np.arange(9, dtype=np.float64).reshape(3, 3) % 5) - 2.0
        _pipeline_cache.append(solve(kernel, backend='pure-python', store=False))
    return _pipeline_cache[0]


def scenario_queue(seed: int, inject: str | None = None) -> ScenarioResult:
    """EDF admission queue: producers racing a draining consumer.

    Capacity forces deadline-edf evictions mid-schedule. Invariant: every
    produced request is settled exactly once — served with a result,
    evicted with a structured error, or rejected at push — and never both
    served and evicted (no lost request, no double resolution).
    """
    import numpy as np

    from ..serve.batching import AdmissionQueue, InferRequest, QueueFull

    n_producers, per_producer = 3, 4
    total = n_producers * per_producer
    q = AdmissionQueue(cap_rows=6, policy='deadline-edf')
    stop = threading.Event()
    m = threading.Lock()
    produced: list[InferRequest] = []
    served: list[InferRequest] = []
    rejected: list[InferRequest] = []
    settled = [0]

    def _settle(n: int = 1) -> None:
        with m:
            settled[0] += n
            if settled[0] >= total:
                stop.set()

    def producer(pi: int) -> None:
        for j in range(per_producer):
            # deadlines spaced in whole seconds: EDF comparisons stay
            # deterministic against scheduling jitter
            req = InferRequest(np.zeros((2, 3)), deadline_s=float(10 + ((pi * 7 + j * 3) % 9) * 10))
            with m:
                produced.append(req)
            try:
                victim = q.push(req)
            except QueueFull:
                with m:
                    rejected.append(req)
                _settle()
            else:
                if victim is not None:
                    _settle()  # victim was resolved via set_error by push

    def consumer() -> None:
        while settled[0] < total:
            batch = q.take_batch(max_rows=4, window_s=0.0, stop=stop, poll_s=0.001)
            for req in batch:
                req.set_result(np.zeros((req.n_rows, 1)), served_by='interleave')
                with m:
                    served.append(req)
            _settle(len(batch))

    sched = Schedule(seed)
    for pi in range(n_producers):
        sched.spawn(f'prod{pi}', producer, pi)
    sched.spawn('consumer', consumer)
    sched.run()

    violations: list[str] = []
    if inject == 'double-serve' and served:
        served.append(served[0])  # harness self-test: a double resolution
    evicted = [r for r in produced if r._error is not None and r not in rejected]
    if len(served) + len(evicted) + len(rejected) != total:
        violations.append(
            f'lost request: {len(served)} served + {len(evicted)} evicted + '
            f'{len(rejected)} rejected != {total} produced'
        )
    seen_ids = [r.id for r in served] + [r.id for r in evicted] + [r.id for r in rejected]
    if len(set(seen_ids)) != len(seen_ids):
        violations.append('double resolution: a request was settled more than once')
    for req in served:
        if req._error is not None:
            violations.append(f'request {req.id} both served and evicted')
    if q.depth_requests() != 0:
        violations.append(f'{q.depth_requests()} requests left in the queue')
    return _finish(sched, 'queue', violations)


def scenario_lease(seed: int, inject: str | None = None) -> ScenarioResult:
    """Lease claim/steal race on an expired lease: exactly one winner.

    Every claimant finds the lease expired and races the steal protocol;
    the ``lease.steal`` site parks each of them between the expiry read
    and the steal-lock attempt — the exact window the single-winner rename
    must protect. ``inject='double-claim'`` makes ``exclusive_create`` lie
    (every O_EXCL attempt "succeeds"), proving the invariant catches a
    broken mutual exclusion as X512.
    """
    import json

    from ..reliability import lease as lease_mod

    n_claimants = 4
    winners: list = []
    m = threading.Lock()

    with tempfile.TemporaryDirectory() as tmp:
        lease_dir = f'{tmp}/leases'
        # a dead owner's lease: expired beyond any grace
        stale = lease_mod.Lease(
            path=lease_mod.Path(lease_dir) / 'work.lease',
            key='work',
            owner='dead-owner',
            ttl_s=1.0,
            expires_at=time.time() - 60.0,
        )
        lease_mod.Path(lease_dir).mkdir(parents=True)
        stale.path.write_text(json.dumps(stale._doc()))

        def claim(ci: int) -> None:
            got = lease_mod.claim_lease(lease_dir, 'work', owner=f'claimant-{ci}', ttl_s=30.0, grace_s=0.0)
            if got is not None:
                with m:
                    winners.append(got)

        real_excl = lease_mod.exclusive_create
        if inject == 'double-claim':

            def lying_excl(path, payload):
                real_excl(path, payload)
                return True  # mutual exclusion broken on purpose

            lease_mod.exclusive_create = lying_excl
        try:
            sched = Schedule(seed)
            for ci in range(n_claimants):
                sched.spawn(f'claim{ci}', claim, ci)
            sched.run()
        finally:
            lease_mod.exclusive_create = real_excl

    violations: list[str] = []
    if len(winners) != 1:
        violations.append(f'{len(winners)} claimants won the expired lease (expected exactly 1)')
    return _finish(sched, 'lease', violations)


def scenario_store(seed: int, inject: str | None = None) -> ScenarioResult:
    """Single-flight winner death: the first winner's cold solve dies; the
    key must be re-solved exactly once and every other caller must get the
    published result.

    The dead winner raises :class:`SolveTimeout` (no negative marker), its
    lease is released in the winner's ``finally``, and the next claimant
    through the loop becomes the new winner. Invariants: exactly 2 cold
    solves (the death + the recovery), exactly 1 caller sees the death,
    everyone else returns the bit-exact published pipeline.
    """
    from ..reliability.errors import SolveTimeout
    from ..store.solution_store import SolutionStore

    pipeline = _reference_pipeline()

    n_callers = 3
    m = threading.Lock()
    cold_calls = [0]
    outcomes: dict[str, object] = {}

    with tempfile.TemporaryDirectory() as tmp:
        store = SolutionStore(tmp, lease_ttl_s=10.0)
        key = 'deadbeef' * 8

        def cold_solve():
            with m:
                cold_calls[0] += 1
                first = cold_calls[0] == 1
            if first:
                raise SolveTimeout('injected winner death: search budget blown')
            return pipeline

        def caller(ci: int) -> None:
            try:
                outcomes[f'c{ci}'] = store.solve_through(key, cold_solve)
            except SolveTimeout as e:
                outcomes[f'c{ci}'] = e

        sched = Schedule(seed)
        for ci in range(n_callers):
            sched.spawn(f'call{ci}', caller, ci)
        sched.run()

        violations: list[str] = []
        if inject == 'double-solve':
            cold_calls[0] += 1  # harness self-test
        deaths = [v for v in outcomes.values() if isinstance(v, SolveTimeout)]
        results = [v for v in outcomes.values() if not isinstance(v, BaseException)]
        if cold_calls[0] != 2:
            violations.append(f'{cold_calls[0]} cold solves for one key (expected 2: death + recovery)')
        if len(deaths) != 1:
            violations.append(f'{len(deaths)} callers saw the winner death (expected exactly 1)')
        if len(results) != n_callers - 1:
            violations.append(f'{len(results)}/{n_callers - 1} surviving callers got a pipeline')
        blobs = {str(sorted(r.to_dict().items())) for r in results}
        if len(blobs) > 1:
            violations.append('surviving callers disagree on the published pipeline')
        if store.lookup(key) is None:
            violations.append('recovery result was never published')
    return _finish(sched, 'store', violations, deterministic_log=False)


def scenario_router(seed: int, inject: str | None = None) -> ScenarioResult:
    """Hedged legs with a mid-flight cancel: inflight bookkeeping returns
    to zero and exactly the uncancelled winner's bytes count.

    Two legs race canned transports against one replica's shared state
    while a canceller revokes the hedge at an arbitrary point in the
    schedule; every leg still deposits exactly one outcome (cancelled legs
    must not vanish — the router's outcome loop accounts for them).
    """
    import queue as queue_mod

    from ..reliability.breaker import reset_all_breakers
    from ..serve.router import _Leg, _Replica

    reset_all_breakers()
    rep = _Replica('r0', 'http://127.0.0.1:1')
    outcomes: 'queue_mod.Queue[dict]' = queue_mod.Queue()

    class _CannedLeg(_Leg):
        def __init__(self, body: bytes, sched_ref):
            super().__init__(rep, 'POST', '/v1/infer', b'{}', timeout_s=1.0, outcomes=outcomes)
            self._body = body
            self._sched = sched_ref

        def _transport(self) -> dict:
            self._sched[0].checkpoint('leg.transport')
            return {'status': 200, 'body': self._body, 'headers': {}}

    sched_ref: list = [None]
    leg_a = _CannedLeg(b'A', sched_ref)
    leg_b = _CannedLeg(b'B', sched_ref)

    def canceller() -> None:
        leg_b.cancel()

    sched = Schedule(seed)
    sched_ref[0] = sched
    sched.spawn('legA', leg_a.run)
    sched.spawn('legB', leg_b.run)
    sched.spawn('cancel', canceller)
    sched.run()

    violations: list[str] = []
    outs = []
    while not outcomes.empty():
        outs.append(outcomes.get_nowait())
    if inject == 'lost-leg' and outs:
        outs.pop()  # harness self-test: a leg's outcome vanished
    if len(outs) != 2:
        violations.append(f'{len(outs)} outcomes from 2 legs (a leg was lost or double-counted)')
    with rep.lock:
        inflight = rep.inflight
    if inflight != 0:
        violations.append(f'replica inflight count is {inflight} after all legs resolved (leak)')
    winners = [o for o in outs if not o['leg'].cancelled and o.get('status') == 200]
    if not any(o['leg'] is leg_a for o in winners):
        violations.append('the uncancelled primary leg is missing from the winner set')
    return _finish(sched, 'router', violations)


def scenario_fleet(seed: int, inject: str | None = None) -> ScenarioResult:
    """Slot adoption race: the slot's previous holder is dead (expired
    lease); concurrent announcers must adopt it exactly once."""
    import json

    from ..reliability import lease as lease_mod
    from ..serve.fleet import _LEASE_PREFIX, announce_replica

    n_announcers = 3
    announcements: list = []
    m = threading.Lock()

    with tempfile.TemporaryDirectory() as tmp:
        lease_dir = lease_mod.Path(tmp) / 'leases'
        lease_dir.mkdir(parents=True)
        stale = lease_mod.Lease(
            path=lease_dir / f'{_LEASE_PREFIX}slot0.lease',
            key=f'{_LEASE_PREFIX}slot0',
            owner='dead-replica',
            ttl_s=1.0,
            expires_at=time.time() - 60.0,
        )
        stale.path.write_text(json.dumps(stale._doc()))

        def announce(ai: int) -> None:
            got = announce_replica(tmp, 'slot0', url=f'http://127.0.0.1:{9000 + ai}', ttl_s=30.0)
            if got is not None:
                with m:
                    announcements.append(got)

        sched = Schedule(seed)
        for ai in range(n_announcers):
            sched.spawn(f'ann{ai}', announce, ai)
        sched.run()

        violations: list[str] = []
        if len(announcements) != 1:
            violations.append(f'{len(announcements)} announcers adopted the expired slot (expected exactly 1)')
        for ann in announcements:
            ann.close()
    return _finish(sched, 'fleet', violations)


SCENARIOS = {
    'queue': scenario_queue,
    'lease': scenario_lease,
    'store': scenario_store,
    'router': scenario_router,
    'fleet': scenario_fleet,
}


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def run_scenario(name: str, seed: int, inject: str | None = None) -> ScenarioResult:
    """One scenario under one seed, with the lock tracer armed and reset."""
    was_armed = locktrace.locktrace_enabled()
    locktrace.enable_locktrace()
    locktrace.reset_locktrace()
    try:
        return SCENARIOS[name](seed, inject=inject)
    finally:
        locktrace.reset_locktrace()
        if not was_armed:
            locktrace.disable_locktrace()


def run_suite(
    scenarios: list[str] | None = None,
    seeds: int = _DEFAULT_SEEDS,
    seed_base: int = 0,
) -> VerifyResult:
    """Every scenario × ``seeds`` schedules; diagnostics from failing seeds
    only (a failing seed's log is the reproduction: re-run it by name)."""
    diags: list[Diagnostic] = []
    for name in scenarios or sorted(SCENARIOS):
        for seed in range(seed_base, seed_base + seeds):
            result = run_scenario(name, seed)
            diags.extend(result.diagnostics)
    return VerifyResult(diags, target='interleave')


def add_interleave_args(parser) -> None:
    parser.add_argument('--scenario', action='append', choices=sorted(SCENARIOS), help='scenario(s) to run (default: all)')
    parser.add_argument('--seeds', type=int, default=_DEFAULT_SEEDS, help='schedules per scenario')
    parser.add_argument('--seed-base', type=int, default=0, help='first seed')
    parser.add_argument('--show-log', type=int, default=None, metavar='SEED', help='print one seed\'s schedule log')
    parser.add_argument('--json', action='store_true', help='machine-readable result')


def interleave_main(args) -> int:
    if args.show_log is not None:
        for name in args.scenario or sorted(SCENARIOS):
            result = run_scenario(name, args.show_log)
            print(f'--- {name} seed={args.show_log} ok={result.ok}')
            print(result.log)
        return 0
    result = run_suite(args.scenario, seeds=args.seeds, seed_base=args.seed_base)
    print(result.to_json(indent=1) if args.json else result.format_text())
    return 0 if result.ok else 1


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description='deterministic interleaving harness')
    add_interleave_args(parser)
    return interleave_main(parser.parse_args(argv))


if __name__ == '__main__':
    raise SystemExit(main())
