"""Dead-code & cost lint pass.

- **D301 dead-op** — an op whose result no output transitively reads. Copy
  ops (opcode -1) are exempt: keeping unread input fetches is how the IR
  preserves a program's input arity (``dead_statement_elimination``'s
  ``keep_dead_inputs``), and the CMVM solver always emits one per input.
- **D302 cost-model** — negative or non-finite latency/cost poisons every
  aggregate metric (``CombLogic.cost``, retiming cutoffs), so it is an error.
- **D303 latency-monotone** — an op scheduled before one of its operands
  finishes; the cost model guarantees ``latency >= max(operand latencies)``,
  a violation means the latency fields were corrupted or miscomputed.
"""

from __future__ import annotations

from math import isfinite

from ..ir.comb import CombLogic
from ..ir.optable import COPY_OPCODES
from .diagnostics import Diagnostic
from .wellformed import op_operands

_EPS = 1e-9


def live_ops(comb: CombLogic) -> bytearray:
    """Backward reachability from the output bindings (1 = live)."""
    n = len(comb.ops)
    live = bytearray(n)
    stack = [int(i) for i in comb.out_idxs if 0 <= int(i) < n]
    for i in stack:
        live[i] = 1
    while stack:
        i = stack.pop()
        for j in op_operands(comb.ops[i]):
            if 0 <= j < n and not live[j]:
                live[j] = 1
                stack.append(j)
    return live


def check_deadcode(
    comb: CombLogic,
    stage: int | None = None,
    skip_ops: frozenset[int] = frozenset(),
) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    n = len(comb.ops)
    live = live_ops(comb)

    for i, op in enumerate(comb.ops):
        if i in skip_ops:
            continue

        def emit(rule: str, message: str, op_index: int, _oc=op.opcode):
            diags.append(Diagnostic(rule, message, op_index=op_index, stage=stage, opcode=_oc))

        for name, v in (('latency', op.latency), ('cost', op.cost)):
            if not isinstance(v, (int, float)) or not isfinite(v):
                emit('D302', f'op {name} is {v!r}', i)
            elif v < 0:
                emit('D302', f'op {name} is negative ({v})', i)

        if not live[i] and op.opcode not in COPY_OPCODES:
            emit('D301', f'op result (opcode {op.opcode}) never reaches an output', i)

        if isinstance(op.latency, (int, float)) and isfinite(op.latency):
            for j in op_operands(op):
                if 0 <= j < min(i, n) and j not in skip_ops:
                    dep = comb.ops[j].latency
                    if isinstance(dep, (int, float)) and isfinite(dep) and op.latency + _EPS < dep:
                        emit(
                            'D303',
                            f'op latency {op.latency} is below operand slot {j} latency {dep}',
                            i,
                        )

    return diags


__all__ = ['check_deadcode', 'live_ops']
