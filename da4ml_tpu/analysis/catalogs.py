"""Drift gates for the environment-knob and metric catalogs.

Two more instances of the library's "one declarative table, lint the
world against it" discipline (docs/analysis.md#drift-lints):

- **Knobs** — :data:`KNOBS` declares every ``DA4ML_*`` environment
  variable the library reads, with a one-line meaning. A regex scan of
  the package finds the names actually consulted; an undocumented knob
  or a stale table entry fails CI (X524/X525). The docs/api.md knob
  table is *generated* from this table (``analysis.docgen``), so the
  table, the code, and the docs cannot drift apart independently.
- **Metrics** — :data:`da4ml_tpu.telemetry.catalog.METRICS` declares
  every metric family with its OpenMetrics HELP text. An AST scan finds
  every ``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` /
  ``timer(...)`` emission site; an emitted name missing from the
  catalog, a catalogued family with no emission site, a *dynamic*
  (f-string) emission in a module not registered in ``DYNAMIC_SITES``,
  or a catalogued family missing its docs/telemetry.md row fails CI
  (X520–X523).

CLI: ``python -m da4ml_tpu.analysis.catalogs [--json]`` (the CI lint
job); also folded into ``da4ml-tpu verify --concurrency``.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from .diagnostics import Diagnostic, VerifyResult

__all__ = ['KNOBS', 'lint_knobs', 'lint_metrics', 'render_knob_table', 'scan_knobs', 'scan_metrics']

#: every ``DA4ML_*`` environment variable the library reads -> meaning.
#: docs/api.md#environment-knobs is generated from this table.
KNOBS: dict[str, str] = {
    'DA4ML_DIST_CONNECT_RETRIES': 'distributed coordinator connect attempts before giving up',
    'DA4ML_DIST_CONNECT_TIMEOUT_S': 'per-attempt distributed coordinator connect timeout',
    'DA4ML_FAULT_INJECT': 'fault-injection plan, `site=mode[:count[:arg]]` entries (reliability.md)',
    'DA4ML_FUSED_L': 'pin the fused-CSE tile length L instead of auto-tuning it',
    'DA4ML_HEALTH_STALL_S': 'heartbeat age that flips /healthz to degraded',
    'DA4ML_INTERLEAVE_SEEDS': 'schedules per primitive in the deterministic interleaving suite (default 200)',
    'DA4ML_JAX_ASYNC_EMIT': '`0` emits search buckets serially instead of overlapping device rounds',
    'DA4ML_JAX_CACHE': 'legacy alias of `DA4ML_XLA_CACHE`',
    'DA4ML_JAX_DEBUG': 'verbose device-search logging + sanity checks',
    'DA4ML_JAX_DEVICE_RESIDENT': '`0` restores the host-state rung loop (per-rung fetch/re-upload)',
    'DA4ML_JAX_EINSUM_DTYPE': '`bf16`/`f32` digit-einsum element type (default bf16 on TPU)',
    'DA4ML_JAX_EXPORT_CACHE': '`0` disables the jax.export artifact runner cache',
    'DA4ML_JAX_HBM_BUDGET': 'device-memory budget (bytes) steering search chunking',
    'DA4ML_JAX_INFER_CHUNKS': 'fixed inference sample-axis chunk count override',
    'DA4ML_JAX_INFER_CHUNK_BYTES': 'inference chunking byte budget (alternative to a fixed count)',
    'DA4ML_JAX_MESH': '`0` never auto-mesh, `1` force the multi-device mesh',
    'DA4ML_JAX_PMAX': 'cap on the decomposition power P explored by the device search',
    'DA4ML_JAX_PREWARM': '`0` disables the background shape-class prewarm compiler',
    'DA4ML_JAX_SELECT': 'selection kernel: `top4` | `xla` | `fused`',
    'DA4ML_JAX_TOPK': 'device search top-k width override',
    'DA4ML_JAX_TOPK_IMPL': 'top-k implementation: `sort` (fused lax.top_k) or `scan`',
    'DA4ML_LOCKTRACE': '`1` arms the runtime lock-order tracer (locktrace.LOCK_TABLE ranks)',
    'DA4ML_LOG_LEVEL': 'library log level (`debug`/`info`/`warning`/...)',
    'DA4ML_METRICS_PORT': 'start the observability endpoint on this port (`0` = ephemeral)',
    'DA4ML_NO_NATIVE_BUILD': '`1` skips building the native extension (pure-python/jax only)',
    'DA4ML_PALLAS_AUTOTUNE': '`1` forces the pallas candidate into autotune races even on interpret-only platforms',
    'DA4ML_PALLAS_INTERPRET': 'force (`1`) / forbid (`0`) pallas interpret mode instead of auto-detecting by platform',
    'DA4ML_PALLAS_VMEM': 'VMEM budget (bytes) the pallas mega-kernel sizes its sample block against',
    'DA4ML_PROFILE': 'arm `jax.profiler` and write device profiles to this directory',
    'DA4ML_RUN_AUTOTUNE': '`0` disables runtime execution-mode autotuning',
    'DA4ML_RUN_AUTOTUNE_BATCH': 'sample rows per autotune probe',
    'DA4ML_RUN_AUTOTUNE_MIN_OPS': 'minimum program size before autotune probes run',
    'DA4ML_RUN_DONATE': '`0` disables input-buffer donation on dispatch',
    'DA4ML_RUN_MODE': 'force the DAIS execution mode instead of resolving it',
    'DA4ML_RUN_MODEL_SHARD': 'model-axis sharding policy: `0`/`off`, `auto` (race anywhere), `on`/`1` or an integer K>=2 (force); default races on TPU only',
    'DA4ML_RUN_SHARD': '`0` disables sample-axis sharding across the mesh',
    'DA4ML_SEARCH_TRACE_DIR': 'write beam solve traces here (learned-ranker training data)',
    'DA4ML_SERVE_MAX_BODY_BYTES': 'HTTP request-body ceiling (rejected 413 before buffering)',
    'DA4ML_SERVE_STALL_S': 'serve queue age that flips /healthz to degraded',
    'DA4ML_SOLUTION_STORE': 'default solution-store root (`resolve_store(None)`)',
    'DA4ML_SOLVE_FALLBACK': '`0` disables the solve backend fallback chain (fail fast)',
    'DA4ML_STORE_LOCAL_TIER': 'local-disk tier root layered in front of the shared store',
    'DA4ML_STORE_MEM_ENTRIES': 'in-process LRU tier capacity (entries)',
    'DA4ML_STORE_NEGATIVE_TTL_S': 'negative-marker lifetime after terminal solve failures',
    'DA4ML_STORE_RO': '`1` opens the solution store read-only (no publishes, no leases)',
    'DA4ML_TRACE': 'trace sink path (`.jsonl` streaming, else Chrome trace JSON)',
    'DA4ML_VERIFY': '`1` verifies every solve post-hoc; `0` bypasses codegen preconditions',
    'DA4ML_XLA_CACHE': 'persistent XLA compile cache dir (`0` disables)',
}

#: modules excluded from the metric emission scan: the registry
#: implementation itself (its accessors take caller-supplied names)
_METRIC_SCAN_SKIP = frozenset({'da4ml_tpu/telemetry/metrics.py'})

_METRIC_FNS = frozenset({'counter', 'gauge', 'histogram', 'timer'})
_KNOB_RE = re.compile(r'DA4ML_[A-Z0-9_]+')


def _package_root() -> Path:
    return Path(__file__).resolve().parents[1]


def _py_files(root: Path):
    for path in sorted(root.rglob('*.py')):
        yield path, path.relative_to(root.parent).as_posix()


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


def scan_knobs(root: Path | None = None) -> dict[str, list[str]]:
    """Every ``DA4ML_*`` name appearing in the package -> modules using it.

    A plain text scan on purpose: knobs are read through ``os.environ``,
    ``os.getenv`` and doc strings alike, and a knob mentioned only in a
    docstring still promises behavior the table must document.
    """
    root = root or _package_root()
    found: dict[str, list[str]] = {}
    for path, rel in _py_files(root):
        if rel == 'da4ml_tpu/analysis/catalogs.py':
            continue  # the table itself
        for name in set(_KNOB_RE.findall(path.read_text())):
            found.setdefault(name, []).append(rel)
    return found


def lint_knobs(root: Path | None = None) -> VerifyResult:
    found = scan_knobs(root)
    diags: list[Diagnostic] = []
    for name in sorted(set(found) - set(KNOBS)):
        diags.append(
            Diagnostic(
                rule='X524',
                message=f'{name} (read in {found[name][0]}) is not documented in catalogs.KNOBS',
            )
        )
    for name in sorted(set(KNOBS) - set(found)):
        diags.append(
            Diagnostic(rule='X525', message=f'KNOBS entry {name} has no remaining reader in the library')
        )
    return VerifyResult(diags, target='knob-catalog')


def render_knob_table() -> str:
    """The generated docs/api.md environment-knob table."""
    lines = ['| knob | meaning |', '|---|---|']
    for name, meaning in sorted(KNOBS.items()):
        lines.append(f'| `{name}` | {meaning} |')
    return '\n'.join(lines)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def _call_fn_name(node: ast.Call) -> str | None:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def scan_metrics(root: Path | None = None) -> tuple[dict[str, list[str]], list[tuple[str, int, str]]]:
    """(literal emissions -> modules, dynamic emission sites).

    Dynamic sites are ``(module, lineno, repr)`` for every metric call
    whose name argument is not a string literal.
    """
    root = root or _package_root()
    literal: dict[str, list[str]] = {}
    dynamic: list[tuple[str, int, str]] = []
    for path, rel in _py_files(root):
        if rel in _METRIC_SCAN_SKIP:
            continue
        tree = ast.parse(path.read_text(), filename=rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or _call_fn_name(node) not in _METRIC_FNS or not node.args:
                continue
            arg = node.args[0]
            branches = [arg.body, arg.orelse] if isinstance(arg, ast.IfExp) else [arg]
            for branch in branches:
                if isinstance(branch, ast.Constant) and isinstance(branch.value, str):
                    literal.setdefault(branch.value, []).append(rel)
                else:  # f-string/variable/call — anything we cannot resolve
                    dynamic.append((rel, node.lineno, ast.unparse(branch)))
    return literal, dynamic


def lint_metrics(root: Path | None = None, docs_root: Path | None = None) -> VerifyResult:
    from ..telemetry.catalog import DYNAMIC_SITES, METRICS, fold_family

    literal, dynamic = scan_metrics(root)
    diags: list[Diagnostic] = []

    for name in sorted(set(literal)):
        if fold_family(name) not in METRICS:
            diags.append(
                Diagnostic(
                    rule='X520',
                    message=(
                        f'metric {name!r} (emitted in {literal[name][0]}) has no telemetry.catalog.METRICS '
                        f'entry — give it a HELP string'
                    ),
                )
            )

    emitted = {fold_family(name) for name in literal}
    for families in DYNAMIC_SITES.values():
        emitted.update(families)
    for name in sorted(set(METRICS) - emitted):
        diags.append(
            Diagnostic(rule='X521', message=f'METRICS entry {name!r} has no emission site left in the library')
        )

    for rel, lineno, expr in sorted(dynamic):
        if rel not in DYNAMIC_SITES:
            diags.append(
                Diagnostic(
                    rule='X522',
                    message=(
                        f'{rel}:{lineno}: dynamic metric name `{expr}` in a module not registered in '
                        f'telemetry.catalog.DYNAMIC_SITES'
                    ),
                )
            )
    for rel, families in DYNAMIC_SITES.items():
        if not any(site_rel == rel for site_rel, _, _ in dynamic):
            diags.append(
                Diagnostic(rule='X521', message=f'DYNAMIC_SITES entry {rel!r} has no dynamic emission left')
            )
        for fam in families:
            if fam not in METRICS:
                diags.append(
                    Diagnostic(rule='X520', message=f'DYNAMIC_SITES family {fam!r} ({rel}) missing from METRICS')
                )

    docs = (docs_root or _package_root().parent / 'docs') / 'telemetry.md'
    try:
        doc_text = docs.read_text()
    except OSError:
        doc_text = None  # installed without docs: the doc-row check is a repo gate
    if doc_text is not None:
        for name in sorted(METRICS):
            # folded families may be documented as `family.<label>` rows
            if f'`{name}`' not in doc_text and f'`{name}.' not in doc_text:
                diags.append(
                    Diagnostic(
                        rule='X523',
                        message=f'metric family {name!r} has no `{name}` row/mention in docs/telemetry.md',
                    )
                )
    return VerifyResult(diags, target='metric-catalog')


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def lint_catalogs() -> VerifyResult:
    """Both gates as one result (the CI lint job entry point)."""
    knobs, metrics = lint_knobs(), lint_metrics()
    return VerifyResult(knobs.diagnostics + metrics.diagnostics, target='catalogs')


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog='python -m da4ml_tpu.analysis.catalogs', description=__doc__)
    parser.add_argument('--json', action='store_true')
    args = parser.parse_args(argv)
    result = lint_catalogs()
    print(result.to_json(indent=1) if args.json else result.format_text())
    return 0 if result.ok else 1


if __name__ == '__main__':
    raise SystemExit(main())
