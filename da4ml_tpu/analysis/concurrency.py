"""Static concurrency lint (``da4ml-tpu verify --concurrency``).

The declarative lock/thread registry (:mod:`da4ml_tpu.reliability.locktrace`)
is the single source of truth for the host plane's synchronization. This
lint AST-scans the package and fails when the source drifts from the
tables, in the same spirit as the opcode drift lint (driftlint.py):

- **X501** every ``threading.Lock/RLock/Condition`` construction must go
  through ``make_lock``/``make_condition`` with a registered name — or,
  for the telemetry bootstrap layer, match a ``traced=False`` table entry
  at the declared module + attribute.
- **X502 / X506** table entries whose construction site vanished are
  stale — the tables cannot rot.
- **X503** lexically nested ``with``-acquisitions must strictly ascend
  the declared rank order (the total-order deadlock-freedom argument;
  cross-function nesting is the runtime tracer's job).
- **X504** no HTTP / subprocess / jax-dispatch / sleep call while
  lexically holding a lock, unless the entry declares ``io_ok`` with a
  reason.
- **X505 / X507** every ``threading.Thread(...)`` must carry a ``name=``
  whose static prefix resolves in ``THREAD_TABLE``, and daemon threads
  must have a documented shutdown/drain path.

Violations are structured :class:`Diagnostic` objects (X5xx rules,
docs/analysis.md catalog), so the CLI, CI and ``/statusz`` consume the
same shapes as the IR verifier.
"""

from __future__ import annotations

import ast
from pathlib import Path

from ..reliability.locktrace import LOCK_TABLE, THREAD_TABLE, LockSpec
from .diagnostics import Diagnostic, VerifyResult

#: modules allowed to construct raw threading primitives wholesale, with the
#: reason (driftlint-style allowlist; growing it is a reviewed act).
RAW_ALLOWLIST: dict[str, str] = {
    'da4ml_tpu/reliability/locktrace.py': 'the lock factory itself (its internal graph lock must be raw)',
    'da4ml_tpu/analysis/interleave.py': 'the deterministic scheduler: its gates/thread machinery must not be traced',
}

#: call names that mean blocking I/O or device dispatch under a lock (X504).
_IO_CALLS = frozenset(
    {
        'urlopen',
        'getresponse',
        'HTTPConnection',
        'Popen',
        'check_call',
        'check_output',
        'communicate',
        'serve_forever',
        'block_until_ready',
        'device_put',
    }
)
#: dotted calls that mean the same (module alias -> attr).
_IO_DOTTED = frozenset({('time', 'sleep'), ('subprocess', 'run'), ('jax', 'jit')})

_LOCKISH = ('lock', 'cond')


def _attr_form(node: ast.expr) -> str | None:
    """The table's attr-form for an expression: ``.x`` for attribute access
    (``self._lock``, ``state.lock``), bare ``x`` for a module-level name."""
    if isinstance(node, ast.Attribute):
        return '.' + node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _looks_lockish(form: str | None) -> bool:
    return form is not None and any(form.lower().rstrip('s').endswith(k) for k in _LOCKISH)


def _call_name(node: ast.expr) -> str | None:
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name):
            return node.func.id
        if isinstance(node.func, ast.Attribute):
            return node.func.attr
    return None


def _dotted(node: ast.expr) -> tuple[str, str] | None:
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and isinstance(node.func.value, ast.Name)
    ):
        return (node.func.value.id, node.func.attr)
    return None


def _is_super_call(node: ast.Call) -> bool:
    return (
        isinstance(node.func, ast.Attribute)
        and isinstance(node.func.value, ast.Call)
        and isinstance(node.func.value.func, ast.Name)
        and node.func.value.func.id == 'super'
    )


def _walk_no_funcs(stmts: list[ast.stmt]):
    """Walk statements without descending into nested function/lambda
    bodies — code in a nested def does not run under the enclosing lock."""
    stack: list[ast.AST] = list(stmts)
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


def _static_prefix(node: ast.expr) -> str | None:
    """The constant prefix of a thread-name expression (Constant or the
    leading literal of an f-string)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return None


class _ModuleIndex:
    """Per-module resolution of attr-forms to LOCK_TABLE entries."""

    def __init__(self, rel: str):
        self.rel = rel
        self.by_form: dict[str, LockSpec] = {}
        for spec in LOCK_TABLE.values():
            if spec.module == rel or rel in spec.shared_with:
                for form in spec.attrs:
                    self.by_form[form] = spec

    def resolve(self, node: ast.expr) -> LockSpec | None:
        form = _attr_form(node)
        return self.by_form.get(form) if form is not None else None


class _Scanner(ast.NodeVisitor):
    def __init__(self, rel: str, lines: list[str]):
        self.rel = rel
        self.lines = lines
        self.index = _ModuleIndex(rel)
        self.diags: list[Diagnostic] = []
        self.make_lock_names: list[tuple[str, int]] = []  # (name, lineno)
        self.thread_prefixes: list[str] = []
        self.raw_locks: list[tuple[str | None, int, str]] = []  # (target form, lineno, kind)
        self._with_stack: list[LockSpec] = []

    def _snippet(self, node: ast.AST) -> str:
        i = getattr(node, 'lineno', 1) - 1
        return self.lines[i].strip() if i < len(self.lines) else ''

    def _diag(self, rule: str, node: ast.AST, msg: str) -> None:
        self.diags.append(Diagnostic(rule=rule, message=f'{self.rel}:{getattr(node, "lineno", "?")}: {msg}'))

    # -- constructions -------------------------------------------------------

    def _check_construction(self, node: ast.Call, target_form: str | None) -> None:
        """One threading.Lock/RLock/Condition() call: raw constructions are
        only legal at a declared traced=False site."""
        kind = _call_name(node)
        dotted = _dotted(node)
        if dotted and dotted[0] not in ('threading', '_threading'):
            return
        spec = self.index.by_form.get(target_form) if target_form else None
        if spec is not None and not spec.traced:
            self.make_lock_names.append((spec.name, node.lineno))
            return
        self._diag(
            'X501',
            node,
            f'raw threading.{kind}() construction — use locktrace.make_lock/make_condition with a '
            f'LOCK_TABLE entry (or declare a traced=False bootstrap entry): {self._snippet(node)}',
        )

    def _check_make_lock(self, node: ast.Call) -> None:
        if not node.args or not isinstance(node.args[0], ast.Constant) or not isinstance(node.args[0].value, str):
            self._diag('X501', node, f'make_lock/make_condition requires a literal registered name: {self._snippet(node)}')
            return
        name = node.args[0].value
        spec = LOCK_TABLE.get(name)
        if spec is None:
            self._diag('X501', node, f'make_lock({name!r}): name not in locktrace.LOCK_TABLE')
            return
        if spec.module != self.rel:
            self._diag(
                'X501',
                node,
                f'make_lock({name!r}) constructed outside its declared owning module ({spec.module})',
            )
            return
        self.make_lock_names.append((name, node.lineno))

    def _check_thread(self, node: ast.Call) -> None:
        name_kw = next((kw.value for kw in node.keywords if kw.arg == 'name'), None)
        daemon = any(
            kw.arg == 'daemon' and isinstance(kw.value, ast.Constant) and kw.value.value is True
            for kw in node.keywords
        )
        if name_kw is None:
            self._diag('X505', node, f'Thread() without a name= (every library thread is registered by prefix): {self._snippet(node)}')
            return
        prefix = _static_prefix(name_kw)
        if prefix is None:
            self._diag('X505', node, f'Thread name is not statically prefixed (use a literal or f-string with a literal head): {self._snippet(node)}')
            return
        # longest table prefix the static name head extends; when the head is
        # itself shorter than every table prefix (a bare f-string stem), fall
        # back to the longest table prefix it is a stem of
        spec = None
        for ts in THREAD_TABLE.values():
            if prefix.startswith(ts.prefix) and (spec is None or len(ts.prefix) > len(spec.prefix)):
                spec = ts
        if spec is None:
            for ts in THREAD_TABLE.values():
                if ts.prefix.startswith(prefix) and (spec is None or len(ts.prefix) > len(spec.prefix)):
                    spec = ts
        if spec is None:
            self._diag('X505', node, f'Thread name prefix {prefix!r} has no locktrace.THREAD_TABLE entry')
            return
        if spec.module != self.rel:
            self._diag('X505', node, f'Thread prefix {spec.prefix!r} constructed outside its declared module ({spec.module})')
            return
        if daemon and (not spec.shutdown or spec.shutdown.strip().lower() in ('none', '')):
            self._diag('X507', node, f'daemon thread {spec.prefix!r} declares no shutdown/drain path in THREAD_TABLE')
        self.thread_prefixes.append(spec.prefix)

    def visit_Assign(self, node: ast.Assign):
        self._maybe_construction(node.value, node.targets[0] if len(node.targets) == 1 else None)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self._maybe_construction(node.value, node.target)
        self.generic_visit(node)

    def _maybe_construction(self, value: ast.expr, target: ast.expr | None) -> None:
        if not isinstance(value, ast.Call):
            return
        cname = _call_name(value)
        if cname in ('Lock', 'RLock', 'Condition'):
            dotted = _dotted(value)
            if dotted is None or dotted[0] in ('threading', '_threading'):
                value._lt_handled = True  # type: ignore[attr-defined]
                self._check_construction(value, _attr_form(target) if target is not None else None)

    def visit_Call(self, node: ast.Call):
        cname = _call_name(node)
        dotted = _dotted(node)
        if cname in ('make_lock', '_make_lock', 'make_condition'):
            # conditions constructed over an existing lock only re-register it
            if cname != 'make_condition' or not (len(node.args) > 1 or any(k.arg == 'lock' for k in node.keywords)):
                self._check_make_lock(node)
        elif cname == 'Thread':
            if dotted is None or dotted[0] in ('threading', '_threading'):
                self._check_thread(node)
        elif cname == '__init__' and _is_super_call(node):
            # Thread subclasses register through super().__init__(name=...)
            if any(kw.arg == 'name' for kw in node.keywords):
                prefix = _static_prefix(next(kw.value for kw in node.keywords if kw.arg == 'name'))
                if prefix is not None and prefix.startswith('da4ml-'):
                    self._check_thread(node)
        elif cname in ('Lock', 'RLock', 'Condition'):
            if dotted is not None and dotted[0] in ('threading', '_threading'):
                # a construction not captured by visit_Assign (argument,
                # default, field factory): raw and unanchored
                if not getattr(node, '_lt_handled', False):
                    self._check_construction(node, None)
        self.generic_visit(node)

    # -- nesting + IO-under-lock --------------------------------------------

    def visit_With(self, node: ast.With):
        specs = []
        for item in node.items:
            expr = item.context_expr
            spec = self.index.resolve(expr)
            if spec is None and _looks_lockish(_attr_form(expr)):
                self._diag(
                    'X501',
                    node,
                    f'`with {self._snippet(node).removeprefix("with ").rstrip(":")}`: lock-like context '
                    f'manager not resolvable to a LOCK_TABLE entry for this module',
                )
            if spec is not None:
                for held in self._with_stack:
                    if held.rank >= spec.rank:
                        self._diag(
                            'X503',
                            node,
                            f'acquires {spec.name!r} (rank {spec.rank}) while lexically holding '
                            f'{held.name!r} (rank {held.rank}) — nested acquisition must ascend rank',
                        )
                specs.append(spec)
        self._with_stack.extend(specs)
        if specs and not all(s.io_ok for s in self._with_stack):
            held = ', '.join(s.name for s in self._with_stack)
            for sub in _walk_no_funcs(node.body):
                if isinstance(sub, ast.Call):
                    cname = _call_name(sub)
                    if cname in _IO_CALLS or _dotted(sub) in _IO_DOTTED:
                        self._diag(
                            'X504',
                            sub,
                            f'{cname} called while holding {held} — move the I/O outside the lock '
                            f'or declare io_ok with a reason in LOCK_TABLE: {self._snippet(sub)}',
                        )
        self.generic_visit(node)
        del self._with_stack[len(self._with_stack) - len(specs):]


def _scan_source(rel: str, source: str) -> _Scanner:
    scanner = _Scanner(rel, source.splitlines())
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return scanner
    scanner.visit(tree)
    return scanner


def lint_concurrency(root: str | Path | None = None) -> VerifyResult:
    """Scan the package against LOCK_TABLE/THREAD_TABLE; returns a
    :class:`VerifyResult` whose diagnostics are the X5xx findings."""
    if root is None:
        root = Path(__file__).resolve().parents[2]
    root = Path(root)
    pkg = root / 'da4ml_tpu'
    diags: list[Diagnostic] = []
    seen_locks: set[str] = set()
    seen_threads: set[str] = set()
    for path in sorted(pkg.rglob('*.py')):
        rel = path.relative_to(root).as_posix()
        if rel in RAW_ALLOWLIST:
            continue
        scanner = _scan_source(rel, path.read_text())
        diags.extend(scanner.diags)
        seen_locks.update(name for name, _ in scanner.make_lock_names)
        seen_threads.update(scanner.thread_prefixes)
    for name, spec in LOCK_TABLE.items():
        if name not in seen_locks:
            diags.append(
                Diagnostic(rule='X502', message=f'LOCK_TABLE entry {name!r} has no construction site in {spec.module}')
            )
    for prefix, tspec in THREAD_TABLE.items():
        if prefix not in seen_threads and tspec.module not in RAW_ALLOWLIST:
            diags.append(
                Diagnostic(
                    rule='X506', message=f'THREAD_TABLE entry {prefix!r} has no construction site in {tspec.module}'
                )
            )
    seen_msgs: set[tuple[str, str]] = set()
    unique = [d for d in diags if (d.rule, d.message) not in seen_msgs and not seen_msgs.add((d.rule, d.message))]
    return VerifyResult(unique, target='concurrency')


def lint_concurrency_main(args) -> int:
    result = lint_concurrency(getattr(args, 'root', None))
    if result.ok:
        print(
            f'lint-concurrency: ok ({len(LOCK_TABLE)} registered locks, '
            f'{len(THREAD_TABLE)} registered thread families, 0 violations)'
        )
        return 0
    print(result.format_text())
    return 1


def add_lint_concurrency_args(parser) -> None:
    parser.add_argument('--root', default=None, help='repository root to scan (default: the installed package root)')


__all__ = [
    'RAW_ALLOWLIST',
    'lint_concurrency',
    'lint_concurrency_main',
    'add_lint_concurrency_args',
]
