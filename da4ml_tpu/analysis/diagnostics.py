"""Structured diagnostics for the DAIS static-analysis framework.

Every finding a pass emits is a :class:`Diagnostic`: a stable rule id from
the catalog below, a severity, the op index it anchors to (when applicable)
and a human-readable message. Diagnostics are plain data — JSON-serializable
via :meth:`Diagnostic.to_dict` — so the CLI, the post-solve hook and CI can
all consume the same objects.

Rule catalog (docs/analysis.md keeps the user-facing copy):

======  ==================  ========  =============================================
id      name                severity  meaning
======  ==================  ========  =============================================
W101    shape-mismatch      error     io binding arrays inconsistent with ``shape``
W102    unknown-opcode      error     opcode not in the DAIS v1 table
W103    operand-violation   error     operand slot out of range or not earlier (SSA)
W104    input-lane          error     copy op reads a non-existent input lane
W105    output-binding      error     output bound to a non-existent op slot
W106    shift-range         error     implausible power-of-two shift magnitude
W110    lut-binding         error     lookup references a missing/invalid table
W111    bitwise-subop       error     unknown bitwise sub-opcode
W120    stage-interface     error     pipeline stage widths do not chain
Q201    step-not-pow2       error     ``QInterval.step`` not a positive power of two
Q202    interval-bounds     error     NaN/inf interval bound, or min > max
Q210    interval-unsound    error     annotation cannot hold the computed interval
Q220    precision-loss      warning   quantize op drops bits vs its operand
Q221    lut-interval        warning   lookup annotation disagrees with its table
D301    dead-op             warning   op result never reaches an output
D302    cost-model          error     negative/NaN latency or cost
D303    latency-monotone    warning   op latency below an operand's latency
======  ==================  ========  =============================================
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

ERROR = 'error'
WARNING = 'warning'
INFO = 'info'

_SEVERITY_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

#: rule id -> (short name, default severity)
RULES: dict[str, tuple[str, str]] = {
    'W101': ('shape-mismatch', ERROR),
    'W102': ('unknown-opcode', ERROR),
    'W103': ('operand-violation', ERROR),
    'W104': ('input-lane', ERROR),
    'W105': ('output-binding', ERROR),
    'W106': ('shift-range', ERROR),
    'W110': ('lut-binding', ERROR),
    'W111': ('bitwise-subop', ERROR),
    'W120': ('stage-interface', ERROR),
    'Q201': ('step-not-pow2', ERROR),
    'Q202': ('interval-bounds', ERROR),
    'Q210': ('interval-unsound', ERROR),
    'Q220': ('precision-loss', WARNING),
    'Q221': ('lut-interval', WARNING),
    'D301': ('dead-op', WARNING),
    'D302': ('cost-model', ERROR),
    'D303': ('latency-monotone', WARNING),
}


@dataclass(frozen=True)
class Diagnostic:
    """One finding of a verifier pass."""

    rule: str
    message: str
    op_index: int | None = None
    stage: int | None = None
    severity: str = field(default='')

    def __post_init__(self):
        if self.rule not in RULES:
            raise ValueError(f'unknown rule id {self.rule!r}')
        if not self.severity:
            object.__setattr__(self, 'severity', RULES[self.rule][1])
        elif self.severity not in _SEVERITY_ORDER:
            raise ValueError(f'unknown severity {self.severity!r}')

    @property
    def name(self) -> str:
        return RULES[self.rule][0]

    def to_dict(self) -> dict:
        return {
            'rule': self.rule,
            'name': self.name,
            'severity': self.severity,
            'stage': self.stage,
            'op': self.op_index,
            'message': self.message,
        }

    def __str__(self) -> str:
        where = ''
        if self.stage is not None:
            where += f'stage {self.stage} '
        if self.op_index is not None:
            where += f'op {self.op_index} '
        return f'{self.severity.upper()} {self.rule} [{self.name}] {where.strip()}: {self.message}'.replace(' :', ':')


class VerifyResult:
    """Outcome of running the verifier: an ordered list of diagnostics."""

    def __init__(self, diagnostics: list[Diagnostic], target: str = 'program'):
        self.diagnostics = list(diagnostics)
        self.target = target

    @property
    def errors(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == ERROR]

    @property
    def warnings(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == WARNING]

    @property
    def ok(self) -> bool:
        """No errors (warnings/info allowed)."""
        return not self.errors

    def by_rule(self, rule: str) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.rule == rule]

    def sorted(self) -> list[Diagnostic]:
        return sorted(
            self.diagnostics,
            key=lambda d: (_SEVERITY_ORDER[d.severity], d.stage or 0, d.op_index if d.op_index is not None else -1),
        )

    def summary(self) -> str:
        n_err, n_warn = len(self.errors), len(self.warnings)
        verdict = 'FAILED' if n_err else 'ok'
        return f'{self.target}: {verdict} ({n_err} error(s), {n_warn} warning(s))'

    def format_text(self, show_warnings: bool = True) -> str:
        lines = [self.summary()]
        for d in self.sorted():
            if d.severity != ERROR and not show_warnings:
                continue
            lines.append(f'  {d}')
        return '\n'.join(lines)

    def to_dict(self) -> dict:
        return {
            'target': self.target,
            'ok': self.ok,
            'n_errors': len(self.errors),
            'n_warnings': len(self.warnings),
            'diagnostics': [d.to_dict() for d in self.sorted()],
        }

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def __repr__(self) -> str:
        return f'VerifyResult({self.summary()})'


class VerificationError(ValueError):
    """A DAIS program failed verification. Carries the full result."""

    def __init__(self, result: VerifyResult, context: str = ''):
        self.result = result
        prefix = f'{context}: ' if context else ''
        super().__init__(prefix + result.format_text(show_warnings=False))
