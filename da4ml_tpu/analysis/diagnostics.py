"""Structured diagnostics for the DAIS static-analysis framework.

Every finding a pass emits is a :class:`Diagnostic`: a stable rule id from
the catalog below, a severity, the op index it anchors to (when applicable),
the DAIS opcode it concerns (when applicable — sourced from the declarative
opcode table so ``da4ml-tpu verify --json`` output can be grouped
per-opcode), and a human-readable message. Diagnostics are plain data —
JSON-serializable via :meth:`Diagnostic.to_dict` — so the CLI, the
post-solve hook and CI can all consume the same objects.

The user-facing rule catalog in docs/analysis.md is *generated* from
``RULES`` below (``python -m da4ml_tpu.analysis.docgen``); CI diffs the
regenerated section against the committed file.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

ERROR = 'error'
WARNING = 'warning'
INFO = 'info'

_SEVERITY_ORDER = {ERROR: 0, WARNING: 1, INFO: 2}

#: rule id -> (short name, default severity, meaning). The meaning column is
#: the docs/analysis.md catalog text (analysis.docgen renders it).
RULES: dict[str, tuple[str, str, str]] = {
    'W101': ('shape-mismatch', ERROR, 'io binding arrays inconsistent with `shape`'),
    'W102': ('unknown-opcode', ERROR, 'opcode not in the DAIS v1 table'),
    'W103': ('operand-violation', ERROR, 'operand slot out of range or not earlier (SSA)'),
    'W104': ('input-lane', ERROR, 'copy op reads a non-existent input lane'),
    'W105': ('output-binding', ERROR, 'output bound to a non-existent op slot'),
    'W106': ('shift-range', ERROR, 'implausible power-of-two shift magnitude'),
    'W110': ('lut-binding', ERROR, 'lookup references a missing/invalid table'),
    'W111': ('bitwise-subop', ERROR, 'unknown bitwise sub-opcode'),
    'W120': ('stage-interface', ERROR, 'pipeline stage widths do not chain'),
    'Q201': ('step-not-pow2', ERROR, '`QInterval.step` not a positive power of two'),
    'Q202': ('interval-bounds', ERROR, 'NaN/inf interval bound, or min > max'),
    'Q210': ('interval-unsound', ERROR, 'annotation cannot hold the computed interval'),
    'Q220': ('precision-loss', WARNING, 'quantize op drops bits vs its operand'),
    'Q221': ('lut-interval', WARNING, 'lookup annotation disagrees with its table'),
    'D301': ('dead-op', WARNING, 'op result never reaches an output'),
    'D302': ('cost-model', ERROR, 'negative/NaN latency or cost'),
    'D303': ('latency-monotone', WARNING, 'op latency below an operand\'s latency'),
    'D310': ('transfer-unsound', ERROR, 'a concrete result escapes the abstract transfer interval (verifier bug)'),
    'C401': ('backend-mismatch', ERROR, 'a runtime backend diverges bit-wise from the table-generated reference'),
    'C402': ('coverage-gap', ERROR, 'an opcode of the DAIS v1 table has no coverage in the fuzz corpus'),
    'X501': ('unregistered-lock', ERROR, 'a `threading` lock/condition constructed outside `locktrace.LOCK_TABLE`'),
    'X502': ('stale-lock-entry', ERROR, 'a `LOCK_TABLE` entry with no construction site left in the library'),
    'X503': ('static-rank-inversion', ERROR, 'lexically nested lock acquisition against the declared rank order'),
    'X504': ('lock-over-io', ERROR, 'HTTP/subprocess/jax-dispatch/sleep call while lexically holding a lock (absent a documented `io_ok` waiver)'),
    'X505': ('unregistered-thread', ERROR, 'a `threading.Thread` whose name prefix is missing from `locktrace.THREAD_TABLE` (or unnamed)'),
    'X506': ('stale-thread-entry', ERROR, 'a `THREAD_TABLE` entry with no construction site left in the library'),
    'X507': ('no-shutdown-path', ERROR, 'a daemon thread whose table entry declares no shutdown/drain path'),
    'X510': ('lock-cycle', ERROR, 'runtime lock-order graph contains a cycle (potential deadlock) — DA4ML_LOCKTRACE'),
    'X511': ('rank-inversion', ERROR, 'runtime acquisition nested against the declared rank order — DA4ML_LOCKTRACE'),
    'X512': ('invariant-violation', ERROR, 'an interleaving-harness invariant (single winner, exact tally, no lost request) failed under a seeded schedule'),
    'X513': ('schedule-deadlock', ERROR, 'every runnable thread blocked under a seeded schedule — a real interleaving deadlock'),
    'X520': ('undocumented-metric', ERROR, 'a metric emitted by the library with no `telemetry.catalog.METRICS` entry (no HELP text)'),
    'X521': ('stale-metric-entry', ERROR, 'a `METRICS`/`DYNAMIC_SITES` entry with no emission site left in the library'),
    'X522': ('unregistered-dynamic-metric', ERROR, 'a dynamically-named metric emission in a module not registered in `telemetry.catalog.DYNAMIC_SITES`'),
    'X523': ('metric-doc-missing', ERROR, 'a catalogued metric family with no row in docs/telemetry.md'),
    'X524': ('undocumented-knob', ERROR, 'a `DA4ML_*` environment variable read by the library but missing from `analysis.catalogs.KNOBS`'),
    'X525': ('stale-knob-entry', ERROR, 'a `KNOBS` entry no longer read anywhere in the library'),
}


@dataclass(frozen=True)
class Diagnostic:
    """One finding of a verifier pass."""

    rule: str
    message: str
    op_index: int | None = None
    stage: int | None = None
    severity: str = field(default='')
    opcode: int | None = None

    def __post_init__(self):
        if self.rule not in RULES:
            raise ValueError(f'unknown rule id {self.rule!r}')
        if not self.severity:
            object.__setattr__(self, 'severity', RULES[self.rule][1])
        elif self.severity not in _SEVERITY_ORDER:
            raise ValueError(f'unknown severity {self.severity!r}')

    @property
    def name(self) -> str:
        return RULES[self.rule][0]

    @property
    def opcode_family(self) -> str | None:
        """Stable family label from the opcode table (None when no opcode)."""
        from ..ir.optable import family_of

        return family_of(self.opcode)

    def to_dict(self) -> dict:
        return {
            'rule': self.rule,
            'name': self.name,
            'severity': self.severity,
            'stage': self.stage,
            'op': self.op_index,
            'opcode': self.opcode,
            'opcode_family': self.opcode_family,
            'message': self.message,
        }

    def __str__(self) -> str:
        where = ''
        if self.stage is not None:
            where += f'stage {self.stage} '
        if self.op_index is not None:
            where += f'op {self.op_index} '
        if self.opcode is not None:
            where += f'(opcode {self.opcode}) '
        return f'{self.severity.upper()} {self.rule} [{self.name}] {where.strip()}: {self.message}'.replace(' :', ':')


class VerifyResult:
    """Outcome of running the verifier: an ordered list of diagnostics."""

    def __init__(self, diagnostics: list[Diagnostic], target: str = 'program'):
        self.diagnostics = list(diagnostics)
        self.target = target

    @property
    def errors(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == ERROR]

    @property
    def warnings(self) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == WARNING]

    @property
    def ok(self) -> bool:
        """No errors (warnings/info allowed)."""
        return not self.errors

    def by_rule(self, rule: str) -> list[Diagnostic]:
        return [d for d in self.diagnostics if d.rule == rule]

    def by_opcode(self) -> dict[int | None, list[Diagnostic]]:
        """Diagnostics grouped by the DAIS opcode they concern."""
        groups: dict[int | None, list[Diagnostic]] = {}
        for d in self.diagnostics:
            groups.setdefault(d.opcode, []).append(d)
        return groups

    def sorted(self) -> list[Diagnostic]:
        return sorted(
            self.diagnostics,
            key=lambda d: (_SEVERITY_ORDER[d.severity], d.stage or 0, d.op_index if d.op_index is not None else -1),
        )

    def summary(self) -> str:
        n_err, n_warn = len(self.errors), len(self.warnings)
        verdict = 'FAILED' if n_err else 'ok'
        return f'{self.target}: {verdict} ({n_err} error(s), {n_warn} warning(s))'

    def format_text(self, show_warnings: bool = True) -> str:
        lines = [self.summary()]
        for d in self.sorted():
            if d.severity != ERROR and not show_warnings:
                continue
            lines.append(f'  {d}')
        return '\n'.join(lines)

    def to_dict(self) -> dict:
        return {
            'target': self.target,
            'ok': self.ok,
            'n_errors': len(self.errors),
            'n_warnings': len(self.warnings),
            'diagnostics': [d.to_dict() for d in self.sorted()],
        }

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def __repr__(self) -> str:
        return f'VerifyResult({self.summary()})'


class VerificationError(ValueError):
    """A DAIS program failed verification. Carries the full result."""

    def __init__(self, result: VerifyResult, context: str = ''):
        self.result = result
        prefix = f'{context}: ' if context else ''
        super().__init__(prefix + result.format_text(show_warnings=False))
