"""QInterval soundness pass: abstract interpretation over the op list.

Recomputes each op's value interval from its operands per the DAIS opcode
semantics (the same semantics ``CombLogic.__call__`` replays) and flags
annotations that cannot hold the computed values — an overflow hazard, since
codegen sizes every wire from ``minimal_kif(op.qint)``.

Soundness conventions this pass must respect (learned from the producers):

- The greedy CMVM optimizer (cmvm/core.py ``to_solution``) tracks negative
  adder-tree contributions by *sign-flipping* the stored interval, so an
  add op's annotation may be the interval of the negated value — and after
  two levels of mixing, an interval of equal span but shifted position.
  Containment is therefore checked against the computed interval, its
  negation, and finally span+step (which is invariant under those flips).
- Quantize-family ops (copy, relu-quantize, quantize) *define* their result
  container — a narrower annotation is the whole point. They get
  precision-loss warnings (Q220) instead of errors, and their annotation is
  trusted for downstream propagation.
- ``msb_mux`` annotations may be narrower than the branch hull (the tracer
  exploits branch correlation, e.g. in ``abs``), so the mux gets only
  structural checks.
- Squaring (``mul`` with id0 == id1) is bounded by the squared endpoints,
  not the four-corner product hull.

Every interval is dyadic and computed the same way the producers compute it,
so comparisons use an epsilon only as belt-and-braces.
"""

from __future__ import annotations

from math import isfinite, log2

from ..ir.comb import CombLogic
from ..ir.types import QInterval, minimal_kif, qint_add
from .diagnostics import Diagnostic

_EPS = 1e-9


def is_pow2(step: float) -> bool:
    """True when ``step`` is a positive (finite) power of two."""
    if not isinstance(step, (int, float)) or not isfinite(step) or step <= 0:
        return False
    f = log2(step)
    return f == round(f)


def _tol(*vals: float) -> float:
    return _EPS * max(1.0, *(abs(v) for v in vals if isfinite(v)))


def _contains(outer: QInterval, lo: float, hi: float, step: float) -> bool:
    t = _tol(lo, hi)
    return outer.min <= lo + t and outer.max >= hi - t and outer.step <= step * (1.0 + _EPS)


def _neg(lo: float, hi: float) -> tuple[float, float]:
    return -hi, -lo


def check_intervals(
    comb: CombLogic,
    stage: int | None = None,
    skip_ops: frozenset[int] = frozenset(),
) -> list[Diagnostic]:
    diags: list[Diagnostic] = []

    def emit(rule: str, message: str, op_index: int):
        diags.append(Diagnostic(rule, message, op_index=op_index, stage=stage))

    n_ops = len(comb.ops)
    computed: list[QInterval | None] = [None] * n_ops

    def operand(idx: int) -> QInterval | None:
        if 0 <= idx < n_ops:
            return computed[idx]
        return None

    for i, op in enumerate(comb.ops):
        if i in skip_ops:
            continue
        q = op.qint

        # ---- structural interval validity (applies to every opcode)
        bad = False
        for name, v in (('min', q.min), ('max', q.max), ('step', q.step)):
            if not isinstance(v, (int, float)) or not isfinite(v):
                emit('Q202', f'QInterval.{name} is {v!r}', i)
                bad = True
        if not bad and q.min > q.max + _tol(q.min, q.max):
            emit('Q202', f'QInterval has min {q.min} > max {q.max}', i)
            bad = True
        # zero-point intervals mark dead/constant-zero slots; any step is
        # accepted there, mirroring minimal_kif's early return
        if not bad and not (q.min == q.max == 0.0) and not is_pow2(q.step):
            emit('Q201', f'QInterval.step must be a positive power of two, got {q.step}', i)
            bad = True
        if bad:
            continue  # computed[i] stays None: downstream checks skip

        opc = op.opcode

        # ---- per-opcode abstract interpretation
        if opc in (-1, 2, -2, 3, -3):
            # quantize family: the annotation defines the result container.
            # Warn when it is strictly coarser than the operand's values.
            src = operand(int(op.id0)) if opc != -1 else None
            if src is not None and q.step > src.step * (1.0 + _EPS):
                emit(
                    'Q220',
                    f'quantize drops precision: result step {q.step} is coarser than operand step {src.step}',
                    i,
                )
            computed[i] = q

        elif opc in (0, 1):
            q0, q1 = operand(int(op.id0)), operand(int(op.id1))
            if q0 is None or q1 is None:
                computed[i] = q
                continue
            try:
                c = qint_add(q0, q1, int(op.data), False, opc == 1)
            except OverflowError:
                computed[i] = q
                continue
            computed[i] = c
            if _contains(q, c.min, c.max, c.step):
                continue
            nlo, nhi = _neg(c.min, c.max)
            if _contains(q, nlo, nhi, c.step):
                continue
            # CMVM sign-flip mixing can shift the position; span and step are
            # invariant under it, so that is the weakest sound criterion
            span_c, span_q = c.max - c.min, q.max - q.min
            if span_q + _tol(span_c) >= span_c and q.step <= c.step * (1.0 + _EPS):
                continue
            emit(
                'Q210',
                f'annotation [{q.min}, {q.max}] step {q.step} cannot hold computed '
                f'[{c.min}, {c.max}] step {c.step}',
                i,
            )

        elif opc == 4:
            q0 = operand(int(op.id0))
            if q0 is None:
                computed[i] = q
                continue
            c_add = int(op.data) * q.step
            c = QInterval(q0.min + c_add, q0.max + c_add, min(q0.step, q.step))
            computed[i] = c
            if not (_contains(q, c.min, c.max, c.step) or _contains(q, *_neg(c.min, c.max), c.step)):
                emit(
                    'Q210',
                    f'annotation [{q.min}, {q.max}] cannot hold operand + {c_add} = [{c.min}, {c.max}]',
                    i,
                )

        elif opc == 5:
            value = int(op.data) * q.step
            computed[i] = QInterval(value, value, q.step)
            t = _tol(value)
            if not (q.min - t <= value <= q.max + t or q.min - t <= -value <= q.max + t):
                emit('Q210', f'constant value {value} lies outside its annotation [{q.min}, {q.max}]', i)

        elif opc in (6, -6):
            # branch-correlated annotations are legitimately narrower than the
            # branch hull (e.g. ``abs``), so the annotation is trusted both as
            # the result container and for downstream propagation
            computed[i] = q

        elif opc == 7:
            q0, q1 = operand(int(op.id0)), operand(int(op.id1))
            if q0 is None or q1 is None:
                computed[i] = q
                continue
            if int(op.id0) == int(op.id1):
                ends = [q0.min * q0.min, q0.max * q0.max]
                if q0.min < 0 < q0.max:
                    ends.append(0.0)
            else:
                ends = [q0.min * q1.min, q0.min * q1.max, q0.max * q1.min, q0.max * q1.max]
            c = QInterval(min(ends), max(ends), q0.step * q1.step)
            computed[i] = c
            if not (_contains(q, c.min, c.max, c.step) or _contains(q, *_neg(c.min, c.max), c.step)):
                emit(
                    'Q210',
                    f'annotation [{q.min}, {q.max}] step {q.step} cannot hold product '
                    f'[{c.min}, {c.max}] step {c.step}',
                    i,
                )

        elif opc == 8:
            tables = comb.lookup_tables
            tbl = int(op.data)
            if tables is None or not 0 <= tbl < len(tables):
                computed[i] = q  # W110 already flagged it
                continue
            ft = tables[tbl].float_table
            lo, hi = float(ft.min()), float(ft.max())
            step = tables[tbl].spec.out_qint.step
            computed[i] = q
            if not (_contains(q, lo, hi, step) or _contains(q, *_neg(lo, hi), step)):
                emit(
                    'Q221',
                    f'lookup annotation [{q.min}, {q.max}] step {q.step} disagrees with its '
                    f'table range [{lo}, {hi}] step {step}',
                    i,
                )

        else:  # bitwise ops (9/-9/10): the annotation defines the container
            computed[i] = q

    return diags


def representable(q: QInterval) -> QInterval:
    """Full value range of the minimal fixed-point container of ``q``."""
    k, i, f = minimal_kif(q)
    step = 2.0**-f
    span = float(2.0**i)
    return QInterval(-span if k else 0.0, span - step, step)


__all__ = ['check_intervals', 'is_pow2', 'representable']
