"""QInterval soundness pass: abstract interpretation over the op list.

Recomputes each op's value interval from its operands per the DAIS opcode
semantics (the same semantics ``CombLogic.__call__`` replays) and flags
annotations that cannot hold the computed values — an overflow hazard, since
codegen sizes every wire from ``minimal_kif(op.qint)``.

The per-opcode transfer functions live in the declarative opcode table
(``ir/optable.py``, one ``transfer`` per row) — this pass only owns the
structural interval checks (finite ordered bounds, power-of-two step) and
the dispatch loop. The producer conventions the transfers respect:

- The greedy CMVM optimizer (cmvm/core.py ``to_solution``) tracks negative
  adder-tree contributions by *sign-flipping* the stored interval, so an
  add op's annotation may be the interval of the negated value — and after
  two levels of mixing, an interval of equal span but shifted position.
  Containment is therefore checked against the computed interval, its
  negation, and finally span+step (which is invariant under those flips).
- Quantize-family ops (copy, relu-quantize, quantize) *define* their result
  container — a narrower annotation is the whole point. They get
  precision-loss warnings (Q220) instead of errors, and their annotation is
  trusted for downstream propagation.
- ``msb_mux`` annotations may be narrower than the branch hull (the tracer
  exploits branch correlation, e.g. in ``abs``), so the mux gets only
  structural checks.
- Squaring (``mul`` with id0 == id1) is bounded by the squared endpoints,
  not the four-corner product hull.

Every interval is dyadic and computed the same way the producers compute it,
so comparisons use an epsilon only as belt-and-braces. The per-opcode
transfer functions are fuzz-verified against the concrete replay semantics
by the transfer-soundness checker (``analysis.soundness``).
"""

from __future__ import annotations

from math import isfinite, log2

from ..ir.comb import CombLogic
from ..ir.optable import OPCODE_TO_SPEC
from ..ir.types import QInterval, minimal_kif
from .diagnostics import Diagnostic

_EPS = 1e-9


def is_pow2(step: float) -> bool:
    """True when ``step`` is a positive (finite) power of two."""
    if not isinstance(step, (int, float)) or not isfinite(step) or step <= 0:
        return False
    f = log2(step)
    return f == round(f)


def _tol(*vals: float) -> float:
    return _EPS * max(1.0, *(abs(v) for v in vals if isfinite(v)))


def compute_intervals(
    comb: CombLogic,
    skip_ops: frozenset[int] = frozenset(),
) -> tuple[list[QInterval | None], list[Diagnostic]]:
    """Abstractly interpret the op list; returns (per-op computed intervals,
    diagnostics). ``None`` marks a slot whose interval could not be computed
    (structurally bad or skipped)."""
    diags: list[Diagnostic] = []
    n_ops = len(comb.ops)
    computed: list[QInterval | None] = [None] * n_ops

    def operand(idx: int) -> QInterval | None:
        if 0 <= idx < n_ops:
            return computed[idx]
        return None

    for i, op in enumerate(comb.ops):
        if i in skip_ops:
            continue
        q = op.qint

        def emit(rule: str, message: str, _i=i, _oc=op.opcode):
            diags.append(Diagnostic(rule, message, op_index=_i, opcode=_oc))

        # ---- structural interval validity (applies to every opcode)
        bad = False
        for name, v in (('min', q.min), ('max', q.max), ('step', q.step)):
            if not isinstance(v, (int, float)) or not isfinite(v):
                emit('Q202', f'QInterval.{name} is {v!r}')
                bad = True
        if not bad and q.min > q.max + _tol(q.min, q.max):
            emit('Q202', f'QInterval has min {q.min} > max {q.max}')
            bad = True
        # zero-point intervals mark dead/constant-zero slots; any step is
        # accepted there, mirroring minimal_kif's early return
        if not bad and not (q.min == q.max == 0.0) and not is_pow2(q.step):
            emit('Q201', f'QInterval.step must be a positive power of two, got {q.step}')
            bad = True
        if bad:
            continue  # computed[i] stays None: downstream checks skip

        # ---- per-opcode abstract interpretation (table-generated dispatch)
        spec = OPCODE_TO_SPEC.get(op.opcode)
        if spec is None:
            continue  # W102 territory; wellformed flags it
        c, checks = spec.transfer(comb, op, q, operand)
        computed[i] = c
        for rule, message in checks:
            emit(rule, message)

    return computed, diags


def check_intervals(
    comb: CombLogic,
    stage: int | None = None,
    skip_ops: frozenset[int] = frozenset(),
) -> list[Diagnostic]:
    _, diags = compute_intervals(comb, skip_ops=skip_ops)
    if stage is not None:
        diags = [
            Diagnostic(d.rule, d.message, op_index=d.op_index, stage=stage, severity=d.severity, opcode=d.opcode)
            for d in diags
        ]
    return diags


def representable(q: QInterval) -> QInterval:
    """Full value range of the minimal fixed-point container of ``q``."""
    k, i, f = minimal_kif(q)
    step = 2.0**-f
    span = float(2.0**i)
    return QInterval(-span if k else 0.0, span - step, step)


__all__ = ['check_intervals', 'compute_intervals', 'is_pow2', 'representable']
