"""Pass runner: orchestrates the analysis passes over CombLogic / Pipeline.

The framework is a registry of named passes; each pass is a function
``(comb, stage, skip_ops) -> list[Diagnostic]``. ``verify`` runs a selection
of passes (all by default) over every stage of the program and returns a
:class:`~.diagnostics.VerifyResult`; ``verify_or_raise`` is the fail-fast
form used as a precondition by codegen and the ``DA4ML_VERIFY=1`` post-solve
hook (cmvm/api.py).

The well-formedness pass always runs first: the op slots it flags as
structurally broken are skipped by the later passes, so a single corrupted
op yields one precise diagnostic instead of a cascade.
"""

from __future__ import annotations

import os
from typing import Callable, Protocol

from ..ir.comb import CombLogic, Pipeline
from .deadcode import check_deadcode
from .diagnostics import Diagnostic, VerificationError, VerifyResult
from .interval import check_intervals
from .wellformed import bad_op_indices, check_pipeline_interfaces, check_wellformed


class PassFn(Protocol):
    def __call__(
        self, comb: CombLogic, stage: int | None, skip_ops: frozenset[int]
    ) -> list[Diagnostic]: ...  # pragma: no cover - typing only


def _conformance_pass(comb, stage, skip_ops):
    from .conformance import conformance_pass

    return conformance_pass(comb, stage, skip_ops)


#: name -> pass; order is execution order ('wellformed' must stay first)
PASSES: dict[str, Callable] = {
    'wellformed': lambda comb, stage, skip_ops: check_wellformed(comb, stage=stage),
    'qinterval': lambda comb, stage, skip_ops: check_intervals(comb, stage=stage, skip_ops=skip_ops),
    'deadcode': lambda comb, stage, skip_ops: check_deadcode(comb, stage=stage, skip_ops=skip_ops),
    'conformance': _conformance_pass,
}

#: passes excluded from the default selection (expensive: the conformance
#: pass compiles and runs the program through every jax execution mode) —
#: opt in explicitly via ``passes=(..., 'conformance')`` or the CLI's
#: ``--conformance`` flag
OPT_IN_PASSES = frozenset({'conformance'})


def _resolve_passes(passes) -> list[str]:
    if passes is None:
        return [p for p in PASSES if p not in OPT_IN_PASSES]
    unknown = [p for p in passes if p not in PASSES]
    if unknown:
        raise ValueError(f'unknown analysis pass(es) {unknown}; available: {list(PASSES)}')
    return [p for p in PASSES if p in passes]  # registry order


def verify_comb(comb: CombLogic, passes=None, stage: int | None = None) -> list[Diagnostic]:
    """Run the selected passes over one CombLogic block."""
    selected = _resolve_passes(passes)
    diags: list[Diagnostic] = []
    skip: frozenset[int] = frozenset()
    if 'wellformed' in selected:
        wf = check_wellformed(comb, stage=stage)
        diags.extend(wf)
        skip = bad_op_indices(wf)
        selected = [p for p in selected if p != 'wellformed']
    for name in selected:
        diags.extend(PASSES[name](comb, stage, skip))
    return diags


def verify(program: CombLogic | Pipeline, passes=None, target: str = '') -> VerifyResult:
    """Verify a CombLogic or Pipeline; returns the full diagnostic set."""
    if isinstance(program, Pipeline):
        diags = list(check_pipeline_interfaces(program)) if passes is None or 'wellformed' in passes else []
        for si, stage in enumerate(program.stages):
            diags.extend(verify_comb(stage, passes=passes, stage=si))
        kind = f'Pipeline[{len(program.stages)} stages]'
    elif isinstance(program, CombLogic):
        diags = verify_comb(program, passes=passes)
        kind = 'CombLogic'
    else:
        raise TypeError(f'expected CombLogic or Pipeline, got {type(program).__name__}')
    return VerifyResult(diags, target=target or kind)


def verify_or_raise(program: CombLogic | Pipeline, context: str = '', passes=None) -> VerifyResult:
    """Fail-fast form: raise :class:`VerificationError` when errors exist."""
    result = verify(program, passes=passes)
    if not result.ok:
        raise VerificationError(result, context=context)
    return result


# ---------------------------------------------------------------------------
# environment gating (same style as DA4ML_SOLVE_FALLBACK / DA4ML_FAULT_INJECT)
# ---------------------------------------------------------------------------

_ENV_VAR = 'DA4ML_VERIFY'


def post_solve_verify_enabled() -> bool:
    """Opt-in: the post-solve hook only runs with ``DA4ML_VERIFY=1``."""
    return os.environ.get(_ENV_VAR, '0') in ('1', 'true', 'on')


def codegen_verify_enabled() -> bool:
    """Opt-out: codegen preconditions run unless ``DA4ML_VERIFY=0``."""
    return os.environ.get(_ENV_VAR, '1') not in ('0', 'false', 'off')


__all__ = [
    'PASSES',
    'PassFn',
    'verify',
    'verify_comb',
    'verify_or_raise',
    'post_solve_verify_enabled',
    'codegen_verify_enabled',
]
