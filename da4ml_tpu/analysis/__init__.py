"""Static analysis of DAIS programs: IR verifier & lint framework.

Three passes over ``CombLogic`` / ``Pipeline`` (docs/analysis.md):

- **wellformed** — SSA causality, opcode table membership, payload ranges,
  io-binding consistency, pipeline stage interfaces;
- **qinterval** — abstract interpretation recomputing every op's value
  interval and flagging unsound annotations (overflow hazards), bad steps,
  and precision loss;
- **deadcode** — unreachable ops, negative/NaN latency or cost, latency
  monotonicity;
- **conformance** (opt-in) — differential execution of every runtime
  backend against the reference interpreter generated from the declarative
  opcode table (``ir/optable.py``), reporting per-opcode bit mismatches.

The opcode-specific parts of every pass — legality ranges, interval
transfer functions, the mutation catalog — are generated from the same
table, and the :mod:`.soundness` checker fuzz-proves the transfers against
the concrete replay semantics.

Entry points: :func:`verify` (full diagnostics), :func:`verify_or_raise`
(fail-fast, used by codegen preconditions and the ``DA4ML_VERIFY=1``
post-solve hook), the ``da4ml-tpu verify`` CLI subcommand (``--conformance``
per program, ``--fuzz`` for the corpus sweep), and the :mod:`.mutation`
corruption harness for self-tests.
"""

from .conformance import CONFORMANCE_MODES, check_conformance, run_conformance_corpus
from .deadcode import check_deadcode, live_ops
from .diagnostics import ERROR, INFO, RULES, WARNING, Diagnostic, VerificationError, VerifyResult
from .interval import check_intervals, compute_intervals, is_pow2, representable
from .mutation import (
    COMB_CORRUPTIONS,
    PIPELINE_CORRUPTIONS,
    Corruption,
    apply_planned_corruptions,
    corruption_by_name,
)
from .runner import (
    OPT_IN_PASSES,
    PASSES,
    codegen_verify_enabled,
    post_solve_verify_enabled,
    verify,
    verify_comb,
    verify_or_raise,
)
from .soundness import check_spec_soundness, check_transfer_soundness
from .wellformed import DAIS_V1_OPCODES, check_pipeline_interfaces, check_wellformed

__all__ = [
    'Diagnostic',
    'VerifyResult',
    'VerificationError',
    'RULES',
    'ERROR',
    'WARNING',
    'INFO',
    'PASSES',
    'OPT_IN_PASSES',
    'verify',
    'verify_comb',
    'verify_or_raise',
    'post_solve_verify_enabled',
    'codegen_verify_enabled',
    'check_wellformed',
    'check_pipeline_interfaces',
    'check_intervals',
    'compute_intervals',
    'check_deadcode',
    'check_conformance',
    'run_conformance_corpus',
    'check_spec_soundness',
    'check_transfer_soundness',
    'CONFORMANCE_MODES',
    'live_ops',
    'is_pow2',
    'representable',
    'DAIS_V1_OPCODES',
    'COMB_CORRUPTIONS',
    'PIPELINE_CORRUPTIONS',
    'Corruption',
    'apply_planned_corruptions',
    'corruption_by_name',
]
