"""Static analysis of DAIS programs: IR verifier & lint framework.

Three passes over ``CombLogic`` / ``Pipeline`` (docs/analysis.md):

- **wellformed** — SSA causality, opcode table membership, payload ranges,
  io-binding consistency, pipeline stage interfaces;
- **qinterval** — abstract interpretation recomputing every op's value
  interval and flagging unsound annotations (overflow hazards), bad steps,
  and precision loss;
- **deadcode** — unreachable ops, negative/NaN latency or cost, latency
  monotonicity.

Entry points: :func:`verify` (full diagnostics), :func:`verify_or_raise`
(fail-fast, used by codegen preconditions and the ``DA4ML_VERIFY=1``
post-solve hook), the ``da4ml-tpu verify`` CLI subcommand, and the
:mod:`.mutation` corruption harness for self-tests.
"""

from .deadcode import check_deadcode, live_ops
from .diagnostics import ERROR, INFO, RULES, WARNING, Diagnostic, VerificationError, VerifyResult
from .interval import check_intervals, is_pow2, representable
from .mutation import (
    COMB_CORRUPTIONS,
    PIPELINE_CORRUPTIONS,
    Corruption,
    apply_planned_corruptions,
    corruption_by_name,
)
from .runner import (
    PASSES,
    codegen_verify_enabled,
    post_solve_verify_enabled,
    verify,
    verify_comb,
    verify_or_raise,
)
from .wellformed import DAIS_V1_OPCODES, check_pipeline_interfaces, check_wellformed

__all__ = [
    'Diagnostic',
    'VerifyResult',
    'VerificationError',
    'RULES',
    'ERROR',
    'WARNING',
    'INFO',
    'PASSES',
    'verify',
    'verify_comb',
    'verify_or_raise',
    'post_solve_verify_enabled',
    'codegen_verify_enabled',
    'check_wellformed',
    'check_pipeline_interfaces',
    'check_intervals',
    'check_deadcode',
    'live_ops',
    'is_pow2',
    'representable',
    'DAIS_V1_OPCODES',
    'COMB_CORRUPTIONS',
    'PIPELINE_CORRUPTIONS',
    'Corruption',
    'apply_planned_corruptions',
    'corruption_by_name',
]
