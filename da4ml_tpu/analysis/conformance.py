"""Cross-backend conformance checker: differential execution against the
table-generated reference interpreter.

Every runtime backend (numpy oracle, jax ``unroll`` / ``scan`` / ``level``
/ ``pallas`` modes) promises bit-exactness with the DAIS v1 semantics. This pass makes
that promise checkable: it executes a program through each backend and
compares outputs bit-wise against ``runtime.reference`` — the interpreter
generated from the declarative opcode table (``ir/optable.py``). A
divergence is reported as a structured **C401 backend-mismatch** diagnostic
anchored to the earliest divergent op (numpy exposes its execution buffer;
jax modes are attributed through the output binding), carrying the opcode
so ``--json`` output groups per-opcode.

Two entry points:

- :func:`check_conformance` — one program (CombLogic or decoded
  DaisProgram); runs as the opt-in ``conformance`` pass of
  ``da4ml-tpu verify --conformance``;
- :func:`run_conformance_corpus` — the fuzz-corpus sweep over ``ir.synth``
  programs (``da4ml-tpu verify --fuzz N``, CI job ``opcode-conformance``),
  which additionally audits per-opcode corpus coverage (**C402**) and
  returns a JSON-ready report with per-opcode op/mismatch counts.
"""

from __future__ import annotations

import numpy as np

from ..ir.comb import CombLogic
from ..ir.dais_binary import DaisProgram, decode
from ..ir.optable import DAIS_V1_OPCODES, OPCODE_TO_SPEC, family_of
from ..ir.synth import random_inputs, random_program
from .diagnostics import Diagnostic

#: execution targets differentially checked against the reference;
#: pallas runs interpret mode on CPU and compiled on TPU/GPU
CONFORMANCE_MODES = ('numpy', 'unroll', 'scan', 'level', 'pallas')


def _as_prog(program) -> DaisProgram:
    if isinstance(program, CombLogic):
        return decode(program.to_binary())
    return program


def _first_divergent_op(prog: DaisProgram, ref_buf: np.ndarray, got_buf: np.ndarray) -> int:
    diff = np.any(ref_buf != got_buf, axis=1)
    return int(np.argmax(diff)) if diff.any() else -1


def _run_mode(prog: DaisProgram, mode: str, data: np.ndarray):
    """Execute one backend; returns (outputs, buffer | None)."""
    if mode == 'numpy':
        from ..runtime.numpy_backend import run_program

        return run_program(prog, data, return_buf=True)
    from ..runtime.jax_backend import DaisExecutor

    return DaisExecutor(prog, mode=mode)(data), None


def check_conformance(
    program,
    modes: tuple[str, ...] = CONFORMANCE_MODES,
    n_samples: int = 64,
    seed: int = 0,
    stage: int | None = None,
    data: np.ndarray | None = None,
) -> list[Diagnostic]:
    """Differentially execute ``program`` through each backend vs the
    reference interpreter; bit-mismatches become C401 diagnostics.

    ``data`` overrides the synthetic input batch — for programs whose input
    lanes carry narrower-than-declared upstream values (e.g. partition cells
    receiving another shard's lookup index), the caller supplies realistic
    carries instead of the full-width random sweep.
    """
    from ..runtime import reference
    from ..runtime.jax_backend import DaisExecutor

    prog = _as_prog(program)
    if data is None:
        rng = np.random.default_rng(seed)
        data = random_inputs(rng, prog, n_samples)
    else:
        data = np.asarray(data, dtype=np.float64)
        n_samples = len(data)
    ref, ref_buf = reference.run_program(prog, data, return_buf=True)

    diags: list[Diagnostic] = []
    for mode in modes:
        if mode == 'unroll' and prog.n_ops > DaisExecutor.UNROLL_LIMIT:
            continue  # unroll refuses by design; not a conformance failure
        if mode == 'pallas':
            from ..runtime.pallas_backend import unavailable_reason

            if unavailable_reason(prog) is not None:
                continue  # pallas/jaxlib absent or family unlowered; fallback, not a failure
        try:
            got, got_buf = _run_mode(prog, mode, data)
        except Exception as e:  # a backend crash on a valid program is a divergence
            diags.append(
                Diagnostic(
                    'C401',
                    f"backend '{mode}' raised {type(e).__name__} on a program the reference executes: {e}",
                    stage=stage,
                )
            )
            continue
        if np.array_equal(np.asarray(got), ref):
            continue
        if got_buf is not None:
            op = _first_divergent_op(prog, ref_buf, got_buf)
            oc = int(prog.opcode[op]) if op >= 0 else None
            where = f'first divergent op {op}'
        else:
            bad_cols = np.flatnonzero(np.any(np.asarray(got) != ref, axis=0))
            j = int(bad_cols[0]) if len(bad_cols) else 0
            op = int(prog.out_idxs[j])
            oc = int(prog.opcode[op]) if op >= 0 else None
            where = f'first divergent output {j} (bound to op {op})'
        n_bad = int(np.count_nonzero(np.any(np.asarray(got) != ref, axis=1)))
        diags.append(
            Diagnostic(
                'C401',
                f"backend '{mode}' diverges bit-wise from the table reference on "
                f'{n_bad}/{n_samples} samples; {where}',
                op_index=op if op >= 0 else None,
                stage=stage,
                opcode=oc,
            )
        )
    return diags


def conformance_pass(comb, stage, skip_ops) -> list[Diagnostic]:
    """Registry adapter: skip programs with structural errors (backends
    would crash on them for the right reasons)."""
    if skip_ops:
        return []
    return check_conformance(comb, stage=stage)


def run_conformance_corpus(
    n_programs: int = 12,
    n_ops: int = 180,
    n_samples: int = 64,
    seed: int = 0,
    modes: tuple[str, ...] = CONFORMANCE_MODES,
) -> tuple[dict, list[Diagnostic]]:
    """Differential fuzz over the ``ir.synth`` corpus.

    Every 4th program is wide (int64 device path); per-opcode op counts are
    accumulated so a table row the generator never emits is flagged as a
    C402 coverage gap. Returns ``(report, diagnostics)`` where the report
    is JSON-ready (the CI job uploads it as an artifact).
    """
    per_opcode: dict[int, dict] = {
        oc: {'family': spec.family, 'ops': 0, 'mismatches': 0} for oc, spec in OPCODE_TO_SPEC.items()
    }
    diags: list[Diagnostic] = []

    for pi in range(n_programs):
        rng = np.random.default_rng(seed * 100_003 + pi)
        prog = random_program(rng, n_ops=n_ops, n_in=6, n_out=5, wide=(pi % 4 == 3))
        for oc in prog.opcode.tolist():
            per_opcode[int(oc)]['ops'] += 1
        found = check_conformance(prog, modes=modes, n_samples=n_samples, seed=seed * 7 + pi)
        for d in found:
            if d.opcode is not None:
                per_opcode[int(d.opcode)]['mismatches'] += 1
        diags.extend(found)

    for oc in sorted(DAIS_V1_OPCODES):
        if per_opcode[oc]['ops'] == 0:
            diags.append(
                Diagnostic(
                    'C402',
                    f'opcode {oc} ({family_of(oc)}) was never emitted by the {n_programs}-program fuzz corpus; '
                    f'grow ir/synth.py coverage or the corpus size',
                    opcode=oc,
                )
            )

    report = {
        'ok': not diags,
        'n_programs': n_programs,
        'n_ops_per_program': n_ops,
        'n_samples': n_samples,
        'modes': list(modes),
        'seed': seed,
        'per_opcode': {str(oc): info for oc, info in sorted(per_opcode.items())},
        'diagnostics': [d.to_dict() for d in diags],
    }
    return report, diags


__all__ = ['CONFORMANCE_MODES', 'check_conformance', 'conformance_pass', 'run_conformance_corpus']
