"""Well-formedness pass: structural SSA validity of a DAIS program.

Checks that the program is executable at all — every operand reference names
an earlier buffer slot (SSA causality), every opcode is in the DAIS v1 table,
packed payloads (mux condition/shift, bitwise sub-opcodes, lookup table
indices) are in range, and the io binding arrays are consistent with
``shape``. Runs in O(n_ops); the other passes assume a program that passed
this one (the runner feeds them the set of structurally-bad ops to skip).

Everything opcode-specific here is *generated* from the declarative opcode
table (``ir/optable.py``): the legal opcode set, which ops read ``id1`` /
carry a condition slot in ``data``, how payload shifts are extracted, and
the per-row payload legality checks. A new opcode lands by adding a table
row — this pass picks it up without edits.
"""

from __future__ import annotations

from ..ir.comb import CombLogic, Pipeline
from ..ir.optable import (
    BINARY_OPCODES as _BINARY_OPCODES,  # noqa: F401  (re-export for consumers)
    DAIS_V1_OPCODES,
    OPCODE_TO_SPEC,
    SHIFT_LIMIT,
    op_operands,
    op_shift,
)
from .diagnostics import Diagnostic


def check_wellformed(comb: CombLogic, stage: int | None = None) -> list[Diagnostic]:
    diags: list[Diagnostic] = []

    def emit(rule: str, message: str, op_index: int | None = None, opcode: int | None = None):
        diags.append(Diagnostic(rule, message, op_index=op_index, stage=stage, opcode=opcode))

    # ---- container-level consistency
    n_in, n_out = (int(v) for v in comb.shape)
    if n_in <= 0 or n_out <= 0:
        emit('W101', f'shape must be positive, got ({n_in}, {n_out})')
    if len(comb.inp_shifts) != n_in:
        emit('W101', f'inp_shifts has {len(comb.inp_shifts)} entries for {n_in} inputs')
    if not (len(comb.out_idxs) == len(comb.out_shifts) == len(comb.out_negs) == n_out):
        emit(
            'W101',
            f'output bindings have {len(comb.out_idxs)}/{len(comb.out_shifts)}/{len(comb.out_negs)} '
            f'entries for {n_out} outputs',
        )

    n_ops = len(comb.ops)
    n_tables = len(comb.lookup_tables) if comb.lookup_tables is not None else None

    # ---- per-op checks (legality data generated from the opcode table)
    for i, op in enumerate(comb.ops):
        spec = OPCODE_TO_SPEC.get(op.opcode)
        if spec is None:
            emit('W102', f'opcode {op.opcode} is not in the DAIS v1 table', i, opcode=int(op.opcode))
            continue

        if spec.id0 == 'lane':
            lane = int(op.id0)
            if not 0 <= lane < n_in:
                emit('W104', f'copy op reads input lane {lane}, program has {n_in} inputs', i, opcode=op.opcode)
        else:
            for slot in op_operands(op):
                if not 0 <= slot < i:
                    which = 'condition' if spec.cond_in_data and slot not in (op.id0, op.id1) else 'operand'
                    emit(
                        'W103',
                        f'{which} slot {slot} is not an earlier SSA slot (op is at slot {i})',
                        i,
                        opcode=op.opcode,
                    )

        shift = op_shift(op)
        if shift is not None and abs(shift) > SHIFT_LIMIT:
            emit('W106', f'shift {shift} exceeds the plausible range +-{SHIFT_LIMIT}', i, opcode=op.opcode)

        if spec.payload_check is not None:
            for rule, message in spec.payload_check(op, n_tables):
                emit(rule, message, i, opcode=op.opcode)

    # ---- output bindings (out_idx == -1 marks an intentionally dead lane)
    for j, idx in enumerate(comb.out_idxs):
        idx = int(idx)
        if idx != -1 and not 0 <= idx < n_ops:
            emit('W105', f'output {j} bound to slot {idx}, program has {n_ops} ops')

    return diags


def check_pipeline_interfaces(pipeline: Pipeline) -> list[Diagnostic]:
    """Stage-to-stage interface consistency of a Pipeline."""
    diags: list[Diagnostic] = []
    if not pipeline.stages:
        return [Diagnostic('W101', 'pipeline has no stages')]
    for si in range(len(pipeline.stages) - 1):
        n_out = int(pipeline.stages[si].shape[1])
        n_in = int(pipeline.stages[si + 1].shape[0])
        if n_out != n_in:
            diags.append(
                Diagnostic(
                    'W120',
                    f'stage {si} produces {n_out} outputs but stage {si + 1} expects {n_in} inputs',
                    stage=si,
                )
            )
    return diags


def bad_op_indices(diags: list[Diagnostic]) -> frozenset[int]:
    """Op slots with structural errors — downstream passes skip these."""
    return frozenset(d.op_index for d in diags if d.op_index is not None and d.severity == 'error')


__all__ = [
    'DAIS_V1_OPCODES',
    'SHIFT_LIMIT',
    'check_wellformed',
    'check_pipeline_interfaces',
    'bad_op_indices',
    'op_operands',
    'op_shift',
]
