"""Well-formedness pass: structural SSA validity of a DAIS program.

Checks that the program is executable at all — every operand reference names
an earlier buffer slot (SSA causality), every opcode is in the DAIS v1 table
(ir/types.py), packed payloads (mux condition/shift, bitwise sub-opcodes,
lookup table indices) are in range, and the io binding arrays are consistent
with ``shape``. Runs in O(n_ops); the other passes assume a program that
passed this one (the runner feeds them the set of structurally-bad ops to
skip).
"""

from __future__ import annotations

from ..ir.comb import CombLogic, Pipeline, _i32
from ..ir.types import Op
from .diagnostics import Diagnostic

#: every opcode of the DAIS v1 table (docs/dais.md)
DAIS_V1_OPCODES = frozenset((-1, 0, 1, 2, -2, 3, -3, 4, 5, 6, -6, 7, 8, 9, -9, 10))

#: opcodes whose id1 names a second operand slot
_BINARY_OPCODES = frozenset((0, 1, 6, -6, 7, 10))

#: largest plausible power-of-two shift in an op payload (DAIS values are
#: fixed-point with at most a few hundred bits; anything beyond is corruption
#: and would overflow float replay)
SHIFT_LIMIT = 256

_UNARY_BIT_SUBOPS = (0, 1, 2)  # NOT, OR-reduce, AND-reduce
_BINARY_BIT_SUBOPS = (0, 1, 2)  # AND, OR, XOR


def op_shift(op: Op) -> int | None:
    """The power-of-two shift an op applies to its second operand, if any."""
    if op.opcode in (0, 1):
        return int(op.data)
    if op.opcode in (6, -6):
        return _i32(int(op.data) >> 32)
    if op.opcode == 10:
        return _i32(int(op.data))
    return None


def op_operands(op: Op) -> list[int]:
    """Buffer slots an op reads (input lanes of copy ops are *not* slots)."""
    reads = []
    if op.opcode == -1 or op.opcode == 5:
        return reads
    reads.append(int(op.id0))
    if op.opcode in _BINARY_OPCODES:
        reads.append(int(op.id1))
    if op.opcode in (6, -6):
        reads.append(int(op.data) & 0xFFFFFFFF)
    return reads


def check_wellformed(comb: CombLogic, stage: int | None = None) -> list[Diagnostic]:
    diags: list[Diagnostic] = []

    def emit(rule: str, message: str, op_index: int | None = None):
        diags.append(Diagnostic(rule, message, op_index=op_index, stage=stage))

    # ---- container-level consistency
    n_in, n_out = (int(v) for v in comb.shape)
    if n_in <= 0 or n_out <= 0:
        emit('W101', f'shape must be positive, got ({n_in}, {n_out})')
    if len(comb.inp_shifts) != n_in:
        emit('W101', f'inp_shifts has {len(comb.inp_shifts)} entries for {n_in} inputs')
    if not (len(comb.out_idxs) == len(comb.out_shifts) == len(comb.out_negs) == n_out):
        emit(
            'W101',
            f'output bindings have {len(comb.out_idxs)}/{len(comb.out_shifts)}/{len(comb.out_negs)} '
            f'entries for {n_out} outputs',
        )

    n_ops = len(comb.ops)
    n_tables = len(comb.lookup_tables) if comb.lookup_tables is not None else 0

    # ---- per-op checks
    for i, op in enumerate(comb.ops):
        if op.opcode not in DAIS_V1_OPCODES:
            emit('W102', f'opcode {op.opcode} is not in the DAIS v1 table', i)
            continue

        if op.opcode == -1:
            lane = int(op.id0)
            if not 0 <= lane < n_in:
                emit('W104', f'copy op reads input lane {lane}, program has {n_in} inputs', i)
        else:
            for slot in op_operands(op):
                if not 0 <= slot < i:
                    which = 'condition' if op.opcode in (6, -6) and slot not in (op.id0, op.id1) else 'operand'
                    emit('W103', f'{which} slot {slot} is not an earlier SSA slot (op is at slot {i})', i)

        shift = op_shift(op)
        if shift is not None and abs(shift) > SHIFT_LIMIT:
            emit('W106', f'shift {shift} exceeds the plausible range +-{SHIFT_LIMIT}', i)

        if op.opcode == 8:
            tbl = int(op.data)
            if comb.lookup_tables is None:
                emit('W110', f'lookup op references table {tbl} but the program carries no tables', i)
            elif not 0 <= tbl < n_tables:
                emit('W110', f'lookup op references table {tbl}, program has {n_tables} tables', i)
        elif op.opcode in (9, -9) and int(op.data) not in _UNARY_BIT_SUBOPS:
            emit('W111', f'unary bitwise sub-opcode {int(op.data)} (valid: 0=NOT, 1=OR-reduce, 2=AND-reduce)', i)
        elif op.opcode == 10:
            subop = (int(op.data) >> 56) & 0xFF
            if subop not in _BINARY_BIT_SUBOPS:
                emit('W111', f'binary bitwise sub-opcode {subop} (valid: 0=AND, 1=OR, 2=XOR)', i)

    # ---- output bindings (out_idx == -1 marks an intentionally dead lane)
    for j, idx in enumerate(comb.out_idxs):
        idx = int(idx)
        if idx != -1 and not 0 <= idx < n_ops:
            emit('W105', f'output {j} bound to slot {idx}, program has {n_ops} ops')

    return diags


def check_pipeline_interfaces(pipeline: Pipeline) -> list[Diagnostic]:
    """Stage-to-stage interface consistency of a Pipeline."""
    diags: list[Diagnostic] = []
    if not pipeline.stages:
        return [Diagnostic('W101', 'pipeline has no stages')]
    for si in range(len(pipeline.stages) - 1):
        n_out = int(pipeline.stages[si].shape[1])
        n_in = int(pipeline.stages[si + 1].shape[0])
        if n_out != n_in:
            diags.append(
                Diagnostic(
                    'W120',
                    f'stage {si} produces {n_out} outputs but stage {si + 1} expects {n_in} inputs',
                    stage=si,
                )
            )
    return diags


def bad_op_indices(diags: list[Diagnostic]) -> frozenset[int]:
    """Op slots with structural errors — downstream passes skip these."""
    return frozenset(d.op_index for d in diags if d.op_index is not None and d.severity == 'error')


__all__ = [
    'DAIS_V1_OPCODES',
    'SHIFT_LIMIT',
    'check_wellformed',
    'check_pipeline_interfaces',
    'bad_op_indices',
    'op_operands',
    'op_shift',
]
