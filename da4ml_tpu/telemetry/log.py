"""Telemetry-aware stdlib logging for the whole package.

:func:`get_logger` hands out loggers under the ``da4ml_tpu`` hierarchy,
lazily configuring the base logger exactly once:

- INFO and below render as the bare message on the *current* ``sys.stdout``
  (dynamic lookup, so pytest's capsys and stream redirection keep working) —
  byte-identical with the ``print()`` calls this replaced;
- WARNING and above render as ``[LEVEL] message`` on the current
  ``sys.stderr``;
- every record is additionally mirrored into the active trace sinks as an
  instant event (``log.<level>``), so warnings land in the Perfetto
  timeline next to the spans they interrupted;
- ``DA4ML_LOG_LEVEL`` overrides the default INFO threshold;
- nothing is touched if the application already configured handlers on the
  ``da4ml_tpu`` logger.
"""

from __future__ import annotations

import logging
import os
import sys
import threading

from . import core

_configure_lock = threading.Lock()
_configured = False


class _DynamicStreamHandler(logging.StreamHandler):
    """Routes INFO-and-below to sys.stdout and WARNING+ to sys.stderr,
    resolving the stream at emit time (not handler creation time)."""

    def __init__(self):
        super().__init__(stream=sys.stdout)

    def emit(self, record: logging.LogRecord) -> None:
        self.stream = sys.stderr if record.levelno >= logging.WARNING else sys.stdout
        super().emit(record)


class _Formatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = record.getMessage()
        if record.levelno >= logging.WARNING:
            return f'[{record.levelname}] {msg}'
        return msg


class _TelemetryHandler(logging.Handler):
    """Mirrors log records into the trace as instant events."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            core.instant(
                f'log.{record.levelname.lower()}',
                message=record.getMessage(),
                logger=record.name,
            )
        except Exception:
            pass


def _configure_base() -> None:
    global _configured
    with _configure_lock:
        if _configured:
            return
        base = logging.getLogger('da4ml_tpu')
        if not base.handlers:  # respect an application-provided config
            stream = _DynamicStreamHandler()
            stream.setFormatter(_Formatter())
            base.addHandler(stream)
            base.addHandler(_TelemetryHandler())
            level = os.environ.get('DA4ML_LOG_LEVEL', 'INFO').upper()
            base.setLevel(getattr(logging, level, logging.INFO))
            base.propagate = False
        _configured = True


_warned_once: set[str] = set()
_warn_once_lock = threading.Lock()


def warn_once(key: str, message: str, logger: str = '') -> bool:
    """Emit ``message`` as a warning exactly once per process per ``key``.

    For conditions that are worth surfacing but would otherwise repeat on a
    hot path (e.g. a process-global config flag being flipped as a fallback).
    Returns True when the warning was actually emitted.
    """
    with _warn_once_lock:
        if key in _warned_once:
            return False
        _warned_once.add(key)
    get_logger(logger).warning(message)
    return True


def get_logger(name: str = '') -> logging.Logger:
    """A logger under the ``da4ml_tpu`` hierarchy (``name`` may be a bare
    suffix like ``'cmvm.jax'`` or a full ``da4ml_tpu.*`` module path)."""
    _configure_base()
    if not name or name == 'da4ml_tpu':
        return logging.getLogger('da4ml_tpu')
    if name.startswith('da4ml_tpu.'):
        return logging.getLogger(name)
    return logging.getLogger(f'da4ml_tpu.{name}')
