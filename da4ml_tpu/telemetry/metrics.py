"""Metrics registry: counters, gauges, and fixed-bucket histograms.

Process-global, thread-safe, and disabled by default: the accessor
functions (:func:`counter` / :func:`gauge` / :func:`histogram`) return a
shared no-op metric until :func:`enable_metrics` runs (directly, via
``telemetry.enable()``, or via ``DA4ML_TRACE``), so instrumentation sites
cost one function call + one flag read when telemetry is off.

Names follow a dotted ``subsystem.metric`` convention — the catalog lives
in docs/telemetry.md. :func:`metrics_snapshot` returns the whole registry
as a JSON-serializable dict; the Chrome trace exporter embeds it in the
trace file's ``otherData`` and ``bench.py`` attaches it to the BENCH JSON.

Device-scheduler metrics (docs/telemetry.md#scheduler): the CMVM search
driver reports its canonical shape buckets (``sched.bucket_groups`` /
``sched.bucket_lanes`` / ``sched.dedup_lanes``), rung ladder
(``sched.rungs``), compile-vs-persistent-cache split (``jit.compile`` /
``jit.cache_load`` and their ``_s`` histograms — the legacy
``jit.cache_miss`` / ``jit.first_call_s`` aggregate both), and
dispatch/emit overlap (``emit.async_batches`` / ``emit.async_wait_s`` —
a ~0 wait means emission fully overlapped device rounds).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

#: default histogram bucket upper bounds (seconds-oriented, exponential):
#: spans 100µs .. 100s, which covers everything from a single no-op solve to
#: a full-model conversion. Counts above the last bound land in +Inf.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
)  # fmt: skip

#: count-valued histograms (adder counts, batch sizes, substitutions):
#: 1 .. 1M in a 1/2.5/5 ladder — the seconds buckets put every such sample
#: in +Inf, which made the distributions invisible
COUNT_BUCKETS: tuple[float, ...] = (
    1, 2, 5, 10, 25, 50, 100, 250, 500, 1_000, 2_500, 5_000,
    10_000, 25_000, 50_000, 100_000, 250_000, 500_000, 1_000_000,
)  # fmt: skip

#: byte-valued histograms (transfer sizes, HBM-resident estimates):
#: 1KiB .. 16GiB in powers of four
BYTES_BUCKETS: tuple[float, ...] = tuple(float(1024 * 4**k) for k in range(13))

_registry: dict[str, 'Counter | Gauge | Histogram'] = {}
_lock = threading.Lock()
_enabled = False


class Counter:
    """Monotonically increasing count."""

    __slots__ = ('name', '_value', '_lock')

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def to_dict(self) -> dict:
        return {'type': 'counter', 'value': self._value}


class Gauge:
    """Last-written value (breaker state, campaign progress)."""

    __slots__ = ('name', '_value', '_lock')

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def to_dict(self) -> dict:
        return {'type': 'gauge', 'value': self._value}


class Histogram:
    """Fixed-bucket histogram (cumulative counts, Prometheus-style).

    ``observe(v, trace_id=...)`` additionally retains the most recent
    ``(trace_id, value, unix time)`` triple per bucket as an OpenMetrics
    **exemplar**, so a bad latency bucket on ``/metrics`` links straight to
    the trace that landed in it (docs/observability.md#fleet-tracing).
    """

    __slots__ = ('name', 'bounds', '_counts', '_sum', '_count', '_min', '_max', '_exemplars', '_lock')

    def __init__(self, name: str, buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.bounds = tuple(sorted(buckets))
        self._counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._min = float('inf')
        self._max = float('-inf')
        self._exemplars: dict[int, tuple[str, float, float]] = {}
        self._lock = threading.Lock()

    def observe(self, v: float, trace_id: 'str | None' = None) -> None:
        v = float(v)
        with self._lock:
            i = 0
            for bound in self.bounds:
                if v <= bound:
                    break
                i += 1
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if trace_id is not None:
                self._exemplars[i] = (trace_id, v, time.time())

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def to_dict(self) -> dict:
        with self._lock:
            d = {
                'type': 'histogram',
                'count': self._count,
                'sum': round(self._sum, 6),
                'bounds': list(self.bounds),
                'buckets': list(self._counts),
            }
            if self._count:
                d['min'] = round(self._min, 6)
                d['max'] = round(self._max, 6)
                d['mean'] = round(self._sum / self._count, 6)
            if self._exemplars:
                d['exemplars'] = {str(i): [t, v, round(ts, 3)] for i, (t, v, ts) in sorted(self._exemplars.items())}
            return d


class _NoopMetric:
    """Disabled-path metric: every mutator is a no-op."""

    __slots__ = ()
    name = ''
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float, trace_id: 'str | None' = None) -> None:
        pass

    def to_dict(self) -> dict:
        return {}


_NOOP_METRIC = _NoopMetric()


def _get(name: str, cls, **kwargs):
    m = _registry.get(name)
    if m is None:
        with _lock:
            m = _registry.get(name)
            if m is None:
                _registry[name] = m = cls(name, **kwargs)
    if not isinstance(m, cls):
        raise TypeError(f'metric {name!r} already registered as {type(m).__name__}, not {cls.__name__}')
    return m


def counter(name: str) -> Counter:
    if not _enabled:
        return _NOOP_METRIC  # type: ignore[return-value]
    return _get(name, Counter)


def gauge(name: str) -> Gauge:
    if not _enabled:
        return _NOOP_METRIC  # type: ignore[return-value]
    return _get(name, Gauge)


def histogram(name: str, buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
    if not _enabled:
        return _NOOP_METRIC  # type: ignore[return-value]
    return _get(name, Histogram, buckets=buckets)


def metrics_on() -> bool:
    return _enabled


def enable_metrics() -> None:
    global _enabled
    _enabled = True


def disable_metrics() -> None:
    global _enabled
    _enabled = False


def reset_metrics() -> None:
    with _lock:
        _registry.clear()


@contextmanager
def timer(name: str, buckets: tuple[float, ...] = DEFAULT_BUCKETS):
    """Observe a code block's wall clock into histogram ``name``.

    No-op when metrics are disabled — the clock is never read on the
    disabled path, matching the zero-cost contract of the accessors.
    """
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        histogram(name, buckets).observe(time.perf_counter() - t0)


def metrics_snapshot() -> dict:
    """The whole registry as ``{name: {type, value | count/sum/buckets...}}``."""
    with _lock:
        items = sorted(_registry.items())
    return {name: m.to_dict() for name, m in items}
