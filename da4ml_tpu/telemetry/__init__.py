"""Observability layer: spans, metrics, and trace export (docs/telemetry.md).

Activation (disabled by default, near-zero overhead when off):

- ``DA4ML_TRACE=<path>`` in the environment — opens a trace sink at import
  (``.jsonl`` → streaming event log, else Chrome trace-event JSON for
  Perfetto / chrome://tracing) and enables the metrics registry;
- programmatically: ``telemetry.enable(path)`` / ``telemetry.disable()``;
- ``da4ml-tpu convert --trace <path>`` on the CLI, and ``da4ml-tpu stats
  <path>`` to summarize a captured trace.

Instrumentation API (all safe to call when disabled)::

    from da4ml_tpu import telemetry

    with telemetry.span('cmvm.solve', backend='jax') as sp:
        ...
        sp.set(cost=result.cost)

    telemetry.counter('jit.compile').inc()
    telemetry.histogram('solve.duration_s').observe(dt)
    telemetry.gauge('campaign.done').set(i)
    telemetry.instant('campaign.progress', done=i, total=n)
    log = telemetry.get_logger('cmvm.jax')
"""

from .core import (
    Span,
    active_spans,
    add_sink,
    beat,
    beat_age_s,
    bind_trace,
    collect_phases,
    current_span,
    current_trace,
    current_trace_id,
    disable,
    emit_span,
    enable,
    format_traceparent,
    instant,
    new_span_id,
    new_trace_id,
    parse_traceparent,
    remove_sink,
    reset,
    span,
    tracing_active,
)
from .export import (
    REQUIRED_EVENT_KEYS,
    ChromeTraceSink,
    JsonlSink,
    load_trace,
    sink_for,
    validate_trace,
)
from .log import get_logger, warn_once
from .metrics import (
    BYTES_BUCKETS,
    COUNT_BUCKETS,
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    counter,
    gauge,
    histogram,
    metrics_on,
    metrics_snapshot,
    timer,
)

def serve(port: int | None = None, host: str = '127.0.0.1'):
    """Start the live observability endpoint (``/metrics`` OpenMetrics,
    ``/healthz``, ``/statusz``) on a daemon thread and return the server
    (docs/observability.md). Idempotent; also reachable via
    ``DA4ML_METRICS_PORT=<port>`` or ``da4ml-tpu monitor``. Enables the
    metrics registry so scrapes see data."""
    from .obs.server import serve as _serve

    return _serve(port=port, host=host)


__all__ = [
    'Span',
    'span',
    'instant',
    'emit_span',
    'collect_phases',
    'current_span',
    'active_spans',
    'bind_trace',
    'current_trace',
    'current_trace_id',
    'new_trace_id',
    'new_span_id',
    'format_traceparent',
    'parse_traceparent',
    'beat',
    'beat_age_s',
    'serve',
    'enable',
    'disable',
    'reset',
    'add_sink',
    'remove_sink',
    'tracing_active',
    'sink_for',
    'ChromeTraceSink',
    'JsonlSink',
    'load_trace',
    'validate_trace',
    'REQUIRED_EVENT_KEYS',
    'counter',
    'gauge',
    'histogram',
    'metrics_on',
    'metrics_snapshot',
    'timer',
    'Counter',
    'Gauge',
    'Histogram',
    'DEFAULT_BUCKETS',
    'COUNT_BUCKETS',
    'BYTES_BUCKETS',
    'get_logger',
    'warn_once',
]

from .core import _init_from_env

_init_from_env()
