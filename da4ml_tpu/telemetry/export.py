"""Trace exporters: Chrome trace-event JSON and a JSONL event log.

Both sinks receive the same event dicts from :mod:`.core` (Chrome
trace-event schema: ``name``, ``ph``, ``ts``/``dur`` in microseconds,
``pid``, ``tid``, ``args``).

- :class:`ChromeTraceSink` buffers events in memory and writes one
  ``{"traceEvents": [...], "otherData": {"metrics": ...}}`` JSON document
  at close — load it in Perfetto (https://ui.perfetto.dev) or
  chrome://tracing. The write is atomic (tmp + rename, same idiom as the
  reliability checkpoints).
- :class:`JsonlSink` streams one JSON object per line as events close, so
  a killed process still leaves a readable prefix; the metrics snapshot is
  appended as a final ``ph: "M"`` record at close.

Both sinks record a **clock anchor** at open — one ``(unix_time_us, ts)``
pair sampled back-to-back — that maps the process-local ``ts`` epoch
(``time.perf_counter`` at telemetry import) onto the shared wall clock.
The fleet trace merger (:mod:`.obs.collect`) uses it to align N replica
traces onto one timeline; JSONL traces carry it as a first
``ph: "M"``/``name: "clock_sync"`` record, Chrome traces under
``otherData.clock_sync``.

Both are fork-safe (events from a forked child are dropped — the child
inherited the parent's buffer/handle and must not corrupt its file) and
registered with ``atexit`` so an unclosed trace still flushes.

:func:`load_trace` / :func:`validate_trace` are the shared readers used by
the ``da4ml-tpu stats`` renderer, the tests, and the CI smoke check.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from pathlib import Path

#: keys every exported event must carry (the CI smoke step checks these)
REQUIRED_EVENT_KEYS = ('name', 'ph', 'ts', 'pid', 'tid')


def _json_default(obj):
    return str(obj)


def _clock_anchor() -> dict:
    """One ``(unix wall clock, process-local ts)`` pair sampled back-to-back:
    ``unix_time_us - ts`` is this process's offset onto the shared clock."""
    from .core import _now_us

    return {'unix_time_us': time.time() * 1e6, 'ts': round(_now_us(), 1)}


class ChromeTraceSink:
    def __init__(self, path: 'str | os.PathLike'):
        self.path = Path(path)
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._anchor = _clock_anchor()
        self._closed = False
        atexit.register(self.close)

    def emit(self, event: dict) -> None:
        if self._closed or os.getpid() != self._pid:
            return
        with self._lock:
            self._events.append(event)

    def close(self) -> None:
        if self._closed or os.getpid() != self._pid:
            return
        with self._lock:
            if self._closed:
                return
            self._closed = True
            events = self._events
        from .metrics import metrics_snapshot

        payload = {
            'traceEvents': events,
            'displayTimeUnit': 'ms',
            'otherData': {
                'producer': 'da4ml_tpu.telemetry',
                'pid': self._pid,
                'unix_time': time.time(),
                'clock_sync': self._anchor,
                'metrics': metrics_snapshot(),
            },
        }
        tmp = self.path.with_name(self.path.name + f'.tmp.{self._pid}')
        tmp.parent.mkdir(parents=True, exist_ok=True)
        with open(tmp, 'w') as fh:
            json.dump(payload, fh, default=_json_default)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)


class JsonlSink:
    def __init__(self, path: 'str | os.PathLike'):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = open(self.path, 'w')
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._closed = False
        anchor = _clock_anchor()
        self._fh.write(
            json.dumps(
                {
                    'name': 'clock_sync',
                    'ph': 'M',
                    'ts': anchor['ts'],
                    'pid': self._pid,
                    'tid': 0,
                    'args': {'unix_time_us': anchor['unix_time_us']},
                }
            )
            + '\n'
        )
        self._fh.flush()
        atexit.register(self.close)

    def emit(self, event: dict) -> None:
        if self._closed or os.getpid() != self._pid:
            return
        line = json.dumps(event, default=_json_default)
        with self._lock:
            if not self._closed:
                self._fh.write(line + '\n')

    def close(self) -> None:
        if self._closed or os.getpid() != self._pid:
            return
        from .core import _PID, _now_us
        from .metrics import metrics_snapshot

        with self._lock:
            if self._closed:
                return
            self._closed = True
            snap = metrics_snapshot()
            if snap:
                self._fh.write(
                    json.dumps(
                        {
                            'name': 'metrics',
                            'ph': 'M',
                            'ts': round(_now_us(), 1),
                            'pid': _PID,
                            'tid': 0,
                            'args': {'metrics': snap},
                        },
                        default=_json_default,
                    )
                    + '\n'
                )
            self._fh.close()


def sink_for(path: 'str | os.PathLike'):
    """Pick the exporter from the file extension: ``.jsonl`` streams an
    event log, anything else buffers Chrome trace-event JSON."""
    if str(path).endswith('.jsonl'):
        return JsonlSink(path)
    return ChromeTraceSink(path)


# ---------------------------------------------------------------------------
# readers (stats CLI, tests, CI validation)
# ---------------------------------------------------------------------------


def load_trace(path: 'str | os.PathLike') -> tuple[list[dict], dict]:
    """Read a trace file in either format. Returns ``(events, metrics)``."""
    text = Path(path).read_text()
    if not text.strip():
        return [], {}
    if text.lstrip()[0] == '{' and '\n{' not in text.strip():
        doc = json.loads(text)
        if isinstance(doc, dict) and 'traceEvents' in doc:
            return doc['traceEvents'], doc.get('otherData', {}).get('metrics', {})
        if isinstance(doc, list):
            return doc, {}
    events: list[dict] = []
    metrics_by_pid: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        ev = json.loads(line)
        if ev.get('ph') == 'M' and ev.get('name') == 'metrics':
            # latest mirror per producing process: a merged multi-process
            # trace must aggregate across pids, never double-count one
            # process's repeated snapshots
            metrics_by_pid[ev.get('pid', 0)] = ev.get('args', {}).get('metrics', {})
        else:
            events.append(ev)
    if len(metrics_by_pid) > 1:
        from .obs.collect import merge_metrics

        return events, merge_metrics(metrics_by_pid)
    return events, next(iter(metrics_by_pid.values()), {})


def validate_trace(events: list[dict]) -> None:
    """Raise ``ValueError`` unless every event carries the Chrome trace-event
    required keys with sane types (``dur`` additionally for ``X`` events)."""
    if not events:
        raise ValueError('trace contains no events')
    for i, ev in enumerate(events):
        for key in REQUIRED_EVENT_KEYS:
            if key not in ev:
                raise ValueError(f'event {i} missing required key {key!r}: {ev}')
        if not isinstance(ev['name'], str) or not ev['name']:
            raise ValueError(f'event {i} has a non-string name: {ev}')
        if ev['ph'] not in ('X', 'B', 'E', 'i', 'C', 'M'):
            raise ValueError(f'event {i} has unknown phase {ev["ph"]!r}')
        for key in ('ts', 'pid', 'tid'):
            if not isinstance(ev[key], (int, float)):
                raise ValueError(f'event {i} key {key!r} is not numeric: {ev}')
        if ev['ph'] == 'X' and not isinstance(ev.get('dur'), (int, float)):
            raise ValueError(f'complete event {i} lacks a numeric dur: {ev}')
