"""Telemetry core: hierarchical spans, event sinks, and phase collectors.

Design constraints (docs/telemetry.md):

- **Near-zero overhead when disabled.** ``span()`` checks one module-level
  flag and returns a shared no-op singleton — no object allocation, no
  clock read — so the hot solve path costs one function call + one
  attribute read per instrumentation site when nothing is listening.
- **Thread-safe.** Each thread owns its span stack (parentage never crosses
  threads); sinks are appended under a lock but read lock-free as an
  immutable tuple; span ids come from ``itertools.count`` (atomic under the
  GIL).
- **Fork-safe.** Sinks record their creating pid and drop events from
  forked children (the host dc-sweep uses a fork pool), so a child's atexit
  can never corrupt the parent's trace file. Span ids are re-seeded in
  forked children (``os.register_at_fork``) so a merged fleet timeline
  never aliases two spans from different processes.
- **Fleet-unique identity.** Span ids carry a per-process random epoch in
  their high bits; requests crossing process boundaries share a 128-bit
  trace id propagated via a W3C ``traceparent``-style header
  (:func:`bind_trace` / :func:`parse_traceparent` /
  :func:`format_traceparent` — docs/observability.md#fleet-tracing).

Spans deliver Chrome trace-event ``"X"`` (complete) events to every
registered sink; :func:`instant` delivers ``"i"`` events. Phase collectors
(:func:`collect_phases`) aggregate closed-span durations per name on the
calling thread — the reliability orchestrator uses one to attach phase
timings to a ``SolveReport`` without requiring a trace file.
"""

from __future__ import annotations

import itertools
import os
import threading
import time

_T0 = time.perf_counter()
_PID = os.getpid()


def _span_id_source() -> 'itertools.count[int]':
    """Per-process-seeded span ids: a 31-bit pid+random epoch in the high
    bits over a 32-bit in-process counter. Two processes (or a parent and
    its forked child) can then never mint the same span id, so merged
    multi-replica timelines keep span/parent links unambiguous."""
    epoch = (os.getpid() ^ int.from_bytes(os.urandom(4), 'big')) & 0x7FFFFFFF
    return itertools.count((epoch << 32) | 1)


_ids = _span_id_source()


def _after_fork_child() -> None:
    global _PID, _ids
    _PID = os.getpid()
    _ids = _span_id_source()


if hasattr(os, 'register_at_fork'):  # pragma: no branch
    os.register_at_fork(after_in_child=_after_fork_child)


def _now_us() -> float:
    """Microseconds since the telemetry epoch (module import)."""
    return (time.perf_counter() - _T0) * 1e6


def monotonic_ts_us(t_mono: float) -> float:
    """Map a ``time.monotonic`` stamp onto the trace ``ts`` epoch — for
    emitting spans (:func:`emit_span`) whose brackets were recorded with the
    monotonic clock (the serve queue's waterfall timestamps)."""
    return (time.perf_counter() - _T0 - (time.monotonic() - t_mono)) * 1e6


class _State:
    __slots__ = ('sinks', 'collectors', 'watchers', 'active', 'lock')

    def __init__(self):
        self.sinks: tuple = ()  # immutable tuple -> lock-free reads on the hot path
        self.collectors = 0  # process-wide count of open collect_phases() blocks
        self.watchers = 0  # live span observers (the /statusz endpoint)
        self.active = False  # sinks, collectors, or watchers present
        self.lock = threading.Lock()

    def refresh(self) -> None:
        self.active = bool(self.sinks) or self.collectors > 0 or self.watchers > 0


_state = _State()
_tls = threading.local()

#: spans currently open anywhere in the process (span_id -> Span); only
#: populated while telemetry is active (disabled spans are the shared no-op
#: singleton and never registered). /statusz renders this live.
_active_spans: dict[int, 'Span'] = {}

#: liveness heartbeats: name -> last-beat monotonic clock. Written by
#: long-running drivers (solve_many campaigns), read by the /healthz
#: endpoint to detect stalled workers. Plain dict ops are atomic under the
#: GIL; no lock needed.
_heartbeats: dict[str, float] = {}


def beat(name: str) -> None:
    """Record a liveness heartbeat for ``name`` (monotonic clock). Unlike
    metrics this is always on — it is one dict store, and health checks
    must work even when the metrics registry is disabled."""
    _heartbeats[name] = time.monotonic()


def beat_age_s(name: str) -> float | None:
    """Seconds since the last :func:`beat` for ``name``, or None if never."""
    t = _heartbeats.get(name)
    return None if t is None else time.monotonic() - t


def current_span() -> 'Span | None':
    """The innermost open span of the calling thread, or None."""
    st = getattr(_tls, 'stack', None)
    return st[-1] if st else None


# ---------------------------------------------------------------------------
# trace context: fleet-unique identity + traceparent-style propagation
# ---------------------------------------------------------------------------


def new_trace_id() -> str:
    """A fresh 128-bit trace id as 32 lowercase hex chars."""
    return os.urandom(16).hex()


def new_span_id() -> int:
    """Mint a fleet-unique span id without opening a span — used by callers
    that must hand a child span id to a remote party (the router's hedge
    legs) before the span's duration is known."""
    return next(_ids)


def format_traceparent(trace_id: str, span_id: int | None = None) -> str:
    """Render a W3C ``traceparent``-style header value:
    ``00-<32 hex trace id>-<16 hex parent span id>-01``."""
    return f'00-{trace_id}-{(span_id or 0) & 0xFFFFFFFFFFFFFFFF:016x}-01'


def parse_traceparent(header: 'str | None') -> 'tuple[str, int | None] | None':
    """Parse a ``traceparent`` header into ``(trace_id, parent_span_id)``.
    Returns None for anything malformed (wrong version, lengths, non-hex,
    all-zero trace id); an all-zero parent id maps to ``None`` parent."""
    if not header:
        return None
    parts = header.strip().lower().split('-')
    if len(parts) < 4 or parts[0] != '00' or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        t_val = int(parts[1], 16)
        s_val = int(parts[2], 16)
    except ValueError:
        return None
    if t_val == 0:
        return None
    return parts[1], (s_val or None)


class bind_trace:
    """Bind a trace context to the calling thread for the ``with`` block.

    Spans opened inside the block carry ``trace_id`` in their emitted args,
    and a root span (no in-thread parent) adopts ``parent_span_id`` as its
    parent — stitching this process's subtree under the remote caller's
    span in a merged timeline. Mints a fresh 128-bit trace id when none is
    given. Bindings nest; the previous context is restored on exit.
    """

    __slots__ = ('trace_id', 'parent_span_id', '_prev')

    def __init__(self, trace_id: 'str | None' = None, parent_span_id: 'int | None' = None):
        self.trace_id = trace_id or new_trace_id()
        self.parent_span_id = parent_span_id

    def __enter__(self) -> 'bind_trace':
        self._prev = getattr(_tls, 'trace', None)
        _tls.trace = (self.trace_id, self.parent_span_id)
        return self

    def __exit__(self, exc_type, exc, tb):
        _tls.trace = self._prev
        return False


def current_trace() -> 'tuple[str, int | None] | None':
    """The calling thread's bound ``(trace_id, parent_span_id)``, or None."""
    return getattr(_tls, 'trace', None)


def current_trace_id() -> 'str | None':
    """The calling thread's bound trace id, or None."""
    tb = getattr(_tls, 'trace', None)
    return tb[0] if tb is not None else None


def active_spans() -> list[dict]:
    """Snapshot of every span currently open in the process (any thread),
    oldest first: ``{span_id, parent_id, name, age_s, attrs}``."""
    now = time.perf_counter()
    out = []
    for sp in sorted(_active_spans.values(), key=lambda s: s.t0):
        out.append(
            {
                'span_id': sp.span_id,
                'parent_id': sp.parent_id,
                'name': sp.name,
                'age_s': round(now - sp.t0, 6) if sp.t0 else 0.0,
                'attrs': {k: v for k, v in sp.attrs.items()},
            }
        )
    return out


def _stack() -> list:
    st = getattr(_tls, 'stack', None)
    if st is None:
        st = _tls.stack = []
    return st


def _collectors() -> list:
    pc = getattr(_tls, 'phases', None)
    if pc is None:
        pc = _tls.phases = []
    return pc


def _tid() -> int:
    return threading.get_ident() & 0x7FFFFFFF


def _emit(event: dict) -> None:
    for sink in _state.sinks:
        try:
            sink.emit(event)
        except Exception:
            pass  # a broken sink must never fail the instrumented call


class Span:
    """One timed region. Context manager; nests via the per-thread stack."""

    __slots__ = ('name', 'attrs', 'span_id', 'parent_id', 'trace_id', 't0', 'ts_us', 'duration_s')

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.span_id = next(_ids)
        self.parent_id: int | None = None
        self.trace_id: str | None = None
        self.t0 = 0.0
        self.ts_us = 0.0
        self.duration_s = 0.0

    def set(self, **attrs) -> 'Span':
        """Attach attributes after entry (e.g. a result count known at exit)."""
        self.attrs.update(attrs)
        return self

    def __bool__(self) -> bool:
        return True

    def __enter__(self) -> 'Span':
        st = _stack()
        self.parent_id = st[-1].span_id if st else None
        tb = getattr(_tls, 'trace', None)
        if tb is not None:
            self.trace_id = tb[0]
            if self.parent_id is None:
                self.parent_id = tb[1]
        st.append(self)
        self.t0 = time.perf_counter()
        self.ts_us = (self.t0 - _T0) * 1e6
        _active_spans[self.span_id] = self
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration_s = time.perf_counter() - self.t0
        _active_spans.pop(self.span_id, None)
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        elif self in st:  # unbalanced exit: drop self and everything above it
            del st[st.index(self) :]
        if exc_type is not None:
            self.attrs['error'] = exc_type.__name__
        if _state.sinks:
            args = dict(self.attrs)
            args['span_id'] = self.span_id
            if self.parent_id is not None:
                args['parent_id'] = self.parent_id
            if self.trace_id is not None:
                args['trace_id'] = self.trace_id
            _emit(
                {
                    'name': self.name,
                    'ph': 'X',
                    'ts': round(self.ts_us, 1),
                    'dur': round(self.duration_s * 1e6, 1),
                    'pid': _PID,
                    'tid': _tid(),
                    'args': args,
                }
            )
        for phases in _collectors():
            phases[self.name] = phases.get(self.name, 0.0) + self.duration_s
        return False


class _NoopSpan:
    """Shared disabled-path span: reusable, reentrant, allocation-free."""

    __slots__ = ()
    span_id = None
    parent_id = None
    trace_id = None
    duration_s = 0.0

    def set(self, **attrs) -> '_NoopSpan':
        return self

    def __bool__(self) -> bool:
        return False

    def __enter__(self) -> '_NoopSpan':
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP_SPAN = _NoopSpan()


def span(name: str, /, **attrs):
    """A timed region. Returns the no-op singleton when telemetry is off.

    ``name`` is positional-only so an attribute may also be called "name"
    (e.g. ``span('codegen.rtl.write', name=model.name)``)."""
    if not _state.active:
        return _NOOP_SPAN
    return Span(name, attrs)


def instant(name: str, /, **attrs) -> None:
    """A point-in-time event (campaign heartbeats, breaker transitions).
    Carries the thread's bound trace id (:class:`bind_trace`) so log
    mirrors and access-log records correlate with their request trace."""
    if not _state.sinks:
        return
    tb = getattr(_tls, 'trace', None)
    if tb is not None and 'trace_id' not in attrs:
        attrs['trace_id'] = tb[0]
    _emit(
        {
            'name': name,
            'ph': 'i',
            's': 't',
            'ts': round(_now_us(), 1),
            'pid': _PID,
            'tid': _tid(),
            'args': attrs,
        }
    )


def emit_span(
    name: str,
    ts_us: float,
    duration_s: float,
    *,
    trace_id: 'str | None' = None,
    parent_id: 'int | None' = None,
    span_id: 'int | None' = None,
    **attrs,
) -> int:
    """Emit a completed span event directly, bypassing the thread stack.

    For cross-thread waterfall segments whose begin and end are observed on
    a different thread than the owning request (the serve engine's batcher
    recording per-request queue/execute/serialize segments, the router's
    hedge legs): the caller supplies explicit timing and parentage instead
    of inheriting the emitting thread's stack. Returns the span id used
    (minted when not supplied), or 0 when no sink is registered.
    """
    if not _state.sinks:
        return 0
    sid = span_id if span_id is not None else next(_ids)
    args = dict(attrs)
    args['span_id'] = sid
    if parent_id is not None:
        args['parent_id'] = parent_id
    if trace_id is not None:
        args['trace_id'] = trace_id
    _emit(
        {
            'name': name,
            'ph': 'X',
            'ts': round(ts_us, 1),
            'dur': round(duration_s * 1e6, 1),
            'pid': _PID,
            'tid': _tid(),
            'args': args,
        }
    )
    return sid


class _PhaseCollector:
    """Aggregates closed-span durations by name on the entering thread."""

    __slots__ = ('phases',)

    def __enter__(self) -> dict:
        self.phases: dict[str, float] = {}
        _collectors().append(self.phases)
        with _state.lock:
            _state.collectors += 1
            _state.refresh()
        return self.phases

    def __exit__(self, exc_type, exc, tb):
        pcs = _collectors()
        if self.phases in pcs:
            pcs.remove(self.phases)
        with _state.lock:
            _state.collectors -= 1
            _state.refresh()
        return False


def collect_phases() -> _PhaseCollector:
    """Context manager yielding a ``{span name: cumulative seconds}`` dict
    of every span closed on this thread while the block is open. Activates
    the span machinery even without a trace sink."""
    return _PhaseCollector()


# ---------------------------------------------------------------------------
# sink management / activation
# ---------------------------------------------------------------------------


def add_sink(sink) -> None:
    with _state.lock:
        _state.sinks = _state.sinks + (sink,)
        _state.refresh()


def remove_sink(sink) -> None:
    with _state.lock:
        _state.sinks = tuple(s for s in _state.sinks if s is not sink)
        _state.refresh()


def tracing_active() -> bool:
    """True when at least one event sink is registered."""
    return bool(_state.sinks)


def add_span_watcher() -> None:
    """Arm real (registered) spans without a trace sink, so ``active_spans``
    reflects live work — held by the /statusz endpoint for its lifetime.
    Spans still emit nothing; the only cost over the no-op path is the
    per-span object and stack bookkeeping."""
    with _state.lock:
        _state.watchers += 1
        _state.refresh()


def remove_span_watcher() -> None:
    with _state.lock:
        _state.watchers = max(0, _state.watchers - 1)
        _state.refresh()


def enable(path: 'str | os.PathLike | None' = None, metrics: bool = True):
    """Turn telemetry on: enable the metrics registry and (optionally) open a
    trace sink at ``path`` (``.jsonl`` → JSONL event log, anything else →
    Chrome trace-event JSON for Perfetto / chrome://tracing).

    Returns the created sink (or None when no path was given). Equivalent to
    setting ``DA4ML_TRACE=<path>`` in the environment before import.
    """
    if metrics:
        from .metrics import enable_metrics

        enable_metrics()
    if path:
        from .export import sink_for

        sink = sink_for(path)
        add_sink(sink)
        return sink
    return None


def disable() -> None:
    """Close and unregister every sink (flushing trace files) and freeze the
    metrics registry. Recorded metric values are kept until :func:`reset`."""
    with _state.lock:
        sinks, _state.sinks = _state.sinks, ()
        _state.refresh()
    for sink in sinks:
        try:
            sink.close()
        except Exception:
            pass
    from .metrics import disable_metrics

    disable_metrics()


def reset() -> None:
    """Full teardown for test isolation: close sinks, drop all metric values."""
    disable()
    from .metrics import reset_metrics

    reset_metrics()
    _heartbeats.clear()
    _active_spans.clear()


def _init_from_env() -> None:
    path = os.environ.get('DA4ML_TRACE')
    if path:
        enable(path)
    port = os.environ.get('DA4ML_METRICS_PORT')
    if port:
        # opt-in live endpoint; a bad port value or bind failure must never
        # break the instrumented process at import time
        try:
            from .obs.server import serve

            serve(port=int(port))
        except Exception as e:
            from .log import get_logger

            get_logger('telemetry.obs').warning(f'DA4ML_METRICS_PORT={port!r}: could not start endpoint: {e}')
