"""Stdlib HTTP endpoint for live observability.

``serve(port)`` binds a :class:`http.server.ThreadingHTTPServer` on a
daemon thread (named ``da4ml-obs-server``) and enables the metrics
registry so scrapes see data. Three routes:

- ``GET /metrics``  — OpenMetrics text (:mod:`.openmetrics`)
- ``GET /healthz``  — JSON health document; HTTP 200 when ``ok``,
  503 when ``degraded``
- ``GET /statusz``  — JSON status document (autotune decisions,
  scheduler occupancy, active spans, ...)

Off by default: no server object exists and no thread is spawned until
``serve()`` runs (``telemetry.serve(port)``, ``DA4ML_METRICS_PORT``, or
``da4ml-tpu monitor``). Fork-safe: the serving thread never survives a
fork, and a forked child's ``serve()`` starts a fresh server rather than
touching the parent's socket. Providers are injectable so ``da4ml-tpu
monitor --follow`` can serve metrics mirrored from another process's
streaming trace instead of this process's registry.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlsplit

from .openmetrics import CONTENT_TYPE, render_openmetrics

_lock = threading.Lock()
_server: 'ObsServer | None' = None
_atexit_registered = False


class ObsServer:
    def __init__(
        self,
        port: int,
        host: str = '127.0.0.1',
        metrics_provider=None,
        health_provider=None,
        status_provider=None,
    ):
        from .health import health_snapshot, status_snapshot

        self.metrics_provider = metrics_provider or (lambda: render_openmetrics())
        self.health_provider = health_provider or health_snapshot
        self.status_provider = status_provider or status_snapshot
        self._pid = os.getpid()
        obs = self

        class _Handler(BaseHTTPRequestHandler):
            server_version = 'da4ml-obs'
            protocol_version = 'HTTP/1.1'

            def log_message(self, fmt, *args):  # quiet: scrapes are periodic
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header('Content-Type', ctype)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = urlsplit(self.path).path
                try:
                    if path == '/metrics':
                        body = obs.metrics_provider().encode()
                        self._send(200, body, CONTENT_TYPE)
                    elif path == '/healthz':
                        doc = obs.health_provider()
                        code = 200 if doc.get('status') == 'ok' else 503
                        self._send(code, json.dumps(doc, indent=1, default=str).encode(), 'application/json')
                    elif path == '/statusz':
                        doc = obs.status_provider()
                        self._send(200, json.dumps(doc, indent=1, default=str).encode(), 'application/json')
                    elif path in ('/', ''):
                        body = b'da4ml_tpu observability: /metrics /healthz /statusz\n'
                        self._send(200, body, 'text/plain; charset=utf-8')
                    else:
                        self._send(404, b'not found\n', 'text/plain; charset=utf-8')
                except Exception as e:  # a broken provider must not kill the thread
                    try:
                        self._send(500, f'internal error: {type(e).__name__}: {e}\n'.encode(), 'text/plain; charset=utf-8')
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        # a live endpoint arms real spans (no sink needed) so /statusz can
        # show what the process is doing right now
        from ..core import add_span_watcher

        add_span_watcher()
        self._watching = True
        self._thread = threading.Thread(target=self._httpd.serve_forever, name='da4ml-obs-server', daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f'http://{self.host}:{self.port}'

    def close(self) -> None:
        if self._watching:
            self._watching = False
            from ..core import remove_span_watcher

            remove_span_watcher()
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass


def serve(
    port: int | None = None,
    host: str = '127.0.0.1',
    metrics_provider=None,
    health_provider=None,
    status_provider=None,
) -> ObsServer:
    """Start (or return the already-running) observability endpoint.

    ``port=None`` reads ``DA4ML_METRICS_PORT`` (0 = ephemeral, surfaced via
    ``server.port``). Enables the metrics registry — a live endpoint with
    an empty registry would be useless.
    """
    global _server, _atexit_registered
    from ..metrics import enable_metrics

    with _lock:
        if _server is not None and _server._pid == os.getpid():
            return _server
        if port is None:
            try:
                port = int(os.environ.get('DA4ML_METRICS_PORT', '') or 0)
            except ValueError:
                port = 0
        enable_metrics()
        _server = ObsServer(
            port,
            host,
            metrics_provider=metrics_provider,
            health_provider=health_provider,
            status_provider=status_provider,
        )
        if not _atexit_registered:
            # drain the serving socket at interpreter exit instead of
            # abandoning the daemon thread mid-write; _stop_at_exit checks
            # the owning pid, so a forked child never closes its parent's
            # socket (THREAD_TABLE['da4ml-obs-server'])
            atexit.register(_stop_at_exit)
            _atexit_registered = True
        return _server


def server_port() -> int | None:
    """The bound port of this process's endpoint, or None when not serving."""
    s = _server
    return s.port if s is not None and s._pid == os.getpid() else None


def stop_server() -> None:
    """Shut the endpoint down (test isolation; production servers live
    until interpreter exit, where the atexit hook drains them)."""
    global _server
    with _lock:
        s, _server = _server, None
    if s is not None:
        s.close()


def _stop_at_exit() -> None:
    """atexit hook: close this process's server only (fork-safe — a child
    inherits ``_server`` but must not shut down the parent's socket)."""
    s = _server
    if s is not None and s._pid == os.getpid():
        stop_server()
