"""Perf-regression gates over BENCH/metrics snapshots.

``da4ml-tpu bench-diff A.json B.json [--budget budgets.toml]`` flattens
two snapshots into ``dotted.metric -> float`` maps, compares the
intersection under per-metric tolerance budgets, and exits nonzero on any
regression — so the perf claims committed in the ``BENCH_r0*.json``
trajectory stop being unguarded prose.

Accepted snapshot shapes (auto-detected):

- ``bench.py`` output: ``{"metric", "value", "detail": {"configs": [...],
  ...}}`` — configs flatten as ``configs.<name>.<key>``;
- the driver-wrapped capture committed as ``BENCH_r0*.json``
  (``{"n", "cmd", "rc", "tail", "parsed"}``): ``parsed`` when present,
  otherwise metrics are **recovered from the truncated stdout tail** by
  scanning for balanced JSON objects (config entries, named sections) and
  trailing top-level scalars;
- a ``telemetry.metrics_snapshot()`` dict (counters/gauges flatten to
  their value, histograms to ``.mean`` / ``.count``).

Budget semantics (docs/observability.md#budgets): metrics are classified
by name — *exactness* (``exact``, ``bit_exact``: may never drop), *cost*
(``*cost*``: lower-better, default +2% ceiling), *rate* (``*_rate``,
``*_per_s``, ``speedup*``, the headline ``value``: higher-better, default
-50% floor — wide because committed rounds span different machines; CI
budgets tighten it). Wall-clock/compile-time metrics are reported but
never gate by default (machine-dependent noise); a budgets file can add
rules for them. TOML budgets override defaults per metric name or
``fnmatch`` pattern.
"""

from __future__ import annotations

import fnmatch
import json
import re
from pathlib import Path

# ---------------------------------------------------------------------------
# snapshot loading / flattening
# ---------------------------------------------------------------------------

_NUM = (int, float)


def _flatten(obj, prefix: str, out: dict[str, float]) -> None:
    if isinstance(obj, bool):
        out[prefix] = 1.0 if obj else 0.0
        return
    if isinstance(obj, _NUM):
        out[prefix] = float(obj)
        return
    if isinstance(obj, str):
        m = re.fullmatch(r'(\d+)\s*/\s*(\d+)', obj)  # "16/16" exactness ratios
        if m and int(m.group(2)):
            out[prefix] = int(m.group(1)) / int(m.group(2))
        return
    if isinstance(obj, dict):
        for k, v in obj.items():
            _flatten(v, f'{prefix}.{k}' if prefix else str(k), out)
        return
    if isinstance(obj, list):
        for i, v in enumerate(obj):
            key = f'{prefix}.{i}'
            if isinstance(v, dict) and isinstance(v.get('config'), str):
                key = f'{prefix}.{v["config"]}'
            _flatten(v, key, out)


def flatten_bench(doc: dict) -> dict[str, float]:
    """One parsed snapshot document -> flat ``dotted.metric: float`` map."""
    out: dict[str, float] = {}
    if 'traceEvents' in doc:  # a Chrome trace: use its embedded metrics
        doc = doc.get('otherData', {}).get('metrics', {})
    if _looks_like_metrics_snapshot(doc):
        for name, m in doc.items():
            kind = m.get('type')
            if kind in ('counter', 'gauge'):
                out[name] = float(m.get('value', 0.0))
            elif kind == 'histogram':
                out[f'{name}.count'] = float(m.get('count', 0))
                if m.get('count'):
                    out[f'{name}.mean'] = float(m.get('mean', m.get('sum', 0.0) / m['count']))
        return out
    detail = doc.get('detail') if isinstance(doc.get('detail'), dict) else None
    if detail is not None:
        for k, v in doc.items():
            if k != 'detail' and isinstance(v, _NUM):
                out[k] = float(v)
        _flatten_detail(detail, out)
        return out
    _flatten_detail(doc, out)
    return out


def _flatten_detail(detail: dict, out: dict[str, float]) -> None:
    skip = {'last_known_tpu', 'config1_top4'}  # prior-round attachments, not this run
    for k, v in detail.items():
        if k in skip:
            continue
        _flatten(v, k, out)


def _looks_like_metrics_snapshot(doc: dict) -> bool:
    if not doc:
        return False
    vals = list(doc.values())
    return all(isinstance(v, dict) and v.get('type') in ('counter', 'gauge', 'histogram') for v in vals)


def _scan_tail(tail: str) -> dict[str, float]:
    """Recover metrics from a *truncated* bench stdout tail.

    The committed ``BENCH_r0*.json`` captures hold only the last N bytes
    of the bench JSON line — unparsable as a document. Balanced JSON
    objects are still recoverable: config entries (``{"config": ...}``),
    named sections (``"quality_sweep": {...}``), and any top-level scalars
    after the last recovered object."""
    dec = json.JSONDecoder()
    out: dict[str, float] = {}
    pos = 0
    last_end = 0
    while True:
        b = tail.find('{', pos)
        if b < 0:
            break
        try:
            obj, end = dec.raw_decode(tail, b)
        except ValueError:
            pos = b + 1
            continue
        if isinstance(obj, dict) and obj:
            if isinstance(obj.get('config'), str):
                _flatten(obj, f'configs.{obj["config"]}', out)
            else:
                # name the object from the `"key": ` immediately before it
                m = re.search(r'"([A-Za-z0-9_.-]+)"\s*:\s*$', tail[:b])
                if m:
                    _flatten(obj, m.group(1), out)
            last_end = max(last_end, end)
        pos = end if end > b else b + 1
    # trailing top-level scalars, e.g. `"full_model_cold_over_warm": 5.63}}`
    for m in re.finditer(r'"([A-Za-z0-9_.-]+)"\s*:\s*(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)', tail[last_end:]):
        out.setdefault(m.group(1), float(m.group(2)))
    return out


def load_bench_metrics(path: 'str | Path') -> dict[str, float]:
    """Load any accepted snapshot file into a flat metric map."""
    text = Path(path).read_text()
    doc = json.loads(text)
    if not isinstance(doc, dict):
        raise ValueError(f'{path}: expected a JSON object, got {type(doc).__name__}')
    if 'tail' in doc and 'cmd' in doc:  # driver-wrapped BENCH_r0*.json capture
        parsed = doc.get('parsed')
        if isinstance(parsed, dict):
            return flatten_bench(parsed)
        tail = doc.get('tail') or ''
        try:  # the tail may happen to be the complete JSON line
            inner = json.loads(tail)
            if isinstance(inner, dict):
                return flatten_bench(inner)
        except ValueError:
            pass
        return _scan_tail(tail)
    return flatten_bench(doc)


# ---------------------------------------------------------------------------
# budgets
# ---------------------------------------------------------------------------

#: built-in tolerances; a budgets file overrides any of these per pattern
DEFAULT_BUDGET = {
    'rate_drop_pct': 50.0,  # higher-better metrics may drop this much
    'cost_rise_pct': 2.0,  # lower-better quality metrics may rise this much
    'exact_drop': 0.0,  # exactness ratios may never drop
}

_EXACT_LAST = ('exact', 'bit_exact', 'pipeline_bit_exact')
_RATE_SUFFIX = ('_rate', '_per_s', '_throughput')


def classify_metric(name: str) -> str:
    """'exact' | 'cost' | 'rate' | 'info' from the dotted metric name."""
    last = name.rsplit('.', 1)[-1]
    if last in _EXACT_LAST or last.endswith('_bit_exact'):
        return 'exact'
    if 'cost' in last:
        return 'cost'
    if last.endswith(_RATE_SUFFIX) or last.startswith('speedup') or last == 'value':
        return 'rate'
    return 'info'


class Budgets:
    """Default tolerances + per-pattern rule overrides."""

    def __init__(self, defaults: dict | None = None, rules: dict[str, dict] | None = None):
        self.defaults = dict(DEFAULT_BUDGET, **(defaults or {}))
        self.rules = dict(rules or {})  # pattern -> {max_drop_pct|max_rise_pct|ignore}

    def rule_for(self, name: str) -> 'dict | None':
        if name in self.rules:
            return self.rules[name]
        for pattern, rule in self.rules.items():
            if fnmatch.fnmatchcase(name, pattern):
                return rule
        return None


def _parse_toml_minimal(text: str) -> dict:
    """Tiny TOML subset for budgets files on py<3.11 (no tomllib): table
    headers (possibly with one quoted dotted part), ``key = value`` with
    number / boolean / quoted-string values, comments, blank lines."""
    doc: dict = {}
    table = doc
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith('#'):
            continue
        if line.startswith('['):
            if not line.endswith(']'):
                raise ValueError(f'bad table header: {raw!r}')
            header = line[1:-1].strip()
            # split on dots outside quotes: rules."configs.*.jax_rate"
            parts: list[str] = []
            buf, quoted = '', False
            for ch in header:
                if ch == '"':
                    quoted = not quoted
                elif ch == '.' and not quoted:
                    parts.append(buf)
                    buf = ''
                else:
                    buf += ch
            parts.append(buf)
            table = doc
            for part in parts:
                table = table.setdefault(part, {})
            continue
        key, sep, val = line.partition('=')
        if not sep:
            raise ValueError(f'bad line in budgets file: {raw!r}')
        key = key.strip().strip('"')
        val = val.split('#', 1)[0].strip()
        if val in ('true', 'false'):
            table[key] = val == 'true'
        elif val.startswith('"') and val.endswith('"') and len(val) >= 2:
            table[key] = val[1:-1]
        else:
            try:
                table[key] = int(val)
            except ValueError:
                table[key] = float(val)
    return doc


def load_budgets(path: 'str | Path | None') -> Budgets:
    """Load a budgets TOML (None -> built-in defaults).

    Format::

        [default]
        rate_drop_pct = 40.0
        cost_rise_pct = 2.0

        [rules."configs.*.jax_rate"]
        max_drop_pct = 10.0

        [rules."configs.*.jax_compile_s"]
        max_rise_pct = 100.0       # opt a wall-clock metric into gating

        [rules."fleet.p99_ms"]
        max_value = 250.0          # absolute ceiling on the current value

        [rules."configs.*.host_rate"]
        ignore = true
    """
    if path is None:
        return Budgets()
    text = Path(path).read_text()
    try:
        import tomllib  # py3.11+

        doc = tomllib.loads(text)
    except ModuleNotFoundError:
        doc = _parse_toml_minimal(text)
    return Budgets(defaults=doc.get('default'), rules=doc.get('rules'))


# ---------------------------------------------------------------------------
# diff
# ---------------------------------------------------------------------------


def _pct(a: float, b: float) -> 'float | None':
    if a == 0:
        return None
    return (b - a) / abs(a) * 100.0


def diff_metrics(a: dict[str, float], b: dict[str, float], budgets: 'Budgets | None' = None) -> dict:
    """Compare snapshot B against baseline A under the budgets.

    Returns ``{'rows': [...], 'regressions': [...], 'n_compared': int,
    'only_a': [...], 'only_b': [...]}``; each row is ``{metric, kind, a, b,
    delta_pct, limit, status}`` with status ``ok`` / ``regressed`` /
    ``info`` / ``ignored``."""
    budgets = budgets or Budgets()
    rows: list[dict] = []
    common = sorted(set(a) & set(b))
    for name in common:
        va, vb = a[name], b[name]
        kind = classify_metric(name)
        rule = budgets.rule_for(name)
        delta = _pct(va, vb)
        row = {'metric': name, 'kind': kind, 'a': va, 'b': vb, 'delta_pct': None if delta is None else round(delta, 2)}
        if rule is not None and rule.get('ignore'):
            row.update(status='ignored', limit='ignored')
            rows.append(row)
            continue
        limit: str | None = None
        status = 'info'
        max_drop = rule.get('max_drop_pct') if rule else None
        max_rise = rule.get('max_rise_pct') if rule else None
        min_value = rule.get('min_value') if rule else None
        max_value = rule.get('max_value') if rule else None
        if max_drop is None and max_rise is None and (min_value is not None or max_value is not None):
            # an absolute floor/ceiling alone opts the metric out of the
            # relative defaults — the bound IS the budget
            pass
        elif max_drop is None and max_rise is None:
            # defaults by classification
            if kind == 'exact':
                max_drop = budgets.defaults['exact_drop']
            elif kind == 'cost':
                max_rise = budgets.defaults['cost_rise_pct']
            elif kind == 'rate':
                max_drop = budgets.defaults['rate_drop_pct']
        if max_drop is not None:
            limit = f'drop<={max_drop:g}%'
            if kind == 'exact':
                status = 'regressed' if va - vb > max_drop / 100.0 + 1e-12 else 'ok'
            else:
                status = 'regressed' if delta is not None and -delta > max_drop + 1e-9 else 'ok'
                if delta is None and vb < va:
                    status = 'regressed'  # baseline 0 -> any drop below is real
        if max_rise is not None:
            limit = (limit + ',' if limit else '') + f'rise<={max_rise:g}%'
            if delta is not None and delta > max_rise + 1e-9:
                status = 'regressed'
            elif status == 'info':
                status = 'ok'
        if min_value is not None:
            # absolute floor on the CURRENT value (baseline-independent):
            # gates a hard-won level — e.g. the device-resident ladder's
            # jax_rate — rather than a relative drop from a noisy baseline
            limit = (limit + ',' if limit else '') + f'min>={min_value:g}'
            if vb < min_value - 1e-9:
                status = 'regressed'
            elif status == 'info':
                status = 'ok'
        if max_value is not None:
            # absolute ceiling on the CURRENT value — gates a latency-class
            # metric (e.g. the fleet drill's p99) against a hard budget
            # instead of a relative rise from a noisy baseline
            limit = (limit + ',' if limit else '') + f'max<={max_value:g}'
            if vb > max_value + 1e-9:
                status = 'regressed'
            elif status == 'info':
                status = 'ok'
        row.update(status=status, limit=limit or '-')
        rows.append(row)
    regressions = [r for r in rows if r['status'] == 'regressed']
    return {
        'rows': rows,
        'regressions': regressions,
        'n_compared': len(common),
        'only_a': sorted(set(a) - set(b)),
        'only_b': sorted(set(b) - set(a)),
    }


def render_diff(result: dict, verbose: bool = False) -> str:
    """Human-readable diff table; regressions always shown, ok/info rows
    only under ``verbose``."""
    lines: list[str] = []
    shown = [r for r in result['rows'] if verbose or r['status'] == 'regressed']
    if shown:
        w = max(len('metric'), *(len(r['metric']) for r in shown))
        lines.append(f'{"metric":<{w}}  {"kind":<6} {"baseline":>12}  {"current":>12}  {"delta":>8}  {"limit":>14}  status')
        for r in shown:
            delta = '-' if r['delta_pct'] is None else f'{r["delta_pct"]:+.1f}%'
            lines.append(
                f'{r["metric"]:<{w}}  {r["kind"]:<6} {r["a"]:>12.4g}  {r["b"]:>12.4g}'
                f'  {delta:>8}  {r["limit"]:>14}  {r["status"]}'
            )
    n_reg = len(result['regressions'])
    lines.append(
        f'{result["n_compared"]} metrics compared, {n_reg} regression{"s" if n_reg != 1 else ""}'
        f' ({len(result["only_a"])} only in baseline, {len(result["only_b"])} only in current)'
    )
    return '\n'.join(lines)


# ---------------------------------------------------------------------------
# CLI (`da4ml-tpu bench-diff`)
# ---------------------------------------------------------------------------


def add_bench_diff_args(parser) -> None:
    parser.add_argument('baseline', help='Baseline snapshot (bench JSON, BENCH_r0*.json capture, or metrics snapshot)')
    parser.add_argument('current', help='Snapshot to gate against the baseline')
    parser.add_argument('--budget', default=None, help='Budgets TOML overriding the default tolerances')
    parser.add_argument('--json', action='store_true', help='Emit the full diff as JSON')
    parser.add_argument('-v', '--verbose', action='store_true', help='Show all compared metrics, not just regressions')


def bench_diff_main(args) -> int:
    from ..log import get_logger

    log = get_logger('cli.bench_diff')
    try:
        a = load_bench_metrics(args.baseline)
        b = load_bench_metrics(args.current)
        budgets = load_budgets(args.budget)
    except (OSError, ValueError) as e:
        log.warning(f'bench-diff: {e}')
        return 2
    if not a or not b:
        log.warning(f'bench-diff: no numeric metrics recovered ({args.baseline}: {len(a)}, {args.current}: {len(b)})')
        return 2
    result = diff_metrics(a, b, budgets)
    if args.json:
        print(json.dumps(result, indent=1))
    else:
        print(render_diff(result, verbose=args.verbose))
    return 1 if result['regressions'] else 0
