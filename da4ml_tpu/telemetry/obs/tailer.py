"""Incremental reader for a growing JSONL telemetry trace.

``DA4ML_TRACE=<x>.jsonl`` streams one event per line as spans close, so a
long campaign can be watched from outside the process without the HTTP
endpoint: ``da4ml-tpu stats --follow trace.jsonl`` re-renders the summary
as the file grows, and ``da4ml-tpu monitor --follow trace.jsonl`` serves
the mirrored metrics over ``/metrics``.

:class:`TraceTailer` keeps a byte offset and a partial-line buffer, so
each :meth:`poll` parses only the newly appended complete lines; a
truncated/rotated file (size shrank) resets the reader.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path


class TraceTailer:
    def __init__(self, path: 'str | os.PathLike'):
        self.path = Path(path)
        self.events: list[dict] = []
        self.metrics: dict = {}
        self.n_bad_lines = 0
        self._pos = 0
        self._buf = ''
        self._last_new = time.monotonic()

    def poll(self) -> int:
        """Read any newly appended complete lines; returns the number of new
        events absorbed (metrics records update :attr:`metrics` instead)."""
        try:
            size = self.path.stat().st_size
        except OSError:
            return 0
        if size < self._pos:  # truncated or rotated: start over
            self._pos = 0
            self._buf = ''
            self.events.clear()
            self.metrics = {}
        if size == self._pos:
            return 0
        with open(self.path) as fh:
            fh.seek(self._pos)
            chunk = fh.read()
            self._pos = fh.tell()
        self._buf += chunk
        lines = self._buf.split('\n')
        self._buf = lines.pop()  # trailing partial line (or '')
        n_new = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                self.n_bad_lines += 1
                continue
            if ev.get('ph') == 'M' and ev.get('name') == 'metrics':
                self.metrics = ev.get('args', {}).get('metrics', {})
            else:
                self.events.append(ev)
                n_new += 1
        if n_new:
            self._last_new = time.monotonic()
        return n_new

    @property
    def staleness_s(self) -> float:
        """Seconds since the last new event was absorbed."""
        return time.monotonic() - self._last_new
