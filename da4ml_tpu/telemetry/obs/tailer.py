"""Incremental reader for a growing JSONL telemetry trace.

``DA4ML_TRACE=<x>.jsonl`` streams one event per line as spans close, so a
long campaign can be watched from outside the process without the HTTP
endpoint: ``da4ml-tpu stats --follow trace.jsonl`` re-renders the summary
as the file grows, and ``da4ml-tpu monitor --follow trace.jsonl`` serves
the mirrored metrics over ``/metrics``.

:class:`TraceTailer` keeps a byte offset and a partial-line buffer, so
each :meth:`poll` parses only the newly appended complete lines; a
truncated/rotated file (size shrank) resets the reader.

Merged multi-process traces (``da4ml-tpu trace-view`` output, or a file
several replicas append metrics mirrors into) are handled without
double-counting: metrics records are kept *per pid* — a process's newer
mirror replaces its older one — and :attr:`metrics` aggregates across the
distinct pids (:func:`..obs.collect.merge_metrics`).
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path


class TraceTailer:
    def __init__(self, path: 'str | os.PathLike'):
        self.path = Path(path)
        self.events: list[dict] = []
        self.metrics_by_pid: dict[int, dict] = {}
        self.n_bad_lines = 0
        self._pos = 0
        self._buf = ''
        self._last_new = time.monotonic()

    def poll(self) -> int:
        """Read any newly appended complete lines; returns the number of new
        events absorbed (metrics records update :attr:`metrics` instead)."""
        try:
            size = self.path.stat().st_size
        except OSError:
            return 0
        if size < self._pos:  # truncated or rotated: start over
            self._pos = 0
            self._buf = ''
            self.events.clear()
            self.metrics_by_pid.clear()
        if size == self._pos:
            return 0
        with open(self.path) as fh:
            fh.seek(self._pos)
            chunk = fh.read()
            self._pos = fh.tell()
        self._buf += chunk
        lines = self._buf.split('\n')
        self._buf = lines.pop()  # trailing partial line (or '')
        n_new = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                self.n_bad_lines += 1
                continue
            if ev.get('ph') == 'M' and ev.get('name') == 'metrics':
                # latest mirror per producing process — merged multi-pid
                # traces must replace per pid, never accumulate blindly
                self.metrics_by_pid[ev.get('pid', 0)] = ev.get('args', {}).get('metrics', {})
            else:
                self.events.append(ev)
                n_new += 1
        if n_new:
            self._last_new = time.monotonic()
        return n_new

    @property
    def metrics(self) -> dict:
        """The latest metrics, aggregated across producing processes (one
        process: its snapshot verbatim; several: counters/histograms summed
        per distinct pid, each pid contributing only its newest mirror)."""
        if not self.metrics_by_pid:
            return {}
        if len(self.metrics_by_pid) == 1:
            return next(iter(self.metrics_by_pid.values()))
        from .collect import merge_metrics

        return merge_metrics(self.metrics_by_pid)

    @property
    def staleness_s(self) -> float:
        """Seconds since the last new event was absorbed."""
        return time.monotonic() - self._last_new
