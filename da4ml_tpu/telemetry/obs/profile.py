"""XLA device-profile correlation (``DA4ML_PROFILE=<dir>``).

Setting ``DA4ML_PROFILE`` arms ``jax.profiler``: the first annotated
region starts ``jax.profiler.start_trace(dir)`` (stopped atexit), and
every CMVM device rung / runtime batch call is wrapped in a
``jax.profiler.TraceAnnotation`` named ``da4ml:<span name>#span=<id>`` —
the owning telemetry span id — so the resulting Perfetto/TensorBoard
view shows host telemetry spans and XLA device kernels on one correlated
timeline (load the xplane from ``<dir>`` next to the ``DA4ML_TRACE``
Chrome trace).

Disabled (no env var): :func:`annotate` costs one dict lookup and returns
a shared ``nullcontext`` — the hot paths stay clean.

The profiler start is best-effort: a missing/broken profiler plugin logs
one warning and disarms for the process instead of failing the solve.
"""

from __future__ import annotations

import atexit
import os
import threading
from contextlib import nullcontext

_NULL = nullcontext()
_lock = threading.Lock()
_started = False
_failed = False


def profile_dir() -> str | None:
    """The armed profile output directory, or None when profiling is off."""
    return os.environ.get('DA4ML_PROFILE') or None


def _stop_trace() -> None:
    global _started
    if not _started:
        return
    _started = False
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:
        pass


def _ensure_started(d: str) -> bool:
    """Start the process-wide profiler trace once; False if unavailable."""
    global _started, _failed
    if _started:
        return True
    if _failed:
        return False
    with _lock:
        if _started:
            return True
        if _failed:
            return False
        try:
            import jax

            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
            _started = True
            atexit.register(_stop_trace)
        except Exception as e:
            _failed = True
            from ..log import warn_once

            warn_once(
                'obs.profile.start_failed',
                f'DA4ML_PROFILE={d!r}: jax profiler unavailable, device profiling disabled: {e}',
                logger='telemetry.obs',
            )
            return False
    return True


def annotate(name: str, span_id: 'int | None' = None):
    """Context manager bracketing a device dispatch/fetch region.

    When profiling is armed, returns a ``jax.profiler.TraceAnnotation``
    tagged with the owning telemetry span id; otherwise a shared no-op
    context. ``span_id=None`` falls back to the innermost open span of the
    calling thread."""
    d = profile_dir()
    if not d or not _ensure_started(d):
        return _NULL
    if span_id is None:
        from ..core import current_span

        sp = current_span()
        span_id = sp.span_id if sp is not None else None
    try:
        import jax

        tag = f'da4ml:{name}' if span_id is None else f'da4ml:{name}#span={span_id}'
        return jax.profiler.TraceAnnotation(tag)
    except Exception:
        return _NULL


def profiling_active() -> bool:
    """True once the process-wide profiler trace has been started."""
    return _started
