"""Live observability plane (docs/observability.md).

PR-3 telemetry is post-hoc: spans and metrics land in a trace file read
after the process exits. This package adds *live* introspection of a
running process on three legs:

- **Exposition** — :mod:`.openmetrics` renders the metrics registry in
  OpenMetrics/Prometheus text format; :mod:`.server` serves it over a
  stdlib ``http.server`` endpoint (``/metrics``, ``/healthz``,
  ``/statusz``) started via ``telemetry.serve(port)``,
  ``DA4ML_METRICS_PORT``, or ``da4ml-tpu monitor``. Off by default and
  fork-safe like the rest of telemetry.
- **Device-profile correlation** — :mod:`.profile` arms
  ``jax.profiler`` around the CMVM device rungs and runtime batch calls
  when ``DA4ML_PROFILE=<dir>`` is set, tagging XLA device events with the
  owning telemetry span id.
- **Regression gates** — :mod:`.bench_diff` compares BENCH/metrics
  snapshots against per-metric tolerance budgets
  (``da4ml-tpu bench-diff A.json B.json [--budget budgets.toml]``).

Everything here imports lazily from ``da4ml_tpu.telemetry`` — importing
the telemetry package never pulls in the HTTP server or jax.
"""

from .bench_diff import diff_metrics, load_bench_metrics, load_budgets
from .collect import merge_metrics, merge_traces, trace_index, write_merged
from .health import health_snapshot, status_snapshot
from .openmetrics import render_openmetrics, validate_openmetrics
from .server import serve, server_port, stop_server
from .tailer import TraceTailer

__all__ = [
    'render_openmetrics',
    'validate_openmetrics',
    'merge_traces',
    'merge_metrics',
    'trace_index',
    'write_merged',
    'health_snapshot',
    'status_snapshot',
    'serve',
    'server_port',
    'stop_server',
    'TraceTailer',
    'load_bench_metrics',
    'load_budgets',
    'diff_metrics',
]
