"""Health and status snapshots for the live endpoints.

``/healthz`` aggregates three signals into ``ok`` / ``degraded``:

- **circuit breakers** (``reliability.breaker``): any open breaker means a
  backend is currently being skipped;
- **campaign heartbeat**: ``solve_many`` beats ``telemetry.beat('campaign')``
  per kernel; an in-progress campaign whose last beat is older than
  ``DA4ML_HEALTH_STALL_S`` (default 120 s) indicates a stalled worker;
- **compile-cache hit ratio** (informational, never degrades health);
- **solution store** (when one is open in this process): an open
  ``store.read``/``store.write`` breaker degrades health.

``/statusz`` is the wide-angle JSON: run-mode autotune decisions,
scheduler bucket occupancy, deadline workers, active spans, device
inventory. Snapshots must be scrape-safe: they never initialize jax or
import heavy modules that are not already loaded.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from .. import core
from ..metrics import metrics_on, metrics_snapshot

_T0 = time.monotonic()

#: campaign heartbeat older than this (while a campaign is in progress)
#: flips health to degraded
DEFAULT_STALL_S = 120.0


def _stall_threshold_s() -> float:
    try:
        return float(os.environ.get('DA4ML_HEALTH_STALL_S', '') or DEFAULT_STALL_S)
    except ValueError:
        return DEFAULT_STALL_S


def _breaker_states() -> dict[str, str]:
    """Live breaker states without forcing a reliability import on scrape."""
    mod = sys.modules.get('da4ml_tpu.reliability.breaker')
    if mod is None:
        return {}
    return mod.breaker_states()


def _metric_value(snap: dict, name: str) -> float | None:
    m = snap.get(name)
    return None if m is None else m.get('value')


def _serve_check() -> dict | None:
    """Serve-plane health of any live :class:`~da4ml_tpu.serve.ServeEngine`
    (queue stall, shed rate, per-model breaker states). Resolved via
    ``sys.modules`` — a scrape never imports the serve stack; None when no
    engine exists in this process."""
    mod = sys.modules.get('da4ml_tpu.serve.engine')
    if mod is None:
        return None
    try:
        return mod.serve_health()
    except Exception:  # pragma: no cover - never fail a scrape
        return None


def _store_check() -> dict | None:
    """Solution-store health (breaker pair + occupancy) of any store opened
    in this process. Resolved via ``sys.modules`` — a scrape never imports
    the store; None when no store exists in this process."""
    mod = sys.modules.get('da4ml_tpu.store.solution_store')
    if mod is None:
        return None
    try:
        return mod.store_health()
    except Exception:  # pragma: no cover - never fail a scrape
        return None


def _router_check() -> dict | None:
    """Replica-router health (routable replica count, per-replica probe +
    breaker states) when a :class:`~da4ml_tpu.serve.router.Router` runs in
    this process. Resolved via ``sys.modules`` — scrape-safe."""
    mod = sys.modules.get('da4ml_tpu.serve.router')
    if mod is None:
        return None
    try:
        return mod.router_health()
    except Exception:  # pragma: no cover - never fail a scrape
        return None


def _fleet_check() -> dict | None:
    """Fleet-driver health (live/announced replica counts, restarts) when a
    :class:`~da4ml_tpu.serve.fleet.Fleet` runs in this process. Resolved
    via ``sys.modules`` — scrape-safe."""
    mod = sys.modules.get('da4ml_tpu.serve.fleet')
    if mod is None:
        return None
    try:
        return mod.fleet_health()
    except Exception:  # pragma: no cover - never fail a scrape
        return None


def _store_status() -> dict | None:
    """Occupancy + hit ratio of any solution store opened in this process
    (``/statusz``)."""
    mod = sys.modules.get('da4ml_tpu.store.solution_store')
    if mod is None:
        return None
    try:
        return mod.store_status()
    except Exception:  # pragma: no cover - never fail a scrape
        return None


def _campaign_workers() -> dict | None:
    """Cross-process worker liveness of an active multi-worker campaign
    (``parallel.campaign.worker_health``: heartbeat files in the shared
    campaign dir). Resolved via ``sys.modules`` — a scrape never imports
    the campaign driver."""
    mod = sys.modules.get('da4ml_tpu.parallel.campaign')
    if mod is None:
        return None
    try:
        return mod.worker_health(stall_s=_stall_threshold_s())
    except Exception:  # pragma: no cover - never fail a scrape
        return None


def _campaign_check(snap: dict) -> dict:
    done = _metric_value(snap, 'campaign.done')
    total = _metric_value(snap, 'campaign.total')
    age = core.beat_age_s('campaign')
    in_progress = total is not None and total > 0 and (done is None or done < total)
    stalled = bool(in_progress and age is not None and age > _stall_threshold_s())
    out = {
        'status': 'degraded' if stalled else 'ok',
        'in_progress': bool(in_progress),
        'done': done,
        'total': total,
        'heartbeat_age_s': None if age is None else round(age, 3),
        'stall_threshold_s': _stall_threshold_s(),
    }
    workers = _campaign_workers()
    if workers is not None:
        out['workers'] = workers
        # a stalled *worker* degrades health even while this process's own
        # loop beats on time — its kernels sit leased-but-dead until expiry
        if workers.get('in_progress') and workers.get('stalled'):
            out['status'] = 'degraded'
    return out


def _locktrace_status() -> dict | None:
    """Runtime lock-order tracer counters + violations (``/statusz``), when
    ``DA4ML_LOCKTRACE=1`` armed it. Resolved via ``sys.modules`` — the
    tracer module is always loaded (locks are built through it), so gate on
    its armed flag instead to keep unarmed scrapes silent."""
    mod = sys.modules.get('da4ml_tpu.reliability.locktrace')
    if mod is None or not mod.locktrace_enabled():
        return None
    try:
        out = dict(mod.locktrace_counters())
        out['violations'] = mod.locktrace_violations()
        return out
    except Exception:  # pragma: no cover - never fail a scrape
        return None


def _cache_check(snap: dict) -> dict:
    compiles = _metric_value(snap, 'jit.compile') or 0.0
    loads = _metric_value(snap, 'jit.cache_load') or 0.0
    first_calls = compiles + loads
    return {
        'status': 'ok',  # informational: a cold cache is not ill health
        'compiles': compiles,
        'cache_loads': loads,
        'hit_ratio': round(loads / first_calls, 4) if first_calls else None,
    }


def refresh_computed_gauges() -> None:
    """Materialize scrape-time values into the registry so ``/metrics`` and
    ``metrics_snapshot()`` carry them: breaker states (set even before any
    transition), campaign heartbeat age, compile-cache hit ratio, and the
    aggregate health bit. No-op while metrics are disabled."""
    if not metrics_on():
        return
    from ..metrics import gauge

    state_code = {'closed': 0.0, 'half-open': 0.5, 'open': 1.0}
    for name, state in _breaker_states().items():
        gauge(f'breaker.state.{name}').set(state_code.get(state, -1.0))
    age = core.beat_age_s('campaign')
    if age is not None:
        gauge('campaign.heartbeat_age_s').set(round(age, 6))
    lock = _locktrace_status()
    if lock is not None:
        gauge('locktrace.acquires').set(float(lock.get('acquires', 0)))
        gauge('locktrace.edges').set(float(lock.get('edges', 0)))
        gauge('locktrace.rank_inversions').set(float(lock.get('rank_inversions', 0)))
        gauge('locktrace.cycles').set(float(lock.get('cycles', 0)))
    snap = metrics_snapshot()
    ratio = _cache_check(snap)['hit_ratio']
    if ratio is not None:
        gauge('cache.hit_ratio').set(ratio)
    gauge('health.status').set({'ok': 0.0, 'draining': 0.5}.get(health_snapshot(snap)['status'], 1.0))


def health_snapshot(snap: dict | None = None) -> dict:
    """The ``/healthz`` document. ``status`` is ``ok`` or ``degraded``."""
    if snap is None:
        snap = metrics_snapshot()
    breakers = _breaker_states()
    open_breakers = sorted(n for n, s in breakers.items() if s == 'open')
    campaign = _campaign_check(snap)
    checks = {
        'breakers': {
            'status': 'degraded' if open_breakers else 'ok',
            'open': open_breakers,
            'states': breakers,
        },
        'campaign': campaign,
        'compile_cache': _cache_check(snap),
    }
    serve = _serve_check()
    if serve is not None:
        checks['serve'] = serve
    store = _store_check()
    if store is not None:
        checks['store'] = store
    router = _router_check()
    if router is not None:
        checks['router'] = router
    fleet = _fleet_check()
    if fleet is not None:
        checks['fleet'] = fleet
    # draining trumps degraded: an explicitly-draining serve plane is about
    # to exit — routers must stop sending to it now, whatever else is true
    if any(c['status'] == 'draining' for c in checks.values()):
        status = 'draining'
    elif any(c['status'] == 'degraded' for c in checks.values()):
        status = 'degraded'
    else:
        status = 'ok'
    return {
        'status': status,
        'checks': checks,
        'pid': os.getpid(),
        'uptime_s': round(time.monotonic() - _T0, 3),
        'metrics_enabled': metrics_on(),
    }


def _run_mode_decisions() -> dict:
    """Persisted/in-process autotune decisions, if the runtime is loaded."""
    mod = sys.modules.get('da4ml_tpu.runtime.jax_backend')
    if mod is None:
        return {}
    try:
        return mod.mode_decisions()
    except Exception:
        return {}


def _serve_status() -> dict | None:
    """Loaded models + executor-cache occupancy (``/statusz``), when a
    serve engine is live in this process."""
    mod = sys.modules.get('da4ml_tpu.serve.engine')
    if mod is None:
        return None
    try:
        return mod.serve_status()
    except Exception:
        return None


def _router_status() -> dict | None:
    """Per-replica router detail for ``/statusz``."""
    mod = sys.modules.get('da4ml_tpu.serve.router')
    if mod is None:
        return None
    try:
        return mod.router_status()
    except Exception:
        return None


def _fleet_status() -> dict | None:
    """Fleet-driver detail (slots, restarts, registry) for ``/statusz``."""
    mod = sys.modules.get('da4ml_tpu.serve.fleet')
    if mod is None:
        return None
    try:
        return mod.fleet_status()
    except Exception:
        return None


def _device_inventory() -> dict | None:
    """Local device info — only when jax is already initialized (a scrape
    must never pay, or trigger, backend startup)."""
    if 'jax' not in sys.modules:
        return None
    try:
        from ...parallel import device_inventory

        return device_inventory()
    except Exception:
        return None


def status_snapshot() -> dict:
    """The ``/statusz`` document: everything a person debugging a live
    process wants on one page."""
    snap = metrics_snapshot()
    sched = {k: v.get('value', v.get('count')) for k, v in snap.items() if k.startswith(('sched.', 'emit.'))}
    run = {k: v.get('value', v.get('count')) for k, v in snap.items() if k.startswith('run.')}
    serve_metrics = {k: v.get('value', v.get('count')) for k, v in snap.items() if k.startswith('serve.')}
    deadline_workers = [t.name for t in threading.enumerate() if t.name.startswith('da4ml-deadline-')]
    serve = _serve_status()
    return {
        'pid': os.getpid(),
        'uptime_s': round(time.monotonic() - _T0, 3),
        'telemetry': {
            'metrics_enabled': metrics_on(),
            'tracing_active': core.tracing_active(),
            'n_metrics': len(snap),
        },
        'health': health_snapshot(snap),
        'active_spans': core.active_spans(),
        'run_modes': _run_mode_decisions(),
        'scheduler': sched,
        'runtime': run,
        'serve': serve,
        'serve_metrics': serve_metrics,
        'store': _store_status(),
        'router': _router_status(),
        'fleet': _fleet_status(),
        'locktrace': _locktrace_status(),
        'deadline_workers': deadline_workers,
        'devices': _device_inventory(),
    }
