"""Fleet trace merge: N per-process trace files -> one Perfetto timeline.

Each replica (and the router process) writes its own trace with
process-local timestamps — ``ts`` is microseconds since *that process's*
telemetry import. This module merges them into a single Chrome
trace-event document whose events share one time axis:

1. **Clock alignment.** Every sink records a clock anchor at open — one
   ``(unix_time_us, ts)`` pair (:mod:`..export`). ``unix_time_us - ts`` is
   the process's offset onto the shared wall clock; the merger re-bases
   every event onto the earliest process's epoch. A file without an anchor
   (a pre-anchor trace) merges unshifted and is flagged ``aligned: False``.
2. **Trace grouping.** Spans carry ``args.trace_id`` when they ran under a
   bound trace context (:func:`...core.bind_trace`); :func:`trace_index`
   groups the merged events by trace id so callers can answer "which
   processes did request X touch" — the CI ``fleet-trace`` gate requires at
   least one trace whose spans span ≥3 distinct processes.
3. **Metric aggregation.** Per-file metrics snapshots are aggregated with
   :func:`merge_metrics` — last snapshot *per pid*, then summed across
   pids — the same rule ``da4ml-tpu stats`` and the ``TraceTailer`` apply,
   so a replica that mirrored its counters twice is never double-counted.

Surfaced as ``da4ml-tpu trace-view`` and wired into the fleet chaos drill
via ``da4ml-tpu fleet --chaos --trace`` (docs/observability.md#fleet-tracing).
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from ..export import load_trace


def _anchor_offset_us(path: 'str | os.PathLike', events: list[dict]) -> float | None:
    """The file's wall-clock offset (``unix_time_us - ts``), or None."""
    for ev in events:
        if ev.get('ph') == 'M' and ev.get('name') == 'clock_sync':
            args = ev.get('args', {})
            if 'unix_time_us' in args:
                return float(args['unix_time_us']) - float(ev.get('ts', 0.0))
    # Chrome-format traces carry the anchor in otherData instead
    try:
        doc = json.loads(Path(path).read_text())
        cs = doc.get('otherData', {}).get('clock_sync') if isinstance(doc, dict) else None
        if cs and 'unix_time_us' in cs:
            return float(cs['unix_time_us']) - float(cs.get('ts', 0.0))
    except Exception:
        pass
    return None


def merge_metrics(snapshots_by_pid: dict) -> dict:
    """Aggregate one metrics snapshot per process into a fleet view.

    Keys identify the producing process (pid or source label — only their
    uniqueness matters). Counters and histograms are additive across
    processes; gauges sum too (fleet queue depth is the sum of replica
    depths — state-valued gauges like ``breaker.state.*`` read as "count of
    replicas in a non-closed state"). The caller is responsible for keeping
    only the *latest* snapshot per process — repeated mirrors from one
    process must replace, not accumulate.
    """
    out: dict[str, dict] = {}
    for _pid, snap in sorted(snapshots_by_pid.items(), key=lambda kv: str(kv[0])):
        for name, m in snap.items():
            if not isinstance(m, dict) or 'type' not in m:
                continue
            cur = out.get(name)
            if cur is None:
                out[name] = json.loads(json.dumps(m))  # deep copy, JSON-shaped
                continue
            if cur.get('type') != m.get('type'):
                continue  # conflicting kinds across processes: keep the first
            kind = m['type']
            if kind in ('counter', 'gauge'):
                cur['value'] = cur.get('value', 0.0) + m.get('value', 0.0)
            elif kind == 'histogram':
                if cur.get('bounds') != m.get('bounds'):
                    continue  # incompatible ladders: keep the first
                cur['count'] = cur.get('count', 0) + m.get('count', 0)
                cur['sum'] = round(cur.get('sum', 0.0) + m.get('sum', 0.0), 6)
                cur['buckets'] = [a + b for a, b in zip(cur.get('buckets', []), m.get('buckets', []))]
                for k, pick in (('min', min), ('max', max)):
                    if k in m:
                        cur[k] = pick(cur[k], m[k]) if k in cur else m[k]
                if cur.get('count'):
                    cur['mean'] = round(cur['sum'] / cur['count'], 6)
                if 'exemplars' in m:
                    ex = cur.setdefault('exemplars', {})
                    for bi, triple in m['exemplars'].items():
                        # newest exemplar per bucket wins across processes
                        if bi not in ex or triple[2] >= ex[bi][2]:
                            ex[bi] = triple
    return out


def merge_traces(paths: 'list[str | os.PathLike]', *, align: bool = True) -> dict:
    """Merge trace files onto one timeline; returns a report dict.

    Keys: ``doc`` (the merged Chrome trace-event document — write it out
    and load in Perfetto), ``sources`` (per-file pids/offsets/aligned
    flags), ``traces`` (per-trace-id index from :func:`trace_index`),
    ``max_processes_per_trace``, ``n_events``, and ``metrics`` (the
    :func:`merge_metrics` aggregate).
    """
    sources: list[dict] = []
    per_file: list[tuple[str, list[dict], float | None]] = []
    snapshots_by_source: dict[str, dict] = {}
    for path in paths:
        events, metrics = load_trace(path)
        offset = _anchor_offset_us(path, events) if align else None
        label = Path(path).stem
        pids = sorted({ev.get('pid') for ev in events if 'pid' in ev})
        per_file.append((label, events, offset))
        if metrics:
            snapshots_by_source[str(path)] = metrics
        sources.append(
            {
                'path': str(path),
                'label': label,
                'pids': pids,
                'n_events': len(events),
                'offset_us': offset,
                'aligned': offset is not None,
            }
        )

    offsets = [off for _, _, off in per_file if off is not None]
    base = min(offsets) if offsets else 0.0
    merged: list[dict] = []
    seen_pids: dict[int, str] = {}
    for label, events, offset in per_file:
        shift = (offset - base) if offset is not None else 0.0
        for ev in events:
            if ev.get('ph') == 'M' and ev.get('name') == 'clock_sync':
                continue  # consumed by the alignment above
            ev = dict(ev)
            ev['ts'] = round(float(ev.get('ts', 0.0)) + shift, 1)
            merged.append(ev)
            pid = ev.get('pid')
            if isinstance(pid, int) and pid not in seen_pids:
                seen_pids[pid] = label
    merged.sort(key=lambda ev: ev.get('ts', 0.0))
    for pid, label in sorted(seen_pids.items()):
        merged.append(
            {'name': 'process_name', 'ph': 'M', 'ts': 0.0, 'pid': pid, 'tid': 0, 'args': {'name': f'{label} (pid {pid})'}}
        )

    traces = trace_index(merged)
    max_procs = max((len(t['pids']) for t in traces.values()), default=0)
    metrics = merge_metrics(snapshots_by_source)
    doc = {
        'traceEvents': merged,
        'displayTimeUnit': 'ms',
        'otherData': {
            'producer': 'da4ml_tpu.telemetry.obs.collect',
            'sources': [{k: v for k, v in s.items() if k != 'pids'} for s in sources],
            'metrics': metrics,
        },
    }
    return {
        'doc': doc,
        'sources': sources,
        'traces': traces,
        'n_events': len(merged),
        'max_processes_per_trace': max_procs,
        'metrics': metrics,
    }


def trace_index(events: list[dict]) -> dict:
    """Group events by ``args.trace_id``: ``{trace_id: {n_spans, pids,
    names, t_min_us, t_max_us}}`` (span names capped at 32 per trace)."""
    traces: dict[str, dict] = {}
    for ev in events:
        trace_id = ev.get('args', {}).get('trace_id')
        if not trace_id:
            continue
        t = traces.setdefault(
            trace_id, {'n_spans': 0, 'pids': set(), 'names': set(), 't_min_us': float('inf'), 't_max_us': float('-inf')}
        )
        t['n_spans'] += 1
        if 'pid' in ev:
            t['pids'].add(ev['pid'])
        if len(t['names']) < 32:
            t['names'].add(ev.get('name', ''))
        ts = float(ev.get('ts', 0.0))
        t['t_min_us'] = min(t['t_min_us'], ts)
        t['t_max_us'] = max(t['t_max_us'], ts + float(ev.get('dur', 0.0)))
    for t in traces.values():
        t['pids'] = sorted(t['pids'])
        t['names'] = sorted(t['names'])
        t['span_ms'] = round((t['t_max_us'] - t['t_min_us']) / 1e3, 3) if t['n_spans'] else 0.0
    return traces


def write_merged(report: dict, out_path: 'str | os.PathLike') -> None:
    """Write the merged Chrome document atomically (tmp + rename)."""
    out = Path(out_path)
    out.parent.mkdir(parents=True, exist_ok=True)
    tmp = out.with_name(out.name + f'.tmp.{os.getpid()}')
    with open(tmp, 'w') as fh:
        json.dump(report['doc'], fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, out)
