"""OpenMetrics/Prometheus text exposition over the metrics registry.

The dotted catalog (docs/telemetry.md) maps 1:1 onto OpenMetrics
families prefixed ``da4ml_``: counters gain the ``_total`` sample suffix,
seconds-valued names (``*_s``) are renamed ``*_seconds``, and histograms
expose the standard cumulative ``_bucket{le=...}`` / ``_sum`` / ``_count``
triplet. Dimension-carrying names are folded into labels instead of an
unbounded family namespace:

- ``breaker.state.<name>``  -> ``da4ml_breaker_state{breaker="<name>"}``
- ``run.mode.<mode>``       -> ``da4ml_run_mode{mode="<mode>"}``

Histogram buckets carry **exemplars** when the registry recorded one
(``Histogram.observe(v, trace_id=...)``): the OpenMetrics
``# {trace_id="..."} <value> <timestamp>`` suffix that links a latency
bucket to the most recent trace that landed in it.

:func:`validate_openmetrics` is a line-by-line grammar checker for the
exposition format (HELP/TYPE ordering, name/label syntax, label-value
escaping, cumulative bucket monotonicity, exemplar syntax and placement,
``# EOF`` terminator) shared by the tests and the CI obs-smoke job; it
returns the parsed samples so callers can assert on values.
"""

from __future__ import annotations

import re

from ..catalog import FOLDS, help_for

#: ``dotted-prefix -> (family, label key)``: trailing name component
#: becomes a label value instead of a per-instance metric family. Families
#: come from the shared catalog (``telemetry.catalog.FOLDS``) so the
#: encoder and the drift lint fold identically; only the label key is ours.
_FOLD_LABEL_KEYS = {'breaker.state': 'breaker', 'run.mode': 'mode'}
_LABEL_FOLD = {prefix: (family, _FOLD_LABEL_KEYS[family]) for prefix, family in FOLDS.items()}

def _family_name(dotted: str) -> str:
    """Dotted catalog name -> OpenMetrics family name (no type suffix)."""
    name = dotted.replace('.', '_').replace('-', '_')
    name = re.sub(r'[^a-zA-Z0-9_]', '_', name)
    if name.endswith('_s') and not name.endswith('_per_s'):
        name = name[:-2] + '_seconds'
    return 'da4ml_' + name


def _fold(dotted: str) -> tuple[str, dict[str, str]]:
    """Split a dotted name into (family dotted name, labels)."""
    for prefix, (family, key) in _LABEL_FOLD.items():
        if dotted.startswith(prefix) and len(dotted) > len(prefix):
            return family, {key: dotted[len(prefix) :]}
    return dotted, {}


def _escape_label(v: str) -> str:
    return v.replace('\\', '\\\\').replace('"', '\\"').replace('\n', '\\n')


def _escape_help(v: str) -> str:
    return v.replace('\\', '\\\\').replace('\n', '\\n')


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return 'NaN'
    if v == float('inf'):
        return '+Inf'
    if v == float('-inf'):
        return '-Inf'
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_str(labels: dict[str, str]) -> str:
    if not labels:
        return ''
    inner = ','.join(f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items()))
    return '{' + inner + '}'


def _exemplar_str(ex) -> str:
    """Render a registry exemplar triple as the OpenMetrics suffix."""
    if not ex:
        return ''
    trace_id, value, ts = ex
    return f' # {{trace_id="{_escape_label(str(trace_id))}"}} {_fmt(float(value))} {_fmt(float(ts))}'


def render_openmetrics(snapshot: dict | None = None) -> str:
    """Render a metrics snapshot (default: the live registry, with health
    gauges refreshed) as OpenMetrics text ending in ``# EOF``."""
    if snapshot is None:
        from ..metrics import metrics_snapshot
        from .health import refresh_computed_gauges

        refresh_computed_gauges()
        snapshot = metrics_snapshot()

    # group dotted metrics into families (label folding can merge several
    # registry entries into one family)
    families: dict[str, dict] = {}
    for dotted, m in sorted(snapshot.items()):
        kind = m.get('type')
        if kind not in ('counter', 'gauge', 'histogram'):
            continue
        fam_dotted, labels = _fold(dotted)
        fam = families.setdefault(fam_dotted, {'type': kind, 'samples': []})
        if fam['type'] != kind:
            # conflicting types across a folded family: keep the first,
            # expose the oddball unfolded rather than emitting bad text
            fam = families.setdefault(dotted, {'type': kind, 'samples': []})
            labels = {}
        fam['samples'].append((labels, m))

    lines: list[str] = []
    for fam_dotted, fam in sorted(families.items()):
        name = _family_name(fam_dotted)
        kind = fam['type']
        help_text = help_for(fam_dotted)  # telemetry.catalog.METRICS, drift-linted
        lines.append(f'# HELP {name} {_escape_help(help_text)}')
        lines.append(f'# TYPE {name} {kind}')
        for labels, m in fam['samples']:
            ls = _labels_str(labels)
            if kind == 'counter':
                lines.append(f'{name}_total{ls} {_fmt(m["value"])}')
            elif kind == 'gauge':
                lines.append(f'{name}{ls} {_fmt(m["value"])}')
            else:  # histogram: registry buckets are per-bin -> cumulate
                bounds = m.get('bounds', [])
                counts = m.get('buckets', [])
                exemplars = m.get('exemplars') or {}
                cum = 0
                for bi, (bound, c) in enumerate(zip(bounds, counts)):
                    cum += c
                    bl = dict(labels, le=_fmt(float(bound)))
                    lines.append(f'{name}_bucket{_labels_str(bl)} {cum}{_exemplar_str(exemplars.get(str(bi)))}')
                total = m.get('count', 0)
                bl = dict(labels, le='+Inf')
                lines.append(f'{name}_bucket{_labels_str(bl)} {total}{_exemplar_str(exemplars.get(str(len(bounds))))}')
                lines.append(f'{name}_sum{ls} {_fmt(float(m.get("sum", 0.0)))}')
                lines.append(f'{name}_count{ls} {total}')
    lines.append('# EOF')
    return '\n'.join(lines) + '\n'


# ---------------------------------------------------------------------------
# grammar validation (tests + CI obs-smoke)
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*$')
_NUM = r'-?(?:\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|\+Inf|-Inf|NaN)'
_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^{}]*)\})?'
    rf' (?P<value>{_NUM})'
    # optional OpenMetrics exemplar: " # {labels} value [timestamp]"
    r'(?: # \{(?P<ex_labels>[^{}]*)\}'
    rf' (?P<ex_value>{_NUM})'
    r'(?: (?P<ex_ts>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?))?)?$'
)
_LABEL_RE = re.compile(r'^(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>(?:[^"\\]|\\["\\n])*)"$')


def _split_labels(raw: str) -> dict[str, str]:
    """Split a label body on commas that are outside quoted values."""
    labels: dict[str, str] = {}
    if not raw:
        return labels
    parts: list[str] = []
    depth_quote = False
    cur = []
    i = 0
    while i < len(raw):
        ch = raw[i]
        if ch == '\\' and depth_quote and i + 1 < len(raw):
            cur.append(raw[i : i + 2])
            i += 2
            continue
        if ch == '"':
            depth_quote = not depth_quote
        if ch == ',' and not depth_quote:
            parts.append(''.join(cur))
            cur = []
        else:
            cur.append(ch)
        i += 1
    if cur:
        parts.append(''.join(cur))
    for part in parts:
        m = _LABEL_RE.match(part)
        if m is None:
            raise ValueError(f'bad label pair: {part!r}')
        labels[m.group('key')] = m.group('val')
    return labels


def _parse_value(s: str) -> float:
    if s == '+Inf':
        return float('inf')
    if s == '-Inf':
        return float('-inf')
    return float(s)


def validate_openmetrics(text: str) -> dict[str, dict]:
    """Validate OpenMetrics exposition text line by line; raise ``ValueError``
    on any grammar violation. Returns ``{family: {'type', 'help', 'samples':
    {sample_line_name+labels: value}}}`` for value assertions."""
    lines = text.split('\n')
    if lines and lines[-1] == '':
        lines.pop()
    if not lines or lines[-1] != '# EOF':
        raise ValueError('exposition must end with "# EOF"')
    families: dict[str, dict] = {}
    current: str | None = None
    seen_order: list[str] = []
    for i, line in enumerate(lines[:-1]):
        if not line:
            raise ValueError(f'line {i}: empty line inside exposition')
        if line.startswith('# HELP '):
            rest = line[len('# HELP ') :]
            name, _, help_text = rest.partition(' ')
            if not _NAME_RE.match(name):
                raise ValueError(f'line {i}: bad metric name in HELP: {name!r}')
            if name in families:
                raise ValueError(f'line {i}: duplicate HELP for {name}')
            families[name] = {'type': None, 'help': help_text, 'samples': {}}
            seen_order.append(name)
            current = name
            continue
        if line.startswith('# TYPE '):
            rest = line[len('# TYPE ') :]
            name, _, kind = rest.partition(' ')
            if name not in families or families[name]['type'] is not None:
                raise ValueError(f'line {i}: TYPE without preceding HELP (or duplicate) for {name}')
            if name != current:
                raise ValueError(f'line {i}: TYPE {name} interleaved with family {current}')
            if kind not in ('counter', 'gauge', 'histogram', 'summary', 'info', 'unknown'):
                raise ValueError(f'line {i}: unknown TYPE {kind!r}')
            families[name]['type'] = kind
            continue
        if line.startswith('#'):
            raise ValueError(f'line {i}: unexpected comment {line!r}')
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f'line {i}: unparsable sample line {line!r}')
        sname = m.group('name')
        labels = _split_labels(m.group('labels') or '')
        value = _parse_value(m.group('value'))
        if current is None:
            raise ValueError(f'line {i}: sample before any HELP/TYPE block')
        fam = families[current]
        kind = fam['type']
        if kind == 'counter':
            if sname != current + '_total':
                raise ValueError(f'line {i}: counter sample must be {current}_total, got {sname}')
            if value < 0:
                raise ValueError(f'line {i}: counter value negative')
        elif kind == 'gauge':
            if sname != current:
                raise ValueError(f'line {i}: gauge sample must be {current}, got {sname}')
        elif kind == 'histogram':
            if sname not in (current + '_bucket', current + '_sum', current + '_count'):
                raise ValueError(f'line {i}: histogram sample {sname} not in bucket/sum/count')
            if sname.endswith('_bucket') and 'le' not in labels:
                raise ValueError(f'line {i}: histogram bucket without le label')
        else:
            raise ValueError(f'line {i}: sample for family {current} with no TYPE')
        ex_labels_raw = m.group('ex_labels')
        if ex_labels_raw is not None:
            # exemplars are only legal on counter _total and histogram
            # _bucket samples (OpenMetrics 1.0 §exemplars)
            if kind == 'histogram':
                if not sname.endswith('_bucket'):
                    raise ValueError(f'line {i}: exemplar on histogram sample {sname} (only _bucket may carry one)')
            elif kind != 'counter':
                raise ValueError(f'line {i}: exemplar on {kind} sample {sname}')
            try:
                ex_labels = _split_labels(ex_labels_raw)
            except ValueError as e:
                raise ValueError(f'line {i}: bad exemplar labels: {e}') from None
            if sum(len(k) + len(v) for k, v in ex_labels.items()) > 128:
                raise ValueError(f'line {i}: exemplar label set exceeds 128 characters')
            _parse_value(m.group('ex_value'))
        key = sname + _labels_str({k: v for k, v in labels.items()})
        if key in fam['samples']:
            raise ValueError(f'line {i}: duplicate sample {key}')
        fam['samples'][key] = value

    # semantic checks per histogram family: cumulative monotone buckets and
    # the +Inf bucket equal to _count
    for name, fam in families.items():
        if fam['type'] != 'histogram':
            continue
        by_series: dict[str, list[tuple[float, float]]] = {}
        counts: dict[str, float] = {}
        for key, value in fam['samples'].items():
            if key.startswith(name + '_bucket'):
                labels = _split_labels(key[len(name + '_bucket') :].strip('{}'))
                le = labels.pop('le')
                series = _labels_str(labels)
                by_series.setdefault(series, []).append((_parse_value(le), value))
            elif key.startswith(name + '_count'):
                series = key[len(name + '_count') :].strip('{}')
                counts[_labels_str(_split_labels(series))] = value
        for series, buckets in by_series.items():
            buckets.sort(key=lambda t: t[0])
            prev = -1.0
            for le, v in buckets:
                if v < prev:
                    raise ValueError(f'{name}{series}: bucket counts not cumulative at le={le}')
                prev = v
            if buckets[-1][0] != float('inf'):
                raise ValueError(f'{name}{series}: missing le="+Inf" bucket')
            if series in counts and buckets[-1][1] != counts[series]:
                raise ValueError(f'{name}{series}: +Inf bucket != _count')
    return families


#: content type a compliant scraper expects from /metrics
CONTENT_TYPE = 'application/openmetrics-text; version=1.0.0; charset=utf-8'
