"""The declarative metric catalog: every family this library emits.

One table, three consumers — the same discipline the opcode table applies
to the DAIS ISA and ``locktrace.LOCK_TABLE`` applies to locks:

- :mod:`.obs.openmetrics` renders each family's OpenMetrics ``HELP``
  string from the ``METRICS`` value (no second copy of the text);
- the drift lint (:mod:`da4ml_tpu.analysis.catalogs`) AST-scans the
  library for ``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` /
  ``timer(...)`` emission sites and fails CI when an emitted name is
  missing here, when a catalog entry no longer has an emission site, or
  when a catalogued family is missing from the docs/telemetry.md table;
- dashboards read docs/telemetry.md, which the catalog keeps honest.

Dynamic families (``run.mode.<mode>``, ``breaker.state.<name>``) are
catalogued under their *folded* family name — the exposition layer folds
the trailing component into a label (``openmetrics._LABEL_FOLD``) — and
their construction sites are registered in ``DYNAMIC_SITES`` below, so a
new f-string metric cannot appear without a catalog decision either.

This module is import-light on purpose (stdlib only): the catalog must be
readable by the analysis layer without pulling in the metrics runtime.
"""

from __future__ import annotations

__all__ = ['DYNAMIC_SITES', 'FOLDS', 'METRICS', 'fold_family', 'help_for']

#: dotted family name -> HELP text (OpenMetrics HELP + docs/telemetry.md)
METRICS: dict[str, str] = {
    # -- solve plane --------------------------------------------------------
    'solve.calls': 'cmvm.api.solve invocations',
    'solve.duration_s': 'wall clock per solve',
    'solve.adders': 'result cost (adder count) per solve',
    'fallback.events': 'reliability chain degradations (solve + runtime)',
    'retry.sleeps': 'transient-error retry sleeps',
    'retry.delay_s': 'backoff delay per retry sleep',
    'retry.hints_honored': 'retry sleeps that honored a server Retry-After hint',
    # -- device search ------------------------------------------------------
    'jit.compile': 'first calls of a device compile class paying a real XLA compile',
    'jit.compile_s': 'wall clock of first calls that paid a real XLA compile',
    'jit.cache_load': 'first calls of a device compile class served from the persistent cache',
    'jit.cache_load_s': 'wall clock of first calls served from the persistent cache',
    'jit.cache_miss': 'aggregate first calls per device compile class (compile or cache load)',
    'jit.first_call_s': 'aggregate first-call wall clock per device compile class',
    'jit.execute_s': 'steady-state executor dispatch wall clock',
    'jit.export_load': 'serialized executors hot-loaded from an export artifact',
    'jit.export_save': 'compiled executors serialized to an export artifact',
    'cache.hit_ratio': 'persistent compile cache hit ratio (jit.cache_load / first calls)',
    'cse.device_rounds': 'greedy-CSE device calls',
    'cse.substitutions': 'CSE substitutions materialized across lanes',
    'search.beam_width': 'current adaptive beam width',
    'search.lanes_expanded': 'beam lanes expanded on device',
    'search.frontier_culled': 'frontier states culled by dominance',
    'search.device_forks': 'beam forks dispatched to the device path',
    'search.device_prunes': 'beam prunes decided on device',
    'search.fork_lanes': 'lanes created by device forks',
    'search.host_rescues': 'device-search rungs rescued by the host fallback',
    'search.host_seeded_lanes': 'beam lanes seeded from host solutions',
    'search.root_park_hits': 'root-parking cache hits during beam expansion',
    'search.strict_wins': 'candidate comparisons won strictly',
    'search.ties': 'candidate comparisons tied on cost',
    'search.trace_records': 'search-trace records written (DA4ML_SEARCH_TRACE_DIR)',
    'sched.rungs': 'CMVM search rungs scheduled',
    'sched.device_resident_rungs': 'rungs kept device-resident end to end',
    'sched.bucket_groups': 'same-shape rung groups batched into one dispatch',
    'sched.bucket_lanes': 'lanes packed via shape-bucket batching',
    'sched.dedup_lanes': 'duplicate lanes elided by the scheduler',
    'sched.entry_carry_groups': 'entry-carry groups propagated across rungs',
    'sched.fetch_bytes': 'bytes fetched from device per rung chunk',
    'sched.upload_bytes': 'bytes uploaded to device per rung chunk',
    'sched.device_s': 'device wall clock per CMVM search rung chunk (dispatch to fetch)',
    'sched.hbm_bytes': 'estimated device-resident bytes per CMVM search rung chunk',
    # -- runtime ------------------------------------------------------------
    'run.mode': 'DAIS executors constructed per resolved execution mode',
    'run.mode_cache_hit': 'executor constructions answered by the mode cache',
    'run.autotune': 'autotune decisions recorded',
    'run.samples': 'DAIS inference samples served',
    'run.samples_per_s': 'recent DAIS inference throughput',
    'run.batch_s': 'wall clock per inference batch',
    'run.batch_samples': 'samples per inference batch',
    'run.compile_s': 'runtime executor compile wall clock',
    'run.pallas.compile_s': 'pallas mega-kernel build + first-compile wall clock',
    'run.pallas.vmem_bytes': 'estimated VMEM footprint per pallas mega-kernel grid step',
    'run.pallas.fallbacks': "mode='pallas' requests degraded to 'level' (pallas missing, unlowered family, or build failure)",
    'run.device_s': 'device wall clock per DAIS inference batch',
    'run.hbm_bytes': 'estimated device-resident bytes per DAIS inference batch',
    'run.shard.partitions': 'model-axis shards adopted per partitioned executor',
    'run.shard.exchange_bytes': 'bytes all-gathered per segment boundary of a model-sharded program',
    'run.shard.imbalance': 'max/mean per-shard op count of the adopted partition plan',
    'run.shard.fallbacks': 'model-shard requests degraded to single-device (mesh unavailable or build failure)',
    'runtime.samples': 'samples served by the legacy runtime entry point',
    'runtime.run_s': 'wall clock per legacy runtime batch',
    'emit.async_batches': 'asynchronously emitted device batches',
    'emit.async_wait_s': 'wait for async emission drains',
    'trace.ops': 'DAIS ops traced into programs',
    'fuse.stages': 'pipeline stages fused',
    'fuse.seam_ops': 'seam ops eliminated by pipeline fusion',
    'fuse.depth_before': 'pipeline depth before fusion',
    'fuse.depth_after': 'pipeline depth after fusion',
    # -- reliability --------------------------------------------------------
    'breaker.state': 'circuit breaker state: 0 closed, 0.5 half-open, 1 open',
    'breaker.transitions': 'circuit breaker state transitions',
    'checkpoint.hits': 'campaign kernels restored from a checkpoint instead of re-solved',
    'checkpoint.misses': 'campaign kernels absent from the checkpoint (solved fresh)',
    'lease.claims': 'work-item leases claimed',
    'lease.renewals': 'lease deadline extensions',
    'lease.steals': 'expired leases stolen from dead owners',
    'lease.lost': 'leases lost to a stealer (owner presumed dead)',
    'locktrace.acquires': 'traced lock acquisitions (DA4ML_LOCKTRACE=1)',
    'locktrace.edges': 'distinct held->acquired orderings in the runtime lock-order graph',
    'locktrace.rank_inversions': 'runtime acquisitions against the declared lock-rank order',
    'locktrace.cycles': 'cycles detected in the runtime lock-order graph',
    'campaign.claims': 'campaign work items claimed',
    'campaign.kernel_failures': 'campaign kernels that exhausted every backend',
    'campaign.kernels_stolen': 'campaign kernels stolen from dead workers',
    'campaign.done': 'campaign kernels completed',
    'campaign.total': 'campaign kernels total',
    'campaign.workers_alive': 'campaign workers with a live heartbeat',
    'campaign.heartbeat_age_s': 'seconds since the last solve_many campaign heartbeat',
    'health.status': 'aggregate health: 0 ok, 1 degraded',
    # -- solution store -----------------------------------------------------
    'store.hits': 'verified solution-store hits',
    'store.misses': 'solution-store lookups that missed',
    'store.publishes': 'solutions published to the store',
    'store.read_errors': 'store reads that failed (unreachable/corrupt path)',
    'store.write_errors': 'store writes that failed',
    'store.corrupt_quarantined': 'store entries quarantined after failing verification',
    'store.negative_hits': 'lookups answered by a live negative marker',
    'store.negative_publishes': 'negative markers published after terminal solve failures',
    'store.singleflight_waits': 'cold misses that waited on another solver\'s lease',
    'store.singleflight_fallthroughs': 'waiters that solved locally to honor a deadline',
    'store.gc_evictions': 'store entries evicted by gc',
    'store.lookup_s': 'wall clock per store lookup',
    'store.tier.mem_hits': 'solution lookups served from the in-process LRU tier',
    'store.tier.local_hits': 'solution lookups served from the local-disk tier',
    'store.tier.shared_hits': 'solution lookups served from the shared-FS tier',
    'store.tier.misses': 'solution lookups that missed every cache tier',
    'store.tier.promotes_mem': 'entries promoted into the in-process LRU tier',
    'store.tier.promotes_local': 'shared-tier entries promoted to the local-disk tier',
    'store.tier.writethroughs': 'published solutions written through to the local tier',
    'store.tier.mem_evictions': 'entries evicted from the in-process LRU tier',
    'serve.solve_requests': 'solve requests admitted by the solve service',
    'serve.solve_shed': 'solve requests shed by admission control',
    'serve.solve_expired': 'solve requests whose deadline expired before dispatch',
    'serve.solve_hits': 'solve-service answers served from the store',
    'serve.solve_misses': 'solve-service answers that ran a cold solve',
    # -- serve plane --------------------------------------------------------
    'serve.requests': 'inference requests admitted to a serve queue',
    'serve.samples': 'inference sample rows served',
    'serve.shed': 'requests shed by admission control (HTTP 429)',
    'serve.deadline_miss': 'requests whose deadline expired while queued (rejected before dispatch)',
    'serve.batches': 'coalesced device batches dispatched by the serve plane',
    'serve.batch_rows': 'rows per coalesced serve batch',
    'serve.batch_fill': 'serve batch fill ratio (rows dispatched / row budget)',
    'serve.latency_s': 'request latency: admission to resolution',
    'serve.queue_wait_s': 'request queue wait before its batch dispatched',
    'serve.queue_depth': 'admission queue depth in rows (last served model)',
    'serve.queue_age_s': 'age of the oldest queued serve request',
    'serve.degraded': 'serve batches answered by the bit-exact fallback chain',
    'serve.dispatch_failures': 'device dispatch failures absorbed by the serve envelope',
    'serve.shape_miss': 'serve batches whose padded shape was not prewarmed (new XLA compile)',
    'serve.shape_hit': 'serve batches landing on a prewarmed canonical shape',
    'serve.hedge_fired': 'straggler hedges launched against slow device batches',
    'serve.hedge_won': 'hedged batches answered by the fallback chain first',
    'serve.reloads': 'hot executor reloads',
    'serve.executor_evictions': 'compiled executors evicted from the LRU serve cache',
    'serve.exports': 'serving artifacts exported',
    'router.requests': 'client requests proxied by the replica router',
    'router.samples': 'inference sample rows answered through the router',
    'router.hedges_fired': 'hedge legs launched against slow replicas',
    'router.hedges_won': 'requests answered by the hedge leg first',
    'router.hedge_cancelled': 'loser legs torn down after a definitive answer',
    'router.retries': 'retry legs after a retryable replica outcome',
    'router.leg_failures': 'transport-level leg failures (replica died mid-request)',
    'router.no_replica': 'requests rejected because no replica was routable',
    'router.probes': 'active /healthz probe rounds',
    'router.scrape.errors': 'replica /metrics scrapes that failed during federation',
    'router.scrape.duration_s': 'wall clock per fleet-wide /metrics/fleet scrape round',
    'router.scrape.replicas': 'replicas answering the last federation scrape',
    'request.access': 'structured access-log records emitted (one per client request)',
    'request.queue_s': 'per-request queue-wait segment (admission to batch dequeue)',
    'request.coalesce_s': 'per-request coalesce-window segment (batch open to dequeue)',
    'request.execute_s': 'per-request device-execute segment',
    'request.serialize_s': 'per-request serialize segment (execute done to resolution)',
    'fleet.spawns': 'replica subprocesses spawned by the fleet driver',
    'fleet.restarts': 'crashed replicas restarted with backoff',
    'fleet.kills': 'replicas signalled by the chaos drill',
    'fleet.announcements': 'replica registry slots claimed (lease + URL sidecar)',
    'fleet.announcements_lost': 'replica slots stolen while presumed dead',
    # -- warmup -------------------------------------------------------------
    'warmup.grid_s': 'wall clock per canonical-grid warmup shape',
    'warmup.compile_s': 'wall clock per warmup compile',
}

#: label-folded family prefixes: a literal ``<prefix><variant>`` emission
#: (e.g. ``run.mode.fused_ir``) belongs to the ``<family>`` catalog entry;
#: the OpenMetrics encoder folds the variant into a label the same way.
FOLDS: dict[str, str] = {
    'breaker.state.': 'breaker.state',
    'run.mode.': 'run.mode',
}


def fold_family(name: str) -> str:
    """The catalog family a metric name belongs to (identity when unfolded)."""
    for prefix, family in FOLDS.items():
        if name.startswith(prefix):
            return family
    return name

#: registered dynamic emission sites: module (repo-relative) -> folded
#: family names its f-string metrics resolve to. The drift lint rejects
#: any non-literal ``counter(f'...')`` call outside this table.
DYNAMIC_SITES: dict[str, tuple[str, ...]] = {
    'da4ml_tpu/runtime/jax_backend.py': ('run.mode',),
    'da4ml_tpu/reliability/breaker.py': ('breaker.state',),
    'da4ml_tpu/telemetry/obs/health.py': ('breaker.state',),
    'da4ml_tpu/cmvm/jax_search.py': ('jit.compile', 'jit.compile_s', 'jit.cache_load', 'jit.cache_load_s'),
    'da4ml_tpu/store/service.py': ('serve.solve_hits', 'serve.solve_misses'),
    'da4ml_tpu/serve/engine.py': (
        'request.queue_s',
        'request.coalesce_s',
        'request.execute_s',
        'request.serialize_s',
    ),
}


def help_for(family: str) -> str:
    """HELP text for a (folded) family; generic pointer when uncatalogued
    (the drift lint keeps this branch unreachable for library metrics)."""
    return METRICS.get(family, f'da4ml_tpu metric {family} (docs/telemetry.md)')
