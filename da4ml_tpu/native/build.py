"""Build the native shared library with g++ (no meson/pybind11 dependency).

Usage: ``python -m da4ml_tpu.native.build [--force]``. The library is also
auto-built on first use (bindings.load_lib) unless DA4ML_NO_NATIVE_BUILD is
set. Output: ``_da4ml_native.so`` next to this file.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

_HERE = Path(__file__).parent
SRC_DIR = _HERE / 'src'
LIB_PATH = _HERE / '_da4ml_native.so'
FINGERPRINT_PATH = _HERE / '_da4ml_native.fingerprint'


def _sources() -> list[Path]:
    return sorted(SRC_DIR.glob('*.cc'))


def _fingerprint() -> str:
    """Content hash of every native source/header — mtimes are unreliable
    (git checkouts give all files the same timestamp)."""
    import hashlib

    h = hashlib.sha256()
    for p in sorted(SRC_DIR.glob('*.cc')) + sorted(SRC_DIR.glob('*.hh')):
        h.update(p.name.encode())
        h.update(p.read_bytes())
    return h.hexdigest()


def needs_build() -> bool:
    if not LIB_PATH.exists():
        return True
    try:
        return FINGERPRINT_PATH.read_text().strip() != _fingerprint()
    except OSError:
        return True


def build(force: bool = False, verbose: bool = False) -> Path:
    if not force and not needs_build():
        return LIB_PATH
    cxx = os.environ.get('CXX', 'g++')
    cmd = [
        cxx,
        '-std=c++20',
        '-O3',
        '-fPIC',
        '-shared',
        '-fopenmp',
        '-fvisibility=hidden',
        '-Wall',
        *[str(s) for s in _sources()],
        '-o',
        str(LIB_PATH),
    ]
    if verbose:
        from ..telemetry import get_logger

        get_logger('native.build').info(' '.join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f'native build failed:\n{proc.stderr}')
    FINGERPRINT_PATH.write_text(_fingerprint() + '\n')
    return LIB_PATH


if __name__ == '__main__':
    force = '--force' in sys.argv
    path = build(force=force, verbose=True)
    from da4ml_tpu.telemetry import get_logger

    get_logger('native.build').info(f'built {path}')
