"""ctypes bindings to the native library (_da4ml_native.so).

The native sources live in ``da4ml_tpu/native/src`` and are compiled with
``g++ -fopenmp`` by :mod:`da4ml_tpu.native.build` (auto-invoked on first use
unless ``DA4ML_NO_NATIVE_BUILD`` is set). Bindings use ctypes only — no
pybind11/nanobind dependency.

Reference parity: the nanobind modules src/da4ml/_binary/{dais,cmvm}/bindings.cc.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np
from numpy.typing import NDArray

from ..reliability.locktrace import make_lock

_lock = make_lock('native.build')
_lib: ctypes.CDLL | None = None
_lib_failed: str | None = None

_ERR_LEN = 4096


def load_lib() -> ctypes.CDLL | None:
    """Load (building on demand) the native library; None if unavailable."""
    global _lib, _lib_failed
    try:
        from ..reliability.faults import fault_check

        # orchestration drill point (DA4ML_FAULT_INJECT=native.load_lib=...):
        # simulates a missing toolchain / failed build WITHOUT poisoning the
        # _lib/_lib_failed cache, so the library loads again once the fault
        # budget is spent
        fault_check('native.load_lib')
    except Exception as e:
        from ..reliability.errors import ReliabilityError

        if not isinstance(e, ReliabilityError):
            raise
        return None
    if _lib is not None:
        return _lib
    if _lib_failed is not None:
        return None
    with _lock:
        if _lib is not None:
            return _lib
        try:
            from .build import LIB_PATH, build, needs_build

            if needs_build():
                if os.environ.get('DA4ML_NO_NATIVE_BUILD'):
                    _lib_failed = 'native library not built (DA4ML_NO_NATIVE_BUILD set)'
                    return None
                build()
            lib = ctypes.CDLL(str(LIB_PATH))
        except Exception as e:  # toolchain missing, build error, bad .so
            _lib_failed = str(e)
            return None

        lib.dais_run.restype = ctypes.c_int
        lib.dais_run.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.c_int64,
        ]
        lib.dais_program_info.restype = ctypes.c_int
        lib.dais_program_info.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_char_p,
            ctypes.c_int64,
        ]
        lib.da4ml_native_abi_version.restype = ctypes.c_int
        _lib = lib
        return _lib


def load_error() -> str | None:
    return _lib_failed


def run_binary(binary: NDArray[np.int32], data: NDArray[np.float64], n_threads: int = 0) -> NDArray[np.float64]:
    """Execute a serialized DAIS program over a (n_samples, n_in) batch."""
    lib = load_lib()
    if lib is None:
        raise RuntimeError(f'Native DAIS interpreter unavailable: {_lib_failed}')
    binary = np.ascontiguousarray(binary, dtype=np.int32)
    n_in, n_out = int(binary[2]), int(binary[3])
    data = np.ascontiguousarray(data, dtype=np.float64)
    data = data.reshape(len(data), -1)
    if data.shape[1] != n_in:
        raise ValueError(f'Input size mismatch: expected {n_in}, got {data.shape[1]}')
    n_samples = data.shape[0]
    out = np.empty((n_samples, n_out), dtype=np.float64)
    err = ctypes.create_string_buffer(_ERR_LEN)
    if n_threads <= 0:
        n_threads = int(os.environ.get('DA_DEFAULT_THREADS', 0) or 0)
    rc = lib.dais_run(
        binary.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        binary.size,
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_samples,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_threads,
        err,
        _ERR_LEN,
    )
    if rc != 0:
        raise RuntimeError(f'dais_run failed: {err.value.decode(errors="replace")}')
    return out


def program_info(binary: NDArray[np.int32]) -> dict:
    lib = load_lib()
    if lib is None:
        raise RuntimeError(f'Native DAIS interpreter unavailable: {_lib_failed}')
    binary = np.ascontiguousarray(binary, dtype=np.int32)
    vals = [ctypes.c_int64() for _ in range(4)]
    err = ctypes.create_string_buffer(_ERR_LEN)
    rc = lib.dais_program_info(
        binary.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        binary.size,
        *[ctypes.byref(v) for v in vals],
        err,
        _ERR_LEN,
    )
    if rc != 0:
        raise RuntimeError(f'dais_program_info failed: {err.value.decode(errors="replace")}')
    n_in, n_out, n_ops, max_width = (v.value for v in vals)
    return {'n_in': n_in, 'n_out': n_out, 'n_ops': n_ops, 'max_width': max_width}


def _declare_cmvm(lib: ctypes.CDLL) -> None:
    if getattr(lib, '_cmvm_declared', False):
        return
    lib.cmvm_solve.restype = ctypes.c_void_p
    lib.cmvm_solve.argtypes = [
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_int64,
    ]
    lib.cmvm_stage_shape.restype = ctypes.c_int
    lib.cmvm_stage_shape.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.cmvm_stage_fill.restype = ctypes.c_int
    lib.cmvm_stage_fill.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.cmvm_free.restype = None
    lib.cmvm_free.argtypes = [ctypes.c_void_p]
    lib._cmvm_declared = True


def _unpack_stage(lib: ctypes.CDLL, handle: int, stage: int):
    from ..ir.comb import CombLogic
    from ..ir.types import Op, QInterval

    n_in, n_out, n_ops = (ctypes.c_int64() for _ in range(3))
    rc = lib.cmvm_stage_shape(handle, stage, *(ctypes.byref(v) for v in (n_in, n_out, n_ops)))
    if rc != 0:
        raise RuntimeError('cmvm_stage_shape failed')
    ops9 = np.empty((n_ops.value, 9), dtype=np.float64)
    inp_shifts = np.empty(n_in.value, dtype=np.int32)
    out_idxs = np.empty(n_out.value, dtype=np.int32)
    out_shifts = np.empty(n_out.value, dtype=np.int32)
    out_negs = np.empty(n_out.value, dtype=np.int32)
    rc = lib.cmvm_stage_fill(
        handle,
        stage,
        ops9.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        inp_shifts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_idxs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_shifts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_negs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        raise RuntimeError('cmvm_stage_fill failed')
    ops = [
        Op(int(r[0]), int(r[1]), int(r[2]), int(r[3]), QInterval(r[4], r[5], r[6]), float(r[7]), float(r[8]))
        for r in ops9
    ]
    return CombLogic(
        shape=(n_in.value, n_out.value),
        inp_shifts=[int(v) for v in inp_shifts],
        out_idxs=[int(v) for v in out_idxs],
        out_shifts=[int(v) for v in out_shifts],
        out_negs=[bool(v) for v in out_negs],
        ops=ops,
        carry_size=-1,
        adder_size=-1,
    )


def solve_native(
    kernel,
    method0: str = 'wmc',
    method1: str = 'auto',
    hard_dc: int = -1,
    decompose_dc: int = -2,
    qintervals=None,
    latencies=None,
    adder_size: int = -1,
    carry_size: int = -1,
    search_all_decompose_dc: bool = True,
    n_threads: int = 0,
):
    """Full CMVM solve in the native library; returns an ir.Pipeline.

    Decision-identical with the Python host solver (cmvm/api.py solve),
    parallelized over decompose-depth candidates with OpenMP
    (reference: api.cc:194-238).
    """
    from ..ir.comb import Pipeline
    from ..ir.types import QInterval

    lib = load_lib()
    if lib is None:
        raise RuntimeError(f'Native CMVM solver unavailable: {_lib_failed}')
    _declare_cmvm(lib)

    kernel = np.ascontiguousarray(kernel, dtype=np.float64)
    if kernel.ndim != 2 or kernel.shape[0] == 0 or kernel.shape[1] == 0:
        raise ValueError(f'kernel must be a non-empty 2D matrix, got shape {kernel.shape}')
    n_in, n_out = kernel.shape
    if not qintervals:
        qintervals = [QInterval(-128.0, 127.0, 1.0)] * n_in
    if not latencies:
        latencies = [0.0] * n_in
    qarr = np.ascontiguousarray([[q[0], q[1], q[2]] for q in qintervals], dtype=np.float64)
    larr = np.ascontiguousarray(latencies, dtype=np.float64)
    if len(qarr) != n_in or len(larr) != n_in:
        raise ValueError('qintervals/latencies length must match kernel rows')

    err = ctypes.create_string_buffer(_ERR_LEN)
    handle = lib.cmvm_solve(
        kernel.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_in,
        n_out,
        method0.encode(),
        method1.encode(),
        hard_dc,
        decompose_dc,
        qarr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        larr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        adder_size,
        carry_size,
        int(search_all_decompose_dc),
        n_threads,
        err,
        _ERR_LEN,
    )
    if not handle:
        raise RuntimeError(f'cmvm_solve failed: {err.value.decode(errors="replace")}')
    try:
        sol0 = _unpack_stage(lib, handle, 0)
        sol1 = _unpack_stage(lib, handle, 1)
    finally:
        lib.cmvm_free(handle)
    sol0 = sol0._replace(carry_size=carry_size, adder_size=adder_size)
    sol1 = sol1._replace(carry_size=carry_size, adder_size=adder_size)
    return Pipeline(stages=(sol0, sol1))


def _declare_emit(lib: ctypes.CDLL) -> None:
    if getattr(lib, '_emit_declared', False):
        return
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i8p = ctypes.POINTER(ctypes.c_int8)
    f64p = ctypes.POINTER(ctypes.c_double)
    lib.cmvm_emit_batch.restype = ctypes.c_void_p
    lib.cmvm_emit_batch.argtypes = [
        ctypes.c_int64, i64p, i32p, i32p, f64p, f64p, i8p, i32p,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.cmvm_emit_shape.restype = ctypes.c_int
    lib.cmvm_emit_shape.argtypes = [ctypes.c_void_p, ctypes.c_int64, i64p, i64p, i64p]
    lib.cmvm_emit_fill.restype = ctypes.c_int
    lib.cmvm_emit_fill.argtypes = [ctypes.c_void_p, ctypes.c_int64, f64p, i32p, i32p, i32p, i32p]
    lib.cmvm_emit_free.restype = None
    lib.cmvm_emit_free.argtypes = [ctypes.c_void_p]
    lib.cmvm_decompose_batch.restype = ctypes.c_int
    lib.cmvm_decompose_batch.argtypes = [
        ctypes.c_int64, i64p, f64p, f64p, f64p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib._emit_declared = True


def has_emit() -> bool:
    lib = load_lib()
    return lib is not None and hasattr(lib, 'cmvm_emit_batch')


class RawComb:
    """Array-backed solution handle: cheap cost/latency/qint accessors, with
    the full :class:`~da4ml_tpu.ir.comb.CombLogic` materialized only on demand
    (candidate solutions that lose the decompose-dc argmin are never built)."""

    __slots__ = ('shape', 'inp_shifts', 'out_idxs', 'out_shifts', 'out_negs', 'ops9', 'adder_size', 'carry_size')

    def __init__(self, shape, inp_shifts, out_idxs, out_shifts, out_negs, ops9, adder_size, carry_size):
        self.shape = shape
        self.inp_shifts = inp_shifts
        self.out_idxs = out_idxs
        self.out_shifts = out_shifts
        self.out_negs = out_negs
        self.ops9 = ops9
        self.adder_size = adder_size
        self.carry_size = carry_size

    @property
    def cost(self) -> float:
        return float(self.ops9[:, 8].sum())

    @property
    def out_latency(self) -> list[float]:
        lat = self.ops9[:, 7]
        return [float(lat[i]) if i >= 0 else 0.0 for i in self.out_idxs]

    @property
    def out_qint(self) -> list:
        from ..ir.types import QInterval

        out = []
        for i, idx in enumerate(self.out_idxs):
            if idx < 0:
                out.append(QInterval(0.0, 0.0, 1.0))
                continue
            lo, hi, step = self.ops9[idx, 4:7]
            sf = 2.0 ** float(self.out_shifts[i])
            lo, hi, step = lo * sf, hi * sf, step * sf
            if self.out_negs[i]:
                lo, hi = -hi, -lo
            out.append(QInterval(float(lo), float(hi), float(step)))
        return out

    def to_comb(self):
        from ..ir.comb import CombLogic
        from ..ir.types import Op, QInterval

        # tolist() converts the whole array to python scalars in C — much
        # faster than per-element numpy indexing for the big op arrays
        ops = [
            Op(int(a), int(b), int(c), int(d), QInterval(e, f, g), h, i)
            for a, b, c, d, e, f, g, h, i in self.ops9.tolist()
        ]
        return CombLogic(
            shape=self.shape,
            inp_shifts=[int(v) for v in self.inp_shifts],
            out_idxs=[int(v) for v in self.out_idxs],
            out_shifts=[int(v) for v in self.out_shifts],
            out_negs=[bool(v) for v in self.out_negs],
            ops=ops,
            carry_size=self.carry_size,
            adder_size=self.adder_size,
        )


def emit_batch(
    lanes: list[tuple],
    adder_size: int,
    carry_size: int,
    n_threads: int = 0,
    raw: bool = False,
) -> list:
    """Batched adder-tree emission from device search decisions.

    Each lane is ``(shift0 [ni] i32, shift1 [no] i32, qints [ni,3] f64,
    lats [ni] f64, E [(ni+n_add), no, nb] i8, rec [n_add,4] i32)``.
    Returns one :class:`~da4ml_tpu.ir.comb.CombLogic` per lane (OpenMP over
    lanes; reference pattern api.cc:208-238), or :class:`RawComb` array
    handles when ``raw`` is set.
    """
    lib = load_lib()
    if lib is None:
        raise RuntimeError(f'Native emission unavailable: {_lib_failed}')
    _declare_emit(lib)

    n_lanes = len(lanes)
    geo = np.empty((n_lanes, 4), dtype=np.int64)
    s0_l, s1_l, q_l, la_l, E_l, r_l = [], [], [], [], [], []
    for x, (shift0, shift1, qints, lats, E, rec) in enumerate(lanes):
        ni = len(shift0)
        no = len(shift1)
        n_add = len(rec)
        nb = E.shape[2] if E.ndim == 3 else 0
        geo[x] = (ni, no, nb, n_add)
        s0_l.append(np.ascontiguousarray(shift0, dtype=np.int32))
        s1_l.append(np.ascontiguousarray(shift1, dtype=np.int32))
        q_l.append(np.ascontiguousarray(qints, dtype=np.float64).reshape(ni, 3))
        la_l.append(np.ascontiguousarray(lats, dtype=np.float64))
        E_l.append(np.ascontiguousarray(E, dtype=np.int8).reshape(-1))
        r_l.append(np.ascontiguousarray(rec, dtype=np.int32).reshape(-1))
    shift0s = np.concatenate(s0_l) if s0_l else np.zeros(0, np.int32)
    shift1s = np.concatenate(s1_l) if s1_l else np.zeros(0, np.int32)
    qints_f = np.concatenate(q_l).reshape(-1) if q_l else np.zeros(0, np.float64)
    lats_f = np.concatenate(la_l) if la_l else np.zeros(0, np.float64)
    E_f = np.concatenate(E_l) if E_l else np.zeros(0, np.int8)
    rec_f = np.concatenate(r_l) if r_l else np.zeros(0, np.int32)

    err = ctypes.create_string_buffer(_ERR_LEN)
    if n_threads <= 0:
        n_threads = int(os.environ.get('DA_DEFAULT_THREADS', 0) or 0)
    handle = lib.cmvm_emit_batch(
        n_lanes,
        geo.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        shift0s.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        shift1s.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        qints_f.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        lats_f.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        E_f.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        rec_f.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        adder_size,
        carry_size,
        n_threads,
        err,
        _ERR_LEN,
    )
    if not handle:
        raise RuntimeError(f'cmvm_emit_batch failed: {err.value.decode(errors="replace")}')
    try:
        out = []
        for x in range(n_lanes):
            n_in, n_out, n_ops = (ctypes.c_int64() for _ in range(3))
            rc = lib.cmvm_emit_shape(handle, x, *(ctypes.byref(v) for v in (n_in, n_out, n_ops)))
            if rc != 0:
                raise RuntimeError('cmvm_emit_shape failed')
            ops9 = np.empty((n_ops.value, 9), dtype=np.float64)
            inp_shifts = np.empty(n_in.value, dtype=np.int32)
            out_idxs = np.empty(n_out.value, dtype=np.int32)
            out_shifts = np.empty(n_out.value, dtype=np.int32)
            out_negs = np.empty(n_out.value, dtype=np.int32)
            rc = lib.cmvm_emit_fill(
                handle,
                x,
                ops9.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                inp_shifts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                out_idxs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                out_shifts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                out_negs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
            if rc != 0:
                raise RuntimeError('cmvm_emit_fill failed')
            sol = RawComb(
                (n_in.value, n_out.value), inp_shifts, out_idxs, out_shifts, out_negs, ops9, adder_size, carry_size
            )
            out.append(sol if raw else sol.to_comb())
        return out
    finally:
        lib.cmvm_emit_free(handle)


def decompose_batch(
    kernels: list[NDArray[np.float64]],
    dcs: list[int],
    n_threads: int = 0,
) -> list[tuple[NDArray[np.float64], NDArray[np.float64]]]:
    """Batched ``kernel_decompose`` (OpenMP over lanes): m0 @ m1 == kernel."""
    lib = load_lib()
    if lib is None:
        raise RuntimeError(f'Native decomposition unavailable: {_lib_failed}')
    _declare_emit(lib)

    n_lanes = len(kernels)
    geo = np.empty((n_lanes, 3), dtype=np.int64)
    k_l = []
    n_k = n_m1 = 0
    for x, (k, dc) in enumerate(zip(kernels, dcs)):
        k = np.ascontiguousarray(k, dtype=np.float64)
        ni, no = k.shape
        geo[x] = (ni, no, dc)
        k_l.append(k.reshape(-1))
        n_k += ni * no
        n_m1 += no * no
    kern_f = np.concatenate(k_l) if k_l else np.zeros(0, np.float64)
    m0_out = np.zeros(n_k, dtype=np.float64)
    m1_out = np.zeros(n_m1, dtype=np.float64)
    err = ctypes.create_string_buffer(_ERR_LEN)
    if n_threads <= 0:
        n_threads = int(os.environ.get('DA_DEFAULT_THREADS', 0) or 0)
    rc = lib.cmvm_decompose_batch(
        n_lanes,
        geo.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        kern_f.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        m0_out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        m1_out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_threads,
        err,
        _ERR_LEN,
    )
    if rc != 0:
        raise RuntimeError(f'cmvm_decompose_batch failed: {err.value.decode(errors="replace")}')
    out = []
    ok = om = 0
    for x in range(n_lanes):
        ni, no = int(geo[x, 0]), int(geo[x, 1])
        out.append((m0_out[ok : ok + ni * no].reshape(ni, no), m1_out[om : om + no * no].reshape(no, no)))
        ok += ni * no
        om += no * no
    return out
