"""ctypes bindings to the native library (_da4ml_native.so).

The native sources live in ``da4ml_tpu/native/src`` and are compiled with
``g++ -fopenmp`` by :mod:`da4ml_tpu.native.build` (auto-invoked on first use
unless ``DA4ML_NO_NATIVE_BUILD`` is set). Bindings use ctypes only — no
pybind11/nanobind dependency.

Reference parity: the nanobind modules src/da4ml/_binary/{dais,cmvm}/bindings.cc.
"""

from __future__ import annotations

import ctypes
import os
import threading

import numpy as np
from numpy.typing import NDArray

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed: str | None = None

_ERR_LEN = 4096


def load_lib() -> ctypes.CDLL | None:
    """Load (building on demand) the native library; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed is not None:
        return None
    with _lock:
        if _lib is not None:
            return _lib
        try:
            from .build import LIB_PATH, build, needs_build

            if needs_build():
                if os.environ.get('DA4ML_NO_NATIVE_BUILD'):
                    _lib_failed = 'native library not built (DA4ML_NO_NATIVE_BUILD set)'
                    return None
                build()
            lib = ctypes.CDLL(str(LIB_PATH))
        except Exception as e:  # toolchain missing, build error, bad .so
            _lib_failed = str(e)
            return None

        lib.dais_run.restype = ctypes.c_int
        lib.dais_run.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.c_int64,
        ]
        lib.dais_program_info.restype = ctypes.c_int
        lib.dais_program_info.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_char_p,
            ctypes.c_int64,
        ]
        lib.da4ml_native_abi_version.restype = ctypes.c_int
        _lib = lib
        return _lib


def load_error() -> str | None:
    return _lib_failed


def run_binary(binary: NDArray[np.int32], data: NDArray[np.float64], n_threads: int = 0) -> NDArray[np.float64]:
    """Execute a serialized DAIS program over a (n_samples, n_in) batch."""
    lib = load_lib()
    if lib is None:
        raise RuntimeError(f'Native DAIS interpreter unavailable: {_lib_failed}')
    binary = np.ascontiguousarray(binary, dtype=np.int32)
    n_in, n_out = int(binary[2]), int(binary[3])
    data = np.ascontiguousarray(data, dtype=np.float64)
    data = data.reshape(len(data), -1)
    if data.shape[1] != n_in:
        raise ValueError(f'Input size mismatch: expected {n_in}, got {data.shape[1]}')
    n_samples = data.shape[0]
    out = np.empty((n_samples, n_out), dtype=np.float64)
    err = ctypes.create_string_buffer(_ERR_LEN)
    if n_threads <= 0:
        n_threads = int(os.environ.get('DA_DEFAULT_THREADS', 0) or 0)
    rc = lib.dais_run(
        binary.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        binary.size,
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_samples,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_threads,
        err,
        _ERR_LEN,
    )
    if rc != 0:
        raise RuntimeError(f'dais_run failed: {err.value.decode(errors="replace")}')
    return out


def program_info(binary: NDArray[np.int32]) -> dict:
    lib = load_lib()
    if lib is None:
        raise RuntimeError(f'Native DAIS interpreter unavailable: {_lib_failed}')
    binary = np.ascontiguousarray(binary, dtype=np.int32)
    vals = [ctypes.c_int64() for _ in range(4)]
    err = ctypes.create_string_buffer(_ERR_LEN)
    rc = lib.dais_program_info(
        binary.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        binary.size,
        *[ctypes.byref(v) for v in vals],
        err,
        _ERR_LEN,
    )
    if rc != 0:
        raise RuntimeError(f'dais_program_info failed: {err.value.decode(errors="replace")}')
    n_in, n_out, n_ops, max_width = (v.value for v in vals)
    return {'n_in': n_in, 'n_out': n_out, 'n_ops': n_ops, 'max_width': max_width}


def solve_native(kernel, **kwargs):
    raise NotImplementedError('Native CMVM solver lands with the cmvm_core native module.')
