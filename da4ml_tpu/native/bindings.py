"""ctypes bindings for the native library (placeholder until the C++ core lands).

The native sources live in da4ml_tpu/native/src; ``python -m
da4ml_tpu.native.build`` compiles them with g++ -fopenmp into
_da4ml_native.so next to this file.
"""

from __future__ import annotations


def load_lib():
    return None


def run_binary(binary, data, n_threads: int = 0):
    raise NotImplementedError(
        'Native DAIS interpreter is not built. Run `python -m da4ml_tpu.native.build` '
        "or use backend='numpy' / backend='jax'."
    )


def solve_native(kernel, **kwargs):
    raise NotImplementedError(
        'Native CMVM solver is not built. Run `python -m da4ml_tpu.native.build` '
        "or use backend='cpu' / backend='jax'."
    )
