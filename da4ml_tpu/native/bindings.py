"""ctypes bindings to the native library (_da4ml_native.so).

The native sources live in ``da4ml_tpu/native/src`` and are compiled with
``g++ -fopenmp`` by :mod:`da4ml_tpu.native.build` (auto-invoked on first use
unless ``DA4ML_NO_NATIVE_BUILD`` is set). Bindings use ctypes only — no
pybind11/nanobind dependency.

Reference parity: the nanobind modules src/da4ml/_binary/{dais,cmvm}/bindings.cc.
"""

from __future__ import annotations

import ctypes
import os
import threading

import numpy as np
from numpy.typing import NDArray

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed: str | None = None

_ERR_LEN = 4096


def load_lib() -> ctypes.CDLL | None:
    """Load (building on demand) the native library; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed is not None:
        return None
    with _lock:
        if _lib is not None:
            return _lib
        try:
            from .build import LIB_PATH, build, needs_build

            if needs_build():
                if os.environ.get('DA4ML_NO_NATIVE_BUILD'):
                    _lib_failed = 'native library not built (DA4ML_NO_NATIVE_BUILD set)'
                    return None
                build()
            lib = ctypes.CDLL(str(LIB_PATH))
        except Exception as e:  # toolchain missing, build error, bad .so
            _lib_failed = str(e)
            return None

        lib.dais_run.restype = ctypes.c_int
        lib.dais_run.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.c_int64,
        ]
        lib.dais_program_info.restype = ctypes.c_int
        lib.dais_program_info.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_char_p,
            ctypes.c_int64,
        ]
        lib.da4ml_native_abi_version.restype = ctypes.c_int
        _lib = lib
        return _lib


def load_error() -> str | None:
    return _lib_failed


def run_binary(binary: NDArray[np.int32], data: NDArray[np.float64], n_threads: int = 0) -> NDArray[np.float64]:
    """Execute a serialized DAIS program over a (n_samples, n_in) batch."""
    lib = load_lib()
    if lib is None:
        raise RuntimeError(f'Native DAIS interpreter unavailable: {_lib_failed}')
    binary = np.ascontiguousarray(binary, dtype=np.int32)
    n_in, n_out = int(binary[2]), int(binary[3])
    data = np.ascontiguousarray(data, dtype=np.float64)
    data = data.reshape(len(data), -1)
    if data.shape[1] != n_in:
        raise ValueError(f'Input size mismatch: expected {n_in}, got {data.shape[1]}')
    n_samples = data.shape[0]
    out = np.empty((n_samples, n_out), dtype=np.float64)
    err = ctypes.create_string_buffer(_ERR_LEN)
    if n_threads <= 0:
        n_threads = int(os.environ.get('DA_DEFAULT_THREADS', 0) or 0)
    rc = lib.dais_run(
        binary.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        binary.size,
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_samples,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_threads,
        err,
        _ERR_LEN,
    )
    if rc != 0:
        raise RuntimeError(f'dais_run failed: {err.value.decode(errors="replace")}')
    return out


def program_info(binary: NDArray[np.int32]) -> dict:
    lib = load_lib()
    if lib is None:
        raise RuntimeError(f'Native DAIS interpreter unavailable: {_lib_failed}')
    binary = np.ascontiguousarray(binary, dtype=np.int32)
    vals = [ctypes.c_int64() for _ in range(4)]
    err = ctypes.create_string_buffer(_ERR_LEN)
    rc = lib.dais_program_info(
        binary.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        binary.size,
        *[ctypes.byref(v) for v in vals],
        err,
        _ERR_LEN,
    )
    if rc != 0:
        raise RuntimeError(f'dais_program_info failed: {err.value.decode(errors="replace")}')
    n_in, n_out, n_ops, max_width = (v.value for v in vals)
    return {'n_in': n_in, 'n_out': n_out, 'n_ops': n_ops, 'max_width': max_width}


def _declare_cmvm(lib: ctypes.CDLL) -> None:
    if getattr(lib, '_cmvm_declared', False):
        return
    lib.cmvm_solve.restype = ctypes.c_void_p
    lib.cmvm_solve.argtypes = [
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_int64,
    ]
    lib.cmvm_stage_shape.restype = ctypes.c_int
    lib.cmvm_stage_shape.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.cmvm_stage_fill.restype = ctypes.c_int
    lib.cmvm_stage_fill.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.cmvm_free.restype = None
    lib.cmvm_free.argtypes = [ctypes.c_void_p]
    lib._cmvm_declared = True


def _unpack_stage(lib: ctypes.CDLL, handle: int, stage: int):
    from ..ir.comb import CombLogic
    from ..ir.types import Op, QInterval

    n_in, n_out, n_ops = (ctypes.c_int64() for _ in range(3))
    rc = lib.cmvm_stage_shape(handle, stage, *(ctypes.byref(v) for v in (n_in, n_out, n_ops)))
    if rc != 0:
        raise RuntimeError('cmvm_stage_shape failed')
    ops9 = np.empty((n_ops.value, 9), dtype=np.float64)
    inp_shifts = np.empty(n_in.value, dtype=np.int32)
    out_idxs = np.empty(n_out.value, dtype=np.int32)
    out_shifts = np.empty(n_out.value, dtype=np.int32)
    out_negs = np.empty(n_out.value, dtype=np.int32)
    rc = lib.cmvm_stage_fill(
        handle,
        stage,
        ops9.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        inp_shifts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_idxs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_shifts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_negs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        raise RuntimeError('cmvm_stage_fill failed')
    ops = [
        Op(int(r[0]), int(r[1]), int(r[2]), int(r[3]), QInterval(r[4], r[5], r[6]), float(r[7]), float(r[8]))
        for r in ops9
    ]
    return CombLogic(
        shape=(n_in.value, n_out.value),
        inp_shifts=[int(v) for v in inp_shifts],
        out_idxs=[int(v) for v in out_idxs],
        out_shifts=[int(v) for v in out_shifts],
        out_negs=[bool(v) for v in out_negs],
        ops=ops,
        carry_size=-1,
        adder_size=-1,
    )


def solve_native(
    kernel,
    method0: str = 'wmc',
    method1: str = 'auto',
    hard_dc: int = -1,
    decompose_dc: int = -2,
    qintervals=None,
    latencies=None,
    adder_size: int = -1,
    carry_size: int = -1,
    search_all_decompose_dc: bool = True,
    n_threads: int = 0,
):
    """Full CMVM solve in the native library; returns an ir.Pipeline.

    Decision-identical with the Python host solver (cmvm/api.py solve),
    parallelized over decompose-depth candidates with OpenMP
    (reference: api.cc:194-238).
    """
    from ..ir.comb import Pipeline
    from ..ir.types import QInterval

    lib = load_lib()
    if lib is None:
        raise RuntimeError(f'Native CMVM solver unavailable: {_lib_failed}')
    _declare_cmvm(lib)

    kernel = np.ascontiguousarray(kernel, dtype=np.float64)
    if kernel.ndim != 2 or kernel.shape[0] == 0 or kernel.shape[1] == 0:
        raise ValueError(f'kernel must be a non-empty 2D matrix, got shape {kernel.shape}')
    n_in, n_out = kernel.shape
    if not qintervals:
        qintervals = [QInterval(-128.0, 127.0, 1.0)] * n_in
    if not latencies:
        latencies = [0.0] * n_in
    qarr = np.ascontiguousarray([[q[0], q[1], q[2]] for q in qintervals], dtype=np.float64)
    larr = np.ascontiguousarray(latencies, dtype=np.float64)
    if len(qarr) != n_in or len(larr) != n_in:
        raise ValueError('qintervals/latencies length must match kernel rows')

    err = ctypes.create_string_buffer(_ERR_LEN)
    handle = lib.cmvm_solve(
        kernel.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n_in,
        n_out,
        method0.encode(),
        method1.encode(),
        hard_dc,
        decompose_dc,
        qarr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        larr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        adder_size,
        carry_size,
        int(search_all_decompose_dc),
        n_threads,
        err,
        _ERR_LEN,
    )
    if not handle:
        raise RuntimeError(f'cmvm_solve failed: {err.value.decode(errors="replace")}')
    try:
        sol0 = _unpack_stage(lib, handle, 0)
        sol1 = _unpack_stage(lib, handle, 1)
    finally:
        lib.cmvm_free(handle)
    sol0 = sol0._replace(carry_size=carry_size, adder_size=adder_size)
    sol1 = sol1._replace(carry_size=carry_size, adder_size=adder_size)
    return Pipeline(stages=(sol0, sol1))
