"""Tracer plugin base class — the front-end extension point.

Third-party frameworks (keras/HGQ2, torch exporters, ...) implement a small
subclass that replays their model with numpy-protocol ops over
``FixedVariableArray`` inputs; everything below (CMVM optimization, IR,
codegen) is framework-agnostic. Behavior parity with the reference plugin ABC
(reference src/da4ml/converter/plugin.py:22-135): subclasses provide
``apply_model`` and ``get_input_shapes``; ``trace`` builds inputs, applies the
model, and flattens the named outputs into a single 1-d array.
"""

from __future__ import annotations

from collections.abc import Callable, Sequence
from typing import Any

import numpy as np

from ..cmvm import solver_options_t
from ..trace import FixedVariable, FixedVariableArray, FixedVariableArrayInput, HWConfig


def flatten_arrays(args: Any) -> FixedVariableArray | None:
    """Ravel-and-concatenate any nesting of FixedVariableArray/FixedVariable."""
    if isinstance(args, FixedVariableArray):
        return np.ravel(args)  # type: ignore[return-value]
    if isinstance(args, FixedVariable):
        return FixedVariableArray(np.array([args]))
    if isinstance(args, Sequence) and not isinstance(args, (str, bytes)):
        flat = [flatten_arrays(a) for a in args]
        flat = [a for a in flat if a is not None]
        if not flat:
            return None
        return np.concatenate(flat)  # type: ignore[return-value]
    return None


class TracerPluginBase:
    """Base class for DAIS tracer plugins.

    Subclasses implement:

    - ``apply_model(verbose, inputs) -> (dict[name, FixedVariableArray], [output names])``
    - ``get_input_shapes() -> list[shape] | None``
    """

    def __init__(
        self,
        model: Callable,
        hwconf: HWConfig,
        solver_options: solver_options_t | None = None,
        **kwargs: Any,
    ):
        self.model = model
        self.hwconf = hwconf
        self.solver_options = solver_options
        if kwargs:
            raise TypeError(f'Unexpected keyword arguments: {sorted(kwargs)}')

    # -------------------------------------------------------- to implement

    def apply_model(
        self,
        verbose: bool,
        inputs: tuple[FixedVariableArray, ...],
    ) -> tuple[dict[str, Any], list[str]]:
        """Replay the model over symbolic inputs.

        Returns a dict of every named intermediate trace and the list of
        output names (keys into the dict, in output order).
        """
        raise NotImplementedError

    def get_input_shapes(self) -> Sequence[tuple[int, ...]] | None:
        """Input shapes (batch dim excluded), or None if not inferable."""
        raise NotImplementedError

    def prewarm_kernel_groups(self) -> list[list[np.ndarray]] | None:
        """Constant-matrix groups (one per future CMVM solve call) for
        background shape-class prewarming, or None.

        Front-ends that can enumerate their layers' weight matrices before
        tracing should override this; ``trace`` then AOT-compiles every
        device shape class concurrently with the layer-by-layer solve flow
        instead of paying one serial trace+compile per class. Best-effort:
        a missed or extra group only costs a background compile.
        """
        return None

    # ------------------------------------------------------------ plumbing

    def _get_inputs(
        self,
        inputs: tuple[FixedVariableArray, ...] | FixedVariableArray | None,
        inputs_kif: tuple[int, int, int] | Sequence[tuple[int, int, int]] | None,
    ) -> tuple[FixedVariableArray, ...]:
        if inputs is not None:
            return inputs if isinstance(inputs, tuple) else (inputs,)

        shapes = self.get_input_shapes()
        if shapes is None:
            raise ValueError('Inputs must be provided: cannot determine input shapes automatically.')

        if inputs_kif is None:
            # Unquantized sentinel inputs: the first quantize() call on each
            # records the input precision.
            return tuple(FixedVariableArrayInput(shape, self.hwconf, self.solver_options) for shape in shapes)

        kifs: Sequence[tuple[int, int, int]]
        if not isinstance(inputs_kif[0], Sequence):
            kifs = (inputs_kif,) * len(shapes)  # type: ignore[assignment]
        else:
            kifs = inputs_kif  # type: ignore[assignment]
        if len(kifs) != len(shapes):
            raise ValueError('Length of inputs_kif must match number of inputs')

        return tuple(
            FixedVariableArray.from_kif(
                np.full(shape, kif[0], np.int8),
                np.full(shape, kif[1], np.int8),
                np.full(shape, kif[2], np.int8),
                self.hwconf,
                0.0,
                self.solver_options,
            )
            for kif, shape in zip(kifs, shapes)
        )

    def trace(
        self,
        verbose: bool = False,
        inputs: tuple[FixedVariableArray, ...] | FixedVariableArray | None = None,
        inputs_kif: tuple[int, int, int] | None = None,
        dump: bool = False,
    ):
        """Trace the model.

        With ``dump=True`` returns the dict of all intermediate traces;
        otherwise returns ``(inputs, outputs)`` as flat FixedVariableArrays,
        ready for ``comb_trace``.
        """
        if (self.solver_options or {}).get('backend') == 'jax':
            groups = self.prewarm_kernel_groups()
            if groups:
                from ..cmvm import jax_search

                opts = {k: v for k, v in (self.solver_options or {}).items() if k != 'backend'}
                opts.setdefault('adder_size', self.hwconf.adder_size)
                opts.setdefault('carry_size', self.hwconf.carry_size)
                jax_search.prewarm_for_kernels(groups, **opts)
        inps = self._get_inputs(inputs, inputs_kif)
        all_traces, output_names = self.apply_model(verbose=verbose, inputs=inps)
        if dump:
            return all_traces
        out = flatten_arrays([all_traces[name] for name in output_names])
        inp = flatten_arrays(inps)
        return inp, out
