"""Built-in example model + plugin — the template third parties follow.

Fills the same role as the reference's example plugin (reference
src/da4ml/converter/example.py) but demonstrates a different computation: a
tiny gated-residual block exercising quantize / relu / slicing / a tanh
lookup table / an elementwise variable product / matmul / einsum. The same
``operation`` runs both eagerly on numpy arrays (the golden path) and
symbolically on FixedVariableArrays.
"""

from __future__ import annotations

import numpy as np

from ..trace import FixedVariableArray
from ..trace.ops import einsum, quantize, relu
from .plugin import TracerPluginBase


def operation(inp):
    """Example computation, traceable and numpy-executable alike.

    A gated-residual block on a (4, 5) input: the first two rows drive a
    tanh gate, the last two rows go through a CMVM mixing matrix; the gated
    product and the mixed features are concatenated and contracted with a
    per-row head tensor.
    """
    # Deterministic pseudo-random fixed-point weights (exact on a 2^-6 grid).
    w_mix = ((np.arange(35) * 13 + 5) % 29 - 14).reshape(5, 7).astype(np.float64) / 2**6
    w_head = ((np.arange(96) * 7 % 41) - 20).reshape(2, 12, 4).astype(np.float64) / 2**5

    x = quantize(inp, 1, 5, 2)  # inputs must be quantized before use
    head, tail = x[:2], x[2:]

    gate = quantize(np.tanh(head), 1, 0, 6, 'SAT_SYM', 'RND')
    mixed = quantize(tail @ w_mix, 1, 9, 3)  # CMVM-optimized matmul
    gated = quantize(gate * tail, 1, 6, 4)  # elementwise variable product
    resid = relu(np.abs(mixed) - 1)

    feats = np.concatenate([gated, resid], axis=1)  # (2, 12)
    return einsum('ki,kio->ko', feats, w_head)  # CMVM-optimized contraction


class ExampleModel:
    """Tiny callable model for showcasing the plugin system."""

    def __init__(self, input_shape: tuple[int, ...] | None = None):
        self.input_shape = input_shape

    def __call__(self, x):
        return operation(x)


class ExampleTracer(TracerPluginBase):
    """Plugin for :class:`ExampleModel`.

    Registered under the framework name ``da4ml_tpu`` (the root module of
    ``ExampleModel``) — both in-process and as a ``da4ml_tpu.plugins`` entry
    point in pyproject.toml.
    """

    model: ExampleModel

    def get_input_shapes(self):
        return [self.model.input_shape] if self.model.input_shape is not None else None

    def apply_model(
        self,
        verbose: bool,
        inputs: tuple[FixedVariableArray, ...],
    ) -> tuple[dict[str, FixedVariableArray], list[str]]:
        assert len(inputs) == 1, 'ExampleModel expects a single input.'
        out = operation(inputs[0])
        return {'output': out}, ['output']
