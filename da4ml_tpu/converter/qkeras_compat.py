"""Minimal QKeras-compatible quantized layers and quantizers.

Registered under the ``qkeras`` serialization package, so ``.keras`` files
built with these classes — and, name-for-name, files saved by real QKeras —
deserialize without the qkeras package installed. This is the in-tree
quantized-model ingestion path: the reference keeps its quantized front-end
out-of-tree and imports it for custom objects at load time
(reference src/da4ml/_cli/convert.py:32-35).

Semantics are ap_fixed-style (SAT/SAT_SYM overflow, round-half-up), matching
this framework's golden ``fixed_quantize`` exactly, so a model built from
these layers converts with zero mismatches. True QKeras rounds ties to even
(tf.round); importing a real QKeras model is bit-exact except on exact
half-LSB ties.

Every quantizer exposes ``da_spec`` — the duck-typed protocol the Keras
front-end reads:

``{'k': 0|1, 'i': int, 'f': int, 'overflow_mode': str, 'round_mode': str,
   'relu': bool}``
"""

from __future__ import annotations

from typing import Any

import keras
import numpy as np
from keras import ops


def _spec(k: int, i: int, f: int, overflow: str, rounding: str, relu: bool = False) -> dict[str, Any]:
    return {'k': int(k), 'i': int(i), 'f': int(f), 'overflow_mode': overflow, 'round_mode': rounding, 'relu': relu}


@keras.saving.register_keras_serializable(package='qkeras')
class quantized_bits:
    """Signed/unsigned fixed-point quantizer: ``bits`` total, ``integer``
    integer bits (sign excluded), saturating, round-half-up."""

    def __init__(self, bits: int = 8, integer: int = 0, symmetric: int = 0, keep_negative: bool = True, **_ignored):
        self.bits = int(bits)
        self.integer = int(integer)
        self.symmetric = int(symmetric)
        self.keep_negative = bool(keep_negative)

    @property
    def da_spec(self) -> dict[str, Any]:
        k = 1 if self.keep_negative else 0
        f = self.bits - self.integer - k
        return _spec(k, self.integer, f, 'SAT_SYM' if self.symmetric else 'SAT', 'RND')

    def __call__(self, x):
        s = self.da_spec
        eps = 2.0 ** -s['f']
        span = 2.0 ** s['i']
        hi = span - eps
        lo = -hi * s['k'] if s['overflow_mode'] == 'SAT_SYM' else -span * s['k']
        q = ops.floor(x / eps + 0.5) * eps
        return ops.clip(q, lo, hi)

    def get_config(self):
        return {'bits': self.bits, 'integer': self.integer, 'symmetric': self.symmetric, 'keep_negative': self.keep_negative}

    @classmethod
    def from_config(cls, config):
        return cls(**config)


@keras.saving.register_keras_serializable(package='qkeras')
class quantized_relu:
    """Unsigned fixed-point ReLU: clamp to [0, 2^integer - lsb], round-half-up."""

    def __init__(self, bits: int = 8, integer: int = 0, **_ignored):
        self.bits = int(bits)
        self.integer = int(integer)

    @property
    def da_spec(self) -> dict[str, Any]:
        return _spec(0, self.integer, self.bits - self.integer, 'SAT', 'RND', relu=True)

    def __call__(self, x):
        s = self.da_spec
        eps = 2.0 ** -s['f']
        q = ops.floor(ops.relu(x) / eps + 0.5) * eps
        return ops.clip(q, 0.0, 2.0 ** s['i'] - eps)

    def get_config(self):
        return {'bits': self.bits, 'integer': self.integer}

    @classmethod
    def from_config(cls, config):
        return cls(**config)


def _as_quantizer(q):
    if q is None or callable(q):
        return q if not isinstance(q, dict) else keras.saving.deserialize_keras_object(q)
    if isinstance(q, dict):
        return keras.saving.deserialize_keras_object(q)
    raise ValueError(f'Not a quantizer: {q!r}')


def _maybe_serialize(q):
    return None if q is None else keras.saving.serialize_keras_object(q)


@keras.saving.register_keras_serializable(package='qkeras')
class QActivation(keras.layers.Layer):
    """Standalone quantizer layer (the usual input-quantization entry)."""

    def __init__(self, activation=None, **kwargs):
        super().__init__(**kwargs)
        self.quantizer = _as_quantizer(activation)

    def call(self, inputs):
        return self.quantizer(inputs)

    def get_config(self):
        cfg = super().get_config()
        cfg['activation'] = _maybe_serialize(self.quantizer)
        return cfg


class _QuantizedWeightsMixin:
    def _init_quantizers(self, kernel_quantizer, bias_quantizer):
        self.kernel_quantizer = _as_quantizer(kernel_quantizer)
        self.bias_quantizer = _as_quantizer(bias_quantizer)

    def _qkernel(self):
        return self.kernel_quantizer(self.kernel) if self.kernel_quantizer is not None else self.kernel

    def _qbias(self):
        if not self.use_bias:
            return None
        return self.bias_quantizer(self.bias) if self.bias_quantizer is not None else self.bias

    def _quantizer_config(self, cfg):
        cfg['kernel_quantizer'] = _maybe_serialize(self.kernel_quantizer)
        cfg['bias_quantizer'] = _maybe_serialize(self.bias_quantizer)
        return cfg


@keras.saving.register_keras_serializable(package='qkeras')
class QDense(_QuantizedWeightsMixin, keras.layers.Dense):
    def __init__(self, units, kernel_quantizer=None, bias_quantizer=None, **kwargs):
        super().__init__(units, **kwargs)
        self._init_quantizers(kernel_quantizer, bias_quantizer)

    def call(self, inputs):
        y = ops.matmul(inputs, self._qkernel())
        b = self._qbias()
        if b is not None:
            y = y + b
        return self.activation(y) if self.activation is not None else y

    def get_config(self):
        return self._quantizer_config(super().get_config())


@keras.saving.register_keras_serializable(package='qkeras')
class QConv1D(_QuantizedWeightsMixin, keras.layers.Conv1D):
    def __init__(self, filters, kernel_size, kernel_quantizer=None, bias_quantizer=None, **kwargs):
        super().__init__(filters, kernel_size, **kwargs)
        self._init_quantizers(kernel_quantizer, bias_quantizer)

    def call(self, inputs):
        return _conv_call(self, inputs)

    def get_config(self):
        return self._quantizer_config(super().get_config())


@keras.saving.register_keras_serializable(package='qkeras')
class QConv2D(_QuantizedWeightsMixin, keras.layers.Conv2D):
    def __init__(self, filters, kernel_size, kernel_quantizer=None, bias_quantizer=None, **kwargs):
        super().__init__(filters, kernel_size, **kwargs)
        self._init_quantizers(kernel_quantizer, bias_quantizer)

    def call(self, inputs):
        return _conv_call(self, inputs)

    def get_config(self):
        return self._quantizer_config(super().get_config())


@keras.saving.register_keras_serializable(package='qkeras')
class QDepthwiseConv2D(keras.layers.DepthwiseConv2D):
    def __init__(self, kernel_size, depthwise_quantizer=None, bias_quantizer=None, **kwargs):
        super().__init__(kernel_size, **kwargs)
        self.depthwise_quantizer = _as_quantizer(depthwise_quantizer)
        self.bias_quantizer = _as_quantizer(bias_quantizer)

    def call(self, inputs):
        k = self.kernel
        if self.depthwise_quantizer is not None:
            k = self.depthwise_quantizer(k)
        y = ops.depthwise_conv(
            inputs, k, strides=self.strides, padding=self.padding, data_format='channels_last',
            dilation_rate=self.dilation_rate,
        )  # fmt: skip
        if self.use_bias:
            b = self.bias_quantizer(self.bias) if self.bias_quantizer is not None else self.bias
            y = y + ops.reshape(b, (1,) * (y.ndim - 1) + (-1,))
        return self.activation(y) if self.activation is not None else y

    def get_config(self):
        cfg = super().get_config()
        cfg['depthwise_quantizer'] = _maybe_serialize(self.depthwise_quantizer)
        cfg['bias_quantizer'] = _maybe_serialize(self.bias_quantizer)
        return cfg


def _conv_call(layer, inputs):
    y = ops.conv(
        inputs,
        layer._qkernel(),
        strides=layer.strides,
        padding=layer.padding,
        data_format='channels_last',
        dilation_rate=layer.dilation_rate,
    )
    b = layer._qbias()
    if b is not None:
        y = y + ops.reshape(b, (1,) * (y.ndim - 1) + (-1,))
    return layer.activation(y) if layer.activation is not None else y


def read_quantizer_spec(q) -> dict[str, Any] | None:
    """The duck-typed quantizer protocol the Keras front-end consumes.

    Accepts this module's quantizers (``da_spec``) and, best-effort, real
    QKeras objects (``bits``/``integer``/``keep_negative`` attributes).
    Returns None when ``q`` carries no readable bit widths.
    """
    if q is None:
        return None
    spec = getattr(q, 'da_spec', None)
    if spec is not None:
        return dict(spec)
    bits = getattr(q, 'bits', None)
    integer = getattr(q, 'integer', None)
    if bits is None or integer is None:
        return None
    name = type(q).__name__
    if 'relu' in name:
        return _spec(0, int(integer), int(bits) - int(integer), 'SAT', 'RND', relu=True)
    keep_negative = bool(getattr(q, 'keep_negative', True))
    symmetric = bool(getattr(q, 'symmetric', False))
    k = 1 if keep_negative else 0
    return _spec(k, int(integer), int(bits) - int(integer) - k, 'SAT_SYM' if symmetric else 'SAT', 'RND')


def quantize_weights(w: np.ndarray, q) -> np.ndarray:
    """Quantize a weight tensor numerically by the quantizer's spec (exact —
    runs in float64 through the golden fixed_quantize)."""
    spec = read_quantizer_spec(q)
    if spec is None:
        return w
    from ..trace.ops.quantization import fixed_quantize

    return fixed_quantize(w, spec['k'], spec['i'], spec['f'], spec['overflow_mode'], spec['round_mode'])
