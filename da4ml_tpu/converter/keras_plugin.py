"""Keras front-end: trace Sequential / Functional models into the DAIS graph.

Each supported layer is replayed with numpy-protocol ops over
``FixedVariableArray``s (Dense and Conv route through the CMVM optimizer);
functional graphs are walked with the model's own ``_run_through_graph`` so
arbitrary branching topologies (Add / Concatenate / multi-output) trace
without re-implementing Keras graph traversal. Tracing is per-sample: the
batch dimension is dropped throughout.

The reference keeps its Keras/HGQ2 front-end out-of-tree and registers it via
the plugin entry-point group (reference src/da4ml/converter/__init__.py:10-16,
docs/getting_started.md); this module provides an in-tree equivalent for
plain Keras layers. Unquantized nonlinearities (softmax, sigmoid, ...) are
rejected — DA semantics need an explicit output precision, which plain Keras
layers do not carry; quantize activations explicitly or use a quantized
front-end.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ..telemetry import get_logger
from ..trace import FixedVariableArray
from ..trace.ops import (
    avg_pool1d,
    avg_pool2d,
    conv1d,
    conv2d,
    depthwise_conv1d,
    depthwise_conv2d,
    max_pool1d,
    max_pool2d,
    leaky_relu,
    relu,
    relu6,
    upsample_nearest,
    zero_pad,
)
from .plugin import TracerPluginBase

_logger = get_logger('converter.keras')

_SUPPORTED_ACTIVATIONS = ('linear', 'relu', 'relu6', 'leaky_relu')

#: quantized layers route through their base handler with quantized weights
_QUANTIZED_BASE = {
    'QDense': 'Dense',
    'QConv1D': 'Conv1D',
    'QConv2D': 'Conv2D',
    'QDepthwiseConv2D': 'DepthwiseConv2D',
    'QSeparableConv2D': 'SeparableConv2D',
    'QBatchNormalization': 'BatchNormalization',
    # HGQ2 names (batchnorm-fused variants expose the fused qkernel/qbias)
    'QDenseBatchnorm': 'Dense',
    'QConv1DBatchnorm': 'Conv1D',
    'QConv2DBatchnorm': 'Conv2D',
    'QEinsumDense': 'EinsumDense',
    'QEinsumDenseBatchnorm': 'EinsumDense',
    'QMaxPool1D': 'MaxPooling1D',
    'QMaxPool2D': 'MaxPooling2D',
    'QAveragePooling1D': 'AveragePooling1D',
    'QAveragePooling2D': 'AveragePooling2D',
    'QGlobalAveragePooling1D': 'GlobalAveragePooling1D',
    'QGlobalAveragePooling2D': 'GlobalAveragePooling2D',
    'QGlobalMaxPooling1D': 'GlobalMaxPooling1D',
    'QGlobalMaxPooling2D': 'GlobalMaxPooling2D',
    'QAdd': 'Add',
    'QSubtract': 'Subtract',
    'QMultiply': 'Multiply',
    'QMaximum': 'Maximum',
    'QMinimum': 'Minimum',
    'QAverage': 'Average',
    'QConcatenate': 'Concatenate',
    'QFlatten': 'Flatten',
    'QReshape': 'Reshape',
}


def _weight(w) -> np.ndarray:
    return np.asarray(w, dtype=np.float64)


def _quantized_weight(layer, attr: str, quantizer_attrs: tuple[str, ...]) -> np.ndarray:
    """A layer weight, passed through its quantizer when one is attached.

    HGQ2 layers expose the already-quantized values under a ``q`` prefix
    (``qkernel``/``qbias``) — exact, so they win outright; otherwise QKeras-
    style duck typing applies the first readable quantizer attribute."""
    from .qkeras_compat import quantize_weights

    qw = getattr(layer, 'q' + attr, None)
    if qw is not None:
        return _weight(qw)
    w = _weight(getattr(layer, attr))
    for qa in quantizer_attrs:
        q = getattr(layer, qa, None)
        if q is not None:
            return quantize_weights(w, q)
    return w


def _apply_quantizer_spec(x, spec: dict):
    """Apply a quantizer's (k, i, f, overflow, round) to a traced array.

    Unquantized sentinel inputs only accept WRAP (the call records the input
    precision; in-range data is unaffected by the overflow mode), and a relu
    spec on a sentinel assumes non-negative input data.
    """
    from ..trace.fixed_variable import FixedVariableInput
    from ..trace.ops.quantization import quantize

    flat = x._vars.ravel() if isinstance(x, FixedVariableArray) else np.array([])
    if flat.size and isinstance(flat[0], FixedVariableInput):
        x = quantize(x, spec['k'], spec['i'], spec['f'], 'WRAP', spec['round_mode'])
        return relu(x) if spec['relu'] else x
    if spec['relu']:
        x = relu(x)
    return quantize(x, spec['k'], spec['i'], spec['f'], spec['overflow_mode'], spec['round_mode'])


def _apply_activation(x, act):
    """Apply a Keras activation — a name, a function, or a quantizer object
    carrying bit widths (QKeras-style)."""
    from .qkeras_compat import read_quantizer_spec

    spec = read_quantizer_spec(act)
    if spec is not None:
        return _apply_quantizer_spec(x, spec)
    name = act if isinstance(act, str) else getattr(act, '__name__', type(act).__name__)
    if name == 'linear':
        return x
    if name == 'relu':
        return relu(x)
    if name == 'relu6':
        return relu6(x)
    if name == 'leaky_relu':
        return leaky_relu(x, 0.2)  # keras.activations.leaky_relu default slope
    raise NotImplementedError(
        f'Activation {name!r} is not traceable: DA semantics need an explicit output precision. '
        f'Supported: {_SUPPORTED_ACTIVATIONS} or a quantizer carrying bit widths.'
    )


class KerasTracer(TracerPluginBase):
    """Tracer plugin for ``keras.Model`` / ``keras.Sequential`` (Keras 3)."""

    def get_input_shapes(self):
        try:
            shapes = [tuple(int(d) for d in t.shape[1:]) for t in self.model.inputs]
        except Exception:
            return None
        return shapes or None

    def prewarm_kernel_groups(self):
        """One weight-matrix group per CMVM-bearing layer, mirroring how the
        trace handlers shape each layer's solve call (Dense: one matrix;
        Conv: the im2col matrix; Depthwise: one small matrix per channel),
        so the background prewarm compiles exactly the classes the real
        layer-by-layer flow will request. Best-effort — unreadable layers
        are skipped."""
        groups: list[list[np.ndarray]] = []
        try:
            layers = list(self.model.layers)
        except Exception:
            return None
        for layer in layers:
            try:
                name = _QUANTIZED_BASE.get(type(layer).__name__, type(layer).__name__)
                if name == 'Dense':
                    w = _quantized_weight(layer, 'kernel', ('kernel_quantizer_internal', 'kernel_quantizer'))
                    groups.append([w])
                elif name in ('Conv1D', 'Conv2D'):
                    k = _quantized_weight(layer, 'kernel', ('kernel_quantizer_internal', 'kernel_quantizer'))
                    groups.append([k.reshape(-1, k.shape[-1])])
                elif name in ('DepthwiseConv1D', 'DepthwiseConv2D', 'SeparableConv1D', 'SeparableConv2D'):
                    dk_attr = 'kernel' if getattr(layer, 'depthwise_kernel', None) is None else 'depthwise_kernel'
                    dk = _quantized_weight(
                        layer, dk_attr, ('depthwise_quantizer_internal', 'depthwise_quantizer', 'kernel_quantizer')
                    )
                    if dk.ndim == 3:  # [k, C, M] -> lift like depthwise_conv1d
                        dk = dk[:, None]
                    kh, kw, cin, mult = dk.shape
                    groups.append([dk[:, :, c, :].reshape(kh * kw, mult) for c in range(cin)])
                    if name.startswith('Separable'):
                        pk = _quantized_weight(
                            layer, 'pointwise_kernel', ('pointwise_quantizer_internal', 'pointwise_quantizer')
                        )
                        groups.append([pk.reshape(pk.shape[-2], pk.shape[-1])])
            except Exception:
                continue
        return groups or None

    # ------------------------------------------------------------ layers

    def _trace_layer(self, layer, args: tuple, kwargs: dict):
        """HGQ2-aware entry: wrap the base handler with the layer's input /
        output quantizers (heterogeneous per-element kif), then dispatch."""
        from .hgq2_compat import apply_hgq_quantizer, is_hgq_layer

        if not is_hgq_layer(layer):
            return self._trace_layer_inner(layer, args, kwargs)

        def _maybe_q(a, q, where):
            if isinstance(a, FixedVariableArray):
                return apply_hgq_quantizer(a, q, where)
            if isinstance(a, (list, tuple)):
                return type(a)(_maybe_q(e, q, where) for e in a)
            return a

        iq = getattr(layer, 'iq', None)
        if iq is not None:
            args = tuple(_maybe_q(a, iq, 'input') for a in args)
        out = self._trace_layer_inner(layer, args, kwargs)
        oq = getattr(layer, 'oq', None)
        if oq is not None and isinstance(out, FixedVariableArray):
            out = apply_hgq_quantizer(out, oq, 'output')
        return out

    def _trace_layer_inner(self, layer, args: tuple, kwargs: dict):
        name = type(layer).__name__

        if name == 'QActivation':
            from .qkeras_compat import read_quantizer_spec

            q = getattr(layer, 'quantizer', None) or getattr(layer, 'activation', None)
            spec = read_quantizer_spec(q)
            if spec is None:
                raise NotImplementedError(f'QActivation quantizer {q!r} carries no readable bit widths')
            return _apply_quantizer_spec(args[0], spec)
        name = _QUANTIZED_BASE.get(name, name)

        if name == 'InputLayer':
            return args[0]

        if name in ('Dropout', 'SpatialDropout1D', 'SpatialDropout2D'):
            return args[0]

        if name == 'Dense':
            x = args[0]
            y = x @ _quantized_weight(layer, 'kernel', ('kernel_quantizer_internal', 'kernel_quantizer'))
            if layer.use_bias:
                y = y + _quantized_weight(layer, 'bias', ('bias_quantizer_internal', 'bias_quantizer'))
            return _apply_activation(y, layer.activation)

        if name == 'EinsumDense':
            eq = layer.equation.replace(' ', '')
            lhs, rhs = eq.split('->')
            in_spec, k_spec = lhs.split(',')
            # drop the batch token ('...' or a leading letter absent from the
            # kernel spec) — tracing is per-sample
            if in_spec.startswith('...') and rhs.startswith('...') and '...' not in k_spec:
                eq2 = f'{in_spec[3:]},{k_spec}->{rhs[3:]}'
            elif in_spec and rhs and in_spec[0] == rhs[0] and in_spec[0] not in k_spec:
                eq2 = f'{in_spec[1:]},{k_spec}->{rhs[1:]}'
            else:
                raise NotImplementedError(f'EinsumDense equation {eq!r}: cannot identify the batch axis')
            from ..trace.ops import einsum as _einsum

            y = _einsum(eq2, args[0], _quantized_weight(layer, 'kernel', ('kernel_quantizer_internal', 'kernel_quantizer')))
            if getattr(layer, 'qbias', None) is not None or getattr(layer, 'bias', None) is not None:
                y = y + _quantized_weight(layer, 'bias', ('bias_quantizer_internal', 'bias_quantizer'))
            return _apply_activation(y, layer.activation)

        if name in ('Conv1D', 'Conv2D'):
            x = args[0]
            if getattr(layer, 'data_format', 'channels_last') != 'channels_last':
                raise NotImplementedError('Only channels_last convolutions are supported')
            if getattr(layer, 'groups', 1) != 1:
                raise NotImplementedError('Grouped convolutions are not supported')
            k = _quantized_weight(layer, 'kernel', ('kernel_quantizer_internal', 'kernel_quantizer'))
            if name == 'Conv1D':
                y = conv1d(x, k, stride=layer.strides[0], padding=layer.padding, dilation=layer.dilation_rate[0])
            else:
                y = conv2d(x, k, strides=layer.strides, padding=layer.padding, dilation=layer.dilation_rate)
            if layer.use_bias:
                y = y + _quantized_weight(layer, 'bias', ('bias_quantizer_internal', 'bias_quantizer'))
            return _apply_activation(y, layer.activation)

        if name in ('DepthwiseConv1D', 'DepthwiseConv2D', 'SeparableConv1D', 'SeparableConv2D'):
            x = args[0]
            if getattr(layer, 'data_format', 'channels_last') != 'channels_last':
                raise NotImplementedError('Only channels_last convolutions are supported')
            # Keras 3: Separable* exposes depthwise_kernel, Depthwise* plain kernel
            dk_attr = 'kernel' if getattr(layer, 'depthwise_kernel', None) is None else 'depthwise_kernel'
            dk = _quantized_weight(layer, dk_attr, ('depthwise_quantizer_internal', 'depthwise_quantizer', 'kernel_quantizer'))
            if name.endswith('1D'):
                y = depthwise_conv1d(x, dk, stride=layer.strides[0], padding=layer.padding, dilation=layer.dilation_rate[0])
            else:
                y = depthwise_conv2d(x, dk, strides=layer.strides, padding=layer.padding, dilation=layer.dilation_rate)
            if name.startswith('Separable'):
                # 1D: [1, Cin*M, Cout]; 2D: [1, 1, Cin*M, Cout]
                pk = _quantized_weight(layer, 'pointwise_kernel', ('pointwise_quantizer_internal', 'pointwise_quantizer'))
                y = y @ pk.reshape(pk.shape[-2], pk.shape[-1])
            if layer.use_bias:
                y = y + _quantized_weight(layer, 'bias', ('bias_quantizer_internal', 'bias_quantizer'))
            return _apply_activation(y, layer.activation)

        if name in (
            'MaxPooling1D',
            'AveragePooling1D',
            'MaxPooling2D',
            'AveragePooling2D',
            'GlobalAveragePooling1D',
            'GlobalMaxPooling1D',
            'GlobalAveragePooling2D',
            'GlobalMaxPooling2D',
        ):
            if getattr(layer, 'data_format', 'channels_last') != 'channels_last':
                raise NotImplementedError('Only channels_last pooling is supported')
        if name == 'MaxPooling1D':
            return max_pool1d(args[0], layer.pool_size, layer.strides, layer.padding)
        if name == 'AveragePooling1D':
            return avg_pool1d(args[0], layer.pool_size, layer.strides, layer.padding)
        if name == 'MaxPooling2D':
            return max_pool2d(args[0], layer.pool_size, layer.strides, layer.padding)
        if name == 'AveragePooling2D':
            return avg_pool2d(args[0], layer.pool_size, layer.strides, layer.padding)
        if name == 'GlobalAveragePooling1D':
            return np.mean(args[0], axis=0, keepdims=bool(getattr(layer, 'keepdims', False)))
        if name == 'GlobalMaxPooling1D':
            return np.amax(args[0], axis=0, keepdims=bool(getattr(layer, 'keepdims', False)))
        if name == 'GlobalAveragePooling2D':
            return np.mean(args[0], axis=(0, 1), keepdims=bool(getattr(layer, 'keepdims', False)))
        if name == 'GlobalMaxPooling2D':
            return np.amax(args[0], axis=(0, 1), keepdims=bool(getattr(layer, 'keepdims', False)))

        if name in ('ZeroPadding1D', 'ZeroPadding2D'):
            if getattr(layer, 'data_format', 'channels_last') not in (None, 'channels_last'):
                raise NotImplementedError('Only channels_last padding is supported')
            pad = layer.padding  # Keras normalizes to ((t, b),) per spatial axis
            pads = [tuple(int(v) for v in p) for p in (pad if isinstance(pad[0], (tuple, list)) else (pad,))]
            return zero_pad(args[0], pads)

        if name in ('UpSampling1D', 'UpSampling2D'):
            if getattr(layer, 'data_format', 'channels_last') not in (None, 'channels_last'):
                raise NotImplementedError('Only channels_last upsampling is supported')
            if getattr(layer, 'interpolation', 'nearest') != 'nearest':
                raise NotImplementedError('Only nearest-neighbor upsampling is traceable')
            size = layer.size if name == 'UpSampling2D' else (layer.size,)
            return upsample_nearest(args[0], tuple(int(s) for s in np.atleast_1d(size).ravel()))

        if name == 'Flatten':
            return args[0].reshape(-1)
        if name == 'Reshape':
            return args[0].reshape(*layer.target_shape)
        if name == 'Permute':
            return args[0].transpose([d - 1 for d in layer.dims])

        if name == 'ReLU':
            if getattr(layer, 'threshold', 0.0):
                raise NotImplementedError('Thresholded ReLU is not supported')
            slope = float(getattr(layer, 'negative_slope', 0.0) or 0.0)
            y = leaky_relu(args[0], slope) if slope else relu(args[0])
            if layer.max_value is not None:
                y = np.minimum(y, float(layer.max_value))
            return y
        if name == 'LeakyReLU':
            slope = float(getattr(layer, 'negative_slope', getattr(layer, 'alpha', 0.3)))
            return leaky_relu(args[0], slope)
        if name == 'PReLU':
            alpha = np.asarray(layer.get_weights()[0], np.float64)
            return leaky_relu(args[0], alpha)
        if name == 'Activation':
            return _apply_activation(args[0], layer.activation)

        if name == 'BatchNormalization':
            x = args[0]
            eps = float(layer.epsilon)
            # QKeras-style QBatchNormalization quantizes each folded
            # component; plain BN layers carry no quantizer attrs
            gamma = _quantized_weight(layer, 'gamma', ('gamma_quantizer',)) if layer.scale else 1.0
            beta = _quantized_weight(layer, 'beta', ('beta_quantizer',)) if layer.center else 0.0
            mean = _quantized_weight(layer, 'moving_mean', ('mean_quantizer',))
            var = _quantized_weight(layer, 'moving_variance', ('variance_quantizer',))
            a = np.atleast_1d(gamma / np.sqrt(var + eps))
            b = np.atleast_1d(beta - mean * a)
            ax = layer.axis if isinstance(layer.axis, int) else layer.axis[0]
            if ax == 0:
                raise NotImplementedError('BatchNormalization along the batch axis is not traceable')
            ax = ax - 1 if ax > 0 else ax % x.ndim  # batch dim dropped in tracing
            shape = [1] * x.ndim
            shape[ax] = a.size
            return x * a.reshape(shape) + b.reshape(shape)

        if name == 'Add':
            vals = args[0] if isinstance(args[0], (list, tuple)) else args
            out = vals[0]
            for v in vals[1:]:
                out = out + v
            return out
        if name == 'Subtract':
            vals = args[0] if isinstance(args[0], (list, tuple)) else args
            return vals[0] - vals[1]
        if name in ('Maximum', 'Minimum'):
            vals = args[0] if isinstance(args[0], (list, tuple)) else args
            fn = np.maximum if name == 'Maximum' else np.minimum
            out = vals[0]
            for v in vals[1:]:
                out = fn(out, v)
            return out
        if name == 'Multiply':
            vals = args[0] if isinstance(args[0], (list, tuple)) else args
            out = vals[0]
            for v in vals[1:]:
                out = out * v  # variable x variable -> explicit multiplier ops
            return out
        if name in ('Cropping1D', 'Cropping2D'):
            if getattr(layer, 'data_format', 'channels_last') != 'channels_last':
                raise NotImplementedError('Only channels_last cropping is supported')
            crop = layer.cropping
            if name == 'Cropping1D':
                (lo, hi) = crop
                return args[0][lo : args[0].shape[0] - hi]
            (t, b), (lft, r) = crop
            x = args[0]
            return x[t : x.shape[0] - b, lft : x.shape[1] - r]
        if name == 'Average':
            vals = args[0] if isinstance(args[0], (list, tuple)) else args
            out = vals[0]
            for v in vals[1:]:
                out = out + v
            return out * (1.0 / len(vals))
        if name == 'Concatenate':
            vals = args[0] if isinstance(args[0], (list, tuple)) else args
            axis = layer.axis
            if axis == 0:
                raise NotImplementedError('Concatenate along the batch axis (axis=0) is not traceable')
            if axis > 0:
                axis -= 1  # batch dim dropped in tracing
            return np.concatenate(vals, axis=axis)

        # ------------------------------------------------- keras.ops nodes
        # functional graphs built with keras.ops (the HGQ2 style) walk the
        # same graph executor; these are Operation nodes, not layers. The
        # traced arrays carry no batch axis, so every axis/subscript that
        # references it is stripped here.
        if name == 'Relu':
            return relu(args[0])
        if name == 'Relu6':
            return relu6(args[0])
        if name == 'LeakyRelu':
            return leaky_relu(args[0], float(layer.negative_slope))
        if name == 'GetItem':
            key = args[1] if len(args) > 1 else kwargs.get('key')
            if not isinstance(key, tuple):
                key = (key,)
            if not key or key[0] != slice(None):
                raise NotImplementedError('cannot index the batch axis in a traced graph')
            rest = key[1:]
            return args[0][rest] if rest else args[0]
        if name == 'Einsum':
            eq = layer.subscripts
            if '...' in eq:
                raise NotImplementedError('ellipsis einsum is not supported through keras.ops tracing')
            lhs, rhs = eq.replace(' ', '').split('->')
            terms = lhs.split(',')
            operands = list(args)
            sym = [isinstance(o, FixedVariableArray) for o in operands]
            lead = {t[0] for t, s in zip(terms, sym) if s and t}
            if len(lead) == 1 and rhs and rhs[0] in lead:
                b = lead.pop()
                if all(b not in t for t, s in zip(terms, sym) if not s):
                    terms = [t[1:] if s else t for t, s in zip(terms, sym)]
                    eq = ','.join(terms) + '->' + rhs[1:]
            from ..trace.ops import einsum as _einsum

            return _einsum(eq, *operands)
        if name in ('Mean', 'Sum', 'Max', 'Min'):
            ax = getattr(layer, 'axis', None)
            if ax is not None:
                axes = (ax,) if isinstance(ax, int) else tuple(ax)
                if 0 in axes:
                    raise NotImplementedError('cannot reduce over the batch axis in a traced graph')
                ax = tuple(a - 1 if a > 0 else a for a in axes)
                ax = ax[0] if len(ax) == 1 else ax
            fn = {'Mean': np.mean, 'Sum': np.sum, 'Max': np.amax, 'Min': np.amin}[name]
            return fn(args[0], axis=ax, keepdims=bool(getattr(layer, 'keepdims', False)))
        if name == 'Transpose':
            axes = layer.axes
            if axes is None or tuple(axes)[0] != 0:
                raise NotImplementedError('transpose must keep the batch axis first in a traced graph')
            return args[0].transpose([a - 1 for a in tuple(axes)[1:]])
        if name in ('ExpandDims', 'Squeeze'):
            ax = layer.axis
            if ax == 0:
                raise NotImplementedError('cannot reshape the batch axis in a traced graph')
            if ax is not None and not isinstance(ax, int):
                raise NotImplementedError('only a single axis is supported')
            ax = (ax - 1 if ax > 0 else ax) if ax is not None else None
            if name == 'ExpandDims':
                return np.expand_dims(args[0], ax)
            return np.squeeze(args[0], ax) if ax is not None else np.squeeze(args[0])
        if name == 'Stack':
            vals = args[0] if isinstance(args[0], (list, tuple)) else args
            ax = layer.axis
            if ax == 0:
                raise NotImplementedError('cannot stack along the batch axis in a traced graph')
            return np.stack(list(vals), axis=ax - 1 if ax > 0 else ax)
        if name == 'Clip':
            return np.clip(args[0], float(layer.x_min), float(layer.x_max))
        if name == 'Matmul':
            return args[0] @ args[1]
        if name in ('Divide', 'TrueDivide'):
            if isinstance(args[1], FixedVariableArray):
                raise NotImplementedError('division by a traced tensor is not supported (divide by constants only)')
            return args[0] / args[1]
        if name == 'Absolute':
            return abs(args[0])
        if name == 'Negative':
            return -args[0]

        raise NotImplementedError(f'Layer type {name!r} is not supported by the Keras tracer')

    # ------------------------------------------------------------ model walk

    def apply_model(self, verbose: bool, inputs: tuple[FixedVariableArray, ...]):
        import keras

        model = self.model
        traces: dict[str, Any] = {}

        if isinstance(model, keras.Sequential):
            x = inputs[0]
            for layer in model.layers:
                x = self._trace_layer(layer, (x,), {})
                traces[layer.name] = x
                if verbose:
                    _logger.info(f'  {layer.name}: {getattr(x, "shape", None)}')
            out_name = model.layers[-1].name if model.layers else 'out'
            return traces, [out_name]

        # Functional: reuse the model's own graph executor, substituting every
        # operation with the symbolic tracer.
        def operation_fn(op):
            def apply(*args, **kwargs):
                out = self._trace_layer(op, args, kwargs)
                traces[op.name] = out
                if verbose:
                    _logger.info(f'  {op.name}: {getattr(out, "shape", None)}')
                return out

            return apply

        outputs = model._run_through_graph(tuple(inputs), operation_fn=operation_fn)
        flat_outputs = keras.tree.flatten(outputs)
        names = []
        for i, out in enumerate(flat_outputs):
            name = f'output_{i}'
            traces[name] = out
            names.append(name)
        return traces, names
