"""HGQ2 (Keras-3) model ingestion: duck-typed quantizer/weight readers.

HGQ2 (github.com/calad0i/HGQ2) is the reference ecosystem's primary
quantized front-end; its layers carry trainable heterogeneous fixed-point
quantizers on inputs (``iq``), weights (``kq``/``bq``) and outputs (``oq``),
and expose the already-quantized weights as ``qkernel`` / ``qbias``
(reference src/da4ml/converter/__init__.py:10-78 dispatches such models to
an out-of-tree plugin; here the in-tree Keras tracer handles them).

Nothing in this module imports ``hgq`` — all access is duck-typed over the
attribute surface HGQ2 layers/quantizers expose, so the tracer ingests real
HGQ2 checkpoints when the package is installed and the mock-surface test
exercises the same code paths without it:

- ``layer.iq`` / ``layer.oq``: quantizer objects whose internals carry
  per-element (k, i, f) — KIF parameterization — or (k, b, i) with
  ``f = b - i`` — KBI — as tensors, plus overflow/round mode strings.
- ``layer.qkernel`` / ``layer.qbias``: the quantized weight values (exact;
  no spec needed).
"""

from __future__ import annotations

from typing import Any

import numpy as np

#: attribute spellings for the internal fixed-point parameter tensors
_INNER_ATTRS = ('quantizer', 'q', '_quantizer')
_OVERFLOW_ATTRS = ('overflow_mode', 'overflow')
_ROUND_ATTRS = ('round_mode', 'rounding')

_OVERFLOW_MAP = {'WRAP': 'WRAP', 'SAT': 'SAT', 'SAT_SYM': 'SAT_SYM'}
#: S_RND (stochastic) trains stochastically but quantizes deterministically
#: at inference time (== RND). RND_CONV is ties-to-even: it maps to RND,
#: which rounds ties up — bit-exact EXCEPT on exact half-LSB ties (the same
#: carve-out the QKeras front-end documents). Unknown modes raise.
_ROUND_MAP = {'TRN': 'TRN', 'RND': 'RND', 'S_RND': 'RND', 'RND_CONV': 'RND'}


def is_hgq_layer(layer) -> bool:
    """An HGQ2-style layer: by module, or by its quantizer attribute surface."""
    mod = type(layer).__module__ or ''
    if mod.split('.', 1)[0] == 'hgq':
        return True
    return hasattr(layer, 'oq') and (hasattr(layer, 'iq') or hasattr(layer, 'qkernel'))


def _tensor(v) -> np.ndarray | None:
    if v is None:
        return None
    try:
        arr = np.asarray(v, dtype=np.float64)
    except Exception:
        return None
    return arr if arr.size else None


def _squeeze_batch(arr: np.ndarray) -> np.ndarray:
    """HGQ2 parameter tensors keep a leading broadcast (batch) axis of 1."""
    while arr.ndim > 0 and arr.shape[0] == 1 and arr.ndim > 1:
        arr = arr[0]
    return arr


def _mode(obj, attrs: tuple[str, ...], mapping: dict[str, str], default: str) -> str:
    """Read a mode string; an attribute that is present but unmapped raises
    (silent fallback would break the bit-exact ingestion contract)."""
    for a in attrs:
        v = getattr(obj, a, None)
        if v is None:
            continue
        name = v if isinstance(v, str) else type(v).__name__
        key = name.upper().replace('-', '_')
        if key in mapping:
            return mapping[key]
        raise NotImplementedError(f'HGQ2 quantizer mode {name!r} (attribute {a!r}) is not supported')
    return default


def quantizer_kif(q) -> dict[str, Any] | None:
    """Per-element (k, i, f) + overflow/round of an HGQ2-style quantizer.

    Returns ``{'k': arr, 'i': arr, 'f': arr, 'overflow_mode': str,
    'round_mode': str}`` (arrays already rounded to ints, leading broadcast
    axis squeezed) or None when no fixed-point surface is found.
    """
    if q is None:
        return None
    seen = [q] + [getattr(q, a) for a in _INNER_ATTRS if getattr(q, a, None) is not None]
    for c in seen:
        k = _tensor(getattr(c, 'k', None))
        if k is None:
            k = _tensor(getattr(c, 'keep_negative', None))
        i = _tensor(getattr(c, 'i', None))
        f = _tensor(getattr(c, 'f', None))
        b = _tensor(getattr(c, 'b', None))
        if k is None or i is None or (f is None and b is None):
            continue
        if f is None:
            f = b - i  # KBI: total (non-sign) bits b = i + f
        k, i, f = (np.round(_squeeze_batch(t)).astype(np.int64) for t in (k, i, f))
        over = _mode(c, _OVERFLOW_ATTRS, _OVERFLOW_MAP, 'WRAP')
        rnd = _mode(c, _ROUND_ATTRS, _ROUND_MAP, 'RND')
        for other in seen:  # mode strings may live on the wrapper
            over = _mode(other, _OVERFLOW_ATTRS, _OVERFLOW_MAP, over)
            rnd = _mode(other, _ROUND_ATTRS, _ROUND_MAP, rnd)
        return {'k': k, 'i': i, 'f': f, 'overflow_mode': over, 'round_mode': rnd}
    return None


def apply_hgq_quantizer(x, q, where: str):
    """Quantize a traced array with an HGQ2 quantizer's (k, i, f) surface."""
    if q is None or getattr(q, 'enabled', True) is False:
        return x
    spec = quantizer_kif(q)
    if spec is None:
        raise NotImplementedError(
            f'HGQ2 {where} quantizer {type(q).__name__!r} exposes no readable (k, i, f) surface'
        )
    from ..trace.fixed_variable import FixedVariableInput
    from ..trace.fixed_variable_array import FixedVariableArray
    from ..trace.ops.quantization import quantize

    k, i, f = spec['k'], spec['i'], spec['f']
    over, rnd = spec['overflow_mode'], spec['round_mode']
    flat = x._vars.ravel() if isinstance(x, FixedVariableArray) else np.array([])
    if flat.size and isinstance(flat[0], FixedVariableInput):
        over = 'WRAP'  # sentinel inputs only record precision; data is in range
    return quantize(x, k, i, f, over, rnd)
